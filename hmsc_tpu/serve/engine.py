"""Long-lived posterior serving engine: open a fitted run once, answer
batched prediction queries at low latency.

The scaling story (ROADMAP: "predictions as a product"): a fitted
posterior is loaded exactly once — an mmap'd append-layout manifest or a
compacted :mod:`~hmsc_tpu.serve.artifact` — staged to the device as one
stacked (n_draws, ...) batch, and every query is answered by a
precompiled jitted kernel (:mod:`~hmsc_tpu.serve.kernels`).  Three
mechanisms keep the device-call count low and the compile count bounded:

- **Shape buckets.**  Query row counts are padded up to a small fixed set
  of bucket sizes, so arbitrary query sizes map onto a handful of
  compiled programs and steady-state traffic NEVER triggers a recompile
  (asserted by ``benchmarks/bench_serving.py`` via the engine's
  compile-cache hit counters).
- **An LRU compile cache.**  Kernels are keyed by (kind, bucket, static
  config, staged shapes); entries beyond ``cache_size`` evict
  least-recently-used.  ``stats()["cache"]`` exposes hits/misses — the
  zero-recompile gate.
- **Micro-batching.**  Concurrent queries are coalesced within a bounded
  window (``coalesce_ms``, or until the largest bucket fills) into ONE
  device call per bucket; results are split back per request.  At 64
  concurrent single-site queries this is one kernel dispatch instead of
  64 (gated ≥5x the serial ``predict()`` path).

**Epoch flips** (streaming refits): everything a query touches — staged
device arrays, unit lookup tables, model metadata — lives in ONE
immutable generation object, and :meth:`ServingEngine.reload` swaps the
engine's reference to it atomically.  A request snapshots the generation
at submit time and is dispatched against that same generation, so
in-flight queries finish on the epoch they were validated against while
new queries see the refreshed posterior; a same-shape flip (refit rows at
existing units, same draw count) reuses every compiled kernel — zero
recompiles, asserted by ``tests/test_refit.py``.  ``POST /flip`` exposes
the reload over HTTP.

Per-request telemetry rides the same :class:`~hmsc_tpu.obs.RunTelemetry`
machinery as the sampler: ``queue_wait`` / ``pad`` / ``dispatch`` /
``fetch`` spans per batch, request/row counters, and an optional JSONL
sink next to the artifact — ``python -m hmsc_tpu report`` renders it, and
``serve --prom`` exports Prometheus gauges through the report machinery.
"""

from __future__ import annotations

import collections
import os
import queue as _queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..obs import RunTelemetry, events_path
from .artifact import (ServingArtifact, load_artifact, load_run_posterior,
                       resolve_run_epoch)
from .kernels import (make_conditional_kernel, make_predict_kernel,
                      make_sharded_conditional_kernel,
                      make_sharded_predict_kernel)

__all__ = ["ServingEngine", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

_STOP = object()


class _Request:
    __slots__ = ("config", "n_rows", "arrays", "future", "t_submit",
                 "staged")

    def __init__(self, config, n_rows, arrays, future, staged):
        self.config = config          # kernel config key (kind + statics)
        self.n_rows = n_rows
        self.arrays = arrays          # dict of per-row host arrays
        self.future = future
        self.staged = staged          # the generation it was validated on
        self.t_submit = time.perf_counter()


class _Staged:
    """One immutable serving generation: the staged device arrays plus
    every piece of model metadata a query resolves against.  Built once
    per (re)load, swapped atomically — never mutated."""

    __slots__ = ("gen", "epoch", "hM", "artifact", "ns", "nc", "nr",
                 "n_draws", "fam", "any_probit", "any_normal",
                 "any_poisson", "level_names", "unit_lut", "new_unit",
                 "ym_host", "ys_host", "Beta", "sigma", "lams", "etas",
                 "fam_d", "ym", "ys", "shape_key", "mesh", "draw_shards")


class ServingEngine:
    """Serve predictions from a fitted posterior (see module docstring).

    ``source`` is a :class:`~hmsc_tpu.post.Posterior`, a
    :class:`~hmsc_tpu.serve.artifact.ServingArtifact`, or a path (a
    compacted artifact directory, or a — possibly epoched — run directory
    written by ``python -m hmsc_tpu run``; the newest COMMITTED epoch is
    served).  ``hM`` is required only when ``source`` does not carry the
    model itself (a run-directory path rebuilds it from ``model.json``
    plus any committed appends; an artifact is self-contained for raw-X
    queries).

    Serving scope (v1): shared-design models (``x_is_list=False``) without
    a reduced-rank term, random levels with unit loadings
    (``x_dim == 0``).  Queries at *training* units gather their posterior
    Eta rows; unknown/new units use the mean-field zero row (the
    ``predict_eta_mean`` semantics).  Richer structures fall back to the
    offline :func:`hmsc_tpu.predict` path.

    ``draw_shards > 1`` stages the posterior's draw axis over a 1-D
    device mesh (:data:`~hmsc_tpu.mcmc.partition.SERVE_DRAW_DIMS`) and
    answers every query with the draw-sharded kernels: per-device HBM
    drops to ``1/k`` of the posterior and the per-query draw work fans
    out ``k``-wide with one psum per query; answers agree with the
    single-device engine within ``SHARD_AGREEMENT_TOL``.  Widths that
    don't divide the draw count (or exceed the device count) fall back
    to the nearest valid width with a warning.
    """

    # the submit path (any caller thread) and the coalescing worker share
    # the compile cache and the counters; `hmsc_tpu lint` (lock-discipline)
    # enforces the declaration below
    # hmsc: guarded-by[_lock]: _cache, _hits, _misses, _n_requests, _n_batches, _n_device_calls, _rows_served, _rows_padded

    def __init__(self, source, hM=None, *, buckets=DEFAULT_BUCKETS,
                 coalesce_ms: float = 2.0, cache_size: int = 32,
                 draw_thin: int = 1, draw_shards: int | None = None,
                 telemetry=None, seed: int = 0):
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        self.max_bucket = self.buckets[-1]
        self.coalesce_s = float(coalesce_ms) / 1e3
        self.cache_size = int(cache_size)
        self._rng = np.random.default_rng(seed)

        # telemetry follows the sample_mcmc convention: falsy = aggregates
        # only (no event retention), True = in-memory events, a directory
        # = events + JSONL sink
        self.telem = RunTelemetry(proc=0, enabled=bool(telemetry))
        if telemetry:
            # a serving process is a top-level entry point: join the
            # spawning fleet's trace from the env, else mint a root —
            # every serve event (flips included) links back to it
            from ..obs.trace import inherit_or_mint
            self.telem.set_trace(inherit_or_mint())
        if telemetry and not isinstance(telemetry, bool):
            self.telem.attach_sink(events_path(telemetry, 0), truncate=True)
            self.telem.emit("run", "serve_start", buckets=list(self.buckets),
                            coalesce_ms=float(coalesce_ms))

        self._source = source
        self._hM0 = hM
        self._draw_thin = int(draw_thin)
        # requested draw-mesh width (None/1 = the committed single-device
        # path, byte-identical staging); resolved per generation against
        # the draw count + device count in _build_staged.  One Mesh per
        # resolved width, cached so a same-shape flip reuses the same
        # mesh object (NamedSharding equality → zero recompiles).
        self._draw_shards_req = (None if draw_shards is None
                                 else int(draw_shards))
        if self._draw_shards_req is not None and self._draw_shards_req < 1:
            raise ValueError(f"draw_shards={draw_shards} must be >= 1")
        self._mesh_cache: dict = {}
        # serialises reload(): two concurrent flips must not both build
        # gen N+1 and race the swap (one fully-staged generation would be
        # silently discarded while _source recorded the other)
        self._reload_lock = threading.Lock()
        # the ONE atomically-swapped reference: everything a query touches
        # hangs off this generation object (see module docstring)
        self._staged = self._build_staged(source, hM, self._draw_thin, 0)
        # wall-clock of the last generation swap (initial staging counts):
        # /healthz exposes it so an external probe can confirm a flip
        # landed without scraping the event log
        self._last_flip_wall = time.time()

        self._lock = threading.Lock()
        self._cache: collections.OrderedDict = collections.OrderedDict()
        self._hits = 0
        self._misses = 0
        self._n_requests = 0
        self._n_batches = 0
        self._n_device_calls = 0
        self._rows_served = 0
        self._rows_padded = 0

        self._queue: _queue.Queue = _queue.Queue()
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="hmsc-serve-batcher")
        self._worker.start()

    # ------------------------------------------------------------------
    # generation accessors (the staged snapshot is the source of truth)
    # ------------------------------------------------------------------

    @property
    def hM(self):
        return self._staged.hM

    @property
    def artifact(self):
        return self._staged.artifact

    @property
    def epoch(self):
        """The served epoch index (``None`` for non-run sources)."""
        return self._staged.epoch

    @property
    def generation(self) -> int:
        """Monotonic reload counter (0 = the initial staging)."""
        return self._staged.gen

    @property
    def last_flip_wall(self) -> float:
        """Wall-clock (``time.time()``) of the last generation swap — the
        initial staging for a never-flipped engine."""
        return self._last_flip_wall

    @property
    def n_draws(self):
        return self._staged.n_draws

    @property
    def draw_shards(self) -> int:
        """Resolved draw-mesh width this generation serves at (1 = the
        single-device path)."""
        return self._staged.draw_shards

    @property
    def ns(self):
        return self._staged.ns

    @property
    def nc(self):
        return self._staged.nc

    @property
    def nr(self):
        return self._staged.nr

    @property
    def level_names(self):
        return list(self._staged.level_names)

    @property
    def any_probit(self):
        return self._staged.any_probit

    @property
    def any_normal(self):
        return self._staged.any_normal

    @property
    def any_poisson(self):
        return self._staged.any_poisson

    # ------------------------------------------------------------------
    # posterior staging
    # ------------------------------------------------------------------

    def _build_staged(self, source, hM, draw_thin, gen) -> _Staged:
        import jax.numpy as jnp

        st = _Staged()
        st.gen = int(gen)
        st.epoch = None
        if isinstance(source, str) or hasattr(source, "__fspath__"):
            p = os.fspath(source)
            if os.path.exists(os.path.join(p, "serving.json")):
                source = load_artifact(p)
            else:
                # resolve ONCE and pin the load to that epoch: a refit
                # committing between a resolve and the load must not make
                # the engine serve epoch k+1 while labelling it k
                st.epoch, _ = resolve_run_epoch(p)
                source, hM = load_run_posterior(p, hM, epoch=st.epoch)
        st.hM = hM

        if isinstance(source, ServingArtifact):
            meta = source.meta["model"]
            if meta["nc_rrr"] > 0 or meta["x_is_list"]:
                raise NotImplementedError(
                    "serving engine v1: reduced-rank terms and "
                    "species-specific designs are not servable — use "
                    "hmsc_tpu.predict on the loaded posterior")
            levels = source.meta["levels"]
            if any(lv["x_dim"] > 0 for lv in levels):
                raise NotImplementedError(
                    "serving engine v1: covariate-dependent random levels "
                    "(xDim > 0) are not servable — use hmsc_tpu.predict")
            # stored(): bf16 artifacts stage their draws AS bf16 (half the
            # serving HBM; the kernels widen at entry — exact), f32
            # artifacts stay the zero-copy memmap
            pooled = {name: source.stored(name)[::draw_thin]
                      for name in (["Beta", "sigma"]
                                   + [f"Eta_{r}" for r in range(len(levels))]
                                   + [f"Lambda_{r}"
                                      for r in range(len(levels))])}
            st.ns = int(meta["ns"])
            st.nc = int(meta["nc"])
            st.fam = np.asarray(meta["distr"], dtype=np.int32)
            ym = np.asarray(meta["y_scale_m"], dtype=np.float32)
            ys = np.asarray(meta["y_scale_s"], dtype=np.float32)
            st.level_names = [lv["name"] for lv in levels]
            unit_lists = [lv["units"] for lv in levels]
            st.artifact = source
        else:                               # a Posterior
            post = source
            hM = st.hM = post.hM if hM is None else hM
            spec = post.spec
            if hM.nc_rrr > 0 or hM.x_is_list:
                raise NotImplementedError(
                    "serving engine v1: reduced-rank terms and "
                    "species-specific designs are not servable — use "
                    "hmsc_tpu.predict on the posterior")
            if any(spec.levels[r].x_dim > 0 for r in range(spec.nr)):
                raise NotImplementedError(
                    "serving engine v1: covariate-dependent random levels "
                    "(xDim > 0) are not servable — use hmsc_tpu.predict")
            # per-chain thinning rides Posterior.pooled so an mmap-backed
            # history copies only the kept rows
            pooled = {"Beta": post.pooled("Beta", thin=draw_thin),
                      "sigma": post.pooled("sigma", thin=draw_thin)}
            for r in range(spec.nr):
                pooled[f"Eta_{r}"] = post.pooled(f"Eta_{r}",
                                                 thin=draw_thin)
                # the x_dim==0 ndim-4 trim happens once, in the shared
                # staging loop below
                pooled[f"Lambda_{r}"] = post.pooled(f"Lambda_{r}",
                                                    thin=draw_thin)
            st.ns = int(hM.ns)
            st.nc = int(hM.nc)
            st.fam = np.asarray(hM.distr[:, 0], dtype=np.int32)
            m, s = hM.y_scale_par
            ym = np.asarray(m, dtype=np.float32)
            ys = np.asarray(s, dtype=np.float32)
            st.level_names = list(hM.rl_names)
            unit_lists = [list(hM.pi_names[r]) for r in range(spec.nr)]
            st.artifact = None

        st.nr = len(st.level_names)
        st.n_draws = int(pooled["Beta"].shape[0])
        st.any_probit = bool((st.fam == 2).any())
        st.any_normal = bool((st.fam == 1).any())
        st.any_poisson = bool((st.fam == 3).any())
        st.ym_host, st.ys_host = ym, ys
        st.draw_shards, st.mesh = self._resolve_draw_mesh(st.n_draws)
        # unit label -> Eta row; unknown labels get the appended zero row
        # (index np_r): the mean-field new-unit semantics
        st.unit_lut = [{str(u): i for i, u in enumerate(us)}
                       for us in unit_lists]
        st.new_unit = [len(us) for us in unit_lists]

        with self.telem.span("stage", n_draws=st.n_draws, gen=st.gen,
                             draw_shards=st.draw_shards):
            f32 = jnp.float32

            def _stage_dtype(a):
                # preserve a bf16-stored artifact's dtype on device; all
                # other sources stage f32 exactly as before
                import ml_dtypes
                if getattr(a, "dtype", None) == ml_dtypes.bfloat16:
                    return jnp.bfloat16
                return f32

            def _stage(a, name):
                # single device: jnp.asarray exactly as before (zero-copy
                # for the f32 memmap).  On a draw mesh: device_put with
                # the SERVE_DRAW_DIMS NamedSharding so each device holds
                # only its contiguous draw block — bf16-stored artifacts
                # stage their STORED dtype per device (half the per-device
                # HBM, same as the single-device path).
                if st.mesh is None:
                    return jnp.asarray(a, _stage_dtype(a))
                import jax
                from jax.sharding import NamedSharding

                from ..mcmc.partition import serve_draw_pspec
                host = np.asarray(a, dtype=np.dtype(_stage_dtype(a)))
                return jax.device_put(
                    host, NamedSharding(st.mesh, serve_draw_pspec(name)))

            st.Beta = _stage(pooled["Beta"], "Beta")
            st.sigma = _stage(pooled["sigma"], "sigma")
            lams, etas = [], []
            for r in range(st.nr):
                lam = pooled[f"Lambda_{r}"]
                if lam.ndim == 4:
                    lam = lam[..., 0]
                lams.append(_stage(lam, f"Lambda_{r}"))
                dt = np.dtype(_stage_dtype(pooled[f"Eta_{r}"]))
                eta = np.asarray(pooled[f"Eta_{r}"], dtype=dt)
                zero = np.zeros((eta.shape[0], 1, eta.shape[2]), dtype=dt)
                etas.append(_stage(np.concatenate([eta, zero], axis=1),
                                   f"Eta_{r}"))
            st.lams = tuple(lams)
            st.etas = tuple(etas)
            st.fam_d = jnp.asarray(st.fam)
            st.ym = jnp.asarray(ym)
            st.ys = jnp.asarray(ys)
        # the compile-cache facet of a generation: kernels retrace only
        # when a staged shape/dtype, the draw-mesh width, or a trace-time
        # static actually changed, so a same-shape flip on the same mesh
        # reuses every compiled kernel — zero recompiles
        st.shape_key = (
            (st.nr, st.any_probit, st.any_normal, st.any_poisson,
             st.draw_shards),
        ) + tuple((tuple(a.shape), str(a.dtype))
                  for a in (st.Beta, st.sigma, *st.lams, *st.etas))
        return st

    def _resolve_draw_mesh(self, n_draws: int):
        """Resolve the requested draw-mesh width against this
        generation's draw count and the visible device count: the widest
        divisor of ``n_draws`` not exceeding either, warning when it
        differs from the request (``nearest_divisor`` semantics — the
        engine serves correctly at the fallback width rather than
        refusing).  Returns ``(draw_shards, mesh-or-None)``; width 1 is
        the committed single-device path (no mesh, no shard_map)."""
        k_req = self._draw_shards_req
        if k_req is None or k_req == 1:
            return 1, None
        import jax

        from ..utils.mesh import make_draw_mesh
        ndev = len(jax.devices())
        cap = min(k_req, ndev, int(n_draws))
        k = max(d for d in range(1, cap + 1) if n_draws % d == 0)
        if k != k_req:
            import warnings
            warnings.warn(
                f"draw_shards={k_req} does not fit n_draws={n_draws} on "
                f"{ndev} device(s); serving at the nearest width "
                f"draw_shards={k}", stacklevel=3)
        if k == 1:
            return 1, None
        mesh = self._mesh_cache.get(k)
        if mesh is None:
            mesh = self._mesh_cache[k] = make_draw_mesh(k)
        return k, mesh

    # ------------------------------------------------------------------
    # epoch flip
    # ------------------------------------------------------------------

    def reload(self, source=None, *, warmup: bool = True,
               trace=None) -> dict:
        """Hot-reload the served posterior and flip to it atomically.

        ``source=None`` re-resolves the engine's ORIGINAL source — for an
        epoched run directory that picks up the newest committed epoch
        (the streaming-refit serving flip); pass an explicit source to
        re-point the engine.  The new generation is fully staged (and, by
        default, its predict kernels pre-warmed when the staged shapes
        changed) BEFORE the swap, so the flip itself is one reference
        assignment: queries already submitted finish on the old
        generation, queries submitted after see the new one, and nothing
        ever observes a half-staged posterior.  Returns a summary dict
        (old/new epoch, generation, whether shapes changed)."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        import jax.numpy as jnp

        with self._reload_lock:      # one flip at a time: concurrent
            #                          reloads must not duplicate gen
            #                          numbers or discard a staged build
            old = self._staged
            src = self._source if source is None else source
            new = self._build_staged(
                src, self._hM0 if source is None else None,
                self._draw_thin, old.gen + 1)
            shapes_changed = new.shape_key != old.shape_key
            if shapes_changed and warmup:
                # pre-warm OFF the query path: compile the new shapes'
                # predict kernels before any query can reach them (counted
                # as cache misses — they are real compiles — but paid
                # here, not by the first post-flip query)
                for b in self.buckets:
                    fn = self._kernel(new, ("predict", True), b)
                    args = self._device_args(
                        new, ("predict", True),
                        np.zeros((b, new.nc), np.float32),
                        np.full((new.nr, b), 0, np.int32))
                    jnp.asarray(fn(*args)[0]).block_until_ready()
            self._staged = new                  # the atomic flip
            self._last_flip_wall = time.time()
            if source is not None:
                self._source = source
                self._hM0 = None
        # `trace` (a TraceContext parsed from the caller's X-Hmsc-Trace
        # header) joins this flip to the rollout that requested it
        self.telem.emit("run", "epoch_flip", gen=new.gen,
                        old_epoch=old.epoch, epoch=new.epoch,
                        n_draws=new.n_draws,
                        shapes_changed=bool(shapes_changed),
                        **(trace.fields() if trace is not None else {}))
        if self.telem.has_sink:
            self.telem.flush()        # flips must be tailable live
        return {"old_epoch": old.epoch, "epoch": new.epoch,
                "generation": new.gen, "n_draws": new.n_draws,
                "shapes_changed": bool(shapes_changed),
                "last_flip_wall": self._last_flip_wall}

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def submit(self, X, *, units=None, Yc=None, expected: bool = True,
               mcmc_step: int = 1, quantiles=None) -> Future:
        """Enqueue one prediction query; returns a Future resolving to
        ``{"mean": (q, ns), "sd": (q, ns)}`` — plus ``{"quantiles":
        (nq, q, ns), "q": [...]}`` when ``quantiles`` is given.

        ``X`` is the (q, nc) design block (model scale, intercept
        included).  ``units`` optionally maps level name -> q unit labels
        (training labels gather their posterior Eta rows; unknown labels
        serve mean-field).  ``Yc`` (q, ns) with NaN for unobserved cells
        switches to conditional prediction refined by ``mcmc_step`` Gibbs
        iterations.  ``expected=False`` samples responses instead of
        returning the location parameter.  ``quantiles`` (marginal
        prediction only) is a sequence of probabilities in [0, 1]: the
        kernel computes full-draw response quantiles on device before the
        draw-axis reduction — each distinct tuple is its own compiled
        config, so steady traffic should reuse a small fixed set."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        st = self._staged            # one generation per request, start to
        #                              finish — an epoch flip mid-request
        #                              cannot mix LUTs and arrays
        X = np.atleast_2d(np.asarray(X, dtype=np.float32))
        q = X.shape[0]
        if X.shape[1] != st.nc:
            raise ValueError(
                f"query X has {X.shape[1]} columns, the model has "
                f"nc={st.nc} covariates (intercept included)")
        uidx = np.empty((st.nr, q), dtype=np.int32)
        for r in range(st.nr):
            lut, new = st.unit_lut[r], st.new_unit[r]
            if units is None or st.level_names[r] not in units:
                uidx[r] = new
            else:
                labels = units[st.level_names[r]]
                if len(labels) != q:
                    raise ValueError(
                        f"units[{st.level_names[r]!r}] has {len(labels)} "
                        f"labels for {q} query rows")
                uidx[r] = [lut.get(str(u), new) for u in labels]
        arrays = {"X": X, "uidx": uidx}
        qs = ()
        if quantiles is not None:
            qs = tuple(float(q) for q in np.atleast_1d(
                np.asarray(quantiles, dtype=np.float64)))
            if not qs or any(not (0.0 <= q <= 1.0) for q in qs):
                raise ValueError(
                    f"quantiles must be probabilities in [0, 1], got "
                    f"{quantiles!r}")
            if Yc is not None:
                raise NotImplementedError(
                    "serving engine v1: quantiles are marginal-prediction "
                    "only (conditional queries return mean/sd)")
        if Yc is not None:
            Yc = np.atleast_2d(np.asarray(Yc, dtype=np.float32))
            if Yc.shape != (q, st.ns):
                raise ValueError(
                    f"Yc has shape {Yc.shape}, expected ({q}, {st.ns})")
            if st.any_poisson:
                raise NotImplementedError(
                    "serving engine v1: conditional prediction conditions "
                    "on probit/normal cells only — Poisson models fall "
                    "back to hmsc_tpu.predict(Yc=...)")
            # to the model's (y-scaled) Z scale, NaNs masked out
            Ycs = (Yc - st.ym_host[None, :]) / st.ys_host[None, :]
            mask = (~np.isnan(Ycs)).astype(np.float32)
            arrays["Yc"] = np.nan_to_num(Ycs, nan=0.0).astype(np.float32)
            arrays["mask"] = mask
            config = ("cond", bool(expected), int(mcmc_step))
        elif qs:
            config = ("predict", bool(expected), qs)
        else:
            config = ("predict", bool(expected))
        req = _Request(config, q, arrays, Future(), st)
        with self._lock:
            self._n_requests += 1
        self._queue.put(req)
        return req.future

    def predict(self, X, **kw) -> dict:
        """Synchronous :meth:`submit`."""
        return self.submit(X, **kw).result()

    def gradient(self, focal_variable: str, non_focal_variables=None,
                 ngrid: int = 20, expected: bool = True) -> dict:
        """Serve an environmental-gradient query: the
        :func:`~hmsc_tpu.predict.construct_gradient` design for
        ``focal_variable``, answered through the bucketed predict kernels
        (new gradient units serve mean-field).  Returns
        ``{"grid", "mean", "sd"}``."""
        hM = self._staged.hM
        if hM is None:
            raise ValueError(
                "gradient queries need the fitted Hmsc model (formula + "
                "training covariates); construct the engine with hM=")
        from ..predict.gradient import construct_gradient
        from ..utils.formula import design_matrix

        grad = construct_gradient(hM, focal_variable,
                                  non_focal_variables, ngrid=ngrid)
        Xn, _ = design_matrix(hM.x_formula, grad["XDataNew"])
        out = self.predict(np.asarray(Xn, dtype=np.float32),
                           expected=expected)
        out["grid"] = np.asarray(grad["XDataNew"][focal_variable])
        return out

    def warmup(self, *, expected: bool = True, conditional: bool = False,
               mcmc_step: int = 1) -> int:
        """Precompile one kernel per bucket for the given config (and the
        conditional variant when asked), so first-query latency is a
        dispatch, not a compile.  Returns the number of kernels built."""
        import jax.numpy as jnp

        st = self._staged
        built = 0
        configs = [("predict", bool(expected))]
        if conditional:
            configs.append(("cond", bool(expected), int(mcmc_step)))
        for config in configs:
            for b in self.buckets:
                with self._lock:
                    fresh = (config, b, st.shape_key) not in self._cache
                fn = self._kernel(st, config, b)
                if fresh:
                    built += 1
                    args = self._device_args(
                        st, config, np.zeros((b, st.nc), np.float32),
                        np.full((st.nr, b), 0, np.int32),
                        np.zeros((b, st.ns), np.float32),
                        np.zeros((b, st.ns), np.float32))
                    # force the compile now (block on the result)
                    jnp.asarray(fn(*args)[0]).block_until_ready()
        return built

    def stats(self) -> dict:
        """Serving counters + compile-cache stats + span aggregates."""
        st = self._staged
        with self._lock:
            cache = {"hits": self._hits, "misses": self._misses,
                     "size": len(self._cache),
                     "capacity": self.cache_size}
            counts = {"requests": self._n_requests,
                      "batches": self._n_batches,
                      "device_calls": self._n_device_calls,
                      "rows_served": self._rows_served,
                      "rows_padded": self._rows_padded}
        return {"n_draws": st.n_draws, "ns": st.ns,
                "epoch": st.epoch, "generation": st.gen,
                "last_flip_wall": self._last_flip_wall,
                "buckets": list(self.buckets),
                "coalesce_ms": self.coalesce_s * 1e3,
                "draw_shards": int(st.draw_shards),
                "n_devices": int(st.draw_shards),
                "mesh": None if st.mesh is None else
                        {"draws": int(st.draw_shards)},
                "cache": cache, **counts,
                "spans": self.telem.totals()}

    def close(self) -> None:
        """Stop the batching worker (pending requests fail)."""
        if self._closed:
            return
        self._closed = True
        self._queue.put(_STOP)
        self._worker.join(timeout=10.0)
        # fail anything that raced past the _closed check in submit() and
        # landed behind the sentinel — a Future must never hang forever
        while True:
            try:
                item = self._queue.get_nowait()
            except _queue.Empty:
                break
            if item is not _STOP and not item.future.done():
                item.future.set_exception(
                    RuntimeError("ServingEngine closed"))
        self.telem.flush()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # ------------------------------------------------------------------
    # compile cache
    # ------------------------------------------------------------------

    def _kernel(self, st, config, bucket: int):
        import jax

        key = (config, int(bucket), st.shape_key)
        with self._lock:
            fn = self._cache.get(key)
            if fn is not None:
                self._hits += 1
                self._cache.move_to_end(key)
                return fn
            self._misses += 1
        # build outside the lock (tracing/compiling can be slow); a racing
        # duplicate build is harmless — last one in wins the cache slot
        if config[0] == "predict":
            # ("predict", expected) or ("predict", expected, quantiles)
            q = config[2] if len(config) > 2 else ()
            if st.mesh is None:
                raw = make_predict_kernel(
                    nr=st.nr, expected=config[1], any_probit=st.any_probit,
                    any_poisson=st.any_poisson, quantiles=q)
            else:
                raw = make_sharded_predict_kernel(
                    st.mesh, nr=st.nr, expected=config[1],
                    any_probit=st.any_probit, any_poisson=st.any_poisson,
                    quantiles=q)
        elif st.mesh is None:
            raw = make_conditional_kernel(
                nr=st.nr, mcmc_step=config[2], expected=config[1],
                any_probit=st.any_probit, any_normal=st.any_normal)
        else:
            raw = make_sharded_conditional_kernel(
                st.mesh, nr=st.nr, mcmc_step=config[2], expected=config[1],
                any_probit=st.any_probit, any_normal=st.any_normal)
        fn = jax.jit(raw)
        self.telem.emit("metric", "kernel_build",
                        config=list(map(str, config)), bucket=int(bucket))
        with self._lock:
            self._cache[key] = fn
            self._cache.move_to_end(key)
            while len(self._cache) > self.cache_size:
                self._cache.popitem(last=False)
        return fn

    def _bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if b >= rows:
                return b
        return self.max_bucket

    def _device_args(self, st, config, Xpad, uidx, Yc=None, mask=None):
        import jax

        key = jax.random.key(int(self._rng.integers(0, 2**31 - 1)))
        base = (st.Beta, st.sigma, st.lams, st.etas, st.fam_d,
                st.ym, st.ys, Xpad, uidx)
        if config[0] == "predict":
            return base + (key,)
        return base + (Yc, mask, key)

    # ------------------------------------------------------------------
    # coalescing worker
    # ------------------------------------------------------------------

    def _run(self) -> None:
        pending: collections.deque = collections.deque()
        while True:
            if pending:
                item = pending.popleft()
            else:
                item = self._queue.get()
            if item is _STOP:
                break
            batch, rows = [item], item.n_rows
            deadline = time.perf_counter() + self.coalesce_s
            stop = False
            while rows < self.max_bucket:
                wait = deadline - time.perf_counter()
                if wait <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=wait)
                except _queue.Empty:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                # same kernel config AND same generation: a batch must
                # never mix requests validated against different epochs
                if nxt.config == item.config \
                        and nxt.staged is item.staged:
                    batch.append(nxt)
                    rows += nxt.n_rows
                else:
                    pending.append(nxt)
                    break            # dispatch what we have; regroup next
            try:
                self._dispatch(batch)
            except Exception as e:   # noqa: BLE001 — a query must fail its
                # futures, never kill the serving loop
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)
            if stop:
                break
        while pending:
            req = pending.popleft()
            req.future.set_exception(RuntimeError("ServingEngine closed"))

    def _dispatch(self, batch: list) -> None:
        import jax.numpy as jnp

        st = batch[0].staged         # the generation every request in this
        #                              batch was validated against
        config = batch[0].config
        now = time.perf_counter()
        for req in batch:
            self.telem.observe("queue_wait", now - req.t_submit,
                               rows=req.n_rows)
        total = sum(req.n_rows for req in batch)
        conditional = config[0] == "cond"

        with self.telem.span("pad", rows=total) as sp:
            X = np.concatenate([req.arrays["X"] for req in batch], axis=0)
            uidx = np.concatenate([req.arrays["uidx"] for req in batch],
                                  axis=1)
            Yc = mask = None
            if conditional:
                Yc = np.concatenate([req.arrays["Yc"] for req in batch],
                                    axis=0)
                mask = np.concatenate([req.arrays["mask"] for req in batch],
                                      axis=0)
            calls, padded = [], 0
            for c0 in range(0, total, self.max_bucket):
                n = min(self.max_bucket, total - c0)
                b = self._bucket_for(n)
                padded += b - n
                Xp = np.zeros((b, st.nc), dtype=np.float32)
                Xp[:n] = X[c0:c0 + n]
                up = np.empty((st.nr, b), dtype=np.int32)
                up[:] = np.asarray(st.new_unit,
                                   dtype=np.int32).reshape(-1, 1) \
                    if st.nr else 0
                up[:, :n] = uidx[:, c0:c0 + n]
                Ycp = maskp = None
                if conditional:
                    Ycp = np.zeros((b, st.ns), dtype=np.float32)
                    Ycp[:n] = Yc[c0:c0 + n]
                    maskp = np.zeros((b, st.ns), dtype=np.float32)
                    maskp[:n] = mask[c0:c0 + n]
                calls.append((n, b, Xp, up, Ycp, maskp))
            sp.fields["padded"] = padded

        has_q = config[0] == "predict" and len(config) > 2 and config[2]
        outs, qouts = [], []
        for n, b, Xp, up, Ycp, maskp in calls:
            fn = self._kernel(st, config, b)
            with self.telem.span("dispatch", bucket=b, rows=n):
                res = fn(*self._device_args(st, config, Xp, up,
                                            Ycp, maskp))
            with self.telem.span("fetch", bucket=b):
                outs.append((np.asarray(res[0])[:n], np.asarray(res[1])[:n]))
                if has_q:
                    qouts.append(np.asarray(res[2])[:, :n])
        mean = np.concatenate([m for m, _ in outs], axis=0)
        sd = np.concatenate([s for _, s in outs], axis=0)
        quants = np.concatenate(qouts, axis=1) if has_q else None

        with self._lock:
            self._n_batches += 1
            self._n_device_calls += len(calls)
            self._rows_served += total
            self._rows_padded += sum(b - n for n, b, *_ in calls)
        off = 0
        for req in batch:
            # the generation/epoch the answer was COMPUTED on (a flip
            # landing mid-response must not mislabel it): the fleet's
            # mixed-generation drill asserts on these
            res = {"mean": mean[off:off + req.n_rows],
                   "sd": sd[off:off + req.n_rows],
                   "generation": st.gen, "epoch": st.epoch}
            if has_q:
                res["quantiles"] = quants[:, off:off + req.n_rows]
                res["q"] = list(config[2])
            req.future.set_result(res)
            off += req.n_rows
        if self.telem.has_sink:
            self.telem.flush()
