"""``python -m hmsc_tpu serve`` — stdlib HTTP + JSON front end over
:class:`~hmsc_tpu.serve.engine.ServingEngine`.

A deliberately dependency-free server: ``ThreadingHTTPServer`` handles
each connection on its own thread, every handler thread funnels its query
through ``engine.submit`` — so concurrent HTTP requests micro-batch into
shared device calls exactly like in-process callers.

Endpoints::

    POST /predict   {"X": [[...]], "units": {level: [...]}?, "Yc": ...?,
                     "expected": true?, "mcmc_step": 1?,
                     "quantiles": [0.05, 0.5, 0.95]?}
                    -> {"mean": [[...]], "sd": [[...]], "n_draws": N}
                       (+ "quantiles"/"q" when requested: full-draw
                       response quantiles computed on device)
    POST /gradient  {"focal": "x1", "ngrid": 20?, "expected": true?}
    POST /flip      {"source": "<path>"?, "warmup": true?}  — admin: hot-
                    reload the served posterior and flip to it atomically
                    (source omitted = re-resolve the engine's run
                    directory, i.e. pick up the newest committed refit
                    epoch); in-flight queries finish on the old epoch
    GET  /healthz   liveness + posterior shape + served epoch/generation
    GET  /statz     engine stats (counters, cache, span aggregates)
    GET  /metrics   Prometheus textfile export (obs.report machinery)

``serve <dir>`` accepts a compacted artifact directory (self-contained)
or a run directory written by ``python -m hmsc_tpu run`` (the model is
rebuilt from its ``model.json``).  ``--prom FILE`` additionally writes
the Prometheus textfile on shutdown for node-exporter collection.
"""

from __future__ import annotations

import json

__all__ = ["make_server", "serve_main"]


def _json_body(handler):
    n = int(handler.headers.get("Content-Length") or 0)
    raw = handler.rfile.read(n) if n else b"{}"
    try:
        doc = json.loads(raw.decode() or "{}")
    except ValueError as e:
        raise ValueError(f"request body is not valid JSON: {e}") from e
    if not isinstance(doc, dict):
        raise ValueError("request body must be a JSON object")
    return doc


def make_server(engine, host: str = "127.0.0.1", port: int = 0):
    """A ready-to-run ``ThreadingHTTPServer`` bound to ``engine`` (port 0
    picks a free port; read it back from ``server.server_address``)."""
    import http.server

    import numpy as np

    from ..obs.report import serving_prometheus_textfile
    from ..obs.trace import from_header

    class Handler(http.server.BaseHTTPRequestHandler):
        # route access logging through the library logger, not stderr
        def log_message(self, fmt, *args):  # noqa: ARG002 — BaseHTTP API
            pass

        def _send(self, code: int, payload, content_type="application/json"):
            body = (payload if isinstance(payload, bytes)
                    else json.dumps(payload).encode())
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTP API
            if self.path == "/healthz":
                self._send(200, {"ok": True, "n_draws": engine.n_draws,
                                 "ns": engine.ns, "nc": engine.nc,
                                 "epoch": engine.epoch,
                                 "generation": engine.generation,
                                 "last_flip_wall": engine.last_flip_wall,
                                 "draw_shards": engine.draw_shards,
                                 "buckets": list(engine.buckets)})
            elif self.path == "/statz":
                self._send(200, engine.stats())
            elif self.path == "/metrics":
                self._send(200,
                           serving_prometheus_textfile(
                               engine.stats()).encode(),
                           content_type="text/plain; version=0.0.4")
            else:
                self._send(404, {"error": f"unknown path {self.path!r}"})

        def do_POST(self):  # noqa: N802 — BaseHTTP API
            # cross-process trace correlation: a request carrying an
            # X-Hmsc-Trace header (e.g. an autopilot-driven flip, or the
            # first query against a freshly flipped epoch) joins the
            # caller's trace — its serve events and its response tag the
            # same trace_id the rollout started with
            tctx = from_header(self.headers.get("X-Hmsc-Trace") or "")
            try:
                doc = _json_body(self)
                if self.path == "/predict":
                    X = np.asarray(doc["X"], dtype=np.float32)
                    Yc = doc.get("Yc")
                    if Yc is not None:
                        # JSON has no NaN literal: null marks unobserved
                        Yc = np.asarray(
                            [[np.nan if v is None else float(v) for v in row]
                             for row in Yc], dtype=np.float32)
                    out = engine.predict(
                        X, units=doc.get("units"), Yc=Yc,
                        expected=bool(doc.get("expected", True)),
                        mcmc_step=int(doc.get("mcmc_step", 1)),
                        quantiles=doc.get("quantiles"))
                elif self.path == "/gradient":
                    out = engine.gradient(
                        doc["focal"],
                        non_focal_variables=doc.get("non_focal"),
                        ngrid=int(doc.get("ngrid", 20)),
                        expected=bool(doc.get("expected", True)))
                    out["grid"] = np.asarray(out["grid"])
                elif self.path == "/flip":
                    self._send(200, engine.reload(
                        doc.get("source"),
                        warmup=bool(doc.get("warmup", True)),
                        trace=tctx))
                    return
                else:
                    self._send(404,
                               {"error": f"unknown path {self.path!r}"})
                    return
                if tctx is not None:
                    # a traced query leaves an event: the hub links the
                    # first post-flip query to the rollout's trace
                    engine.telem.emit(
                        "metric", "query", path=self.path,
                        epoch=out.get("epoch"),
                        generation=out.get("generation"),
                        **tctx.fields())
                    if engine.telem.has_sink:
                        engine.telem.flush()
                self._send(200, {
                    "mean": np.asarray(out["mean"]).tolist(),
                    "sd": np.asarray(out["sd"]).tolist(),
                    **({"grid": out["grid"].tolist()}
                       if "grid" in out else {}),
                    **({"quantiles": np.asarray(out["quantiles"]).tolist(),
                        "q": out["q"]}
                       if "quantiles" in out else {}),
                    "n_draws": engine.n_draws,
                    **({"generation": out["generation"],
                        "epoch": out["epoch"]}
                       if "generation" in out else {}),
                    **({"trace": tctx.trace_id} if tctx is not None else {}),
                })
            except (KeyError, ValueError, NotImplementedError) as e:
                self._send(400, {"error": f"{type(e).__name__}: {e}"})
            except Exception as e:   # noqa: BLE001 — a failed query must
                # answer 500, never take down the server loop
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

    return http.server.ThreadingHTTPServer((host, int(port)), Handler)


def serve_main(argv=None) -> int:
    """``python -m hmsc_tpu serve`` — long-lived posterior serving."""
    import argparse

    from ..obs import get_logger
    from .engine import DEFAULT_BUCKETS, ServingEngine

    ap = argparse.ArgumentParser(
        prog="python -m hmsc_tpu serve",
        description="serve batched posterior predictions over HTTP from a "
                    "fitted run directory or a compacted serving artifact")
    ap.add_argument("source", nargs="?", default=None,
                    help="compacted artifact directory (`hmsc_tpu "
                         "compact`), or a run directory written by "
                         "`python -m hmsc_tpu run` (optional with --fleet: "
                         "the fleet config names its own source)")
    ap.add_argument("--fleet", metavar="CONFIG", default=None,
                    help="run a replicated serving fleet from a JSON "
                         "config instead of a single engine: N supervised "
                         "replica processes behind one front end "
                         "(see fleet.serving.ServeFleetConfig)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--buckets",
                    default=",".join(str(b) for b in DEFAULT_BUCKETS),
                    help="comma-separated padded query-row buckets")
    ap.add_argument("--coalesce-ms", type=float, default=2.0,
                    help="micro-batch coalescing window (milliseconds)")
    ap.add_argument("--draw-thin", type=int, default=1,
                    help="serve every Nth pooled draw")
    ap.add_argument("--draw-shards", type=int, default=None,
                    help="shard the posterior draw axis over this many "
                         "local devices (1/omitted = single device)")
    ap.add_argument("--telemetry-dir", default=None,
                    help="write the serving event stream "
                         "(events-p0.jsonl) here")
    ap.add_argument("--prom", metavar="FILE", default=None,
                    help="write a Prometheus textfile export of the final "
                         "serving gauges on shutdown (live scrape: "
                         "GET /metrics)")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip precompiling one predict kernel per bucket "
                         "at startup")
    # replica mode (spawned by the serving fleet — not for direct use):
    # beats a heartbeat file carrying the bound port so the parent
    # discovers where a port-0 replica landed and watches its liveness
    ap.add_argument("--replica-rank", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--heartbeat-dir", default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--heartbeat-interval-s", type=float, default=0.25,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.fleet is not None:
        from ..fleet.serving import serve_fleet_main
        return serve_fleet_main(args.fleet, source_override=args.source)
    if args.source is None:
        ap.error("source is required (unless --fleet is given)")

    log = get_logger()
    engine = ServingEngine(
        args.source,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        coalesce_ms=args.coalesce_ms, draw_thin=args.draw_thin,
        draw_shards=args.draw_shards,
        telemetry=args.telemetry_dir)
    if not args.no_warmup:
        n = engine.warmup()
        log.info(f"serve: precompiled {n} predict kernels "
                 f"(buckets {list(engine.buckets)})")
    server = make_server(engine, args.host, args.port)
    host, port = server.server_address[:2]
    log.info(f"serve: {engine.n_draws} draws x {engine.ns} species ready "
             f"on http://{host}:{port} (POST /predict, /gradient; "
             f"GET /healthz, /statz, /metrics)")
    hb = hb_stop = None
    if args.heartbeat_dir is not None:
        # serving-replica liveness beacon: same machinery as the fleet
        # sampler ranks; the payload's `port` is how the parent finds a
        # port-0 replica, generation/epoch ride along for observability
        import threading

        from ..utils.coordination import HeartbeatWriter
        hb = HeartbeatWriter(args.heartbeat_dir, args.replica_rank or 0,
                             interval_s=args.heartbeat_interval_s)
        hb.update(port=int(port), host=str(host), role="serve_replica",
                  generation=engine.generation, epoch=engine.epoch)
        hb.start()
        hb_stop = threading.Event()

        def _refresh():
            while not hb_stop.wait(args.heartbeat_interval_s):
                hb.update(generation=engine.generation, epoch=engine.epoch)
        threading.Thread(target=_refresh, daemon=True,
                         name="hmsc-serve-hb-refresh").start()
    # SIGTERM unwinds like Ctrl-C: the --prom export and the telemetry
    # flush must survive an orchestrator's ordinary stop signal, same as
    # the sampler's preemption-safe shutdown
    import signal

    def _term(signum, frame):  # noqa: ARG001 — signal API
        raise KeyboardInterrupt
    old_term = signal.signal(signal.SIGTERM, _term)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        log.info("serve: interrupted, shutting down")
    finally:
        signal.signal(signal.SIGTERM, old_term)
        if hb is not None:
            hb_stop.set()
            hb.stop()
        server.server_close()
        engine.close()
        if args.prom:
            from ..obs.report import serving_prometheus_textfile
            with open(args.prom, "w") as f:
                f.write(serving_prometheus_textfile(engine.stats()))
    return 0


if __name__ == "__main__":
    raise SystemExit(serve_main())
