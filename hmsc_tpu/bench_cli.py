"""Installed-package CLI entry points.

``main`` (= ``hmsc-tpu-bench`` / ``python -m hmsc_tpu bench``) measures
steady-state posterior samples/sec of the blocked-Gibbs engine on whatever
accelerator JAX finds (compile excluded, best-of-3 windows) and prints one
JSON line.  The repo-level ``bench.py`` harness additionally runs the
reference-style NumPy baseline for a measured ``vs_baseline`` ratio; from an
installed wheel only the package itself is available, so the ratio is
reported as ``null`` here.

``run_main`` (= ``python -m hmsc_tpu run``) drives a checkpointed sampling
run of the same synthetic probit JSDM: auto-snapshots every
``--checkpoint-every`` samples into ``--checkpoint-dir`` (pipelined host
loop: fetches + writes overlap the next segment's compute; ``--no-pipeline``
serialises for A/B), exits with the documented code taxonomy
(:mod:`hmsc_tpu.exit_codes`): 75 (EX_TEMPFAIL) when preempted by
SIGTERM/SIGINT after writing a resumable snapshot, 77 when the run
completed but chains ended diverged and unhealed, 78 when ``--resume``
found no usable checkpoint, 1 otherwise — so a supervisor or shell script
can branch on the failure class.  ``--resume``
continues from the newest valid one (corrupt slots fall back to the
previous rotation slot; ``--verbose`` / ``--checkpoint-every`` act as
draw-invariant overrides).  Snapshots use the append-only layout by
default (O(segment) per snapshot; ``--layout rotating`` keeps the legacy
self-contained files).  Rotation: ``--keep`` newest, ``--keep-age-s`` age
policy, ``--max-bytes`` total-bytes budget, ``--archive-every`` Nth
snapshot archived.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _model(ny, ns, nf, seed=66):
    import pandas as pd

    from .model import Hmsc
    from .random_level import HmscRandomLevel, set_priors_random_level

    rng = np.random.default_rng(seed)
    X = np.column_stack([np.ones(ny), rng.standard_normal(ny)])
    Y = ((X @ (rng.standard_normal((2, ns)) * 0.5)
          + rng.standard_normal((ny, 2)) @ (rng.standard_normal((2, ns)) * 0.7)
          + rng.standard_normal((ny, ns))) > 0).astype(float)
    study = pd.DataFrame({"sample": [f"s{i:04d}" for i in range(ny)]})
    rL = HmscRandomLevel(units=study["sample"])
    set_priors_random_level(rL, nf_max=nf, nf_min=2)
    return Hmsc(Y=Y, X=X, study_design=study, ran_levels={"sample": rL},
                distr="probit", x_scale=False)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="hmsc-tpu sampling-throughput probe")
    parser.add_argument("--ny", type=int, default=200)
    parser.add_argument("--ns", type=int, default=100)
    parser.add_argument("--nf", type=int, default=4)
    parser.add_argument("--samples", type=int, default=200)
    parser.add_argument("--chains", type=int, default=4)
    args = parser.parse_args(argv)

    import jax

    from .mcmc.sampler import sample_mcmc

    hM = _model(args.ny, args.ns, args.nf)
    kw = dict(samples=args.samples, transient=10, n_chains=args.chains,
              align_post=False, nf_cap=args.nf)
    sample_mcmc(hM, seed=0, **kw)               # warm-up: compile
    t = np.inf
    for rep in range(3):
        t0 = time.time()
        post = sample_mcmc(hM, seed=1 + rep, **kw)
        t = min(t, time.time() - t0)
        assert np.all(np.isfinite(post["Beta"]))
    print(json.dumps({
        "metric": f"posterior samples/sec ({args.ns}-species probit JSDM, "
                  f"{args.chains} chains, {jax.devices()[0].platform})",
        "value": round(args.chains * args.samples / t, 2),
        "unit": "samples/sec",
        "vs_baseline": None,
    }))


def run_main(argv=None):
    """``python -m hmsc_tpu run`` — fault-tolerant long-run driver."""
    parser = argparse.ArgumentParser(
        prog="python -m hmsc_tpu run",
        description="checkpointed (preemption-safe, resumable) sampling run "
                    "of the synthetic benchmark JSDM")
    parser.add_argument("--ny", type=int, default=200)
    parser.add_argument("--ns", type=int, default=100)
    parser.add_argument("--nf", type=int, default=4)
    parser.add_argument("--samples", type=int, default=200)
    parser.add_argument("--transient", type=int, default=50)
    parser.add_argument("--chains", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--verbose", type=int, default=0)
    parser.add_argument("--checkpoint-dir", required=True,
                        help="snapshot directory (append layout: shards + "
                             "state files + manifests; --layout rotating: "
                             "self-contained ckpt-<n>.npz files)")
    parser.add_argument("--checkpoint-every", type=int, default=None,
                        help="recorded samples between snapshots "
                             "(default 25; on --resume the stored cadence "
                             "is kept unless this is given explicitly — "
                             "cadence only re-segments the host loop, so "
                             "the draws are unchanged)")
    parser.add_argument("--keep", type=int, default=None,
                        help="rotation depth (newest K snapshots kept; "
                             "default 3, stored cadence kept on --resume "
                             "unless given explicitly)")
    parser.add_argument("--keep-age-s", type=float, default=None,
                        help="additionally delete kept snapshots older than "
                             "this many seconds (newest always survives)")
    parser.add_argument("--archive-every", type=int, default=None,
                        help="hard-link every Nth snapshot into "
                             "<checkpoint-dir>/archive/, exempt from "
                             "rotation (post-hoc divergence debugging); "
                             "an explicit 0 on --resume stops archiving")
    parser.add_argument("--max-bytes", type=int, default=None,
                        help="total on-disk bytes budget for the snapshot "
                             "layout; oldest snapshots are dropped first "
                             "(the newest always survives)")
    parser.add_argument("--layout", choices=("append", "rotating"),
                        default=None,
                        help="snapshot layout: 'append' (default) writes "
                             "each flushed segment once as an immutable "
                             "shard + a small state file + a manifest "
                             "(O(segment) per snapshot); 'rotating' keeps "
                             "the legacy self-contained ckpt-<n>.npz files "
                             "(O(history) per snapshot)")
    parser.add_argument("--no-pipeline", action="store_true",
                        help="disable the background writer / donated-carry "
                             "pipeline (serialised host loop, for A/B)")
    parser.add_argument("--resume", action="store_true",
                        help="continue from the newest valid checkpoint "
                             "instead of starting fresh; --verbose and "
                             "--checkpoint-every act as overrides")
    args = parser.parse_args(argv)

    import os

    from .exit_codes import EXIT_CKPT_CORRUPT, EXIT_DIVERGED, EXIT_PREEMPTED
    from .mcmc.sampler import sample_mcmc
    from .utils.checkpoint import CheckpointError, PreemptedRun, resume_run

    # the spec fingerprint in every checkpoint rejects a resume against a
    # different model, so the model args are persisted next to the snapshots
    # and --resume rebuilds from them instead of trusting the CLI defaults
    model_json = os.path.join(args.checkpoint_dir, "model.json")
    if args.resume and os.path.exists(model_json):
        with open(model_json) as f:
            margs = json.load(f)
    else:
        margs = {"ny": args.ny, "ns": args.ns, "nf": args.nf}
    hM = _model(margs["ny"], margs["ns"], margs["nf"], seed=66)
    try:
        if args.resume:
            # the run configuration (samples/transient/chains/seed) always
            # comes from the checkpoint — passing different values with
            # --resume would otherwise be silently ignored
            import sys
            ignored = [f for f, v, d in (
                ("--samples", args.samples, 200),
                ("--transient", args.transient, 50),
                ("--chains", args.chains, 4),
                ("--seed", args.seed, 0)) if v != d]
            if ignored:
                print(f"run --resume: {', '.join(ignored)} ignored — the "
                      "run configuration comes from the checkpoint "
                      "(overridable: --verbose, --checkpoint-every, --keep, "
                      "--keep-age-s, --archive-every, --max-bytes, "
                      "--layout)", file=sys.stderr)
            post = resume_run(hM, args.checkpoint_dir, verbose=args.verbose,
                              checkpoint_every=args.checkpoint_every,
                              checkpoint_keep=args.keep,
                              checkpoint_max_age_s=args.keep_age_s,
                              # pass an explicit 0 through: it means "stop
                              # archiving", not "use the stored cadence"
                              checkpoint_archive_every=args.archive_every,
                              checkpoint_max_bytes=args.max_bytes,
                              checkpoint_layout=args.layout,
                              pipeline=not args.no_pipeline)
        else:
            os.makedirs(args.checkpoint_dir, exist_ok=True)
            with open(model_json, "w") as f:
                json.dump(margs, f)
            post = sample_mcmc(
                hM, samples=args.samples, transient=args.transient,
                n_chains=args.chains, seed=args.seed, nf_cap=args.nf,
                align_post=False, verbose=args.verbose,
                checkpoint_every=(25 if args.checkpoint_every is None
                                  else args.checkpoint_every),
                checkpoint_path=args.checkpoint_dir,
                checkpoint_keep=3 if args.keep is None else args.keep,
                checkpoint_max_age_s=args.keep_age_s,
                checkpoint_archive_every=args.archive_every or 0,
                checkpoint_max_bytes=args.max_bytes,
                checkpoint_layout=args.layout or "append",
                pipeline=not args.no_pipeline)
    except PreemptedRun as e:
        print(json.dumps({
            "preempted": True, "signal": e.signum,
            "samples_done": e.samples_done, "checkpoint": e.checkpoint_path,
            "resume": f"python -m hmsc_tpu run --resume --checkpoint-dir "
                      f"{args.checkpoint_dir}",
        }))
        return EXIT_PREEMPTED          # 75, EX_TEMPFAIL: try again (resume)
    except CheckpointError as e:
        # --resume found no usable snapshot (every slot corrupt, or the
        # directory belongs to a different model): blind retries cannot
        # help, so the code is distinct from the resumable failures —
        # a supervisor must stop and surface it
        print(json.dumps({"error": "checkpoint", "detail": str(e),
                          "checkpoint_dir": args.checkpoint_dir}))
        return EXIT_CKPT_CORRUPT       # 78
    good = np.asarray(post.chain_health["good_chains"])
    print(json.dumps({
        "preempted": False, "samples": int(post.samples),
        "chains": int(post.n_chains),
        "finite": bool(np.isfinite(post["Beta"]).all()),
        "diverged_chains": int((~good).sum()),
        "checkpoint_dir": args.checkpoint_dir,
    }))
    # divergence-abort: the run COMPLETED but chains ended non-finite and
    # no retry healed them — distinct from 0 (healthy) and from the
    # resumable 75/76 family, because a deterministic blow-up recurs on
    # restart; branch on 77 to inspect instead of resubmitting
    return 0 if good.all() else EXIT_DIVERGED


if __name__ == "__main__":
    main()
