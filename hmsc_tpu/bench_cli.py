"""Installed-package CLI entry points.

``main`` (= ``hmsc-tpu-bench`` / ``python -m hmsc_tpu bench``) measures
steady-state posterior samples/sec of the blocked-Gibbs engine on whatever
accelerator JAX finds (compile excluded, best-of-3 windows) and prints one
JSON line.  The repo-level ``bench.py`` harness additionally runs the
reference-style NumPy baseline for a measured ``vs_baseline`` ratio; from an
installed wheel only the package itself is available, so the ratio is
reported as ``null`` here.

``run_main`` (= ``python -m hmsc_tpu run``) drives a checkpointed sampling
run of the same synthetic probit JSDM: auto-snapshots every
``--checkpoint-every`` samples into ``--checkpoint-dir``, exits with code 75
(EX_TEMPFAIL) when preempted by SIGTERM/SIGINT after writing a resumable
snapshot, and ``--resume`` continues from the newest valid one (corrupt
slots fall back to the previous rotation slot).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _model(ny, ns, nf, seed=66):
    import pandas as pd

    from .model import Hmsc
    from .random_level import HmscRandomLevel, set_priors_random_level

    rng = np.random.default_rng(seed)
    X = np.column_stack([np.ones(ny), rng.standard_normal(ny)])
    Y = ((X @ (rng.standard_normal((2, ns)) * 0.5)
          + rng.standard_normal((ny, 2)) @ (rng.standard_normal((2, ns)) * 0.7)
          + rng.standard_normal((ny, ns))) > 0).astype(float)
    study = pd.DataFrame({"sample": [f"s{i:04d}" for i in range(ny)]})
    rL = HmscRandomLevel(units=study["sample"])
    set_priors_random_level(rL, nf_max=nf, nf_min=2)
    return Hmsc(Y=Y, X=X, study_design=study, ran_levels={"sample": rL},
                distr="probit", x_scale=False)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="hmsc-tpu sampling-throughput probe")
    parser.add_argument("--ny", type=int, default=200)
    parser.add_argument("--ns", type=int, default=100)
    parser.add_argument("--nf", type=int, default=4)
    parser.add_argument("--samples", type=int, default=200)
    parser.add_argument("--chains", type=int, default=4)
    args = parser.parse_args(argv)

    import jax

    from .mcmc.sampler import sample_mcmc

    hM = _model(args.ny, args.ns, args.nf)
    kw = dict(samples=args.samples, transient=10, n_chains=args.chains,
              align_post=False, nf_cap=args.nf)
    sample_mcmc(hM, seed=0, **kw)               # warm-up: compile
    t = np.inf
    for rep in range(3):
        t0 = time.time()
        post = sample_mcmc(hM, seed=1 + rep, **kw)
        t = min(t, time.time() - t0)
        assert np.all(np.isfinite(post["Beta"]))
    print(json.dumps({
        "metric": f"posterior samples/sec ({args.ns}-species probit JSDM, "
                  f"{args.chains} chains, {jax.devices()[0].platform})",
        "value": round(args.chains * args.samples / t, 2),
        "unit": "samples/sec",
        "vs_baseline": None,
    }))


def run_main(argv=None):
    """``python -m hmsc_tpu run`` — fault-tolerant long-run driver."""
    parser = argparse.ArgumentParser(
        prog="python -m hmsc_tpu run",
        description="checkpointed (preemption-safe, resumable) sampling run "
                    "of the synthetic benchmark JSDM")
    parser.add_argument("--ny", type=int, default=200)
    parser.add_argument("--ns", type=int, default=100)
    parser.add_argument("--nf", type=int, default=4)
    parser.add_argument("--samples", type=int, default=200)
    parser.add_argument("--transient", type=int, default=50)
    parser.add_argument("--chains", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--verbose", type=int, default=0)
    parser.add_argument("--checkpoint-dir", required=True,
                        help="directory for the rotating ckpt-<n>.npz files")
    parser.add_argument("--checkpoint-every", type=int, default=25,
                        help="recorded samples between snapshots")
    parser.add_argument("--keep", type=int, default=3,
                        help="rotation depth (newest K snapshots kept)")
    parser.add_argument("--resume", action="store_true",
                        help="continue from the newest valid checkpoint "
                             "instead of starting fresh")
    args = parser.parse_args(argv)

    import os

    from .mcmc.sampler import sample_mcmc
    from .utils.checkpoint import PreemptedRun, resume_run

    # the spec fingerprint in every checkpoint rejects a resume against a
    # different model, so the model args are persisted next to the snapshots
    # and --resume rebuilds from them instead of trusting the CLI defaults
    model_json = os.path.join(args.checkpoint_dir, "model.json")
    if args.resume and os.path.exists(model_json):
        with open(model_json) as f:
            margs = json.load(f)
    else:
        margs = {"ny": args.ny, "ns": args.ns, "nf": args.nf}
    hM = _model(margs["ny"], margs["ns"], margs["nf"], seed=66)
    try:
        if args.resume:
            post = resume_run(hM, args.checkpoint_dir, verbose=args.verbose)
        else:
            os.makedirs(args.checkpoint_dir, exist_ok=True)
            with open(model_json, "w") as f:
                json.dump(margs, f)
            post = sample_mcmc(
                hM, samples=args.samples, transient=args.transient,
                n_chains=args.chains, seed=args.seed, nf_cap=args.nf,
                align_post=False, verbose=args.verbose,
                checkpoint_every=args.checkpoint_every,
                checkpoint_path=args.checkpoint_dir,
                checkpoint_keep=args.keep)
    except PreemptedRun as e:
        print(json.dumps({
            "preempted": True, "signal": e.signum,
            "samples_done": e.samples_done, "checkpoint": e.checkpoint_path,
            "resume": f"python -m hmsc_tpu run --resume --checkpoint-dir "
                      f"{args.checkpoint_dir}",
        }))
        return 75                      # EX_TEMPFAIL: try again (resume)
    print(json.dumps({
        "preempted": False, "samples": int(post.samples),
        "chains": int(post.n_chains),
        "finite": bool(np.isfinite(post["Beta"]).all()),
        "checkpoint_dir": args.checkpoint_dir,
    }))
    return 0


if __name__ == "__main__":
    main()
