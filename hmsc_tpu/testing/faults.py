"""Fault injection for the fault-tolerance layer (tests/test_fault_tolerance).

Long multi-chip MCMC runs fail in three characteristic ways (ROADMAP
north-star; the round-5 outage probe in ``benchmarks/tpu_outage_r05.log``):
a numerical blow-up inside one updater poisons a chain, the host or device
is preempted mid-run, and checkpoint files rot on disk.  Each helper here
injects exactly one of those, deterministically, so the recovery paths
(divergence containment + ``retry_diverged``, auto-checkpoint +
``resume_run``, checksum rejection + rotation fallback) can be proven
end-to-end rather than assumed.
"""

from __future__ import annotations

import contextlib
import os
import signal

import numpy as np

__all__ = ["InjectedFault", "InjectedDeviceLoss", "inject_nan",
           "device_loss_after", "sigterm_after", "flip_bytes",
           "slow_checkpoint_writes", "failing_checkpoint_writes"]


class InjectedFault(RuntimeError):
    """Base class for deliberately injected failures."""


class InjectedDeviceLoss(InjectedFault):
    """Simulated loss of the accelerator / host between compiled segments."""


@contextlib.contextmanager
def inject_nan(updater: str = "update_beta_lambda", at_iteration: int = 1,
               field: str = "Beta"):
    """Poison ``state.<field>`` with NaN at the exact sweep
    ``state.it == at_iteration`` — *inside* the compiled scan, like a real
    numerical blow-up (the gate is traced on the carried iteration counter,
    so it fires mid-scan, not between host segments).

    Monkeypatches ``mcmc.updaters.<updater>`` (the sweep resolves updaters
    from the module at trace time) and clears the compiled-program cache on
    entry and exit, so the poison is actually traced in and is fully gone
    afterwards.  Affects every chain — chains are vmapped over one program.

    Yields a ``disarm()`` callable that restores the updater early (and
    clears the compile cache) — a real blow-up is stochastic and does NOT
    recur when the chain re-runs the same sweep with a fresh key stream,
    but this gate is on the deterministic iteration counter, so any restart
    covering ``at_iteration`` would be re-poisoned.  Calling ``disarm()``
    from a ``progress_callback`` once the poison has struck models the
    real, non-recurring failure (the warm-restart tests rely on this);
    disarming is idempotent and the context exit remains a no-op after it.
    """
    import jax.numpy as jnp

    from ..mcmc import sampler as sampler_mod
    from ..mcmc import updaters as U

    real = getattr(U, updater)

    def poisoned(spec, data, state, key, *a, **kw):
        state = real(spec, data, state, key, *a, **kw)
        tgt = getattr(state, field)
        hit = (state.it == at_iteration).astype(tgt.dtype)
        return state.replace(**{field: tgt + hit * jnp.asarray(
            jnp.nan, dtype=tgt.dtype)})

    def disarm():
        if getattr(U, updater) is not real:
            setattr(U, updater, real)
            sampler_mod._compiled_runner.cache_clear()

    setattr(U, updater, poisoned)
    sampler_mod._compiled_runner.cache_clear()
    try:
        yield disarm
    finally:
        disarm()


def device_loss_after(samples_done: int):
    """Progress callback raising :class:`InjectedDeviceLoss` once the run
    has recorded ``samples_done`` samples — simulating losing the device
    between two compiled segments.  The auto-checkpoint for that boundary is
    submitted before the callback fires and the sampler drains its writer
    thread before unwinding, so the snapshot is durably on disk by the time
    the error escapes ``sample_mcmc`` and ``resume_run`` recovers from it.
    """
    def cb(done, total):
        if done >= samples_done:
            raise InjectedDeviceLoss(
                f"injected device loss at {done}/{total} recorded samples")
    return cb


def sigterm_after(samples_done: int):
    """Progress callback delivering a real SIGTERM to this process once
    ``samples_done`` samples are recorded — a preemption rehearsal: the
    sampler's handler finishes the segment, snapshots, and unwinds with
    :class:`~hmsc_tpu.utils.checkpoint.PreemptedRun`.  Fires once."""
    fired = {"done": False}

    def cb(done, total):
        if not fired["done"] and done >= samples_done:
            fired["done"] = True
            os.kill(os.getpid(), signal.SIGTERM)
    return cb


@contextlib.contextmanager
def slow_checkpoint_writes(delay_s: float):
    """Make every checkpoint payload write sleep ``delay_s`` first — a
    slow-disk rehearsal for the pipelined sampler's backpressure path: the
    background writer falls behind, its bounded queue fills, and the
    segment loop must block (not buffer unboundedly) until the disk
    catches up.  Patches ``utils.checkpoint._atomic_savez``, which both
    sample and burn-in snapshots go through."""
    import time

    from ..utils import checkpoint as ck

    real = ck._atomic_savez

    def slow(path, payload, **kw):
        time.sleep(delay_s)
        real(path, payload, **kw)

    ck._atomic_savez = slow
    try:
        yield
    finally:
        ck._atomic_savez = real


@contextlib.contextmanager
def failing_checkpoint_writes(exc: BaseException | None = None):
    """Make every checkpoint write raise (default: ``OSError`` — a full
    disk).  The write happens on the sampler's background writer thread;
    this proves the failure is captured there and re-raised on the driver
    thread instead of being silently swallowed with the run reporting
    success over checkpoints that do not exist."""
    from ..utils import checkpoint as ck

    real = ck._atomic_savez

    def failing(path, payload, **kw):
        raise exc if exc is not None else OSError(
            f"injected checkpoint write failure for {path} (disk full)")

    ck._atomic_savez = failing
    try:
        yield
    finally:
        ck._atomic_savez = real


def flip_bytes(path: str, n: int = 16, offset: int | None = None,
               seed: int = 0) -> list[int]:
    """Flip ``n`` bytes of a file in place (bit-rot simulation for
    checkpoint-integrity tests).  With ``offset=None`` the positions are
    drawn deterministically from the middle 80% of the file (the payload
    region); returns the flipped offsets."""
    with open(path, "rb") as f:
        data = bytearray(f.read())
    if not data:
        raise ValueError(f"{path}: empty file, nothing to corrupt")
    if offset is not None:
        offs = list(range(offset, min(offset + n, len(data))))
    else:
        lo = int(len(data) * 0.1)
        hi = max(int(len(data) * 0.9), lo + 1)
        rng = np.random.default_rng(seed)
        offs = sorted({int(x) for x in rng.integers(lo, hi, size=n)})
    for o in offs:
        data[o] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(data))
    return offs
