"""Fault-injection harness for rehearsing long-run failure modes
(see :mod:`hmsc_tpu.testing.faults`).  Ships with the wheel so operators can
drill kill → resume recovery against their own models, not just the test
suite's."""

from .chaos import ChaosEvent, ChaosPlan, poisson_schedule
from .faults import (InjectedFault, InjectedDeviceLoss, device_loss_after,
                     failing_checkpoint_writes, flip_bytes, inject_nan,
                     sigterm_after, slow_checkpoint_writes)
from .multiproc import (EXIT_CKPT_CORRUPT, EXIT_COORDINATION, EXIT_DIVERGED,
                        EXIT_OK, EXIT_PREEMPTED, build_worker_model,
                        spawn_workers, worker_cmd, worker_env, worker_main)

__all__ = ["InjectedFault", "InjectedDeviceLoss", "device_loss_after",
           "failing_checkpoint_writes", "flip_bytes", "inject_nan",
           "sigterm_after", "slow_checkpoint_writes",
           "build_worker_model", "spawn_workers", "worker_main",
           "worker_cmd", "worker_env",
           "ChaosEvent", "ChaosPlan", "poisson_schedule",
           "EXIT_OK", "EXIT_PREEMPTED", "EXIT_COORDINATION",
           "EXIT_DIVERGED", "EXIT_CKPT_CORRUPT"]
