"""Deterministic chaos schedules for supervised fleet runs.

The fleet supervisor's whole claim — **zero committed draws lost, ever** —
is only credible if it survives scripted infrastructure abuse.  This
module describes that abuse as data: a :class:`ChaosEvent` list the
supervisor (and ``benchmarks/bench_chaos.py``) executes deterministically,
covering the four characteristic failure modes of preemptible fleet
capacity:

- ``sigkill`` — a rank vanishes (host preempted without grace);
- ``sigterm`` — a rank is preempted WITH grace (the coordinated unwind);
- ``freeze``  — a rank wedges: the process lives but stops heartbeating
  (armed in the worker via ``--freeze-at``; the supervisor must detect the
  silence and SIGKILL it);
- ``disk_full`` — checkpoint writes start failing mid-run (armed via
  ``--fail-writes-at``, backed by the ``testing.faults`` write hook).

Two trigger styles:

- **armed** events (``at_samples`` + optional ``attempt``) become worker
  CLI flags at spawn time — they key on the worker's own progress counter,
  so a test's kill lands mid-segment regardless of CI machine speed;
- **wall-clock** events (``at_s``, seconds since the supervisor started)
  are delivered by the supervisor's watch loop — :func:`poisson_schedule`
  generates these, seeded, for the chaos bench's random-kill gate.
"""

from __future__ import annotations

import dataclasses

__all__ = ["ChaosEvent", "ChaosPlan", "poisson_schedule",
           "SIGNAL_ACTIONS", "ARMED_ACTIONS", "PIPELINE_PHASES",
           "PipelineChaos"]

SIGNAL_ACTIONS = ("sigkill", "sigterm")
ARMED_ACTIONS = ("sigkill", "sigterm", "freeze", "disk_full")

# the autopilot's per-drop phase boundaries (hmsc_tpu.pipeline): a
# PipelineChaos event strikes at the matching boundary of the matching drop
PIPELINE_PHASES = ("validate", "refit", "flip", "compact")


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scripted fault.  Exactly one of ``at_s`` (wall-clock since
    supervisor start; signal actions only) or ``at_samples`` (worker
    progress trigger, armed as a spawn flag) must be set.  ``attempt``
    restricts an armed event to one spawn attempt (1-based; ``None`` arms
    it on the first attempt that spawns the rank)."""

    action: str
    rank: int
    at_s: float | None = None
    at_samples: int | None = None
    attempt: int | None = None

    def __post_init__(self):
        if self.action not in ARMED_ACTIONS:
            raise ValueError(f"unknown chaos action {self.action!r} "
                             f"(valid: {ARMED_ACTIONS})")
        if (self.at_s is None) == (self.at_samples is None):
            raise ValueError(
                "a ChaosEvent needs exactly one of at_s / at_samples")
        if self.at_s is not None and self.action not in SIGNAL_ACTIONS:
            raise ValueError(
                f"wall-clock delivery only supports {SIGNAL_ACTIONS}; "
                f"{self.action!r} must be armed via at_samples")


# worker CLI flag per armed action (see testing.multiproc.worker_main)
_ARM_FLAGS = {"sigkill": "--kill-at", "sigterm": "--sigterm-at",
              "freeze": "--freeze-at", "disk_full": "--fail-writes-at"}


class ChaosPlan:
    """Executable view over a list of :class:`ChaosEvent` — tracks which
    events already fired so the supervisor can poll it cheaply."""

    def __init__(self, events):
        self.events = list(events)
        self._armed: set = set()
        self._fired: set = set()

    def arm_flags(self, rank: int, attempt: int) -> list:
        """Worker CLI flags for the armed events matching this (rank,
        attempt) spawn.  Each event arms at most once: an event with
        ``attempt=None`` fires on the first spawn of its rank only (a
        restarted rank must not be re-poisoned — real faults don't
        recur on the replacement)."""
        flags = []
        for i, ev in enumerate(self.events):
            if i in self._armed or ev.at_samples is None:
                continue
            if ev.rank != int(rank):
                continue
            if ev.attempt is not None and ev.attempt != int(attempt):
                continue
            flags += [_ARM_FLAGS[ev.action], str(int(ev.at_samples))]
            self._armed.add(i)
        return flags

    def due_signals(self, elapsed_s: float) -> list:
        """Wall-clock events due at ``elapsed_s`` (each returned once)."""
        due = []
        for i, ev in enumerate(self.events):
            if i in self._fired or ev.at_s is None:
                continue
            if float(elapsed_s) >= float(ev.at_s):
                self._fired.add(i)
                due.append(ev)
        return due

    def summary(self) -> dict:
        """Digest for bench records: counts per action + trigger style."""
        by_action: dict = {}
        for ev in self.events:
            by_action[ev.action] = by_action.get(ev.action, 0) + 1
        return {"events": len(self.events), "by_action": by_action,
                "armed": sum(1 for e in self.events
                             if e.at_samples is not None),
                "wall_clock": sum(1 for e in self.events
                                  if e.at_s is not None)}


class PipelineChaos:
    """Phase-keyed chaos for the autopilot daemon (``hmsc_tpu.pipeline``).

    Events are plain dicts ``{"action", "drop", "phase"}``: the fault
    strikes when the autopilot reaches ``phase`` (one of
    :data:`PIPELINE_PHASES`) while processing the ``drop``-th accepted
    drop (0-based).  ``sigkill``/``sigterm`` are valid at every phase
    (the daemon kills ITSELF at the boundary — restart-recovery is the
    property under test); ``freeze`` and ``disk_full`` are armed onto the
    supervised refit worker, or — for ``disk_full`` — into the compact
    step's write path, so they are only valid at ``refit`` (and
    ``compact`` for ``disk_full``).

    Fired-marks are persisted to ``state_path`` BEFORE the fault executes
    (atomic tmp+rename), so a daemon an event SIGKILLs does not re-fire
    the same event after its supervisor restarts it — exactly-once
    delivery across restarts, like :class:`ChaosPlan`'s arm-once rule."""

    def __init__(self, events, state_path: str | None = None):
        self.events = []
        for ev in events:
            action, phase = str(ev["action"]), str(ev["phase"])
            if action not in ARMED_ACTIONS:
                raise ValueError(f"unknown chaos action {action!r} "
                                 f"(valid: {ARMED_ACTIONS})")
            if phase not in PIPELINE_PHASES:
                raise ValueError(f"unknown pipeline phase {phase!r} "
                                 f"(valid: {PIPELINE_PHASES})")
            if action == "freeze" and phase != "refit":
                raise ValueError(
                    "freeze is a worker heartbeat fault — only the "
                    "'refit' phase has a supervised worker to freeze")
            if action == "disk_full" and phase not in ("refit", "compact"):
                raise ValueError(
                    "disk_full is a write-path fault — valid at 'refit' "
                    "(worker checkpoint writes) and 'compact' only")
            self.events.append(
                {"action": action, "drop": int(ev["drop"]), "phase": phase})
        self.state_path = state_path
        self._fired: set = set(self._load_state())

    def _load_state(self) -> list:
        if self.state_path is None:
            return []
        import json
        import os
        try:
            with open(self.state_path) as f:
                return [int(i) for i in json.load(f)]
        except (OSError, ValueError):
            return []

    def _save_state(self) -> None:
        if self.state_path is None:
            return
        import json
        import os
        tmp = f"{self.state_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(sorted(self._fired), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path)

    def due(self, drop: int, phase: str) -> list:
        """Events striking at this (drop, phase) boundary, marked fired
        (and persisted) before they are returned."""
        due = [(i, ev) for i, ev in enumerate(self.events)
               if i not in self._fired
               and ev["drop"] == int(drop) and ev["phase"] == str(phase)]
        if due:
            self._fired.update(i for i, _ in due)
            self._save_state()
        return [ev for _, ev in due]

    def remaining(self) -> int:
        return len(self.events) - len(self._fired)

    def summary(self) -> dict:
        by_action: dict = {}
        by_phase: dict = {}
        for ev in self.events:
            by_action[ev["action"]] = by_action.get(ev["action"], 0) + 1
            by_phase[ev["phase"]] = by_phase.get(ev["phase"], 0) + 1
        return {"events": len(self.events), "by_action": by_action,
                "by_phase": by_phase, "fired": len(self._fired)}


def poisson_schedule(seed: int, rate_per_s: float, horizon_s: float,
                     nprocs: int, actions=SIGNAL_ACTIONS,
                     min_gap_s: float = 0.0) -> ChaosPlan:
    """Seeded Poisson kill schedule: exponential inter-arrival gaps at
    ``rate_per_s`` over ``[0, horizon_s)``, each event striking a uniform
    random rank with a uniform random action from ``actions``.
    Deterministic in ``seed`` — the chaos bench's random kills are
    reproducible bit-for-bit.  ``min_gap_s`` floors the gap between
    consecutive events so a pathological draw cannot kill the fleet
    faster than it can possibly restart."""
    import numpy as np

    rng = np.random.default_rng(int(seed))
    events, t = [], 0.0
    while True:
        t += max(float(rng.exponential(1.0 / float(rate_per_s))),
                 float(min_gap_s))
        if t >= float(horizon_s):
            break
        events.append(ChaosEvent(
            action=str(rng.choice(list(actions))),
            rank=int(rng.integers(int(nprocs))), at_s=round(t, 3)))
    return ChaosPlan(events)
