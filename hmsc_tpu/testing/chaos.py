"""Deterministic chaos schedules for supervised fleet runs.

The fleet supervisor's whole claim — **zero committed draws lost, ever** —
is only credible if it survives scripted infrastructure abuse.  This
module describes that abuse as data: a :class:`ChaosEvent` list the
supervisor (and ``benchmarks/bench_chaos.py``) executes deterministically,
covering the four characteristic failure modes of preemptible fleet
capacity:

- ``sigkill`` — a rank vanishes (host preempted without grace);
- ``sigterm`` — a rank is preempted WITH grace (the coordinated unwind);
- ``freeze``  — a rank wedges: the process lives but stops heartbeating
  (armed in the worker via ``--freeze-at``; the supervisor must detect the
  silence and SIGKILL it);
- ``disk_full`` — checkpoint writes start failing mid-run (armed via
  ``--fail-writes-at``, backed by the ``testing.faults`` write hook).

Two trigger styles:

- **armed** events (``at_samples`` + optional ``attempt``) become worker
  CLI flags at spawn time — they key on the worker's own progress counter,
  so a test's kill lands mid-segment regardless of CI machine speed;
- **wall-clock** events (``at_s``, seconds since the supervisor started)
  are delivered by the supervisor's watch loop — :func:`poisson_schedule`
  generates these, seeded, for the chaos bench's random-kill gate.
"""

from __future__ import annotations

import dataclasses

__all__ = ["ChaosEvent", "ChaosPlan", "poisson_schedule",
           "SIGNAL_ACTIONS", "ARMED_ACTIONS"]

SIGNAL_ACTIONS = ("sigkill", "sigterm")
ARMED_ACTIONS = ("sigkill", "sigterm", "freeze", "disk_full")


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scripted fault.  Exactly one of ``at_s`` (wall-clock since
    supervisor start; signal actions only) or ``at_samples`` (worker
    progress trigger, armed as a spawn flag) must be set.  ``attempt``
    restricts an armed event to one spawn attempt (1-based; ``None`` arms
    it on the first attempt that spawns the rank)."""

    action: str
    rank: int
    at_s: float | None = None
    at_samples: int | None = None
    attempt: int | None = None

    def __post_init__(self):
        if self.action not in ARMED_ACTIONS:
            raise ValueError(f"unknown chaos action {self.action!r} "
                             f"(valid: {ARMED_ACTIONS})")
        if (self.at_s is None) == (self.at_samples is None):
            raise ValueError(
                "a ChaosEvent needs exactly one of at_s / at_samples")
        if self.at_s is not None and self.action not in SIGNAL_ACTIONS:
            raise ValueError(
                f"wall-clock delivery only supports {SIGNAL_ACTIONS}; "
                f"{self.action!r} must be armed via at_samples")


# worker CLI flag per armed action (see testing.multiproc.worker_main)
_ARM_FLAGS = {"sigkill": "--kill-at", "sigterm": "--sigterm-at",
              "freeze": "--freeze-at", "disk_full": "--fail-writes-at"}


class ChaosPlan:
    """Executable view over a list of :class:`ChaosEvent` — tracks which
    events already fired so the supervisor can poll it cheaply."""

    def __init__(self, events):
        self.events = list(events)
        self._armed: set = set()
        self._fired: set = set()

    def arm_flags(self, rank: int, attempt: int) -> list:
        """Worker CLI flags for the armed events matching this (rank,
        attempt) spawn.  Each event arms at most once: an event with
        ``attempt=None`` fires on the first spawn of its rank only (a
        restarted rank must not be re-poisoned — real faults don't
        recur on the replacement)."""
        flags = []
        for i, ev in enumerate(self.events):
            if i in self._armed or ev.at_samples is None:
                continue
            if ev.rank != int(rank):
                continue
            if ev.attempt is not None and ev.attempt != int(attempt):
                continue
            flags += [_ARM_FLAGS[ev.action], str(int(ev.at_samples))]
            self._armed.add(i)
        return flags

    def due_signals(self, elapsed_s: float) -> list:
        """Wall-clock events due at ``elapsed_s`` (each returned once)."""
        due = []
        for i, ev in enumerate(self.events):
            if i in self._fired or ev.at_s is None:
                continue
            if float(elapsed_s) >= float(ev.at_s):
                self._fired.add(i)
                due.append(ev)
        return due

    def summary(self) -> dict:
        """Digest for bench records: counts per action + trigger style."""
        by_action: dict = {}
        for ev in self.events:
            by_action[ev.action] = by_action.get(ev.action, 0) + 1
        return {"events": len(self.events), "by_action": by_action,
                "armed": sum(1 for e in self.events
                             if e.at_samples is not None),
                "wall_clock": sum(1 for e in self.events
                                  if e.at_s is not None)}


def poisson_schedule(seed: int, rate_per_s: float, horizon_s: float,
                     nprocs: int, actions=SIGNAL_ACTIONS,
                     min_gap_s: float = 0.0) -> ChaosPlan:
    """Seeded Poisson kill schedule: exponential inter-arrival gaps at
    ``rate_per_s`` over ``[0, horizon_s)``, each event striking a uniform
    random rank with a uniform random action from ``actions``.
    Deterministic in ``seed`` — the chaos bench's random kills are
    reproducible bit-for-bit.  ``min_gap_s`` floors the gap between
    consecutive events so a pathological draw cannot kill the fleet
    faster than it can possibly restart."""
    import numpy as np

    rng = np.random.default_rng(int(seed))
    events, t = [], 0.0
    while True:
        t += max(float(rng.exponential(1.0 / float(rate_per_s))),
                 float(min_gap_s))
        if t >= float(horizon_s):
            break
        events.append(ChaosEvent(
            action=str(rng.choice(list(actions))),
            rank=int(rng.integers(int(nprocs))), at_s=round(t, 3)))
    return ChaosPlan(events)
