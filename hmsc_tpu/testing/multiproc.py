"""Multi-process protocol harness: subprocess workers + spawn helpers.

The cross-host story must be testable without a TPU pod: each *worker* is a
plain CPU subprocess running ``sample_mcmc`` over its chain slice under a
:class:`~hmsc_tpu.utils.coordination.FileCoordinator`, so the FULL
multi-process checkpoint protocol — barrier-gated manifest commits,
committer-only GC, kill-one-process timeouts, resume under a different
process count — runs in tier-1 tests and in
``benchmarks/bench_multiproc.py`` on any machine.  The fleet supervisor
(:mod:`hmsc_tpu.fleet`) spawns the SAME worker via :func:`worker_cmd` /
:func:`worker_env`, so a supervised fleet exercises exactly the protocol
the tests pin.

Run one worker by hand::

    python -m hmsc_tpu.testing.multiproc --rank 0 --nprocs 2 \
        --coord-dir /tmp/coord --ckpt-dir /tmp/ck \
        --run '{"samples": 8, "n_chains": 2, "checkpoint_every": 4}'

Exit codes come from :mod:`hmsc_tpu.exit_codes`: 0 success, 75 preempted
(resumable — the CLI convention), 76 coordination failure (a peer died or
timed out), 77 completed-but-diverged, 78 no usable checkpoint on resume,
1 anything else.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from ..exit_codes import (EXIT_CKPT_CORRUPT, EXIT_COORDINATION,
                          EXIT_DIVERGED, EXIT_FAILURE, EXIT_OK,
                          EXIT_PREEMPTED)

__all__ = ["build_worker_model", "worker_main", "spawn_workers",
           "worker_cmd", "worker_env",
           "EXIT_OK", "EXIT_PREEMPTED", "EXIT_COORDINATION",
           "EXIT_DIVERGED", "EXIT_CKPT_CORRUPT", "EXIT_FAILURE"]


def _log():
    from ..obs import get_logger
    return get_logger()


def build_worker_model(ny: int = 24, ns: int = 3, nc: int = 2,
                       distr: str = "normal", n_units: int = 5,
                       seed: int = 3, nf: int = 2, spatial: str | None = None,
                       n_neighbours: int = 5, n_knots: int | None = None):
    """A compact one-random-level model every worker (and the in-test
    reference run) builds identically from the same kwargs — the
    multi-process bit-identity assertions compare runs of THIS model.
    ``spatial`` upgrades the level to a spatial one (``'Full'`` /
    ``'NNGP'`` / ``'GPP'``) for the scenario-engine jobs; the default
    (non-spatial) rng consumption order is untouched, so every committed
    worker-model stream stays byte-identical."""
    import numpy as np
    import pandas as pd

    from ..model import Hmsc
    from ..random_level import HmscRandomLevel, set_priors_random_level

    rng = np.random.default_rng(seed)
    X = np.column_stack([np.ones(ny), rng.standard_normal((ny, nc - 1))])
    Y = rng.standard_normal((ny, ns)) + X @ rng.standard_normal((nc, ns))
    if distr == "probit":
        Y = (Y > 0).astype(float)
    units = [f"u{i:02d}" for i in rng.integers(0, n_units, ny)]
    for i in range(n_units):
        units[i % ny] = f"u{i:02d}"
    study = pd.DataFrame({"lvl": units})
    if spatial is not None:
        # spatial draws come AFTER every default-path draw, so non-spatial
        # jobs see the exact historical stream
        xy = rng.uniform(size=(n_units, 2))
        s_df = pd.DataFrame(xy, index=sorted(set(units)),
                            columns=["x", "y"])
        skw = dict(s_data=s_df, s_method=spatial)
        if spatial == "GPP":
            skw["s_knot"] = rng.uniform(size=(n_knots or 4, 2))
        if spatial == "NNGP":
            skw["n_neighbours"] = n_neighbours
        rl = HmscRandomLevel(**skw)
    else:
        rl = HmscRandomLevel(units=study["lvl"])
    set_priors_random_level(rl, nf_max=nf, nf_min=nf)
    return Hmsc(Y=Y, X=X, distr=distr, study_design=study,
                ran_levels={"lvl": rl})


def worker_main(argv=None) -> int:
    import argparse
    import contextlib

    ap = argparse.ArgumentParser(description="multi-process sampling worker")
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--coord-dir", required=True,
                    help="FileCoordinator sentinel directory (fresh per "
                         "run attempt)")
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--model", default="{}",
                    help="JSON kwargs for build_worker_model")
    ap.add_argument("--run", default="{}",
                    help="JSON kwargs for sample_mcmc (checkpoint_path is "
                         "set to --ckpt-dir automatically)")
    ap.add_argument("--action", choices=("run", "resume"), default="run")
    ap.add_argument("--kill-at", type=int, default=None,
                    help="hard-kill (SIGKILL) this worker once its "
                         "progress counter reaches N recorded samples — "
                         "the mid-run death the protocol must survive")
    ap.add_argument("--kill-calls", type=int, default=None,
                    help="hard-kill after the Nth progress callback — "
                         "reaches burn-in boundaries, where the recorded-"
                         "sample counter --kill-at keys on is still 0")
    ap.add_argument("--sigterm-at", type=int, default=None,
                    help="deliver SIGTERM (once) at N recorded samples — "
                         "the preemption rehearsal: EVERY rank must unwind "
                         "with PreemptedRun at the same committed boundary")
    ap.add_argument("--freeze-at", type=int, default=None,
                    help="chaos heartbeat-freeze: at N recorded samples "
                         "stop heartbeating and wedge this worker (sleep "
                         "forever) — the supervisor must detect the silent "
                         "rank and SIGKILL it")
    ap.add_argument("--fail-writes-at", type=int, default=None,
                    help="chaos disk-full: every checkpoint payload write "
                         "raises OSError once N recorded samples are done "
                         "(testing.faults hook armed mid-run)")
    ap.add_argument("--inject-nan", default=None,
                    help="JSON {updater, at_iteration, field, disarm_at}: "
                         "poison the carry at the given sweep via "
                         "testing.faults.inject_nan, disarming at "
                         "disarm_at recorded samples (a real blow-up does "
                         "not recur under a fresh key stream)")
    ap.add_argument("--heartbeat-dir", default=None,
                    help="write heartbeat-p<rank>.json here every "
                         "--heartbeat-interval seconds (liveness beacon "
                         "for the fleet supervisor)")
    ap.add_argument("--heartbeat-interval", type=float, default=0.5)
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="coordination timeout (seconds)")
    ap.add_argument("--pin-cpu", type=int, default=None,
                    help="restrict this worker (all threads) to one CPU "
                         "core — XLA-CPU's intra-op pool otherwise spreads "
                         "each worker over every core, so R 'single-core' "
                         "workers silently share the whole box and scaling "
                         "numbers lie")
    ap.add_argument("--out", default=None,
                    help="write a JSON result record here on success")
    args = ap.parse_args(argv)

    if args.pin_cpu is not None and hasattr(os, "sched_setaffinity"):
        os.sched_setaffinity(0, {args.pin_cpu})

    from ..utils.coordination import (CoordinationError, FileCoordinator,
                                      HeartbeatWriter)
    from ..utils.checkpoint import (CheckpointError, PreemptedRun,
                                    resume_run)

    coord = FileCoordinator(args.coord_dir, args.rank, args.nprocs,
                            timeout_s=args.timeout,
                            heartbeat_dir=args.heartbeat_dir)
    hM = build_worker_model(**json.loads(args.model))
    run_kw = json.loads(args.run)
    # an explicit checkpoint_path in --run (including null) overrides the
    # --ckpt-dir default: the checkpoint-FREE mesh path (telemetry-only
    # runs, end-of-run skew gather) is protocol surface too
    ckpt_path = run_kw.pop("checkpoint_path", args.ckpt_dir)

    hb = None
    if args.heartbeat_dir is not None:
        hb = HeartbeatWriter(args.heartbeat_dir, args.rank,
                             interval_s=args.heartbeat_interval).start()

    import time as _time
    prog = []                         # [perf_counter, process_time,
                                      # samples_done] per segment boundary
                                      # (bench steady-state windows are cut
                                      # from these; process_time gives the
                                      # hypervisor-noise-immune CPU window)
    kill_at, kill_calls = args.kill_at, args.kill_calls
    sigterm_at, sigterm_fired = args.sigterm_at, [False]
    freeze_at = args.freeze_at

    if args.fail_writes_at is not None:
        # disk-full chaos, armed mid-run: committed snapshots up to the
        # trigger stay durable; the first write after it raises on the
        # background writer and propagates as a clean run failure
        from ..utils import checkpoint as _ckmod
        _real_savez = _ckmod._atomic_savez
        trip = int(args.fail_writes_at)

        def _maybe_failing_savez(path, payload, **kw):
            done = prog[-1][2] if prog else 0
            if done >= trip:
                raise OSError(
                    f"injected disk-full at {done} recorded samples "
                    f"(chaos --fail-writes-at {trip}) for {path}")
            _real_savez(path, payload, **kw)
        _ckmod._atomic_savez = _maybe_failing_savez

    nan_cm, nan_disarm_at = contextlib.nullcontext(None), None
    if args.inject_nan is not None:
        from .faults import inject_nan
        nan_kw = dict(json.loads(args.inject_nan))
        nan_disarm_at = nan_kw.pop("disarm_at", None)
        nan_cm = inject_nan(**nan_kw)

    def progress_callback(done, total):
        prog.append([_time.perf_counter(), _time.process_time(), int(done)])
        if hb is not None:
            hb.update(samples_done=int(done), samples_total=int(total))
        if (kill_at is not None and done >= kill_at) or \
                (kill_calls is not None and len(prog) >= kill_calls):
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        if sigterm_at is not None and done >= sigterm_at \
                and not sigterm_fired[0]:
            sigterm_fired[0] = True
            import signal
            os.kill(os.getpid(), signal.SIGTERM)
        if freeze_at is not None and done >= freeze_at:
            if hb is not None:
                hb.freeze()
            _log().warn(f"worker {args.rank}: chaos freeze at {done} "
                        "recorded samples (heartbeat silent, wedged)")
            while True:               # wedged until the supervisor kills us
                _time.sleep(3600)

    try:
        with nan_cm as disarm:
            if disarm is not None and nan_disarm_at is not None:
                inner = progress_callback

                def progress_callback(done, total):  # noqa: F811
                    if done >= nan_disarm_at:
                        disarm()
                    inner(done, total)
            if args.action == "resume":
                post = resume_run(hM, args.ckpt_dir, coordinator=coord,
                                  progress_callback=progress_callback,
                                  **run_kw)
            else:
                from ..mcmc.sampler import sample_mcmc
                post = sample_mcmc(hM, coordinator=coord,
                                   checkpoint_path=ckpt_path,
                                   progress_callback=progress_callback,
                                   **run_kw)
    except PreemptedRun as e:
        _log().warn(f"worker {args.rank}: preempted ({e})")
        return EXIT_PREEMPTED
    except CoordinationError as e:
        _log().warn(f"worker {args.rank}: coordination failed ({e})")
        return EXIT_COORDINATION
    except CheckpointError as e:
        _log().warn(f"worker {args.rank}: no usable checkpoint ({e})")
        return EXIT_CKPT_CORRUPT
    finally:
        coord.cleanup()
        if hb is not None:
            hb.stop()

    if args.out:
        import numpy as np
        rec = {
            "rank": args.rank, "nprocs": args.nprocs,
            "samples": int(post.samples), "n_chains": int(post.n_chains),
            "io_stats": {k: v for k, v in post.io_stats.items()
                         if not isinstance(v, list)},
            # a cheap draw digest per parameter for cross-run comparisons
            "digest": {k: float(np.asarray(v, dtype=np.float64).sum())
                       for k, v in post.arrays.items()},
            "retry_info": {k: list(v) if isinstance(v, tuple) else v
                           for k, v in post.retry_info.items()},
            "timing": post.timing,
            "telemetry": post.telemetry,
            "prog": prog,
        }
        with open(args.out, "w") as f:
            json.dump(rec, f)
    import numpy as np
    if not np.asarray(post.chain_health["good_chains"]).all():
        _log().warn(f"worker {args.rank}: completed with diverged chain(s) "
                    f"(first_bad_it={post.chain_health['first_bad_it']})")
        return EXIT_DIVERGED
    return EXIT_OK


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def worker_env(env: dict | None = None, *, trace=None) -> dict:
    """The spawn environment every worker runs under: CPU backend,
    single-threaded XLA-CPU eigen, the shared persistent compilation cache
    (each spawned interpreter would otherwise recompile the identical
    sampling program from scratch), and the package root on PYTHONPATH.

    ``trace`` (a :class:`~hmsc_tpu.obs.trace.TraceContext`) propagates the
    caller's trace to the child via ``HMSC_TPU_TRACE_CTX`` — the child's
    sampler inherits it at its run-start mark, so the cross-process event
    chain joins on one trace id.  With no ``trace``, any context already
    in ``os.environ`` passes through unchanged (a grandparent's)."""
    base_env = dict(os.environ)
    if trace is not None:
        from ..obs.trace import trace_env
        base_env.update(trace_env(trace))
    base_env["JAX_PLATFORMS"] = "cpu"
    flags = base_env.get("XLA_FLAGS", "")
    if "xla_cpu_multi_thread_eigen" not in flags:
        flags = (flags + " --xla_cpu_multi_thread_eigen=false").strip()
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=1").strip()
    base_env["XLA_FLAGS"] = flags
    base_env["PYTHONPATH"] = os.pathsep.join(
        [_pkg_root()] + ([base_env["PYTHONPATH"]]
                         if base_env.get("PYTHONPATH") else []))
    base_env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.environ.get("HMSC_TEST_XLA_CACHE", "/tmp/hmsc_tpu_xla_cache"))
    base_env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    base_env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    base_env.update(env or {})
    return base_env


def worker_cmd(rank: int, nprocs: int, *, coord_dir: str, ckpt_dir: str,
               model_kw: dict | None = None, run_kw: dict | None = None,
               action: str = "run", timeout_s: float = 30.0,
               out: str | None = None, heartbeat_dir: str | None = None,
               heartbeat_interval_s: float = 0.5,
               extra_args: list | None = None) -> list:
    """The argv for one worker subprocess (shared by :func:`spawn_workers`
    and the fleet supervisor, which spawns ranks individually so it can
    watch and restart them)."""
    # -c (not -m): `-m hmsc_tpu.testing.multiproc` imports this module
    # twice (once as __main__), which runpy warns about since the
    # testing package re-exports the worker entry points
    cmd = [sys.executable, "-c",
           "from hmsc_tpu.testing.multiproc import worker_main; "
           "raise SystemExit(worker_main())",
           "--rank", str(int(rank)), "--nprocs", str(int(nprocs)),
           "--coord-dir", coord_dir, "--ckpt-dir", ckpt_dir,
           "--model", json.dumps(model_kw or {}),
           "--run", json.dumps(run_kw or {}),
           "--action", action, "--timeout", str(timeout_s)]
    if out is not None:
        cmd += ["--out", out]
    if heartbeat_dir is not None:
        cmd += ["--heartbeat-dir", heartbeat_dir,
                "--heartbeat-interval", str(heartbeat_interval_s)]
    cmd += [str(a) for a in (extra_args or [])]
    return cmd


def spawn_workers(nprocs: int, *, ckpt_dir: str, coord_dir: str,
                  model_kw: dict | None = None, run_kw: dict | None = None,
                  action: str = "run", kill_at: int | None = None,
                  kill_calls: int | None = None,
                  sigterm_at: int | None = None,
                  kill_rank: int | None = None, timeout_s: float = 30.0,
                  wall_timeout_s: float = 600.0, out_dir: str | None = None,
                  env: dict | None = None, pin_cpus: bool = False,
                  extra_rank_args: dict | None = None) -> list:
    """Launch ``nprocs`` workers and wait for all of them.

    Returns one record per rank: ``{"rank", "returncode", "stdout",
    "stderr", "result"}`` (``result`` parsed from the worker's ``--out``
    JSON when present).  ``kill_at``/``kill_calls`` + ``kill_rank`` arm the
    SIGKILL fault on one rank (by recorded-sample count, or by progress-
    callback count for deaths at burn-in boundaries where the sample
    counter is still 0).  Workers run with ``JAX_PLATFORMS=cpu`` and
    single-threaded XLA-CPU eigen; ``pin_cpus=True`` additionally pins
    rank ``r`` (all its threads) to CPU core ``r % n_cores`` — the eigen
    flag alone does NOT stop XLA-CPU's intra-op pool from spreading each
    worker over every core, so without pinning R "single-core" workers
    silently share the whole box and a scaling measurement lies (the
    bench pins; protocol tests don't care)."""
    base_env = worker_env(env)

    procs, outs = [], []
    for r in range(int(nprocs)):
        out = (os.path.join(out_dir, f"worker-{r}.json")
               if out_dir is not None else None)
        outs.append(out)
        extra = []
        if kill_at is not None and r == (kill_rank or 0):
            extra += ["--kill-at", str(kill_at)]
        if kill_calls is not None and r == (kill_rank or 0):
            extra += ["--kill-calls", str(kill_calls)]
        if sigterm_at is not None and r == (kill_rank or 0):
            extra += ["--sigterm-at", str(sigterm_at)]
        if pin_cpus:
            extra += ["--pin-cpu", str(r % (os.cpu_count() or 1))]
        extra += [str(a) for a in (extra_rank_args or {}).get(r, [])]
        cmd = worker_cmd(r, nprocs, coord_dir=coord_dir, ckpt_dir=ckpt_dir,
                         model_kw=model_kw, run_kw=run_kw, action=action,
                         timeout_s=timeout_s, out=out, extra_args=extra)
        procs.append(subprocess.Popen(
            cmd, cwd=_pkg_root(), env=base_env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))

    records = []
    for r, p in enumerate(procs):
        try:
            so, se = p.communicate(timeout=wall_timeout_s)
        except subprocess.TimeoutExpired:
            p.kill()
            so, se = p.communicate()
            se = (se or "") + "\n[spawn_workers: wall timeout, killed]"
        result = None
        if outs[r] is not None and os.path.exists(outs[r]):
            try:
                with open(outs[r]) as f:
                    result = json.load(f)
            except (OSError, ValueError):
                pass
        records.append({"rank": r, "returncode": p.returncode,
                        "stdout": so, "stderr": se, "result": result})
    return records


if __name__ == "__main__":
    raise SystemExit(worker_main())
