"""``python -m hmsc_tpu lint`` — the static-correctness gate.

Exit status: 0 when no active severity=error finding remains after
suppressions and the committed baseline; 1 otherwise.  ``--json`` prints
the machine-readable report (schema pinned by ``tests/test_analysis.py``),
``--update-baseline`` rewrites the grandfather file from the current
findings, ``--update-fingerprints`` re-records the jaxpr structural
fingerprints after a reviewed change to the compiled surface.

The jaxpr layer traces on whatever JAX platform is configured; the CLI
defaults ``JAX_PLATFORMS=cpu`` (abstract evaluation is platform-
independent, and a lint must never block on an unreachable accelerator).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def lint_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hmsc_tpu lint",
        description="Static correctness suite: AST lint + jaxpr audits "
                    "over hmsc_tpu/.")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--layer", choices=("ast", "jaxpr", "all"),
                        default="all",
                        help="run only one analysis layer (default: all)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the committed baseline from the "
                             "current findings and exit 0")
    parser.add_argument("--update-fingerprints", action="store_true",
                        help="re-record jaxpr structural fingerprints "
                             "(after reviewing the diff) and exit 0")
    parser.add_argument("--baseline", default=None,
                        help="override the baseline file path")
    parser.add_argument("--root", default=None,
                        help="lint a different package root (fixture "
                             "trees in tests; default: the installed "
                             "hmsc_tpu package)")
    parser.add_argument("--fingerprints", default=None,
                        help="override the fingerprints file path")
    args = parser.parse_args(argv)

    # lint must never block on an unreachable accelerator: abstract eval
    # is platform-independent, so trace on CPU unless told otherwise
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the sharded-sweep audits trace shard_map programs over an emulated
    # 8-device species mesh; force the virtual device count BEFORE the
    # backend initialises (no-op when the flag — or a backend — already
    # exists, e.g. under pytest where conftest set it)
    from ..mcmc.partition import force_emulated_device_count
    force_emulated_device_count(8)

    from .findings import load_baseline
    from .runner import BASELINE_PATH, run_analysis, findings_to_json
    from . import jaxpr_rules

    layers = ("ast", "jaxpr") if args.layer == "all" else (args.layer,)
    baseline_path = args.baseline or BASELINE_PATH
    fp_path = args.fingerprints or jaxpr_rules.FINGERPRINTS_PATH

    audit = None
    if args.update_fingerprints:
        audit = jaxpr_rules.build_audit_context()
        fps = jaxpr_rules.current_fingerprints(audit)
        jaxpr_rules.save_fingerprints(fps, fp_path)
        print(f"wrote {fp_path} "
              f"({len(audit.programs)} audited programs)")
        if not args.update_baseline:
            return 0
        # fall through to the baseline rewrite, reusing the audit we just
        # traced (against the fingerprints we just wrote)
        audit.expected_fingerprints = fps

    result = run_analysis(root=args.root, layers=layers,
                          baseline=load_baseline(baseline_path),
                          expected_fingerprints=fp_path,
                          audit=audit if "jaxpr" in layers else None)

    if args.update_baseline:
        from .findings import save_baseline
        save_baseline(baseline_path, result["all_findings"])
        print(f"wrote {baseline_path} "
              f"({len(result['all_findings'])} grandfathered findings)")
        return 0

    if args.json:
        print(json.dumps(findings_to_json(result), indent=1))
    else:
        for f in result["findings"]:
            print(f.render())
        print(f"hmsc_tpu lint: {result['errors']} error(s), "
              f"{result['warnings']} warning(s) "
              f"({result['suppressed']} suppressed, "
              f"{result['baselined']} baselined)", file=sys.stderr)
    return 1 if result["errors"] else 0


if __name__ == "__main__":
    raise SystemExit(lint_main())
