"""Static correctness suite for the hmsc_tpu runtime stack.

The sampler's performance rests on invariants that runtime tests can only
catch *after* they are violated, and never localise: bit-identical draw
streams (RNG key discipline), no host sync inside the jitted hot loop,
a single dtype policy (no silent f64 upcasts), and strict lock discipline
between the driver thread and the background segment writer.  This package
turns those invariants into machine-checked rules that fail fast with a
``file:line``:

- **Layer 1 — AST lint** (:mod:`.ast_rules`): pure-syntax rules over every
  module in ``hmsc_tpu/`` — RNG key reuse, host-RNG misuse, host-sync and
  ``numpy`` hazards inside traced code, mutable dataclass defaults, bare
  ``print``, and declared-lock discipline for writer-shared state.
- **Layer 2 — jaxpr audits** (:mod:`.jaxpr_rules`): abstract-eval every
  registered updater and the jitted segment runner on a canonical small
  spec and assert properties of the *traced program*: no f64 leaks, no
  host callbacks, donation aliasing actually established, no large baked
  constants, bounded shape specialisation, and a committed structural
  fingerprint per program (``fingerprints.json``) so any change to the
  compiled surface shows up in review.

Findings carry a rule id, severity, and ``file:line``; inline
``# hmsc: ignore[rule-id]`` comments suppress single findings, and a
committed JSON baseline grandfathers pre-existing ones.  The whole suite
runs as ``python -m hmsc_tpu lint`` and as the tier-1 ``test_lint_clean``
gate.  The rule catalog lives in ``ANALYSIS.md`` at the repo root.
"""

from .findings import (Finding, Baseline, load_baseline, save_baseline,
                       parse_suppressions, RULES, RuleInfo, rule)
from .runner import run_analysis, findings_to_json, analysis_summary
from .cli import lint_main

__all__ = ["Finding", "Baseline", "load_baseline", "save_baseline",
           "parse_suppressions", "RULES", "RuleInfo", "rule",
           "run_analysis", "findings_to_json", "analysis_summary",
           "lint_main"]
