"""Layer 1 — AST lint rules over ``hmsc_tpu/``.

Pure-syntax checks; no imports of the checked modules, so a module with a
latent import-time bug still gets linted.  Each rule receives a
:class:`ModuleContext` and yields :class:`~.findings.Finding`.

Traced-scope heuristic (used by the in-jit rules): a function is
considered *traced* when it (a) is decorated with ``jax.jit`` (directly or
via ``functools.partial``), (b) has its name passed to ``jax.jit`` /
``jax.vmap`` / ``jax.lax.scan`` / ``jax.lax.cond`` somewhere in the same
module, (c) lives in one of the sweep-level modules
(``mcmc/{sweep,updaters,updaters_sel,updaters_marginal,spatial}.py``) and
takes a ``state``/``carry``/``key`` parameter, or (d) is nested inside a
traced function.  Host-side gate helpers (no state/key parameter) in those
modules are deliberately out of scope — the heuristic is documented in
``ANALYSIS.md`` and tuned to zero false positives on the shipped tree.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from .findings import RULES, rule

__all__ = ["ModuleContext", "run_ast_rules", "SWEEP_MODULES"]

SWEEP_MODULES = ("mcmc/sweep.py", "mcmc/updaters.py", "mcmc/updaters_sel.py",
                 "mcmc/updaters_marginal.py", "mcmc/spatial.py")

# expression roots treated as trace-time-static inside traced scopes: the
# hashable ModelSpec/LevelSpec objects the sweep closes over, the frozen
# ShardCtx (static mesh geometry: axis name / shard count / global ns),
# and the conventional `ns_g` global-species-count scalar derived from
# them (spec.ns is the LOCAL width inside a sharded trace)
STATIC_ROOTS = {"spec", "spec_x", "spec0", "ls", "shard", "ns_g"}

GUARD_RE = re.compile(
    r"#\s*hmsc:\s*guarded-by\[([A-Za-z_][A-Za-z0-9_]*)\]:\s*([A-Za-z0-9_,\s]+)")
HOLDS_RE = re.compile(r"#\s*hmsc:\s*holds\[([A-Za-z_][A-Za-z0-9_]*)\]")


@dataclasses.dataclass
class ModuleContext:
    path: str                     # repo-relative, forward slashes
    source: str
    tree: ast.Module
    lines: list[str]

    @classmethod
    def parse(cls, path: str, source: str) -> "ModuleContext":
        return cls(path=path, source=source,
                   tree=ast.parse(source, filename=path),
                   lines=source.splitlines())


def run_ast_rules(ctx: ModuleContext):
    """All registered layer-1 rules over one parsed module."""
    for info in RULES.values():
        if info.layer != "ast":
            continue
        yield from info.checker(ctx)


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def dotted_name(node) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def expr_roots(node) -> set[str]:
    """Root ``Name`` ids reachable in an expression (the base of every
    attribute/subscript chain plus bare names)."""
    roots: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            roots.add(n.id)
    return roots


def _is_static_expr(node) -> bool:
    """True when every root of the expression is a trace-time constant."""
    if isinstance(node, ast.Constant):
        return True
    return expr_roots(node) <= (STATIC_ROOTS | {"len", "np", "jnp", "int",
                                                "float", "min", "max", "sum"})


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _param_names(fn) -> set[str]:
    a = fn.args
    names = [p.arg for p in
             (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _jit_decorated(fn) -> bool:
    for dec in fn.decorator_list:
        d = dotted_name(dec)
        if d in ("jax.jit", "jit"):
            return True
        if isinstance(dec, ast.Call):
            d = dotted_name(dec.func)
            if d in ("jax.jit", "jit"):
                return True
            if d in ("functools.partial", "partial") and dec.args:
                inner = dotted_name(dec.args[0])
                if inner in ("jax.jit", "jit"):
                    return True
    return False


_TRANSFORM_FNS = {"jax.jit", "jit", "jax.vmap", "vmap", "jax.lax.scan",
                  "lax.scan", "jax.lax.cond", "lax.cond", "jax.lax.while_loop",
                  "lax.while_loop", "jax.checkpoint", "jax.remat",
                  "jax.grad", "jax.pmap", "shard_map"}


def traced_functions(ctx: ModuleContext) -> set[ast.AST]:
    """Function-def nodes considered traced (see module docstring)."""
    # names handed to jax transforms anywhere in the module
    transformed_names: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in _TRANSFORM_FNS:
                for arg in node.args:
                    for r in ast.walk(arg):
                        if isinstance(r, ast.Name):
                            transformed_names.add(r.id)

    in_sweep_module = ctx.path.replace("\\", "/").endswith(SWEEP_MODULES)
    traced: set[ast.AST] = set()
    for fn in _functions(ctx.tree):
        params = _param_names(fn)
        if (_jit_decorated(fn)
                or fn.name in transformed_names
                or (in_sweep_module
                    and params & {"state", "carry", "key"})):
            traced.add(fn)
    # nested defs inherit traced-ness
    changed = True
    while changed:
        changed = False
        for fn in list(traced):
            for inner in ast.walk(fn):
                if (isinstance(inner, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                        and inner is not fn and inner not in traced):
                    traced.add(inner)
                    changed = True
    return traced


def _own_statements(fn):
    """Nodes of a function body excluding nested function bodies."""
    skip: set[ast.AST] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            for sub in ast.walk(node):
                skip.add(sub)
            skip.discard(node)
    for node in ast.walk(fn):
        if node is fn or node in skip:
            continue
        yield node


# ---------------------------------------------------------------------------
# rule: rng-key-reuse
# ---------------------------------------------------------------------------

_KEY_SOURCE_FNS = {"split", "key", "PRNGKey", "fold_in", "wrap_key_data",
                   "clone"}
# second-arg-varying derivation: safe to call repeatedly on the same key
_KEY_DERIVE_FNS = {"fold_in"}


def _jax_random_call(d: str | None) -> bool:
    """Any dotted call into the jax.random namespace (or a common alias).
    ``np.random.*`` deliberately does not match — numpy Generators are
    stateful and reusable."""
    if d is None:
        return False
    return d.startswith(("jax.random.", "jr.", "jrandom.", "random."))


def _is_random_fn(d: str | None) -> bool:
    if d is None:
        return False
    parts = d.split(".")
    return _jax_random_call(d) and parts[-1] in _KEY_SOURCE_FNS


@rule("rng-key-reuse", "error", "ast",
      "a jax.random key is consumed at most once per scope; reuse "
      "correlates draw streams silently")
def check_rng_key_reuse(ctx: ModuleContext):
    findings = []

    def fn_scopes(tree):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    for fn in fn_scopes(ctx.tree):
        findings.extend(_scan_key_scope(ctx, fn))
    return findings


def _assigned_names(target) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for el in target.elts:
            out.extend(_assigned_names(el))
        return out
    return []


def _terminates(body: list) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _scan_key_scope(ctx: ModuleContext, fn):
    """Track key-typed names through one function's straight-line flow.

    state: name -> "fresh" | "consumed".  Consuming a fresh key marks it;
    consuming a consumed key is a finding.  ``fold_in`` never consumes
    (it derives with explicit data).  Loop bodies additionally flag keys
    from the enclosing scope that are consumed per-iteration without being
    rebound inside the body."""
    findings: list = []
    keys: dict[str, str] = {}
    # a param named `key`/`*_key` is tracked only with *evidence* it is a
    # PRNG key: the module is a sweep-level module (where key params are
    # PRNG keys by convention), or the function hands the name to a
    # jax.random.* call somewhere.  (`ShardBackedArrays.__getitem__(self,
    # key)`-style dict keys must not be tracked; np.random.Generator
    # params are stateful and *meant* to be reused, so `rng` never is.)
    in_sweep = ctx.path.replace("\\", "/").endswith(SWEEP_MODULES)
    evidence = in_sweep or any(
        isinstance(n, ast.Call) and _jax_random_call(dotted_name(n.func))
        for n in ast.walk(fn))
    if evidence:
        for p in _param_names(fn):
            if p == "key" or p.endswith("_key"):
                keys[p] = "fresh"

    def handle_call(node, keys, loop_outer, loop_consumed):
        d = dotted_name(node.func)
        derive = d is not None and d.split(".")[-1] in _KEY_DERIVE_FNS
        if derive:
            return
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in keys:
                name = arg.id
                if keys[name] == "consumed":
                    findings.append(RULES["rng-key-reuse"].finding(
                        ctx.path, node.lineno,
                        f"key `{name}` consumed again without an "
                        f"intervening split (same scope)"))
                keys[name] = "consumed"
                if loop_outer is not None and name in loop_outer:
                    loop_consumed.add(name)

    def handle_assign(node, keys):
        value = node.value
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        names = []
        for t in targets:
            names.extend(_assigned_names(t))
        is_key_src = (isinstance(value, ast.Call)
                      and _is_random_fn(dotted_name(value.func)))
        for name in names:
            if is_key_src:
                keys[name] = "fresh"
            elif name in keys:
                del keys[name]       # rebound to something non-key

    def scan_stmts(stmts, keys, loop_outer=None, loop_consumed=None):
        for stmt in stmts:
            scan_stmt(stmt, keys, loop_outer, loop_consumed)

    def handle_comp_call(node, keys):
        """A call inside a comprehension body runs once per iteration: a
        tracked key consumed there is reused every iteration (the
        comprehension cannot rebind it)."""
        d = dotted_name(node.func)
        if d is not None and d.split(".")[-1] in _KEY_DERIVE_FNS:
            return
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in keys:
                findings.append(RULES["rng-key-reuse"].finding(
                    ctx.path, node.lineno,
                    f"key `{arg.id}` consumed inside a comprehension — "
                    f"every iteration reuses the same key"))
                keys[arg.id] = "consumed"

    def scan_expr_calls(node, keys, loop_outer, loop_consumed):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return               # nested scopes scanned independently
        # comprehension bodies iterate: consumption there is per-iteration
        # reuse.  The FIRST generator's iterable evaluates once, so calls
        # there are ordinary single consumptions.
        comp_calls: set = set()
        once_calls: set = set()
        for sub in ast.walk(node):
            if isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                for inner in ast.walk(sub.generators[0].iter):
                    if isinstance(inner, ast.Call):
                        once_calls.add(inner)
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Call) \
                            and inner not in once_calls:
                        comp_calls.add(inner)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if sub in comp_calls:
                handle_comp_call(sub, keys)
            else:
                handle_call(sub, keys, loop_outer, loop_consumed)

    def scan_stmt(stmt, keys, loop_outer, loop_consumed):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if stmt.value is not None:
                scan_expr_calls(stmt.value, keys, loop_outer, loop_consumed)
            if not isinstance(stmt, ast.AugAssign):
                handle_assign(stmt, keys)
            return
        if isinstance(stmt, ast.If):
            scan_expr_calls(stmt.test, keys, loop_outer, loop_consumed)
            k1, k2 = dict(keys), dict(keys)
            scan_stmts(stmt.body, k1, loop_outer, loop_consumed)
            scan_stmts(stmt.orelse, k2, loop_outer, loop_consumed)
            # a branch ending in return/raise/break/continue never reaches
            # the fallthrough: its consumptions don't merge (the common
            # `if fast_path: return f(key)` + `return g(key)` shape is one
            # consumption per execution, not two)
            merged = [k for k, body in ((k1, stmt.body), (k2, stmt.orelse))
                      if not _terminates(body)]
            if not merged:
                merged = [dict(keys)]
            for name in {n for k in merged for n in k} | set(keys):
                states = [k.get(name) for k in merged]
                if all(s is None for s in states):
                    keys.pop(name, None)
                elif "consumed" in states:
                    keys[name] = "consumed"
                else:
                    keys[name] = "fresh"
            return
        if isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                scan_expr_calls(stmt.iter, keys, loop_outer, loop_consumed)
                for name in _assigned_names(stmt.target):
                    keys.pop(name, None)
            else:
                scan_expr_calls(stmt.test, keys, loop_outer, loop_consumed)
            outer = set(keys)
            consumed_in_body: set[str] = set()
            rebound = {n for s in ast.walk(stmt)
                       if isinstance(s, ast.Assign)
                       for t in s.targets for n in _assigned_names(t)}
            scan_stmts(stmt.body, keys, outer, consumed_in_body)
            for name in consumed_in_body - rebound:
                findings.append(RULES["rng-key-reuse"].finding(
                    ctx.path, stmt.lineno,
                    f"key `{name}` from the enclosing scope is consumed "
                    f"inside a loop body without being rebound — every "
                    f"iteration reuses the same key"))
            scan_stmts(stmt.orelse, keys, loop_outer, loop_consumed)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                scan_expr_calls(item.context_expr, keys, loop_outer,
                                loop_consumed)
            scan_stmts(stmt.body, keys, loop_outer, loop_consumed)
            return
        if isinstance(stmt, ast.Try):
            scan_stmts(stmt.body, keys, loop_outer, loop_consumed)
            for h in stmt.handlers:
                scan_stmts(h.body, dict(keys), loop_outer, loop_consumed)
            scan_stmts(stmt.orelse, keys, loop_outer, loop_consumed)
            scan_stmts(stmt.finalbody, keys, loop_outer, loop_consumed)
            return
        # generic statement: scan expressions for calls
        scan_expr_calls(stmt, keys, loop_outer, loop_consumed)

    scan_stmts(fn.body, keys)
    return findings


# ---------------------------------------------------------------------------
# rule: py-random
# ---------------------------------------------------------------------------

_NP_GLOBAL_DRAWS = {"seed", "RandomState", "rand", "randn", "randint",
                    "random", "normal", "uniform", "choice", "permutation",
                    "shuffle", "standard_normal", "gamma", "beta", "poisson",
                    "binomial", "exponential"}


@rule("py-random", "error", "ast",
      "all draws are reproducible: device randomness uses jax.random, host "
      "randomness uses an explicitly seeded np.random.Generator")
def check_py_random(ctx: ModuleContext):
    findings = []
    info = RULES["py-random"]
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "random":
                    findings.append(info.finding(
                        ctx.path, node.lineno,
                        "stdlib `random` imported in library code (use "
                        "jax.random on device, seeded np.random.Generator "
                        "on host)"))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "random":
                findings.append(info.finding(
                    ctx.path, node.lineno,
                    "stdlib `random` imported in library code"))
        elif isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d is None:
                continue
            parts = d.split(".")
            if parts[:2] in (["np", "random"], ["numpy", "random"]) \
                    and len(parts) == 3:
                if parts[2] in _NP_GLOBAL_DRAWS:
                    findings.append(info.finding(
                        ctx.path, node.lineno,
                        f"global-state numpy RNG `{d}(...)` — "
                        f"unreproducible; use a seeded "
                        f"np.random.default_rng(seed)"))
                elif parts[2] == "default_rng" and not node.args \
                        and not node.keywords:
                    findings.append(info.finding(
                        ctx.path, node.lineno,
                        "unseeded np.random.default_rng() — draws are not "
                        "reproducible; thread a seed or a Generator through"))
    return findings


# ---------------------------------------------------------------------------
# rules: host-sync-in-jit / numpy-in-jit
# ---------------------------------------------------------------------------

@rule("host-sync-in-jit", "error", "ast",
      "the jitted hot loop never blocks on device→host sync (.item(), "
      "float()/int()/bool() on traced values)")
def check_host_sync(ctx: ModuleContext):
    findings = []
    info = RULES["host-sync-in-jit"]
    for fn in traced_functions(ctx):
        for node in _own_statements(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item":
                findings.append(info.finding(
                    ctx.path, node.lineno,
                    ".item() inside traced code forces a device→host sync "
                    "(and fails under jit on abstract values)"))
            d = dotted_name(node.func)
            if d in ("float", "int", "bool") and node.args \
                    and not _is_static_expr(node.args[0]):
                findings.append(info.finding(
                    ctx.path, node.lineno,
                    f"{d}() on a traced value inside traced code — host "
                    f"sync / ConcretizationTypeError hazard"))
    return findings


@rule("numpy-in-jit", "error", "ast",
      "traced code computes with jnp, never np: numpy on traced values "
      "either crashes under jit or silently constant-folds a draw")
def check_numpy_in_jit(ctx: ModuleContext):
    findings = []
    info = RULES["numpy-in-jit"]
    for fn in traced_functions(ctx):
        for node in _own_statements(fn):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None or not (d.startswith("np.")
                                 or d.startswith("numpy.")):
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            if all(_is_static_expr(a) for a in args):
                continue             # static shape/prior arithmetic is fine
            findings.append(info.finding(
                ctx.path, node.lineno,
                f"`{d}(...)` on a non-static value inside traced code "
                f"(use jnp, or hoist to trace-time constants)"))
    return findings


# ---------------------------------------------------------------------------
# rule: mutable-default
# ---------------------------------------------------------------------------

def _is_mutable_literal(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        return d in ("list", "dict", "set")
    return False


def _is_dataclass_like(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        d = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
        if d in ("dataclasses.dataclass", "dataclass", "struct.dataclass"):
            return True
    for base in node.bases:
        d = dotted_name(base)
        if d in ("struct.PyTreeNode", "PyTreeNode"):
            return True
    return False


@rule("mutable-default", "error", "ast",
      "spec/struct dataclasses and function signatures never share mutable "
      "default instances across calls")
def check_mutable_default(ctx: ModuleContext):
    findings = []
    info = RULES["mutable-default"]
    for fn in _functions(ctx.tree):
        for default in list(fn.args.defaults) + \
                [d for d in fn.args.kw_defaults if d is not None]:
            if _is_mutable_literal(default):
                findings.append(info.finding(
                    ctx.path, default.lineno,
                    f"mutable default argument in `{fn.name}(...)` is "
                    f"shared across calls (use None + init inside)"))
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef) and _is_dataclass_like(node):
            for stmt in node.body:
                value = None
                if isinstance(stmt, ast.AnnAssign):
                    value = stmt.value
                elif isinstance(stmt, ast.Assign):
                    value = stmt.value
                if value is not None and _is_mutable_literal(value):
                    findings.append(info.finding(
                        ctx.path, value.lineno,
                        f"mutable class-level default in dataclass "
                        f"`{node.name}` is shared across instances "
                        f"(use dataclasses.field(default_factory=...))"))
    return findings


# ---------------------------------------------------------------------------
# rule: bare-print (migrated from tests/test_telemetry.py)
# ---------------------------------------------------------------------------

_PRINT_EXEMPT = ("obs/", "__main__.py", "bench_cli.py", "analysis/cli.py",
                 "fleet/cli.py", "refit/cli.py")


@rule("bare-print", "error", "ast",
      "library progress output routes through hmsc_tpu.obs.log (rank-"
      "prefixed, telemetry-recorded); bare print is reserved for the CLI "
      "entry points")
def check_bare_print(ctx: ModuleContext):
    p = ctx.path.replace("\\", "/")
    rel = p.split("hmsc_tpu/", 1)[-1]
    if rel.startswith(_PRINT_EXEMPT) or rel.endswith(_PRINT_EXEMPT):
        return []
    findings = []
    info = RULES["bare-print"]
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "print":
            findings.append(info.finding(
                ctx.path, node.lineno,
                "bare print( in library code — route through "
                "hmsc_tpu.obs.log"))
    return findings


# ---------------------------------------------------------------------------
# rule: lock-discipline
# ---------------------------------------------------------------------------

@rule("lock-discipline", "error", "ast",
      "attributes declared `# hmsc: guarded-by[<lock>]: a, b` are only "
      "touched under that lock (driver vs background-writer thread safety)")
def check_lock_discipline(ctx: ModuleContext):
    findings = []
    info = RULES["lock-discipline"]
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded: dict[str, str] = {}   # attr -> lock attr
        end = getattr(cls, "end_lineno", cls.lineno)
        for line in ctx.lines[cls.lineno - 1:end]:
            m = GUARD_RE.search(line)
            if m:
                lock = m.group(1)
                for attr in m.group(2).split(","):
                    attr = attr.strip()
                    if attr:
                        guarded[attr] = lock
        if not guarded:
            continue
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in ("__init__", "__del__", "__repr__"):
                continue
            held: set[str] = set()
            if meth.name.endswith("_locked"):
                held = set(guarded.values())
            # `# hmsc: holds[_lock]` on the def line or the line above
            for ln in (meth.lineno - 1, meth.lineno):
                if 1 <= ln <= len(ctx.lines):
                    hm = HOLDS_RE.search(ctx.lines[ln - 1])
                    if hm:
                        held.add(hm.group(1))
            out: list = []
            _walk_locked(ctx, info, meth, guarded, held, False, out)
            findings.extend(out)
    return findings


def _walk_locked(ctx, info, node, guarded, held, in_nested, out):
    """Recursive visitor; ``held`` is the set of lock attrs lexically held
    at this point.  Nested closures reset it — they run later, on an
    unknown thread, without the enclosing lock."""
    lock_names = set(guarded.values())
    if isinstance(node, (ast.With, ast.AsyncWith)):
        acquired = set(held)
        for item in node.items:
            d = dotted_name(item.context_expr)
            if d is not None and d.startswith("self."):
                lk = d.split(".", 1)[1]
                if lk in lock_names:
                    acquired.add(lk)
            _walk_locked(ctx, info, item.context_expr, guarded, held,
                         in_nested, out)
        for inner in node.body:
            _walk_locked(ctx, info, inner, guarded, acquired,
                         in_nested, out)
        return
    for sub in ast.iter_child_nodes(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            _walk_locked(ctx, info, sub, guarded, set(), True, out)
            continue
        if isinstance(sub, ast.Attribute) \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "self" and sub.attr in guarded:
            lock = guarded[sub.attr]
            if lock not in held:
                where = ("a nested closure (runs without the enclosing "
                         "lock)" if in_nested else "this method")
                out.append(info.finding(
                    ctx.path, sub.lineno,
                    f"self.{sub.attr} touched in {where} without holding "
                    f"self.{lock} (declared guarded-by[{lock}])"))
            continue
        _walk_locked(ctx, info, sub, guarded, held, in_nested, out)
