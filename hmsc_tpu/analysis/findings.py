"""Finding/rule framework shared by both analysis layers.

A *rule* is a registered checker with a stable kebab-case id, a severity,
and a one-line statement of the invariant it protects (rendered into the
CLI output and ``ANALYSIS.md``).  A *finding* is one violation, pinned to
a ``file:line``.

Suppression: a ``# hmsc: ignore[rule-id]`` comment on the offending line
(or the line directly above it) suppresses findings of that rule on that
line; ``# hmsc: ignore[rule-a,rule-b]`` lists several, ``# hmsc: ignore``
suppresses every rule.  Suppressions should carry a justification in the
trailing text — the lint is a reviewer aid, not an oracle.

Baseline: a committed JSON file of grandfathered findings.  Matching is by
``(rule, path, message)`` — line numbers drift with unrelated edits, so
they are recorded for display but ignored when matching.  Regenerate with
``python -m hmsc_tpu lint --update-baseline``.
"""

from __future__ import annotations

import dataclasses
import json
import re

__all__ = ["Finding", "RuleInfo", "RULES", "rule", "Baseline",
           "load_baseline", "save_baseline", "parse_suppressions",
           "SUPPRESS_RE"]

SEVERITIES = ("error", "warning")

# `# hmsc: ignore` / `# hmsc: ignore[rule-a, rule-b] -- justification`
SUPPRESS_RE = re.compile(r"#\s*hmsc:\s*ignore(?:\[([a-z0-9_,\s-]*)\])?")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str                 # "error" | "warning"
    path: str                     # repo-relative, forward slashes
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.severity} [{self.rule}] " \
               f"{self.message}"

    def match_key(self) -> tuple:
        """Baseline identity — line numbers excluded (they drift)."""
        return (self.rule, self.path, self.message)

    def to_json(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line,
                "message": self.message}


@dataclasses.dataclass(frozen=True)
class RuleInfo:
    id: str
    severity: str
    layer: str                    # "ast" | "jaxpr"
    protects: str                 # the invariant, one line
    checker: object               # callable; signature depends on layer

    def finding(self, path: str, line: int, message: str) -> Finding:
        return Finding(self.id, self.severity, path, int(line), message)


RULES: dict[str, RuleInfo] = {}


def rule(id: str, severity: str, layer: str, protects: str):
    """Register a checker under a stable rule id.

    AST checkers are called as ``checker(ctx)`` with a
    :class:`~hmsc_tpu.analysis.ast_rules.ModuleContext` and yield findings
    for one parsed module; jaxpr checkers are called once with the audit
    context (see :mod:`~hmsc_tpu.analysis.jaxpr_rules`)."""
    if severity not in SEVERITIES:
        raise ValueError(f"severity must be one of {SEVERITIES}: {severity}")

    def deco(fn):
        if id in RULES:
            raise ValueError(f"duplicate rule id: {id}")
        RULES[id] = RuleInfo(id=id, severity=severity, layer=layer,
                             protects=protects, checker=fn)
        return fn
    return deco


def parse_suppressions(source: str) -> dict[int, set[str] | None]:
    """``{line_no: suppressed-rule-ids or None (= all rules)}``.

    A trailing comment covers its own line; a comment-only line covers the
    line below it (so both styles work without a trailing suppression
    accidentally bleeding onto the next statement).  Only real COMMENT
    tokens count — the marker inside a string literal or docstring (e.g.
    a lint rule's own help text) must never suppress anything."""
    import io
    import tokenize

    out: dict[int, set[str] | None] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out                   # unparseable files produce no findings
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        ids = m.group(1)
        val = (None if ids is None
               else {s.strip() for s in ids.split(",") if s.strip()})
        row, col = tok.start
        comment_only = not tok.line[:col].strip()
        for ln in ((row + 1,) if comment_only else (row,)):
            prev = out.get(ln, set())
            if val is None or prev is None:
                out[ln] = None
            else:
                out[ln] = set(prev) | val
    return out


def is_suppressed(finding: Finding,
                  suppressions: dict[int, set[str] | None]) -> bool:
    sup = suppressions.get(finding.line)
    if sup is None and finding.line in suppressions:
        return True
    return bool(sup) and finding.rule in sup


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


class Baseline:
    """Committed grandfathered findings; ``known`` matches by
    ``(rule, path, message)``."""

    def __init__(self, findings: list[Finding] | None = None):
        self.findings = list(findings or [])
        self._keys = {f.match_key() for f in self.findings}

    def known(self, finding: Finding) -> bool:
        return finding.match_key() in self._keys

    def to_json(self) -> dict:
        return {"version": BASELINE_VERSION,
                "findings": [f.to_json() for f in sorted(
                    self.findings,
                    key=lambda f: (f.path, f.line, f.rule))]}


def load_baseline(path) -> Baseline:
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return Baseline()
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: unsupported baseline version "
                         f"{doc.get('version')!r}")
    return Baseline([Finding(**f) for f in doc.get("findings", [])])


def save_baseline(path, findings: list[Finding]) -> None:
    with open(path, "w") as f:
        json.dump(Baseline(findings).to_json(), f, indent=1, sort_keys=True)
        f.write("\n")
