"""Layer 2 — jaxpr audits of the traced sampling AND serving programs.

Abstract-evals every registered updater (``hmsc_tpu.mcmc.registry``), the
assembled sweep, the jitted segment runner, and the serving kernels
(``hmsc_tpu.serve.kernels.audit_kernels`` — the predict / conditional
programs the serving engine compile-caches) on canonical small specs,
then audits the *programs* rather than the source:

- ``jaxpr-f64``: no float64/complex128 anywhere in the traced program.
  Tracing runs under ``jax.experimental.enable_x64`` with f32 inputs, so
  any op that fails to derive its dtype from its inputs (a bare
  ``jnp.ones(n)``, an np-computed constant) surfaces as a leak — under
  the production x64-off config the same site silently downcasts, which
  is why no runtime test can pin it.
- ``jaxpr-host-callback``: no ``pure_callback``/``io_callback``/
  ``debug_callback`` primitives in the sweep or the segment runner — the
  hot loop never re-enters Python.
- ``jaxpr-large-const``: no constant baked into a jaxpr above a size
  threshold (model data rides in as arguments; a large closed-over
  constant is duplicated per executable and bloats HBM).
- ``jaxpr-donation``: the segment runner's lowering actually establishes
  input→output aliasing for every carry leaf (donation configured but
  not established doubles steady-state HBM).
- ``jaxpr-recompile``: the sweep's *shape-blind* structure is identical
  across a small shape sweep — a program whose structure varies with
  array dims recompiles per shape in production.
- ``jaxpr-fingerprint``: each audited program's structural fingerprint
  matches the committed ``fingerprints.json``; any change to the compiled
  surface therefore shows up in review as a one-line diff.  Regenerate
  with ``python -m hmsc_tpu lint --update-fingerprints``.
- ``jaxpr-registry-coverage``: every registered updater is exercised by
  at least one canonical spec (the audit cannot silently skip one).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

from .findings import RULES, rule

__all__ = ["run_jaxpr_rules", "build_audit_context", "JaxprAudit",
           "fingerprint_jaxpr", "FINGERPRINTS_PATH", "load_fingerprints",
           "save_fingerprints", "LARGE_CONST_BYTES"]

FINGERPRINTS_PATH = os.path.join(os.path.dirname(__file__),
                                 "fingerprints.json")
FINGERPRINTS_VERSION = 1

# constants above this baked into a traced program are HBM bloat: model
# data arrays must ride in as arguments, not closure constants
LARGE_CONST_BYTES = 256 * 1024

# host-callback primitives that would re-enter Python from the hot loop
_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "callback", "host_callback_call", "outside_call"}


@dataclasses.dataclass
class AuditProgram:
    name: str                     # e.g. "updater:BetaLambda", "sweep@base"
    path: str                     # repo-relative module the program lives in
    closed: object                # ClosedJaxpr (production trace)
    closed_x64: object            # ClosedJaxpr traced under enable_x64
    x64_error: str | None = None  # x64 trace failure (itself an f64 leak:
    #                               a scan carry changed dtype mid-sweep)


@dataclasses.dataclass
class JaxprAudit:
    programs: list
    runner_text: str              # segment-runner lowering (StableHLO text)
    runner_n_carry_leaves: int
    sweep_shape_variants: dict    # shape-blind fp -> [size labels]
    expected_fingerprints: dict | None
    missing_updaters: list
    # True when the sharded-sweep programs could not be traced (fewer than
    # SHARD_AUDIT_DEVICES devices): committed "sharded_sweep@*" fingerprints
    # are then exempt from the stale-entry check instead of erroring
    sharded_skipped: bool = False


# ---------------------------------------------------------------------------
# canonical specs
# ---------------------------------------------------------------------------

def _canonical_models():
    """Small deterministic models that, together, exercise every
    registered updater: ``base`` (probit + traits + phylo + one
    unstructured level), ``spatial`` (Full GP level), ``rrr`` and ``sel``
    (reduced-rank / spike-and-slab designs)."""
    import numpy as np
    import pandas as pd

    from ..model import Hmsc, XSelect
    from ..random_level import HmscRandomLevel, set_priors_random_level

    def _design(rng, ny, nc):
        return np.column_stack([np.ones(ny),
                                rng.standard_normal((ny, nc - 1))])

    def _units(rng, ny, n_units):
        units = [f"u{i:02d}" for i in rng.integers(0, n_units, ny)]
        for i in range(n_units):
            units[i % ny] = f"u{i:02d}"
        return units

    models = {}

    def base(ny=12, ns=4):
        rng = np.random.default_rng(11)
        X = _design(rng, ny, 2)
        Y = (rng.standard_normal((ny, ns)) > 0).astype(float)
        study = pd.DataFrame({"lvl": _units(rng, ny, 5)})
        rl = HmscRandomLevel(units=study["lvl"])
        set_priors_random_level(rl, nf_max=2, nf_min=2)
        from ..data.td import random_coalescent_corr
        Tr = np.column_stack([np.ones(ns), rng.standard_normal(ns)])
        return Hmsc(Y=Y, X=X, distr="probit", study_design=study,
                    ran_levels={"lvl": rl}, Tr=Tr,
                    C=random_coalescent_corr(ns, rng))

    models["base"] = base

    def spatial(ny=12, ns=3):
        rng = np.random.default_rng(12)
        n_units = 6
        X = _design(rng, ny, 2)
        Y = rng.standard_normal((ny, ns))
        units = _units(rng, ny, n_units)
        study = pd.DataFrame({"lvl": units})
        s_df = pd.DataFrame(rng.uniform(size=(n_units, 2)),
                            index=sorted(set(units)), columns=["x", "y"])
        rl = HmscRandomLevel(s_data=s_df, s_method="Full")
        set_priors_random_level(rl, nf_max=2, nf_min=2)
        return Hmsc(Y=Y, X=X, distr="normal", study_design=study,
                    ran_levels={"lvl": rl})

    models["spatial"] = spatial

    def rrr(ny=12, ns=3):
        rng = np.random.default_rng(13)
        X = _design(rng, ny, 2)
        XRRR = rng.standard_normal((ny, 2))
        Y = rng.standard_normal((ny, ns))
        return Hmsc(Y=Y, X=X, XRRR=XRRR, nc_rrr=1, distr="normal")

    models["rrr"] = rrr

    def sel(ny=12, ns=4):
        rng = np.random.default_rng(14)
        X = _design(rng, ny, 2)
        Y = (rng.standard_normal((ny, ns)) > 0).astype(float)
        s = XSelect(cov_group=[1],
                    sp_group=[0] * (ns // 2) + [1] * (ns - ns // 2),
                    q=[0.5, 0.5])
        return Hmsc(Y=Y, X=X, x_select=[s], distr="probit")

    models["sel"] = sel
    return models


# species count of the sharded audit/ledger variants: divisible by every
# emulated shard count the CI mesh uses (1, 2, 4, 8)
SHARD_AUDIT_NS = 8
SHARD_AUDIT_DEVICES = 8


def _shard_models():
    """The canonical factories re-sized so ``ns`` divides every emulated
    shard count — the specs the sharded-sweep audits, the comm-bytes
    ledger, and ``tests/test_shard.py`` all trace."""
    base = _canonical_models()
    return {name: (lambda fn=fn: fn(ns=SHARD_AUDIT_NS))
            for name, fn in base.items()}


# 2D (species x sites) audit mesh: the emulated 8 devices reshaped
SITE_AUDIT_SP = 4
SITE_AUDIT_ST = 2


def _site_shard_models():
    """Canonical factories for the 2D (species × sites) mesh: ``ns``
    divides the species extent, and ``ny`` + every level's unit count
    divide every emulated site extent (2 and 4) — the specs the 2D
    sharded-sweep audits, the ``shard4x2`` ledger entries, and the
    site-axis agreement tests in ``tests/test_shard.py`` all trace.
    Covers the unstructured base class plus all three spatial methods
    (Full + NNGP + GPP — the np-dominated classes the site axis is
    for)."""
    import numpy as np
    import pandas as pd

    from ..model import Hmsc
    from ..random_level import HmscRandomLevel, set_priors_random_level

    ny, ns, n_units = 16, SHARD_AUDIT_NS, 8

    def _design(rng):
        return np.column_stack([np.ones(ny),
                                rng.standard_normal((ny, 1))])

    def _units():
        # round-robin: every unit appears, ny divides evenly
        return [f"u{i % n_units:02d}" for i in range(ny)]

    def _spatial(method, seed, **rl_kw):
        def build():
            rng = np.random.default_rng(seed)
            X = _design(rng)
            Y = rng.standard_normal((ny, ns))
            units = _units()
            s_df = pd.DataFrame(rng.uniform(size=(n_units, 2)) * 4,
                                index=sorted(set(units)),
                                columns=["x", "y"])
            rl = HmscRandomLevel(s_data=s_df, s_method=method, **rl_kw)
            set_priors_random_level(rl, nf_max=2, nf_min=2)
            return Hmsc(Y=Y, X=X, distr="normal",
                        study_design=pd.DataFrame({"lvl": units}),
                        ran_levels={"lvl": rl})
        return build

    def base():
        rng = np.random.default_rng(21)
        X = _design(rng)
        Y = (rng.standard_normal((ny, ns)) > 0).astype(float)
        units = _units()
        rl = HmscRandomLevel(units=pd.Series(units))
        set_priors_random_level(rl, nf_max=2, nf_min=2)
        from ..data.td import random_coalescent_corr
        Tr = np.column_stack([np.ones(ns), rng.standard_normal(ns)])
        return Hmsc(Y=Y, X=X, distr="probit",
                    study_design=pd.DataFrame({"lvl": units}),
                    ran_levels={"lvl": rl}, Tr=Tr,
                    C=random_coalescent_corr(ns, rng))

    rngk = np.random.default_rng(23)
    knots = pd.DataFrame(rngk.uniform(size=(3, 2)) * 4,
                         columns=["x", "y"])
    return {
        "base": base,
        "spatial": _spatial("Full", 22),
        "nngp": _spatial("NNGP", 23, n_neighbours=4),
        "gpp": _spatial("GPP", 24, s_knot=knots),
    }


def _build(hM, nf_cap=2, seed=0):
    from ..precompute import compute_data_parameters
    from ..mcmc.structs import build_model_data, build_spec, build_state
    spec = build_spec(hM, nf_cap)
    data = build_model_data(hM, compute_data_parameters(hM), spec)
    state = build_state(hM, spec, seed)
    return spec, data, state


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def _aval_sig(v, shape_blind: bool) -> str:
    aval = v.aval
    shape = "r%d" % len(aval.shape) if shape_blind else list(aval.shape)
    return f"{aval.dtype}{shape}"


def _serialize(jaxpr, depth, lines, shape_blind):
    import jax.core as jcore
    for eqn in jaxpr.eqns:
        ins = ",".join(
            ("lit" if isinstance(v, jcore.Literal) else "") +
            _aval_sig(v, shape_blind) for v in eqn.invars)
        outs = ",".join(_aval_sig(v, shape_blind) for v in eqn.outvars)
        lines.append(f"{depth}:{eqn.primitive.name}({ins})->({outs})")
        for sub in _sub_jaxprs(eqn):
            _serialize(sub, depth + 1, lines, shape_blind)


def _sub_jaxprs(eqn):
    """Nested jaxprs inside an eqn's params (scan/cond/pjit/...)."""
    import jax.core as jcore
    out = []

    def visit(v):
        if isinstance(v, jcore.ClosedJaxpr):
            out.append(v.jaxpr)
        elif isinstance(v, jcore.Jaxpr):
            out.append(v)
        elif isinstance(v, (tuple, list)):
            for x in v:
                visit(x)

    for v in eqn.params.values():
        visit(v)
    return out


def fingerprint_jaxpr(closed, shape_blind: bool = False) -> dict:
    """Stable structural fingerprint of a ClosedJaxpr: primitive sequence
    with in/out dtypes+shapes (ranks only when ``shape_blind``), hashed.
    Variable names and constant *values* are excluded, so the fingerprint
    moves exactly when the compiled surface does."""
    lines: list[str] = []
    _serialize(closed.jaxpr, 0, lines, shape_blind)
    blob = "\n".join(lines).encode()
    prims: dict[str, int] = {}
    for ln in lines:
        p = ln.split(":", 1)[1].split("(", 1)[0]
        prims[p] = prims.get(p, 0) + 1
    return {"sha256": hashlib.sha256(blob).hexdigest()[:16],
            "n_eqns": len(lines),
            "prims": dict(sorted(prims.items()))}


def load_fingerprints(path=FINGERPRINTS_PATH) -> dict | None:
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return None
    if doc.get("version") != FINGERPRINTS_VERSION:
        return None
    return doc.get("programs", {})


def save_fingerprints(programs: dict, path=FINGERPRINTS_PATH) -> None:
    with open(path, "w") as f:
        json.dump({"version": FINGERPRINTS_VERSION,
                   "jax": __import__("jax").__version__,
                   "programs": dict(sorted(programs.items()))},
                  f, indent=1, sort_keys=False)
        f.write("\n")


# ---------------------------------------------------------------------------
# audit-context construction (the tracing pass)
# ---------------------------------------------------------------------------

_MOD_PATHS = {
    "updaters": "hmsc_tpu/mcmc/updaters.py",
    "updaters_sel": "hmsc_tpu/mcmc/updaters_sel.py",
    "updaters_marginal": "hmsc_tpu/mcmc/updaters_marginal.py",
    "spatial": "hmsc_tpu/mcmc/spatial.py",
}


def build_audit_context(expected_fingerprints=None) -> JaxprAudit:
    """Trace every registered updater + sweep + segment runner on the
    canonical specs.  Pure abstract evaluation — nothing compiles except
    the segment runner's (StableHLO-only) lowering."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    from ..mcmc.registry import UPDATER_REGISTRY
    from ..mcmc.sweep import make_sweep

    models = _canonical_models()
    built = {name: _build(fn()) for name, fn in models.items()}

    # fresh exemplar key per trace (abstract eval never draws, but the
    # audited code must still see a key-typed input of the production impl)
    def _k():
        return jax.random.key(0, impl="threefry2x32")

    def _trace_pair(f, *args):
        closed = jax.make_jaxpr(f)(*args)
        try:
            with enable_x64():
                closed_x64 = jax.make_jaxpr(f)(*args)
        except Exception as e:     # noqa: BLE001 — surfaced as a finding
            return closed, None, f"{type(e).__name__}: {str(e)[:300]}"
        return closed, closed_x64, None

    programs: list[AuditProgram] = []
    covered: set[str] = set()

    for entry in UPDATER_REGISTRY:
        for mname, (spec, data, state) in built.items():
            if not entry.applies(spec, data):
                continue
            wrapped = (lambda e, s: lambda d, st, k: e.fn(s, d, st, k))(
                entry, spec)
            closed, closed_x64, err = _trace_pair(wrapped, data, state,
                                                  _k())
            programs.append(AuditProgram(
                name=f"updater:{entry.name}",
                path=_MOD_PATHS.get(entry.module,
                                    "hmsc_tpu/mcmc/updaters.py"),
                closed=closed, closed_x64=closed_x64, x64_error=err))
            covered.add(entry.name)
            break                  # first applicable canonical spec

    missing = [e.name for e in UPDATER_REGISTRY if e.name not in covered]

    # the assembled sweep, per canonical spec
    for mname, (spec, data, state) in built.items():
        sweep = make_sweep(spec, None, tuple(0 for _ in range(spec.nr)))
        closed, closed_x64, err = _trace_pair(sweep, data, state, _k())
        programs.append(AuditProgram(
            name=f"sweep@{mname}", path="hmsc_tpu/mcmc/sweep.py",
            closed=closed, closed_x64=closed_x64, x64_error=err))

    # the mixed-precision sweep per canonical spec, under that class's
    # in-code default policy (ledger-independent, so the audit is stable
    # while the ledger is being re-recorded): same f64 probe / callback /
    # const rules, committed fingerprints named `sweep_mp@<model>`, plus
    # the jaxpr-mixed-precision rule asserting bf16 stays confined to
    # these programs and never reaches a Cholesky/solve pivot
    from ..mcmc.precision import default_policy, stage_data
    for mname, (spec, data, state) in built.items():
        policy = default_policy(spec, ledger={})
        if policy is None:
            continue
        sweep_mp = make_sweep(spec, None, tuple(0 for _ in range(spec.nr)),
                              precision=policy)
        staged = stage_data(data, policy)
        closed, closed_x64, err = _trace_pair(sweep_mp, data, state, _k(),
                                              staged)
        programs.append(AuditProgram(
            name=f"sweep_mp@{mname}", path="hmsc_tpu/mcmc/precision.py",
            closed=closed, closed_x64=closed_x64, x64_error=err))

    # the tenant-masked batched sweep (mcmc/multitenant.py) on the padded
    # canonical specs that can join a batch: same f64 probe / callback /
    # const / fingerprint rules, committed fingerprints named
    # `batched_sweep@<model>`.  A zero-padding bucket folds the EXACT
    # production sweep (no mask ops), so only the padded variant needs its
    # own fingerprint; the unpadded programs above already pin that path.
    from ..mcmc.multitenant import (batch_unsupported_reason, bucket_dims,
                                    make_batched_sweep, pad_spec,
                                    pad_state, pad_tenant)
    for mname, (spec, data, state) in built.items():
        if batch_unsupported_reason(spec) is not None:
            continue
        dims = bucket_dims(spec)
        spec_b = pad_spec(spec, dims, has_na=True)
        data_b = pad_tenant(spec, data, dims)
        state_b = pad_state(spec, state, dims)
        sweep_b = make_batched_sweep(spec_b, None,
                                     tuple(0 for _ in range(spec_b.nr)))
        closed, closed_x64, err = _trace_pair(sweep_b, data_b, state_b,
                                              _k())
        programs.append(AuditProgram(
            name=f"batched_sweep@{mname}",
            path="hmsc_tpu/mcmc/multitenant.py",
            closed=closed, closed_x64=closed_x64, x64_error=err))

    # segment runner: traced jaxpr + lowering (donation aliasing lives in
    # the lowering, not the jaxpr)
    from ..mcmc import sampler as sampler_mod
    from ..mcmc import spatial as spatial_mod
    spec, data, state = built["base"]
    states = jax.tree.map(lambda x: jnp.stack([x, x]), state)
    keys = jax.vmap(
        lambda s: jax.random.key(s, impl="threefry2x32"))(jnp.arange(2))
    bad = jnp.full((2,), -1, jnp.int32)
    fn = sampler_mod._compiled_runner(
        spec, None, tuple(0 for _ in range(spec.nr)), 2, 1, 1, False, None,
        spatial_mod._NNGP_DENSE_MAX)
    runner_closed, runner_closed_x64, err = _trace_pair(fn, data, states,
                                                        keys, bad)
    programs.append(AuditProgram(
        name="segment_runner@base", path="hmsc_tpu/mcmc/sampler.py",
        closed=runner_closed, closed_x64=runner_closed_x64, x64_error=err))
    runner_text = fn.lower(data, states, keys, bad).as_text()
    n_carry = len(jax.tree_util.tree_leaves(states))

    # serving kernels (hmsc_tpu/serve/kernels.py): the prediction programs
    # the serving engine compiles and caches — audited exactly like the
    # updaters (f64-leak probe, host callbacks, baked constants, committed
    # structural fingerprints), so the query path cannot silently regress
    # its dtype policy or grow a Python re-entry
    from ..serve.kernels import audit_kernels
    for sname, sfn, sargs in audit_kernels():
        closed, closed_x64, err = _trace_pair(sfn, *sargs)
        programs.append(AuditProgram(
            name=sname, path="hmsc_tpu/serve/kernels.py",
            closed=closed, closed_x64=closed_x64, x64_error=err))

    # shape sweep: the sweep's shape-blind structure must not vary
    variants: dict[str, list] = {}
    for ny, ns in ((12, 4), (16, 5), (20, 6)):
        spec_i, data_i, state_i = _build(models["base"](ny=ny, ns=ns))
        sweep_i = make_sweep(spec_i, None,
                             tuple(0 for _ in range(spec_i.nr)))
        closed_i = jax.make_jaxpr(sweep_i)(data_i, state_i, _k())
        fp = fingerprint_jaxpr(closed_i, shape_blind=True)["sha256"]
        variants.setdefault(fp, []).append(f"ny={ny},ns={ns}")

    # sharded sweep, per canonical spec at ns=SHARD_AUDIT_NS over an
    # emulated SHARD_AUDIT_DEVICES-way species mesh: same f64 probe /
    # callback / const / fingerprint rules, with the collective sequence
    # (psum / all_gather eqn counts) captured by the fingerprint's
    # primitive profile.  Skipped (flagged, not failed) when the process
    # has fewer devices — CI pins XLA_FLAGS=--xla_force_host_platform_
    # device_count=8 so the tier-1 lint gate always audits them.
    sharded_skipped = len(jax.devices()) < SHARD_AUDIT_DEVICES
    if not sharded_skipped:
        import numpy as _np
        from jax.sharding import Mesh

        from ..mcmc.sweep import make_sharded_sweep
        mesh = Mesh(
            _np.array(jax.devices()[:SHARD_AUDIT_DEVICES]).reshape(
                1, SHARD_AUDIT_DEVICES),
            axis_names=("chains", "species"))
        for mname, fn in _shard_models().items():
            spec_s, data_s, state_s = _build(fn())
            sweep_s = make_sharded_sweep(
                spec_s, mesh, None, tuple(1 for _ in range(spec_s.nr)))
            closed, closed_x64, err = _trace_pair(sweep_s, data_s, state_s,
                                                  _k())
            programs.append(AuditProgram(
                name=f"sharded_sweep@{mname}@sp{SHARD_AUDIT_DEVICES}",
                path="hmsc_tpu/mcmc/partition.py",
                closed=closed, closed_x64=closed_x64, x64_error=err))

        # 2D (species x sites) sharded sweep: the same 8 emulated devices
        # reshaped to a (1, 4, 2) mesh, per site-capable canonical spec
        # (base + the three spatial methods) — the committed
        # `sharded_sweep@*@sp4x2` fingerprints record the 2D collective
        # sequence additively; the v1 `@sp8` entries above are untouched
        mesh2 = Mesh(
            _np.array(jax.devices()[:SHARD_AUDIT_DEVICES]).reshape(
                1, SITE_AUDIT_SP, SITE_AUDIT_ST),
            axis_names=("chains", "species", "sites"))
        for mname, fn in _site_shard_models().items():
            spec_s, data_s, state_s = _build(fn())
            sweep_s = make_sharded_sweep(
                spec_s, mesh2, None, tuple(1 for _ in range(spec_s.nr)))
            closed, closed_x64, err = _trace_pair(sweep_s, data_s, state_s,
                                                  _k())
            programs.append(AuditProgram(
                name=(f"sharded_sweep@{mname}"
                      f"@sp{SITE_AUDIT_SP}x{SITE_AUDIT_ST}"),
                path="hmsc_tpu/mcmc/partition.py",
                closed=closed, closed_x64=closed_x64, x64_error=err))

    return JaxprAudit(
        programs=programs, runner_text=runner_text,
        runner_n_carry_leaves=n_carry, sweep_shape_variants=variants,
        expected_fingerprints=expected_fingerprints,
        missing_updaters=missing, sharded_skipped=sharded_skipped)


def run_jaxpr_rules(audit: JaxprAudit):
    for info in RULES.values():
        if info.layer != "jaxpr":
            continue
        yield from info.checker(audit)


def current_fingerprints(audit: JaxprAudit) -> dict:
    return {p.name: fingerprint_jaxpr(p.closed) for p in audit.programs}


# ---------------------------------------------------------------------------
# the audit rules
# ---------------------------------------------------------------------------

def _all_vars(jaxpr):
    import jax.core as jcore
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for v in list(j.invars) + list(j.constvars):
            yield v
        for eqn in j.eqns:
            for v in eqn.outvars:
                yield v
            for v in eqn.invars:
                if isinstance(v, jcore.Literal):
                    yield v
            stack.extend(_sub_jaxprs(eqn))


def _all_prims(jaxpr):
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            yield eqn
            stack.extend(_sub_jaxprs(eqn))


@rule("jaxpr-f64", "error", "jaxpr",
      "dtype policy: no float64/complex128 in any traced program — every "
      "op derives its dtype from its inputs (audited under enable_x64, "
      "where an unpinned dtype surfaces instead of silently downcasting)")
def check_f64(audit: JaxprAudit):
    findings = []
    info = RULES["jaxpr-f64"]
    for p in audit.programs:
        if p.closed_x64 is None:
            findings.append(info.finding(
                p.path, 1,
                f"{p.name}: trace under enable_x64 failed — an op inside "
                f"does not derive its dtype from its inputs "
                f"({p.x64_error})"))
            continue
        bad: dict[str, int] = {}
        for v in _all_vars(p.closed_x64.jaxpr):
            dt = str(getattr(v.aval, "dtype", ""))
            # weak-typed f64 (a bare Python-float literal) never
            # materialises: it promotes to its operand's dtype
            if dt in ("float64", "complex128") \
                    and not getattr(v.aval, "weak_type", False):
                bad[dt] = bad.get(dt, 0) + 1
        if bad:
            findings.append(info.finding(
                p.path, 1,
                f"{p.name}: {sum(bad.values())} {'/'.join(sorted(bad))} "
                f"values in the x64 trace — some op does not derive its "
                f"dtype from its inputs"))
    return findings


@rule("jaxpr-mixed-precision", "error", "jaxpr",
      "deliberate bf16 only: reduced-precision values appear ONLY in the "
      "policy'd `sweep_mp@*` programs (a bf16 value in any other mcmc "
      "program is a precision leak), and no Cholesky/triangular-solve "
      "pivot ever takes a bf16 operand — the policy computes grams in "
      "bf16 but factorises f32")
def check_mixed_precision(audit: JaxprAudit):
    findings = []
    info = RULES["jaxpr-mixed-precision"]
    for p in audit.programs:
        is_mp = "_mp@" in p.name
        in_mcmc = p.path.startswith("hmsc_tpu/mcmc")
        n_bf16 = 0
        for v in _all_vars(p.closed.jaxpr):
            if str(getattr(v.aval, "dtype", "")) == "bfloat16":
                n_bf16 += 1
        if n_bf16 and in_mcmc and not is_mp:
            findings.append(info.finding(
                p.path, 1,
                f"{p.name}: {n_bf16} bfloat16 value(s) in a program with "
                f"no active precision policy — reduced precision must be "
                f"scoped to the policy'd blocks"))
        for eqn in _all_prims(p.closed.jaxpr):
            if eqn.primitive.name not in ("cholesky", "triangular_solve"):
                continue
            bad = [str(v.aval.dtype) for v in eqn.invars
                   if str(getattr(v.aval, "dtype", "")) == "bfloat16"]
            if bad:
                findings.append(info.finding(
                    p.path, 1,
                    f"{p.name}: `{eqn.primitive.name}` takes a bfloat16 "
                    f"operand — pivots are f32-pinned under every policy"))
    return findings


@rule("jaxpr-host-callback", "error", "jaxpr",
      "the hot loop never re-enters Python: no pure_callback/io_callback/"
      "debug_callback primitives in the sweep or segment runner")
def check_host_callback(audit: JaxprAudit):
    findings = []
    info = RULES["jaxpr-host-callback"]
    for p in audit.programs:
        hits: dict[str, int] = {}
        for eqn in _all_prims(p.closed.jaxpr):
            if eqn.primitive.name in _CALLBACK_PRIMS:
                hits[eqn.primitive.name] = hits.get(eqn.primitive.name,
                                                    0) + 1
        for prim, n in sorted(hits.items()):
            findings.append(info.finding(
                p.path, 1, f"{p.name}: {n}x `{prim}` primitive in the "
                           f"traced program"))
    return findings


@rule("jaxpr-large-const", "error", "jaxpr",
      "model data rides in as arguments: no constant larger than "
      f"{LARGE_CONST_BYTES // 1024} KiB baked into a traced program "
      "(per-executable HBM bloat)")
def check_large_const(audit: JaxprAudit):
    findings = []
    info = RULES["jaxpr-large-const"]
    for p in audit.programs:
        for c in p.closed.consts:
            nbytes = int(getattr(c, "nbytes", 0))
            if nbytes > LARGE_CONST_BYTES:
                shape = getattr(c, "shape", ())
                findings.append(info.finding(
                    p.path, 1,
                    f"{p.name}: baked-in constant of {nbytes} bytes "
                    f"(shape {tuple(shape)}) — pass it as an argument"))
    return findings


@rule("jaxpr-donation", "error", "jaxpr",
      "the segment runner's carry donation is actually established in the "
      "lowering (one carry copy in HBM, not two)")
def check_donation(audit: JaxprAudit):
    info = RULES["jaxpr-donation"]
    # + 2: the key array and the divergence tracker are donated alongside
    # the state pytree (sampler._compiled_runner donate_argnums=(1, 2, 3))
    want = audit.runner_n_carry_leaves + 2
    got = audit.runner_text.count("tf.aliasing_output")
    if got < want:
        return [info.finding(
            "hmsc_tpu/mcmc/sampler.py", 1,
            f"segment runner lowering establishes only {got} input→output "
            f"aliases; expected ≥ {want} (state leaves + keys + "
            f"divergence tracker)")]
    return []


@rule("jaxpr-recompile", "error", "jaxpr",
      "bounded shape specialisation: the sweep's shape-blind structure is "
      "identical across a shape sweep (structure varying with dims means "
      "one recompile per shape in production)")
def check_recompile(audit: JaxprAudit):
    info = RULES["jaxpr-recompile"]
    if len(audit.sweep_shape_variants) <= 1:
        return []
    desc = "; ".join(f"{fp[:8]}…: {sizes}" for fp, sizes
                     in sorted(audit.sweep_shape_variants.items()))
    return [info.finding(
        "hmsc_tpu/mcmc/sweep.py", 1,
        f"{len(audit.sweep_shape_variants)} distinct shape-blind sweep "
        f"structures across the shape sweep ({desc})")]


@rule("jaxpr-registry-coverage", "error", "jaxpr",
      "every registered updater is exercised by at least one canonical "
      "audit spec")
def check_coverage(audit: JaxprAudit):
    info = RULES["jaxpr-registry-coverage"]
    return [info.finding(
        "hmsc_tpu/mcmc/registry.py", 1,
        f"updater `{name}` has no applicable canonical spec — extend "
        f"_canonical_models() so the audit covers it")
        for name in audit.missing_updaters]


@rule("jaxpr-fingerprint", "error", "jaxpr",
      "each audited program's structural fingerprint matches the committed "
      "fingerprints.json (changes to the compiled surface are review-"
      "visible; regenerate with --update-fingerprints)")
def check_fingerprint(audit: JaxprAudit):
    findings = []
    info = RULES["jaxpr-fingerprint"]
    expected = audit.expected_fingerprints
    if expected is None:
        return [info.finding(
            "hmsc_tpu/analysis/fingerprints.json", 1,
            "fingerprints.json missing or unreadable — run "
            "`python -m hmsc_tpu lint --update-fingerprints`")]
    current = current_fingerprints(audit)
    for name, fp in sorted(current.items()):
        exp = expected.get(name)
        if exp is None:
            findings.append(info.finding(
                "hmsc_tpu/analysis/fingerprints.json", 1,
                f"{name}: no committed fingerprint — run "
                f"--update-fingerprints"))
        elif exp.get("sha256") != fp["sha256"]:
            findings.append(info.finding(
                "hmsc_tpu/analysis/fingerprints.json", 1,
                f"{name}: traced structure changed "
                f"({exp.get('sha256')} → {fp['sha256']}, "
                f"{exp.get('n_eqns')} → {fp['n_eqns']} eqns) — review, "
                f"then --update-fingerprints"))
    for name in sorted(set(expected) - set(current)):
        if audit.sharded_skipped and name.startswith("sharded_sweep@"):
            continue              # no mesh this run (devices < 8), not stale
        findings.append(info.finding(
            "hmsc_tpu/analysis/fingerprints.json", 1,
            f"{name}: committed fingerprint has no audited program "
            f"(stale entry) — run --update-fingerprints"))
    return findings
