"""Analysis driver: walks the package, runs both layers, applies
suppressions + baseline, and shapes the result for the CLI/tests/bench."""

from __future__ import annotations

import os

from . import ast_rules  # noqa: F401 — registers the layer-1 rules
from . import jaxpr_rules  # noqa: F401 — registers the layer-2 rules
from .findings import (RULES, Baseline, Finding, is_suppressed,
                       load_baseline, parse_suppressions)

__all__ = ["package_root", "repo_root", "iter_module_contexts",
           "run_analysis", "findings_to_json", "analysis_summary",
           "BASELINE_PATH"]

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return os.path.dirname(package_root())


def iter_module_contexts(root: str | None = None):
    """Parse every library module under ``hmsc_tpu/`` (repo-relative
    paths, deterministic order)."""
    root = root or package_root()
    base = os.path.dirname(root)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, base).replace(os.sep, "/")
            with open(path) as f:
                source = f.read()
            yield ast_rules.ModuleContext.parse(rel, source), path


def run_analysis(root: str | None = None,
                 layers: tuple = ("ast", "jaxpr"),
                 baseline: Baseline | None = None,
                 expected_fingerprints: dict | str | None = "auto",
                 audit=None) -> dict:
    """Run the suite.  Returns::

        {"findings": [Finding...],      # active (not suppressed/baselined)
         "errors": int, "warnings": int,
         "suppressed": int, "baselined": int,
         "all_findings": [...],         # pre-filter, for --update-baseline
         "audit": JaxprAudit | None}
    """
    if baseline is None:
        baseline = load_baseline(BASELINE_PATH)

    raw: list[Finding] = []
    suppressed = 0

    if "ast" in layers:
        for ctx, _path in iter_module_contexts(root):
            sup = parse_suppressions(ctx.source)
            for f in ast_rules.run_ast_rules(ctx):
                if is_suppressed(f, sup):
                    suppressed += 1
                else:
                    raw.append(f)

    if "jaxpr" not in layers:
        audit = None
    elif audit is None:              # a prebuilt audit skips the retrace
        exp = expected_fingerprints
        if exp == "auto":
            exp = jaxpr_rules.load_fingerprints()
        elif isinstance(exp, str):
            exp = jaxpr_rules.load_fingerprints(exp)
        audit = jaxpr_rules.build_audit_context(expected_fingerprints=exp)
    if audit is not None:
        raw.extend(jaxpr_rules.run_jaxpr_rules(audit))

    active, baselined = [], 0
    for f in raw:
        if baseline.known(f):
            baselined += 1
        else:
            active.append(f)
    active.sort(key=lambda f: (f.path, f.line, f.rule))
    return {
        "findings": active,
        "errors": sum(1 for f in active if f.severity == "error"),
        "warnings": sum(1 for f in active if f.severity == "warning"),
        "suppressed": suppressed,
        "baselined": baselined,
        "all_findings": raw,
        "audit": audit,
    }


def findings_to_json(result: dict) -> dict:
    """The ``--json`` output schema (version-stamped; tests pin it)."""
    per_rule: dict[str, int] = {}
    for f in result["findings"]:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    return {
        "version": 1,
        "errors": result["errors"],
        "warnings": result["warnings"],
        "suppressed": result["suppressed"],
        "baselined": result["baselined"],
        "findings": [f.to_json() for f in result["findings"]],
        "rules": {rid: {"severity": info.severity, "layer": info.layer,
                        "protects": info.protects,
                        "count": per_rule.get(rid, 0)}
                  for rid, info in sorted(RULES.items())},
    }


def analysis_summary(layers: tuple = ("ast", "jaxpr")) -> dict:
    """Small digest for bench records: finding counts only."""
    r = run_analysis(layers=layers)
    return {"errors": r["errors"], "warnings": r["warnings"],
            "suppressed": r["suppressed"], "baselined": r["baselined"]}
