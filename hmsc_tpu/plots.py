"""Visualization layer (L6, reference ``R/plotBeta.R:59-264``,
``R/plotGamma.R:50-180``, ``R/plotGradient.R:63-210``,
``R/plotVariancePartitioning.R:21-41``, ``R/biPlot.R:26-59``).

Matplotlib-level presentation over the L4/L5 outputs; pure host-side.  Each
function returns the matplotlib ``Axes`` so callers can restyle or save.
``plot_beta``/``plot_gamma`` support the reference's three display modes:
posterior mean, support (P(>0)), and sign-thresholded mean.
"""

from __future__ import annotations

import numpy as np

__all__ = ["plot_beta", "plot_gamma", "plot_gradient",
           "plot_variance_partitioning", "bi_plot"]


def _ax(ax):
    if ax is not None:
        return ax
    import matplotlib.pyplot as plt
    _, ax = plt.subplots()
    return ax


def _support_plot(est, row_names, col_names, plot_type, support_level, ax,
                  title):
    ax = _ax(ax)
    mean = est["mean"]
    if plot_type == "Mean":
        M = mean
    elif plot_type == "Support":
        M = np.where(est["support"] > support_level, est["support"],
                     np.where(est["supportNeg"] > support_level,
                              -est["supportNeg"], 0.0))
    elif plot_type == "Sign":
        sig = (est["support"] > support_level) | (est["supportNeg"] > support_level)
        M = np.where(sig, np.sign(mean), 0.0)
    else:
        raise ValueError("plotType must be 'Mean', 'Support' or 'Sign'")
    vmax = np.max(np.abs(M)) or 1.0
    im = ax.imshow(M, cmap="RdBu_r", vmin=-vmax, vmax=vmax, aspect="auto")
    ax.set_xticks(range(len(col_names)))
    ax.set_xticklabels(col_names, rotation=90, fontsize=7)
    ax.set_yticks(range(len(row_names)))
    ax.set_yticklabels(row_names, fontsize=7)
    ax.set_title(title)
    ax.figure.colorbar(im, ax=ax, shrink=0.8)
    return ax


def plot_beta(post, plot_type: str = "Support", support_level: float = 0.89,
              ax=None):
    """Heatmap of species' environmental responses Beta (covariates x
    species), reference ``plotBeta.R`` (the optional phylo-tree side panel is
    not drawn)."""
    hM = post.hM
    est = post.get_post_estimate("Beta")
    return _support_plot(est, hM.cov_names, hM.sp_names, plot_type,
                         support_level, ax, "Beta")


def plot_gamma(post, plot_type: str = "Support", support_level: float = 0.89,
               ax=None):
    """Heatmap of trait effects Gamma (covariates x traits), reference
    ``plotGamma.R``."""
    hM = post.hM
    est = post.get_post_estimate("Gamma")
    return _support_plot(est, hM.cov_names, hM.tr_names, plot_type,
                         support_level, ax, "Gamma")


def plot_gradient(post, gradient, pred=None, measure: str = "S", index: int = 0,
                  q=(0.25, 0.5, 0.75), show_data: bool = True, ax=None,
                  seed: int = 0):
    """Prediction along an environmental gradient with credible ribbons
    (reference ``plotGradient.R``): ``measure``='S' species richness, 'Y'
    one species (``index``), 'T' community-weighted mean trait (``index``)."""
    from .predict import predict as _predict

    hM = post.hM
    if pred is None:
        pred = _predict(post, gradient=gradient, expected=True, seed=seed)
    xx = np.asarray(gradient["XDataNew"].iloc[:, 0], dtype=float)
    if measure == "S":
        stat = pred.sum(axis=2)                      # (n, ngrid)
        label = "Summed response (richness)"
    elif measure == "Y":
        stat = pred[:, :, index]
        label = f"{hM.sp_names[index]}"
    elif measure == "T":
        tw = pred @ hM.Tr[:, index]
        stat = tw / np.maximum(pred.sum(axis=2), 1e-12)
        label = f"CWM {hM.tr_names[index]}"
    else:
        raise ValueError("measure must be 'S', 'Y' or 'T'")
    lo, med, hi = np.quantile(stat, q, axis=0)
    ax = _ax(ax)
    ax.fill_between(xx, lo, hi, alpha=0.3, color="#4477aa", lw=0)
    ax.plot(xx, med, color="#4477aa")
    ax.set_xlabel(str(gradient["XDataNew"].columns[0]))
    ax.set_ylabel(label)
    if show_data and measure == "S" and hM.x_data is not None:
        try:
            v = np.asarray(hM.x_data[gradient["XDataNew"].columns[0]], float)
            ax.plot(v, np.nansum(hM.Y, axis=1), ".", color="#666666",
                    markersize=3)
        except Exception:
            pass
    return ax


def plot_variance_partitioning(post, vp=None, ax=None, cmap: str = "tab20"):
    """Stacked per-species bars of the variance shares (reference
    ``plotVariancePartitioning.R``)."""
    from .post.metrics import compute_variance_partitioning

    hM = post.hM
    if vp is None:
        vp = compute_variance_partitioning(post)
    vals = vp["vals"]
    ax = _ax(ax)
    import matplotlib.pyplot as plt

    colors = plt.get_cmap(cmap)(np.linspace(0, 1, vals.shape[0]))
    bottom = np.zeros(vals.shape[1])
    xs = np.arange(vals.shape[1])
    means = vals.mean(axis=1)
    for i in range(vals.shape[0]):
        ax.bar(xs, vals[i], bottom=bottom, color=colors[i],
               label=f"{vp['names'][i]} (mean = {means[i]:.2f})")
        bottom += vals[i]
    ax.set_xticks(xs)
    ax.set_xticklabels(hM.sp_names, rotation=90, fontsize=7)
    ax.set_ylabel("Variance proportion")
    ax.legend(fontsize=6, loc="upper right")
    return ax


def bi_plot(post, r: int = 0, factors=(0, 1), color_var=None, ax=None):
    """Ordination of sites (posterior-mean Eta) against species loadings
    (posterior-mean Lambda) for one random level (reference ``biPlot.R``)."""
    hM = post.hM
    eta = post.get_post_estimate("Eta", r=r)["mean"]       # (np, nf)
    lam = post.get_post_estimate("Lambda", r=r)["mean"]    # (nf, ns[, ncr])
    lam = lam[..., 0] if lam.ndim == 3 else lam
    f1, f2 = factors
    ax = _ax(ax)
    c = None
    if color_var is not None and hM.x_data is not None:
        v = np.asarray(hM.x_data[color_var], dtype=float)
        if len(v) == eta.shape[0]:           # one row per unit already
            c = v
        elif len(v) == hM.ny:                # map rows -> first row per unit
            first_row = np.zeros(eta.shape[0], dtype=int)
            first_row[hM.Pi[::-1, r]] = np.arange(hM.ny - 1, -1, -1)
            c = v[first_row]
    kw = {"c": c, "cmap": "viridis"} if c is not None else {}
    ax.scatter(eta[:, f1], eta[:, f2], s=12, label="sites", **kw)
    scale = (np.abs(eta[:, [f1, f2]]).max() /
             max(np.abs(lam[[f1, f2]]).max(), 1e-12))
    for j in range(hM.ns):
        ax.annotate(hM.sp_names[j], (lam[f1, j] * scale, lam[f2, j] * scale),
                    color="#bb3333", fontsize=8)
    ax.set_xlabel(f"Latent factor {f1 + 1}")
    ax.set_ylabel(f"Latent factor {f2 + 1}")
    return ax
