"""Dense linear-algebra helpers for the Gibbs engine.

All solvers are batched-friendly (leading batch axes via vmap) and keep
everything on the MXU: cholesky + triangular solves, no explicit inverses
(the reference's ``chol2inv``/``backsolve`` pattern, e.g.
``R/updateBetaLambda.R:100-103``, maps to ``cho_solve``)."""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import cho_solve, solve_triangular

__all__ = ["chol_spd", "solve_from_chol", "sample_mvn_prec"]

# Relative jitter added to diagonals before cholesky; f32 MCMC insurance
# (design choice documented in SURVEY.md §7 point 6).
_JITTER = 1e-6


def chol_spd(A: jnp.ndarray, jitter: float = _JITTER) -> jnp.ndarray:
    """Cholesky of a symmetric PD matrix with relative diagonal jitter."""
    n = A.shape[-1]
    scale = jnp.mean(jnp.diagonal(A, axis1=-2, axis2=-1), axis=-1)
    eye = jnp.eye(n, dtype=A.dtype)
    A = A + (jitter * scale)[..., None, None] * eye
    return jnp.linalg.cholesky(A)


def solve_from_chol(L: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Solve A x = b given L = chol(A) (lower)."""
    return cho_solve((L, True), b)


def sample_mvn_prec(L: jnp.ndarray, rhs: jnp.ndarray, eps: jnp.ndarray) -> jnp.ndarray:
    """Draw from N(P^{-1} rhs, P^{-1}) given L = chol(P) and eps ~ N(0, I).

    mean = P^{-1} rhs; noise = L^{-T} eps  (cov L^{-T} L^{-1} = P^{-1}).
    """
    mean = cho_solve((L, True), rhs)
    noise = solve_triangular(jnp.swapaxes(L, -1, -2), eps, lower=False)
    return mean + noise
