"""Dense linear-algebra helpers for the Gibbs engine.

All solvers are batched-friendly (leading batch axes via vmap) and keep
everything on the MXU: cholesky + triangular solves, no explicit inverses
(the reference's ``chol2inv``/``backsolve`` pattern, e.g.
``R/updateBetaLambda.R:100-103``, maps to ``cho_solve``)."""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import cho_solve, solve_triangular

from . import mixed as _mx

__all__ = ["chol_spd", "solve_from_chol", "sample_mvn_prec",
           "sample_mvn_prec_batched"]

# Relative jitter added to diagonals before cholesky; f32 MCMC insurance
# (design choice documented in SURVEY.md §7 point 6).
_JITTER = 1e-6


def chol_spd(A: jnp.ndarray, jitter: float = _JITTER) -> jnp.ndarray:
    """Cholesky of a symmetric PD matrix with relative diagonal jitter."""
    n = A.shape[-1]
    scale = jnp.mean(jnp.diagonal(A, axis1=-2, axis2=-1), axis=-1)
    eye = jnp.eye(n, dtype=A.dtype)
    A = A + (jitter * scale)[..., None, None] * eye
    return jnp.linalg.cholesky(A)


def solve_from_chol(L: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Solve A x = b given L = chol(A) (lower)."""
    return cho_solve((L, True), b)


def sample_mvn_prec(L: jnp.ndarray, rhs: jnp.ndarray, eps: jnp.ndarray) -> jnp.ndarray:
    """Draw from N(P^{-1} rhs, P^{-1}) given L = chol(P) and eps ~ N(0, I).

    mean = P^{-1} rhs; noise = L^{-T} eps  (cov L^{-T} L^{-1} = P^{-1}).

    Under an active precision-policy scope with batched layouts
    (:func:`hmsc_tpu.ops.mixed.layouts_active`) the mean and noise fold
    into ONE forward/back solve pair — ``x = L^{-T}(L^{-1} rhs + eps)`` —
    instead of the historical three triangular solves (cho_solve's two
    plus the separate noise solve): same distribution exactly, one fewer
    pass over ``L``.  The solves themselves always run in the operands'
    own (f32) dtype — the policy's bf16 compute never reaches a pivot."""
    if _mx.layouts_active():
        y = solve_triangular(L, rhs, lower=True)
        return solve_triangular(jnp.swapaxes(L, -1, -2), y + eps,
                                lower=False)
    mean = cho_solve((L, True), rhs)
    noise = solve_triangular(jnp.swapaxes(L, -1, -2), eps, lower=False)
    return mean + noise


# Above this matrix size the unrolled code (~P^3/6 vector ops) stops paying
# for itself and the generic batched LAPACK-style path takes over.
_SMALL_P_MAX = 16


def sample_mvn_prec_batched(prec: jnp.ndarray, rhs: jnp.ndarray,
                            eps: jnp.ndarray,
                            jitter: float = _JITTER) -> jnp.ndarray:
    """Fused chol + N(P^{-1} rhs, P^{-1}) draw for a batch of small SPD
    precisions — the Gibbs sweep's hottest linear algebra (per-species
    (nc+K)^2 systems in updateBetaLambda, per-unit nf^2 systems in updateEta;
    reference R/updateBetaLambda.R:76-122, R/updateEta.R:44-92).

    For P <= ``_SMALL_P_MAX`` the factorisation is fully unrolled over the
    static P with the batch as the vector dimension: XLA's batched
    ``cholesky`` keeps the (P, P) minor dims in lane/sublane position, so a
    10x10 factorisation uses 10 of 128 lanes and serialises sublane steps —
    measured ~20x slower than this formulation at (4000, 10, 10) on TPU v5e.
    Semantics (incl. the relative diagonal jitter and NaN propagation on
    indefinite input — relied on by divergence containment) match
    ``chol_spd`` + ``sample_mvn_prec``.
    """
    P = prec.shape[-1]
    if P > _SMALL_P_MAX:
        return sample_mvn_prec(chol_spd(prec, jitter), rhs, eps)

    A = [[prec[..., i, j] for j in range(P)] for i in range(P)]
    scale = A[0][0]
    for j in range(1, P):
        scale = scale + A[j][j]
    bump = (jitter / P) * scale
    L = [[None] * P for _ in range(P)]
    inv = [None] * P
    for j in range(P):
        s = A[j][j] + bump
        for k in range(j):
            s = s - L[j][k] * L[j][k]
        d = jnp.sqrt(s)                       # NaN if indefinite, like chol
        inv[j] = 1.0 / d
        L[j][j] = d
        for i in range(j + 1, P):
            s2 = A[i][j]
            for k in range(j):
                s2 = s2 - L[i][k] * L[j][k]
            L[i][j] = s2 * inv[j]
    # forward solve L y = rhs, then back solve L' x = y + eps
    y = [None] * P
    for i in range(P):
        s = rhs[..., i]
        for k in range(i):
            s = s - L[i][k] * y[k]
        y[i] = s * inv[i]
    x = [None] * P
    for i in reversed(range(P)):
        s = y[i] + eps[..., i]
        for k in range(i + 1, P):
            s = s - L[k][i] * x[k]
        x[i] = s * inv[i]
    return jnp.stack(x, axis=-1)
