"""Trace-time mixed-precision context for the Gibbs sweep's hot ops.

The per-block precision policy (:mod:`hmsc_tpu.mcmc.precision`) decides
*which* schedule blocks compute their heavy dots and grams in reduced
precision; this module is the *mechanism*: a trace-time scope the sweep
assembler enters around a policy'd block, plus drop-in ``matmul`` /
``einsum`` wrappers the updaters route their large contractions through.

Contract (the whole point of the design):

- **No active scope -> byte-identical traces.**  Outside a scope every
  wrapper is *literally* the plain ``jnp`` call — same primitive, same
  params — so the default ``precision_policy=None`` path produces the
  exact jaxprs the committed fingerprints pin.  The analysis layer
  verifies this, not just asserts it.
- **bf16 compute, f32 accumulate.**  Inside a scope, float operands are
  cast to the scope's compute dtype and every dot/einsum carries
  ``preferred_element_type=float32``, so accumulation and all outputs
  stay f32.  Reductions outside these wrappers, Cholesky factorisations
  and triangular solves are *never* routed through this module — their
  pivots stay f32-pinned (audited by the ``jaxpr-mixed-precision``
  rule).
- **Staged operands.**  Sweep-invariant model-data arrays (the phylo
  eigenbasis ``U``, the spatial ``iWg``/Vecchia grids, the design
  ``X``...) dominate the bytes of the hot blocks, and casting them
  inside the sweep would *add* traffic every sweep (XLA does not hoist
  converts out of the scan — measured).  The policy therefore stages
  bf16 shadow copies once per run, passed to the runner as a real
  argument; :func:`staged` resolves a name to the shadow inside a
  scope and falls back to the f32 array outside one (or when the
  policy does not stage that name).  The f32 originals stay intact for
  every non-policy'd block.

The scopes are plain Python stacks manipulated at *trace* time (the
sweep assembles blocks in Python), never inside traced control flow.
"""

from __future__ import annotations

import contextlib
import dataclasses

import jax.numpy as jnp

__all__ = ["scope", "staged_scope", "active_dtype", "layouts_active",
           "staged", "staged_level", "matmul", "einsum"]


@dataclasses.dataclass(frozen=True)
class _Scope:
    dtype: object          # jnp dtype for compute casts; None = pass-through
    layouts: bool          # fused batched cholesky/solve layouts active


_SCOPES: list[_Scope] = []
_STAGED: list[dict] = []   # name -> pre-cast shadow array (trace-time values)


@contextlib.contextmanager
def scope(dtype, layouts: bool = True):
    """Enter a mixed-precision compute scope for one schedule block.

    ``dtype`` is a dtype-like (``"bfloat16"``) or ``None``/``"float32"``
    for a layout-only scope (fused solves, full-precision compute)."""
    dt = None
    if dtype is not None:
        dt = jnp.dtype(dtype)
        if dt == jnp.float32:
            dt = None             # layout-only: keep the exact f32 ops
    _SCOPES.append(_Scope(dtype=dt, layouts=bool(layouts)))
    try:
        yield
    finally:
        _SCOPES.pop()


@contextlib.contextmanager
def staged_scope(staged: dict | None):
    """Provide the staged shadow table for the duration of a sweep trace
    (entered once around the whole block chain; per-block :func:`scope`
    entries decide whether lookups resolve)."""
    _STAGED.append(staged or {})
    try:
        yield
    finally:
        _STAGED.pop()


def active_dtype():
    """The current compute dtype, or ``None`` outside any scope (or in a
    layout-only scope)."""
    return _SCOPES[-1].dtype if _SCOPES else None


def layouts_active() -> bool:
    return bool(_SCOPES) and _SCOPES[-1].layouts


def staged(name: str, x):
    """The policy's pre-cast shadow of model-data array ``name`` inside an
    active compute scope; ``x`` itself otherwise."""
    if _SCOPES and _SCOPES[-1].dtype is not None and _STAGED:
        shadow = _STAGED[-1].get(name)
        if shadow is not None:
            return shadow
    return x


def staged_level(name: str, r: int, x):
    """Per-level variant: level arrays stage under ``"<name>_<r>"``."""
    return staged(f"{name}_{r}", x)


def _cast(x, dt):
    if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) \
            and x.dtype != dt:
        return x.astype(dt)
    return x


def matmul(a, b):
    """``a @ b``; bf16 compute / f32 accumulate inside an active scope."""
    dt = active_dtype()
    if dt is None:
        return a @ b
    return jnp.matmul(_cast(a, dt), _cast(b, dt),
                      preferred_element_type=jnp.float32)


def einsum(eq: str, *operands):
    """``jnp.einsum``; bf16 compute / f32 accumulate inside an active
    scope."""
    dt = active_dtype()
    if dt is None:
        return jnp.einsum(eq, *operands)
    return jnp.einsum(eq, *(_cast(o, dt) for o in operands),
                      preferred_element_type=jnp.float32)
