"""Random-draw primitives the reference imports from native CRAN packages,
re-built as whole-array JAX ops (reference's ``truncnorm::rtruncnorm``,
``BayesLogit::rpg``, ``MCMCpack::rwish`` -> SURVEY.md §2.4).

Everything here is elementwise / batched and jit-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtr, ndtri

__all__ = ["truncated_normal", "truncated_normal_onesided", "standard_gamma",
           "polya_gamma", "wishart", "mvn_from_prec_chol",
           "categorical_logits"]

_TINY = 1e-38  # smallest safe f32 normal-ish
# f32 ndtri overflows to -inf below ~1e-33 (ndtri(1e-38) = -inf while
# ndtri(1e-30) = -11.46); quantile-space probabilities are floored here and
# the final clip to [a, b] bounds the draw
_P_FLOOR = 1e-30


def truncated_normal(key, lower, upper, mean=0.0, std=1.0, *, _u=None):
    """Truncated normal draw on [lower, upper], elementwise over the broadcast
    shape.  Replaces the per-cell ``rtruncnorm`` loop flagged as "often the
    bottleneck" (reference ``R/updateZ.R:59``) with one fused array op.

    Numerics: inverse-CDF in the *survival* parameterisation whenever the
    interval sits in the right tail, so one-sided probit truncations stay
    accurate far into the tail in f32 (the naive CDF form saturates at ~5
    sigma).  Beyond ~9 sigma even the survival probability underflows f32;
    there the exact asymptotic draw (X | X > t) = t + Exp(1)/t + O(t^-3)
    (Robert 1995) takes over, so the op is finite at any truncation.
    """
    shape = jnp.broadcast_shapes(jnp.shape(lower), jnp.shape(upper),
                                 jnp.shape(mean), jnp.shape(std))
    a = (jnp.broadcast_to(lower, shape) - mean) / std
    b = (jnp.broadcast_to(upper, shape) - mean) / std
    # _u: test hook to inject the uniform draw (the s==1.0 rounding overflow
    # below is backend-dependent — TPU's non-FMA schedule hits it, CPU's FMA
    # does not — so the regression test injects the adversarial u directly)
    u = (jax.random.uniform(key, shape, dtype=a.dtype, minval=_TINY,
                            maxval=1.0)
         if _u is None else jnp.broadcast_to(_u, shape))

    # right-tail intervals: work with survival probs S(x) = Phi(-x)
    right = (a + jnp.clip(b, -1e30, 1e30)) > 0
    right = jnp.where(jnp.isinf(b), a > 0, right)
    right = jnp.where(jnp.isinf(a), b > 0, right)

    # left-oriented intervals reflect into the right parameterisation
    # (X in [a,b] = -X' with X' in [-b,-a]), so only one ndtri and two ndtr
    # evaluations are needed per cell — this op is ~70% of a probit sweep
    a2 = jnp.where(right, a, -b)
    b2 = jnp.where(right, b, -a)

    sa, sb = ndtr(-a2), ndtr(-b2)         # P(X > a2) >= P(X > b2)
    s = sb + u * (sa - sb)
    # cap s strictly below 1: when the interval is unbounded on the reflected
    # left (sa == 1), u near 1 rounds s to exactly 1.0 in f32 and ndtri(1) is
    # +-inf — one such cell per ~1.7e7 draws, enough to poison a chain at the
    # 1000x1000 bench scale.  1 - epsneg is the largest float below 1; the
    # draw saturates at ~5.4 sigma into the unbounded side (f32), which is
    # the inverse-CDF resolution there anyway.
    s_ceil = 1.0 - jnp.finfo(s.dtype).epsneg
    x_r = -ndtri(jnp.clip(s, _P_FLOOR, s_ceil))

    # far-tail fallback: past ~9 sigma the interval probability underflows
    # f32 and ndtri saturates; the exponential asymptotic (Robert 1995) is
    # exact there, truncated to [a2, b2] so two-sided far intervals stay
    # continuous (no point mass at the clipped bound).
    FAR = 9.0
    span = jnp.clip(b2 - a2, 0.0, jnp.inf)
    lam_r = jnp.maximum(a2, 1.0)
    x_far = a2 - jnp.log1p(-u * (1.0 - jnp.exp(-lam_r * span))) / lam_r
    x = jnp.where(a2 > FAR, x_far, x_r)
    x = jnp.clip(x, a2, b2)                # guard the clipped-quantile edges
    x = jnp.where(right, x, -x)
    return mean + std * x


def truncated_normal_onesided(key, bound, is_lower, mean=0.0, std=1.0, *,
                              _u=None):
    """One-sided truncated normal: X > bound where ``is_lower`` is true,
    X < bound where false, elementwise.

    The probit Z augmentation (reference ``R/updateZ.R:43-63``) only ever
    truncates on one side (Y=1 -> Z > 0, Y=0 -> Z < 0), and for a one-sided
    interval one of the two survival probabilities in the general
    :func:`truncated_normal` is exactly 0 — but its ``ndtr`` is still
    evaluated over the whole array.  This op drops it: 1 ndtr + 1 ndtri per
    cell instead of 2 + 1, with the same survival-parameterisation accuracy
    and the same Robert (1995) exponential far-tail fallback.  On the
    1000x1000 probit bench the Z update is ~2/3 of the sweep, so the saved
    transcendental is a real win.
    """
    shape = jnp.broadcast_shapes(jnp.shape(bound), jnp.shape(is_lower),
                                 jnp.shape(mean), jnp.shape(std))
    is_lower = jnp.broadcast_to(is_lower, shape)
    # reflect upper-bounded cells into the right-tail parameterisation:
    # X < b  <=>  -X > -b, with X standardized to W = (X - mean)/std
    t = (jnp.broadcast_to(bound, shape) - mean) / std
    t = jnp.where(is_lower, t, -t)
    u = (jax.random.uniform(key, shape, dtype=t.dtype, minval=_TINY,
                            maxval=1.0)
         if _u is None else jnp.broadcast_to(_u, shape))

    sa = ndtr(-t)                          # P(W > t)
    s = u * sa
    # same f32 rounding guards as truncated_normal: s can round to 1.0 when
    # sa == 1 and u ~ 1 (ndtri(1) = inf), and underflows past ~9 sigma
    s_ceil = 1.0 - jnp.finfo(s.dtype).epsneg
    x_r = -ndtri(jnp.clip(s, _P_FLOOR, s_ceil))
    lam = jnp.maximum(t, 1.0)
    x_far = t - jnp.log1p(-u) / lam        # (X | X > t) ~ t + Exp(lam)/1
    x = jnp.where(t > 9.0, x_far, x_r)
    x = jnp.maximum(x, t)                  # guard the clipped-quantile edge
    x = jnp.where(is_lower, x, -x)
    return mean + std * x


def standard_gamma(key, a, shape=None, n_rounds: int = 8):
    """Standard Gamma(a, 1) draw, TPU-native.

    ``jax.random.gamma`` lowers to a per-element rejection ``while_loop`` over
    per-element split keys; on TPU that is ~35x slower than a same-shape
    normal draw and was 94% of the whole Gibbs sweep at the 1000-species
    bench scale.  This sampler vectorises Marsaglia-Tsang (2000) rejection
    instead: ``n_rounds`` candidate batches are drawn up front as fused
    whole-array normal/uniform ops and the first accepted candidate is
    selected per element — no per-element keys, no data-dependent loop.

    Exact on acceptance; the probability that all ``n_rounds`` candidates are
    rejected is <= 0.05^n_rounds (~4e-11 at the default), in which case the
    draw falls back to the distribution mode — far below Monte-Carlo
    resolution.  Shapes a < 1 use the boost ``Ga(a) = Ga(a+1) * U^(1/a)``.
    """
    a = jnp.asarray(a)
    if shape is None:
        shape = a.shape
    dtype = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) \
        else jnp.result_type(a.dtype, jnp.float32)
    a = jnp.broadcast_to(a, shape).astype(dtype)

    boost = a < 1.0
    a_eff = jnp.where(boost, a + 1.0, jnp.maximum(a, 1.0))
    d = a_eff - 1.0 / 3.0
    c = 1.0 / jnp.sqrt(9.0 * d)

    kx, ku, kb = jax.random.split(key, 3)
    cand = (n_rounds,) + tuple(shape)
    x = jax.random.normal(kx, cand, dtype=dtype)
    v = (1.0 + c[None] * x) ** 3
    u = jax.random.uniform(ku, cand, dtype=dtype, minval=_TINY, maxval=1.0)
    vsafe = jnp.where(v > 0, v, 1.0)
    ok = (v > 0) & (jnp.log(u) < 0.5 * x * x + d[None] * (1.0 - v + jnp.log(vsafe)))

    idx = jnp.argmax(ok, axis=0)                  # first accepting round
    vsel = jnp.take_along_axis(vsafe, idx[None], axis=0)[0]
    draw = d * jnp.where(jnp.any(ok, axis=0), vsel, 1.0)

    # a < 1: multiply by U^(1/a).  boost is data-dependent under jit, so the
    # uniform + pow run on every call; both are single fused elementwise ops,
    # negligible next to the n_rounds candidate batches above.
    ub = jax.random.uniform(kb, shape, dtype=dtype, minval=_TINY, maxval=1.0)
    pow_ = ub ** (1.0 / jnp.where(boost, a, 1.0))
    return jnp.where(boost, draw * pow_, draw)


def _pg_moments(h, z):
    """Mean/variance of PG(h, z) from its cumulant generating function."""
    u = 0.5 * jnp.abs(z)
    small = u < 1e-3
    us = jnp.where(small, 1.0, u)         # safe denominator
    t = jnp.tanh(us)
    sech2 = 1.0 - t * t
    mean = jnp.where(small, h / 4.0 * (1.0 - u * u / 3.0), h * t / (4.0 * us))
    var = jnp.where(small, h / 24.0, h * (t - us * sech2) / (16.0 * us**3))
    return mean, var


def polya_gamma(key, h, z, n_terms: int = 0, *, _eps=None):
    """Polya-Gamma PG(h, z) draw (reference uses ``BayesLogit::rpg`` with
    h = y + 1000, ``R/updateZ.R:68,79``).

    For the shape parameters the reference ever produces (h >= 1000) the PG
    variable is a sum of >=1000 independent PG(1, z) terms, so a moment-matched
    Gaussian (clipped at 0) is exact to well below Monte-Carlo error; this is
    the default path and is a single fused elementwise op.

    Set ``n_terms > 0`` to add a truncated sum-of-gammas correction
    (Devroye-series representation) for small-h fidelity:
    PG(h,z) = (1/(2 pi^2)) sum_k g_k / ((k-1/2)^2 + z^2/(4 pi^2)), g_k~Ga(h,1).
    """
    if n_terms > 0:
        ks = jnp.arange(1, n_terms + 1, dtype=jnp.result_type(h, z))
        denom = (ks - 0.5) ** 2 + (jnp.asarray(z)[..., None] / (2 * jnp.pi)) ** 2
        g = standard_gamma(key, jnp.asarray(h)[..., None] * jnp.ones_like(denom))
        draw = (g / denom).sum(-1) / (2 * jnp.pi**2)
        # truncation loses mass in the tail terms; add its expected value
        mean, _ = _pg_moments(h, z)
        mean_trunc = (jnp.asarray(h)[..., None] / denom).sum(-1) / (2 * jnp.pi**2)
        return draw + (mean - mean_trunc)
    mean, var = _pg_moments(h, z)
    # _eps: pre-drawn standard normals (the species-sharded sweep draws
    # them full-width and slices, keeping shard draws independent and
    # equal to the replicated stream)
    eps = (jax.random.normal(key,
                             jnp.broadcast_shapes(jnp.shape(h), jnp.shape(z)),
                             dtype=jnp.result_type(h, z))
           if _eps is None else _eps)
    return jnp.maximum(mean + jnp.sqrt(var) * eps, _TINY)


def wishart(key, df, scale_factor):
    """W ~ Wishart(df, S) via the Bartlett decomposition, where
    ``scale_factor`` is any T with T T' = S.  Used for the conjugate iV draw
    (reference ``R/updateGammaV.R:21``, ``MCMCpack::rwish``)."""
    p = scale_factor.shape[-1]
    kn, kc = jax.random.split(key)
    dtype = scale_factor.dtype
    # chi^2_{df-i} = 2 * Gamma((df-i)/2)
    dfs = (df - jnp.arange(p, dtype=dtype)) / 2.0
    diag = jnp.sqrt(2.0 * standard_gamma(kc, dfs))
    A = jnp.tril(jax.random.normal(kn, (p, p), dtype=dtype), -1) + jnp.diag(diag)
    TA = scale_factor @ A
    return TA @ TA.T


def mvn_from_prec_chol(key, L, rhs):
    """Draw from N(P^{-1} rhs, P^{-1}) given L = chol(P); see sample_mvn_prec."""
    from .linalg import sample_mvn_prec
    eps = jax.random.normal(key, rhs.shape, dtype=rhs.dtype)
    return sample_mvn_prec(L, rhs, eps)


def categorical_logits(key, logits, axis=-1):
    """Categorical draw from unnormalised log-weights (grid samplers for rho
    and alpha, reference ``R/updateRho.R:22``, ``R/updateAlpha.R:80``)."""
    return jax.random.categorical(key, logits, axis=axis)
