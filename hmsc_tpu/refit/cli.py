"""``python -m hmsc_tpu refit`` — streaming-refit driver for run
directories written by ``python -m hmsc_tpu run``.

Appends ``--new-rows`` freshly surveyed rows to the synthetic benchmark
JSDM (each new row is a new sampling unit of the run's random level,
generated from the same design family with ``--data-seed``), warm-starts
every chain from the last committed epoch, runs the adaptive transient,
and commits the refreshed posterior as the next epoch.  Prints one JSON
record; exit codes reuse the run driver's taxonomy (75 = preempted with a
resumable epoch in place — rerun with ``--resume``; 78 = no usable
parent checkpoint).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

__all__ = ["refit_main", "synthesize_rows"]


def synthesize_rows(run_dir: str, n_rows: int, data_seed: int = 1):
    """New survey rows for the run driver's synthetic probit JSDM: fresh
    covariate draws from the training design family, responses from a
    ground truth re-derived from the model's own seed, each row a NEW
    sampling unit continuing the ``s<idx>`` labelling."""
    import os

    from ..serve.artifact import _rebuild_run_model
    from .epochs import rebuild_epoch_model

    hM0 = _rebuild_run_model(os.fspath(run_dir))
    from ..utils.checkpoint import committed_epochs
    ks = committed_epochs(run_dir)
    hM = rebuild_epoch_model(run_dir, ks[-1] if ks else 0, hM0=hM0)
    rng = np.random.default_rng(data_seed)
    m = int(n_rows)
    X = np.column_stack([np.ones(m), rng.standard_normal(m)])
    # same generative family as bench_cli._model (coefficients re-drawn
    # under data_seed — the refit does not assume access to the truth)
    B = rng.standard_normal((X.shape[1], hM.ns)) * 0.5
    Y = ((X @ B + rng.standard_normal((m, 2))
          @ (rng.standard_normal((2, hM.ns)) * 0.7)
          + rng.standard_normal((m, hM.ns))) > 0).astype(float)
    level = hM.rl_names[0]
    units = {level: [f"s{hM.ny + i:04d}" for i in range(m)]}
    return Y, X, units


def refit_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hmsc_tpu refit",
        description="incrementally refit a checkpointed run on appended "
                    "survey rows: warm-started chains, adaptive "
                    "abbreviated transient, a new atomic manifest epoch")
    ap.add_argument("run_dir", help="run directory written by "
                                    "`python -m hmsc_tpu run` (epoch 0)")
    ap.add_argument("--new-rows", type=int, default=50,
                    help="synthetic new survey rows to append (each a new "
                         "sampling unit; default 50)")
    ap.add_argument("--data-seed", type=int, default=1,
                    help="RNG seed for the synthesized rows")
    ap.add_argument("--samples", type=int, default=None,
                    help="refreshed draws to record (default: the parent "
                         "epoch's draw count)")
    ap.add_argument("--min-sweeps", type=int, default=8)
    ap.add_argument("--max-sweeps", type=int, default=64)
    ap.add_argument("--probe-every", type=int, default=8)
    ap.add_argument("--rhat", type=float, default=1.10,
                    help="split-R-hat stopping threshold (default 1.10)")
    ap.add_argument("--ess", type=float, default=None,
                    help="running-ESS stopping threshold (default "
                         "4 x chains)")
    ap.add_argument("--seed", type=int, default=0,
                    help="seed for new-unit warm-start draws")
    ap.add_argument("--resume", action="store_true",
                    help="continue an interrupted refit (the epoch's "
                         "persisted rows are used; no new rows are "
                         "synthesized)")
    ap.add_argument("--verbose", type=int, default=0)
    args = ap.parse_args(argv)

    from ..exit_codes import EXIT_CKPT_CORRUPT, EXIT_PREEMPTED
    from ..utils.checkpoint import CheckpointError, PreemptedRun
    from .driver import update_run

    t0 = time.perf_counter()
    try:
        if args.resume:
            res = update_run(args.run_dir, verbose=args.verbose)
        else:
            Y, X, units = synthesize_rows(args.run_dir, args.new_rows,
                                          args.data_seed)
            res = update_run(
                args.run_dir, Y, X, units, samples=args.samples,
                min_sweeps=args.min_sweeps, max_sweeps=args.max_sweeps,
                probe_every=args.probe_every, rhat_threshold=args.rhat,
                ess_target=args.ess, seed=args.seed,
                verbose=args.verbose)
    except PreemptedRun as e:
        print(json.dumps({
            "preempted": True, "signal": e.signum,
            "resume": f"python -m hmsc_tpu refit --resume {args.run_dir}",
        }))
        return EXIT_PREEMPTED
    except CheckpointError as e:
        print(json.dumps({"error": "checkpoint", "detail": str(e),
                          "run_dir": args.run_dir}))
        return EXIT_CKPT_CORRUPT
    print(json.dumps({
        "epoch": res.epoch,
        "new_rows": args.new_rows if not args.resume else None,
        "transient_sweeps": res.transient_sweeps,
        "rhat_max": res.diagnostics.get("rhat_max"),
        "ess_min": res.diagnostics.get("ess_min"),
        "samples": int(res.post.samples),
        "finite": bool(np.isfinite(res.post["Beta"]).all()),
        "epoch_dir": res.epoch_dir,
        "wall_s": round(time.perf_counter() - t0, 3),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(refit_main())
