"""Data-append validation for streaming refits.

:func:`append_data` takes a fitted model and the newly surveyed rows and
builds the *grown* :class:`~hmsc_tpu.model.Hmsc` the refit samples: the
response matrix gains rows (NA-imputed cells allowed — exactly like the
original fit's missing-data handling), the design matrix gains the matching
covariate rows, and new sampling units may join existing unstructured
random levels.

Everything stream-defining is PINNED from the parent model, never
re-derived from the appended data:

- X/Y/Tr column scaling uses the parent's recorded scale parameters (a
  refit must live in the parent's covariate space, or the carried Beta
  would be silently mis-scaled);
- priors (V0, f0, Gamma, sigma, rho grid) are copied verbatim;
- the random-level prior objects are shared, so factor bounds match.

The one deliberately *derived* piece is the unit index space: the ``Hmsc``
constructor sorts unit labels, so an appended unit can land anywhere in the
new index order — :func:`append_data` therefore reports nothing about
ordering and the warm start re-aligns Eta rows by LABEL
(:func:`hmsc_tpu.mcmc.sampler.grow_carry_state`).

v1 scope: shared designs only (no per-species X lists), no reduced-rank
covariates, no spike-and-slab selection groups; new units are accepted on
unstructured levels only (spatial / covariate-dependent levels need
per-unit data an append cannot invent — rows at *existing* units of those
levels are fine).
"""

from __future__ import annotations

import hashlib
import json

import numpy as np

from ..model import Hmsc

__all__ = ["append_data", "new_data_digest"]


def _scale_with(par, M):
    """Apply recorded (mu, sd) column scaling: columns the original fit
    left unscaled carry (0, 1) and pass through."""
    mu, sd = np.asarray(par)[0], np.asarray(par)[1]
    return (np.asarray(M, dtype=float) - mu) / sd


def new_data_digest(new_Y, new_X, new_units) -> str:
    """Deterministic content digest of one append — a resumed refit
    validates the caller's rows against the epoch's persisted copy."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(np.asarray(new_Y, dtype=np.float64)))
    if new_X is not None:
        h.update(np.ascontiguousarray(np.asarray(new_X, dtype=np.float64)))
    units = {k: [str(u) for u in v] for k, v in (new_units or {}).items()}
    h.update(json.dumps(units, sort_keys=True).encode())
    return h.hexdigest()


def append_data(hM: Hmsc, new_Y, new_X=None, new_units=None) -> Hmsc:
    """Validate appended survey rows and build the grown model.

    ``new_Y`` is the ``(m, ns)`` block of new responses (NaN marks
    unobserved cells).  ``new_X`` is the matching ``(m, nc)`` block of RAW
    covariate rows (same columns as the parent's ``X``; scaled here with
    the parent's recorded parameters).  ``new_units`` maps each random
    level's name to its ``m`` unit labels — labels already in the training
    design join their unit, unseen labels create new units (unstructured
    levels only).  Returns the grown ``Hmsc``; the caller warm-starts it
    via :func:`~hmsc_tpu.mcmc.sampler.grow_carry_state`."""
    if hM.x_is_list:
        raise NotImplementedError(
            "append_data: species-specific designs (X lists) are not "
            "refittable yet — fit the grown dataset fresh")
    if hM.nc_rrr > 0:
        raise NotImplementedError(
            "append_data: reduced-rank covariates (XRRR) are not "
            "refittable yet — fit the grown dataset fresh")
    if hM.ncsel > 0:
        raise NotImplementedError(
            "append_data: spike-and-slab selection groups (XSelect) are "
            "not refittable yet — fit the grown dataset fresh")

    new_Y = np.atleast_2d(np.asarray(new_Y, dtype=float))
    m = new_Y.shape[0]
    if m < 1 or new_Y.shape[1] != hM.ns:
        raise ValueError(
            f"append_data: new_Y has shape {new_Y.shape}, expected "
            f"(m >= 1, ns={hM.ns}) — one row per new sampling unit, one "
            "column per species (NaN for unobserved cells)")
    probit = hM.distr[:, 0] == 2
    if probit.any():
        v = new_Y[:, probit]
        bad = np.isfinite(v) & (v != 0.0) & (v != 1.0)
        if bad.any():
            raise ValueError(
                "append_data: probit species take 0/1 (or NaN) responses; "
                f"got {v[bad][:5].tolist()}")

    if hM.nc > 0:
        if new_X is None:
            if np.all(hM.X == hM.X[:1]):
                # constant design (e.g. intercept-only): replicate it
                new_X = np.repeat(hM.X[:1], m, axis=0)
            else:
                raise ValueError(
                    "append_data: the model has covariates — pass new_X "
                    f"with shape (m={m}, nc={hM.nc}) raw covariate rows "
                    "(same columns as the training X, unscaled)")
        new_X = np.atleast_2d(np.asarray(new_X, dtype=float))
        if new_X.shape != (m, hM.nc):
            raise ValueError(
                f"append_data: new_X has shape {new_X.shape}, expected "
                f"({m}, {hM.nc}) — raw rows in the training X's columns")
        if np.isnan(new_X).any():
            raise ValueError("append_data: new_X must contain no NA values")
    else:
        new_X = np.empty((m, 0))

    # per-level labels for the new rows; unseen labels create new units
    # on unstructured levels only
    new_units = dict(new_units or {})
    unknown = sorted(set(new_units) - set(hM.rl_names))
    if unknown:
        raise ValueError(
            f"append_data: new_units names unknown level(s) {unknown}; "
            f"the model's random levels are {hM.rl_names}")
    labels_by_level = []
    for r, name in enumerate(hM.rl_names):
        labels = new_units.get(name)
        if labels is None:
            raise ValueError(
                f"append_data: new_units must give the {m} unit labels "
                f"for random level {name!r} (new rows must join the "
                "study design)")
        labels = [str(u) for u in labels]
        if len(labels) != m:
            raise ValueError(
                f"append_data: new_units[{name!r}] has {len(labels)} "
                f"labels for {m} new rows")
        rL = hM.ranLevels[r]
        fresh = sorted(set(labels) - set(hM.pi_names[r]))
        if fresh and rL.s_dim != 0:
            raise NotImplementedError(
                f"append_data: new units {fresh[:5]} on the spatial "
                f"level {name!r} need coordinates — refit with rows at "
                "existing units, or fit the grown level fresh")
        if fresh and rL.x_dim > 0:
            raise NotImplementedError(
                f"append_data: new units {fresh[:5]} on the covariate-"
                f"dependent level {name!r} (xDim > 0) need per-unit "
                "covariates — not refittable yet")
        labels_by_level.append(labels)

    # ---- build the grown model on the PARENT's scaled spaces -------------
    import pandas as pd

    study = pd.DataFrame({
        name: list(hM.df_pi[r]) + labels_by_level[r]
        for r, name in enumerate(hM.rl_names)}) if hM.nr else None
    Xs_new = _scale_with(hM.x_scale_par, new_X) if hM.nc else new_X
    grown = Hmsc(
        Y=np.vstack([hM.Y, new_Y]),
        X=np.vstack([np.asarray(hM.XScaled), Xs_new]),
        x_scale=False,
        y_scale=False,
        Tr=hM.Tr,
        tr_scale=False,
        C=hM.C,
        study_design=study,
        ran_levels={name: hM.ranLevels[r]
                    for r, name in enumerate(hM.rl_names)} or None,
        ran_levels_used=list(hM.rl_names) or None,
        distr=np.asarray(hM.distr),
    )
    # pin the parent's scaling / naming / priors (stream-defining: the
    # carried Beta lives in the parent's scaled covariate space)
    grown.X = np.vstack([hM.X, new_X]) if hM.nc else grown.X
    grown.x_scale_par = np.asarray(hM.x_scale_par).copy()
    grown.cov_names = list(hM.cov_names)
    grown.x_intercept_ind = hM.x_intercept_ind
    ym, ys = np.asarray(hM.y_scale_par)
    grown.YScaled = np.vstack([hM.YScaled, (new_Y - ym) / ys])
    grown.y_scale_par = np.asarray(hM.y_scale_par).copy()
    grown.Tr = np.asarray(hM.Tr).copy()
    grown.TrScaled = np.asarray(hM.TrScaled).copy()
    grown.tr_scale_par = np.asarray(hM.tr_scale_par).copy()
    grown.tr_intercept_ind = hM.tr_intercept_ind
    grown.tr_names = list(hM.tr_names)
    grown.sp_names = list(hM.sp_names)
    for attr in ("V0", "f0", "mGamma", "UGamma", "aSigma", "bSigma",
                 "rhopw", "nuRRR", "a1RRR", "b1RRR", "a2RRR", "b2RRR"):
        setattr(grown, attr, getattr(hM, attr))
    return grown
