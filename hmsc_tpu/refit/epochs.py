"""Epoch layout of a streaming-refit run directory.

A refitted run is a sequence of *epochs*: epoch 0 is the original fit (the
run root's ordinary append-only layout — old directories read as epoch 0
with no migration), and each ``update_run`` commits one more
``epoch-<k>/`` subdirectory holding

- ``new-data.npz`` — the appended rows (responses, raw covariate rows,
  per-level unit labels), persisted FIRST so a resumed refit revalidates
  against exactly the data the epoch was started with, and so any reader
  can rebuild the epoch's grown model deterministically
  (:func:`rebuild_epoch_model` replays the appends on top of epoch 0);
- ``transient/`` — the adaptive warm-up's probe layout (diagnostic draws,
  checkpointed so a killed refit resumes its warm-up bit-exactly);
- the epoch's own shards / state files / manifests — the refreshed
  posterior on the appended dataset;
- ``epoch.json`` — the epoch's metadata (parent, shapes, adaptive-
  transient summary, spec fingerprint).

The run-root ``epochs.json`` registry (:mod:`hmsc_tpu.utils.checkpoint`)
is the COMMIT point: it is rewritten atomically only after the epoch's
final manifest and ``epoch.json`` are durable, so a reader resolving
through the registry can never open a half-written epoch.  Prior epochs
are immutable and GC-pinned while the registry references them
(``gc_checkpoints(pin_epochs=...)`` is the explicit unpin).
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..utils.checkpoint import (CheckpointError, _atomic_savez,
                                _atomic_write_bytes, committed_epochs,
                                epoch_dir_path, latest_valid_checkpoint,
                                read_epoch_registry, write_epoch_registry)
from .data import append_data

__all__ = ["EPOCH_META_FILE", "NEW_DATA_FILE", "REFIT_STATE_FILE",
           "save_new_data", "load_new_data", "rebuild_epoch_model",
           "commit_epoch", "load_epoch_posterior", "epoch_metadata"]

EPOCH_META_FILE = "epoch.json"
NEW_DATA_FILE = "new-data.npz"
REFIT_STATE_FILE = "refit-state.json"


def save_new_data(epoch_dir: str, new_Y, new_X, new_units) -> str:
    """Persist one append's rows (atomic): the resumable ground truth the
    epoch's grown model is rebuilt from."""
    payload = {"Y": np.asarray(new_Y, dtype=float)}
    if new_X is not None:
        payload["X"] = np.asarray(new_X, dtype=float)
    for name, labels in (new_units or {}).items():
        payload[f"units:{name}"] = np.asarray([str(u) for u in labels])
    path = os.path.join(os.fspath(epoch_dir), NEW_DATA_FILE)
    _atomic_savez(path, payload)
    return path


def load_new_data(epoch_dir: str):
    """``(new_Y, new_X, new_units)`` back from ``new-data.npz``."""
    path = os.path.join(os.fspath(epoch_dir), NEW_DATA_FILE)
    try:
        with np.load(path, allow_pickle=False) as z:
            Y = np.asarray(z["Y"])
            X = np.asarray(z["X"]) if "X" in z.files else None
            units = {k[6:]: [str(u) for u in z[k]]
                     for k in z.files if k.startswith("units:")}
    except (OSError, ValueError, KeyError) as e:
        raise CheckpointError(
            f"{path}: unreadable appended-data record "
            f"({type(e).__name__}: {e}) — the epoch cannot be rebuilt") \
            from e
    return Y, X, units


def epoch_metadata(run_dir: str, epoch: int) -> dict | None:
    """The parsed ``epoch.json`` for one epoch (``None`` for epoch 0 or a
    not-yet-finalised epoch directory)."""
    p = os.path.join(epoch_dir_path(run_dir, epoch), EPOCH_META_FILE)
    if not os.path.exists(p):
        return None
    with open(p, "rb") as f:
        return json.loads(f.read().decode())


def rebuild_epoch_model(run_dir: str, epoch: int, hM0=None):
    """The grown :class:`~hmsc_tpu.model.Hmsc` an epoch was fitted on,
    rebuilt deterministically: epoch 0's model (``hM0``, or the run
    driver's persisted ``model.json``) plus every committed append up to
    ``epoch``, replayed through :func:`~hmsc_tpu.refit.data.append_data`
    (scaling and priors pinned at every step, so the result is exactly the
    model the refit sampled)."""
    if hM0 is None:
        from ..serve.artifact import _rebuild_run_model
        hM0 = _rebuild_run_model(os.fspath(run_dir))
    hM = hM0
    for k in range(1, int(epoch) + 1):
        d = epoch_dir_path(run_dir, k)
        if not os.path.isdir(d):
            raise CheckpointError(
                f"{run_dir}: epoch {k} directory is missing — the epoch "
                "chain up to the requested epoch cannot be rebuilt")
        hM = append_data(hM, *load_new_data(d))
    return hM


def commit_epoch(run_dir: str, epoch: int, info: dict) -> None:
    """Finalise one refit epoch: write its ``epoch.json``, then atomically
    flip the run-root registry to include it — the serving layer's epoch
    resolution observes the flip, never a partial epoch.  Creates the
    registry (with the implicit epoch-0 entry) on the first refit."""
    run_dir = os.fspath(run_dir)
    k = int(epoch)
    d = epoch_dir_path(run_dir, k)
    info = dict(info, epoch=k)
    _atomic_write_bytes(os.path.join(d, EPOCH_META_FILE),
                        json.dumps(info, sort_keys=True).encode())
    reg = read_epoch_registry(run_dir)
    if reg is None:
        reg = {"epochs": [{"epoch": 0}]}
    entries = [e for e in reg["epochs"] if int(e["epoch"]) != k]
    entries.append({"epoch": k,
                    "dir": os.path.relpath(d, run_dir),
                    "parent": int(info.get("parent", k - 1)),
                    "ny": info.get("ny"),
                    "spec_sha256": info.get("spec_sha256")})
    reg["epochs"] = entries
    write_epoch_registry(run_dir, reg)


def load_epoch_posterior(run_dir: str, epoch: int | None = None, *,
                         hM0=None, allow_legacy_pickle: bool = False):
    """``(posterior, hM, epoch)`` for one committed epoch (default: the
    newest).  Selection is fully deterministic — the registry picks the
    epoch by INDEX and the layout picks the manifest by its encoded sample
    index (never directory mtime), so concurrent refits can never make a
    reader open a half-written epoch."""
    run_dir = os.fspath(run_dir)
    ks = committed_epochs(run_dir)
    if not ks:
        raise CheckpointError(f"no committed epochs under {run_dir!r}")
    k = ks[-1] if epoch is None else int(epoch)
    if k not in ks:
        raise CheckpointError(
            f"{run_dir}: epoch {k} is not committed (committed: {ks})")
    hM = (rebuild_epoch_model(run_dir, k, hM0) if k > 0
          else (hM0 if hM0 is not None else None))
    if hM is None:
        from ..serve.artifact import _rebuild_run_model
        hM = _rebuild_run_model(run_dir)
    ck = latest_valid_checkpoint(epoch_dir_path(run_dir, k), hM,
                                 allow_legacy_pickle=allow_legacy_pickle)
    return ck.post, hM, k
