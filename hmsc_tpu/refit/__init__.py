"""Streaming refits: models that live with their data.

``update_run(run_dir, new_Y, ...)`` appends freshly surveyed rows to a
fitted, checkpointed run, warm-starts every chain from the last committed
posterior state, runs an abbreviated *adaptive* transient (stopping on
running split-R-hat/ESS), and commits the refreshed draws as a new
immutable manifest epoch — which the serving engine hot-reloads behind an
atomic flip (``ServingEngine.reload()`` / ``POST /flip``).

See :mod:`hmsc_tpu.refit.driver` for the phase protocol and
:mod:`hmsc_tpu.refit.epochs` for the on-disk epoch layout.
"""

from .data import append_data, new_data_digest
from .driver import RefitAborted, RefitResult, update_run
from .epochs import (commit_epoch, epoch_metadata, load_epoch_posterior,
                     load_new_data, rebuild_epoch_model, save_new_data)

__all__ = [
    "update_run", "RefitResult", "RefitAborted", "append_data",
    "new_data_digest", "rebuild_epoch_model", "load_epoch_posterior",
    "epoch_metadata", "commit_epoch", "save_new_data", "load_new_data",
]
