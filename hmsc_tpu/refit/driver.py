"""``update_run`` — incremental refits of a fitted, checkpointed run.

The streaming-refit contract (ROADMAP "models that live with their data"):
new survey rows arrive, the model should NOT pay a full from-scratch
burn-in.  The Gibbs structure makes warm restarts exact — chains resume
from the parent epoch's committed carry state, which already sits in the
(old-data) posterior's typical set — so the refit only needs an
*abbreviated adaptive transient* to re-equilibrate to the appended
dataset, not to find the posterior from a random start.

One ``update_run`` call is one new manifest epoch:

1. **Append + validate** — the new rows are persisted into the epoch
   directory first (``new-data.npz``), then the grown model is built with
   every stream-defining quantity pinned from the parent
   (:func:`~hmsc_tpu.refit.data.append_data`).
2. **Warm start** — every chain's carry re-shapes onto the grown data
   (:func:`~hmsc_tpu.mcmc.sampler.grow_carry_state`).
3. **Adaptive transient** — probe segments of ``probe_every`` sweeps run
   under the parent's sampler configuration (thin=1, Beta-only recording)
   into ``epoch-<k>/transient/``; after each probe the accumulated draws
   feed :class:`~hmsc_tpu.obs.health.RunningDiagnostics`, and the warm-up
   stops once split-R-hat and ESS clear their thresholds (bounded by
   ``min_sweeps``/``max_sweeps``).  Probes are ordinary checkpointed runs,
   so a killed refit resumes its warm-up bit-exactly and the stopping
   decision — a deterministic function of the committed draws — replays
   identically.
4. **Refreshed draws** — the recorded sampling phase runs with every
   stream-defining parameter pinned from the parent run's metadata
   (thin / chains / updaters / dtype / RNG impl / precision policy /
   record selection), checkpointing into the epoch directory itself.
5. **Commit** — ``epoch.json`` then the atomic run-root registry flip
   (:func:`~hmsc_tpu.refit.epochs.commit_epoch`); the serving engine's
   ``reload()`` observes the flip, in-flight queries finish on the old
   epoch.

Every phase transition is persisted (``refit-state.json``), so
``update_run`` called again on a killed refit continues exactly where it
stopped: kill -> resume produces a final epoch bit-identical to an
uninterrupted refit (asserted by ``tests/test_refit.py``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any

import numpy as np

from ..obs import get_logger
from ..obs.events import RunTelemetry, events_path
from ..obs.health import RunningDiagnostics
from ..utils.checkpoint import (CheckpointError, _atomic_write_bytes,
                                checkpoint_files, committed_epochs,
                                epoch_dir_path, gc_checkpoints,
                                latest_valid_checkpoint, resume_run,
                                spec_fingerprint)
from .data import append_data, new_data_digest
from .epochs import (NEW_DATA_FILE, REFIT_STATE_FILE, commit_epoch,
                     load_new_data, rebuild_epoch_model, save_new_data)

__all__ = ["update_run", "RefitResult", "RefitAborted"]


class RefitAborted(RuntimeError):
    """Deterministic mid-refit interruption (the kill/resume test hook —
    raised only via ``update_run(..., _abort_after=...)``).  The refit is
    left exactly as a SIGKILL at the same boundary would leave it;
    ``update_run`` again continues it."""


@dataclasses.dataclass
class RefitResult:
    """What one committed refit epoch produced."""
    epoch: int
    post: Any                    # the refreshed Posterior (appended dataset)
    transient_sweeps: int        # adaptive warm-up length actually used
    diagnostics: dict            # RunningDiagnostics summary at the stop
    epoch_dir: str
    committed: bool
    wall_s: float


def _read_state(epoch_dir: str) -> dict | None:
    p = os.path.join(epoch_dir, REFIT_STATE_FILE)
    if not os.path.exists(p):
        return None
    with open(p, "rb") as f:
        return json.loads(f.read().decode())


def _write_state(epoch_dir: str, st: dict) -> None:
    _atomic_write_bytes(os.path.join(epoch_dir, REFIT_STATE_FILE),
                        json.dumps(st, sort_keys=True).encode())


def _transient_passed(summary: dict, rhat_threshold: float,
                      ess_target: float) -> bool:
    """The adaptive stopping rule: both running diagnostics must exist and
    clear their thresholds (too-few-draws summaries report None and keep
    the warm-up going)."""
    rhat, ess = summary.get("rhat_max"), summary.get("ess_min")
    return (rhat is not None and ess is not None
            and rhat <= rhat_threshold and ess >= ess_target)


def update_run(run_dir: str, new_Y=None, new_X=None, new_units=None, *,
               hM=None, samples: int | None = None,
               min_sweeps: int = 8, max_sweeps: int = 64,
               probe_every: int = 8, rhat_threshold: float = 1.10,
               ess_target: float | None = None, seed: int = 0,
               checkpoint_every: int | None = None, verbose: int = 0,
               mesh=None, chain_axis: str = "chains",
               species_axis: str = "species", site_axis: str = "sites",
               _abort_after=None) -> RefitResult:
    """Incrementally refit a run on appended survey rows (see the module
    docstring for the phase protocol).

    ``run_dir`` is a fitted, auto-checkpointed run directory (the run root
    is epoch 0; prior ``update_run`` epochs stack on top).  ``new_Y`` /
    ``new_X`` / ``new_units`` are the appended rows
    (:func:`~hmsc_tpu.refit.data.append_data`); pass ``new_Y=None`` to
    RESUME an interrupted refit (the epoch's persisted copy is used — and
    when rows ARE passed again, they must digest-match it).

    ``hM`` is the epoch-0 model for run directories not written by
    ``python -m hmsc_tpu run`` (those rebuild it from ``model.json``).
    ``samples`` defaults to the parent epoch's recorded draw count.  The
    adaptive transient is bounded to ``[min_sweeps, max_sweeps]`` total
    sweeps, probed every ``probe_every``; ``ess_target`` defaults to
    ``4 x n_chains``.  Everything else stream-defining is pinned from the
    parent run's metadata and cannot be overridden here.

    ``mesh`` shards the refit's sweeps like ``sample_mcmc``'s.  A parent
    fitted with ``local_rng=True`` REQUIRES it: the shard-folded key
    streams pin the engaged ``(species_shards, site_shards)`` tuple from
    the checkpoint metadata, so the refit must re-shard over the same
    extents (``make_mesh(species_shards=..., site_shards=...)``) or a
    clear ``CheckpointError`` is raised before any epoch state is
    written."""
    import jax.numpy as jnp

    t0 = time.perf_counter()
    run_dir = os.fspath(run_dir)
    log = get_logger()
    ks = committed_epochs(run_dir)
    if not ks:
        raise CheckpointError(
            f"update_run: no fitted run under {run_dir!r} — refits grow an "
            "auto-checkpointed run directory (sample_mcmc with "
            "checkpoint_every=, or `python -m hmsc_tpu run`)")
    parent_k = ks[-1]
    k_new = parent_k + 1
    d_new = epoch_dir_path(run_dir, k_new)
    t_dir = os.path.join(d_new, "transient")

    hM_parent = rebuild_epoch_model(run_dir, parent_k, hM0=hM)
    ck = latest_valid_checkpoint(epoch_dir_path(run_dir, parent_k),
                                 hM_parent)
    meta = dict(ck.run_meta)
    if not meta:
        raise CheckpointError(
            f"{ck.path}: no run metadata — update_run needs an "
            "auto-checkpointed run (save_checkpoint snapshots cannot pin "
            "the sampler configuration)")
    local_rng = bool(meta.get("local_rng", False))
    if local_rng:
        # shard-folded key streams pin the mesh tuple (like resume_run):
        # the species extent is checked raw here, the engaged site extent
        # below once the grown model exists
        axes = getattr(mesh, "axis_names", ()) if mesh is not None else ()
        want_sp = meta.get("species_shards")
        have_sp = (int(mesh.shape[species_axis])
                   if species_axis in axes else None)
        if want_sp is not None and have_sp != want_sp:
            raise CheckpointError(
                f"update_run: the parent run used local_rng over "
                f"{want_sp} species shard(s); the refit must pass a mesh "
                f"pinning the same '{species_axis}' extent (got "
                f"{have_sp if have_sp is not None else 'no mesh'}) — "
                f"e.g. make_mesh(species_shards={want_sp}, "
                f"site_shards={meta.get('site_shards') or 1})")
    good = np.asarray(ck.post.good_chain_mask())
    if not good.all():
        raise CheckpointError(
            f"{ck.path}: {int((~good).sum())} chain(s) ended diverged — a "
            "warm start would propagate the non-finite carry.  Heal the "
            "parent first (retry_diverged=) or fit the grown dataset "
            "fresh")

    # ---- epoch scratch: persist/validate the appended rows ---------------
    st = _read_state(d_new)
    if st is not None and int(st.get("parent", -1)) != parent_k:
        raise CheckpointError(
            f"{d_new}: holds an abandoned refit of epoch "
            f"{st.get('parent')} (current parent is {parent_k}) — remove "
            "the directory to start over")
    if st is None:
        if new_Y is None:
            if os.path.exists(os.path.join(d_new, NEW_DATA_FILE)):
                new_Y, new_X, new_units = load_new_data(d_new)
            else:
                raise ValueError(
                    "update_run: new_Y is required to start a refit "
                    "(pass new_Y=None only to resume an interrupted one)")
        os.makedirs(t_dir, exist_ok=True)
        save_new_data(d_new, new_Y, new_X, new_units)
        st = {
            "phase": "transient", "parent": parent_k, "epoch": k_new,
            "digest": new_data_digest(new_Y, new_X, new_units),
            # the adaptive-transient configuration is pinned at refit
            # start: a resumed refit must replay the same stopping rule
            "config": {
                "samples": int(samples if samples is not None
                               else ck.post.samples),
                "min_sweeps": int(min_sweeps),
                "max_sweeps": int(max_sweeps),
                "probe_every": int(probe_every),
                "rhat_threshold": float(rhat_threshold),
                "ess_target": float(ess_target if ess_target is not None
                                    else 4.0 * int(meta["n_chains"])),
                "seed": int(seed),
                "checkpoint_every": (None if checkpoint_every is None
                                     else int(checkpoint_every)),
            },
        }
        _write_state(d_new, st)
    else:
        stored_Y, stored_X, stored_units = load_new_data(d_new)
        if new_Y is not None:
            if new_data_digest(new_Y, new_X, new_units) != st["digest"]:
                raise CheckpointError(
                    f"{d_new}: an interrupted refit holds DIFFERENT "
                    "appended rows than the ones passed — resume with "
                    "new_Y=None, or remove the epoch directory to refit "
                    "the new rows instead")
        new_Y, new_X, new_units = stored_Y, stored_X, stored_units
    cfg = st["config"]
    if cfg["min_sweeps"] < 1 or cfg["max_sweeps"] < cfg["min_sweeps"] \
            or cfg["probe_every"] < 1:
        raise ValueError(
            f"update_run: need 1 <= min_sweeps <= max_sweeps and "
            f"probe_every >= 1, got min={cfg['min_sweeps']} "
            f"max={cfg['max_sweeps']} probe={cfg['probe_every']}")

    hM2 = append_data(hM_parent, new_Y, new_X, new_units)
    nf_cap = int(meta["nf_cap"])
    if local_rng:
        # the ENGAGED site extent of the GROWN model must match the
        # parent's: appended rows can break ny/unit divisibility and drag
        # the site axis into a fallback the parent never took
        from ..mcmc.partition import engaged_site_extent
        from ..mcmc.structs import build_spec as _build_spec
        want_st = meta.get("site_shards")
        have_st = (engaged_site_extent(
            _build_spec(hM2, nf_cap), mesh, species_axis, site_axis,
            meta.get("updater"),
            has_policy=meta.get("precision_policy") is not None)
            if mesh is not None else 1)
        if want_st is not None and have_st != want_st:
            raise CheckpointError(
                f"update_run: the parent run used local_rng over "
                f"(species_shards={meta.get('species_shards')}, "
                f"site_shards={want_st}); the grown model engages "
                f"'{site_axis}' extent {have_st} on this mesh — the "
                "shard-local key streams are not layout-invariant, so "
                "the refit mesh must reproduce the parent's engaged "
                "extents")

    # sampler configuration pinned from the parent run (stream-defining)
    pinned = dict(
        n_chains=int(meta["n_chains"]),
        nf_cap=nf_cap,
        adapt_nf=meta.get("adapt_nf"),
        updater=meta.get("updater"),
        dtype=getattr(jnp, meta.get("dtype", "float32")),
        rng_impl=meta.get("rng_impl"),
        precision_policy=meta.get("precision_policy"),
        local_rng=local_rng, mesh=mesh, chain_axis=chain_axis,
        species_axis=species_axis, site_axis=site_axis,
        align_post=False, verbose=verbose,
    )
    # carried keys continue the parent's exact stream; a keyless parent
    # snapshot falls back to a seeded, epoch-decorrelated fresh stream
    init_keys = ck.keys
    fresh_seed = (int(meta.get("seed") or 0) + 104729 * k_new
                  if init_keys is None else meta.get("seed"))

    from ..mcmc.sampler import grow_carry_state, sample_mcmc
    from ..mcmc.structs import build_spec
    diag_summary: dict = dict(st.get("diagnostics") or {})
    transient_sweeps = int(st.get("transient_sweeps") or 0)

    # ---- phase 1: adaptive transient ------------------------------------
    if st["phase"] == "transient":
        if not checkpoint_files(t_dir):
            grown = grow_carry_state(ck.state, hM_parent, hM2,
                                     seed=cfg["seed"], nf_cap=nf_cap)
            post_t = sample_mcmc(
                hM2, samples=cfg["min_sweeps"], transient=0, thin=1,
                seed=fresh_seed, init_state=grown, init_keys=init_keys,
                record=("Beta",), checkpoint_every=cfg["probe_every"],
                checkpoint_path=t_dir, checkpoint_keep=2, **pinned)
        else:
            # finish any in-flight probe target first (no-op if complete)
            post_t = resume_run(hM2, t_dir, verbose=verbose, mesh=mesh,
                                chain_axis=chain_axis,
                                species_axis=species_axis,
                                site_axis=site_axis)
        probes = 0
        while True:
            sweeps = int(post_t.samples)
            diag = RunningDiagnostics(monitor=("Beta",))
            diag.update({"Beta": np.asarray(post_t["Beta"])})
            diag_summary = diag.summary()
            probes += 1
            log.info(
                f"refit epoch {k_new}: transient probe at {sweeps} sweeps "
                f"(rhat_max={diag_summary.get('rhat_max')}, "
                f"ess_min={diag_summary.get('ess_min')})")
            if _abort_after == ("transient", probes):
                raise RefitAborted(
                    f"aborted after transient probe {probes} (test hook)")
            if sweeps >= cfg["max_sweeps"] or (
                    sweeps >= cfg["min_sweeps"]
                    and _transient_passed(diag_summary,
                                          cfg["rhat_threshold"],
                                          cfg["ess_target"])):
                break
            post_t = resume_run(
                hM2, t_dir, verbose=verbose, mesh=mesh,
                chain_axis=chain_axis, species_axis=species_axis,
                site_axis=site_axis,
                extra_samples=min(cfg["probe_every"],
                                  cfg["max_sweeps"] - sweeps))
        transient_sweeps = int(post_t.samples)
        st.update(phase="sample", transient_sweeps=transient_sweeps,
                  diagnostics=diag_summary)
        _write_state(d_new, st)

    if _abort_after == ("before_sample",):
        raise RefitAborted("aborted before the sampling phase (test hook)")

    # ---- phase 2: refreshed draws ---------------------------------------
    if st["phase"] == "sample":
        if checkpoint_files(d_new):
            post = resume_run(hM2, d_new, verbose=verbose, mesh=mesh,
                              chain_axis=chain_axis,
                              species_axis=species_axis,
                              site_axis=site_axis)
        else:
            ck_t = latest_valid_checkpoint(t_dir, hM2)
            ck_every = cfg["checkpoint_every"]
            if ck_every is None:
                ck_every = int(meta.get("checkpoint_every") or 0) \
                    or cfg["probe_every"]
            post = sample_mcmc(
                hM2, samples=cfg["samples"], transient=0,
                thin=int(meta["thin"]), seed=fresh_seed,
                init_state=ck_t.state, init_keys=ck_t.keys,
                record=(tuple(meta["record"]) if meta.get("record")
                        else None),
                record_dtype=(getattr(jnp, meta["record_dtype"])
                              if meta.get("record_dtype") else None),
                retry_diverged=int(meta.get("retry_diverged", 0)),
                checkpoint_every=ck_every, checkpoint_path=d_new,
                checkpoint_keep=int(meta.get("checkpoint_keep", 3)),
                **pinned)
        if _abort_after == ("before_commit",):
            raise RefitAborted("aborted before the epoch commit (test hook)")
        # ---- phase 3: commit (atomic registry flip) ---------------------
        commit_epoch(run_dir, k_new, {
            "parent": parent_k,
            "ny": int(hM2.ny), "ns": int(hM2.ns),
            "new_rows": int(np.atleast_2d(np.asarray(new_Y)).shape[0]),
            "n_chains": int(meta["n_chains"]),
            "samples": int(cfg["samples"]), "thin": int(meta["thin"]),
            "transient_sweeps": transient_sweeps,
            "diagnostics": diag_summary,
            "spec_sha256": spec_fingerprint(build_spec(hM2, nf_cap)),
            "data_digest": st["digest"],
        })
        st.update(phase="done")
        _write_state(d_new, st)
        # the probe transient served its purpose: keep one resume slot,
        # reclaim the rest (the committed epoch itself is untouched)
        gc_checkpoints(t_dir, keep=1)
        # epoch-tagged refit telemetry, appended to the epoch's own stream
        telem = RunTelemetry(proc=0)
        telem.emit("run", "refit_commit", epoch=k_new, parent=parent_k,
                   ny=int(hM2.ny), new_rows=int(np.atleast_2d(
                       np.asarray(new_Y)).shape[0]),
                   transient_sweeps=transient_sweeps,
                   rhat_max=diag_summary.get("rhat_max"),
                   ess_min=diag_summary.get("ess_min"))
        telem.attach_sink(events_path(d_new, 0))
        telem.flush()
    else:                                    # phase == "done": re-entry
        from .epochs import load_epoch_posterior
        post, _, _ = load_epoch_posterior(run_dir, k_new, hM0=hM)

    n_new = int(np.atleast_2d(np.asarray(new_Y)).shape[0])
    log.info(f"refit epoch {k_new} committed: +{n_new} rows, transient "
             f"{transient_sweeps} sweeps, {int(post.samples)} refreshed "
             "draws")
    return RefitResult(
        epoch=k_new, post=post, transient_sweeps=transient_sweeps,
        diagnostics=diag_summary, epoch_dir=d_new, committed=True,
        wall_s=time.perf_counter() - t0)
