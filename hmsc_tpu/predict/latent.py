"""Conditional prediction of latent factors at new units (reference
``R/predictLatentFactor.R:35-210``).

TPU-first restructuring: the reference loops over posterior draws and factors,
re-factorising the GP kernel for every (draw, factor) pair.  Here the range
parameter alpha lives on a discrete grid, so all (draw, factor) pairs sharing
one grid value share one kernel factorisation: we group pairs by grid index,
factorise once per visited grid value, and apply the conditional by one
batched matmul per group.  This turns O(n_draws * nf) cubic solves into
O(n_visited_grid_values) solves + large MXU-friendly batched products.

Kriging math per mode mirrors the reference exactly:

- ``Full``: joint-kernel conditional N(K21 K11^-1 eta, K22 - K21 K11^-1 K12)
  (``predictLatentFactor.R:95-117``).
- ``NNGP``: k-nearest-neighbour conditional per new unit
  (``predictLatentFactor.R:118-160``).
- ``GPP``: knot-based predictive-process conditional
  (``predictLatentFactor.R:161-203``).  The reference indexes ``alpha[nf]``
  (the *last* factor's range) for every factor h — a latent bug; we use
  ``alpha[h]`` like the other two branches.
- ``predict_mean`` / ``predict_mean_field`` cheap variants
  (``predictLatentFactor.R:62-92``).
"""

from __future__ import annotations

import numpy as np
from scipy.spatial import cKDTree

__all__ = ["predict_latent_factor"]

_JIT = 1e-8


def _pair_dist(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    d = a[:, None, :] - b[None, :, :]
    return np.sqrt((d**2).sum(-1))


def _dists_for(rL, units, new_units, need22: bool):
    """(D11, D12, D22) between conditioning units and new units, from
    coordinates or a distance matrix."""
    if rL.s is not None:
        s1 = rL.coords_for(units)
        s2 = rL.coords_for(new_units)
        D11 = _pair_dist(s1, s1)
        D12 = _pair_dist(s1, s2)
        D22 = _pair_dist(s2, s2) if need22 else None
    else:
        i1 = [rL._dist_names.index(str(u)) for u in units]
        i2 = [rL._dist_names.index(str(u)) for u in new_units]
        D11 = rL.dist_mat[np.ix_(i1, i1)]
        D12 = rL.dist_mat[np.ix_(i1, i2)]
        D22 = rL.dist_mat[np.ix_(i2, i2)] if need22 else None
    return D11, D12, D22


def predict_latent_factor(units_pred, units, post_eta, post_alpha, rL,
                          predict_mean: bool = False,
                          predict_mean_field: bool = False,
                          rng: np.random.Generator | None = None) -> np.ndarray:
    """Sample Eta at ``units_pred`` conditional on posterior draws at ``units``.

    Parameters mirror the reference, but the posterior enters as stacked
    arrays: ``post_eta`` (n_draws, np, nf) and ``post_alpha`` (n_draws, nf)
    *grid indices* into ``rL.alphapw``.  Returns (n_draws, len(units_pred), nf).
    Factor slots inactive in a draw carry zero loadings downstream, so their
    predicted columns are harmless.
    """
    if predict_mean and predict_mean_field:
        raise ValueError("Hmsc.predictLatentFactor: predictMean and predictMeanField arguments cannot be simultaneously TRUE")
    # deliberately unseeded: omitting `rng` is the caller's explicit opt-out
    # of determinism; pass a Generator to reproduce runs
    rng = rng or np.random.default_rng()  # hmsc: ignore[py-random]
    post_eta = np.asarray(post_eta)
    n_draws, np_old, nf = post_eta.shape
    units = [str(u) for u in units]
    units_pred = [str(u) for u in units_pred]
    pos = {u: i for i, u in enumerate(units)}
    ind_old = np.array([u in pos for u in units_pred], dtype=bool)
    n = len(units_pred)
    out = np.zeros((n_draws, n, nf), dtype=post_eta.dtype)
    if ind_old.any():
        src = [pos[u] for u, o in zip(units_pred, ind_old) if o]
        out[:, ind_old, :] = post_eta[:, src, :]
    new_units = [u for u, o in zip(units_pred, ind_old) if not o]
    nn = len(new_units)
    if nn == 0:
        return out

    if rL.s_dim == 0:
        if predict_mean:
            pass  # zeros
        else:
            out[:, ~ind_old, :] = rng.standard_normal((n_draws, nn, nf))
        return out

    post_alpha = np.asarray(post_alpha, dtype=int)
    if post_alpha.shape != (n_draws, nf):
        post_alpha = np.broadcast_to(post_alpha, (n_draws, nf)).copy()
    alphas = rL.alphapw[:, 0]

    method = rL.spatial_method
    need22 = method == "Full" and not (predict_mean or predict_mean_field)
    if method in ("Full",) or predict_mean or predict_mean_field:
        D11, D12, D22 = _dists_for(rL, units, new_units, need22)
    eta_new = np.empty((n_draws, nn, nf), dtype=post_eta.dtype)
    # (draw, factor) pairs grouped by grid index: one factorisation per value
    flat_alpha = post_alpha.reshape(-1)                     # (n_draws*nf,)
    eta_flat = np.transpose(post_eta, (0, 2, 1)).reshape(-1, np_old)  # (P, np)
    res_flat = np.empty((n_draws * nf, nn), dtype=post_eta.dtype)

    if method == "NNGP" and not (predict_mean or predict_mean_field):
        k = min(int(rL.n_neighbours or 10), np_old)
        s_old = rL.coords_for(units)
        s_new = rL.coords_for(new_units)
        tree = cKDTree(s_old)
        _, nn_idx = tree.query(s_new, k=k)
        nn_idx = np.atleast_2d(nn_idx)
        if nn_idx.shape[0] != nn:
            nn_idx = nn_idx.reshape(nn, -1)
        d12 = np.sqrt(((s_new[:, None, :] - s_old[nn_idx]) ** 2).sum(-1))  # (nn, k)
        d11 = np.sqrt(((s_old[nn_idx][:, :, None, :]
                        - s_old[nn_idx][:, None, :, :]) ** 2).sum(-1))     # (nn,k,k)
    if method == "GPP" and not (predict_mean or predict_mean_field):
        knots = rL.s_knot
        dss = _pair_dist(knots, knots)
        dns = _pair_dist(rL.coords_for(new_units), knots)
        dos = _pair_dist(rL.coords_for(units), knots)

    for g in np.unique(flat_alpha):
        sel = np.nonzero(flat_alpha == g)[0]
        a = alphas[g]
        P = len(sel)
        if a == 0:
            res_flat[sel] = (np.zeros((P, nn)) if predict_mean
                             else rng.standard_normal((P, nn)))
            continue
        E = eta_flat[sel]                                   # (P, np_old)
        if predict_mean or predict_mean_field:
            K11 = np.exp(-D11 / a) + _JIT * np.eye(np_old)
            K12 = np.exp(-D12 / a)
            A = np.linalg.solve(K11, K12)                   # (np_old, nn)
            M = E @ A
            if predict_mean:
                res_flat[sel] = M
            else:
                L11 = np.linalg.cholesky(K11)
                iLK = np.linalg.solve(L11, K12)
                v = np.maximum(1.0 - (iLK**2).sum(axis=0), 0.0)
                res_flat[sel] = M + np.sqrt(v)[None, :] * rng.standard_normal((P, nn))
        elif method == "Full":
            K11 = np.exp(-D11 / a) + _JIT * np.eye(np_old)
            K12 = np.exp(-D12 / a)
            K22 = np.exp(-D22 / a)
            A = np.linalg.solve(K11, K12)
            M = E @ A                                       # (P, nn)
            W = K22 - K12.T @ A
            Lw = np.linalg.cholesky(W + _JIT * np.eye(nn))
            res_flat[sel] = M + rng.standard_normal((P, nn)) @ Lw.T
        elif method == "NNGP":
            K12 = np.exp(-d12 / a)                          # (nn, k)
            K11 = np.exp(-d11 / a) + _JIT * np.eye(d11.shape[-1])[None]
            v = np.linalg.solve(K11, K12[..., None])[..., 0]  # (nn, k)
            Fvar = np.maximum(1.0 - (v * K12).sum(-1), 0.0)   # (nn,)
            # mean: sum over neighbours of coeff * eta at neighbour
            M = np.einsum("pik,ik->pi", E[:, nn_idx], v)     # (P, nn)
            res_flat[sel] = M + np.sqrt(Fvar)[None, :] * rng.standard_normal((P, nn))
        elif method == "GPP":
            nK = knots.shape[0]
            Wss = np.exp(-dss / a) + _JIT * np.eye(nK)
            Wns = np.exp(-dns / a)                          # (nn, nK)
            W12 = np.exp(-dos / a)                          # (np_old, nK)
            iWss = np.linalg.inv(Wss)
            WnsiWss = Wns @ iWss
            dDn = np.maximum(1.0 - (WnsiWss * Wns).sum(-1), 0.0)
            dD = np.maximum(1.0 - np.einsum("ik,kl,il->i", W12, iWss, W12), 1e-12)
            idDW12 = W12 / dD[:, None]
            Fmat = Wss + W12.T @ idDW12
            iF = np.linalg.inv(Fmat)
            LiF = np.linalg.cholesky(iF + _JIT * np.eye(nK))
            muS = (E @ idDW12) @ iF.T                       # (P, nK)
            epsS = rng.standard_normal((P, nK)) @ LiF.T
            M = (muS + epsS) @ Wns.T                        # (P, nn)
            res_flat[sel] = M + np.sqrt(dDn)[None, :] * rng.standard_normal((P, nn))
        else:  # pragma: no cover
            raise ValueError(f"unknown spatial method {method}")

    eta_new[:] = res_flat.reshape(n_draws, nf, nn).transpose(0, 2, 1)
    out[:, ~ind_old, :] = eta_new
    return out
