"""Cross-validation layer (reference ``R/computePredictedValues.R:52-145``,
``R/createPartition.R:16-37``).

The fold refits are full ``sample_mcmc`` runs — already one jitted,
chain-vmapped program each — so k-fold CV is k compiled executions, the
embarrassingly parallel workload SURVEY.md §3.4 highlights.  The per-fold
model rebuild copies the parent's scaling parameters exactly like the
reference (``computePredictedValues.R:95-116``).  One reference bug is fixed
rather than replicated: ``computePredictedValues.R:94`` passes ``hM$rhowp``
(a typo, always NULL) so the reference's CV refits silently lose a custom
rho prior — we pass the parent's ``rhopw``.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

__all__ = ["create_partition", "compute_predicted_values"]


def create_partition(hM, nfolds: int = 10, column=None,
                     rng: np.random.Generator | None = None) -> np.ndarray:
    """Random fold assignment per sampling unit, optionally grouping rows by
    a study-design column so a unit's rows share a fold."""
    # deliberately unseeded: omitting `rng` is the caller's explicit opt-out
    # of determinism; pass a Generator to reproduce runs
    rng = rng or np.random.default_rng()  # hmsc: ignore[py-random]
    if column is not None:
        if hM.nr == 0 and not hasattr(hM, "study_design"):
            raise ValueError("HMSC.createPartition: nfolds cannot exceed the number of units in the specified random level")
        r = column if isinstance(column, int) else hM.rl_names.index(column)
        labels = np.asarray(hM.df_pi[r])
        units = sorted(set(labels))
        if len(units) < nfolds:
            raise ValueError("HMSC.createPartition: nfolds cannot exceed the number of units in the specified random level")
        tags = np.resize(np.arange(1, nfolds + 1), len(units))
        rng.shuffle(tags)
        lut = dict(zip(units, tags))
        return np.array([lut[v] for v in labels], dtype=int)
    if hM.ny < nfolds:
        raise ValueError("HMSC.createPartition: nfolds cannot exceed the number of sampling units")
    tags = np.resize(np.arange(1, nfolds + 1), hM.ny)
    rng.shuffle(tags)
    return tags


def _fold_model(hM, train: np.ndarray):
    """Rebuild the model on the training rows, copying the parent's scaling
    parameters and priors (reference ``computePredictedValues.R:92-116``)."""
    from ..model import Hmsc, set_priors

    X_train = hM.X[:, train, :] if hM.x_is_list else hM.X[train]
    sd = None
    if hM.nr > 0:
        sd = pd.DataFrame({name: np.asarray(hM.df_pi[r])[train]
                           for r, name in enumerate(hM.rl_names)})
    hM1 = Hmsc(
        Y=hM.Y[train], X=list(X_train) if hM.x_is_list else X_train,
        x_scale=False, y_scale=False, tr_scale=False,
        XRRR=None if hM.nc_rrr == 0 else hM.XRRR[train],
        nc_rrr=hM.nc_rrr, xrrr_scale=False,
        x_select=hM.x_select or None,
        Tr=hM.Tr, C=hM.C, distr=hM.distr,
        study_design=sd,
        ran_levels={n: rl for n, rl in zip(hM.rl_names, hM.ranLevels)})
    set_priors(hM1, V0=hM.V0, f0=hM.f0, mGamma=hM.mGamma, UGamma=hM.UGamma,
               aSigma=hM.aSigma, bSigma=hM.bSigma,
               rhopw=hM.rhopw if hM.C is not None else None)
    # copy the parent's scaling state verbatim
    hM1.x_scale_par = hM.x_scale_par
    hM1.x_intercept_ind = hM.x_intercept_ind
    xs = (hM.XScaled[:, train, :] if hM.x_is_list else hM.XScaled[train])
    hM1.XScaled = xs
    hM1.tr_scale_par = hM.tr_scale_par
    hM1.tr_intercept_ind = hM.tr_intercept_ind
    hM1.TrScaled = hM.TrScaled
    hM1.y_scale_par = hM.y_scale_par
    hM1.YScaled = hM.YScaled[train]
    if hM.nc_rrr > 0:
        hM1.xrrr_scale_par = hM.xrrr_scale_par
        hM1.XRRRScaled = hM.XRRRScaled[train]
    hM1.sp_names = hM.sp_names
    hM1.cov_names = hM.cov_names
    return hM1


def compute_predicted_values(post, partition=None, partition_sp=None,
                             start: int = 0, thin: int = 1, Yc=None,
                             mcmc_step: int = 1, expected: bool = True,
                             init_par=None, n_chains: int | None = None,
                             updater: dict | None = None,
                             nf_cap: int | None = None,
                             seed: int | None = None,
                             nfolds: int | None = None,
                             verbose: bool = True) -> np.ndarray:
    """Posterior-predictive values; (n_draws, ny, ns).

    Without ``partition``: predictions on the training data.  With a
    partition vector (from :func:`create_partition`): k-fold CV with a full
    refit per fold; ``partition_sp`` additionally predicts each species fold
    conditional on the remaining species (``Yc`` machinery).  Passing
    ``nfolds`` (with ``partition=None``) draws the partition HERE from the
    same seeded Generator that seeds the fold refits — one ``seed``
    reproduces the whole CV end-to-end (fold vector, refits, predictions);
    the fleet scenario engine mirrors exactly this consumption order.
    """
    from ..mcmc.sampler import sample_mcmc
    from ..mcmc.structs import DEFAULT_NF_CAP
    from .predict import predict

    hM = post.hM
    rng = np.random.default_rng(seed)
    post = post.subset(start, thin)
    if partition is None and nfolds is not None:
        # the partition draw comes FIRST off the seeded stream, before any
        # fold's fit/predict seeds — the scenario workers replay this order
        partition = create_partition(hM, int(nfolds), rng=rng)
    if partition is None:
        return predict(post, Yc=Yc, mcmc_step=mcmc_step, expected=expected,
                       seed=None if seed is None else int(rng.integers(2**31)))

    partition = np.asarray(partition)
    if partition.size != hM.ny:
        raise ValueError("HMSC.computePredictedValues: partition parameter must be a vector of length ny")
    folds = np.unique(partition)
    n_chains = n_chains or post.n_chains
    post_n = post.samples * n_chains
    pred_array = np.full((post_n, hM.ny, hM.ns), np.nan)

    def _fill_rows(pred):
        """Pad a fold's posterior-predictive draws back to post_n rows when a
        refit chain diverged (pooled() excludes it): cycle the healthy draws
        so the fold's Monte-Carlo estimate stays valid and the shared
        pred_array keeps one fixed draw axis."""
        if pred.shape[0] == post_n:
            return pred
        return pred[np.resize(np.arange(pred.shape[0]), post_n)]

    from ..obs import get_logger
    log = get_logger()
    for ki, k in enumerate(folds):
        if verbose:
            log.info(f"Cross-validation, fold {ki + 1} out of {len(folds)}")
        train = partition != k
        val = partition == k
        hM1 = _fold_model(hM, train)
        post1 = sample_mcmc(
            hM1, samples=post.samples, thin=post.thin,
            transient=post.transient, n_chains=n_chains, init_par=init_par,
            updater=updater, nf_cap=nf_cap or DEFAULT_NF_CAP,
            seed=int(rng.integers(2**31)))
        if not post1.chain_health["good_chains"].any():
            # good_chain_mask() falls back to "exclude nothing" when every
            # chain diverged, so this must be caught here, loudly, before
            # NaN draws flow into the shared pred_array
            raise RuntimeError(
                f"cross-validation fold {ki + 1}: every refit chain "
                "diverged; no finite draws to predict from")
        sd_val = (pd.DataFrame({name: np.asarray(hM.df_pi[r])[val]
                                for r, name in enumerate(hM.rl_names)})
                  if hM.nr > 0 else None)
        X_val = (list(hM.X[:, val, :]) if hM.x_is_list else hM.X[val])
        XRRR_val = None if hM.nc_rrr == 0 else hM.XRRR[val]
        if partition_sp is None:
            pred = _fill_rows(predict(
                post1, X=X_val, XRRR=XRRR_val, study_design=sd_val,
                Yc=None if Yc is None else Yc[val],
                mcmc_step=mcmc_step, expected=expected,
                seed=int(rng.integers(2**31))))
        else:
            partition_sp = np.asarray(partition_sp)
            pred = np.full((post_n, int(val.sum()), hM.ns), np.nan)
            for i in np.unique(partition_sp):
                val_sp = partition_sp == i
                Yc_i = np.full((int(val.sum()), hM.ns), np.nan)
                Yc_i[:, ~val_sp] = hM.Y[np.ix_(val, ~val_sp)]
                pred2 = _fill_rows(predict(
                    post1, X=X_val, XRRR=XRRR_val,
                    study_design=sd_val, Yc=Yc_i,
                    mcmc_step=mcmc_step, expected=expected,
                    seed=int(rng.integers(2**31))))
                pred[:, :, val_sp] = pred2[:, :, val_sp]
        pred_array[:, val, :] = pred
    return pred_array
