"""Posterior-predictive distribution at new covariates / units (reference
``R/predict.R:55-232``).

TPU-first restructuring: the reference loops over posterior samples, building
one ny x ns linear predictor per R iteration.  Here the whole posterior is one
stacked (n_draws, ...) batch — the linear predictor, link transform and
response sampling are single batched einsums / elementwise ops over all draws
at once, and the conditional-prediction MCMC refinement (``Yc`` +
``mcmc_step``, reference ``predict.R:181-198``) is a jitted
``lax.scan`` vmapped over draws instead of an interpreted per-sample loop.

Deviations from the reference (latent bugs there):

- conditional prediction on *spatial* levels: the reference passes
  ``rLPar=object$rLPar`` which is never populated (``predict.R:185``), so its
  spatial conditional updates crash.  Here the conditional Eta refresh uses
  the level's *actual* GP prior, per spatial method and at any scale:

  * ``NNGP`` — Vecchia neighbour structures built over the prediction units
    at the alpha grid values visited by the posterior, applied matrix-free
    inside a CG sampler (same perturbation-optimisation draw as the
    training-side ``mcmc/spatial._eta_nngp_cg``) — the >1000-unit regime the
    reference recommends NNGP for works at prediction time too;
  * ``GPP`` — knot-based double-Woodbury draw over the prediction units
    (the training-side ``_eta_gpp`` structure);
  * ``Full`` (and any spatial level with covariate-dependent loadings) —
    exact exponential-kernel precision per draw, joint (np x nf) system,
    processed in draw chunks sized to memory up to
    ``_SPATIAL_COND_DENSE_MAX`` coefficients.

  Only a dense level beyond ``_SPATIAL_COND_DENSE_MAX`` falls back to the
  unstructured N(0,1) prior, and that downgrade emits a ``RuntimeWarning``.
  Non-spatial levels use the N(0,1) prior (exact for them).
- ``predict.R:174,192`` uses ``object$ny`` where the new-data row count
  belongs; we use the new row count.
"""

from __future__ import annotations

import numpy as np

from ..utils.formula import align_factor_levels, design_matrix
from .latent import predict_latent_factor

__all__ = ["predict"]

# above this many (units x factors) coefficients, a *dense* spatial level
# (Full, or covariate-dependent NNGP/GPP) falls back to the unstructured
# prior with a RuntimeWarning; NNGP/GPP levels with unit loadings use their
# own sparse structure and have no cap
_SPATIAL_COND_DENSE_MAX = 20000
# device-memory budget (bytes) for the per-chunk joint dense precisions in
# the conditional refresh; sets how many posterior draws vmap together
_COND_DENSE_MEM_BUDGET = 2.5e9


def _new_design(hM, x_data, X):
    """Resolve the prediction design matrix like the reference's
    model.matrix-with-pinned-xlev step (``predict.R:76-90``)."""
    if x_data is not None and X is not None:
        raise ValueError("Hmsc.predict: only one of XData and X arguments can be specified")
    if x_data is not None:
        # pin the TRAINING frame's factor levels (R's xlev): a prediction
        # frame holding a subset of a categorical's fitted levels — e.g. a
        # gradient frame's constant non-focal factor — must still expand
        # to the fitted design's column count
        ref = hM.x_data
        if isinstance(x_data, (list, tuple)):
            refs = (ref if isinstance(ref, (list, tuple))
                    else [ref] * len(x_data))
            mats = [design_matrix(hM.x_formula,
                                  align_factor_levels(df, rf))[0]
                    for df, rf in zip(x_data, refs)]
            return np.stack(mats, axis=0), True
        M, _ = design_matrix(
            hM.x_formula,
            align_factor_levels(x_data,
                                ref[0] if isinstance(ref, (list, tuple))
                                else ref))
        return M, False
    if X is not None:
        X = np.asarray(X, dtype=float)
        return X, X.ndim == 3
    return hM.X, hM.x_is_list


def predict(post, x_data=None, X=None, xrrr_data=None, XRRR=None,
            study_design=None, ran_levels=None, gradient=None, Yc=None,
            mcmc_step: int = 1, expected: bool = False,
            predict_eta_mean: bool = False, predict_eta_mean_field: bool = False,
            seed: int | None = None) -> np.ndarray:
    """Posterior-predictive draws; returns (n_draws, ny_new, ns).

    ``post`` is the :class:`~hmsc_tpu.post.Posterior` from ``sample_mcmc``
    (all pooled draws are used).  With ``expected=True`` the location
    parameter of each observation model is returned instead of sampled
    responses; ``Yc`` enables conditional prediction refined by ``mcmc_step``
    extra MCMC iterations of the latent factors.
    """
    hM, spec = post.hM, post.spec
    rng = np.random.default_rng(seed)

    if gradient is not None:
        x_data = gradient["XDataNew"]
        study_design = gradient["studyDesignNew"]
        ran_levels = gradient["rLNew"]
    if xrrr_data is not None and XRRR is not None:
        raise ValueError("Hmsc.predict: only one of XRRRData and XRRR arguments can be specified")
    if predict_eta_mean and predict_eta_mean_field:
        raise ValueError("Hmsc.predict: predictEtaMean and predictEtaMeanField arguments cannot be TRUE simultanuisly")

    Xn, x_is_list = _new_design(hM, x_data, X)
    ny_new = Xn.shape[1] if x_is_list else Xn.shape[0]
    if hM.nc_rrr > 0:
        if xrrr_data is not None:
            XRRR, _ = design_matrix(hM.xrrr_formula if hasattr(hM, "xrrr_formula") else "~.-1", xrrr_data)
        if XRRR is None:
            XRRR = hM.XRRR
        XRRR = np.asarray(XRRR, dtype=float)

    if Yc is not None:
        Yc = np.asarray(Yc, dtype=float)
        if Yc.shape[1] != hM.ns:
            raise ValueError("hMsc.predict: number of columns in Yc must be equal to ns")
        if Yc.shape[0] != ny_new:
            raise ValueError("hMsc.predict: number of rows in Yc and X must be equal")

    # ---- study design -> per-level unit labels and row indices -----------
    if ran_levels is None:
        ran_levels = {hM.rl_names[r]: hM.ranLevels[r] for r in range(hM.nr)}
    if study_design is None:
        labels = hM.df_pi                               # training labels
    else:
        cols = ([str(c) for c in study_design.columns]
                if hasattr(study_design, "columns") else None)
        if cols is not None and any(n not in cols for n in hM.rl_names):
            raise ValueError("hMsc.predict: dfPiNew does not contain all the necessary named columns")
        labels = []
        for r, name in enumerate(hM.rl_names):
            col = (study_design[name] if cols is not None
                   else np.asarray(study_design)[:, r])
            labels.append([str(v) for v in np.asarray(col)])
    if any(n not in ran_levels for n in hM.rl_names):
        raise ValueError("hMsc.predict: rL does not contain all the necessary named levels")

    Beta = post.pooled("Beta")                          # (n, nc, ns)
    sigma = post.pooled("sigma")                        # (n, ns)

    # ---- latent factors at prediction units ------------------------------
    will_condition = Yc is not None and not np.all(np.isnan(Yc))
    eta_pred, pi_new, x_row_new, spatial_prior = [], [], [], []
    for r in range(hM.nr):
        rL = ran_levels[hM.rl_names[r]]
        units_pred = sorted(set(labels[r]))
        post_eta = post.pooled(f"Eta_{r}")              # (n, np, nf)
        post_alpha = post.pooled(f"Alpha_{r}")          # (n, nf) grid indices
        ep = predict_latent_factor(units_pred, hM.pi_names[r], post_eta,
                                   post_alpha, rL,
                                   predict_mean=predict_eta_mean,
                                   predict_mean_field=predict_eta_mean_field,
                                   rng=rng)
        lut = {u: i for i, u in enumerate(units_pred)}
        eta_pred.append(ep)
        pi_new.append(np.array([lut[v] for v in labels[r]], dtype=np.int32))
        if spec.levels[r].x_dim > 0:
            x_row_new.append(rL.x_for(labels[r]))
        else:
            x_row_new.append(np.ones((ny_new, 1)))

        # spatial levels: per-method prior structures over the units_pred
        # ordering, at the alpha grid values the posterior actually visits
        # (see module docstring and _spatial_cond_info)
        spatial_prior.append(_spatial_cond_info(
            hM, spec, rL, r, units_pred, post_alpha, will_condition))

    L = _lin_pred(hM, spec, Xn, x_is_list, XRRR, post, Beta, eta_pred, pi_new,
                  x_row_new)

    # ---- conditional prediction: refine Eta with extra MCMC steps --------
    if will_condition:
        eta_pred = _conditional_mcmc(hM, spec, post, Xn, x_is_list, XRRR, Beta,
                                     sigma, Yc, eta_pred, pi_new, x_row_new, L,
                                     mcmc_step, rng, spatial_prior)
        L = _lin_pred(hM, spec, Xn, x_is_list, XRRR, post, Beta, eta_pred,
                      pi_new, x_row_new)

    # ---- observation model: link + response sampling ---------------------
    # (keep everything in the posterior's f32: the (n, ny, ns) block is
    # ~1 GB at the 1000-species scale and the f64 upcasts scipy/np.random
    # default to double both memory traffic and wall-clock)
    if expected:
        Z = L
    else:
        eps = rng.standard_normal(L.shape, dtype=L.dtype) \
            if np.issubdtype(L.dtype, np.floating) else rng.standard_normal(L.shape)
        Z = L + np.sqrt(sigma)[:, None, :] * eps
    fam = hM.distr[:, 0][None, None, :]
    out = Z.copy()
    probit = fam == 2
    if probit.any():
        if expected:
            from scipy.special import ndtr
            out = np.where(probit, ndtr(Z).astype(Z.dtype, copy=False), out)
        else:
            out = np.where(probit, (Z > 0).astype(Z.dtype), out)
    pois = fam == 3
    if pois.any():
        lam = np.exp(np.clip(Z, None, 30.0))
        if expected:
            out = np.where(pois, np.exp(Z + sigma[:, None, :] / 2), out)
        else:
            out = np.where(pois, rng.poisson(lam).astype(Z.dtype), out)
    # Y back-scaling (predict.R:222-228)
    m, s = hM.y_scale_par
    out = out * s[None, None, :] + m[None, None, :]
    return out


def _lin_pred(hM, spec, Xn, x_is_list, XRRR, post, Beta, eta_pred, pi_new,
              x_row_new) -> np.ndarray:
    """(n_draws, ny_new, ns) linear predictor as ONE jitted program (the
    shared serving kernel, :func:`hmsc_tpu.serve.kernels.linear_predictor`
    — offline prediction and the serving engine compile the same code;
    repeated predict() calls at one query shape reuse the executable
    instead of re-dispatching each einsum from Python)."""
    from ..serve.kernels import linear_predictor

    lams = [post.pooled(f"Lambda_{r}") for r in range(hM.nr)]
    kw = {}
    if hM.nc_rrr > 0:
        kw = dict(nc_nrrr=hM.nc_nrrr, XRRR=XRRR, wRRR=post.pooled("wRRR"))
    L = linear_predictor(Xn, x_is_list, Beta, etas=eta_pred, pis=pi_new,
                         xrows=x_row_new, lams=lams, **kw)
    return np.asarray(L)


def _spatial_cond_info(hM, spec, rL, r, units_pred, post_alpha,
                       will_condition):
    """Per-level prior descriptor for the conditional Eta refresh.

    Returns ``None`` (unstructured N(0,1) prior — exact for non-spatial
    levels, loudly-warned fallback otherwise), or one of

    - ``("dense", D, alpha_vals)`` — exact exponential-kernel precision per
      draw (Full method, or spatial levels with covariate-dependent
      loadings), bounded by ``_SPATIAL_COND_DENSE_MAX``;
    - ``("nngp", lp, idx)`` — Vecchia neighbour structures over the
      prediction units at the alpha grid values the posterior visits
      (``precompute._nngp_grids``), ``idx`` (n_draws, nf) indices into them;
    - ``("gpp", lp, idx)`` — knot-based grids over the prediction units
      (``precompute._gpp_grids``), same indexing.
    """
    if not will_condition or spec.levels[r].spatial is None:
        return None
    import warnings

    from ..precompute import _gpp_grids, _nngp_grids

    method = rL.spatial_method
    post_alpha = np.asarray(post_alpha)
    n_coef = len(units_pred) * post_alpha.shape[1]
    x0 = spec.levels[r].x_dim == 0
    if method in ("NNGP", "GPP") and x0:
        uniq, inv = np.unique(post_alpha, return_inverse=True)
        alphas = np.asarray(rL.alphapw, dtype=float)[uniq, 0]
        idx = inv.reshape(post_alpha.shape).astype(np.int32)
        s = rL.coords_for(units_pred)
        if method == "NNGP":
            lp = _nngp_grids(s, rL.n_neighbours or 10, alphas)
        else:
            lp = _gpp_grids(s, np.asarray(rL.s_knot, dtype=float), alphas)
        return (method.lower(), lp, idx)
    if n_coef <= _SPATIAL_COND_DENSE_MAX:
        if rL.dist_mat is not None:
            D = rL.dist_for(units_pred)
        else:
            xy = rL.coords_for(units_pred)
            D = np.linalg.norm(xy[:, None, :] - xy[None, :, :], axis=-1)
        alpha_vals = np.asarray(rL.alphapw, dtype=float)[:, 0][post_alpha]
        return ("dense", D, alpha_vals)
    warnings.warn(
        f"conditional prediction: spatial level '{hM.rl_names[r]}' "
        f"({method}{'' if x0 else ', covariate-dependent loadings'}) has "
        f"{n_coef} unit x factor coefficients, beyond the dense-path cap "
        f"{_SPATIAL_COND_DENSE_MAX}; its conditional Eta refresh falls back "
        "to the unstructured N(0,1) prior, so conditional predictions will "
        "be less well calibrated than the training-side spatial model",
        RuntimeWarning, stacklevel=3)
    return None


def _conditional_mcmc(hM, spec, post, Xn, x_is_list, XRRR, Beta, sigma, Yc,
                      eta_pred, pi_new, x_row_new, L, mcmc_step, rng,
                      spatial_prior=None):
    """``mcmc_step`` iterations of (updateEta, updateZ) per posterior draw,
    conditioning on the observed cells of Yc — vmapped over draws and run as
    one jitted scan (reference ``predict.R:181-198``).

    ``spatial_prior[r]`` is a :func:`_spatial_cond_info` descriptor — the Eta
    refresh uses the level's actual GP prior per spatial method (the
    capability the reference intends but crashes on, ``predict.R:185``);
    ``None`` falls back to the unstructured N(0,1) prior.  Draws are
    processed in memory-sized chunks when a dense spatial level is present.
    """
    import warnings

    import jax
    import jax.numpy as jnp

    from ..ops.rand import truncated_normal_onesided

    # scale Yc for y-scaled normal species so it lives on the Z scale
    m, s = hM.y_scale_par
    Ycs = (Yc - m[None, :]) / s[None, :]
    mask = jnp.asarray((~np.isnan(Ycs)).astype(np.float32))
    Yc0 = jnp.asarray(np.nan_to_num(Ycs, nan=0.0), dtype=jnp.float32)
    fam = jnp.asarray(hM.distr[:, 0], dtype=jnp.int32)[None, :]
    any_probit = bool((hM.distr[:, 0] == 2).any())
    any_normal = bool((hM.distr[:, 0] == 1).any())
    any_poisson = bool((hM.distr[:, 0] == 3).any())

    n_draws = Beta.shape[0]
    nf_r = [post.pooled(f"Lambda_{r}").shape[1] for r in range(hM.nr)]
    # padded Lambda is (n, nf, ns, ncr); squeeze the trivial ncr axis for
    # unstructured levels so the shared-precision path applies
    lam_r = []
    for r in range(hM.nr):
        lam = post.pooled(f"Lambda_{r}")
        if lam.ndim == 4 and spec.levels[r].x_dim == 0:
            lam = lam[..., 0]
        lam_r.append(jnp.asarray(lam, dtype=jnp.float32))
    # per-unit covariate values for covariate-dependent levels
    x_unit_r = []
    for r in range(hM.nr):
        npr = eta_pred[r].shape[1]
        xu = np.ones((npr, x_row_new[r].shape[1]))
        xu[pi_new[r]] = x_row_new[r]
        x_unit_r.append(jnp.asarray(xu, dtype=jnp.float32))
    eta_r = [jnp.asarray(eta_pred[r], dtype=jnp.float32) for r in range(hM.nr)]
    pi_r = [jnp.asarray(pi_new[r]) for r in range(hM.nr)]
    xrow_r = [jnp.asarray(x_row_new[r], dtype=jnp.float32) for r in range(hM.nr)]
    np_r = [eta_pred[r].shape[1] for r in range(hM.nr)]
    if spatial_prior is None:
        spatial_prior = [None] * hM.nr
    # prior structures are draw-invariant closures; the per-draw vmapped
    # input is either the alpha *values* (dense: kernel built per draw) or
    # grid *indices* into the precomputed pred-unit structures (nngp/gpp)
    mode_r = [None if sp is None else sp[0] for sp in spatial_prior]
    D_r, nngp_r, gpp_r, alpha_in = [], [], [], []
    for r in range(hM.nr):
        sp = spatial_prior[r]
        D_r.append(None)
        nngp_r.append(None)
        gpp_r.append(None)
        if sp is None:
            alpha_in.append(jnp.zeros((n_draws, nf_r[r]), dtype=jnp.float32))
        elif sp[0] == "dense":
            D_r[r] = jnp.asarray(sp[1], dtype=jnp.float32)
            alpha_in.append(jnp.asarray(sp[2], dtype=jnp.float32))
        elif sp[0] == "nngp":
            lp = sp[1]
            nngp_r[r] = (jnp.asarray(lp.nn_idx, dtype=jnp.int32),
                         jnp.asarray(lp.nn_coef, dtype=jnp.float32),
                         jnp.asarray(lp.nn_D, dtype=jnp.float32))
            alpha_in.append(jnp.asarray(sp[2], dtype=jnp.int32))
        else:  # gpp
            lp = sp[1]
            gpp_r[r] = (jnp.asarray(lp.idDg, dtype=jnp.float32),
                        jnp.asarray(lp.idDW12g, dtype=jnp.float32),
                        jnp.asarray(lp.Fg, dtype=jnp.float32))
            alpha_in.append(jnp.asarray(sp[2], dtype=jnp.int32))
    alpha_r = tuple(alpha_in)
    iSig = jnp.asarray(1.0 / np.asarray(sigma), dtype=jnp.float32)  # (n, ns)
    LFix0 = jnp.asarray(L, dtype=jnp.float32) - sum(
        _loading_np(eta_r[r], pi_r[r], xrow_r[r], lam_r[r])
        for r in range(hM.nr)) if hM.nr else jnp.asarray(L, dtype=jnp.float32)

    def loading(eta, lam, pi, xrow):
        rows = eta[pi]                                  # (ny, nf)
        if lam.ndim == 2:
            return rows @ lam
        return jnp.einsum("yf,yk,fjk->yj", rows, xrow, lam)

    def z_given_yc(E, z_prev, isig, key):
        """One updateZ pass against the observed Yc cells — one key per draw
        site, so families stay independent even if the disjoint-cell layout
        ever changes."""
        k_base, k_probit, k_pg, k_poisz = jax.random.split(key, 4)
        std = isig[None, :] ** -0.5
        z = E + std * jax.random.normal(k_base, E.shape, dtype=E.dtype)
        if any_normal:
            z = jnp.where((fam == 1) & (mask > 0), Yc0, z)
        if any_probit:
            # one-sided truncation, same specialisation as the sweep's updateZ
            ztn = truncated_normal_onesided(k_probit, 0.0, Yc0 > 0.5, E, std)
            z = jnp.where((fam == 2) & (mask > 0), ztn, z)
        if any_poisson:
            from ..ops.rand import polya_gamma
            logr = jnp.log(1e3)
            w = polya_gamma(k_pg, Yc0 + 1e3, z_prev - logr)
            prec_z = isig[None, :]
            s2 = 1.0 / (prec_z + w)
            mu = s2 * ((Yc0 - 1e3) / 2.0 + prec_z * (E - logr)) + logr
            zp = mu + jnp.sqrt(s2) * jax.random.normal(k_poisz, mu.shape,
                                                       dtype=mu.dtype)
            z = jnp.where((fam == 3) & (mask > 0), zp, z)
        return z

    def one_draw(LFix, lams, etas, isig, alphas, key):
        from jax.scipy.linalg import cho_solve, solve_triangular

        # step-invariant per level: the likelihood gram LiSL (lam/isig/mask
        # only) and the factorisation / closures of the full-conditional
        # precision — dense spatial: joint blkdiag_f(iW(alpha_f)) + unit
        # blocks (the training-side spatial updateEta structure, reference
        # updateEta.R:110-135); nngp: Vecchia factor gathered at each
        # factor's alpha (applied matrix-free, as mcmc/spatial._eta_nngp_cg);
        # gpp: double-Woodbury blocks (as mcmc/spatial._eta_gpp);
        # unstructured: per-unit nf x nf.  Only the rhs changes across the
        # mcmc_step scan, so factorise once per posterior draw.
        lam2_r, solver_r = [], []
        for r in range(hM.nr):
            lam = lams[r]
            lam2 = lam if lam.ndim == 2 else jnp.einsum(
                "fjk,uk->ufj", lam, x_unit_r[r])
            if lam.ndim == 2:
                rows = jnp.einsum("fj,gj,j,ij->ifg", lam, lam, isig, mask)
                LiSL = jax.ops.segment_sum(rows, pi_r[r],
                                           num_segments=np_r[r])
            else:
                Mu_cnt = jax.ops.segment_sum(mask, pi_r[r],
                                             num_segments=np_r[r])
                LiSL = jnp.einsum("ufj,ugj,j,uj->ufg", lam2, lam2, isig,
                                  Mu_cnt)
            lam2_r.append(lam2)
            npr, nf = np_r[r], nf_r[r]
            if mode_r[r] == "dense":
                D = D_r[r]
                eyeu = jnp.eye(npr, dtype=D.dtype)

                def iW_of(a):
                    safe = jnp.maximum(a, 1e-6)
                    W = jnp.where(a > 0, jnp.exp(-D / safe), eyeu)
                    W = W + 1e-5 * eyeu       # f32 far-range conditioning
                    Lw = jnp.linalg.cholesky(W)
                    return cho_solve((Lw, True), eyeu)

                iW = jax.vmap(iW_of)(alphas[r])       # (nf, np, np)
                P4 = jnp.einsum("fuv,fg->ufvg", iW,
                                jnp.eye(nf, dtype=D.dtype))
                u_idx = jnp.arange(npr)
                P4 = P4.at[u_idx, :, u_idx, :].add(LiSL)
                solver_r.append(("dense", jnp.linalg.cholesky(
                    P4.reshape(npr * nf, npr * nf))))
            elif mode_r[r] == "nngp":
                from ..mcmc.spatial import vecchia_ops
                nn, coef_g, Dg = nngp_r[r]
                coef = coef_g[alphas[r]]              # (nf, np, k)
                sqD = jnp.sqrt(Dg[alphas[r]])         # (nf, np)
                solver_r.append(("nngp", vecchia_ops(nn, coef, sqD, LiSL)))
            elif mode_r[r] == "gpp":
                from ..mcmc.spatial import gpp_factor
                idDg, M1g, Fg = gpp_r[r]
                # pred-unit grids degrade to the identity prior naturally at
                # alpha=0 (W12=0, dD=1 in precompute._gpp_grids) — no guard
                solver_r.append(("gpp", gpp_factor(
                    LiSL, idDg[alphas[r]], M1g[alphas[r]], Fg[alphas[r]])))
            else:
                solver_r.append(("none", jnp.linalg.cholesky(
                    LiSL + jnp.eye(nf, dtype=LiSL.dtype)[None])))

        def step(carry, k):
            z, etas, fail = carry
            kz = jax.random.fold_in(k, 0)
            # Eta update per level (the level's GP prior where available,
            # N(0,1) otherwise; see module docstring)
            for r in range(hM.nr):
                others = sum(loading(etas[q], lams[q], pi_r[q], xrow_r[q])
                             for q in range(hM.nr) if q != r)
                S = z - LFix - (others if hM.nr > 1 else 0.0)
                lam = lams[r]
                if lam.ndim == 2:
                    # NA-aware rhs (Yc cells outside the mask carry no
                    # likelihood weight)
                    Fr = jax.ops.segment_sum((S * isig[None, :] * mask) @ lam.T,
                                             pi_r[r], num_segments=np_r[r])
                else:
                    T = jax.ops.segment_sum(S * isig[None, :] * mask, pi_r[r],
                                            num_segments=np_r[r])
                    Fr = jnp.einsum("uj,ufj->uf", T, lam2_r[r])
                npr, nf = np_r[r], nf_r[r]
                mode, payload = solver_r[r]
                kr = jax.random.fold_in(k, 1 + r)
                if mode == "dense":
                    Lc = payload
                    rhs = Fr.reshape(npr * nf)
                    mean = cho_solve((Lc, True), rhs)
                    eps = jax.random.normal(kr, rhs.shape, dtype=rhs.dtype)
                    noise = solve_triangular(Lc.T, eps, lower=False)
                    eta_new = (mean + noise).reshape(npr, nf)
                elif mode == "nngp":
                    from ..mcmc.spatial import vecchia_cg_draw
                    riw_t, pmv = payload
                    ka, kb = jax.random.split(kr)
                    eps1 = jax.random.normal(ka, (npr, nf), dtype=Fr.dtype)
                    xi = jax.random.normal(kb, mask.shape, dtype=Fr.dtype)
                    b_like = jax.ops.segment_sum(
                        (xi * jnp.sqrt(isig)[None, :] * mask) @ lam.T,
                        pi_r[r], num_segments=npr)
                    eta_new, res = vecchia_cg_draw(riw_t, pmv, Fr, b_like,
                                                   eps1, x0=etas[r])
                    # count stalled solves; the maxiter iterate is kept (an
                    # approximate draw) and the host warns post-run
                    fail = fail + (res >= 1e-3).astype(jnp.int32)
                elif mode == "gpp":
                    from ..mcmc.spatial import gpp_draw
                    nK = payload[-1]
                    ka, kb = jax.random.split(kr)
                    eps1 = jax.random.normal(ka, (npr, nf), dtype=Fr.dtype)
                    eps2 = jax.random.normal(kb, (nf * nK,), dtype=Fr.dtype)
                    eta_new = gpp_draw(payload, Fr, eps1, eps2)
                else:
                    Lc = payload
                    mean = cho_solve((Lc, True), Fr[..., None])[..., 0]
                    eps = jax.random.normal(kr, mean.shape, dtype=mean.dtype)
                    noise = solve_triangular(jnp.swapaxes(Lc, -1, -2),
                                             eps[..., None], lower=False)[..., 0]
                    eta_new = mean + noise
                etas = etas[:r] + (eta_new,) + etas[r + 1:]
            # Z update against Yc
            E = LFix + sum(loading(etas[r], lams[r], pi_r[r], xrow_r[r])
                           for r in range(hM.nr))
            z = z_given_yc(E, z, isig, kz)
            return (z, etas, fail), None

        # initial Z draw against Yc before the refinement loop, mirroring
        # the reference's Z = updateZ(...) at predict.R:183 — so even
        # mcmc_step=1 refines Eta against Yc-informed Z
        E0 = LFix + sum(loading(etas[r], lams[r], pi_r[r], xrow_r[r])
                        for r in range(hM.nr))
        key, k0 = jax.random.split(key)
        z0 = z_given_yc(E0, E0, isig, k0)
        keys = jax.random.split(key, mcmc_step)
        fail0 = jnp.zeros((), dtype=jnp.int32)
        (z, etas, fail), _ = jax.lax.scan(step, (z0, etas, fail0), keys)
        return etas, fail

    keys = jax.vmap(jax.random.PRNGKey)(
        jnp.asarray(rng.integers(0, 2**31 - 1, size=n_draws)))
    etas0 = tuple(eta_r)
    run = jax.jit(jax.vmap(one_draw, in_axes=(0, 0, 0, 0, 0, 0)))
    args = (LFix0, tuple(lam_r), etas0, iSig, alpha_r, keys)

    # dense spatial levels hold a (np*nf)^2 joint precision per draw; chunk
    # the draw axis so the vmapped working set stays inside the budget
    dense_bytes = sum((np_r[r] * nf_r[r]) ** 2 * 4
                      for r in range(hM.nr) if mode_r[r] == "dense")
    chunk = n_draws if not dense_bytes else max(
        1, min(n_draws, int(_COND_DENSE_MEM_BUDGET // (dense_bytes * 3))))
    if chunk >= n_draws:
        etas_out, fails = run(*args)
        n_fail = int(np.asarray(fails).sum())
        etas_list = [np.asarray(e) for e in etas_out]
    else:
        # pad to a whole number of chunks: one compiled shape, drop the tail
        n_pad = -(-n_draws // chunk) * chunk
        sel = jnp.asarray(np.r_[np.arange(n_draws),
                                np.full(n_pad - n_draws, n_draws - 1)])
        args = jax.tree.map(lambda a: a[sel], args)
        outs, n_fail = [], 0
        for c0 in range(0, n_pad, chunk):
            eo, fl = run(*jax.tree.map(lambda a: a[c0:c0 + chunk], args))
            outs.append([np.asarray(e) for e in eo])
            # padded duplicates re-run real draws; don't double-count their
            # stalls
            real = (c0 + np.arange(chunk)) < n_draws
            n_fail += int(np.asarray(fl)[real].sum())
        etas_list = [np.concatenate([o[r] for o in outs], axis=0)[:n_draws]
                     for r in range(hM.nr)]
    if n_fail:
        warnings.warn(
            f"conditional prediction: the NNGP Eta CG solve stalled in "
            f"{n_fail} (draw, step, level) instances; those draws keep the "
            "maxiter iterate (an approximate refresh)", RuntimeWarning,
            stacklevel=3)
    return etas_list


def _loading_np(eta, pi, xrow, lam):
    import jax.numpy as jnp
    rows = eta[:, pi, :]
    if lam.ndim == 3:
        return jnp.einsum("nyf,nfj->nyj", rows, lam)
    return jnp.einsum("nyf,yk,nfjk->nyj", rows, xrow, lam)
