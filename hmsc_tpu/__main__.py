"""``python -m hmsc_tpu`` — installed-package CLI.

Subcommands: ``bench`` (default; the throughput probe, same entry as the
``hmsc-tpu-bench`` console script), ``run`` (checkpointed, preemption-safe
long-run driver with ``--resume``), ``report`` (render a run's
telemetry — phase timeline, throughput, cross-rank skew, checkpoint I/O
and MCMC health — from its ``events-p<rank>.jsonl`` streams; ``--prom``
exports Prometheus textfile gauges), ``watch`` (the LIVE counterpart of
``report``: tail every event stream under a watch root into one
fleet-wide view with SLO alert rules — see README "Observability"),
``lint`` (the static correctness
suite: AST lint + jaxpr audits, see ``ANALYSIS.md``; exit 1 on any active
severity=error finding), ``profile`` (sweep-level cost attribution: the
static per-updater flops/HBM ledger with its committed diffable digest,
and measured per-updater wall timing — see README "Profiling"),
``compact`` (thin + re-shard a fitted run into a
serving-optimised artifact, optionally bf16), ``serve`` (long-lived
HTTP posterior-serving engine: compile-cached bucketed predict kernels +
micro-batching, see README "Serving"), ``fleet`` (elastic fleet
supervisor: spawn R worker ranks, heartbeat liveness, backoff restarts,
shrink/grow degradation — see README "Elastic fleet runs"), and ``refit``
(streaming refits: append new survey rows to a fitted run, warm-start
chains, adaptive abbreviated transient, commit a new serving epoch — see
README "Streaming refits"), and ``autopilot`` (the continuous-learning
daemon: watch a drop directory, validate/quarantine data batches, drive
supervised refits, flip serving, retain/compact epochs — see README
"Continuous learning (autopilot)").  Bare arguments keep the historical
bench behaviour: ``python -m hmsc_tpu --ns 50`` still works.
"""

import sys

from .bench_cli import main as bench_main
from .bench_cli import run_main


def main(argv=None):
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv[:1] == ["run"]:
        return run_main(argv[1:])
    if argv[:1] == ["report"]:
        from .obs.report import report_main
        return report_main(argv[1:])
    if argv[:1] == ["watch"]:
        from .obs.hub import watch_main
        return watch_main(argv[1:])
    if argv[:1] == ["lint"]:
        from .analysis.cli import lint_main
        return lint_main(argv[1:])
    if argv[:1] == ["profile"]:
        from .obs.profile import profile_main
        return profile_main(argv[1:])
    if argv[:1] == ["compact"]:
        from .serve.artifact import compact_main
        return compact_main(argv[1:])
    if argv[:1] == ["serve"]:
        from .serve.http import serve_main
        return serve_main(argv[1:])
    if argv[:1] == ["fleet"]:
        from .fleet.cli import fleet_main
        return fleet_main(argv[1:])
    if argv[:1] == ["refit"]:
        from .refit.cli import refit_main
        return refit_main(argv[1:])
    if argv[:1] == ["autopilot"]:
        from .pipeline.cli import autopilot_main
        return autopilot_main(argv[1:])
    if argv[:1] == ["bench"]:
        argv = argv[1:]
    return bench_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
