"""``python -m hmsc_tpu`` — the installed-package throughput probe
(same entry as the ``hmsc-tpu-bench`` console script)."""

from .bench_cli import main

if __name__ == "__main__":
    raise SystemExit(main())
