"""MCMC health metrics: incremental R-hat/ESS over a monitored subset.

The sampler emits a ``segment_health`` telemetry event per flushed segment
(throughput, divergence counters, nf-adaptation trajectory) including a
*running* split-R-hat / ESS computed host-side from the draws flushed so
far — the persisted per-draw diagnostics idiom of ArviZ (Kumar et al.,
JOSS 2019), kept cheap by monitoring a small fixed parameter subset
instead of the full posterior.  The same machinery (:func:`rhat_ess`)
backs ``benchmarks/diag_mixing.py``'s full-array post-hoc pass, so there
is exactly one R-hat/ESS implementation in the repo (the estimators
themselves live in :mod:`hmsc_tpu.post.diagnostics`).

Everything here consumes host-side numpy arrays only — it can never touch
the device draw stream.
"""

from __future__ import annotations

import numpy as np

__all__ = ["rhat_ess", "RunningDiagnostics", "DEFAULT_MONITOR_ENTRIES"]

DEFAULT_MONITOR_ENTRIES = 8

# split-R-hat needs two non-trivial half-chains; below this many draws the
# running summary reports counts only
_MIN_DRAWS = 4


def rhat_ess(x) -> dict:
    """Split-R-hat and ESS over ``(chains, samples, ...)`` in one call.

    Returns ``{"rhat": array, "ess": array}`` with the trailing shape —
    the shared entry point for the running per-segment diagnostics and the
    post-hoc full-array passes (``diag_mixing``)."""
    from ..post.diagnostics import effective_size, gelman_rhat

    x = np.asarray(x, dtype=float)
    return {"rhat": gelman_rhat(x), "ess": effective_size(x)}


def _monitor_indices(shape, max_entries: int) -> np.ndarray:
    """Evenly spaced flat indices into a parameter's trailing dims."""
    m = int(np.prod(shape)) if shape else 1
    k = max(1, min(int(max_entries), m))
    return np.unique(np.linspace(0, m - 1, k).astype(np.int64))


class RunningDiagnostics:
    """Incremental R-hat/ESS over segment-wise flushed draws.

    ``update(segment_arrays)`` appends the monitored entries of one flushed
    host segment (``{name: (chains, seg_samples, ...)}``); ``summary()``
    computes split-R-hat and ESS over everything accumulated so far.  The
    monitored subset is resolved once, from the first segment: up to
    ``max_entries`` evenly spaced scalar entries of each monitored
    parameter (default: Beta, which every run records).  The buffer is
    ``(chains, total_samples, n_monitored)`` float32 — a few KB per
    thousand draws, so a long run's running diagnostics cost nothing.
    """

    def __init__(self, monitor: tuple = ("Beta",),
                 max_entries: int = DEFAULT_MONITOR_ENTRIES):
        self.monitor = tuple(monitor)
        self.max_entries = int(max_entries)
        self._idx: dict | None = None            # name -> flat indices
        self._labels: list[str] = []
        self._chunks: list[np.ndarray] = []
        self.n_samples = 0

    def _resolve(self, arrays) -> None:
        self._idx = {}
        for name in self.monitor:
            a = arrays.get(name)
            if a is None:
                continue
            idx = _monitor_indices(np.shape(a)[2:], self.max_entries)
            self._idx[name] = idx
            self._labels.extend(f"{name}[{int(i)}]" for i in idx)

    @property
    def labels(self) -> list[str]:
        return list(self._labels)

    def update(self, arrays) -> None:
        """Append one flushed segment's monitored draws."""
        if self._idx is None:
            self._resolve(arrays)
        cols = []
        for name, idx in self._idx.items():
            a = arrays.get(name)
            if a is None:
                continue
            a = np.asarray(a)
            flat = a.reshape(a.shape[0], a.shape[1], -1)
            cols.append(flat[:, :, idx].astype(np.float32))
        if not cols:
            return
        chunk = np.concatenate(cols, axis=2)
        self._chunks.append(chunk)
        self.n_samples += int(chunk.shape[1])

    @property
    def draws(self) -> np.ndarray | None:
        """Accumulated monitored draws ``(chains, n, k)`` (folds chunks)."""
        if not self._chunks:
            return None
        if len(self._chunks) > 1:
            self._chunks = [np.concatenate(self._chunks, axis=1)]
        return self._chunks[0]

    def summary(self) -> dict:
        """JSON-safe running diagnostics over everything seen so far."""
        x = self.draws
        out = {"n_draws": self.n_samples, "monitored": len(self._labels)}
        if x is None or x.shape[1] < _MIN_DRAWS:
            out.update(rhat_max=None, ess_min=None)
            return out
        d = rhat_ess(x)
        rhat = np.asarray(d["rhat"], dtype=float).ravel()
        ess = np.asarray(d["ess"], dtype=float).ravel()
        finite = np.isfinite(rhat)
        out.update(
            rhat_max=(round(float(rhat[finite].max()), 4)
                      if finite.any() else None),
            ess_min=round(float(ess.min()), 1),
            ess_median=round(float(np.median(ess)), 1),
        )
        return out
