"""Structured, rank-prefixed run logging.

All library-side progress output goes through :func:`get_logger` instead of
bare ``print`` (a lint test enforces this outside the obs module and the
CLI entry points).  Single-process output is byte-identical to the old
prints — ``verbose=N`` progress keeps its exact text — while multi-process
runs prefix each line with ``[p<rank>]`` so interleaved pod logs stay
attributable.  When a :class:`~hmsc_tpu.obs.events.RunTelemetry` is bound,
every line is mirrored into the event stream as a ``kind="log"`` event, so
the ``report`` CLI can replay a run's messages in timeline order.
"""

from __future__ import annotations

import sys

__all__ = ["RunLogger", "get_logger"]


class RunLogger:
    """Cheap per-run logger: ``info`` to stdout, ``warn`` to stderr."""

    def __init__(self, telemetry=None, proc: int = 0, n_procs: int = 1):
        self.telemetry = telemetry
        self.proc = int(proc)
        self.n_procs = int(n_procs)
        self._warned: set = set()

    def _write(self, stream, level: str, msg: str) -> None:
        prefix = f"[p{self.proc}] " if self.n_procs > 1 else ""
        print(prefix + msg, file=stream)
        if self.telemetry is not None:
            self.telemetry.emit("log", level, text=msg)

    def info(self, msg: str) -> None:
        self._write(sys.stdout, "info", msg)

    def warn(self, msg: str) -> None:
        self._write(sys.stderr, "warning", msg)

    def warn_once(self, key: str, msg: str, *, category=RuntimeWarning,
                  stacklevel: int = 3) -> bool:
        """Deliver ``msg`` as a real ``warnings.warn`` (so test/filtering
        machinery keeps working) at most ONCE per run for a given ``key``.

        Repeated structural conditions — the sharded sweep's
        nearest-valid-divisor fallback, a batched bucket's padding-waste
        report — are re-detected at every segment/bucket boundary; without
        per-run dedup they spam one identical warning per boundary.  The
        dedup scope is this logger: the sampler constructs one logger per
        ``sample_mcmc`` invocation, so a *new* run — including a retry /
        continuation sub-call, which is a new sampling run with its own
        logger — warns afresh.  Returns True when the warning was actually
        delivered."""
        if key in self._warned:
            return False
        self._warned.add(key)
        import warnings
        warnings.warn(msg, category, stacklevel=stacklevel)
        if self.telemetry is not None:
            self.telemetry.emit("log", "warning", text=msg, dedup_key=key)
        return True


def get_logger(telemetry=None, proc: int = 0, n_procs: int = 1) -> RunLogger:
    """A logger bound to (telemetry, rank).  Loggers are stateless and
    cheap — callers construct one per run (the sampler) or per call site
    (library code with no run context: ``get_logger()``)."""
    return RunLogger(telemetry, proc, n_procs)
