"""Live fleet-wide metrics hub: ``python -m hmsc_tpu watch <root>``.

``report`` is a postmortem — it parses completed (or at least quiescent)
event streams.  The hub is the live view: it incrementally tails EVERY
JSONL stream under a watch root (run dirs, ``fleet-events.jsonl`` from
supervisors / job queues / serving fleets / autopilots, tenant fan-out
dirs, serving replica telemetry) with per-file byte offsets and
torn-last-line tolerance, folds the events into rolling fleet-level
aggregates, evaluates the :mod:`~hmsc_tpu.obs.alerts` SLO rules against
each snapshot, and exposes the result three ways: a live terminal view, a
``--once --json`` snapshot, and a stdlib HTTP ``/metrics`` endpoint
speaking the same frozen ``PROM_GAUGES`` registry as the offline
exporters.

Tailing contract (``JsonlTailer``, exercised against a concurrent writer
by ``tests/test_watch.py`` and gated by ``benchmarks/bench_watch.py``):
every COMMITTED event — complete line, newline written — is observed
exactly once; a torn final line is left unconsumed until its newline
lands; a rotation (rename + fresh file at the same path) first drains the
renamed file through the still-open handle, then follows the new inode
from byte 0.  The hub only ever reads — it opens no sampler state, holds
no locks any writer contends on, and adds <2% driver overhead to a live
2-rank run (the bench gate).

Cross-process trace assembly rides the same poll: every event carrying a
schema-v2 ``trace`` field is indexed by trace id, so ``traces()`` joins
one autopilot drop's chain — validate → refit worker → epoch commit →
serving flip — across the processes that wrote it.
"""

from __future__ import annotations

import json
import os
import time

from .events import EVENTS_FILE_RE, RunTelemetry

__all__ = ["JsonlTailer", "MetricsHub", "ALERTS_FILE", "render_watch",
           "watch_main", "serve_hub"]

# the hub's own alert stream under the watch root (kind="alert" events);
# per-rank sampler streams never carry alerts — their kind set is pinned
ALERTS_FILE = "alerts.jsonl"

# fleet-events.jsonl (supervisor/queue/serving/autopilot decision logs);
# name mirrored from fleet.supervisor.FLEET_EVENTS_FILE — imported lazily
# in discover() to keep obs free of an import cycle with fleet
_FLEET_EVENTS_FILE = "fleet-events.jsonl"

_READ_CHUNK = 1 << 16
_MAX_TRACES = 256            # LRU-dropped beyond this
_MAX_TRACE_EVENTS = 2000     # per-trace index cap
_MAX_RECENT_ALERTS = 50
_QUEUE_WAIT_WINDOW = 512     # rolling per-stream queue_wait observations


class JsonlTailer:
    """Incremental exactly-once reader of one append-mode JSONL file."""

    __slots__ = ("path", "_f", "_ino", "_buf", "n_events", "n_malformed")

    def __init__(self, path: str):
        self.path = os.fspath(path)
        self._f = None
        self._ino = None
        self._buf = b""
        self.n_events = 0
        self.n_malformed = 0

    def _open(self) -> bool:
        try:
            f = open(self.path, "rb")
            self._ino = os.fstat(f.fileno()).st_ino
        except OSError:
            return False
        self._f = f
        return True

    def _drain(self) -> list[dict]:
        """Read the open handle to EOF; return the complete events."""
        out = []
        while True:
            try:
                chunk = self._f.read(_READ_CHUNK)
            except OSError:
                break
            if not chunk:
                break
            self._buf += chunk
            while True:
                nl = self._buf.find(b"\n")
                if nl < 0:
                    break           # torn tail: wait for its newline
                line, self._buf = self._buf[:nl], self._buf[nl + 1:]
                if not line.strip():
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    self.n_malformed += 1
                    continue
                if isinstance(ev, dict):
                    self.n_events += 1
                    out.append(ev)
                else:
                    self.n_malformed += 1
        return out

    def poll(self) -> list[dict]:
        """All events committed since the last poll."""
        if self._f is None and not self._open():
            return []
        out = self._drain()
        # rotation / truncation: the path no longer names the inode we
        # hold (rename/GC), or it shrank in place — the old handle was
        # fully drained above, so follow the fresh file from byte 0
        rotated = False
        try:
            st = os.stat(self.path)
            if st.st_ino != self._ino:
                rotated = True
            elif st.st_size < self._f.tell():
                rotated = True
        except OSError:
            rotated = True          # vanished; reopen when it returns
        if rotated:
            try:
                self._f.close()
            except OSError:
                pass
            self._f, self._buf = None, b""
            if self._open():
                out += self._drain()
        return out

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


def _p99(values: list[float]) -> float | None:
    if not values:
        return None
    vs = sorted(values)
    return vs[min(len(vs) - 1, int(0.99 * (len(vs) - 1) + 0.999))]


class MetricsHub:
    """Tail every stream under ``root``; fold into rolling aggregates.

    Single-threaded: callers drive :meth:`poll` / :meth:`pump` from one
    loop (the watch CLI, a supervisor's liveness loop, a test).  All reads
    are lock-free file appends from other processes' perspective."""

    def __init__(self, root: str, *, rules=None, alert_telemetry=None,
                 evaluate_alerts: bool = True):
        from .alerts import AlertEngine
        self.root = os.fspath(root)
        self._tailers: dict[str, JsonlTailer] = {}
        self._hb_dirs: set[str] = set()
        self._engine = AlertEngine(rules)
        self._alert_telem = alert_telemetry
        self._evaluate = bool(evaluate_alerts)
        self._last_pump = 0.0
        self.events_seen = 0
        self.malformed = 0
        # rolling state folded from events
        self._streams: dict[str, dict] = {}
        self._tenants: dict[str, dict] = {}
        self._queue: dict = {}
        self._fleet: dict = {"counts": {}}
        self._serving: dict = {"replicas": {}, "flips": 0,
                               "flip_latency_s": {}}
        self._pipeline: dict = {"counts": {}}
        self._skew: dict = {}
        self._qwait: dict[str, list[float]] = {}
        self._pending_flip_t: dict[str, float] = {}
        self._recent_alerts: list[dict] = []
        self._traces: dict[str, list[dict]] = {}

    # -- discovery ---------------------------------------------------------

    def discover(self) -> int:
        """Walk the root for new streams/heartbeat dirs; idempotent."""
        from ..utils.coordination import HEARTBEAT_FILE_RE
        try:
            from ..fleet.supervisor import FLEET_EVENTS_FILE
        except ImportError:          # pragma: no cover - fleet optional
            FLEET_EVENTS_FILE = _FLEET_EVENTS_FILE
        new = 0
        if os.path.isfile(self.root):
            if self.root not in self._tailers:
                self._tailers[self.root] = JsonlTailer(self.root)
                new += 1
            return new
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                path = os.path.join(dirpath, fn)
                if fn == FLEET_EVENTS_FILE or fn == ALERTS_FILE \
                        or EVENTS_FILE_RE.fullmatch(fn):
                    if path not in self._tailers:
                        self._tailers[path] = JsonlTailer(path)
                        new += 1
                elif HEARTBEAT_FILE_RE.fullmatch(fn):
                    self._hb_dirs.add(dirpath)
        return new

    def _rel(self, path: str) -> str:
        try:
            rel = os.path.relpath(path, self.root)
        except ValueError:           # pragma: no cover - cross-drive
            return path
        return path if rel.startswith("..") else rel

    @staticmethod
    def _stream_kind(path: str) -> str:
        fn = os.path.basename(path)
        if fn == ALERTS_FILE:
            return "alerts"
        if fn == _FLEET_EVENTS_FILE:
            return "fleet"
        return "run"

    # -- folding -----------------------------------------------------------

    def poll(self) -> int:
        """Discover + drain every stream once; fold; return events read."""
        self.discover()
        n = 0
        for path, tailer in sorted(self._tailers.items()):
            events = tailer.poll()
            if not events:
                continue
            rel = self._rel(path)
            kind = self._stream_kind(path)
            for ev in events:
                self._fold(rel, kind, ev)
            n += len(events)
        self.events_seen += n
        self.malformed = sum(t.n_malformed for t in self._tailers.values())
        return n

    def _stream_state(self, rel: str, kind: str) -> dict:
        st = self._streams.get(rel)
        if st is None:
            tenant = None
            for part in rel.split(os.sep):
                if part.startswith("tenant-"):
                    tenant = part[len("tenant-"):]
            st = self._streams[rel] = {
                "kind": kind, "events": 0, "proc": None, "tenant": tenant,
                "started": False, "ended": False, "n_chains": None,
                "health": None, "last_wall": None,
                "last_progress_wall": None,
            }
        return st

    def _fold(self, rel: str, stream_kind: str, ev: dict) -> None:
        st = self._stream_state(rel, stream_kind)
        st["events"] += 1
        st["last_wall"] = ev.get("wall")
        if st["proc"] is None:
            st["proc"] = ev.get("proc")
        kind, name = ev.get("kind"), ev.get("name")
        tid = ev.get("trace")
        if tid:
            self._index_trace(rel, tid, ev)
        if kind == "run":
            if name == "start":
                st["started"] = True
                st["ended"] = False
                st["n_chains"] = ev.get("n_chains", st["n_chains"])
                st["last_progress_wall"] = ev.get("wall")
                tenant = ev.get("tenant") or st["tenant"]
                if tenant:
                    self._tenant(tenant).update(
                        n_chains=ev.get("n_chains"), done=False)
            elif name in ("end", "preempted"):
                st["ended"] = True
        elif kind == "metric":
            self._fold_metric(st, name, ev)
        elif kind == "span" and name == "queue_wait":
            dq = self._qwait.setdefault(rel, [])
            dq.append(float(ev.get("dur_s", 0.0)))
            del dq[:-_QUEUE_WAIT_WINDOW]
        elif kind == "alert":
            self._remember_alert(ev)
        elif kind == "fleet":
            self._fold_fleet(rel, name, ev)
        elif kind == "pipeline":
            self._fold_pipeline(name, ev)

    def _tenant(self, name: str) -> dict:
        return self._tenants.setdefault(str(name), {})

    def _fold_metric(self, st: dict, name: str, ev: dict) -> None:
        if name == "segment_health":
            st["health"] = {k: ev.get(k) for k in
                            ("seg", "samples_done", "draws_per_s",
                             "diverged_chains", "rhat_max", "ess_min")}
            st["last_progress_wall"] = ev.get("wall")
            if st["tenant"]:
                self._tenant(st["tenant"]).update(
                    draws_per_s=ev.get("draws_per_s"),
                    diverged=ev.get("diverged_chains"),
                    n_chains=st["n_chains"]
                    or self._tenant(st["tenant"]).get("n_chains"))
        elif name == "tenant_health":
            t = self._tenant(ev.get("tenant", "?"))
            for k in ("diverged", "n_chains", "draws_per_s", "nf",
                      "samples_done", "done"):
                if ev.get(k) is not None:
                    t[k] = ev.get(k)
        elif name == "rank_skew":
            s = float(ev.get("skew_s", 0.0))
            self._skew["last_s"] = s
            self._skew["max_s"] = max(s, self._skew.get("max_s", 0.0))

    def _fold_fleet(self, rel: str, name: str, ev: dict) -> None:
        c = self._fleet["counts"]
        c[name] = c.get(name, 0) + 1
        if name == "queue_start":
            self._queue.update(
                jobs=ev.get("n_jobs"), tenants=ev.get("n_tenants"),
                buckets=ev.get("n_buckets"), dispatched=0, done=0,
                scenarios=0)
        elif name == "job_dispatch":
            self._queue["dispatched"] = self._queue.get("dispatched", 0) + 1
        elif name == "tenant_done":
            self._queue["done"] = self._queue.get("done", 0) + 1
            t = self._tenant(ev.get("tenant", "?"))
            t["done"] = True
        elif name == "scenario_done":
            self._queue["scenarios"] = self._queue.get("scenarios", 0) + 1
        elif name == "queue_end":
            for k in ("occupancy", "padding_waste"):
                if ev.get(k) is not None:
                    self._queue[k] = ev.get(k)
            self._queue["ended"] = True
        elif name == "bucket_report":
            if ev.get("padding_waste") is not None:
                self._queue.setdefault("bucket_waste", {})[
                    str(ev.get("bucket"))] = ev.get("padding_waste")
        elif name == "replica_stats":
            rep = self._serving["replicas"].setdefault(
                str(ev.get("rank")), {})
            for k in ("generation", "epoch", "requests", "rows_served",
                      "inflight"):
                if ev.get(k) is not None:
                    rep[k] = ev.get(k)
            qn = ev.get("queue_wait_n") or 0
            if qn:
                rep["queue_wait_mean_s"] = round(
                    float(ev.get("queue_wait_s", 0.0)) / qn, 6)
        elif name == "flip_start":
            self._pending_flip_t[rel] = float(ev.get("t", 0.0))
        elif name == "flip_done":
            t0 = self._pending_flip_t.pop(rel, None)
            if t0 is not None:
                lat = max(0.0, float(ev.get("t", t0)) - t0)
                fl = self._serving["flip_latency_s"]
                fl["last"] = round(lat, 6)
                fl["max"] = round(max(lat, fl.get("max", 0.0)), 6)
            self._serving["flips"] += 1

    def _fold_pipeline(self, name: str, ev: dict) -> None:
        c = self._pipeline["counts"]
        c[name] = c.get(name, 0) + 1
        if name == "epoch_committed" and ev.get("epoch") is not None:
            self._pipeline["epoch"] = ev.get("epoch")
        if name in ("drop_seen", "drop_accepted", "drop_rejected",
                    "drop_done") and ev.get("drop") is not None:
            self._pipeline["last_drop"] = ev.get("drop")

    def _remember_alert(self, ev: dict) -> None:
        self._recent_alerts.append(
            {k: ev.get(k) for k in ("wall", "name", "rule", "subject",
                                    "value", "threshold", "severity")})
        del self._recent_alerts[:-_MAX_RECENT_ALERTS]

    def _index_trace(self, rel: str, tid: str, ev: dict) -> None:
        chain = self._traces.get(tid)
        if chain is None:
            if len(self._traces) >= _MAX_TRACES:
                self._traces.pop(next(iter(self._traces)))
            chain = self._traces[tid] = []
        if len(chain) < _MAX_TRACE_EVENTS:
            chain.append({"stream": rel, "proc": ev.get("proc"),
                          "kind": ev.get("kind"), "name": ev.get("name"),
                          "span": ev.get("span"),
                          "parent": ev.get("parent"),
                          "wall": ev.get("wall")})

    # -- views -------------------------------------------------------------

    def traces(self) -> dict[str, list[dict]]:
        """``{trace_id: [indexed events, arrival order]}`` — the
        cross-process join (each entry names its stream and span ids)."""
        return {k: list(v) for k, v in self._traces.items()}

    def heartbeats(self) -> dict:
        from ..utils.coordination import read_heartbeats
        out = {}
        for d in sorted(self._hb_dirs):
            hbs = read_heartbeats(d)
            if hbs:
                out[self._rel(d)] = {
                    str(r): (None if hb.get("age_s") is None
                             else round(float(hb["age_s"]), 3))
                    for r, hb in hbs.items()}
        return out

    def snapshot(self) -> dict:
        """JSON-safe rolling aggregate view (the ``--once --json`` body,
        the alert-probe input, and the Prometheus exporter's source)."""
        active = [rel for rel, st in self._streams.items()
                  if st["kind"] == "run" and st["started"]
                  and not st["ended"]]
        draws = sum((st.get("health") or {}).get("draws_per_s") or 0.0
                    for rel, st in self._streams.items() if rel in active)
        streams = {}
        for rel, st in sorted(self._streams.items()):
            view = dict(st)
            p99 = _p99(self._qwait.get(rel, []))
            if p99 is not None:
                view["queue_wait_p99_s"] = round(p99, 6)
            streams[rel] = view
        reps = self._serving["replicas"]
        serving = {"replicas": {k: dict(v) for k, v in reps.items()},
                   "flips": self._serving["flips"],
                   "flip_latency_s": dict(self._serving["flip_latency_s"])}
        for key, field in (("generation_lag", "generation"),
                           ("epoch_lag", "epoch")):
            vals = [v.get(field) for v in reps.values()
                    if v.get(field) is not None]
            if vals:
                serving[key] = max(vals) - min(vals)
        queue = dict(self._queue)
        if queue.get("tenants") is not None:
            queue["depth"] = max(
                0, int(queue["tenants"]) - int(queue.get("done") or 0))
        return {
            "schema": 1,
            "root": self.root,
            "wall": round(time.time(), 3),
            "streams": streams,
            "n_streams": len(self._tailers),
            "events": self.events_seen,
            "malformed": self.malformed,
            "active_runs": len(active),
            "draws_per_s_total": round(draws, 4),
            "skew": dict(self._skew),
            "tenants": {k: dict(v)
                        for k, v in sorted(self._tenants.items())},
            "queue": queue,
            "fleet": {"counts": dict(self._fleet["counts"])},
            "serving": serving,
            "pipeline": dict(self._pipeline,
                             counts=dict(self._pipeline["counts"])),
            "heartbeats": self.heartbeats(),
            "alerts": {"fired": self._engine.n_fired,
                       "active": self._engine.active(),
                       "recent": list(self._recent_alerts)},
            "traces": {"n": len(self._traces)},
        }

    # -- alert evaluation --------------------------------------------------

    def check_alerts(self, snap: dict | None = None) -> list[dict]:
        """Evaluate the rule set against a snapshot; emit newly-firing
        alerts as ``kind="alert"`` events on the attached telemetry."""
        if not self._evaluate:
            return []
        fired = self._engine.evaluate(snap or self.snapshot())
        if fired and self._alert_telem is not None:
            for a in fired:
                fields = {k: v for k, v in a.items() if k != "rule"}
                self._alert_telem.emit("alert", a["rule"], rule=a["rule"],
                                       **fields)
            self._alert_telem.flush()
        for a in fired:
            self._remember_alert(dict(a, name=a["rule"]))
        return fired

    def pump(self, min_interval_s: float = 1.0) -> list[dict]:
        """Rate-limited poll + alert check, for daemons that attach a hub
        inside their own watch loop (supervisor, autopilot): cheap enough
        to call every liveness tick."""
        now = time.monotonic()
        if now - self._last_pump < min_interval_s:
            return []
        self._last_pump = now
        self.poll()
        return self.check_alerts()

    def prometheus(self) -> str:
        from .report import hub_prometheus_textfile
        return hub_prometheus_textfile(self.snapshot())

    def close(self) -> None:
        for t in self._tailers.values():
            t.close()


# -- rendering ---------------------------------------------------------------

def _fmt(v, nd=2):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render_watch(snap: dict) -> str:
    """Plain-text live view of one hub snapshot."""
    L = [f"watch {snap['root']} — {snap['n_streams']} streams, "
         f"{snap['events']} events, {snap['active_runs']} active runs, "
         f"{snap['draws_per_s_total']:.1f} draws/s"
         + (f", {snap['malformed']} malformed" if snap.get("malformed")
            else "")]
    skew = snap.get("skew") or {}
    if skew:
        L.append(f"  skew: last {_fmt(skew.get('last_s'), 3)}s "
                 f"max {_fmt(skew.get('max_s'), 3)}s")
    runs = [(rel, st) for rel, st in snap["streams"].items()
            if st["kind"] == "run"]
    if runs:
        L.append("ranks:")
        for rel, st in runs:
            h = st.get("health") or {}
            status = ("done" if st["ended"]
                      else "live" if st["started"] else "idle")
            L.append(f"  {rel:40s} {status:5s} "
                     f"draws/s {_fmt(h.get('draws_per_s'), 1):>8s} "
                     f"samples {_fmt(h.get('samples_done')):>6s} "
                     f"rhat {_fmt(h.get('rhat_max')):>6s} "
                     f"div {_fmt(h.get('diverged_chains')):>3s}")
    if snap.get("tenants"):
        L.append("tenants:")
        for name, t in snap["tenants"].items():
            L.append(f"  {name:24s} done={t.get('done', False)} "
                     f"diverged={_fmt(t.get('diverged'))} "
                     f"draws/s={_fmt(t.get('draws_per_s'), 1)}")
    q = snap.get("queue") or {}
    if q:
        L.append(f"queue: {_fmt(q.get('done'))}/{_fmt(q.get('tenants'))} "
                 f"tenants done, depth {_fmt(q.get('depth'))}, "
                 f"occupancy {_fmt(q.get('occupancy'))}, "
                 f"padding waste {_fmt(q.get('padding_waste'))}")
    sv = snap.get("serving") or {}
    if sv.get("replicas"):
        lat = sv.get("flip_latency_s") or {}
        L.append(f"serving: {len(sv['replicas'])} replicas, "
                 f"gen lag {_fmt(sv.get('generation_lag'))}, "
                 f"epoch lag {_fmt(sv.get('epoch_lag'))}, "
                 f"flips {sv.get('flips', 0)} "
                 f"(last {_fmt(lat.get('last'), 3)}s)")
        for rank, rep in sorted(sv["replicas"].items()):
            L.append(f"  replica {rank}: gen {_fmt(rep.get('generation'))} "
                     f"epoch {_fmt(rep.get('epoch'))} "
                     f"req {_fmt(rep.get('requests'))} "
                     f"qwait {_fmt(rep.get('queue_wait_mean_s'), 4)}s")
    pc = (snap.get("pipeline") or {}).get("counts") or {}
    if pc:
        L.append("pipeline: " + " ".join(
            f"{k}={v}" for k, v in sorted(pc.items())))
    hbs = snap.get("heartbeats") or {}
    for d, ranks in hbs.items():
        ages = " ".join(f"p{r}={_fmt(a, 1)}s"
                        for r, a in sorted(ranks.items()))
        L.append(f"heartbeats {d}: {ages}")
    al = snap.get("alerts") or {}
    if al.get("fired") or al.get("recent"):
        L.append(f"alerts: {al.get('fired', 0)} fired, "
                 f"{len(al.get('active') or [])} active")
        for a in (al.get("recent") or [])[-8:]:
            L.append(f"  [{a.get('severity')}] {a.get('rule') or a.get('name')}"
                     f" {a.get('subject')}: {_fmt(a.get('value'), 4)} > "
                     f"{_fmt(a.get('threshold'), 4)}")
    return "\n".join(L)


# -- HTTP endpoint -----------------------------------------------------------

def serve_hub(hub: MetricsHub, host: str = "127.0.0.1", port: int = 0):
    """A stdlib HTTP server exposing the hub: ``/metrics`` (Prometheus
    textfile over the frozen registry), ``/snapshot`` (JSON), ``/healthz``.
    The handler polls the hub before answering, so the endpoint is always
    current without a background thread mutating shared state."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    import threading

    lock = threading.Lock()     # serialise polls across handler threads

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):   # quiet access log
            pass

        def _send(self, code, body: bytes, ctype: str):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?")[0]
            with lock:
                hub.poll()
                hub.check_alerts()
                if path == "/metrics":
                    body, ctype = hub.prometheus().encode(), \
                        "text/plain; version=0.0.4"
                elif path == "/snapshot":
                    body, ctype = json.dumps(hub.snapshot()).encode(), \
                        "application/json"
                elif path == "/healthz":
                    body = json.dumps(
                        {"ok": True, "streams": len(hub._tailers),
                         "events": hub.events_seen}).encode()
                    ctype = "application/json"
                else:
                    self._send(404, b'{"error": "not found"}',
                               "application/json")
                    return
            self._send(200, body, ctype)

    srv = ThreadingHTTPServer((host, port), Handler)
    srv.daemon_threads = True
    return srv


# -- CLI ---------------------------------------------------------------------

def watch_main(argv=None) -> int:
    """``python -m hmsc_tpu watch <root>`` — live terminal view (default),
    one-shot snapshot (``--once [--json]``), or HTTP endpoint
    (``--serve PORT``)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m hmsc_tpu watch",
        description="live fleet-wide metrics hub over a watch root")
    ap.add_argument("root", help="directory tree (or one JSONL file) to "
                                 "tail: run dirs, fleet work dirs, a "
                                 "whole tenant fan-out root")
    ap.add_argument("--once", action="store_true",
                    help="poll once, print, exit")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON snapshot instead of the text view")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll/render period in seconds (default 2)")
    ap.add_argument("--max-seconds", type=float, default=None,
                    help="exit after this long (bounded watch for "
                         "tests/benches)")
    ap.add_argument("--rules", default=None,
                    help="JSON alert-rule config (default: built-in rules)")
    ap.add_argument("--no-alerts", action="store_true",
                    help="disable SLO rule evaluation")
    ap.add_argument("--alerts-sink", default=None,
                    help=f"alert event stream path (default: "
                         f"<root>/{ALERTS_FILE}; 'none' disables writing)")
    ap.add_argument("--serve", type=int, default=None, metavar="PORT",
                    help="also expose /metrics, /snapshot, /healthz on "
                         "this port (0 = ephemeral, printed)")
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)

    from .alerts import load_rules
    rules = load_rules(args.rules) if args.rules else None
    telem = None
    if not args.no_alerts:
        sink = args.alerts_sink
        if sink is None and os.path.isdir(args.root):
            sink = os.path.join(args.root, ALERTS_FILE)
        if sink and sink != "none":
            telem = RunTelemetry(proc=0)
            telem.attach_sink(sink)
    hub = MetricsHub(args.root, rules=rules, alert_telemetry=telem,
                     evaluate_alerts=not args.no_alerts)

    srv = None
    if args.serve is not None:
        import threading
        srv = serve_hub(hub, args.host, args.serve)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        print(f"hub: http://{srv.server_address[0]}:"
              f"{srv.server_address[1]}/metrics")

    t_end = (None if args.max_seconds is None
             else time.monotonic() + args.max_seconds)
    try:
        while True:
            hub.poll()
            snap = hub.snapshot()
            hub.check_alerts(snap)
            if args.json:
                print(json.dumps(snap))
            else:
                print(render_watch(snap))
            if args.once:
                break
            if t_end is not None and time.monotonic() >= t_end:
                break
            time.sleep(max(0.05, args.interval))
            if not args.json:
                print()
    except KeyboardInterrupt:
        pass
    finally:
        if srv is not None:
            srv.shutdown()
        hub.close()
    return 0
