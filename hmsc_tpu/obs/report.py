"""``python -m hmsc_tpu report <run_dir>`` — render a run from its telemetry.

Reads the per-process ``events-p<rank>.jsonl`` streams a checkpointed run
writes alongside its snapshots (completed *or* in-flight: a truncated last
line is tolerated) and prints:

- **phase timeline** — per-span totals/counts as a share of run wall;
- **throughput curve** — recorded samples and draws/sec per segment mark;
- **stall / skew analysis** — cross-rank segment-time skew and per-rank
  barrier waits at every commit mark (multi-process runs);
- **checkpoint I/O breakdown** — shard/state/manifest write time + bytes;
- **health summary** — divergence counters, nf-adaptation trajectory, and
  the latest running R-hat/ESS per rank;
- **cost attribution** — the per-updater wall/share table recorded by
  ``sample_mcmc(profile_updaters=...)`` or ``python -m hmsc_tpu profile
  --measured``, and the static flops / temp-HBM ledger digest emitted by
  ``profile --static --out``;
- **fleet timeline** — for supervised runs (``python -m hmsc_tpu fleet``),
  the supervisor's ``fleet-events.jsonl``: per-attempt spawn/exit
  outcomes, heartbeat kills, chaos injections, backoff/shrink/grow
  decisions, and the final supervision summary;
- **serving-fleet timeline** — for replicated serving runs
  (``python -m hmsc_tpu serve --fleet``), the front end's
  ``fleet-events.jsonl``: per-replica lifecycle (spawns, exits,
  backoff restarts), fleet-wide generation-checked epoch flips, the
  front end's proxied/retried/rejected counters, and per-replica load
  skew (queries/sec + mean queue wait from the periodic
  ``replica_stats`` samples).

``--json`` emits the structured report instead of text; ``--prom FILE``
writes a Prometheus textfile-collector export of the final gauges (point
the node exporter's ``--collector.textfile.directory`` at it).

Prometheus naming scheme
------------------------
Every exporter in this package — run reports (:func:`prometheus_textfile`),
the serving engine (:func:`serving_prometheus_textfile`), and the profile
gauges both share — emits ONLY gauge names from the frozen
:data:`PROM_GAUGES` registry, all under the single ``hmsc_tpu_`` prefix:
``hmsc_tpu_<noun>_<unit>`` with subsystem-scoped nouns (``serve_*`` for
the serving engine, ``updater_*``/``ledger_*`` for cost attribution) and
Prometheus-conventional unit suffixes (``_seconds``/``_bytes``, ``_total``
for monotone counters exported as gauges).  Renaming or adding a gauge is
a deliberate, review-visible edit to the registry — the full set is
pinned by ``tests/test_profile.py`` so dashboards and scrape configs
never break on a silent rename.
"""

from __future__ import annotations

import argparse
import json
import os

__all__ = ["load_run_events", "load_fleet_events", "build_report",
           "render_report", "epoch_drift_report", "render_drift",
           "render_scenarios", "prometheus_textfile",
           "serving_prometheus_textfile", "hub_prometheus_textfile",
           "report_main", "PROM_GAUGES"]

# the frozen gauge-name registry (see the module docstring): every
# *_prometheus_textfile exporter routes through _gauge(), which refuses
# names outside this set
PROM_GAUGES = (
    # run telemetry (prometheus_textfile)
    "hmsc_tpu_span_seconds_total",
    "hmsc_tpu_span_seconds_max",
    "hmsc_tpu_span_count",
    "hmsc_tpu_run_wall_seconds",
    "hmsc_tpu_samples_done",
    "hmsc_tpu_draws_per_second",
    "hmsc_tpu_diverged_chains",
    "hmsc_tpu_rhat_max",
    "hmsc_tpu_ess_min",
    "hmsc_tpu_rank_skew_seconds",
    # cost attribution (profile CLI / profile_updaters hook)
    "hmsc_tpu_updater_wall_seconds",
    "hmsc_tpu_updater_share",
    "hmsc_tpu_profile_attributed_fraction",
    "hmsc_tpu_ledger_flops_total",
    "hmsc_tpu_ledger_temp_bytes_peak",
    # serving engine (serving_prometheus_textfile)
    "hmsc_tpu_serve_requests_total",
    "hmsc_tpu_serve_batches_total",
    "hmsc_tpu_serve_device_calls_total",
    "hmsc_tpu_serve_rows_served_total",
    "hmsc_tpu_serve_rows_padded_total",
    "hmsc_tpu_serve_kernel_cache_hits_total",
    "hmsc_tpu_serve_kernel_cache_misses_total",
    "hmsc_tpu_serve_kernel_cache_size",
    "hmsc_tpu_serve_posterior_draws",
    # live metrics hub (hub_prometheus_textfile / `watch --serve`)
    "hmsc_tpu_watch_streams",
    "hmsc_tpu_watch_events_total",
    "hmsc_tpu_watch_active_runs",
    "hmsc_tpu_watch_draws_per_second",
    "hmsc_tpu_watch_rank_skew_seconds",
    "hmsc_tpu_watch_heartbeat_age_seconds",
    "hmsc_tpu_watch_queue_depth",
    "hmsc_tpu_watch_occupancy_ratio",
    "hmsc_tpu_watch_padding_waste_ratio",
    "hmsc_tpu_watch_epoch_lag",
    "hmsc_tpu_watch_generation_lag",
    "hmsc_tpu_watch_flip_latency_seconds",
    "hmsc_tpu_watch_queue_wait_p99_seconds",
    "hmsc_tpu_watch_diverged_chains",
    "hmsc_tpu_watch_alerts_fired_total",
)
_PROM_SET = frozenset(PROM_GAUGES)


def _gauge(out: list, name: str, labels: str, value) -> None:
    """Append one gauge sample line; ``name`` must be registered in
    :data:`PROM_GAUGES` (the single naming authority — a new gauge that
    skips the registry fails loudly here, not in a consumer's dashboard)."""
    if name not in _PROM_SET:
        raise ValueError(f"unregistered Prometheus gauge {name!r} — add it "
                         "to obs.report.PROM_GAUGES (and the pinning test)")
    out.append(f"{name}{labels} {value}")


def _read_jsonl(path: str) -> list | None:
    """Torn-line-tolerant JSONL reader shared by the per-rank and fleet
    streams: unparseable lines — e.g. the torn last line of an in-flight
    run — are skipped, not fatal; an unreadable file returns ``None``."""
    events = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue                  # torn tail of an in-flight run
                if isinstance(ev, dict):
                    events.append(ev)
    except OSError:
        return None
    return events


def load_run_events(run_dir: str) -> dict:
    """``{proc: [event, ...]}`` from every ``events-p*.jsonl`` under a run
    directory (or a single events file path)."""
    from .events import EVENTS_FILE_RE

    run_dir = os.fspath(run_dir)
    if os.path.isfile(run_dir):
        paths = {0: run_dir}
        m = EVENTS_FILE_RE.fullmatch(os.path.basename(run_dir))
        if m:
            paths = {int(m.group(1)): run_dir}
    else:
        paths = {}
        for fn in sorted(os.listdir(run_dir)):
            m = EVENTS_FILE_RE.fullmatch(fn)
            if m:
                paths[int(m.group(1))] = os.path.join(run_dir, fn)
    out = {}
    for proc, p in sorted(paths.items()):
        events = _read_jsonl(p)
        if events is not None:
            out[proc] = events
    return out


def _span_totals(events) -> dict:
    tot = {}
    for ev in events:
        if ev.get("kind") != "span":
            continue
        agg = tot.setdefault(ev.get("name", "?"),
                             {"count": 0, "total_s": 0.0, "max_s": 0.0})
        d = float(ev.get("dur_s", 0.0))
        agg["count"] += 1
        agg["total_s"] += d
        agg["max_s"] = max(agg["max_s"], d)
    return tot


def _split_epochs(events) -> list:
    """Split one rank's stream at each ``run start``: a resumed run APPENDS
    its continuation with a fresh monotonic clock and seq, so every epoch
    is one sampler invocation.  Events logged before an invocation's start
    mark (e.g. updater-gate messages) belong to the epoch that follows —
    they must not split off a phantom epoch of their own."""
    epochs, cur, seen_start = [], [], False
    for ev in events:
        if ev.get("kind") == "run" and ev.get("name") == "start":
            if seen_start:
                epochs.append(cur)
                cur = []
            seen_start = True
        cur.append(ev)
    if cur:
        epochs.append(cur)
    return epochs


def load_fleet_events(run_dir: str) -> list:
    """The supervisor's ``fleet-events.jsonl`` timeline under a run
    directory (``kind="fleet"`` events, in order); empty when the run was
    not supervised.  Torn/unparseable lines are skipped like the per-rank
    streams'."""
    run_dir = os.fspath(run_dir)
    if not os.path.isdir(run_dir):
        return []
    from ..fleet.supervisor import fleet_events_path
    return _read_jsonl(fleet_events_path(run_dir)) or []


def build_report(run_dir: str) -> dict:
    """Structured report over every rank's event stream."""
    streams = load_run_events(run_dir)
    ops = load_fleet_events(run_dir)   # shared stream: fleet + pipeline
    report = {"run_dir": os.fspath(run_dir),
              "ranks": sorted(streams), "per_rank": {}, "skew": [],
              "fleet": _fleet_section(ops),
              "serve_fleet": _serve_fleet_section(ops),
              "pipeline": _pipeline_section(ops),
              "scenarios": _scenarios_section(ops),
              "alerts": _alerts_section(run_dir, ops),
              "status": "no-events" if not streams else "unknown"}
    for proc, events in streams.items():
        # per-epoch clock re-basing: ``t`` restarts at ~0 in each appended
        # continuation, so offset every epoch by the cumulative wall of the
        # epochs before it — span totals then sum against a wall measured
        # the same way, and the throughput timeline stays monotone
        epochs = _split_epochs(events)
        adj, offset = [], 0.0
        for ep in epochs:
            for e in ep:
                if "t" in e:
                    e = dict(e, t=round(float(e["t"]) + offset, 6))
                adj.append(e)
            offset += max((float(e.get("t", 0.0)) for e in ep), default=0.0)
        events = adj
        wall = offset
        # config/status describe the MOST RECENT invocation (a resume's
        # `samples` is the remainder; cumulative progress lives in the
        # health events' samples_done)
        start = next((e for e in reversed(events)
                      if e.get("kind") == "run" and e.get("name") == "start"),
                     None)
        # the terminal mark must come from the FINAL epoch: an earlier
        # epoch's `preempted` must not mask an in-flight continuation
        final = events[-len(epochs[-1]):] if epochs else []
        end = next((e for e in reversed(final) if e.get("kind") == "run"
                    and e.get("name") in ("end", "preempted")), None)
        health = [e for e in events if e.get("kind") == "metric"
                  and e.get("name") == "segment_health"]
        spans = _span_totals(events)
        io = {k: {kk: (round(vv, 6) if isinstance(vv, float) else vv)
                  for kk, vv in spans[k].items()}
              for k in ("shard_write", "state_write", "manifest_commit",
                        "snapshot_write", "gc", "splice_rewrite")
              if k in spans}
        # shard/state/snapshot spans carry the written payload size
        for name in ("shard_write", "state_write", "snapshot_write"):
            if name in io:
                io[name]["nbytes"] = sum(
                    int(e.get("nbytes", 0)) for e in events
                    if e.get("kind") == "span" and e.get("name") == name)
        logs = [e for e in events if e.get("kind") == "log"]

        # cost attribution: instrumented per-updater passes + static-ledger
        # digests (the profile CLI's --out stream, or the in-run
        # profile_updaters hook)
        def _strip(e):
            return {k: v for k, v in e.items()
                    if k not in ("seq", "t", "wall", "proc", "kind", "name")}
        upd_prof = [_strip(e) for e in events if e.get("kind") == "metric"
                    and e.get("name") == "updater_profile"]
        ledgers = [_strip(e) for e in events if e.get("kind") == "metric"
                   and e.get("name") == "cost_ledger"]
        cost = ({"updater_profile": upd_prof, "ledger": ledgers}
                if upd_prof or ledgers else None)
        report["per_rank"][proc] = {
            "config": ({k: v for k, v in start.items()
                        if k not in ("seq", "t", "wall", "kind", "name")}
                       if start else {}),
            "status": (end["name"] if end else "in-flight"),
            "wall_s": round(wall, 4),
            "resumes": len(epochs) - 1,
            "events": len(events),
            "spans": {k: {kk: (round(vv, 6) if isinstance(vv, float) else vv)
                          for kk, vv in v.items()}
                      for k, v in sorted(spans.items())},
            "throughput": [{"t": e.get("t"),
                            "samples_done": e.get("samples_done"),
                            "draws_per_s": e.get("draws_per_s")}
                           for e in health],
            "health": (health[-1] if health else None),
            "io": io,
            "cost": cost,
            "log_lines": len(logs),
        }
        if proc == min(streams):          # committer stream carries the skew
            report["skew"] = [
                {k: e.get(k) for k in ("t", "tag", "segment_s",
                                       "barrier_wait_s", "skew_s")}
                for e in events if e.get("kind") == "metric"
                and e.get("name") == "rank_skew"]
    if streams:
        # a continuation may run on FEWER ranks than the run it resumes
        # (resume re-shards chains): streams of ranks beyond the newest
        # invocation's process_count are history, not live participants —
        # mark them retired and keep them out of the overall verdict,
        # or a completed 4→2-rank resume would read "preempted" forever
        committer = report["per_rank"][min(streams)]
        cur_pc = committer["config"].get("process_count")
        if isinstance(cur_pc, int) and cur_pc >= 1:
            for proc, r in report["per_rank"].items():
                if proc >= cur_pc:
                    r["status"] = f"retired ({r['status']})"
        live = [r["status"] for r in report["per_rank"].values()
                if not r["status"].startswith("retired")]
        report["status"] = ("preempted" if "preempted" in live else
                            "in-flight" if "in-flight" in live else "end")
    return report


def _fleet_section(events: list) -> dict | None:
    """Structured fleet timeline from the supervisor's event stream:
    per-attempt outcomes plus the supervision decisions (restarts with
    backoff, heartbeat kills, chaos injections, shrink/grow steps).

    ``fleet-events.jsonl`` is shared with the autopilot's
    ``kind="pipeline"`` stream, and several event NAMES collide (backoff,
    heartbeat_silent, chaos, attempt_timeout) — so the section must select
    on kind, not name."""
    events = [e for e in events if e.get("kind") == "fleet"]
    if not events:
        return None
    attempts: dict = {}
    decisions = []
    summary = None
    for ev in events:
        name = ev.get("name")
        att = ev.get("attempt")
        if name == "attempt_start":
            attempts[att] = {"attempt": att, "nprocs": ev.get("nprocs"),
                             "action": ev.get("action"), "exits": {}}
        elif name == "exit" and att in attempts:
            attempts[att]["exits"][str(ev.get("rank"))] = {
                "rc": ev.get("rc"), "outcome": ev.get("outcome")}
        elif name in ("backoff", "shrink", "grow", "heartbeat_silent",
                      "chaos", "abort", "attempt_timeout"):
            decisions.append({k: v for k, v in ev.items()
                              if k not in ("seq", "wall", "proc", "kind")})
        elif name == "fleet_end":
            summary = {k: v for k, v in ev.items()
                       if k not in ("seq", "t", "wall", "proc", "kind",
                                    "name")}
    if not attempts and not decisions and summary is None:
        return None          # e.g. a serving-fleet stream (same kind)
    return {"attempts": [attempts[a] for a in sorted(attempts)],
            "decisions": decisions, "summary": summary}


def _serve_fleet_section(events: list) -> dict | None:
    """Structured serving-fleet timeline from the front end's
    ``fleet-events.jsonl`` (``python -m hmsc_tpu serve --fleet``):
    per-replica lifecycle (spawns, exits with outcome, backoffs), the
    fleet-wide generation-checked epoch flips, and per-replica load skew
    — queries/sec and mean queue-wait derived from the periodic
    ``replica_stats`` samples, so a hot or lagging replica is visible
    without scraping any live /statz."""
    events = [e for e in events if e.get("kind") == "fleet"
              and str(e.get("name", "")).startswith(
                  ("serve_fleet", "replica_", "flip_"))]
    if not events:
        return None
    replicas: dict = {}
    flips, decisions = [], []
    start = summary = None

    def _rep(rank):
        return replicas.setdefault(rank, {
            "rank": rank, "spawns": 0, "exits": [], "stats": []})

    for ev in events:
        name, rank = ev.get("name"), ev.get("rank")
        if name == "serve_fleet_start":
            start = {"replicas": ev.get("replicas"),
                     "source": ev.get("source"),
                     "draw_shards": ev.get("draw_shards")}
        elif name == "replica_spawn":
            _rep(rank)["spawns"] += 1
        elif name == "replica_exit":
            _rep(rank)["exits"].append({"rc": ev.get("rc"),
                                        "outcome": ev.get("outcome")})
        elif name == "replica_stats":
            _rep(rank)["stats"].append(
                {k: ev.get(k) for k in ("t", "requests", "rows_served",
                                        "queue_wait_s", "queue_wait_n",
                                        "inflight", "epoch",
                                        "generation")})
        elif name in ("replica_backoff", "replica_abandoned",
                      "replica_heartbeat_silent", "replica_drain"):
            decisions.append({k: v for k, v in ev.items()
                              if v is not None
                              and k not in ("seq", "wall", "proc",
                                            "kind", "log_tail")})
        elif name == "flip_replica":
            decisions.append({k: v for k, v in ev.items()
                              if v is not None
                              and k not in ("seq", "wall", "proc",
                                            "kind")})
        elif name in ("flip_start", "flip_done"):
            flips.append({k: v for k, v in ev.items()
                          if v is not None
                          and k not in ("seq", "wall", "proc", "kind")})
        elif name == "serve_fleet_end":
            summary = {k: ev.get(k)
                       for k in ("proxied", "retried", "rejected")}

    # per-replica load skew over the sampled window: qps from the first
    # vs last request counter, queue-wait mean from the span aggregate
    for r in replicas.values():
        st = [s for s in r["stats"] if s.get("requests") is not None]
        r["qps"] = r["queue_wait_ms"] = None
        if len(st) >= 2 and st[-1]["t"] > st[0]["t"]:
            r["qps"] = round((st[-1]["requests"] - st[0]["requests"])
                             / (st[-1]["t"] - st[0]["t"]), 2)
        last = next((s for s in reversed(st)
                     if s.get("queue_wait_n")), None)
        if last:
            r["queue_wait_ms"] = round(
                1e3 * last["queue_wait_s"] / last["queue_wait_n"], 3)
        r["final"] = {k: st[-1].get(k) for k in ("epoch", "generation",
                                                 "requests")} if st else None
        del r["stats"]
    qps = [r["qps"] for r in replicas.values() if r["qps"]]
    skew = (round(max(qps) / max(min(qps), 1e-9), 2)
            if len(qps) >= 2 else None)
    return {"start": start,
            "replicas": [replicas[r] for r in sorted(replicas)],
            "qps_skew": skew, "flips": flips, "decisions": decisions,
            "summary": summary}


def _scenarios_section(events: list) -> dict | None:
    """Structured scenario comparison from a job-queue run's fleet stream
    (``python -m hmsc_tpu fleet --jobs`` with cv / waic / gradient jobs):
    one ``scenario_done`` verdict per scenario job — CV aggregate RMSE,
    WAIC, counterfactual-gradient response span — plus the queue-level
    context from ``queue_start`` / ``queue_end``."""
    events = [e for e in events if e.get("kind") == "fleet"]
    scen = [{k: v for k, v in e.items()
             if k not in ("seq", "t", "wall", "proc", "kind", "name")}
            for e in events if e.get("name") == "scenario_done"]
    if not scen:
        return None
    queue = None
    for ev in events:
        if ev.get("name") == "queue_end":
            queue = {k: ev.get(k) for k in ("status", "n_jobs", "n_tenants",
                                            "n_buckets", "wall_s")}
    return {"scenarios": scen, "queue": queue}


def render_scenarios(sec: dict) -> str:
    """Text rendering of the scenario-comparison section — one line per
    scenario job, so a cv / waic sweep over model variants reads as a
    single side-by-side table."""
    lines = ["== scenario comparison (job queue) =="]
    q = sec.get("queue")
    if q:
        lines.append(
            f"  queue: {q.get('status')}; {q.get('n_jobs')} job(s) -> "
            f"{q.get('n_tenants')} tenant(s) in {q.get('n_buckets')} "
            f"bucket(s), wall {q.get('wall_s')}s")
    w = max((len(s.get("scenario", "?")) for s in sec["scenarios"]),
            default=1)
    for s in sec["scenarios"]:
        flag = "" if s.get("ok") else "  [FAILED]"
        typ = s.get("type")
        if typ == "cv":
            verdict = (f"cv      rmse={s.get('rmse')}  "
                       f"({s.get('folds_done')}/{s.get('nfolds')} folds)")
        elif typ == "waic":
            verdict = f"waic    waic={s.get('waic')}"
        elif typ == "gradient":
            verdict = (f"gradient focal={s.get('focal')} "
                       f"ngrid={s.get('ngrid')} "
                       f"pred_span={s.get('pred_span')}")
        else:
            verdict = str({k: v for k, v in s.items()
                           if k not in ("scenario", "ok")})
        lines.append(f"  {s.get('scenario', '?'):<{w}}  {verdict}{flag}")
    return "\n".join(lines)


def _pipeline_section(events: list) -> dict | None:
    """Structured autopilot timeline from the daemon's ``kind="pipeline"``
    stream: per-drop lifecycle (seen -> accepted/rejected -> committed ->
    flipped), the supervision decisions taken along the way (worker
    restarts with backoff, heartbeat kills, chaos strikes, compaction
    retries), and the terminal summary."""
    events = [e for e in events if e.get("kind") == "pipeline"]
    if not events:
        return None

    def _strip(ev):
        return {k: v for k, v in ev.items()
                if v is not None and k not in ("seq", "wall", "proc",
                                               "kind")}

    drops: dict = {}
    decisions, flips, retention = [], [], []
    summary = None
    for ev in events:
        name, idx = ev.get("name"), ev.get("drop")
        if name == "drop_seen":
            drops[idx] = {"drop": idx, "file": ev.get("file"),
                          "status": "validating", "attempts": 0}
        elif name == "drop_accepted" and idx in drops:
            drops[idx].update(status="accepted", rows=ev.get("rows"))
        elif name == "drop_rejected" and idx in drops:
            drops[idx].update(status="rejected", reason=ev.get("reason"),
                              why=ev.get("detail"))
        elif name == "drop_already_committed" and idx in drops:
            drops[idx].update(status="committed", epoch=ev.get("epoch"),
                              deduplicated=True)
        elif name == "refit_dispatch" and idx in drops:
            drops[idx]["attempts"] = ev.get("attempt", 0)
        elif name == "epoch_committed" and idx in drops:
            drops[idx].update(status="committed", epoch=ev.get("epoch"),
                              samples=ev.get("samples"))
        elif name == "flip":
            flips.append(_strip(ev))
            if idx in drops:
                drops[idx]["flipped_to"] = ev.get("epoch")
        elif name == "retention":
            retention.append(_strip(ev))
        elif name in ("backoff", "heartbeat_silent", "attempt_timeout",
                      "chaos", "refit_exit", "compact", "compact_failed",
                      "drift_skipped", "flip_verified",
                      "pipeline_preempted", "pipeline_abort"):
            decisions.append(_strip(ev))
        elif name == "pipeline_end":
            summary = {k: v for k, v in ev.items()
                       if k not in ("seq", "t", "wall", "proc", "kind",
                                    "name")}
    return {"drops": [drops[i] for i in sorted(drops,
                                               key=lambda i: (i is None, i))],
            "decisions": decisions, "flips": flips,
            "retention": retention, "summary": summary}


def _alerts_section(run_dir: str, ops: list) -> dict | None:
    """SLO alerts fired against this run: ``kind="alert"`` events from the
    shared fleet/pipeline stream (written by a supervisor's or autopilot's
    in-process hub) plus the standalone hub's ``alerts.jsonl`` under the
    run directory (``python -m hmsc_tpu watch``)."""
    from .hub import ALERTS_FILE
    alerts = [e for e in ops if e.get("kind") == "alert"]
    run_dir = os.fspath(run_dir)
    if os.path.isdir(run_dir):
        extra = _read_jsonl(os.path.join(run_dir, ALERTS_FILE)) or []
        alerts += [e for e in extra if e.get("kind") == "alert"]
    if not alerts:
        return None
    alerts.sort(key=lambda e: e.get("wall") or 0.0)
    stripped = [{k: e.get(k) for k in ("t", "wall", "name", "rule",
                                       "subject", "value", "threshold",
                                       "severity")}
                for e in alerts]
    by_rule: dict = {}
    for a in stripped:
        r = a.get("rule") or a.get("name") or "?"
        by_rule[r] = by_rule.get(r, 0) + 1
    return {"count": len(stripped), "by_rule": by_rule,
            "alerts": stripped}


def _bar(frac: float, width: int = 24) -> str:
    n = max(0, min(width, int(round(frac * width))))
    return "#" * n + "." * (width - n)


def render_report(report: dict) -> str:
    """Human-readable text rendering of :func:`build_report`'s output."""
    lines = [f"run telemetry report: {report['run_dir']}",
             f"status: {report['status']}   "
             f"ranks: {report['ranks'] or '(no event streams found)'}"]
    for proc in report["ranks"]:
        r = report["per_rank"][proc]
        wall = max(r["wall_s"], 1e-9)
        lines.append("")
        resumed = (f", {r['resumes']} resume(s)" if r.get("resumes") else "")
        lines.append(f"== rank {proc} ({r['status']}, wall {r['wall_s']:.2f}s"
                     f"{resumed}, {r['events']} events) ==")
        cfg = r["config"]
        if cfg:
            keys = ("samples", "transient", "thin", "n_chains",
                    "process_count", "checkpoint_every")
            lines.append("config: " + ", ".join(
                f"{k}={cfg[k]}" for k in keys if k in cfg))
        lines.append("-- phase timeline (span totals) --")
        spans = sorted(r["spans"].items(), key=lambda kv: -kv[1]["total_s"])
        for name, agg in spans:
            frac = agg["total_s"] / wall
            lines.append(f"  {name:<18} {_bar(frac)} {agg['total_s']:8.3f}s "
                         f"({100 * frac:5.1f}%)  x{agg['count']} "
                         f"max {agg['max_s']:.3f}s")
        if not spans:
            lines.append("  (no spans recorded)")
        thr = r["throughput"]
        if thr:
            lines.append("-- throughput curve --")
            peak = max((p["draws_per_s"] or 0.0) for p in thr) or 1.0
            for p in thr:
                rate = p["draws_per_s"] or 0.0
                lines.append(f"  t={p['t']:8.2f}s  "
                             f"samples={p['samples_done']!s:<8} "
                             f"{_bar(rate / peak)} {rate:9.2f} draws/s")
        h = r["health"]
        if h:
            lines.append("-- health (latest) --")
            lines.append(
                f"  diverged_chains={h.get('diverged_chains')}  "
                f"rhat_max={h.get('rhat_max')}  ess_min={h.get('ess_min')}  "
                f"monitored_draws={h.get('monitored')}x{h.get('n_draws')}")
            if h.get("nf_active"):
                lines.append(f"  nf_active per level: {h['nf_active']}")
        io = r.get("io", {})
        if io:
            lines.append("-- checkpoint I/O breakdown --")
            for k, v in io.items():
                size = (f"  {v['nbytes'] / 1e6:.2f} MB"
                        if v.get("nbytes") else "")
                lines.append(f"  {k:<16} {v['total_s']:8.3f}s "
                             f"x{v['count']}{size}")
        cost = r.get("cost")
        if cost:
            lines.append("-- cost attribution --")
            for prof in cost.get("updater_profile", []):
                where = (f"model={prof['model']}" if prof.get("model")
                         else f"sweep={prof.get('sweep')}")
                att = prof.get("attributed_frac")
                fw = prof.get("fused_wall_s")
                lines.append(
                    f"  per-updater wall ({where}, reps={prof.get('reps')}"
                    + (f", fused {fw * 1e3:.3f} ms" if fw else "")
                    + (f", attributed {att * 100:.0f}%" if att else "")
                    + ")")
                for b in prof.get("updaters", []):
                    lines.append(f"    {b['name']:<20} "
                                 f"{b['wall_s'] * 1e3:9.4f} ms "
                                 f"{_bar(b.get('share', 0.0))} "
                                 f"({b.get('share', 0.0) * 100:5.1f}%)")
            for led in cost.get("ledger", []):
                lines.append(
                    f"  static ledger {led.get('model')}: sweep flops "
                    f"{led.get('flops_total')}, peak temp "
                    f"{led.get('temp_bytes_peak')} B over "
                    f"{led.get('programs')} programs")
    if report["skew"]:
        lines.append("")
        lines.append("== cross-rank stall / skew (committer marks) ==")
        for s in report["skew"]:
            lines.append(
                f"  mark {s.get('tag')}: segment skew (max-min) "
                f"{s.get('skew_s'):.4f}s  per-rank segment_s="
                f"{s.get('segment_s')}  barrier_wait_s="
                f"{s.get('barrier_wait_s')}")
    fleet = report.get("fleet")
    if fleet:
        lines.append("")
        lines.append("== fleet timeline (supervisor) ==")
        for a in fleet["attempts"]:
            exits = ", ".join(
                f"r{r}:{v['outcome']}"
                for r, v in sorted(a["exits"].items(), key=lambda kv:
                                   int(kv[0]))) or "(in flight)"
            lines.append(f"  attempt {a['attempt']}: {a['action']} "
                         f"x{a['nprocs']} rank(s) -> {exits}")
        for d in fleet["decisions"]:
            name = d.get("name", "?")
            t = d.get("t")
            detail = ", ".join(f"{k}={v}" for k, v in d.items()
                               if v is not None and k not in ("name", "t"))
            stamp = f" t={t:.2f}s" if isinstance(t, float) else ""
            lines.append(f"  [{name}]{stamp} {detail}")
        s = fleet.get("summary")
        if s:
            lines.append(
                f"  outcome: {s.get('status')} after {s.get('attempts')} "
                f"attempt(s), {s.get('restarts')} restart(s), "
                f"{s.get('shrinks')} shrink(s), {s.get('grows')} grow(s); "
                f"fleet {s.get('fleet_size')}, draws lost "
                f"{s.get('draws_lost')}, wall {s.get('wall_s')}s")
    sf = report.get("serve_fleet")
    if sf:
        lines.append("")
        lines.append("== serving fleet timeline (front end) ==")
        if sf.get("start"):
            s0 = sf["start"]
            lines.append(f"  fleet of {s0.get('replicas')} replica(s) on "
                         f"{s0.get('source')}"
                         + (f", draw_shards={s0['draw_shards']}"
                            if s0.get("draw_shards") else ""))
        for r in sf["replicas"]:
            exits = ", ".join(e["outcome"] or f"rc={e['rc']}"
                              for e in r["exits"]) or "none"
            fin = r.get("final") or {}
            lines.append(
                f"  replica {r['rank']}: {r['spawns']} spawn(s), "
                f"exits: {exits}; "
                f"qps={r['qps'] if r['qps'] is not None else '?'} "
                f"queue_wait_ms="
                f"{r['queue_wait_ms'] if r['queue_wait_ms'] is not None else '?'}"
                + (f"  (epoch {fin.get('epoch')}, gen "
                   f"{fin.get('generation')}, {fin.get('requests')} "
                   f"requests)" if fin else ""))
        if sf.get("qps_skew") is not None:
            lines.append(f"  qps skew (max/min replica): {sf['qps_skew']}x")
        for d in sf["decisions"]:
            name = d.get("name", "?")
            t = d.get("t")
            detail = ", ".join(f"{k}={v}" for k, v in d.items()
                               if k not in ("name", "t"))
            stamp = f" t={t:.2f}s" if isinstance(t, float) else ""
            lines.append(f"  [{name}]{stamp} {detail}")
        for fl in sf["flips"]:
            if fl.get("name") == "flip_done":
                lines.append(
                    f"  flip -> epoch {fl.get('epoch')}: "
                    f"{'acknowledged' if fl.get('ok') else 'FAILED'} "
                    f"in {fl.get('wall_s')}s "
                    f"({json.dumps(fl.get('outcomes'))})")
        s = sf.get("summary")
        if s:
            lines.append(f"  front end: {s.get('proxied')} proxied, "
                         f"{s.get('retried')} retried, "
                         f"{s.get('rejected')} rejected")
    scen = report.get("scenarios")
    if scen:
        lines.append("")
        lines.append(render_scenarios(scen))
    pipe = report.get("pipeline")
    if pipe:
        lines.append("")
        lines.append("== autopilot timeline (pipeline) ==")
        for d in pipe["drops"]:
            extra = ""
            if d["status"] == "committed":
                extra = f" -> epoch {d.get('epoch')}"
                if d.get("deduplicated"):
                    extra += " (already committed; deduplicated)"
                if d.get("flipped_to") is not None:
                    extra += ", flipped to serving"
            elif d["status"] == "rejected":
                extra = f" ({d.get('reason')}: {d.get('why')})"
            att = (f" [{d['attempts']} attempt(s)]"
                   if d.get("attempts", 0) > 1 else "")
            lines.append(f"  drop {d['drop']}: {d.get('file')} "
                         f"{d['status']}{att}{extra}")
        for d in pipe["decisions"]:
            name = d.get("name", "?")
            t = d.get("t")
            detail = ", ".join(f"{k}={v}" for k, v in d.items()
                               if k not in ("name", "t", "log_tail"))
            stamp = f" t={t:.2f}s" if isinstance(t, float) else ""
            lines.append(f"  [{name}]{stamp} {detail}")
        for r in pipe["retention"]:
            lines.append(
                f"  [retention] epochs={r.get('epochs')}"
                + (f" unpinned={r['unpinned']}" if r.get("unpinned") else "")
                + (f" reclaimed={r['reclaimed']}"
                   if r.get("reclaimed") else ""))
        s = pipe.get("summary")
        if s:
            lines.append(
                f"  outcome: {s.get('status')}; drops "
                f"{s.get('drops_committed')} committed / "
                f"{s.get('drops_rejected')} rejected of "
                f"{s.get('drops_seen')} seen; epochs committed "
                f"{s.get('epochs_committed')}, flips {s.get('flips')}, "
                f"worker restarts {s.get('worker_restarts')}, compactions "
                f"{s.get('compactions')}, epochs reclaimed "
                f"{s.get('epochs_reclaimed')}, wall {s.get('wall_s')}s")
    al = report.get("alerts")
    if al:
        lines.append("")
        lines.append("== SLO alerts ==")
        lines.append("  " + ", ".join(f"{r}: {n}" for r, n in
                                      sorted(al["by_rule"].items())))
        for a in al["alerts"]:
            rule = a.get("rule") or a.get("name")
            lines.append(
                f"  [{a.get('severity')}] {rule} {a.get('subject')}: "
                f"{a.get('value')} > {a.get('threshold')}")
    return "\n".join(lines)


def prometheus_textfile(report: dict) -> str:
    """Prometheus textfile-collector export of the report's final gauges
    (every name from :data:`PROM_GAUGES` — see the module docstring)."""
    out = ["# HELP hmsc_tpu_span_seconds_total host-loop span time by stage",
           "# TYPE hmsc_tpu_span_seconds_total gauge"]
    for proc in report["ranks"]:
        r = report["per_rank"][proc]
        for name, agg in sorted(r["spans"].items()):
            _gauge(out, "hmsc_tpu_span_seconds_total",
                   f'{{span="{name}",proc="{proc}"}}',
                   f'{agg["total_s"]:.6f}')
    out += ["# TYPE hmsc_tpu_run_wall_seconds gauge",
            "# TYPE hmsc_tpu_samples_done gauge",
            "# TYPE hmsc_tpu_draws_per_second gauge",
            "# TYPE hmsc_tpu_diverged_chains gauge",
            "# TYPE hmsc_tpu_rhat_max gauge",
            "# TYPE hmsc_tpu_ess_min gauge"]
    for proc in report["ranks"]:
        r = report["per_rank"][proc]
        _gauge(out, "hmsc_tpu_run_wall_seconds", f'{{proc="{proc}"}}',
               f'{r["wall_s"]:.4f}')
        h = r["health"]
        if h:
            for key, metric in (("samples_done", "hmsc_tpu_samples_done"),
                                ("draws_per_s", "hmsc_tpu_draws_per_second"),
                                ("diverged_chains",
                                 "hmsc_tpu_diverged_chains"),
                                ("rhat_max", "hmsc_tpu_rhat_max"),
                                ("ess_min", "hmsc_tpu_ess_min")):
                v = h.get(key)
                if v is not None:
                    _gauge(out, metric, f'{{proc="{proc}"}}', v)
    if report["skew"]:
        out.append("# TYPE hmsc_tpu_rank_skew_seconds gauge")
        _gauge(out, "hmsc_tpu_rank_skew_seconds", "",
               report["skew"][-1].get("skew_s", 0.0))
    # cost attribution: the latest per-updater profile and ledger digests
    typed = ledger_typed = False
    for proc in report["ranks"]:
        cost = report["per_rank"][proc].get("cost")
        if not cost:
            continue
        profs = cost.get("updater_profile", [])
        if profs and not typed:
            out += ["# TYPE hmsc_tpu_updater_wall_seconds gauge",
                    "# TYPE hmsc_tpu_updater_share gauge",
                    "# TYPE hmsc_tpu_profile_attributed_fraction gauge"]
            typed = True
        for prof in profs[-1:]:
            for b in prof.get("updaters", []):
                lbl = f'{{updater="{b["name"]}",proc="{proc}"}}'
                _gauge(out, "hmsc_tpu_updater_wall_seconds", lbl,
                       f'{b["wall_s"]:.7f}')
                _gauge(out, "hmsc_tpu_updater_share", lbl,
                       b.get("share", 0.0))
            if prof.get("attributed_frac") is not None:
                _gauge(out, "hmsc_tpu_profile_attributed_fraction",
                       f'{{proc="{proc}"}}', prof["attributed_frac"])
        leds = cost.get("ledger", [])
        if leds and not ledger_typed:
            out += ["# TYPE hmsc_tpu_ledger_flops_total gauge",
                    "# TYPE hmsc_tpu_ledger_temp_bytes_peak gauge"]
            ledger_typed = True
        for led in leds:
            lbl = f'{{model="{led.get("model")}",proc="{proc}"}}'
            if led.get("flops_total") is not None:
                _gauge(out, "hmsc_tpu_ledger_flops_total", lbl,
                       led["flops_total"])
            _gauge(out, "hmsc_tpu_ledger_temp_bytes_peak", lbl,
                   led.get("temp_bytes_peak", 0))
    return "\n".join(out) + "\n"


def serving_prometheus_textfile(stats: dict) -> str:
    """Prometheus textfile-collector export of a serving engine's
    :meth:`~hmsc_tpu.serve.ServingEngine.stats` — the serving counterpart
    of :func:`prometheus_textfile` (same span-gauge naming, ``proc="serve"``
    label), written by ``python -m hmsc_tpu serve --prom`` and returned
    live on the server's ``GET /metrics``."""
    out = ["# HELP hmsc_tpu_span_seconds_total serving span time by stage",
           "# TYPE hmsc_tpu_span_seconds_total gauge",
           "# TYPE hmsc_tpu_span_seconds_max gauge",
           "# TYPE hmsc_tpu_span_count gauge"]
    for name, agg in sorted(stats.get("spans", {}).items()):
        lbl = f'{{span="{name}",proc="serve"}}'
        _gauge(out, "hmsc_tpu_span_seconds_total", lbl,
               f"{agg['total_s']:.6f}")
        _gauge(out, "hmsc_tpu_span_seconds_max", lbl, f"{agg['max_s']:.6f}")
        _gauge(out, "hmsc_tpu_span_count", lbl, agg["count"])
    cache = stats.get("cache", {})
    gauges = [
        ("hmsc_tpu_serve_requests_total", stats.get("requests", 0)),
        ("hmsc_tpu_serve_batches_total", stats.get("batches", 0)),
        ("hmsc_tpu_serve_device_calls_total",
         stats.get("device_calls", 0)),
        ("hmsc_tpu_serve_rows_served_total", stats.get("rows_served", 0)),
        ("hmsc_tpu_serve_rows_padded_total", stats.get("rows_padded", 0)),
        ("hmsc_tpu_serve_kernel_cache_hits_total", cache.get("hits", 0)),
        ("hmsc_tpu_serve_kernel_cache_misses_total",
         cache.get("misses", 0)),
        ("hmsc_tpu_serve_kernel_cache_size", cache.get("size", 0)),
        ("hmsc_tpu_serve_posterior_draws", stats.get("n_draws", 0)),
    ]
    for name, v in gauges:
        out.append(f"# TYPE {name} gauge")
        _gauge(out, name, "", v)
    return "\n".join(out) + "\n"


def hub_prometheus_textfile(snap: dict) -> str:
    """Prometheus textfile-collector export of a live
    :meth:`~hmsc_tpu.obs.hub.MetricsHub.snapshot` — the fleet-wide
    counterpart of :func:`prometheus_textfile`, served on the hub's
    ``GET /metrics`` (``python -m hmsc_tpu watch --serve``).  Routes
    through the same frozen :data:`PROM_GAUGES` registry."""
    out = ["# HELP hmsc_tpu_watch_streams JSONL streams tailed by the hub",
           "# TYPE hmsc_tpu_watch_streams gauge",
           "# TYPE hmsc_tpu_watch_events_total gauge",
           "# TYPE hmsc_tpu_watch_active_runs gauge",
           "# TYPE hmsc_tpu_watch_draws_per_second gauge",
           "# TYPE hmsc_tpu_watch_alerts_fired_total gauge"]
    _gauge(out, "hmsc_tpu_watch_streams", "", snap.get("n_streams", 0))
    _gauge(out, "hmsc_tpu_watch_events_total", "", snap.get("events", 0))
    _gauge(out, "hmsc_tpu_watch_active_runs", "",
           snap.get("active_runs", 0))
    _gauge(out, "hmsc_tpu_watch_draws_per_second", "",
           snap.get("draws_per_s_total", 0.0))
    _gauge(out, "hmsc_tpu_watch_alerts_fired_total", "",
           (snap.get("alerts") or {}).get("fired", 0))
    skew = (snap.get("skew") or {}).get("last_s")
    if skew is not None:
        out.append("# TYPE hmsc_tpu_watch_rank_skew_seconds gauge")
        _gauge(out, "hmsc_tpu_watch_rank_skew_seconds", "", skew)
    diverged = sum((st.get("health") or {}).get("diverged_chains") or 0
                   for st in (snap.get("streams") or {}).values())
    out.append("# TYPE hmsc_tpu_watch_diverged_chains gauge")
    _gauge(out, "hmsc_tpu_watch_diverged_chains", "", diverged)
    q = snap.get("queue") or {}
    if q:
        for key, name in (("depth", "hmsc_tpu_watch_queue_depth"),
                          ("occupancy", "hmsc_tpu_watch_occupancy_ratio"),
                          ("padding_waste",
                           "hmsc_tpu_watch_padding_waste_ratio")):
            if q.get(key) is not None:
                out.append(f"# TYPE {name} gauge")
                _gauge(out, name, "", q[key])
    sv = snap.get("serving") or {}
    for key, name in (("epoch_lag", "hmsc_tpu_watch_epoch_lag"),
                      ("generation_lag",
                       "hmsc_tpu_watch_generation_lag")):
        if sv.get(key) is not None:
            out.append(f"# TYPE {name} gauge")
            _gauge(out, name, "", sv[key])
    lat = (sv.get("flip_latency_s") or {}).get("last")
    if lat is not None:
        out.append("# TYPE hmsc_tpu_watch_flip_latency_seconds gauge")
        _gauge(out, "hmsc_tpu_watch_flip_latency_seconds", "", lat)
    p99s = [(f'replica="{r}"', rep["queue_wait_p99_s"])
            for r, rep in sorted((sv.get("replicas") or {}).items())
            if rep.get("queue_wait_p99_s") is not None]
    p99s += [(f'stream="{rel}"', st["queue_wait_p99_s"])
             for rel, st in sorted((snap.get("streams") or {}).items())
             if st.get("queue_wait_p99_s") is not None]
    if p99s:
        out.append("# TYPE hmsc_tpu_watch_queue_wait_p99_seconds gauge")
        for lbl, v in p99s:
            _gauge(out, "hmsc_tpu_watch_queue_wait_p99_seconds",
                   "{" + lbl + "}", v)
    hbs = snap.get("heartbeats") or {}
    if hbs:
        out.append("# TYPE hmsc_tpu_watch_heartbeat_age_seconds gauge")
        for d, ranks in sorted(hbs.items()):
            for rank, age in sorted(ranks.items()):
                if age is not None:
                    _gauge(out, "hmsc_tpu_watch_heartbeat_age_seconds",
                           f'{{dir="{d}",rank="{rank}"}}', age)
    return "\n".join(out) + "\n"


def epoch_drift_report(run_dir: str, hM0=None,
                       params: tuple = ("Beta",)) -> dict:
    """Cross-epoch posterior drift for a streaming-refit run directory.

    For every committed epoch (:mod:`hmsc_tpu.refit`), the monitored
    parameters' pooled posterior mean/sd are computed, and each
    consecutive epoch pair gets a Welch-style drift score per entry:
    ``z = |mean_k - mean_{k-1}| / sqrt(sd_{k-1}^2/ess_{k-1}
    + sd_k^2/ess_k)`` with each window's mean-variance scaled by its
    EFFECTIVE sample size (autocorrelated MCMC draws carry far less
    information than their raw count — a plain var/n would flag pure
    Monte-Carlo wobble as drift).  On this scale MC wobble sits near 1
    and a real posterior shift (the appended data moving the estimand)
    stands out.  Epoch 0 is the original fit; the report is the audit
    trail for "did the refreshed posterior move because of the new rows,
    or break?"."""
    import numpy as np

    from ..post.diagnostics import effective_size
    from ..refit.epochs import epoch_metadata, load_epoch_posterior
    from ..utils.checkpoint import committed_epochs

    ks = committed_epochs(run_dir)
    if len(ks) == 0:
        raise ValueError(f"{run_dir}: no committed epochs to report on")
    stats = {}
    epochs_out = []
    for k in ks:
        post, hM, _ = load_epoch_posterior(run_dir, k, hM0=hM0)
        ent = {"epoch": k, "ny": int(hM.ny), "samples": int(post.samples),
               "n_chains": int(post.n_chains)}
        meta = epoch_metadata(run_dir, k)
        if meta:
            ent.update(new_rows=meta.get("new_rows"),
                       transient_sweeps=meta.get("transient_sweeps"))
        epochs_out.append(ent)
        per = {}
        for p in params:
            if p not in post.arrays:
                continue
            a = np.asarray(post.pooled(p), dtype=float)
            # ESS from the chain-structured draws (autocorrelation-aware)
            ess = np.maximum(np.asarray(
                effective_size(np.asarray(post[p], dtype=float)),
                dtype=float), 2.0)
            per[p] = (a.mean(axis=0), a.std(axis=0, ddof=1), ess)
        stats[k] = per
    pairs = []
    for k0, k1 in zip(ks, ks[1:]):
        per_param = {}
        for p in params:
            if p not in stats[k0] or p not in stats[k1]:
                continue
            m0, s0, n0 = stats[k0][p]
            m1, s1, n1 = stats[k1][p]
            se = np.sqrt(s0 ** 2 / n0 + s1 ** 2 / n1)
            z = np.abs(m1 - m0) / np.maximum(se, 1e-12)
            per_param[p] = {"max_z": round(float(z.max()), 3),
                            "mean_z": round(float(z.mean()), 3),
                            "n_entries": int(z.size)}
        pairs.append({"from": k0, "to": k1, "params": per_param})
    return {"run_dir": os.fspath(run_dir), "epochs": epochs_out,
            "drift": pairs}


def render_drift(drift: dict) -> str:
    """Text rendering of :func:`epoch_drift_report`."""
    out = [f"cross-epoch posterior drift — {drift['run_dir']}", ""]
    out.append("  epoch   ny      samples  chains  +rows  transient")
    for e in drift["epochs"]:
        out.append(
            f"  {e['epoch']:>5}   {e['ny']:<7} {e['samples']:<8} "
            f"{e['n_chains']:<7} {e.get('new_rows') or '-':<6} "
            f"{e.get('transient_sweeps') or '-'}")
    out.append("")
    for pair in drift["drift"]:
        out.append(f"  epoch {pair['from']} -> {pair['to']}:")
        for p, d in pair["params"].items():
            out.append(
                f"    {p:<8} max_z={d['max_z']:<8} mean_z={d['mean_z']:<8}"
                f" ({d['n_entries']} entries)")
    if not drift["drift"]:
        out.append("  (single epoch — nothing to compare yet)")
    return "\n".join(out)


def report_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hmsc_tpu report",
        description="render a run's telemetry: phase timeline, throughput, "
                    "cross-rank skew, checkpoint I/O, MCMC health")
    ap.add_argument("run_dir",
                    help="run directory holding events-p<rank>.jsonl "
                         "(usually the checkpoint directory), or one "
                         "events file")
    ap.add_argument("--json", action="store_true",
                    help="emit the structured report as JSON")
    ap.add_argument("--prom", metavar="FILE", default=None,
                    help="also write a Prometheus textfile-collector "
                         "export of the final gauges to FILE")
    ap.add_argument("--drift", action="store_true",
                    help="cross-epoch posterior drift report for a "
                         "streaming-refit run directory (epoch 0 vs each "
                         "committed refit epoch; Welch-style z per "
                         "monitored entry)")
    ap.add_argument("--scenarios", action="store_true",
                    help="scenario comparison for a job-queue run with "
                         "cv / waic / gradient jobs: one verdict line per "
                         "scenario (CV RMSE, WAIC, gradient response span)")
    args = ap.parse_args(argv)

    if args.drift:
        drift = epoch_drift_report(args.run_dir)
        print(json.dumps(drift, indent=1) if args.json
              else render_drift(drift))
        return 0

    if args.scenarios:
        sec = _scenarios_section(load_fleet_events(args.run_dir))
        if sec is None:
            print(f"{args.run_dir}: no scenario_done events "
                  "(not a scenario job-queue run?)")
            return 1
        print(json.dumps(sec, indent=1) if args.json
              else render_scenarios(sec))
        return 0

    report = build_report(args.run_dir)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_report(report))
    if args.prom:
        with open(args.prom, "w") as f:
            f.write(prometheus_textfile(report))
    return 0 if (report["ranks"] or report.get("fleet")
                 or report.get("serve_fleet")
                 or report.get("pipeline")) else 1


if __name__ == "__main__":
    raise SystemExit(report_main())
