"""Declarative SLO/alert rules evaluated by the metrics hub.

A rule is a JSON-loadable threshold on one fleet-level aggregate the
:class:`~hmsc_tpu.obs.hub.MetricsHub` maintains — the quantities that,
historically, each required a human reading ``report`` *after* the run
died: a rank that stopped heartbeating, a stream whose throughput stalled,
a tenant whose chains are diverging, cross-rank skew accumulating into
gather stalls, serving queue waits, a replica serving a stale epoch after
a flip, a bucket burning half its cells on padding.

The engine is edge-triggered with per-``(rule, subject)`` latching: an
alert fires ONCE when its condition first becomes true for a subject and
re-arms only after the condition clears — a stalled rank does not emit one
alert per hub poll.  Fired alerts become ``kind="alert"`` events in the
hub's alert stream (and, when a supervisor/autopilot attaches the hub
in-process, in that daemon's own decision log), so the ``report`` CLI
renders them on the same timeline as the decisions they motivated.

Rule config is a JSON list of objects: ``{"rule": <name>, "threshold":
<number>, "severity": "info"|"warn"|"page", "enabled": true}``.  Unknown
rule names are rejected up front (a typo'd config must not silently
monitor nothing).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

__all__ = ["AlertRule", "AlertEngine", "KNOWN_RULES", "default_rules",
           "load_rules"]

# rule name -> (default threshold, unit, severity, one-line meaning)
KNOWN_RULES = {
    "heartbeat_gap": (10.0, "s", "page",
                      "a rank/replica heartbeat is older than threshold"),
    "throughput_stall": (60.0, "s", "page",
                         "an active run stream reported no segment "
                         "progress for threshold seconds"),
    "divergence_rate": (0.5, "frac", "warn",
                        "diverged chains / total chains on one stream "
                        "exceeds threshold"),
    "rank_skew": (5.0, "s", "warn",
                  "latest cross-rank commit skew exceeds threshold"),
    "queue_wait_p99": (5.0, "s", "warn",
                       "serving queue-wait p99 over the rolling window "
                       "exceeds threshold"),
    "epoch_lag": (0.0, "epochs", "warn",
                  "serving replicas disagree on epoch/generation by more "
                  "than threshold"),
    "padding_waste": (0.5, "frac", "info",
                      "a batched bucket (or the queue aggregate) pads "
                      "more than threshold of its cells"),
}


@dataclass(frozen=True)
class AlertRule:
    """One threshold rule; immutable so a rule set is shareable."""

    rule: str
    threshold: float
    severity: str = "warn"
    enabled: bool = True

    def __post_init__(self):
        if self.rule not in KNOWN_RULES:
            raise ValueError(
                f"unknown alert rule {self.rule!r} — known rules: "
                f"{sorted(KNOWN_RULES)}")


def default_rules() -> list[AlertRule]:
    """One enabled rule per known name at its default threshold."""
    return [AlertRule(name, thr, sev)
            for name, (thr, _unit, sev, _doc) in KNOWN_RULES.items()]


def load_rules(path: str) -> list[AlertRule]:
    """Load a JSON rule list; entries override the defaults field-wise."""
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, list):
        raise ValueError(f"{path}: alert config must be a JSON list")
    rules = []
    for i, entry in enumerate(raw):
        if not isinstance(entry, dict) or "rule" not in entry:
            raise ValueError(f"{path}[{i}]: each entry needs a 'rule' key")
        name = entry["rule"]
        extra = set(entry) - {"rule", "threshold", "severity", "enabled"}
        if extra:
            raise ValueError(f"{path}[{i}]: unknown keys {sorted(extra)}")
        dflt = KNOWN_RULES.get(name, (0.0, "", "warn", ""))
        rules.append(AlertRule(
            name,
            float(entry.get("threshold", dflt[0])),
            str(entry.get("severity", dflt[2])),
            bool(entry.get("enabled", True))))
    return rules


# -- per-rule snapshot probes ------------------------------------------------
# each probe maps a hub snapshot to [(subject, observed value)] — the
# engine compares value > threshold; probes never raise on partial
# snapshots (the hub may not have seen every stream kind yet)

def _probe_heartbeat_gap(snap):
    out = []
    for d, ranks in (snap.get("heartbeats") or {}).items():
        for rank, age in ranks.items():
            if age is not None:
                out.append((f"{d}:p{rank}", float(age)))
    return out


def _probe_throughput_stall(snap):
    out = []
    now = snap.get("wall", 0.0)
    for rel, st in (snap.get("streams") or {}).items():
        if st.get("kind") != "run" or st.get("ended") \
                or not st.get("started"):
            continue
        last = st.get("last_progress_wall")
        if last is not None:
            out.append((rel, float(now - last)))
    return out


def _probe_divergence_rate(snap):
    out = []
    for rel, st in (snap.get("streams") or {}).items():
        h = st.get("health") or {}
        div, nc = h.get("diverged_chains"), st.get("n_chains")
        if div is not None and nc:
            out.append((rel, float(div) / float(nc)))
    for name, t in (snap.get("tenants") or {}).items():
        div, nc = t.get("diverged"), t.get("n_chains")
        if div is not None and nc:
            out.append((f"tenant:{name}", float(div) / float(nc)))
    return out


def _probe_rank_skew(snap):
    last = (snap.get("skew") or {}).get("last_s")
    return [("fleet", float(last))] if last is not None else []


def _probe_queue_wait_p99(snap):
    out = []
    serving = snap.get("serving") or {}
    for rank, rep in (serving.get("replicas") or {}).items():
        p99 = rep.get("queue_wait_p99_s")
        if p99 is not None:
            out.append((f"replica:{rank}", float(p99)))
    for rel, st in (snap.get("streams") or {}).items():
        p99 = st.get("queue_wait_p99_s")
        if p99 is not None:
            out.append((rel, float(p99)))
    return out


def _probe_epoch_lag(snap):
    serving = snap.get("serving") or {}
    out = []
    for key in ("epoch_lag", "generation_lag"):
        v = serving.get(key)
        if v is not None:
            out.append((key, float(v)))
    return out


def _probe_padding_waste(snap):
    out = []
    q = snap.get("queue") or {}
    if q.get("padding_waste") is not None:
        out.append(("queue", float(q["padding_waste"])))
    for bkey, w in (q.get("bucket_waste") or {}).items():
        out.append((f"bucket:{bkey}", float(w)))
    return out


_PROBES = {
    "heartbeat_gap": _probe_heartbeat_gap,
    "throughput_stall": _probe_throughput_stall,
    "divergence_rate": _probe_divergence_rate,
    "rank_skew": _probe_rank_skew,
    "queue_wait_p99": _probe_queue_wait_p99,
    "epoch_lag": _probe_epoch_lag,
    "padding_waste": _probe_padding_waste,
}


class AlertEngine:
    """Evaluate a rule set against successive hub snapshots.

    Single-threaded by design: the hub calls :meth:`evaluate` from its own
    poll loop (the hub holds any cross-thread locking)."""

    def __init__(self, rules=None):
        self.rules = list(default_rules() if rules is None else rules)
        self._active: set[tuple[str, str]] = set()   # latched (rule, subj)
        self.n_fired = 0

    def active(self) -> list[str]:
        return sorted(f"{r}:{s}" for r, s in self._active)

    def evaluate(self, snap: dict) -> list[dict]:
        """Newly-firing alerts for this snapshot (edge-triggered); each is
        a JSON-safe dict ready to emit as a ``kind="alert"`` event."""
        fired = []
        seen_true: set[tuple[str, str]] = set()
        for rule in self.rules:
            if not rule.enabled:
                continue
            probe = _PROBES[rule.rule]
            for subject, value in probe(snap):
                key = (rule.rule, subject)
                if value > rule.threshold:
                    seen_true.add(key)
                    if key not in self._active:
                        self._active.add(key)
                        self.n_fired += 1
                        fired.append({
                            "rule": rule.rule, "subject": subject,
                            "value": round(float(value), 6),
                            "threshold": rule.threshold,
                            "severity": rule.severity,
                        })
        # re-arm every latched pair whose condition cleared (or whose
        # subject vanished from the snapshot — a finished stream clears)
        self._active &= seen_true
        return fired
