"""Run telemetry and observability for hmsc_tpu.

Every checkpointed run records a structured, rank-tagged JSONL event
stream (``events-p<rank>.jsonl``, next to the snapshots) off the critical
path via the sampler's background writer: timed host-loop spans (compile,
dispatch, device→host fetch, shard/state/manifest writes, barrier waits,
GC, splice repairs), per-segment MCMC health metrics (throughput,
divergence counters, nf-adaptation trajectory, running R-hat/ESS over a
small monitored subset), and cross-rank skew aggregated by the committer
at every commit mark.  ``python -m hmsc_tpu report <run_dir>`` renders a
completed or in-flight run from the stream; :mod:`hmsc_tpu.obs.log`
routes all library progress output (rank-prefixed) in place of bare
``print``.

Sweep-level cost attribution lives in :mod:`hmsc_tpu.obs.profile`
(``python -m hmsc_tpu profile``): a committed static flops/HBM ledger per
Gibbs block plus measured per-updater wall timing, with the in-run
``sample_mcmc(profile_updaters=...)`` hook feeding the same event stream.

Telemetry is provably draw-stream-invariant — it only ever sees host-side
copies — and adds <2% host-loop overhead
(``benchmarks/bench_host_loop.py`` gates the isolated per-segment
telemetry cost scaled by segment count, and asserts draw bit-identity
across the on/off A/B).
"""

from .events import (RunTelemetry, SCHEMA_VERSION, compact_summary,
                     events_path)
from .log import RunLogger, get_logger
from .health import RunningDiagnostics, rhat_ess
from .trace import (TraceContext, TRACE_ENV, current_context,
                    inherit_or_mint, trace_env)
from .alerts import AlertEngine, AlertRule, default_rules, load_rules
from .hub import ALERTS_FILE, JsonlTailer, MetricsHub

__all__ = [
    "RunTelemetry", "SCHEMA_VERSION", "compact_summary", "events_path",
    "RunLogger", "get_logger",
    "RunningDiagnostics", "rhat_ess",
    "TraceContext", "TRACE_ENV", "current_context", "inherit_or_mint",
    "trace_env",
    "AlertEngine", "AlertRule", "default_rules", "load_rules",
    "ALERTS_FILE", "JsonlTailer", "MetricsHub",
]
