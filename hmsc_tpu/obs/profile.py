"""Sweep-level cost attribution: the static cost ledger and the measured
per-updater profile behind ``python -m hmsc_tpu profile``.

The ROADMAP's next runtime bets (within-model sharding of the Gibbs sweep,
multi-tenant batched fitting) need to know *where* a sweep's time, FLOPs
and HBM go per Gibbs block — today's telemetry observes the host loop
only, with the jitted sweep as one opaque span.  This module opens the
sweep up along the block schedule (:func:`hmsc_tpu.mcmc.sweep.
make_sweep_schedule`):

- **Static cost ledger** (``--static``): every registered updater
  (``mcmc/registry.py``), the assembled sweep, and the jitted segment
  runner are lowered and compiled on the four canonical analysis specs
  (the same spec/registry plumbing the jaxpr audits use), and XLA's
  ``cost_analysis()`` (flops, bytes accessed) and ``memory_analysis()``
  (argument / output / temp / generated-code bytes) are recorded per
  program.  CPU-CI-runnable — abstract of any accelerator — and committed
  to ``cost_ledger.json`` next to this module so cost-model drift is a
  review-visible diff, exactly like the jaxpr fingerprints
  (re-record deliberately with ``--update-ledger``).
- **Measured mode** (``--measured``): a real model state is advanced a few
  fused sweeps, then one sweep runs with every block dispatched as its own
  jitted call and block-until-ready timed over K repetitions
  (:func:`hmsc_tpu.mcmc.sampler.instrumented_sweep` — proven bit-identical
  to the fused sweep), yielding a per-updater wall/share table and the
  fraction of the fused-sweep wall the named blocks attribute.

Results are emitted as schema-v1 JSONL events through
:mod:`hmsc_tpu.obs.events` (``--out DIR``), rendered by ``python -m
hmsc_tpu report`` ("cost attribution" section) and exported through the
same ``--prom`` path; the in-run counterpart is
``sample_mcmc(profile_updaters=...)``.
"""

from __future__ import annotations

import argparse
import json
import os

__all__ = ["LEDGER_PATH", "build_cost_ledger", "build_shard_ledger",
           "build_precision_ledger", "ledger_digest", "load_ledger",
           "save_ledger", "diff_ledger", "measure_updaters", "profile_main",
           "CANONICAL_MODELS"]

LEDGER_PATH = os.path.join(os.path.dirname(__file__), "cost_ledger.json")
LEDGER_VERSION = 1

# the canonical analysis specs the ledger covers (hmsc_tpu.analysis:
# together they exercise every registered updater)
CANONICAL_MODELS = ("base", "spatial", "rrr", "sel")


def _built_models(models=None):
    """(spec, data, state) per canonical model — the analysis layer's
    spec/registry plumbing, reused verbatim."""
    from ..analysis.jaxpr_rules import _build, _canonical_models
    factories = _canonical_models()
    names = tuple(models) if models else CANONICAL_MODELS
    unknown = [n for n in names if n not in factories]
    if unknown:
        raise ValueError(f"unknown canonical model(s) {unknown}; "
                         f"valid: {sorted(factories)}")
    return {name: _build(factories[name]()) for name in names}


def _cost_entry(compiled) -> dict:
    """flops / bytes-accessed / HBM breakdown of one compiled program."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ma = compiled.memory_analysis()
    return {
        "flops": int(ca.get("flops", 0) or 0),
        "bytes_accessed": int(ca.get("bytes accessed", 0) or 0),
        "arg_bytes": int(ma.argument_size_in_bytes),
        "out_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "code_bytes": int(ma.generated_code_size_in_bytes),
    }


def _keep(name: str, only) -> bool:
    return not only or any(s in name for s in only)


def _carry_pspecs(carry, spec, species_axis, site_axis=None):
    """PartitionSpecs for a block-chain carry (state, Xeff, LRan_total,
    E_shared): the state from the committed table, the aux linear-predictor
    arrays by shape (ny, ns) -> species on dim 1, a per-species design
    list -> dim 0.  A ``site_axis`` engages the 2D tables on top: the
    state's row/unit blocks and the aux arrays' sampling-row dim (Xeff
    rows, the (ny, ns) linear-predictor terms) additionally shard over
    sites — matching the layout the 2D sweep body produces between
    blocks."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..mcmc.partition import (STATE_SITE_DIMS, STATE_SPECIES_DIMS,
                                  tree_pspecs)
    state, Xeff, LRan, E = carry
    st = tree_pspecs(state, spec, species_axis, STATE_SPECIES_DIMS,
                     site_axis=site_axis,
                     site_dims=STATE_SITE_DIMS if site_axis else None)

    def aux(a):
        if a is None or not hasattr(a, "ndim"):
            return None
        if a.ndim == 3 and a.shape[0] == spec.ns:
            return P(species_axis, None, None)
        if a.ndim == 2 and a.shape == (spec.ny, spec.ns):
            return P(site_axis, species_axis)
        if site_axis is not None and a.ndim == 2 and a.shape[0] == spec.ny:
            return P(site_axis, None)
        return P(*([None] * a.ndim))

    return (st, aux(Xeff), aux(LRan), aux(E))


def build_shard_ledger(devices: int = 8, models=None, only=None) -> dict:
    """Sharded-sweep ledger programs: every schedule block of each
    canonical spec's ns-divisible variant, individually ``shard_map``'d
    over an emulated ``devices``-way species mesh with the committed
    in/out PartitionSpecs, compiled, and walked for its collective bytes.

    Entries are named ``<model>/shard<devices>:block:<name>`` (plus a
    whole ``:sweep``) and carry the usual XLA cost/memory columns — all
    PER-DEVICE under SPMD, so ``arg/temp`` bytes directly show the
    ~1/shards state shrink — plus ``comm_bytes``/``collectives``: the
    per-device bytes entering psum/all_gather per sweep, statically
    walked from the jaxpr (:func:`hmsc_tpu.mcmc.partition.
    collective_bytes`).  Returns {} when the process has fewer devices
    (the committed entries are then simply not drift-checked)."""
    import dataclasses as _dc

    import jax
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from ..analysis.jaxpr_rules import _build, _shard_models
    from ..mcmc.partition import (DATA_SPECIES_DIMS, ShardCtx,
                                  collective_bytes, tree_pspecs)
    from ..mcmc.sweep import (make_sharded_sweep, make_sweep_schedule,
                              sweep_prologue)

    if len(jax.devices()) < devices:
        return {}
    mesh = Mesh(np.array(jax.devices()[:devices]).reshape(1, devices),
                axis_names=("chains", "species"))

    def _k():
        return jax.random.key(0, impl="threefry2x32")

    factories = _shard_models()
    names = tuple(models) if models else tuple(factories)
    programs: dict[str, dict] = {}
    for mname in names:
        if mname not in factories:
            continue
        spec, data, state = _build(factories[mname]())
        ones = tuple(1 for _ in range(spec.nr))
        ctx = ShardCtx(axis="species", n=devices, ns=spec.ns)
        spec_l = _dc.replace(spec, ns=spec.ns // devices)

        # global-structure chain (the carries each block receives) runs
        # the replicated blocks eagerly; each sharded block is compiled
        # on that same global carry with explicit in/out specs
        steps_g = make_sweep_schedule(spec, None, ones)
        steps_l = make_sweep_schedule(spec_l, None, ones, shard=ctx)

        # an `only` filter that matches none of this model's shard names
        # skips the whole chain (the tier-1 `--only /block:` slice would
        # otherwise compile ~9 discarded programs per model just to
        # advance the carry)
        cand = [f"{mname}/shard{devices}:block:{b}" for b, _ in steps_g]
        cand.append(f"{mname}/shard{devices}:sweep")
        if only and not any(_keep(n, only) for n in cand):
            continue
        data_specs = tree_pspecs(data, spec, "species", DATA_SPECIES_DIMS,
                                 x_is_list=spec.x_is_list)
        state_it, ks = jax.jit(sweep_prologue)(state, _k())
        carry = (state_it, None, None, None)
        for (bname, block_g), (_, block_l) in zip(steps_g, steps_l):
            carry_next = jax.jit(block_g)(data, carry, ks)
            name = f"{mname}/shard{devices}:block:{bname}"
            if _keep(name, only):
                sm = shard_map(block_l, mesh=mesh,
                               in_specs=(data_specs,
                                         _carry_pspecs(carry, spec,
                                                       "species"), P()),
                               out_specs=_carry_pspecs(carry_next, spec,
                                                       "species"),
                               check_rep=False)
                entry = _cost_entry(
                    jax.jit(sm).lower(data, carry, ks).compile())
                entry.update(collective_bytes(
                    jax.make_jaxpr(sm)(data, carry, ks)))
                programs[name] = entry
            carry = carry_next

        name = f"{mname}/shard{devices}:sweep"
        if _keep(name, only):
            sweep_s = make_sharded_sweep(spec, mesh, None, ones)
            entry = _cost_entry(
                jax.jit(sweep_s).lower(data, state, _k()).compile())
            entry.update(collective_bytes(
                jax.make_jaxpr(sweep_s)(data, state, _k())))
            programs[name] = entry

    # 2D (species x sites) entries: the same emulated devices reshaped to
    # a (1, SITE_AUDIT_SP, SITE_AUDIT_ST) mesh over the site-capable
    # canonical specs (base + Full/NNGP/GPP) — per-device SPMD cost
    # columns plus the 2D collective byte ledger (the site-axis psums,
    # Eta row gathers, and both-axis reductions all land in
    # comm_bytes/collectives, drift-checked by `profile --check`).
    # Alongside the whole ``:sweep`` program, every schedule block gets
    # its own ``:block:<name>`` row (same pattern as the 1D species
    # chain above), so a comm regression is attributable to the Gibbs
    # block that grew it, not just the sweep total.
    from ..analysis.jaxpr_rules import (SITE_AUDIT_SP, SITE_AUDIT_ST,
                                        _site_shard_models)
    from ..mcmc.partition import DATA_SITE_DIMS
    mesh2 = Mesh(np.array(jax.devices()[:SITE_AUDIT_SP * SITE_AUDIT_ST])
                 .reshape(1, SITE_AUDIT_SP, SITE_AUDIT_ST),
                 axis_names=("chains", "species", "sites"))
    tag2 = f"shard{SITE_AUDIT_SP}x{SITE_AUDIT_ST}"
    for mname, fn in _site_shard_models().items():
        spec, data, state = _build(fn())
        ones = tuple(1 for _ in range(spec.nr))
        ctx2 = ShardCtx(axis="species", n=SITE_AUDIT_SP, ns=spec.ns,
                        site_axis="sites", m=SITE_AUDIT_ST, ny=spec.ny,
                        np_r=tuple(ls.n_units for ls in spec.levels))
        spec_l2 = _dc.replace(spec, ns=spec.ns // SITE_AUDIT_SP,
                              ny=spec.ny // SITE_AUDIT_ST)
        steps_g = make_sweep_schedule(spec, None, ones)
        steps_l2 = make_sweep_schedule(spec_l2, None, ones, shard=ctx2)
        cand = [f"{mname}/{tag2}:block:{b}" for b, _ in steps_g]
        cand.append(f"{mname}/{tag2}:sweep")
        if only and not any(_keep(n, only) for n in cand):
            continue
        data_specs2 = tree_pspecs(data, spec, "species", DATA_SPECIES_DIMS,
                                  x_is_list=spec.x_is_list,
                                  site_axis="sites",
                                  site_dims=DATA_SITE_DIMS)
        state_it, ks = jax.jit(sweep_prologue)(state, _k())
        carry = (state_it, None, None, None)
        for (bname, block_g), (_, block_l) in zip(steps_g, steps_l2):
            carry_next = jax.jit(block_g)(data, carry, ks)
            name = f"{mname}/{tag2}:block:{bname}"
            if _keep(name, only):
                sm = shard_map(block_l, mesh=mesh2,
                               in_specs=(data_specs2,
                                         _carry_pspecs(carry, spec,
                                                       "species", "sites"),
                                         P()),
                               out_specs=_carry_pspecs(carry_next, spec,
                                                       "species", "sites"),
                               check_rep=False)
                entry = _cost_entry(
                    jax.jit(sm).lower(data, carry, ks).compile())
                entry.update(collective_bytes(
                    jax.make_jaxpr(sm)(data, carry, ks)))
                programs[name] = entry
            carry = carry_next

        name = f"{mname}/{tag2}:sweep"
        if _keep(name, only):
            sweep_s = make_sharded_sweep(spec, mesh2, None, ones)
            entry = _cost_entry(
                jax.jit(sweep_s).lower(data, state, _k()).compile())
            entry.update(collective_bytes(
                jax.make_jaxpr(sweep_s)(data, state, _k())))
            programs[name] = entry
    return programs


def build_precision_ledger(models=None, only=None) -> tuple[dict, dict]:
    """Mixed-precision ledger programs at the SCALED canonical shapes
    (:func:`hmsc_tpu.mcmc.precision.policy_ledger_models` — species-heavy
    JSDM sizes where the staged operands carry the block bytes; the tiny
    audit specs under-resolve per-sweep traffic):

    - ``<model>/scale:block:<name>`` — every schedule block of the scaled
      spec, f32 (the before column);
    - ``<model>/scale+mp:block:<name>`` — the default policy's targeted
      blocks compiled with the policy scopes active and the staged
      operands passed pre-cast (bf16 arguments: staging is paid once per
      run, so the entry records steady-state per-sweep bytes);
    - ``<model>/scale+mp:sweep`` — the whole policy'd sweep.

    Returns ``(programs, precision_section)`` where the section records,
    per model class, the targeted blocks/staged names and the measured
    per-block ``bytes_ratio`` (f32 bytes-accessed over policy'd) — the
    committed, drift-checked data `default_policy` spends.
    """
    import jax

    from ..mcmc.precision import (default_policy, policy_ledger_models,
                                  stage_data)
    from ..mcmc.sweep import make_sweep, make_sweep_schedule, sweep_prologue
    from ..ops import mixed

    def _k():
        return jax.random.key(0, impl="threefry2x32")

    from ..analysis.jaxpr_rules import _build
    factories = policy_ledger_models()
    names = tuple(models) if models else tuple(factories)
    programs: dict[str, dict] = {}
    section: dict[str, dict] = {}
    for mname in names:
        if mname not in factories:
            continue
        spec, data, state = _build(factories[mname]())
        policy = default_policy(spec, ledger={})   # in-code targets
        if policy is None:
            continue
        ones = tuple(1 for _ in range(spec.nr))
        staged = stage_data(data, policy)

        steps = make_sweep_schedule(spec, None, ones)
        steps_mp = make_sweep_schedule(spec, None, ones, precision=policy)
        # an `only` filter that matches none of this model's names skips
        # the whole (compile-heavy, scaled-shape) chain
        cand = [f"{mname}/scale:block:{b}" for b, _ in steps]
        cand += [f"{mname}/scale+mp:block:{b}" for b in policy.blocks]
        cand.append(f"{mname}/scale+mp:sweep")
        if only and not any(_keep(n, only) for n in cand):
            continue
        state_it, ks = jax.jit(sweep_prologue)(state, _k())
        carry = (state_it, None, None, None)
        ratios: dict[str, float] = {}
        for (bname, block), (_, block_mp) in zip(steps, steps_mp):
            name = f"{mname}/scale:block:{bname}"
            compiled = jax.jit(block).lower(data, carry, ks).compile()
            ref_entry = _cost_entry(compiled)
            if _keep(name, only):
                programs[name] = ref_entry
            if policy.dtype_for(bname) is not None:
                def run_mp(data, carry, ks, staged, _b=block_mp):
                    with mixed.staged_scope(staged):
                        return _b(data, carry, ks)
                mp_entry = _cost_entry(jax.jit(run_mp).lower(
                    data, carry, ks, staged).compile())
                mp_name = f"{mname}/scale+mp:block:{bname}"
                if _keep(mp_name, only):
                    programs[mp_name] = mp_entry
                if mp_entry["bytes_accessed"]:
                    ratios[bname] = round(
                        ref_entry["bytes_accessed"]
                        / mp_entry["bytes_accessed"], 3)
            carry = compiled(data, carry, ks)

        name = f"{mname}/scale+mp:sweep"
        if _keep(name, only):
            sweep_mp = make_sweep(spec, None, ones, precision=policy)
            programs[name] = _cost_entry(jax.jit(sweep_mp).lower(
                data, state, _k(), staged).compile())
        section[mname] = {
            "blocks": list(policy.blocks),
            "staged": list(policy.staged),
            "dtype": policy.dtype,
            "bytes_ratio": ratios,
        }
    return programs, section


BATCH_LEDGER_K = 4


def build_batch_ledger(models=None, only=None) -> tuple[dict, dict]:
    """Multi-tenant batched-sweep ledger programs
    (:mod:`hmsc_tpu.mcmc.multitenant`):

    - ``<model>/batch:sweep@K{k}`` — the tenant-masked padded sweep
      vmapped over a K-lane model axis at the canonical spec's bucket
      dims (the per-sweep cost of one batched bucket step);
    - ``<model>/batch:sweep@pad`` — the single-lane padded masked sweep
      (the marginal per-tenant cost, for occupancy accounting).

    Returns ``(programs, batch_section)`` where the section commits, per
    model class, the bucket dims and the padding occupancy/waste of the
    canonical K-lane bucket — drift-checked by ``profile --check`` like
    the precision selection."""
    import jax

    from ..analysis.jaxpr_rules import _build, _canonical_models
    from ..mcmc.multitenant import (batch_unsupported_reason, bucket_dims,
                                    make_batched_sweep, pad_spec, pad_state,
                                    pad_tenant)

    def _k():
        return jax.random.key(0, impl="threefry2x32")

    factories = _canonical_models()
    names = tuple(models) if models else tuple(factories)
    programs: dict[str, dict] = {}
    section: dict[str, dict] = {}
    for mname in names:
        if mname not in factories:
            continue
        spec, data, state = _build(factories[mname]())
        if batch_unsupported_reason(spec) is not None:
            continue
        dims = bucket_dims(spec)
        cand = [f"{mname}/batch:sweep@K{BATCH_LEDGER_K}",
                f"{mname}/batch:sweep@pad"]
        if only and not any(_keep(n, only) for n in cand):
            continue
        spec_b = pad_spec(spec, dims, has_na=True)
        data_b = pad_tenant(spec, data, dims)
        state_b = pad_state(spec, state, dims)
        sweep_b = make_batched_sweep(spec_b, None,
                                     tuple(0 for _ in range(spec_b.nr)))
        if _keep(cand[1], only):
            programs[cand[1]] = _cost_entry(
                jax.jit(sweep_b).lower(data_b, state_b, _k()).compile())
        if _keep(cand[0], only):
            stack = lambda t: jax.tree.map(
                lambda x: jax.numpy.stack([x] * BATCH_LEDGER_K), t)
            keys = jax.vmap(lambda s: jax.random.key(
                s, impl="threefry2x32"))(jax.numpy.arange(BATCH_LEDGER_K))
            vsweep = jax.vmap(sweep_b, in_axes=(0, 0, 0))
            programs[cand[0]] = _cost_entry(
                jax.jit(vsweep).lower(stack(data_b), stack(state_b),
                                      keys).compile())
        real = spec.ny * spec.ns
        padded = dims["ny"] * dims["ns"]
        section[mname] = {
            "k": BATCH_LEDGER_K,
            "dims": {kk: (list(v) if isinstance(v, tuple) else v)
                     for kk, v in dims.items()},
            "occupancy": round(real / padded, 4),
            "padding_waste": round(1.0 - real / padded, 4),
        }
    return programs, section


def build_cost_ledger(models=None, only=None) -> dict:
    """Compile and cost-analyse, per canonical spec:

    - ``<model>/block:<name>`` — every block of that spec's sweep schedule
      (:func:`~hmsc_tpu.mcmc.sweep.make_sweep_schedule` with one
      adaptation sweep per level, the production program shape), chained
      so each block is lowered on the real mid-sweep carry it receives —
      the per-updater flops/HBM table for that spec;
    - ``<model>/sweep`` — the assembled fused sweep;
    - ``<model>/segment_runner`` — the jitted 2-chain segment runner
      (donated carries; the aliasing shows up as ``alias_bytes``);

    plus ``<model>/updater:<name>`` for every ``UPDATER_REGISTRY`` entry on
    its first applicable spec (the jaxpr audit's union-coverage rule —
    registry entries take the raw design, which only the first-applicable
    spec satisfies; this is what guarantees EVERY registered updater
    appears in the ledger, including the opt-in collapsed blocks the
    default schedule omits).

    ``only`` filters program names by substring (cheap partial
    regeneration in tests)."""
    import jax
    import jax.numpy as jnp

    from ..mcmc import sampler as sampler_mod
    from ..mcmc import spatial as spatial_mod
    from ..mcmc.registry import UPDATER_REGISTRY
    from ..mcmc.sweep import make_sweep, make_sweep_schedule, sweep_prologue

    # fresh exemplar key per lowered program (nothing here ever draws —
    # every program is lowered, and run only to thread the block carry)
    def _k():
        return jax.random.key(0, impl="threefry2x32")

    built = _built_models(models)
    programs: dict[str, dict] = {}
    for mname, (spec, data, state) in built.items():
        ones = tuple(1 for _ in range(spec.nr))

        # schedule blocks, chained on the real mid-sweep carry (each
        # compiled program also RUNS once, eagerly, to produce the next
        # block's inputs — tiny specs, so this costs nothing)
        steps = make_sweep_schedule(spec, None, ones)
        state_it, ks = jax.jit(sweep_prologue)(state, _k())
        carry = (state_it, None, None, None)
        for bname, block in steps:
            name = f"{mname}/block:{bname}"
            compiled = jax.jit(block).lower(data, carry, ks).compile()
            if _keep(name, only):
                programs[name] = _cost_entry(compiled)
            carry = compiled(data, carry, ks)

        name = f"{mname}/sweep"
        if _keep(name, only):
            sweep = make_sweep(spec, None, ones)
            programs[name] = _cost_entry(
                jax.jit(sweep).lower(data, state, _k()).compile())

        name = f"{mname}/segment_runner"
        if _keep(name, only):
            states = jax.tree.map(lambda x: jnp.stack([x, x]), state)
            keys = jax.vmap(lambda s: jax.random.key(
                s, impl="threefry2x32"))(jnp.arange(2))
            bad = jnp.full((2,), -1, jnp.int32)
            fn = sampler_mod._compiled_runner(
                spec, None, ones, 1, 1, 1, False, None,
                spatial_mod._NNGP_DENSE_MAX)
            programs[name] = _cost_entry(
                fn.lower(data, states, keys, bad).compile())

    # registry union coverage: every entry once, on its first applicable
    # canonical spec (mirrors analysis.jaxpr_rules.build_audit_context)
    for entry in UPDATER_REGISTRY:
        for mname, (spec, data, state) in built.items():
            if not entry.applies(spec, data):
                continue
            name = f"{mname}/updater:{entry.name}"
            if _keep(name, only):
                fn = (lambda e, s: lambda d, st, k: e.fn(s, d, st, k))(
                    entry, spec)
                programs[name] = _cost_entry(
                    jax.jit(fn).lower(data, state, _k()).compile())
            break

    # sharded-sweep programs (per-block comm-bytes column): present only
    # when the process has >= 8 devices (CI forces the emulated mesh; a
    # smaller environment simply does not drift-check these entries)
    programs.update(build_shard_ledger(models=models, only=only))

    # mixed-precision programs at the scaled shapes + the committed
    # per-class policy selection (what `default_policy` spends)
    mp_programs, precision = build_precision_ledger(models=models, only=only)
    programs.update(mp_programs)

    # multi-tenant batched-sweep programs + the committed per-class bucket
    # occupancy metrics (mcmc/multitenant.py)
    batch_programs, batch = build_batch_ledger(models=models, only=only)
    programs.update(batch_programs)
    return {"version": LEDGER_VERSION, "jax": jax.__version__,
            "precision": precision, "batch": batch,
            "programs": dict(sorted(programs.items()))}


def ledger_digest(ledger: dict) -> dict:
    """Per-canonical-spec roll-up for bench records and report rendering:
    the sweep program's total flops, the peak temp HBM across that spec's
    programs, and the program count.  The scaled mixed-precision entries
    roll up separately (``precision``: targeted blocks, f32-over-policy'd
    bytes ratio per block, per-sweep bytes saved at the scaled shapes) so
    the tiny-spec numbers keep their historical meaning."""
    out: dict[str, dict] = {}
    saved: dict[str, dict[str, int]] = {}
    for name, entry in ledger.get("programs", {}).items():
        mname, _, prog = name.partition("/")
        d = out.setdefault(mname, {"flops_total": None,
                                   "temp_bytes_peak": 0, "programs": 0})
        d["programs"] += 1
        if prog.startswith("shard"):
            # per-device SPMD numbers roll up separately: the whole-sweep
            # comm bytes and per-device argument footprint (the 2D
            # species x sites mesh rolls into its own "shard2d" slot so
            # the v1 species-only numbers keep their meaning)
            key2d = "shard2d" if "x" in prog.split(":", 1)[0] else "shard"
            sh = d.setdefault(key2d, {"comm_bytes": None,
                                      "arg_bytes_per_device": None})
            if prog.endswith(":sweep"):
                sh["comm_bytes"] = entry.get("comm_bytes", 0)
                sh["arg_bytes_per_device"] = entry.get("arg_bytes")
            continue
        if prog.startswith("scale"):
            _, _, bname = prog.partition(":block:")
            if bname:
                sv = saved.setdefault(mname, {})
                sign = -1 if prog.startswith("scale+mp") else 1
                sv[bname] = sv.get(bname, 0) \
                    + sign * entry.get("bytes_accessed", 0)
            continue
        if prog.startswith("batch"):
            # K-lane padded-bucket numbers roll up separately (the padded
            # shapes would distort the tiny-spec peaks)
            bt = d.setdefault("batch", {})
            if "@K" in prog:
                bt["sweep_flops_k"] = entry.get("flops")
                bt["sweep_bytes_k"] = entry.get("bytes_accessed")
            continue
        d["temp_bytes_peak"] = max(d["temp_bytes_peak"],
                                   entry.get("temp_bytes", 0))
        if prog == "sweep":
            d["flops_total"] = entry.get("flops")
    for mname, sel in ledger.get("precision", {}).items():
        d = out.setdefault(mname, {"flops_total": None,
                                   "temp_bytes_peak": 0, "programs": 0})
        pairs = {b: v for b, v in saved.get(mname, {}).items()
                 if b in sel.get("bytes_ratio", {})}
        d["precision"] = {
            "blocks": sel.get("blocks"),
            "bytes_ratio": sel.get("bytes_ratio"),
            "bytes_saved_per_sweep": int(sum(pairs.values())) or None,
        }
    for mname, sel in ledger.get("batch", {}).items():
        d = out.setdefault(mname, {"flops_total": None,
                                   "temp_bytes_peak": 0, "programs": 0})
        d.setdefault("batch", {}).update(
            k=sel.get("k"), occupancy=sel.get("occupancy"),
            padding_waste=sel.get("padding_waste"))
    return out


def load_ledger(path: str = LEDGER_PATH) -> dict | None:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (FileNotFoundError, ValueError):
        return None
    if doc.get("version") != LEDGER_VERSION:
        return None
    return doc


def save_ledger(ledger: dict, path: str = LEDGER_PATH) -> None:
    with open(path, "w") as f:
        json.dump(ledger, f, indent=1, sort_keys=False)
        f.write("\n")


def diff_ledger(committed: dict | None, current: dict) -> list[str]:
    """Human-readable drift lines between the committed ledger and a fresh
    one (restricted to programs present in ``current``, so partial
    regenerations diff cleanly)."""
    if committed is None:
        return ["no committed cost ledger — record one with "
                "`python -m hmsc_tpu profile --static --update-ledger`"]
    drift = []
    old = committed.get("programs", {})
    for name, entry in current.get("programs", {}).items():
        prev = old.get(name)
        if prev is None:
            drift.append(f"{name}: no committed entry")
            continue
        for k in ("flops", "bytes_accessed", "temp_bytes", "comm_bytes"):
            if prev.get(k) != entry.get(k):
                drift.append(f"{name}: {k} {prev.get(k)} -> {entry.get(k)}")
    # the precision selection (policy'd blocks, staged names, measured
    # byte ratios) is drift-checked like any other ledger column — a
    # routing change that moves a ratio must be a review-visible diff
    old_p = committed.get("precision", {})
    for cls_, sel in current.get("precision", {}).items():
        prev = old_p.get(cls_)
        if prev is None:
            drift.append(f"precision/{cls_}: no committed selection")
            continue
        for k in ("blocks", "staged", "dtype", "bytes_ratio"):
            if prev.get(k) != sel.get(k):
                drift.append(
                    f"precision/{cls_}: {k} {prev.get(k)} -> {sel.get(k)}")
    # the batched-bucket section (bucket dims + occupancy/padding waste of
    # the canonical K-lane bucket) drifts visibly too — a rounding or
    # padding change silently moving occupancy must surface in review
    old_b = committed.get("batch", {})
    for cls_, sel in current.get("batch", {}).items():
        prev = old_b.get(cls_)
        if prev is None:
            drift.append(f"batch/{cls_}: no committed section")
            continue
        for k in ("k", "dims", "occupancy", "padding_waste"):
            if prev.get(k) != sel.get(k):
                drift.append(
                    f"batch/{cls_}: {k} {prev.get(k)} -> {sel.get(k)}")
    return drift


def measure_updaters(models=("base",), reps: int = 3, warmup: int = 3,
                     seed: int = 0) -> dict:
    """Measured per-updater timing on real model state: advance ``warmup``
    fused sweeps from the built initial state, then run ONE instrumented
    per-block pass (``reps`` timed repetitions each, minimum reported) plus
    a fused-sweep reference timing.  Returns ``{model: profile}`` in the
    :func:`~hmsc_tpu.mcmc.sampler.instrumented_sweep` profile shape."""
    import jax

    from ..mcmc.sampler import instrumented_sweep
    from ..mcmc.sweep import make_sweep

    out = {}
    for mname, (spec, data, state) in _built_models(models).items():
        zeros = tuple(0 for _ in range(spec.nr))
        sweep = jax.jit(make_sweep(spec, None, zeros))
        key = jax.random.key(seed, impl="threefry2x32")
        for _ in range(max(0, int(warmup))):
            key, sub = jax.random.split(key)
            state = sweep(data, state, sub)
        jax.block_until_ready(state)
        key, sub = jax.random.split(key)
        _, prof = instrumented_sweep(spec, data, state, sub, reps=reps)
        out[mname] = dict(prof, model=mname, warmup=int(warmup))
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _render_static(ledger: dict, digest: dict, drift: list) -> str:
    lines = ["static cost ledger (XLA cost/memory analysis, "
             f"jax {ledger.get('jax')})"]
    cur = None
    for name, e in ledger["programs"].items():
        mname, _, prog = name.partition("/")
        if mname != cur:
            cur = mname
            d = digest.get(mname, {})
            lines.append(f"\n== {mname} (sweep flops "
                         f"{d.get('flops_total')}, peak temp "
                         f"{d.get('temp_bytes_peak')} B) ==")
            lines.append(f"  {'program':<28} {'Mflops':>9} {'MB acc':>8} "
                         f"{'arg KB':>8} {'temp KB':>8} {'comm KB':>8}")
        comm = e.get("comm_bytes")
        lines.append(f"  {prog:<28} {e['flops'] / 1e6:9.3f} "
                     f"{e['bytes_accessed'] / 1e6:8.2f} "
                     f"{e['arg_bytes'] / 1e3:8.1f} "
                     f"{e['temp_bytes'] / 1e3:8.1f} "
                     + (f"{comm / 1e3:8.2f}" if comm is not None
                        else f"{'-':>8}"))
    prec = ledger.get("precision", {})
    if prec:
        lines.append("\nmixed-precision policy selection (committed, "
                     "drift-checked; ratios are f32 over policy'd "
                     "bytes-accessed at the scaled shapes):")
        for cls_, sel in prec.items():
            ratios = ", ".join(f"{b} x{r}" for b, r
                               in sel.get("bytes_ratio", {}).items())
            lines.append(f"  {cls_}: {','.join(sel.get('blocks', []))} "
                         f"[{ratios}] staged={','.join(sel.get('staged', []))}")
    if drift:
        lines.append("\ncost-model drift vs committed ledger:")
        lines += [f"  {d}" for d in drift]
    else:
        lines.append("\nledger matches the committed cost_ledger.json")
    return "\n".join(lines)


def _render_measured(measured: dict) -> str:
    lines = []
    for mname, prof in measured.items():
        lines.append(f"== measured per-updater wall, {mname} "
                     f"(reps={prof['reps']}, fused sweep "
                     f"{prof.get('fused_wall_s', 0) * 1e3:.3f} ms, "
                     f"attributed {prof.get('attributed_frac', 0) * 100:.0f}"
                     f"%) ==")
        for b in prof["updaters"]:
            bar = "#" * int(round(b["share"] * 30))
            lines.append(f"  {b['name']:<20} {b['wall_s'] * 1e3:9.4f} ms "
                         f"({b['share'] * 100:5.1f}%) {bar}")
    return "\n".join(lines)


def profile_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hmsc_tpu profile",
        description="sweep-level cost attribution: static flops/HBM ledger "
                    "per Gibbs block (XLA cost analysis, CPU-safe) and "
                    "measured per-updater wall timing")
    ap.add_argument("--static", action="store_true",
                    help="build the static cost ledger (default mode)")
    ap.add_argument("--measured", action="store_true",
                    help="timed per-updater profile on real model state")
    ap.add_argument("--models", default=None,
                    help="comma-separated canonical specs (default: all "
                         f"of {','.join(CANONICAL_MODELS)}; measured mode "
                         "defaults to base)")
    ap.add_argument("--only", default=None,
                    help="substring filter on ledger program names "
                         "(partial regeneration)")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions per block in measured mode")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result on stdout")
    ap.add_argument("--update-ledger", action="store_true",
                    help="re-record the committed cost_ledger.json from "
                         "the current build (after reviewing the drift)")
    ap.add_argument("--update-precision", action="store_true",
                    help="re-record the committed precision_tolerance.json "
                         "(measured per-block mixed-precision deviation of "
                         "the default policies — the training-side "
                         "cast_tolerance())")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the static ledger drifts from the "
                         "committed one")
    ap.add_argument("--ledger", default=None,
                    help="override the committed ledger path")
    ap.add_argument("--out", metavar="DIR", default=None,
                    help="also emit results as schema-v1 telemetry events "
                         "(events-p0.jsonl under DIR; render with "
                         "`python -m hmsc_tpu report DIR`)")
    args = ap.parse_args(argv)

    if not args.static and not args.measured:
        args.static = True
    if not args.measured:
        # static-only, like `hmsc_tpu lint`: the ledger is platform-
        # abstract, so never block on an unreachable accelerator.  Measured
        # mode is the opposite contract — it times the backend JAX actually
        # configures (auto-detected TPU included), so it must NOT be pinned
        # to CPU behind the user's back.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        # the shard8 comm-bytes entries need the emulated species mesh;
        # force the virtual device count before the backend initialises
        # (no-op once a backend exists — the entries are then skipped)
        from ..mcmc.partition import force_emulated_device_count
        force_emulated_device_count(8)
    models = tuple(args.models.split(",")) if args.models else None
    only = tuple(args.only.split(",")) if args.only else None
    ledger_path = args.ledger or LEDGER_PATH

    telem = None
    if args.out:
        from .events import SCHEMA_VERSION, RunTelemetry, events_path
        telem = RunTelemetry(proc=0)
        telem.attach_sink(events_path(args.out, 0), truncate=True)
        telem.emit("run", "start", schema=SCHEMA_VERSION, profile=True,
                   mode=("static+measured" if args.static and args.measured
                         else "measured" if args.measured else "static"))

    result: dict = {"version": LEDGER_VERSION}
    drift: list[str] = []
    if args.update_precision:
        if models:
            print("--update-precision requires a full build (no --models): "
                  "the committed artifact covers every canonical class")
            return 2
        from ..mcmc.precision import (TOLERANCE_PATH,
                                      measure_policy_tolerance,
                                      save_tolerance)
        tol = measure_policy_tolerance()
        save_tolerance(tol)
        result["precision_tolerance"] = tol
        print(f"wrote {TOLERANCE_PATH} "
              f"({len(tol['models'])} model classes)")
    if args.static:
        ledger = build_cost_ledger(models=models, only=only)
        digest = ledger_digest(ledger)
        if args.update_ledger:
            if models or only:
                print("--update-ledger requires a full build (no --models/"
                      "--only): the committed ledger covers every program")
                return 2
            save_ledger(ledger, ledger_path)
            print(f"wrote {ledger_path} "
                  f"({len(ledger['programs'])} programs)")
        drift = diff_ledger(load_ledger(ledger_path), ledger)
        result["static"] = {"ledger": ledger, "digest": digest,
                            "drift": drift,
                            "matches_committed": not drift}
        if telem is not None:
            for mname, d in digest.items():
                telem.emit("metric", "cost_ledger", model=mname, **d,
                           programs_detail={
                               n.split("/", 1)[1]: {
                                   "flops": e["flops"],
                                   "temp_bytes": e["temp_bytes"]}
                               for n, e in ledger["programs"].items()
                               if n.startswith(mname + "/")})
    if args.measured:
        m_models = models or ("base",)
        measured = measure_updaters(models=m_models, reps=args.reps)
        result["measured"] = measured
        if telem is not None:
            for mname, prof in measured.items():
                telem.emit("metric", "updater_profile", **prof)

    if telem is not None:
        telem.emit("run", "end")
        telem.flush()

    if args.json:
        print(json.dumps(result, indent=1))
    else:
        if args.static:
            print(_render_static(result["static"]["ledger"],
                                 result["static"]["digest"], drift))
        if args.measured:
            print(_render_measured(result["measured"]))
    return 1 if (args.check and drift) else 0


if __name__ == "__main__":
    raise SystemExit(profile_main())
