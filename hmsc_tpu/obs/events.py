"""Structured run-telemetry event stream (the subsystem's core).

Every sampling run records what happened to it as an append-only JSONL
stream of *events* — one file per process, ``events-p<rank>.jsonl``,
written alongside the checkpoint directory.  Long-running services make
latency and stall structure first-class telemetry (Dean & Barroso, "The
Tail at Scale"); the ``print``-based progress and the after-the-fact
``Posterior.io_stats`` dict gave this sampler neither: when a pod run
stalls, skews, or diverges, there was no recorded timeline to diagnose it
from.  This module records one.

Event shapes (every event carries ``seq``/``t``/``wall``/``proc``/``kind``/
``name``; ``t`` is monotonic seconds since the run's telemetry started —
durations and ordering come from it, ``wall`` is coarse unix time for
cross-host alignment only):

- ``kind="run"`` — lifecycle marks: ``start`` (carries ``schema`` and the
  run configuration), ``end``, ``preempted``.
- ``kind="span"`` — a timed host-loop stage, emitted at CLOSE:
  ``{"sid", "parent", "depth", "thread", "t0", "dur_s", ...}``.  Spans nest
  per thread (the driver loop and the background segment writer each keep
  their own stack), so a child's window lies inside its parent's.
- ``kind="metric"`` — point measurements: ``segment_health`` (per-segment
  MCMC health: throughput, divergence counters, nf-adaptation, running
  R-hat/ESS), ``rank_skew`` (committer-side cross-rank skew at each commit
  mark), ``profile_capture``.
- ``kind="log"`` — messages routed through :mod:`hmsc_tpu.obs.log`.

Schema v2 adds three ADDITIVE optional fields — ``trace``/``span``/
``parent`` (:mod:`hmsc_tpu.obs.trace`) — present only while a
:class:`TraceContext` is bound via :meth:`RunTelemetry.set_trace`.  v1
readers ignore them; with no context bound, event bytes are unchanged.

Threading contract: :class:`RunTelemetry` is shared between the sampler's
driver thread and its background writer thread; one lock guards the buffer
and the aggregates.  Disk writes happen only in :meth:`flush`, which the
sampler submits to the background writer — telemetry stays off the
segment loop's critical path, and the file is opened per flush (append
mode), so there is no handle to leak across preemption unwinds.

Draw-stream invariance: nothing in this module ever touches device data;
the sampler hands it host-side copies only.  Telemetry on/off/cadence can
therefore never change a draw (asserted by ``tests/test_telemetry.py``).
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["RunTelemetry", "SCHEMA_VERSION", "EVENTS_FILE_RE", "events_path",
           "compact_summary", "GATHER_SPAN_SCHEMA", "record_rank_skew"]

SCHEMA_VERSION = 2

# events-p<rank>.jsonl — one stream per writing process, next to the
# checkpoint layout (but not part of it: GC/rotation never touch it)
import re as _re

EVENTS_FILE_RE = _re.compile(r"events-p(\d+)\.jsonl")

# in-memory safety cap for sink-less runs: events beyond this are counted
# (``dropped_events``) but not retained
_MAX_BUFFER = 100_000

# per-segment health points retained for the summary's running series
# (bounded so a million-segment run cannot grow Posterior.telemetry
# without bound; the full series always lives in the event stream)
_MAX_HEALTH = 512

# the per-segment health fields the summary's series keeps (the full
# segment_health event carries more, e.g. the nf-adaptation trajectory)
_HEALTH_KEYS = ("seg", "samples_done", "draws_per_s", "diverged_chains",
                "rhat_max", "ess_min")

# The CLOSED set of span names the commit-gather payload carries
# (:meth:`RunTelemetry.mark_delta`).  The gather rides every multi-process
# commit, so its payload must be fixed-size: an open span-name set would
# grow the serialized payload with every new instrumentation site (the
# ROADMAP known gap on real pods).  Spans outside this schema aggregate
# into ``"other"``; extending the schema is a deliberate, review-visible
# edit here (tests pin the schema, and ``CheckpointWriter._record_skew``
# reads only names from it).
GATHER_SPAN_SCHEMA = (
    "compile", "dispatch", "fetch", "submit_wait", "barrier_wait",
    "shard_write", "state_write", "manifest_commit", "snapshot_write",
    "gc", "splice_rewrite", "warm_restart_find",
)


def events_path(dirpath: str, proc: int = 0) -> str:
    """The event-stream file for process ``proc`` under a run directory."""
    return os.path.join(os.fspath(dirpath), f"events-p{int(proc)}.jsonl")


def compact_summary(summary: dict | None) -> dict | None:
    """Small telemetry digest for embedding into bench records: span
    totals, cross-rank skew, final throughput/health — so the perf
    trajectory carries stall structure, not just wall time."""
    if not summary:
        return None
    health = (summary.get("health", {}).get("final")
              or summary.get("last", {}).get("segment_health", {}))
    return {
        "spans_s": {k: v["total_s"]
                    for k, v in summary.get("spans", {}).items()},
        "skew_s": summary.get("counters", {}).get("rank_skew_s"),
        "draws_per_s": health.get("draws_per_s"),
        "rhat_max": health.get("rhat_max"),
        "ess_min": health.get("ess_min"),
        "events": summary.get("events"),
    }


def record_rank_skew(telem: "RunTelemetry", tag: str, deltas: list) -> None:
    """Record one cross-rank skew mark from gathered per-rank
    :meth:`RunTelemetry.mark_delta` payloads (rank order).

    Called by the committer at every multi-process commit mark, and by the
    sampler's end-of-run gather on checkpoint-free mesh runs — so EVERY
    multi-process run reports skew, not only checkpointed ones (the
    ROADMAP observability gap).  Per-rank segment time is compile +
    dispatch + device→host fetch since the previous mark; ``skew_s`` is
    max−min segment time — the quantity that, left unchecked, accumulates
    into gather stalls (the PR 4 A/B measured 27% overhead without
    per-mark pacing)."""
    tels = [d or {} for d in deltas]
    seg = [round(sum(t.get("spans", {}).get(n, 0.0)
                     for n in ("compile", "dispatch", "fetch")), 6)
           for t in tels]
    bar = [round(t.get("spans", {}).get("barrier_wait", 0.0), 6)
           for t in tels]
    skew = round(max(seg) - min(seg), 6) if seg else 0.0
    telem.emit("metric", "rank_skew", tag=tag, segment_s=seg,
               barrier_wait_s=bar, skew_s=skew)
    telem.count("rank_skew_s", skew)


class _Span:
    """Handle returned by :meth:`RunTelemetry.span`: ``dur_s`` is valid
    after the ``with`` block exits (used by callers that also keep legacy
    accumulators, e.g. ``CheckpointWriter.io``)."""

    __slots__ = ("name", "fields", "sid", "parent", "depth", "t0", "dur_s",
                 "_telem")

    def __init__(self, telem, name, fields):
        self._telem = telem
        self.name = name
        self.fields = fields
        self.dur_s = 0.0

    def __enter__(self):
        self._telem._open_span(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._telem._close_span(self)
        return False


class RunTelemetry:
    """Per-run telemetry: thread-safe event buffer + span aggregates.

    The aggregates (per-span totals/counts, counters, last metric values)
    are maintained even when ``enabled=False`` — they are what the
    backward-compatible ``Posterior.io_stats`` view and the multi-process
    rank-skew gather are derived from — so disabling telemetry only stops
    event *retention and JSONL writing*, never the cheap accounting."""

    # shared between the driver thread and the background segment writer;
    # `hmsc_tpu lint` (lock-discipline) enforces the declaration below
    # hmsc: guarded-by[_lock]: _buffer, _spans, _counters, _last, _mark, _health, _seq, _sid, _trace, n_events, dropped_events

    def __init__(self, proc: int = 0, enabled: bool = True):
        self.proc = int(proc)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._sink_lock = threading.Lock()       # serialises disk flushes
        self._local = threading.local()          # per-thread span stack
        self._t0 = time.perf_counter()
        self._seq = 0
        self._sid = 0
        self._buffer: list[dict] = []
        self._sink_path: str | None = None
        self._spans: dict[str, dict] = {}        # name -> count/total/max
        self._counters: dict[str, float] = {}
        self._last: dict[str, dict] = {}         # latest metric per name
        self._mark: dict[str, float] = {}        # span totals at last mark
        self._health: list[dict] = []            # segment_health series
        self._trace = None                       # bound TraceContext | None
        self.n_events = 0
        self.dropped_events = 0

    # -- event emission ----------------------------------------------------

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    def emit(self, kind: str, name: str, **fields) -> None:
        """Record one event (JSON-serialisable field values only)."""
        with self._lock:
            if kind == "metric":
                self._last[name] = dict(fields)
                if name == "segment_health":
                    # the running MCMC-health series rides the summary
                    # (Posterior.telemetry), not only the event stream —
                    # bounded, and kept even when event recording is off
                    self._health.append(
                        {k: fields.get(k) for k in _HEALTH_KEYS})
                    if len(self._health) > _MAX_HEALTH:
                        del self._health[0]
            self._append_locked(kind, name, fields)

    def _append_locked(self, kind, name, fields) -> None:
        self.n_events += 1
        if not self.enabled:
            return
        if len(self._buffer) >= _MAX_BUFFER:
            self.dropped_events += 1
            return
        ev = {"seq": self._seq, "t": round(self._now(), 6),
              "wall": round(time.time(), 3), "proc": self.proc,
              "kind": kind, "name": name}
        if self._trace is not None:
            # additive v2 fields; explicit per-event fields (a child span's
            # own ids) override via the update below
            ev.update(self._trace.fields())
        ev.update(fields)
        self._seq += 1
        self._buffer.append(ev)

    def set_trace(self, ctx) -> None:
        """Bind a :class:`~hmsc_tpu.obs.trace.TraceContext` (or ``None`` to
        unbind): every subsequent event carries its ``trace``/``span``/
        ``parent`` fields.  Already-buffered events are untouched."""
        with self._lock:
            self._trace = ctx

    def count(self, name: str, value: float) -> None:
        """Accumulate a named counter (surfaced in :meth:`summary`)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + float(value)

    def observe(self, name: str, dur_s: float, **fields) -> None:
        """Record a pre-measured duration as a span: updates the span
        aggregates and emits a ``kind="span"`` event, for stages whose
        start and end live on different threads (e.g. the serving engine's
        per-request ``queue_wait``, measured submit→dequeue) where a
        ``with span(...)`` block cannot bracket the interval."""
        dur_s = float(dur_s)
        with self._lock:
            agg = self._spans.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += dur_s
            agg["max_s"] = max(agg["max_s"], dur_s)
            fields = dict(fields)
            fields.update(sid=self._sid, parent=None, depth=0,
                          thread=threading.get_ident(),
                          t0=round(self._now() - dur_s, 6),
                          dur_s=round(dur_s, 6))
            self._sid += 1
            self._append_locked("span", name, fields)

    # -- spans -------------------------------------------------------------

    def span(self, name: str, **fields) -> _Span:
        """Context manager timing one host-loop stage; nesting is tracked
        per thread.  The span event is emitted at close (``t0``/``dur_s``
        relative to the telemetry clock)."""
        return _Span(self, name, fields)

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _open_span(self, sp: _Span) -> None:
        st = self._stack()
        with self._lock:
            sp.sid = self._sid
            self._sid += 1
        sp.parent = st[-1].sid if st else None
        sp.depth = len(st)
        st.append(sp)
        sp.t0 = self._now()

    def _close_span(self, sp: _Span) -> None:
        sp.dur_s = self._now() - sp.t0
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()
        with self._lock:
            agg = self._spans.setdefault(
                sp.name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += sp.dur_s
            agg["max_s"] = max(agg["max_s"], sp.dur_s)
            fields = dict(sp.fields)
            fields.update(sid=sp.sid, parent=sp.parent, depth=sp.depth,
                          thread=threading.get_ident(),
                          t0=round(sp.t0, 6), dur_s=round(sp.dur_s, 6))
            self._append_locked("span", sp.name, fields)

    # -- sink / flushing ---------------------------------------------------

    @property
    def has_sink(self) -> bool:
        return self._sink_path is not None

    def attach_sink(self, path: str, truncate: bool = False) -> None:
        """Bind the JSONL file this telemetry flushes to.  ``truncate``
        starts the stream fresh (a new run owning its directory); append
        mode continues it (resume).  The file is (re)opened per flush, so
        no handle outlives a preemption unwind."""
        path = os.fspath(path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if truncate:
            with open(path, "w"):
                pass
        self._sink_path = path

    def flush(self) -> None:
        """Append all buffered events to the sink (no-op without one).
        Safe from any thread; the sampler submits it to the background
        writer so the write never sits on the segment loop.  The sink lock
        serialises flushes (keeping the file in seq order); the buffer
        swap holds the main lock only briefly, so a slow or hung disk
        never blocks ``emit``/span closes on the driver thread."""
        with self._sink_lock:
            with self._lock:
                if self._sink_path is None or not self._buffer:
                    return
                batch, self._buffer = self._buffer, []
            try:
                with open(self._sink_path, "a") as f:
                    for ev in batch:
                        f.write(json.dumps(ev) + "\n")
            except OSError:
                # telemetry must never kill the run it observes; the
                # events are dropped and accounted
                with self._lock:
                    self.dropped_events += len(batch)

    # -- aggregation views -------------------------------------------------

    def totals(self) -> dict:
        """``{span name: {"count", "total_s", "max_s"}}`` so far."""
        with self._lock:
            return {k: dict(v) for k, v in self._spans.items()}

    def mark_delta(self) -> dict:
        """Per-span total seconds since the previous mark (the payload each
        rank contributes to the commit gather — the committer derives
        cross-rank skew from these without any extra collective).

        The returned ``spans`` dict has the FIXED key set
        ``GATHER_SPAN_SCHEMA + ("other",)`` regardless of which spans have
        fired: the gather payload must not grow with the span-name set
        (new instrumentation would otherwise silently inflate every
        commit's collective on a real pod)."""
        with self._lock:
            cur = {k: v["total_s"] for k, v in self._spans.items()}
            prev, self._mark = self._mark, cur
            delta = {k: cur[k] - prev.get(k, 0.0) for k in cur}
            spans = {k: round(delta.pop(k, 0.0), 6)
                     for k in GATHER_SPAN_SCHEMA}
            spans["other"] = round(sum(delta.values()), 6)
            return {"spans": spans}

    def summary(self, wall_s: float | None = None) -> dict:
        """JSON-safe roll-up attached to ``Posterior.telemetry`` and
        embedded into bench records: span totals, counters, the latest
        metric values, and the per-segment MCMC-health series (running
        R-hat/ESS, divergence counts, throughput — first-class here, not
        only derivable from the raw event stream)."""
        with self._lock:
            return {
                "schema": SCHEMA_VERSION,
                "proc": self.proc,
                "enabled": self.enabled,
                "wall_s": None if wall_s is None else round(wall_s, 4),
                "events": self.n_events,
                "dropped_events": self.dropped_events,
                "spans": {k: {"count": v["count"],
                              "total_s": round(v["total_s"], 6),
                              "max_s": round(v["max_s"], 6)}
                          for k, v in self._spans.items()},
                "counters": {k: round(v, 6)
                             for k, v in self._counters.items()},
                "last": {k: dict(v) for k, v in self._last.items()},
                "health": {
                    "segments": len(self._health),
                    "final": (dict(self._health[-1]) if self._health
                              else None),
                    "series": [dict(h) for h in self._health],
                },
            }
