"""Cross-process trace correlation for the event streams.

A :class:`TraceContext` is minted at each top-level entry point — a fleet
job, a scenario sweep, an autopilot drop, an HTTP request, a bare
``sample_mcmc`` invocation — and propagated to child processes through the
environment (``HMSC_TPU_TRACE_CTX``, threaded through the existing
``testing/multiproc.worker_env`` spawn surface).  Every event a
:class:`~hmsc_tpu.obs.events.RunTelemetry` writes while a context is bound
gains three ADDITIVE fields:

- ``trace`` — the trace id, constant across every process the causal chain
  touches (supervisor → worker ranks, job queue → bucket worker → tenant
  streams, autopilot drop → refit worker → epoch commit → serving flip).
- ``span``  — this process/phase's own span id.
- ``parent`` — the span id of whoever spawned it (absent at the root).

The propagation model is the W3C ``traceparent`` one: a parent serialises
``<trace>:<its own span>`` into the env/header; the child mints a FRESH
span id and records the carried span as its ``parent``.  Assembling the
chain is therefore a pure read-side join on ``trace`` (the hub's
``traces()`` view) — no coordination, no extra collectives, and schema-v1
readers simply ignore the extra keys.  When no context is bound, event
bytes are unchanged.

Ids come from ``os.urandom`` — host-side entropy only, never drawn from
any sampler RNG stream, so tracing is draw-stream invariant by
construction (asserted by ``tests/test_watch.py``).
"""

from __future__ import annotations

import binascii
import os
from dataclasses import dataclass

__all__ = ["TraceContext", "TRACE_ENV", "mint", "from_header",
           "current_context", "inherit_or_mint", "trace_env"]

# env var carrying "<trace_id>:<parent span_id>" across process spawns
TRACE_ENV = "HMSC_TPU_TRACE_CTX"


def _hex(nbytes: int) -> str:
    return binascii.hexlify(os.urandom(nbytes)).decode("ascii")


@dataclass(frozen=True)
class TraceContext:
    """One node of a cross-process causal chain (immutable)."""

    trace_id: str
    span_id: str
    parent_id: str | None = None

    def child(self) -> "TraceContext":
        """A same-trace child span (new span id, parent = this span)."""
        return TraceContext(self.trace_id, _hex(8), self.span_id)

    def header(self) -> str:
        """Wire form handed to children: ``<trace>:<this span>`` — the
        receiver mints its own span via :func:`from_header`."""
        return f"{self.trace_id}:{self.span_id}"

    def fields(self) -> dict:
        """The additive event fields this context contributes."""
        f = {"trace": self.trace_id, "span": self.span_id}
        if self.parent_id:
            f["parent"] = self.parent_id
        return f


def mint() -> TraceContext:
    """A fresh root context (new trace id, no parent)."""
    return TraceContext(_hex(16), _hex(8), None)


def from_header(header: str | None) -> TraceContext | None:
    """A child context of a serialised ``<trace>:<span>`` header (fresh
    span id, carried span as parent).  Malformed/empty headers yield
    ``None`` — a torn env var must never kill the run it annotates."""
    if not header:
        return None
    parts = str(header).split(":")
    if len(parts) != 2 or not parts[0] or not parts[1]:
        return None
    return TraceContext(parts[0], _hex(8), parts[1])


def current_context(env=None) -> TraceContext | None:
    """The context carried by the (process) environment, if any."""
    env = os.environ if env is None else env
    return from_header(env.get(TRACE_ENV))


def inherit_or_mint(env=None) -> TraceContext:
    """Entry-point rule: join the spawning parent's trace when the env
    carries one, otherwise start a fresh root trace."""
    ctx = current_context(env)
    return ctx if ctx is not None else mint()


def trace_env(ctx: TraceContext | None, env: dict | None = None) -> dict:
    """An env overlay propagating ``ctx`` to a child process (merged over
    ``env``); with ``ctx=None`` returns ``env`` unchanged — spawn sites
    stay trace-agnostic."""
    out = dict(env or {})
    if ctx is not None:
        out[TRACE_ENV] = ctx.header()
    return out
