"""Device-mesh construction for chain / species parallelism.

The reference's only parallelism is a SOCK cluster fanning chains over OS
processes (``R/sampleMcmc.R:329-345``).  Here the equivalent is a
``jax.sharding.Mesh``: chains are the data-parallel axis (no collectives
during sampling — chains are independent), and an optional second axis
shards the species dimension of every site x species array model-parallel,
with XLA inserting the cross-species collectives over ICI.

Multi-host: under ``jax.distributed``, ``jax.devices()`` returns the global
device list, so the same helper lays the mesh over all hosts; chains ride
DCN-free (pure replication) and only the species axis communicates — place
it within a host (the default device order does this) so its collectives
stay on ICI.
"""

from __future__ import annotations

import numpy as np

__all__ = ["make_mesh"]


def make_mesh(n_chains: int | None = None, species_shards: int = 1,
              devices=None, chain_axis: str = "chains",
              species_axis: str = "species"):
    """Build a 1-D ``(chains,)`` or 2-D ``(chains, species)`` Mesh.

    ``n_chains = None`` uses every available device on the chain axis (after
    dividing out ``species_shards``).  Raises if the device count cannot be
    factored as requested.  Pass the result as ``sample_mcmc(mesh=...)``;
    chains need not equal the mesh's chain extent (they are laid out over
    it), but the species extent must divide ``ns`` to engage model
    parallelism (the sampler warns and replicates otherwise).
    """
    import jax
    from jax.sharding import Mesh

    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if species_shards < 1:
        raise ValueError(f"species_shards={species_shards} must be >= 1")
    if n_chains is None:
        # derive the chain extent from the device count; needs divisibility
        if n % species_shards:
            from ..mcmc.partition import nearest_divisor
            raise ValueError(
                f"species_shards={species_shards} must divide the device "
                f"count {n}; the nearest valid species_shards for "
                f"{n} device(s) is {nearest_divisor(n, species_shards)} "
                "(or pass n_chains explicitly)")
        n_chain_devs = n // species_shards
    else:
        n_chain_devs = int(n_chains)
        if n_chain_devs < 1:
            raise ValueError(f"n_chains={n_chains} must be >= 1")
    if n_chain_devs * species_shards > n:
        raise ValueError(
            f"{n_chain_devs} chain-devices x {species_shards} species shards "
            f"> {n} devices")
    grid = np.array(devices[:n_chain_devs * species_shards]).reshape(
        n_chain_devs, species_shards)
    if species_shards == 1:
        return Mesh(grid[:, 0], axis_names=(chain_axis,))
    return Mesh(grid, axis_names=(chain_axis, species_axis))
