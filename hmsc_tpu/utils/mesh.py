"""Device-mesh construction for chain / species / site parallelism.

The reference's only parallelism is a SOCK cluster fanning chains over OS
processes (``R/sampleMcmc.R:329-345``).  Here the equivalent is a
``jax.sharding.Mesh``: chains are the data-parallel axis (no collectives
during sampling — chains are independent), an optional second axis
shards the species dimension of every site x species array
model-parallel, and an optional third axis shards the SITE dimension
(sampling rows + per-level units: Z rows, Eta rows, the NNGP/GPP unit
grids) so np-dominated spatial models stop replicating their per-unit
state, with explicit collectives at the cross-site reductions.

Multi-host: under ``jax.distributed``, ``jax.devices()`` returns the global
device list, so the same helper lays the mesh over all hosts; chains ride
DCN-free (pure replication) and only the species/site axes communicate —
place them within a host (the default device order does this) so their
collectives stay on ICI.
"""

from __future__ import annotations

import numpy as np

__all__ = ["make_mesh", "make_draw_mesh"]


def make_draw_mesh(draw_shards: int, devices=None, axis: str = "draws"):
    """1-D ``(draws,)`` Mesh for the serving engine: the posterior draw
    axis is embarrassingly parallel at query time, so the mesh is a flat
    row of the first ``draw_shards`` devices — one collective (the
    partial-moment psum) per query.  Raises if fewer devices exist than
    requested; divisibility against the artifact's draw count is the
    engine's job (it falls back via ``nearest_divisor`` with a warning).
    """
    import jax
    from jax.sharding import Mesh

    devices = list(jax.devices()) if devices is None else list(devices)
    k = int(draw_shards)
    if k < 1:
        raise ValueError(f"draw_shards={draw_shards} must be >= 1")
    if k > len(devices):
        raise ValueError(
            f"draw_shards={k} exceeds the {len(devices)} available "
            "device(s)")
    return Mesh(np.array(devices[:k]), axis_names=(axis,))


def make_mesh(n_chains: int | None = None, species_shards: int = 1,
              site_shards: int = 1, devices=None,
              chain_axis: str = "chains", species_axis: str = "species",
              site_axis: str = "sites"):
    """Build a 1-D ``(chains,)``, 2-D ``(chains, species)`` or 3-D
    ``(chains, species, sites)`` Mesh.

    ``n_chains = None`` uses every available device on the chain axis
    (after dividing out ``species_shards * site_shards``).  Raises if the
    device count cannot be factored as requested.  Pass the result as
    ``sample_mcmc(mesh=...)``; chains need not equal the mesh's chain
    extent (they are laid out over it), but the species extent must
    divide ``ns`` — and the site extent must divide ``ny`` and every
    level's unit count — to engage model parallelism (the sampler warns
    and replicates the failing axis otherwise).  ``site_shards > 1``
    always emits the 3-D mesh (the species axis rides along at extent 1
    when unused, so the shard context's axis names stay uniform).
    """
    import jax
    from jax.sharding import Mesh

    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if species_shards < 1:
        raise ValueError(f"species_shards={species_shards} must be >= 1")
    if site_shards < 1:
        raise ValueError(f"site_shards={site_shards} must be >= 1")
    model_shards = species_shards * site_shards
    if n_chains is None:
        # derive the chain extent from the device count; needs divisibility
        if n % model_shards:
            from ..mcmc.partition import nearest_divisor
            if site_shards == 1:
                hint = (f"the nearest valid species_shards for {n} "
                        f"device(s) is {nearest_divisor(n, species_shards)}")
            elif n % site_shards == 0:
                # the hinted species count must stay valid JOINTLY with
                # the requested site count: divisors of n//site_shards
                hint = (f"with site_shards={site_shards} the nearest "
                        f"valid species_shards for {n} device(s) is "
                        f"{nearest_divisor(n // site_shards, species_shards)}")
            else:
                hint = (f"no species_shards works: site_shards="
                        f"{site_shards} does not divide {n} device(s) — "
                        f"the nearest valid site_shards is "
                        f"{nearest_divisor(n, site_shards)}")
            raise ValueError(
                f"species_shards*site_shards="
                f"{species_shards}*{site_shards}={model_shards} "
                f"must divide the device count {n}; {hint} "
                "(or pass n_chains explicitly)"
                if site_shards > 1 else
                f"species_shards={species_shards} must divide the device "
                f"count {n}; {hint} (or pass n_chains explicitly)")
        n_chain_devs = n // model_shards
    else:
        n_chain_devs = int(n_chains)
        if n_chain_devs < 1:
            raise ValueError(f"n_chains={n_chains} must be >= 1")
    if n_chain_devs * model_shards > n:
        raise ValueError(
            f"{n_chain_devs} chain-devices x {species_shards} species "
            f"shards x {site_shards} site shards > {n} devices")
    grid = np.array(devices[:n_chain_devs * model_shards]).reshape(
        n_chain_devs, species_shards, site_shards)
    if site_shards > 1:
        return Mesh(grid, axis_names=(chain_axis, species_axis, site_axis))
    if species_shards == 1:
        return Mesh(grid[:, 0, 0], axis_names=(chain_axis,))
    return Mesh(grid[:, :, 0], axis_names=(chain_axis, species_axis))
