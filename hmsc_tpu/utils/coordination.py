"""Cross-process coordination for multi-host sampling runs.

The reference fans chains over a SOCK cluster of R processes
(``nParallel``); this package's equivalent is R independent JAX processes,
each sampling its slice of the chains, coordinated ONLY at checkpoint
boundaries (chains never communicate mid-sweep — the Gibbs sweep is
embarrassingly parallel over chains, the same property Hmsc-HPC exploits
across GPUs).  What does need agreement is durability: every process
appends its own immutable shard stream, and one process (the *committer*,
process 0) publishes the atomically-renamed manifest only after a barrier
confirms every peer fsynced its shards up to the boundary — the
single-committer manifest discipline of multi-host array-checkpointing
systems (Orbax-style).

Three backends behind one tiny interface (``barrier`` / ``broadcast`` /
``all_gather``):

- :class:`SingleProcessCoordinator` — the degenerate R=1 case; every
  collective is a local no-op.  ``sample_mcmc`` without a coordinator
  behaves exactly as before.
- :class:`FileCoordinator` — filesystem sentinels in a shared directory.
  Slow-path but dependency-free, which is the point: the FULL multi-process
  protocol (barrier-gated commits, kill-one-process timeouts, committer-only
  GC) runs in tier-1 CPU tests via plain subprocesses, no TPU pod or
  ``jax.distributed`` rendezvous server required.  Also usable for real
  multi-host runs whose hosts share a filesystem (NFS/GCS-fuse).
- :class:`DistributedCoordinator` — ``jax.distributed`` /
  ``jax.experimental.multihost_utils`` collectives for a real multi-process
  mesh (objects ride pickled uint8 arrays over the existing DCN channel).

Collective calls are SPMD: every process must issue the SAME sequence of
collectives (each call consumes one slot of an internal sequence counter —
that counter is what names the sentinel files / sync keys, so a diverging
call order deadlocks instead of silently mispairing payloads).
"""

from __future__ import annotations

import json
import os
import re
import time

__all__ = [
    "Coordinator", "SingleProcessCoordinator", "FileCoordinator",
    "DistributedCoordinator", "CoordinationError", "get_coordinator",
    "HeartbeatWriter", "heartbeat_path", "read_heartbeats",
    "HEARTBEAT_FILE_RE",
]

# heartbeat-p<rank>.json — one liveness file per worker process, updated by
# a background thread; the fleet supervisor (and FileCoordinator timeout
# messages) read ages off these
HEARTBEAT_FILE_RE = re.compile(r"heartbeat-p(\d+)\.json")


def heartbeat_path(dirpath: str, rank: int) -> str:
    """The heartbeat file for worker ``rank`` under a run directory."""
    return os.path.join(os.fspath(dirpath), f"heartbeat-p{int(rank)}.json")


def read_heartbeats(dirpath: str) -> dict:
    """``{rank: {"age_s", "mtime", **payload}}`` for every heartbeat file
    under ``dirpath``.  ``age_s`` comes from the file's mtime (robust to a
    payload written with a skewed clock); an unreadable/mid-rename payload
    still yields an entry with its age — liveness monitoring must not
    depend on the JSON being intact."""
    out: dict = {}
    try:
        names = os.listdir(os.fspath(dirpath))
    except OSError:
        return out
    now = time.time()
    for fn in names:
        m = HEARTBEAT_FILE_RE.fullmatch(fn)
        if not m:
            continue
        p = os.path.join(os.fspath(dirpath), fn)
        try:
            mtime = os.stat(p).st_mtime
        except OSError:
            continue
        rec = {"age_s": max(0.0, now - mtime), "mtime": mtime}
        try:
            with open(p) as f:
                payload = json.loads(f.read())
            if isinstance(payload, dict):
                rec.update(payload)
        except (OSError, ValueError):
            pass
        out[int(m.group(1))] = rec
    return out


class HeartbeatWriter:
    """Per-rank liveness beacon: a daemon thread atomically re-writes
    ``heartbeat-p<rank>.json`` every ``interval_s`` with a monotonically
    increasing ``beat`` counter plus whatever progress fields the worker
    last reported via :meth:`update` (e.g. ``samples_done``).

    The thread is deliberately independent of the sampling loop: it keeps
    beating through long compiles and compiled segments, so a silent file
    means the *process* is wedged or gone — exactly the signal the fleet
    supervisor kills and restarts on.  :meth:`freeze` stops updates without
    stopping the process (the chaos harness's stuck-rank fault).
    """

    # the progress payload crosses from the caller's thread to the beat
    # thread; `hmsc_tpu lint` enforces the declaration below
    # hmsc: guarded-by[_lock]: _fields, _frozen

    def __init__(self, dirpath: str, rank: int, *, interval_s: float = 0.5):
        import threading
        self.path = heartbeat_path(dirpath, rank)
        self.rank = int(rank)
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._fields: dict = {}
        self._frozen = False
        self._beat = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"hmsc-heartbeat-p{rank}", daemon=True)

    def start(self) -> "HeartbeatWriter":
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._write()                 # visible immediately, not interval_s in
        self._thread.start()
        return self

    def update(self, **fields) -> None:
        """Merge progress fields into the next beats' payload."""
        with self._lock:
            self._fields.update(fields)

    def freeze(self) -> None:
        """Stop beating while the process lives on (chaos: a wedged rank —
        the supervisor must detect the silence and SIGKILL it)."""
        with self._lock:
            self._frozen = True

    def stop(self) -> None:
        """Stop the beat thread and remove the heartbeat file (a clean
        exit must not read as a frozen rank)."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def _write(self) -> None:
        with self._lock:
            if self._frozen:
                return
            payload = dict(self._fields, rank=self.rank, pid=os.getpid(),
                           beat=self._beat, wall=round(time.time(), 3))
            self._beat += 1
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps(payload))
            os.replace(tmp, self.path)
        except OSError:
            pass                      # liveness is best-effort; a full disk
            #                           must not kill the run it monitors

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._write()


class CoordinationError(RuntimeError):
    """A collective failed: a peer died, timed out, or answered garbage.

    Raised instead of hanging forever — the caller (the sampling loop's
    writer thread) propagates it like any other writer failure, so a killed
    peer surfaces as a clean run failure with every already-committed
    manifest intact."""


class Coordinator:
    """Interface: R processes, rank ``process_index``, process 0 commits.

    ``barrier(tag)`` blocks until every process reaches it;
    ``broadcast(obj)`` returns process 0's object on every process;
    ``all_gather(obj)`` returns the list of every process's object in rank
    order.  All three are collectives — every process must call them in the
    same order (see module docstring)."""

    process_index: int = 0
    process_count: int = 1

    @property
    def is_coordinator(self) -> bool:
        """Whether this process is the committer (rank 0): the only rank
        that writes manifests and runs GC."""
        return self.process_index == 0

    def barrier(self, tag: str = "barrier") -> None:
        raise NotImplementedError

    def broadcast(self, obj, tag: str = "bcast"):
        return self.all_gather(obj, tag=tag)[0]

    def all_gather(self, obj, tag: str = "gather") -> list:
        raise NotImplementedError

    def timeout_override(self, timeout_s: float):
        """Context manager raising this coordinator's collective timeout
        while a known-slow section runs (the coordinated divergence
        repair: healthy ranks legitimately wait out a peer's re-sample,
        which can far exceed the per-commit timeout).  No-op on backends
        without their own timeout (``jax.distributed`` owns its
        deadlines)."""
        import contextlib
        return contextlib.nullcontext()


class SingleProcessCoordinator(Coordinator):
    """R = 1: every collective completes immediately with local data."""

    def barrier(self, tag: str = "barrier") -> None:
        pass

    def all_gather(self, obj, tag: str = "gather") -> list:
        return [obj]


class FileCoordinator(Coordinator):
    """Filesystem-sentinel collectives over a shared directory.

    Each collective call ``n`` writes an atomically-renamed
    ``coord-<n>-<rank>.json`` sentinel carrying the (JSON-serialisable)
    payload, then polls until all R sentinels for slot ``n`` exist.  A
    process that completes slot ``n`` sweeps EVERY rank's slot-``n-1``
    sentinels: a peer only writes slot ``n`` after its own slot-``n-1``
    gather returned (collectives are ordered), so those files are provably
    dead, and the directory holds exactly the live slot's O(R) files
    regardless of run length.

    ``timeout_s`` bounds every wait: a peer that died mid-protocol turns
    into :class:`CoordinationError` instead of a hang — the
    kill-one-process-mid-segment story depends on this.  When
    ``heartbeat_dir`` is set (the fleet supervisor's spawn harness points
    it at the workers' heartbeat directory), the timeout message also
    reports the last-heartbeat age of each missing rank, so the operator
    (or supervisor log) can tell a dead rank from a merely stalled one.
    The directory must be empty of another run's sentinels (use a fresh
    subdirectory per run attempt; ``resume`` attempts get their own)."""

    def __init__(self, dirpath: str, process_index: int, process_count: int,
                 *, timeout_s: float = 120.0, poll_s: float = 0.001,
                 heartbeat_dir: str | None = None):
        if not (0 <= int(process_index) < int(process_count)):
            raise ValueError(
                f"process_index {process_index} out of range for "
                f"process_count {process_count}")
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self._dir = os.fspath(dirpath)
        self._timeout = float(timeout_s)
        self._poll = float(poll_s)
        self._hb_dir = (os.fspath(heartbeat_dir)
                        if heartbeat_dir is not None else None)
        self._seq = 0
        os.makedirs(self._dir, exist_ok=True)

    def timeout_override(self, timeout_s: float):
        """Temporarily replace ``timeout_s`` for the collectives issued
        inside the ``with`` block (never lowers it below the configured
        value).  Single-threaded per coordinator instance, like every
        other use of one."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            prev = self._timeout
            self._timeout = max(prev, float(timeout_s))
            try:
                yield
            finally:
                self._timeout = prev
        return _ctx()

    def _heartbeat_detail(self, pending) -> str:
        """last-heartbeat ages of the missing ranks, for timeout messages."""
        if self._hb_dir is None:
            return ""
        hb = read_heartbeats(self._hb_dir)
        bits = []
        for r in sorted(pending):
            rec = hb.get(r)
            bits.append(f"rank {r}: no heartbeat file" if rec is None else
                        f"rank {r}: last heartbeat {rec['age_s']:.1f}s ago")
        return f" ({'; '.join(bits)})" if bits else ""

    def _path(self, seq: int, rank: int) -> str:
        return os.path.join(self._dir, f"coord-{seq:08d}-{rank}.json")

    def barrier(self, tag: str = "barrier") -> None:
        self.all_gather(None, tag=tag)

    def all_gather(self, obj, tag: str = "gather") -> list:
        seq = self._seq
        self._seq += 1
        mine = self._path(seq, self.process_index)
        tmp = f"{mine}.tmp.{os.getpid()}"
        body = json.dumps({"tag": tag, "payload": obj})
        # no fsync: sentinels are transient coordination data, not
        # durability artifacts — the atomic rename is what makes the
        # payload visible to peers, and a crash simply resumes from the
        # committed manifests (whose own writes DO fsync).  Sentinel
        # fsyncs would add several ms to every collective for nothing.
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, mine)

        deadline = time.monotonic() + self._timeout
        out = [None] * self.process_count
        pending = set(range(self.process_count))
        while pending:
            for r in sorted(pending):
                p = self._path(seq, r)
                try:
                    with open(p) as f:
                        rec = json.loads(f.read())
                except (OSError, ValueError):
                    continue           # not there yet / mid-rename
                if rec.get("tag") != tag:
                    raise CoordinationError(
                        f"collective #{seq} mispaired: rank {r} is at "
                        f"{rec.get('tag')!r}, this rank at {tag!r} — the "
                        "processes issued diverging collective sequences")
                out[r] = rec["payload"]
                pending.discard(r)
            if pending:
                if time.monotonic() > deadline:
                    raise CoordinationError(
                        f"collective {tag!r} (#{seq}) timed out after "
                        f"{self._timeout:.0f}s waiting for rank(s) "
                        f"{sorted(pending)} of {self.process_count}"
                        f"{self._heartbeat_detail(pending)} — a "
                        "peer process died or stalled; committed "
                        "checkpoints are intact, resume with resume_run")
                time.sleep(self._poll)
        # every peer has WRITTEN slot `seq`, which it only does after its
        # own slot `seq-1` gather returned — so EVERY rank's slot `seq-1`
        # sentinel is provably dead.  Sweep them all (not just our own, the
        # former behaviour): a rank that crashes later then strands at most
        # its final slot, and the directory holds exactly the live slot's
        # O(R) files instead of leaking one extra slot per rank.  Racing
        # unlinks of the same file are harmless (OSError ignored).
        if seq > 0:
            for r in range(self.process_count):
                try:
                    os.unlink(self._path(seq - 1, r))
                except OSError:
                    pass
        return out

    def cleanup(self) -> None:
        """Reclaim stale sentinels at shutdown — every rank's slots up to
        ``_seq - 2`` (all provably read by every peer; normally already
        swept by the per-collective sweep above, this catches files left by
        a peer that crashed mid-protocol).  The FINAL slot's sentinels must
        stay: a slower peer may still be polling them (deleting one would
        strand that peer until its timeout).  The leftover is therefore
        O(R) tiny files for the last collective only, in a per-attempt
        directory reclaimed with the directory itself."""
        for seq in range(self._seq - 1):
            for r in range(self.process_count):
                try:
                    os.unlink(self._path(seq, r))
                except OSError:
                    pass


class DistributedCoordinator(Coordinator):
    """Collectives over an initialised ``jax.distributed`` runtime.

    Objects are pickled onto uint8 device arrays and gathered with
    ``jax.experimental.multihost_utils`` (two collectives per gather: one
    for the byte lengths, one for the padded payloads) — metadata-sized
    traffic only, the draw shards themselves never cross hosts.  Requires
    ``jax.distributed.initialize()`` to have run (or a single-process
    context, where it degenerates gracefully)."""

    def __init__(self):
        import jax
        self.process_index = int(jax.process_index())
        self.process_count = int(jax.process_count())

    def barrier(self, tag: str = "barrier") -> None:
        if self.process_count == 1:
            return
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)

    def all_gather(self, obj, tag: str = "gather") -> list:
        import pickle

        import numpy as np

        if self.process_count == 1:
            return [obj]
        from jax.experimental import multihost_utils
        data = pickle.dumps(obj)
        lens = np.asarray(multihost_utils.process_allgather(
            np.array([len(data)], dtype=np.int64))).reshape(-1)
        buf = np.zeros(int(lens.max()), dtype=np.uint8)
        buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        allbuf = np.asarray(multihost_utils.process_allgather(buf))
        return [pickle.loads(allbuf[r, :int(lens[r])].tobytes())
                for r in range(self.process_count)]


def get_coordinator(coordinator=None) -> Coordinator:
    """Resolve the coordinator ``sample_mcmc`` runs under.

    An explicit coordinator wins; otherwise a multi-process JAX runtime
    (``jax.process_count() > 1`` — i.e. ``jax.distributed`` was
    initialised) gets the :class:`DistributedCoordinator`, and the common
    single-process case gets the no-op :class:`SingleProcessCoordinator`."""
    if coordinator is not None:
        return coordinator
    import jax
    if int(jax.process_count()) > 1:
        return DistributedCoordinator()
    return SingleProcessCoordinator()
