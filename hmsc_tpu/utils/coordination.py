"""Cross-process coordination for multi-host sampling runs.

The reference fans chains over a SOCK cluster of R processes
(``nParallel``); this package's equivalent is R independent JAX processes,
each sampling its slice of the chains, coordinated ONLY at checkpoint
boundaries (chains never communicate mid-sweep — the Gibbs sweep is
embarrassingly parallel over chains, the same property Hmsc-HPC exploits
across GPUs).  What does need agreement is durability: every process
appends its own immutable shard stream, and one process (the *committer*,
process 0) publishes the atomically-renamed manifest only after a barrier
confirms every peer fsynced its shards up to the boundary — the
single-committer manifest discipline of multi-host array-checkpointing
systems (Orbax-style).

Three backends behind one tiny interface (``barrier`` / ``broadcast`` /
``all_gather``):

- :class:`SingleProcessCoordinator` — the degenerate R=1 case; every
  collective is a local no-op.  ``sample_mcmc`` without a coordinator
  behaves exactly as before.
- :class:`FileCoordinator` — filesystem sentinels in a shared directory.
  Slow-path but dependency-free, which is the point: the FULL multi-process
  protocol (barrier-gated commits, kill-one-process timeouts, committer-only
  GC) runs in tier-1 CPU tests via plain subprocesses, no TPU pod or
  ``jax.distributed`` rendezvous server required.  Also usable for real
  multi-host runs whose hosts share a filesystem (NFS/GCS-fuse).
- :class:`DistributedCoordinator` — ``jax.distributed`` /
  ``jax.experimental.multihost_utils`` collectives for a real multi-process
  mesh (objects ride pickled uint8 arrays over the existing DCN channel).

Collective calls are SPMD: every process must issue the SAME sequence of
collectives (each call consumes one slot of an internal sequence counter —
that counter is what names the sentinel files / sync keys, so a diverging
call order deadlocks instead of silently mispairing payloads).
"""

from __future__ import annotations

import json
import os
import time

__all__ = [
    "Coordinator", "SingleProcessCoordinator", "FileCoordinator",
    "DistributedCoordinator", "CoordinationError", "get_coordinator",
]


class CoordinationError(RuntimeError):
    """A collective failed: a peer died, timed out, or answered garbage.

    Raised instead of hanging forever — the caller (the sampling loop's
    writer thread) propagates it like any other writer failure, so a killed
    peer surfaces as a clean run failure with every already-committed
    manifest intact."""


class Coordinator:
    """Interface: R processes, rank ``process_index``, process 0 commits.

    ``barrier(tag)`` blocks until every process reaches it;
    ``broadcast(obj)`` returns process 0's object on every process;
    ``all_gather(obj)`` returns the list of every process's object in rank
    order.  All three are collectives — every process must call them in the
    same order (see module docstring)."""

    process_index: int = 0
    process_count: int = 1

    @property
    def is_coordinator(self) -> bool:
        """Whether this process is the committer (rank 0): the only rank
        that writes manifests and runs GC."""
        return self.process_index == 0

    def barrier(self, tag: str = "barrier") -> None:
        raise NotImplementedError

    def broadcast(self, obj, tag: str = "bcast"):
        return self.all_gather(obj, tag=tag)[0]

    def all_gather(self, obj, tag: str = "gather") -> list:
        raise NotImplementedError


class SingleProcessCoordinator(Coordinator):
    """R = 1: every collective completes immediately with local data."""

    def barrier(self, tag: str = "barrier") -> None:
        pass

    def all_gather(self, obj, tag: str = "gather") -> list:
        return [obj]


class FileCoordinator(Coordinator):
    """Filesystem-sentinel collectives over a shared directory.

    Each collective call ``n`` writes an atomically-renamed
    ``coord-<n>-<rank>.json`` sentinel carrying the (JSON-serialisable)
    payload, then polls until all R sentinels for slot ``n`` exist.  A
    process may delete its OWN slot-``n-1`` sentinel once its slot-``n``
    gather completes: every peer writing slot ``n`` has by construction
    finished READING slot ``n-1`` (collectives are ordered), so the
    directory holds O(R) live files regardless of run length.

    ``timeout_s`` bounds every wait: a peer that died mid-protocol turns
    into :class:`CoordinationError` instead of a hang — the
    kill-one-process-mid-segment story depends on this.  The directory must
    be empty of another run's sentinels (use a fresh subdirectory per run
    attempt; ``resume`` attempts get their own)."""

    def __init__(self, dirpath: str, process_index: int, process_count: int,
                 *, timeout_s: float = 120.0, poll_s: float = 0.001):
        if not (0 <= int(process_index) < int(process_count)):
            raise ValueError(
                f"process_index {process_index} out of range for "
                f"process_count {process_count}")
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self._dir = os.fspath(dirpath)
        self._timeout = float(timeout_s)
        self._poll = float(poll_s)
        self._seq = 0
        os.makedirs(self._dir, exist_ok=True)

    def _path(self, seq: int, rank: int) -> str:
        return os.path.join(self._dir, f"coord-{seq:08d}-{rank}.json")

    def barrier(self, tag: str = "barrier") -> None:
        self.all_gather(None, tag=tag)

    def all_gather(self, obj, tag: str = "gather") -> list:
        seq = self._seq
        self._seq += 1
        mine = self._path(seq, self.process_index)
        tmp = f"{mine}.tmp.{os.getpid()}"
        body = json.dumps({"tag": tag, "payload": obj})
        # no fsync: sentinels are transient coordination data, not
        # durability artifacts — the atomic rename is what makes the
        # payload visible to peers, and a crash simply resumes from the
        # committed manifests (whose own writes DO fsync).  Sentinel
        # fsyncs would add several ms to every collective for nothing.
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, mine)

        deadline = time.monotonic() + self._timeout
        out = [None] * self.process_count
        pending = set(range(self.process_count))
        while pending:
            for r in sorted(pending):
                p = self._path(seq, r)
                try:
                    with open(p) as f:
                        rec = json.loads(f.read())
                except (OSError, ValueError):
                    continue           # not there yet / mid-rename
                if rec.get("tag") != tag:
                    raise CoordinationError(
                        f"collective #{seq} mispaired: rank {r} is at "
                        f"{rec.get('tag')!r}, this rank at {tag!r} — the "
                        "processes issued diverging collective sequences")
                out[r] = rec["payload"]
                pending.discard(r)
            if pending:
                if time.monotonic() > deadline:
                    raise CoordinationError(
                        f"collective {tag!r} (#{seq}) timed out after "
                        f"{self._timeout:.0f}s waiting for rank(s) "
                        f"{sorted(pending)} of {self.process_count} — a "
                        "peer process died or stalled; committed "
                        "checkpoints are intact, resume with resume_run")
                time.sleep(self._poll)
        # every peer has started slot `seq`, so all of them finished
        # reading slot `seq-1`: our previous sentinel is reclaimable
        if seq > 0:
            try:
                os.unlink(self._path(seq - 1, self.process_index))
            except OSError:
                pass
        return out

    def cleanup(self) -> None:
        """Reclaim this rank's stale sentinels at shutdown.

        Only slots every peer provably finished reading (≤ ``_seq - 2``:
        a peer that completed slot ``n`` has read slot ``n - 1``) are
        removable — the LAST sentinel must stay, because a slower peer may
        still be polling it (deleting it would strand that peer until its
        timeout).  The leftover is O(R) tiny files in a per-attempt
        directory, reclaimed with the directory itself."""
        for seq in range(self._seq - 1):
            try:
                os.unlink(self._path(seq, self.process_index))
            except OSError:
                pass


class DistributedCoordinator(Coordinator):
    """Collectives over an initialised ``jax.distributed`` runtime.

    Objects are pickled onto uint8 device arrays and gathered with
    ``jax.experimental.multihost_utils`` (two collectives per gather: one
    for the byte lengths, one for the padded payloads) — metadata-sized
    traffic only, the draw shards themselves never cross hosts.  Requires
    ``jax.distributed.initialize()`` to have run (or a single-process
    context, where it degenerates gracefully)."""

    def __init__(self):
        import jax
        self.process_index = int(jax.process_index())
        self.process_count = int(jax.process_count())

    def barrier(self, tag: str = "barrier") -> None:
        if self.process_count == 1:
            return
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)

    def all_gather(self, obj, tag: str = "gather") -> list:
        import pickle

        import numpy as np

        if self.process_count == 1:
            return [obj]
        from jax.experimental import multihost_utils
        data = pickle.dumps(obj)
        lens = np.asarray(multihost_utils.process_allgather(
            np.array([len(data)], dtype=np.int64))).reshape(-1)
        buf = np.zeros(int(lens.max()), dtype=np.uint8)
        buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        allbuf = np.asarray(multihost_utils.process_allgather(buf))
        return [pickle.loads(allbuf[r, :int(lens[r])].tobytes())
                for r in range(self.process_count)]


def get_coordinator(coordinator=None) -> Coordinator:
    """Resolve the coordinator ``sample_mcmc`` runs under.

    An explicit coordinator wins; otherwise a multi-process JAX runtime
    (``jax.process_count() > 1`` — i.e. ``jax.distributed`` was
    initialised) gets the :class:`DistributedCoordinator`, and the common
    single-process case gets the no-op :class:`SingleProcessCoordinator`."""
    if coordinator is not None:
        return coordinator
    import jax
    if int(jax.process_count()) > 1:
        return DistributedCoordinator()
    return SingleProcessCoordinator()
