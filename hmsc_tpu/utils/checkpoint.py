"""Checkpoint / resume for long MCMC runs (SURVEY.md §5: the reference has no
in-process fault tolerance — a killed ``sampleMcmc`` loses everything; its
idiom is R serialization of the fitted object plus ``initPar`` warm starts).

Format v2 (this module): one ``.npz`` holding the recorded posterior arrays
(``post:<name>``), the chain carry-state leaves keyed by *structural name*
(``state:levels.0.Eta``), optionally the carried per-chain RNG keys, and a
JSON header with per-payload crc32 checksums plus a model-spec fingerprint.
Nothing is pickled: the state pytree structure is re-derived from
``build_spec(hM)`` at load time, so a checkpoint survives any environment
that can rebuild the model.  Writes are atomic (tmp + rename) and
``sample_mcmc(checkpoint_every=..., checkpoint_path=...)`` rotates the last
K snapshots, so a kill at any instant leaves a loadable file behind.

``load_checkpoint`` + ``sample_mcmc(init_state=...)`` continues the chains
bit-exactly where they left off; when the checkpoint also carries the RNG
keys (auto-checkpoints always do), ``resume_run`` continues the *key stream*
too, making kill → resume produce draws bit-identical to an uninterrupted
run.  Corruption (flipped bytes, truncation) is detected via the checksums
and rejected with :class:`CheckpointCorruptError`; ``resume_run`` then falls
back to the previous rotation slot.  Legacy v1 files (pickled metadata) are
readable only behind an explicit ``allow_legacy_pickle=True``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import warnings
import zipfile
import zlib
from typing import Any

import numpy as np

__all__ = [
    "save_checkpoint", "load_checkpoint", "load_checkpoint_full",
    "concat_posteriors", "resume_run", "checkpoint_files",
    "rotate_checkpoints", "latest_valid_checkpoint", "spec_fingerprint",
    "CheckpointError", "CheckpointCorruptError",
    "CheckpointSpecMismatchError", "PreemptedRun", "LoadedCheckpoint",
    "CKPT_VERSION",
]

CKPT_VERSION = 2
_HEADER_KEY = "__hmsc_ckpt_header__"
# ckpt-<samples>.npz: sample snapshot; ckpt-t<sweep>.npz: state-only burn-in
# snapshot (no draws yet — always older than any sample snapshot)
_CKPT_RE = re.compile(r"ckpt-(t?)(\d+)\.npz")


class CheckpointError(RuntimeError):
    """Base class for checkpoint load/save failures."""


class CheckpointCorruptError(CheckpointError):
    """The file is unreadable or a payload failed its integrity checksum."""


class CheckpointSpecMismatchError(CheckpointError):
    """The checkpoint was written for a different model specification."""


class PreemptedRun(RuntimeError):
    """Raised by ``sample_mcmc`` when SIGTERM/SIGINT arrives during an
    auto-checkpointing run: the in-flight segment is finished, a resumable
    snapshot is written, and the run unwinds with this error.  Continue with
    ``resume_run`` (or ``python -m hmsc_tpu run --resume``)."""

    def __init__(self, msg, checkpoint_path=None, samples_done=0, signum=None):
        super().__init__(msg)
        self.checkpoint_path = checkpoint_path
        self.samples_done = samples_done
        self.signum = signum


@dataclasses.dataclass
class LoadedCheckpoint:
    """Everything a checkpoint carries: the partial posterior, the chain
    carry state, optionally the carried RNG keys, the sampler's run metadata
    (empty for manual ``save_checkpoint`` files), and the parsed header."""
    post: Any
    state: Any
    keys: Any
    run_meta: dict
    header: dict
    path: str


# ---------------------------------------------------------------------------
# structural (pickle-free) state layout
# ---------------------------------------------------------------------------

def _state_skeleton(spec):
    """(leaf names, treedef) of the carry state, derived purely from the
    model spec: a GibbsState whose leaves are their own names has the same
    pytree structure as the real state (every field is a leaf), so the
    flatten order gives a stable name per saved array — no pickled treedef."""
    import jax

    from ..mcmc.structs import GibbsState, LevelState

    def lvl(r):
        return LevelState(
            Eta=f"levels.{r}.Eta", Lambda=f"levels.{r}.Lambda",
            Psi=f"levels.{r}.Psi", Delta=f"levels.{r}.Delta",
            alpha_idx=f"levels.{r}.alpha_idx", nf_mask=f"levels.{r}.nf_mask",
            nf_sat=f"levels.{r}.nf_sat")

    skel = GibbsState(
        Z="Z", Beta="Beta", Gamma="Gamma", iV="iV", rho_idx="rho_idx",
        iSigma="iSigma", levels=tuple(lvl(r) for r in range(spec.nr)),
        it="it", BetaSel=tuple(f"BetaSel.{i}" for i in range(spec.ncsel)),
        wRRR="wRRR", PsiRRR="PsiRRR", DeltaRRR="DeltaRRR")
    names, treedef = jax.tree_util.tree_flatten(skel)
    return list(names), treedef


def _effective_nf_cap(spec) -> int:
    """The smallest nf_cap that rebuilds this spec via ``build_spec``: every
    level's nf_max is min(prior bound, ns, cap), so the max over levels
    reconstructs each level exactly (a capped level stores the cap itself)."""
    from ..mcmc.structs import DEFAULT_NF_CAP
    return max((ls.nf_max for ls in spec.levels), default=DEFAULT_NF_CAP)


def spec_fingerprint(spec) -> str:
    """sha256 of the (frozen, primitive-valued) ModelSpec repr — changes
    whenever the model structure or the package's spec layout changes."""
    return hashlib.sha256(repr(spec).encode()).hexdigest()


def _crc(a) -> str:
    return f"{zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF:08x}"


def _atomic_savez(path: str, payload: dict, compress: bool = False) -> None:
    """tmp + fsync + rename so a kill mid-write never leaves a torn file
    under the final name.

    Uncompressed by default: posterior draws are high-entropy f32 (measured
    ~13% size reduction for ~7x the serialisation CPU), and checkpoint
    serialisation rides the sampler's background writer thread — cheap
    writes keep it off the compute cores the XLA CPU backend shares.  Pass
    ``compress=True`` for cold archival copies; ``np.load`` reads both."""
    tmp = f"{path}.tmp.{os.getpid()}"
    savez = np.savez_compressed if compress else np.savez
    try:
        with open(tmp, "wb") as f:
            savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # fsync the directory so the rename itself is durable — the
        # background writer's barrier relies on a completed write meaning
        # "survives power loss", not just "visible to this process"
        try:
            dfd = os.open(os.path.dirname(os.path.abspath(path)),
                          os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass               # directory fsync unsupported (non-POSIX)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------------

def save_checkpoint(path: str, post, state, *, keys=None, keys_impl=None,
                    run_meta: dict | None = None,
                    compress: bool = False) -> None:
    """Write a resumable snapshot: the Posterior so far + the carry state
    from ``sample_mcmc(..., return_state=True)``.

    ``keys``/``keys_impl`` optionally persist the carried per-chain RNG keys
    so a continuation replays the exact key stream — auto-checkpoints always
    pass them.  ``keys`` may be ``jax.random`` typed keys or the raw uint32
    key-data array (the sampler's background writer snapshots key data, not
    typed keys, before the carry is donated to the next segment).
    ``run_meta`` is an arbitrary JSON-serializable dict stored in the header
    (``resume_run`` reads the sampler's run configuration from it)."""
    import jax

    path = os.fspath(path)
    names, skel_def = _state_skeleton(post.spec)
    leaves, state_def = jax.tree_util.tree_flatten(state)
    if state_def != skel_def:
        raise CheckpointError(
            "carry state structure does not match the layout derived from "
            "the model spec (GibbsState fields changed without updating "
            "checkpoint._state_skeleton?) — refusing to write an "
            "unloadable checkpoint")

    payload = {f"post:{k}": np.asarray(v) for k, v in post.arrays.items()}
    payload.update({f"state:{n}": np.asarray(x)
                    for n, x in zip(names, leaves)})
    if keys is not None:
        if keys_impl is None:
            raise ValueError("save_checkpoint: keys requires keys_impl "
                             "(the PRNG impl name, e.g. 'threefry2x32')")
        kd = keys
        if hasattr(keys, "dtype") and jax.dtypes.issubdtype(
                keys.dtype, jax.dtypes.prng_key):
            kd = jax.random.key_data(keys)
        payload["rngkeys"] = np.asarray(kd)

    import hmsc_tpu as _pkg
    header = {
        "format": "hmsc_tpu-checkpoint",
        "version": CKPT_VERSION,
        "package_version": _pkg.__version__,
        "samples": int(post.samples),
        "transient": int(post.transient),
        "thin": int(post.thin),
        "n_chains": int(post.n_chains),
        "nf_cap": int(_effective_nf_cap(post.spec)),
        "spec_sha256": spec_fingerprint(post.spec),
        "keys_impl": keys_impl,
        "first_bad_it": [int(x) for x in post.chain_health["first_bad_it"]],
        "nf_saturation": {str(r): np.asarray(v).tolist()
                          for r, v in post.nf_saturation.items()},
        "checksums": {k: _crc(v) for k, v in payload.items()},
        "run": run_meta or {},
    }
    payload[_HEADER_KEY] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    _atomic_savez(path, payload, compress=compress)


def load_checkpoint_full(path: str, hM, *,
                         allow_legacy_pickle: bool = False) -> LoadedCheckpoint:
    """Load a checkpoint with full metadata (see :class:`LoadedCheckpoint`).

    Raises :class:`CheckpointCorruptError` on unreadable/byte-flipped files
    (every payload is checksummed) and :class:`CheckpointSpecMismatchError`
    when the file was written for a different model spec."""
    import jax
    import jax.numpy as jnp

    from ..mcmc.structs import build_spec
    from ..post.posterior import Posterior

    path = os.fspath(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            files = set(z.files)
            if _HEADER_KEY not in files:
                if "meta" in files:
                    return _load_legacy_v1(z, hM, path, allow_legacy_pickle)
                raise CheckpointCorruptError(
                    f"{path}: not an hmsc_tpu checkpoint (no v2 header and "
                    "no legacy v1 metadata)")
            header = json.loads(z[_HEADER_KEY].tobytes().decode())

            # materialise each payload exactly once (NpzFile re-inflates the
            # zip member on every access — verifying from z[k] and then
            # loading z[k] again would decompress a multi-GB checkpoint
            # twice), then verify against the header's checksums
            data = {k: z[k] for k in files if k != _HEADER_KEY}
            for k, want in header.get("checksums", {}).items():
                if k not in data:
                    raise CheckpointCorruptError(
                        f"{path}: payload {k!r} is missing — the file is "
                        "truncated or corrupt")
                got = _crc(data[k])
                if got != want:
                    raise CheckpointCorruptError(
                        f"{path}: payload {k!r} failed its integrity "
                        f"checksum (crc32 {got} != {want}) — the file is "
                        "corrupt; fall back to an earlier rotation slot")

            spec = build_spec(hM, int(header["nf_cap"]))
            got_fp = spec_fingerprint(spec)
            if got_fp != header["spec_sha256"]:
                raise CheckpointSpecMismatchError(
                    f"{path}: model spec fingerprint mismatch "
                    f"({got_fp[:12]}… != {header['spec_sha256'][:12]}…) — "
                    "the checkpoint was written for a different model "
                    "(data shapes, levels, priors) or a different "
                    "hmsc_tpu spec layout; rebuild the matching Hmsc "
                    "object to resume")

            names, treedef = _state_skeleton(spec)
            missing = [n for n in names if f"state:{n}" not in data]
            if missing:
                raise CheckpointCorruptError(
                    f"{path}: carry-state leaves missing: {missing}")
            state = jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(data[f"state:{n}"]) for n in names])

            arrays = {k[5:]: v for k, v in data.items()
                      if k.startswith("post:")}
            keys = None
            if "rngkeys" in data and header.get("keys_impl"):
                keys = jax.random.wrap_key_data(
                    jnp.asarray(data["rngkeys"]), impl=header["keys_impl"])
    except CheckpointError:
        raise
    except (zipfile.BadZipFile, zlib.error, OSError, ValueError, KeyError,
            EOFError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable checkpoint ({type(e).__name__}: {e}) — "
            "the file is corrupt or truncated") from e

    post = Posterior(hM, spec, arrays, samples=int(header["samples"]),
                     transient=int(header["transient"]),
                     thin=int(header["thin"]))
    if not post.arrays:
        # state-only burn-in snapshot: no recorded arrays to derive the
        # chain count from — restore it from the header
        post.n_chains = int(header.get("n_chains", 0))
    if "first_bad_it" in header:
        post.set_chain_health(np.asarray(header["first_bad_it"]))
    post.nf_saturation = {int(r): np.asarray(v)
                          for r, v in header.get("nf_saturation", {}).items()}
    return LoadedCheckpoint(post=post, state=state, keys=keys,
                            run_meta=dict(header.get("run", {})),
                            header=header, path=path)


def _load_legacy_v1(z, hM, path, allow_legacy_pickle) -> LoadedCheckpoint:
    """Guarded read path for pre-v2 files: the run metadata is a python
    pickle, so it is only decoded behind an explicit opt-in.  The state
    structure itself is rebuilt from the spec (the v1 leaves ``state:<i>``
    are in the same flatten order), so the pickled treedef is never used."""
    if not allow_legacy_pickle:
        raise CheckpointError(
            f"{path}: legacy v1 checkpoint whose metadata is a python "
            "pickle; refusing to unpickle by default.  Pass "
            "allow_legacy_pickle=True only if you trust the file's origin "
            "(or re-save it in the v2 format via save_checkpoint)")
    import pickle

    import jax.numpy as jnp
    from jax.tree_util import tree_unflatten

    from ..mcmc.structs import build_spec
    from ..post.posterior import Posterior

    meta = pickle.loads(z["meta"].tobytes())
    arrays = {k[5:]: z[k] for k in z.files if k.startswith("post:")}
    n_state = sum(1 for k in z.files if k.startswith("state:"))
    leaves = [jnp.asarray(z[f"state:{i}"]) for i in range(n_state)]
    spec = build_spec(hM)
    names, treedef = _state_skeleton(spec)
    if len(leaves) != len(names):
        raise CheckpointCorruptError(
            f"{path}: legacy checkpoint carries {len(leaves)} state leaves, "
            f"the model spec implies {len(names)}")
    state = tree_unflatten(treedef, leaves)
    post = Posterior(hM, spec, arrays, samples=meta["samples"],
                     transient=meta["transient"], thin=meta["thin"])
    return LoadedCheckpoint(post=post, state=state, keys=None, run_meta={},
                            header={"version": 1}, path=path)


def load_checkpoint(path: str, hM, *, allow_legacy_pickle: bool = False):
    """Returns (Posterior, carry_state) ready for
    ``sample_mcmc(hM, ..., init_state=carry_state)`` — see
    :func:`load_checkpoint_full` for the RNG keys and run metadata."""
    ck = load_checkpoint_full(path, hM, allow_legacy_pickle=allow_legacy_pickle)
    return ck.post, ck.state


# ---------------------------------------------------------------------------
# rotation / discovery
# ---------------------------------------------------------------------------

def checkpoint_files(path: str) -> list[str]:
    """Auto-checkpoint files under a directory, newest first: sample
    snapshots (most samples first), then burn-in snapshots (most sweeps
    first — every burn-in snapshot predates every sample snapshot).  A
    direct file path is returned as a single-element list; an ``archive/``
    subdirectory is never scanned."""
    path = os.fspath(path)
    if os.path.isfile(path):
        return [path]
    if not os.path.isdir(path):
        return []          # no directory yet -> no checkpoints (callers
                           # raise the documented CheckpointError on empty)
    entries = []
    for fn in os.listdir(path):
        m = _CKPT_RE.fullmatch(fn)
        if m:
            kind = 0 if m.group(1) else 1      # burn-in sorts below samples
            entries.append(((kind, int(m.group(2))), os.path.join(path, fn)))
    return [p for _, p in sorted(entries, reverse=True)]


def rotate_checkpoints(path: str, keep: int, *,
                       max_age_s: float | None = None) -> None:
    """Delete all but the newest ``keep`` auto-checkpoints in a directory.

    ``max_age_s`` adds an age-based policy on top: snapshots whose mtime is
    older than ``max_age_s`` seconds are deleted even inside the keep
    window — except the newest, which always survives (a stalled run must
    not age away its only resume point).  Snapshots hard-linked into
    ``archive/`` (``checkpoint_archive_every``) are exempt from both."""
    files = checkpoint_files(path)
    doomed = files[keep:] if keep > 0 else []
    survivors = files[:keep] if keep > 0 else files
    if max_age_s is not None and len(survivors) > 1:
        import time
        now = time.time()
        for p in survivors[1:]:
            try:
                if now - os.path.getmtime(p) > max_age_s:
                    doomed.append(p)
            except OSError:
                pass
    for p in doomed:
        try:
            os.unlink(p)
        except OSError:
            pass


def latest_valid_checkpoint(path: str, hM, *,
                            allow_legacy_pickle: bool = False) -> LoadedCheckpoint:
    """Newest checkpoint that loads cleanly; corrupt slots are skipped with
    a warning (falling back to the previous rotation slot).  A spec mismatch
    is raised immediately — every slot would mismatch the same way."""
    cands = checkpoint_files(path)
    if not cands:
        raise CheckpointError(f"no checkpoints found under {path!r}")
    failures = []
    for p in cands:
        try:
            return load_checkpoint_full(
                p, hM, allow_legacy_pickle=allow_legacy_pickle)
        except CheckpointCorruptError as e:
            warnings.warn(
                f"skipping corrupt checkpoint {p} ({e}); falling back to "
                "the previous rotation slot", RuntimeWarning, stacklevel=2)
            failures.append(f"{p}: {e}")
    raise CheckpointError(
        "every candidate checkpoint failed to load:\n  "
        + "\n  ".join(failures))


# ---------------------------------------------------------------------------
# resume / concat
# ---------------------------------------------------------------------------

def _bounded_align(post, max_passes: int = 5) -> None:
    from ..post.align import align_posterior
    for _ in range(max_passes):
        if align_posterior(post) == 0:
            break


def resume_run(hM, checkpoint_path: str, *, verbose: int = 0,
               progress_callback=None, extra_samples: int = 0,
               checkpoint_every: int | None = None,
               checkpoint_keep: int | None = None,
               checkpoint_max_age_s: float | None = None,
               checkpoint_archive_every: int | None = None,
               allow_legacy_pickle: bool = False, mesh=None,
               chain_axis: str = "chains", species_axis: str = "species",
               pipeline: bool = True):
    """Continue an auto-checkpointed ``sample_mcmc`` run to completion.

    Locates the newest valid checkpoint under ``checkpoint_path`` (corrupt
    slots fall back to the previous rotation slot), restores the carry state
    *and the carried RNG keys*, and samples the remaining draws with the
    stored run configuration — so the concatenated posterior is bit-identical
    to the uninterrupted run.  A burn-in snapshot (``ckpt-t<sweep>.npz``)
    resumes mid-transient: the remaining burn-in runs first, then sampling.
    The continuation keeps auto-checkpointing into the same directory, so
    repeated kill → resume cycles compose.  A run that already completed
    returns its posterior without sampling; ``extra_samples`` extends the
    target beyond the original total.

    Overrides: ``verbose`` and ``checkpoint_every`` may differ from the
    stored run configuration — both only re-segment the host loop, and the
    carried per-chain key makes the draw stream segmentation-invariant, so
    neither can change a single draw (asserted by the pipeline test suite).
    The rotation knobs (``checkpoint_keep`` / ``checkpoint_max_age_s`` /
    ``checkpoint_archive_every``) are likewise overridable — they only
    manage files on disk.  Parameters that *would* change the stream (seed,
    thin, updaters, RNG impl, record selection) are deliberately not
    overridable and always come from the checkpoint.  A device ``mesh`` is not serializable, so a
    sharded run passes its (possibly different) mesh back in via
    ``mesh=``/``chain_axis=``/``species_axis=``."""
    import jax.numpy as jnp

    ck = latest_valid_checkpoint(checkpoint_path, hM,
                                 allow_legacy_pickle=allow_legacy_pickle)
    meta = dict(ck.run_meta)
    if not meta:
        raise CheckpointError(
            f"{ck.path}: no run metadata in this checkpoint (it was written "
            "by save_checkpoint, not by sample_mcmc auto-checkpointing) — "
            "continue it manually via sample_mcmc(init_state=...)")
    if checkpoint_every is None:
        ck_every = int(meta.get("checkpoint_every", 0))
    else:
        ck_every = int(checkpoint_every)
        if ck_every < 0:
            raise ValueError(
                f"checkpoint_every override must be >= 0, got {ck_every}")

    total = int(meta["samples_total"]) + int(extra_samples)
    done = int(meta["samples_done"])
    align = bool(meta.get("align_post", True))
    if total <= done:
        out = ck.post
        if align and out.spec.nr > 0:
            _bounded_align(out)
        return out

    # a burn-in snapshot carries no draws: finish the remaining transient
    # first, then sample everything; the continuation has no base segment
    t_done = int(meta.get("transient_done", 0))
    remaining_t = (max(0, int(meta["transient"]) - t_done)
                   if done == 0 and t_done else 0)
    base = ck.post if ck.post.arrays else None

    rd = meta.get("record_dtype")
    record = meta.get("record")
    ckdir = (os.fspath(checkpoint_path) if os.path.isdir(checkpoint_path)
             else (os.path.dirname(ck.path) or "."))
    from ..mcmc.sampler import sample_mcmc
    cont = sample_mcmc(
        hM, samples=total - done, transient=remaining_t,
        thin=int(meta["thin"]),
        n_chains=ck.post.n_chains, seed=meta.get("seed"),
        init_state=ck.state, init_keys=ck.keys,
        # the original (resolved) adaptation window: its gate is on the
        # carried iteration counter, so it is a no-op here — but matching it
        # lets the continuation reuse the original run's compiled program
        adapt_nf=meta.get("adapt_nf"),
        nf_cap=int(meta["nf_cap"]), updater=meta.get("updater"),
        # model data must be rebuilt at the original precision, or an f64
        # run would continue against f32 data (init_par/data_par are not
        # serializable and so not restored; they only affect retry restarts)
        dtype=getattr(jnp, meta.get("dtype", "float32")),
        record=tuple(record) if record else None,
        record_dtype=None if rd is None else getattr(jnp, rd),
        rng_impl=meta.get("rng_impl"),
        retry_diverged=int(meta.get("retry_diverged", 0)),
        align_post=False, verbose=verbose, mesh=mesh,
        chain_axis=chain_axis, species_axis=species_axis,
        progress_callback=progress_callback,
        checkpoint_every=ck_every,
        checkpoint_path=ckdir,
        checkpoint_keep=int(meta.get("checkpoint_keep", 3)
                            if checkpoint_keep is None else checkpoint_keep),
        checkpoint_max_age_s=(meta.get("checkpoint_max_age_s")
                              if checkpoint_max_age_s is None
                              else checkpoint_max_age_s),
        checkpoint_archive_every=int(
            (meta.get("checkpoint_archive_every", 0) or 0)
            if checkpoint_archive_every is None else checkpoint_archive_every),
        pipeline=pipeline,
        _ckpt_base=base, _transient_base=t_done if base is None else 0)
    if base is None:
        out = cont
    else:
        out = concat_posteriors(base, cont, align=False)
    if align and out.spec.nr > 0:
        _bounded_align(out)
    return out


def concat_posteriors(first, second, *, align: bool = True,
                      max_align_passes: int = 5):
    """Splice two sampling segments of the same model: the recorded-sample
    axis is concatenated per parameter.  Validates that the segments are
    actually compatible — chain counts, parameter keys, per-parameter
    shapes and the ``thin`` stride — naming the offending key on mismatch."""
    if first.n_chains != second.n_chains:
        raise ValueError(
            f"concat_posteriors: chain counts differ "
            f"({first.n_chains} vs {second.n_chains})")
    only_a = sorted(set(first.arrays) - set(second.arrays))
    only_b = sorted(set(second.arrays) - set(first.arrays))
    if only_a or only_b:
        raise ValueError(
            "concat_posteriors: recorded parameter sets differ — "
            f"only in first: {only_a}; only in second: {only_b} "
            "(were the segments run with different record= selections?)")
    for k, v in first.arrays.items():
        w = second.arrays[k]
        if v.shape[2:] != w.shape[2:]:
            raise ValueError(
                f"concat_posteriors: parameter {k!r} has incompatible "
                f"shapes {v.shape} vs {w.shape} (differs beyond the "
                "(chains, samples) axes) — the segments come from "
                "different model configurations")
    if first.thin != second.thin:
        raise ValueError(
            f"concat_posteriors: thin strides differ ({first.thin} vs "
            f"{second.thin}) — the spliced sample axis would not be a "
            "single MCMC stride")
    if second.transient not in (0, first.transient):
        raise ValueError(
            f"concat_posteriors: second segment carries transient="
            f"{second.transient}; expected 0 (a continuation) or "
            f"{first.transient} (an independent replicate)")

    arrays = {k: np.concatenate([first.arrays[k], second.arrays[k]], axis=1)
              for k in first.arrays}
    from ..post.posterior import Posterior

    out = Posterior(first.hM, first.spec, arrays,
                    samples=first.samples + second.samples,
                    transient=first.transient, thin=first.thin)
    fb1 = np.asarray(first.chain_health["first_bad_it"])
    fb2 = np.asarray(second.chain_health["first_bad_it"])
    out.set_chain_health(np.where(fb1 >= 0, fb1, fb2))
    out.nf_saturation = {
        r: np.maximum(np.asarray(first.nf_saturation[r]),
                      np.asarray(second.nf_saturation[r]))
        if r in first.nf_saturation and r in second.nf_saturation
        else np.asarray(first.nf_saturation.get(r,
                        second.nf_saturation.get(r)))
        for r in set(first.nf_saturation) | set(second.nf_saturation)}
    # segments may have been sign-aligned against their own posterior-mean
    # Lambda; re-align per (chain, sample) over the spliced window so factor
    # signs are consistent across segments (bounded: stop once a pass makes
    # no flips instead of the former blind 5 iterations)
    if align and first.spec.nr > 0:
        _bounded_align(out, max_align_passes)
    return out
