"""Checkpoint / resume for long MCMC runs (SURVEY.md §5: the reference has no
in-process fault tolerance — a killed ``sampleMcmc`` loses everything; its
idiom is R serialization of the fitted object plus ``initPar`` warm starts).

Format v2 (this module): one ``.npz`` holding the recorded posterior arrays
(``post:<name>``), the chain carry-state leaves keyed by *structural name*
(``state:levels.0.Eta``), optionally the carried per-chain RNG keys, and a
JSON header with per-payload crc32 checksums plus a model-spec fingerprint.
Nothing is pickled: the state pytree structure is re-derived from
``build_spec(hM)`` at load time, so a checkpoint survives any environment
that can rebuild the model.  Writes are atomic (tmp + rename) and
``sample_mcmc(checkpoint_every=..., checkpoint_path=...)`` rotates the last
K snapshots, so a kill at any instant leaves a loadable file behind.

``load_checkpoint`` + ``sample_mcmc(init_state=...)`` continues the chains
bit-exactly where they left off; when the checkpoint also carries the RNG
keys (auto-checkpoints always do), ``resume_run`` continues the *key stream*
too, making kill → resume produce draws bit-identical to an uninterrupted
run.  Corruption (flipped bytes, truncation) is detected via the checksums
and rejected with :class:`CheckpointCorruptError`; ``resume_run`` then falls
back to the previous rotation slot.  Legacy v1 files (pickled metadata) are
readable only behind an explicit ``allow_legacy_pickle=True``.

Append-only run layout (manifest v1, the auto-checkpoint default): instead
of re-serialising the full draw history into every rotating snapshot (O(S²)
total bytes over a long run), each flushed sample segment becomes an
immutable ``seg-<proc>-<first>-<last>.npz`` shard written exactly once, a
snapshot is a small ``state-<n>.npz`` (carry leaves + RNG key data) plus a
``manifest-<n>.json`` listing the shard sequence with per-payload crc32
checksums — the atomic manifest rename is the commit point, so per-snapshot
cost is O(segment), flat in run length.  ``load_manifest_checkpoint``
assembles the posterior from the manifest (eagerly verified by default, or
as a lazily-materialised memory-mapped view via ``mmap=True``);
``latest_valid_checkpoint`` treats a corrupt shard like a corrupt rotating
slot and falls back to the newest manifest whose shard prefix is intact.
Rotation is manifest-driven (``gc_checkpoints``): manifests rotate by
count / age / total-bytes budget, and shards or state files referenced by
no surviving manifest are garbage-collected.  The per-process shard index
in the file name is the designed-for basis of the multi-host checkpoint
story (one shard stream per process + a coordinated manifest).  The legacy
self-contained ``ckpt-<n>.npz`` format stays fully readable (and writable
via ``sample_mcmc(checkpoint_layout="rotating")``) alongside.

Epochs (streaming refits, :mod:`hmsc_tpu.refit`): a run directory may grow
``epoch-<k>/`` subdirectories, each holding one refit's own append-only
layout (shards + state files + manifests for the *appended* dataset).  The
run root is epoch 0 — an old single-epoch directory reads as epoch 0 with
no migration, and a fresh run writes nothing epoch-related, so the default
single-epoch layout stays byte-identical to the pre-epoch format.  The
``epochs.json`` registry at the run root is the epoch COMMIT point: it is
rewritten atomically after an epoch's final manifest is durable, so a
reader that resolves epochs through the registry
(:func:`read_epoch_registry` / ``serve.artifact.resolve_run_epoch``) can
never observe a half-written epoch.  Committed epochs are immutable and
GC-pinned: :func:`gc_checkpoints` refuses to reclaim any file a surviving
epoch's manifest references unless that epoch is explicitly unpinned via
``pin_epochs=``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import time
import warnings
import zipfile
import zlib
from typing import Any

import numpy as np

from ..obs.events import RunTelemetry

__all__ = [
    "save_checkpoint", "load_checkpoint", "load_checkpoint_full",
    "concat_posteriors", "resume_run", "checkpoint_files",
    "rotate_checkpoints", "gc_checkpoints", "latest_valid_checkpoint",
    "spec_fingerprint", "save_shard", "save_state_file", "save_manifest",
    "load_manifest", "load_manifest_checkpoint", "ShardBackedArrays",
    "ChunkedShardView", "CheckpointWriter",
    "CheckpointError", "CheckpointCorruptError",
    "CheckpointSpecMismatchError", "PreemptedRun", "LoadedCheckpoint",
    "CKPT_VERSION", "MANIFEST_VERSION",
    "EPOCHS_FILE", "EPOCHS_VERSION", "epoch_dir_path", "read_epoch_registry",
    "write_epoch_registry", "committed_epochs",
]

CKPT_VERSION = 2
# manifest v1: single-process (one state file, one contiguous shard stream);
# v2 adds the multi-process fields ("process_count", "states", per-window
# shard groups).  Single-process runs keep WRITING v1 so their snapshots
# stay readable by older packages; v2 is stamped only when the run actually
# spans processes.
MANIFEST_VERSION = 2
_HEADER_KEY = "__hmsc_ckpt_header__"
# ckpt-<samples>.npz: sample snapshot; ckpt-t<sweep>.npz: state-only burn-in
# snapshot (no draws yet — always older than any sample snapshot)
_CKPT_RE = re.compile(r"ckpt-(t?)(\d+)\.npz")
# append-only layout: the manifest is the commit point; shards and state
# files are only ever reached through a manifest that references them
_MANIFEST_RE = re.compile(r"manifest-(t?)(\d+)\.json")
_SHARD_RE = re.compile(r"seg-(\d+)-(\d+)-(\d+)(?:-r(\d+))?\.npz")
# state-<tag>.npz: single-process carry; state-<tag>-p<proc>.npz: one
# process's chain-slice carry on a multi-process mesh
_STATE_RE = re.compile(r"state-(t?)(\d+)(?:-p(\d+))?\.npz")
# streaming refits: epoch-<k>/ subdirectories each hold one refit's own
# append-only layout; the run root is epoch 0 and epochs.json at the root
# is the atomic epoch-commit registry
EPOCHS_FILE = "epochs.json"
EPOCHS_VERSION = 1
_EPOCH_DIR_RE = re.compile(r"epoch-(\d+)")


# ---------------------------------------------------------------------------
# epoch registry (streaming refits)
# ---------------------------------------------------------------------------

def epoch_dir_path(run_dir: str, epoch: int) -> str:
    """An epoch's layout directory: the run root for epoch 0 (old
    single-epoch directories read as epoch 0 unchanged), ``epoch-<k>/``
    for refit epochs."""
    run_dir = os.fspath(run_dir)
    k = int(epoch)
    if k < 0:
        raise ValueError(f"epoch must be >= 0, got {k}")
    return run_dir if k == 0 else os.path.join(run_dir, f"epoch-{k}")


def read_epoch_registry(run_dir: str) -> dict | None:
    """The parsed ``epochs.json`` registry, or ``None`` for a single-epoch
    (pre-refit) run directory.  A malformed registry raises
    :class:`CheckpointCorruptError` — it is the epoch commit point, so a
    torn registry must never be silently read as "no epochs"."""
    path = os.path.join(os.fspath(run_dir), EPOCHS_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            reg = json.loads(f.read().decode())
    except (OSError, ValueError, UnicodeDecodeError) as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable epoch registry "
            f"({type(e).__name__}: {e})") from e
    if (not isinstance(reg, dict)
            or reg.get("format") != "hmsc_tpu-epochs"
            or not isinstance(reg.get("epochs"), list)):
        raise CheckpointCorruptError(f"{path}: not an hmsc_tpu epoch "
                                     "registry")
    if int(reg.get("version", 1)) > EPOCHS_VERSION:
        raise CheckpointError(
            f"{path}: epoch registry version {reg['version']} is newer "
            f"than this package reads (<= {EPOCHS_VERSION}) — upgrade "
            "hmsc_tpu")
    for e in reg["epochs"]:
        if not isinstance(e, dict) or "epoch" not in e:
            raise CheckpointCorruptError(
                f"{path}: malformed epoch entry — corrupt registry")
    return reg


def write_epoch_registry(run_dir: str, registry: dict) -> str:
    """Atomically (re)write the epoch registry — the refit commit point: a
    kill before the rename leaves the previous registry (and every epoch it
    lists) fully intact."""
    registry = dict(registry)
    registry["format"] = "hmsc_tpu-epochs"
    registry["version"] = EPOCHS_VERSION
    registry["epochs"] = sorted(
        (dict(e) for e in registry.get("epochs", [])),
        key=lambda e: int(e["epoch"]))
    path = os.path.join(os.fspath(run_dir), EPOCHS_FILE)
    _atomic_write_bytes(path, json.dumps(registry, sort_keys=True).encode())
    return path


def committed_epochs(run_dir: str) -> list[int]:
    """Committed epoch indices for a run directory, oldest first.  A
    registry-less directory is the single-epoch case: ``[0]`` when it holds
    any resume candidate, else ``[]``."""
    reg = read_epoch_registry(run_dir)
    if reg is None:
        return [0] if checkpoint_files(run_dir) else []
    return sorted(int(e["epoch"]) for e in reg["epochs"])


class CheckpointError(RuntimeError):
    """Base class for checkpoint load/save failures."""


class CheckpointCorruptError(CheckpointError):
    """The file is unreadable or a payload failed its integrity checksum."""


class CheckpointSpecMismatchError(CheckpointError):
    """The checkpoint was written for a different model specification."""


class PreemptedRun(RuntimeError):
    """Raised by ``sample_mcmc`` when SIGTERM/SIGINT arrives during an
    auto-checkpointing run: the in-flight segment is finished, a resumable
    snapshot is written, and the run unwinds with this error.  Continue with
    ``resume_run`` (or ``python -m hmsc_tpu run --resume``)."""

    def __init__(self, msg, checkpoint_path=None, samples_done=0, signum=None):
        super().__init__(msg)
        self.checkpoint_path = checkpoint_path
        self.samples_done = samples_done
        self.signum = signum


@dataclasses.dataclass
class LoadedCheckpoint:
    """Everything a checkpoint carries: the partial posterior, the chain
    carry state, optionally the carried RNG keys, the sampler's run metadata
    (empty for manual ``save_checkpoint`` files), and the parsed header."""
    post: Any
    state: Any
    keys: Any
    run_meta: dict
    header: dict
    path: str


# ---------------------------------------------------------------------------
# structural (pickle-free) state layout
# ---------------------------------------------------------------------------

def _state_skeleton(spec):
    """(leaf names, treedef) of the carry state, derived purely from the
    model spec: a GibbsState whose leaves are their own names has the same
    pytree structure as the real state (every field is a leaf), so the
    flatten order gives a stable name per saved array — no pickled treedef."""
    import jax

    from ..mcmc.structs import GibbsState, LevelState

    def lvl(r):
        return LevelState(
            Eta=f"levels.{r}.Eta", Lambda=f"levels.{r}.Lambda",
            Psi=f"levels.{r}.Psi", Delta=f"levels.{r}.Delta",
            alpha_idx=f"levels.{r}.alpha_idx", nf_mask=f"levels.{r}.nf_mask",
            nf_sat=f"levels.{r}.nf_sat")

    skel = GibbsState(
        Z="Z", Beta="Beta", Gamma="Gamma", iV="iV", rho_idx="rho_idx",
        iSigma="iSigma", levels=tuple(lvl(r) for r in range(spec.nr)),
        it="it", BetaSel=tuple(f"BetaSel.{i}" for i in range(spec.ncsel)),
        wRRR="wRRR", PsiRRR="PsiRRR", DeltaRRR="DeltaRRR")
    names, treedef = jax.tree_util.tree_flatten(skel)
    return list(names), treedef


def _effective_nf_cap(spec) -> int:
    """The smallest nf_cap that rebuilds this spec via ``build_spec``: every
    level's nf_max is min(prior bound, ns, cap), so the max over levels
    reconstructs each level exactly (a capped level stores the cap itself)."""
    from ..mcmc.structs import DEFAULT_NF_CAP
    return max((ls.nf_max for ls in spec.levels), default=DEFAULT_NF_CAP)


def spec_fingerprint(spec) -> str:
    """sha256 of the (frozen, primitive-valued) ModelSpec repr — changes
    whenever the model structure or the package's spec layout changes."""
    return hashlib.sha256(repr(spec).encode()).hexdigest()


def _crc(a) -> str:
    # checksum over the buffer in place: .tobytes() would materialise a
    # second full copy of every payload on the writer thread per snapshot
    buf = memoryview(np.ascontiguousarray(a)).cast("B")
    return f"{zlib.crc32(buf) & 0xFFFFFFFF:08x}"


def _fsync_dir(path: str) -> None:
    """fsync the containing directory so a completed rename is durable —
    the background writer's barrier relies on a completed write meaning
    "survives power loss", not just "visible to this process"."""
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass                   # directory fsync unsupported (non-POSIX)


def _atomic_write(path: str, write_cb, fsync_dir: bool = True) -> None:
    """The atomic durable-write protocol, shared by every on-disk artifact:
    serialise into a tmp file via ``write_cb(fileobj)``, fsync the content,
    rename over the final name, optionally fsync the directory — a kill at
    any instant leaves either the old file or the new one, never a torn
    mix.

    ``fsync_dir=False`` skips the directory fsync: append-layout shard and
    state writes precede a manifest commit in the SAME directory, and the
    manifest's directory fsync durably publishes all three dirents at once
    (measured: each directory fsync costs about as much as the data write
    at segment scale — one per snapshot instead of three keeps the
    per-snapshot cost flat).  A crash before the manifest's fsync loses at
    worst an uncommitted orphan, which resume regenerates."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_cb(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync_dir:
            _fsync_dir(path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _atomic_savez(path: str, payload: dict, compress: bool = False,
                  fsync_dir: bool = True) -> None:
    """Atomic durable ``.npz`` write (see :func:`_atomic_write`).

    Uncompressed by default: posterior draws are high-entropy f32 (measured
    ~13% size reduction for ~7x the serialisation CPU), and checkpoint
    serialisation rides the sampler's background writer thread — cheap
    writes keep it off the compute cores the XLA CPU backend shares.  Pass
    ``compress=True`` for cold archival copies; ``np.load`` reads both.
    (Uncompressed members are also what makes the shard mmap view possible —
    a deflated member cannot be memory-mapped.)"""
    savez = np.savez_compressed if compress else np.savez
    _atomic_write(path, lambda f: savez(f, **payload), fsync_dir=fsync_dir)


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """Atomic durable write of raw bytes (the manifest commit point)."""
    _atomic_write(path, lambda f: f.write(data))


# ---------------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------------

def save_checkpoint(path: str, post, state, *, keys=None, keys_impl=None,
                    run_meta: dict | None = None,
                    compress: bool = False) -> None:
    """Write a resumable snapshot: the Posterior so far + the carry state
    from ``sample_mcmc(..., return_state=True)``.

    ``keys``/``keys_impl`` optionally persist the carried per-chain RNG keys
    so a continuation replays the exact key stream — auto-checkpoints always
    pass them.  ``keys`` may be ``jax.random`` typed keys or the raw uint32
    key-data array (the sampler's background writer snapshots key data, not
    typed keys, before the carry is donated to the next segment).
    ``run_meta`` is an arbitrary JSON-serializable dict stored in the header
    (``resume_run`` reads the sampler's run configuration from it)."""
    import jax

    path = os.fspath(path)
    names, skel_def = _state_skeleton(post.spec)
    leaves, state_def = jax.tree_util.tree_flatten(state)
    if state_def != skel_def:
        raise CheckpointError(
            "carry state structure does not match the layout derived from "
            "the model spec (GibbsState fields changed without updating "
            "checkpoint._state_skeleton?) — refusing to write an "
            "unloadable checkpoint")

    payload = {f"post:{k}": np.asarray(v) for k, v in post.arrays.items()}
    payload.update({f"state:{n}": np.asarray(x)
                    for n, x in zip(names, leaves)})
    if keys is not None:
        if keys_impl is None:
            raise ValueError("save_checkpoint: keys requires keys_impl "
                             "(the PRNG impl name, e.g. 'threefry2x32')")
        kd = keys
        if hasattr(keys, "dtype") and jax.dtypes.issubdtype(
                keys.dtype, jax.dtypes.prng_key):
            kd = jax.random.key_data(keys)
        payload["rngkeys"] = np.asarray(kd)

    import hmsc_tpu as _pkg
    header = {
        "format": "hmsc_tpu-checkpoint",
        "version": CKPT_VERSION,
        "package_version": _pkg.__version__,
        "samples": int(post.samples),
        "transient": int(post.transient),
        "thin": int(post.thin),
        "n_chains": int(post.n_chains),
        "nf_cap": int(_effective_nf_cap(post.spec)),
        "spec_sha256": spec_fingerprint(post.spec),
        "keys_impl": keys_impl,
        "first_bad_it": [int(x) for x in post.chain_health["first_bad_it"]],
        "nf_saturation": {str(r): np.asarray(v).tolist()
                          for r, v in post.nf_saturation.items()},
        "checksums": {k: _crc(v) for k, v in payload.items()},
        "run": run_meta or {},
    }
    payload[_HEADER_KEY] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    _atomic_savez(path, payload, compress=compress)


def load_checkpoint_full(path: str, hM, *,
                         allow_legacy_pickle: bool = False) -> LoadedCheckpoint:
    """Load a checkpoint with full metadata (see :class:`LoadedCheckpoint`).

    Raises :class:`CheckpointCorruptError` on unreadable/byte-flipped files
    (every payload is checksummed) and :class:`CheckpointSpecMismatchError`
    when the file was written for a different model spec."""
    import jax
    import jax.numpy as jnp

    from ..mcmc.structs import build_spec
    from ..post.posterior import Posterior

    path = os.fspath(path)
    if path.endswith(".json"):            # append-only layout manifest
        return load_manifest_checkpoint(path, hM)
    try:
        with np.load(path, allow_pickle=False) as z:
            files = set(z.files)
            if _HEADER_KEY not in files:
                if "meta" in files:
                    return _load_legacy_v1(z, hM, path, allow_legacy_pickle)
                raise CheckpointCorruptError(
                    f"{path}: not an hmsc_tpu checkpoint (no v2 header and "
                    "no legacy v1 metadata)")
            header = json.loads(z[_HEADER_KEY].tobytes().decode())

            # materialise each payload exactly once (NpzFile re-inflates the
            # zip member on every access — verifying from z[k] and then
            # loading z[k] again would decompress a multi-GB checkpoint
            # twice), then verify against the header's checksums
            data = {k: z[k] for k in files if k != _HEADER_KEY}
            for k, want in header.get("checksums", {}).items():
                if k not in data:
                    raise CheckpointCorruptError(
                        f"{path}: payload {k!r} is missing — the file is "
                        "truncated or corrupt")
                got = _crc(data[k])
                if got != want:
                    raise CheckpointCorruptError(
                        f"{path}: payload {k!r} failed its integrity "
                        f"checksum (crc32 {got} != {want}) — the file is "
                        "corrupt; fall back to an earlier rotation slot")

            spec = build_spec(hM, int(header["nf_cap"]))
            got_fp = spec_fingerprint(spec)
            if got_fp != header["spec_sha256"]:
                raise CheckpointSpecMismatchError(
                    f"{path}: model spec fingerprint mismatch "
                    f"({got_fp[:12]}… != {header['spec_sha256'][:12]}…) — "
                    "the checkpoint was written for a different model "
                    "(data shapes, levels, priors) or a different "
                    "hmsc_tpu spec layout; rebuild the matching Hmsc "
                    "object to resume")

            names, treedef = _state_skeleton(spec)
            missing = [n for n in names if f"state:{n}" not in data]
            if missing:
                raise CheckpointCorruptError(
                    f"{path}: carry-state leaves missing: {missing}")
            state = jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(data[f"state:{n}"]) for n in names])

            arrays = {k[5:]: v for k, v in data.items()
                      if k.startswith("post:")}
            keys = None
            if "rngkeys" in data and header.get("keys_impl"):
                keys = jax.random.wrap_key_data(
                    jnp.asarray(data["rngkeys"]), impl=header["keys_impl"])
    except CheckpointError:
        raise
    except (zipfile.BadZipFile, zlib.error, OSError, ValueError, KeyError,
            EOFError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable checkpoint ({type(e).__name__}: {e}) — "
            "the file is corrupt or truncated") from e

    post = Posterior(hM, spec, arrays, samples=int(header["samples"]),
                     transient=int(header["transient"]),
                     thin=int(header["thin"]))
    if not post.arrays:
        # state-only burn-in snapshot: no recorded arrays to derive the
        # chain count from — restore it from the header
        post.n_chains = int(header.get("n_chains", 0))
    if "first_bad_it" in header:
        post.set_chain_health(np.asarray(header["first_bad_it"]))
    post.nf_saturation = {int(r): np.asarray(v)
                          for r, v in header.get("nf_saturation", {}).items()}
    return LoadedCheckpoint(post=post, state=state, keys=keys,
                            run_meta=dict(header.get("run", {})),
                            header=header, path=path)


def _load_legacy_v1(z, hM, path, allow_legacy_pickle) -> LoadedCheckpoint:
    """Guarded read path for pre-v2 files: the run metadata is a python
    pickle, so it is only decoded behind an explicit opt-in.  The state
    structure itself is rebuilt from the spec (the v1 leaves ``state:<i>``
    are in the same flatten order), so the pickled treedef is never used."""
    if not allow_legacy_pickle:
        raise CheckpointError(
            f"{path}: legacy v1 checkpoint whose metadata is a python "
            "pickle; refusing to unpickle by default.  Pass "
            "allow_legacy_pickle=True only if you trust the file's origin "
            "(or re-save it in the v2 format via save_checkpoint)")
    import pickle

    import jax.numpy as jnp
    from jax.tree_util import tree_unflatten

    from ..mcmc.structs import build_spec
    from ..post.posterior import Posterior

    meta = pickle.loads(z["meta"].tobytes())
    arrays = {k[5:]: z[k] for k in z.files if k.startswith("post:")}
    n_state = sum(1 for k in z.files if k.startswith("state:"))
    leaves = [jnp.asarray(z[f"state:{i}"]) for i in range(n_state)]
    spec = build_spec(hM)
    names, treedef = _state_skeleton(spec)
    if len(leaves) != len(names):
        raise CheckpointCorruptError(
            f"{path}: legacy checkpoint carries {len(leaves)} state leaves, "
            f"the model spec implies {len(names)}")
    state = tree_unflatten(treedef, leaves)
    post = Posterior(hM, spec, arrays, samples=meta["samples"],
                     transient=meta["transient"], thin=meta["thin"])
    return LoadedCheckpoint(post=post, state=state, keys=None, run_meta={},
                            header={"version": 1}, path=path)


def load_checkpoint(path: str, hM, *, allow_legacy_pickle: bool = False):
    """Returns (Posterior, carry_state) ready for
    ``sample_mcmc(hM, ..., init_state=carry_state)`` — see
    :func:`load_checkpoint_full` for the RNG keys and run metadata.
    Accepts both a self-contained ``.npz`` checkpoint and an append-only
    ``manifest-<n>.json``."""
    ck = load_checkpoint_full(path, hM, allow_legacy_pickle=allow_legacy_pickle)
    return ck.post, ck.state


# ---------------------------------------------------------------------------
# append-only run layout: shards + state files + manifests
# ---------------------------------------------------------------------------

def save_shard(dirpath: str, arrays: dict, first: int, last: int, *,
               shard_index: int = 0, repair: int = 0,
               compress: bool = False) -> dict:
    """Write one immutable posterior shard covering the recorded-sample
    window ``[first, last]`` (inclusive, global indices) and return its
    manifest entry (file name, window, per-payload crc32 checksums, size).

    ``shard_index`` is the writing process's slot (``jax.process_index()``
    on a multi-host mesh; 0 single-host) — each process appends its own
    shard stream, which is what the coordinated multi-host manifest will
    stitch together.  ``repair`` disambiguates a re-written window (the
    ``retry_diverged`` splice re-writes the tail of a completed run): shard
    files are immutable, so a repaired window gets a NEW file name and the
    superseded shard is garbage-collected once no manifest references it."""
    if last < first:
        raise ValueError(f"save_shard: empty window [{first}, {last}]")
    rep = f"-r{int(repair)}" if repair else ""
    fname = f"seg-{int(shard_index)}-{first:08d}-{last:08d}{rep}.npz"
    payload = {f"post:{k}": np.ascontiguousarray(v) for k, v in arrays.items()}
    if not payload:
        raise ValueError("save_shard: no arrays to write")
    n = next(iter(payload.values())).shape[1]
    if n != last - first + 1:
        raise ValueError(
            f"save_shard: arrays carry {n} samples for window "
            f"[{first}, {last}] ({last - first + 1} wide)")
    checks = {k: _crc(v) for k, v in payload.items()}
    path = os.path.join(dirpath, fname)
    # content fsync only: the manifest commit fsyncs the shared directory
    _atomic_savez(path, payload, compress=compress, fsync_dir=False)
    return {"file": fname, "first": int(first), "last": int(last),
            "proc": int(shard_index),
            "chains": int(next(iter(payload.values())).shape[0]),
            "nbytes": int(os.path.getsize(path)), "checksums": checks}


def save_state_file(dirpath: str, tag: str, spec, state, *,
                    keys_data=None, proc: int | None = None,
                    compress: bool = False) -> dict:
    """Write the O(state) part of an append-only snapshot: the carry leaves
    (structurally named, like format v2) plus the raw RNG key data.  Returns
    the manifest entry (file name, checksums, size).  ``tag`` is the
    snapshot tag (``"00000008"`` for 8 recorded samples, ``"t00000004"`` for
    a burn-in snapshot at sweep 4).  ``proc`` names the writing process on a
    multi-process mesh (``state-<tag>-p<proc>.npz``, one chain-slice carry
    per process); ``None`` keeps the single-process ``state-<tag>.npz``."""
    import jax

    names, skel_def = _state_skeleton(spec)
    leaves, state_def = jax.tree_util.tree_flatten(state)
    if state_def != skel_def:
        raise CheckpointError(
            "carry state structure does not match the layout derived from "
            "the model spec — refusing to write an unloadable snapshot")
    payload = {f"state:{n}": np.asarray(x) for n, x in zip(names, leaves)}
    if keys_data is not None:
        payload["rngkeys"] = np.asarray(keys_data)
    checks = {k: _crc(v) for k, v in payload.items()}
    fname = (f"state-{tag}.npz" if proc is None
             else f"state-{tag}-p{int(proc)}.npz")
    path = os.path.join(dirpath, fname)
    # content fsync only: the manifest commit fsyncs the shared directory
    _atomic_savez(path, payload, compress=compress, fsync_dir=False)
    entry = {"file": fname, "checksums": checks,
             "nbytes": int(os.path.getsize(path))}
    if proc is not None:
        entry["proc"] = int(proc)
        # chain-slice extent so resume can re-shard under a different
        # process count without opening every state file first
        lead = [int(np.asarray(x).shape[0]) for x in leaves
                if np.asarray(x).ndim > 0]
        entry["chains"] = lead[0] if lead else 0
    return entry


def save_manifest(dirpath: str, tag: str, manifest: dict) -> str:
    """Atomically write ``manifest-<tag>.json`` — the snapshot's commit
    point: a kill before the rename leaves the previous manifest (and every
    file it references) fully intact, so the newest *visible* manifest is
    always consistent.  Single-process manifests are stamped format v1
    (readable by older packages); the multi-process fields (``states``,
    ``process_count``) bump the stamp to v2 so an old reader refuses
    cleanly instead of resuming from one process's chain slice."""
    manifest = dict(manifest)
    manifest["format"] = "hmsc_tpu-manifest"
    # v2 whenever the snapshot is structurally multi-process: per-process
    # state files, or a shard history whose windows stitch several streams
    # (a v1 reader's contiguity check would misread either as corruption)
    multi = ("states" in manifest
             or len({_shard_proc(s)
                     for s in manifest.get("shards", [])}) > 1)
    manifest["version"] = 2 if multi else 1
    path = os.path.join(dirpath, f"manifest-{tag}.json")
    _atomic_write_bytes(path, json.dumps(manifest, sort_keys=True).encode())
    return path


def load_manifest(path: str) -> dict:
    """Parse + structurally validate one manifest file (no payload reads).

    Every malformation — unreadable bytes, non-JSON, or JSON that parses
    but is missing/mistyping required fields (a flipped byte inside a key
    name still decodes as valid JSON) — raises
    :class:`CheckpointCorruptError`, so callers' corrupt-slot fallback
    catches it; a bare KeyError must never escape a corrupt manifest."""
    try:
        with open(path, "rb") as f:
            man = json.loads(f.read().decode())
    except (OSError, ValueError, UnicodeDecodeError) as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable manifest ({type(e).__name__}: {e})") from e
    if not isinstance(man, dict) or man.get("format") != "hmsc_tpu-manifest":
        raise CheckpointCorruptError(f"{path}: not an hmsc_tpu manifest")
    try:
        if int(man.get("version", 1)) > MANIFEST_VERSION:
            # raised as a plain CheckpointError (not Corrupt): every slot
            # of a future-format run mismatches the same way, so falling
            # back slot-by-slot would only bury the real message
            raise CheckpointError(
                f"{path}: manifest version {man['version']} is newer than "
                f"this package reads (<= {MANIFEST_VERSION}) — upgrade "
                "hmsc_tpu to resume this run")
        for key in ("samples", "transient", "thin", "n_chains", "nf_cap",
                    "spec_sha256", "state"):
            if key not in man:
                raise CheckpointCorruptError(
                    f"{path}: manifest is missing {key!r} — corrupt")
        for key in ("samples", "transient", "thin", "n_chains", "nf_cap"):
            int(man[key])          # mangled value -> ValueError -> corrupt
        if not isinstance(man["state"], dict) or "file" not in man["state"]:
            raise CheckpointCorruptError(
                f"{path}: manifest carries no state-file entry — corrupt")
        if "states" in man:
            states = man["states"]
            if (not isinstance(states, list) or not states
                    or any(not isinstance(s, dict) or "file" not in s
                           for s in states)):
                raise CheckpointCorruptError(
                    f"{path}: malformed per-process 'states' list — corrupt")
            chains = sum(int(s.get("chains", 0)) for s in states)
            if chains and chains != int(man["n_chains"]):
                raise CheckpointCorruptError(
                    f"{path}: per-process state files carry {chains} chains, "
                    f"manifest claims {man['n_chains']}")
        # the shard streams: windows along the sample axis must tile
        # [0, samples) contiguously; within a window one shard per writing
        # process, together covering every chain.  A single-process run is
        # the one-shard-per-window special case (the v1 layout).
        cursor = 0
        for (first, last), group in _group_shard_windows(
                man.get("shards", [])):
            if first != cursor:
                raise CheckpointCorruptError(
                    f"{path}: shard sequence is not contiguous — window "
                    f"[{first}, {last}] starts at {first}, expected "
                    f"{cursor}")
            cursor = last + 1
            chains = sum(int(s.get("chains", 0)) for s in group)
            if chains and chains != int(man["n_chains"]):
                raise CheckpointCorruptError(
                    f"{path}: shards for window [{first}, {last}] cover "
                    f"{chains} chains, manifest claims {man['n_chains']}")
        if cursor != int(man["samples"]):
            raise CheckpointCorruptError(
                f"{path}: shards cover {cursor} samples, manifest claims "
                f"{man.get('samples')}")
    except CheckpointError:
        raise
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{path}: structurally corrupt manifest "
            f"({type(e).__name__}: {e})") from e
    return man


def _shard_proc(entry: dict) -> int:
    """A shard entry's writing-process slot (explicit field, or parsed from
    the ``seg-<proc>-…`` file name for entries written before the field
    existed)."""
    if "proc" in entry:
        return int(entry["proc"])
    m = _SHARD_RE.fullmatch(entry.get("file", ""))
    return int(m.group(1)) if m else 0


def _group_shard_windows(shards: list) -> list:
    """Group a manifest's shard entries by their sample window: a sorted
    list of ``((first, last), [entries in process order])``.  Overlapping
    but non-identical windows (corruption, or streams from incompatible
    runs mixed into one directory) raise
    :class:`CheckpointCorruptError`."""
    wins: dict = {}
    for s in shards:
        wins.setdefault((int(s["first"]), int(s["last"])), []).append(s)
    out = sorted(wins.items())
    for (a, b), _ in out:
        if b < a:
            raise CheckpointCorruptError(
                f"shard window [{a}, {b}] is empty — corrupt manifest")
    for ((a1, b1), _), ((a2, _b2), _g2) in zip(out, out[1:]):
        if a2 <= b1:
            raise CheckpointCorruptError(
                f"shard windows [{a1}, {b1}] and starting at {a2} overlap "
                "without being identical — corrupt manifest")
    return [(w, sorted(g, key=_shard_proc)) for w, g in out]


def _npz_member_mmap(path: str, name: str):
    """Memory-map one member of an *uncompressed* ``.npz`` without copying.

    ``np.load(mmap_mode=...)`` silently ignores mmap for zipped archives, so
    the member's raw ``.npy`` bytes are located via the zip local header and
    mapped directly.  Returns ``None`` when the member is deflated or the
    layout is unexpected — callers fall back to a regular (copying) read."""
    import zipfile
    try:
        with zipfile.ZipFile(path) as zf:
            info = zf.getinfo(name + ".npy")
            if info.compress_type != zipfile.ZIP_STORED:
                return None
        with open(path, "rb") as f:
            f.seek(info.header_offset)
            hdr = f.read(30)
            if len(hdr) < 30 or hdr[:4] != b"PK\x03\x04":
                return None
            f.seek(info.header_offset + 30
                   + int.from_bytes(hdr[26:28], "little")
                   + int.from_bytes(hdr[28:30], "little"))
            version = np.lib.format.read_magic(f)
            shape, fortran, dtype = np.lib.format._read_array_header(f,
                                                                     version)
            if dtype.hasobject:
                return None
            return np.memmap(path, dtype=dtype, mode="r", offset=f.tell(),
                             shape=shape, order="F" if fortran else "C")
    except (KeyError, OSError, ValueError, zipfile.BadZipFile,
            AttributeError):
        return None


def _read_shard_member(path: str, key: str, entry: dict | None = None, *,
                       mmap: bool = False, verify: bool = True, npz=None):
    """One payload array out of a shard: mmap view when possible and asked
    for (unverified — the fast trusted path), else a verified read.  Pass
    an already-open ``npz`` (NpzFile) to amortise the archive open over
    many members of the same shard."""
    if mmap:
        a = _npz_member_mmap(path, key)
        if a is not None:
            return a
    try:
        if npz is not None:
            if key not in npz.files:
                raise CheckpointCorruptError(
                    f"{path}: payload {key!r} is missing — the shard is "
                    "truncated or corrupt")
            a = npz[key]
        else:
            with np.load(path, allow_pickle=False) as z:
                if key not in z.files:
                    raise CheckpointCorruptError(
                        f"{path}: payload {key!r} is missing — the shard "
                        "is truncated or corrupt")
                a = z[key]
    except CheckpointError:
        raise
    except (zipfile.BadZipFile, zlib.error, OSError, ValueError, KeyError,
            EOFError) as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable shard ({type(e).__name__}: {e})") from e
    if verify and entry is not None:
        want = entry.get("checksums", {}).get(key)
        if want is not None and _crc(a) != want:
            raise CheckpointCorruptError(
                f"{path}: payload {key!r} failed its integrity checksum — "
                "the shard is corrupt; resume falls back to the newest "
                "manifest whose shard prefix is intact")
    return a


class ChunkedShardView:
    """Zero-copy virtual concatenation of per-shard sample windows.

    A parameter that spans multiple shards used to be materialised by
    ``np.concatenate`` — one full host-RAM copy of that parameter's whole
    history, defeating the point of ``mmap=True`` on exactly the long runs
    with many shards.  This view keeps the per-shard (typically
    memory-mapped) chunks as-is and implements windowed ``__getitem__``
    over the sample axis: an access copies only the rows it touches, so
    ``post["Beta"][:, -100:]`` reads one shard's tail, not the run.

    Supported without full materialisation: basic indexing whose sample-axis
    component is an int or a slice (any step), with any basic/advanced
    index on the chain axis and basic indices beyond — i.e. every access
    pattern ``Posterior`` itself issues (``subset``, ``pooled``,
    ``post_list``).  Anything more exotic falls back to ``__array__``
    (one full copy, the old behaviour).  The view is read-only."""

    def __init__(self, chunks: list):
        if not chunks:
            raise ValueError("ChunkedShardView: no chunks")
        self._chunks = list(chunks)
        rest = chunks[0].shape[2:]
        if any(c.shape[0] != chunks[0].shape[0] or c.shape[2:] != rest
               for c in chunks):
            raise ValueError("ChunkedShardView: chunk shapes disagree "
                             "beyond the sample axis")
        self._offsets = np.cumsum([0] + [c.shape[1] for c in chunks])
        self.shape = (chunks[0].shape[0], int(self._offsets[-1]), *rest)
        self.dtype = chunks[0].dtype

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def nbytes(self):
        return int(np.prod(self.shape)) * self.dtype.itemsize

    def __len__(self):
        return self.shape[0]

    def __array__(self, dtype=None, copy=None):
        a = np.concatenate([np.asarray(c) for c in self._chunks], axis=1)
        return a.astype(dtype) if dtype is not None else a

    def reshape(self, *shape):
        return np.asarray(self).reshape(*shape)

    def copy(self):
        return np.asarray(self)

    def _chunk_slices(self, s: slice):
        """Per-chunk (index, local slice) list realising a global
        sample-axis slice (positive step; the caller normalises)."""
        start, stop, step = s.indices(self.shape[1])
        out = []
        for i, c in enumerate(self._chunks):
            o, n = int(self._offsets[i]), c.shape[1]
            if stop <= o or start >= o + n:
                continue
            lo = start if start >= o else start + step * (-(-(o - start) // step))
            if lo >= min(stop, o + n):
                continue
            out.append((i, slice(lo - o, min(stop, o + n) - o, step)))
        return out

    def __getitem__(self, key):
        if not isinstance(key, tuple):
            key = (key,)
        if any(k is Ellipsis or k is None for k in key):
            return np.asarray(self)[key]
        key = key + (slice(None),) * (2 - len(key)) if len(key) < 2 else key
        k0, k1, rest = key[0], key[1], tuple(key[2:])
        if isinstance(k1, (int, np.integer)):
            k1 = int(k1) + (self.shape[1] if k1 < 0 else 0)
            if not 0 <= k1 < self.shape[1]:
                raise IndexError(f"sample index {key[1]} out of range "
                                 f"for {self.shape[1]} samples")
            i = int(np.searchsorted(self._offsets, k1, side="right")) - 1
            return self._chunks[i][(k0, k1 - int(self._offsets[i])) + rest]
        if isinstance(k1, slice) and (k1.step or 1) > 0:
            parts = [self._chunks[i][(k0, ls) + rest]
                     for i, ls in self._chunk_slices(k1)]
            # ints before the sample axis collapse it one position left
            axis = 0 if isinstance(k0, (int, np.integer)) else 1
            if not parts:
                shape = list(self.shape)
                shape[1] = 0
                empty = np.empty(tuple(shape), self.dtype)
                return empty[(k0, slice(0, 0)) + rest]
            return (parts[0] if len(parts) == 1
                    else np.concatenate(parts, axis=axis))
        # negative-step slice or an advanced sample-axis index: materialise
        return np.asarray(self)[key]


class ShardBackedArrays:
    """Posterior arrays assembled lazily from a manifest's shard sequence.

    A MutableMapping drop-in for ``Posterior.arrays``: each parameter is
    materialised (and cached) only when first accessed, reading just that
    parameter's payload from each shard — so constructing a Posterior from a
    multi-GB manifest costs nothing, and a Beta-only workflow never loads
    Eta at all.  With ``mmap=True`` single-shard parameters come back as
    zero-copy ``np.memmap`` views and multi-shard parameters as a
    :class:`ChunkedShardView` over the per-shard maps (windowed access
    copies only what it touches — nothing concatenates the history);
    mmap views skip checksum verification (the fast trusted path — use the
    default eager load when integrity matters more than RAM).  Multi-process
    manifests stitch each sample window's per-process shards along the
    chain axis."""

    def __init__(self, dirpath: str, shards: list, *, mmap: bool = False,
                 verify: bool = True):
        self._dir = os.fspath(dirpath)
        self._windows = _group_shard_windows([dict(s) for s in shards])
        self._mmap = bool(mmap)
        self._verify = bool(verify)
        self._data = {}
        first = self._windows[0][1] if self._windows else []
        self._lazy = ([k[5:] for k in first[0].get("checksums", {})
                       if k.startswith("post:")] if first else [])
        # chain-count hint so Posterior need not materialise a parameter
        # just to read its leading axis
        self.chains = sum(int(s.get("chains", 0)) for s in first)

    def _read_window(self, group, key):
        parts = [_read_shard_member(os.path.join(self._dir, s["file"]),
                                    f"post:{key}", s, mmap=self._mmap,
                                    verify=self._verify)
                 for s in group]
        # one shard per window is the single-process case (zero-copy mmap);
        # a multi-process window stitches chain slices (one window's copy)
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)

    def __getitem__(self, key):
        if key in self._data:
            return self._data[key]
        if key not in self._lazy:
            raise KeyError(key)
        chunks = [self._read_window(g, key) for _, g in self._windows]
        a = chunks[0] if len(chunks) == 1 else ChunkedShardView(chunks)
        self._data[key] = a
        self._lazy.remove(key)       # materialised: exactly one home per key
        return a

    def __setitem__(self, key, value):
        self._data[key] = np.asarray(value)
        if key in self._lazy:
            self._lazy.remove(key)

    def __delitem__(self, key):
        found = key in self._data or key in self._lazy
        self._data.pop(key, None)
        if key in self._lazy:
            self._lazy.remove(key)
        if not found:
            raise KeyError(key)

    def __contains__(self, key):
        return key in self._data or key in self._lazy

    def __iter__(self):
        # snapshot: materialising a key mid-iteration (items()/values())
        # moves it from _lazy to _data, which must not shift the iterator
        yield from [*self._data, *self._lazy]

    def __len__(self):
        return len(self._data) + len(self._lazy)

    def keys(self):
        return list(self)

    def values(self):
        return (self[k] for k in self)

    def items(self):
        return ((k, self[k]) for k in self)

    def get(self, key, default=None):
        return self[key] if key in self else default

    def materialize(self) -> dict:
        """Force every parameter into a plain dict (one pass, cached)."""
        return {k: self[k] for k in self}


def load_manifest_checkpoint(path: str, hM, *, mmap: bool = False,
                             verify: bool = True) -> LoadedCheckpoint:
    """Load an append-only snapshot from its ``manifest-<n>.json``.

    The carry state (and its checksums) is always read eagerly — it is
    O(state), and a resume cannot start from an unverified carry.  The
    posterior is assembled from the shard sequence: eagerly with full
    checksum verification by default (a corrupt shard raises
    :class:`CheckpointCorruptError`, and ``latest_valid_checkpoint`` then
    falls back to the newest manifest whose shard prefix is intact — the
    truncate-to-last-consistent-prefix guarantee), or as a lazily
    materialised, optionally memory-mapped view with ``mmap=True`` so a
    multi-GB draw history loads without a full host-RAM copy."""
    import jax
    import jax.numpy as jnp

    from ..mcmc.structs import build_spec
    from ..post.posterior import Posterior

    path = os.fspath(path)
    d = os.path.dirname(path) or "."
    man = load_manifest(path)

    spec = build_spec(hM, int(man["nf_cap"]))
    got_fp = spec_fingerprint(spec)
    if got_fp != man["spec_sha256"]:
        raise CheckpointSpecMismatchError(
            f"{path}: model spec fingerprint mismatch "
            f"({got_fp[:12]}… != {man['spec_sha256'][:12]}…) — the snapshot "
            "was written for a different model; rebuild the matching Hmsc "
            "object to resume")

    def _read_state_payload(st_entry):
        spath = os.path.join(d, st_entry["file"])
        try:
            with np.load(spath, allow_pickle=False) as z:
                data = {k: z[k] for k in z.files}
        except (zipfile.BadZipFile, zlib.error, OSError, ValueError,
                KeyError, EOFError) as e:
            raise CheckpointCorruptError(
                f"{spath}: unreadable state file "
                f"({type(e).__name__}: {e})") from e
        for k, want in st_entry.get("checksums", {}).items():
            if k not in data:
                raise CheckpointCorruptError(
                    f"{spath}: payload {k!r} is missing — truncated or "
                    "corrupt")
            if _crc(data[k]) != want:
                raise CheckpointCorruptError(
                    f"{spath}: payload {k!r} failed its integrity checksum "
                    "— the state file is corrupt; fall back to an earlier "
                    "manifest")
        names, _ = _state_skeleton(spec)
        missing = [n for n in names if f"state:{n}" not in data]
        if missing:
            raise CheckpointCorruptError(
                f"{spath}: carry-state leaves missing: {missing}")
        return data

    # multi-process manifests carry one chain-slice state file per process;
    # concatenating their leaves in rank order reassembles the GLOBAL carry
    # — which is what lets resume re-shard the chains under a different
    # process count than the run that wrote the snapshot
    st_entries = man.get("states") or [man["state"]]
    payloads = [_read_state_payload(e) for e in st_entries]
    names, treedef = _state_skeleton(spec)

    def _concat(key):
        parts = [p[key] for p in payloads]
        if len(parts) == 1:
            return parts[0]
        # scalar leaves (none today) would be replicated, not stacked
        return (np.concatenate(parts, axis=0) if parts[0].ndim > 0
                else parts[0])

    state = jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(_concat(f"state:{n}")) for n in names])
    keys = None
    if all("rngkeys" in p for p in payloads) and man.get("keys_impl"):
        keys = jax.random.wrap_key_data(
            jnp.asarray(_concat("rngkeys")), impl=man["keys_impl"])

    shards = man.get("shards", [])
    if mmap:
        # mapped members skip checksum verification (the documented fast
        # trusted path); `verify` still governs any fallback copy-read of
        # a member that cannot be mapped (e.g. a compressed shard)
        arrays = ShardBackedArrays(d, shards, mmap=True, verify=verify)
    else:
        # eager: verify + materialise in one pass, opening each shard's
        # archive once and reading each payload exactly once (NpzFile
        # re-inflates the zip member on every access).  Windows concatenate
        # along samples; a multi-process window stitches chains first.
        parts = {}
        for _, group in _group_shard_windows(shards):
            win_parts = {}
            for s in group:
                sp = os.path.join(d, s["file"])
                try:
                    with np.load(sp, allow_pickle=False) as z:
                        for k in s.get("checksums", {}):
                            a = _read_shard_member(sp, k, s, verify=verify,
                                                   npz=z)
                            win_parts.setdefault(k[5:], []).append(a)
                except CheckpointError:
                    raise
                except (zipfile.BadZipFile, zlib.error, OSError, ValueError,
                        KeyError, EOFError) as e:
                    raise CheckpointCorruptError(
                        f"{sp}: unreadable shard "
                        f"({type(e).__name__}: {e})") from e
            for k, v in win_parts.items():
                parts.setdefault(k, []).append(
                    v[0] if len(v) == 1 else np.concatenate(v, axis=0))
        arrays = {k: (v[0] if len(v) == 1 else np.concatenate(v, axis=1))
                  for k, v in parts.items()}

    post = Posterior(hM, spec, arrays, samples=int(man["samples"]),
                     transient=int(man["transient"]),
                     thin=int(man["thin"]))
    if not len(post.arrays):
        post.n_chains = int(man.get("n_chains", 0))
    if "first_bad_it" in man:
        post.set_chain_health(np.asarray(man["first_bad_it"]))
    post.nf_saturation = {int(r): np.asarray(v)
                          for r, v in man.get("nf_saturation", {}).items()}
    # a splice-repaired run records its retry provenance in the manifest;
    # surface it on the stitched posterior like sample_mcmc does in-memory
    ri = (man.get("run") or {}).get("retry_info")
    if ri:
        post.retry_info = {
            k: (tuple(v) if isinstance(v, list) else v)
            for k, v in ri.items()}
    return LoadedCheckpoint(post=post, state=state, keys=keys,
                            run_meta=dict(man.get("run", {})),
                            header=man, path=path)


# ---------------------------------------------------------------------------
# rotation / discovery
# ---------------------------------------------------------------------------

def checkpoint_files(path: str) -> list[str]:
    """Resume candidates under a directory, newest first: append-layout
    manifests and legacy self-contained snapshots interleaved — sample
    snapshots (most samples first), then burn-in snapshots (most sweeps
    first — every burn-in snapshot predates every sample snapshot); at
    equal recency a manifest outranks a legacy file.  Shard and state files
    are *not* listed (they are only reachable through a manifest).  A
    direct file path is returned as a single-element list; an ``archive/``
    subdirectory is never scanned."""
    path = os.fspath(path)
    if os.path.isfile(path):
        return [path]
    if not os.path.isdir(path):
        return []          # no directory yet -> no checkpoints (callers
                           # raise the documented CheckpointError on empty)
    entries = []
    for fn in os.listdir(path):
        m = _CKPT_RE.fullmatch(fn)
        pref = 0
        if m is None:
            m = _MANIFEST_RE.fullmatch(fn)
            pref = 1                           # manifest outranks legacy
        if m:
            kind = 0 if m.group(1) else 1      # burn-in sorts below samples
            entries.append(((kind, int(m.group(2)), pref),
                            os.path.join(path, fn)))
    return [p for _, p in sorted(entries, reverse=True)]


_TMP_RE = re.compile(r"(.+)\.tmp\.\d+")


def _is_layout_name(fn: str) -> bool:
    return bool(_CKPT_RE.fullmatch(fn) or _MANIFEST_RE.fullmatch(fn)
                or _SHARD_RE.fullmatch(fn) or _STATE_RE.fullmatch(fn))


def _layout_files(path: str) -> list[str]:
    """Every file the checkpoint layouts own under a directory (legacy
    snapshots, manifests, shards, state files, and stale ``*.tmp.<pid>``
    atomic-write leftovers from a kill mid-write) — the set a fresh run
    clears so a later ``resume_run`` cannot mix two runs, and the set the
    ``checkpoint_max_bytes`` budget counts."""
    path = os.fspath(path)
    if not os.path.isdir(path):
        return []
    out = []
    for fn in os.listdir(path):
        m = _TMP_RE.fullmatch(fn)
        if _is_layout_name(fn) or (m and _is_layout_name(m.group(1))):
            out.append(os.path.join(path, fn))
    return out


def rotate_checkpoints(path: str, keep: int, *,
                       max_age_s: float | None = None) -> None:
    """Delete all but the newest ``keep`` snapshots in a directory
    (manifests and legacy self-contained files alike — deleting a manifest
    is the append layout's rotation primitive; the shards it alone
    referenced are reclaimed by :func:`gc_checkpoints`).  ``keep <= 0``
    keeps every snapshot (rotation off; age/bytes policies still apply).

    ``max_age_s`` adds an age-based policy on top: snapshots whose mtime is
    older than ``max_age_s`` seconds are deleted even inside the keep
    window — except the newest, which always survives (a stalled run must
    not age away its only resume point).  Snapshots hard-linked into
    ``archive/`` (``checkpoint_archive_every``) are exempt from both."""
    files = checkpoint_files(path)
    doomed = files[keep:] if keep > 0 else []
    survivors = files[:keep] if keep > 0 else files
    if max_age_s is not None and len(survivors) > 1:
        import time
        now = time.time()
        for p in survivors[1:]:
            try:
                if now - os.path.getmtime(p) > max_age_s:
                    doomed.append(p)
            except OSError:
                pass
    for p in doomed:
        try:
            os.unlink(p)
        except OSError:
            pass


def _gc_orphans(path: str, *, protect_uncommitted: bool = False) -> int:
    """Delete shard / state files referenced by no surviving manifest.

    Shards are immutable and shared between manifests, so this is the only
    way they are ever reclaimed: rotation deletes manifests, GC sweeps what
    nothing references any more (including shards orphaned by a kill
    between a shard write and its manifest commit).  Unreadable manifests
    contribute no references — their unique files age out with them.
    Returns the number of files removed.

    ``protect_uncommitted`` is the multi-process guard: on a shared
    directory the committer's GC must never reclaim a PEER's newest shards
    — durably written but not yet referenced because their manifest commit
    is still in flight.  It spares any shard or state file whose boundary
    lies at/after the newest readable manifest's, and skips the foreign
    ``*.tmp.<pid>`` sweep entirely (a pid check cannot distinguish a dead
    writer's leftover from a live peer's in-flight tmp)."""
    path = os.fspath(path)
    if not os.path.isdir(path):
        return 0
    fns = os.listdir(path)
    referenced = set()
    # boundary ordering mirrors checkpoint_files: any sample snapshot is
    # newer than every burn-in snapshot
    newest = (-1, -1)
    for fn in fns:
        m = _MANIFEST_RE.fullmatch(fn)
        if not m:
            continue
        try:
            man = load_manifest(os.path.join(path, fn))
        except CheckpointError:
            continue
        newest = max(newest, (0 if m.group(1) else 1, int(m.group(2))))
        referenced.add(man["state"]["file"])
        referenced.update(s["file"] for s in man.get("states", []))
        referenced.update(s["file"] for s in man.get("shards", []))

    def _uncommitted_newest(fn):
        ms = _SHARD_RE.fullmatch(fn)
        if ms:
            return (1, int(ms.group(3)) + 1) >= newest
        mt = _STATE_RE.fullmatch(fn)
        if mt:
            return (0 if mt.group(1) else 1, int(mt.group(2))) >= newest
        return False

    removed = 0
    for fn in fns:
        doomed = ((_SHARD_RE.fullmatch(fn) or _STATE_RE.fullmatch(fn))
                  and fn not in referenced
                  and not (protect_uncommitted and _uncommitted_newest(fn)))
        if not doomed and not protect_uncommitted:
            # stale atomic-write tmp from a kill mid-write (a SIGKILL can
            # leave up to a full segment of draws behind, invisible to
            # rotation): reclaim any layout-named tmp not owned by this
            # process — our own in-flight tmps clean themselves up and GC
            # runs FIFO-after every write on the same thread anyway
            m = _TMP_RE.fullmatch(fn)
            doomed = (m is not None and _is_layout_name(m.group(1))
                      and not fn.endswith(f".{os.getpid()}"))
        if doomed:
            try:
                os.unlink(os.path.join(path, fn))
                removed += 1
            except OSError:
                pass
    return removed


def _snapshot_floor_bytes(newest: str) -> int:
    """On-disk footprint of one snapshot and everything it references —
    the irreducible floor the ``max_bytes`` budget can reach while that
    snapshot survives.  Unreadable snapshots contribute 0 (the budget loop
    then proceeds normally)."""
    try:
        if newest.endswith(".json"):
            man = load_manifest(newest)
            d = os.path.dirname(newest) or "."
            total = os.path.getsize(newest)
            states = man.get("states") or [man["state"]]
            total += sum(os.path.getsize(os.path.join(d, s["file"]))
                         for s in states)
            total += sum(int(s.get("nbytes", 0))
                         for s in man.get("shards", []))
            return total
        return os.path.getsize(newest)
    except (CheckpointError, OSError):
        return 0


def _layout_bytes(path: str) -> int:
    """Total bytes the checkpoint layouts hold under a directory (manifests
    + state files + shards + legacy snapshots; ``archive/`` excluded)."""
    total = 0
    for p in _layout_files(path):
        try:
            total += os.path.getsize(p)
        except OSError:
            pass
    return total


def gc_checkpoints(path: str, keep: int, *, max_age_s: float | None = None,
                   max_bytes: int | None = None,
                   protect_uncommitted: bool = False,
                   pin_epochs=None) -> None:
    """Manifest-driven rotation for the append-only layout (also rotates
    any legacy self-contained snapshots sharing the directory).

    Count (``keep`` newest) and age (``max_age_s``) policies first, then an
    optional total-bytes budget: while the layout holds more than
    ``max_bytes`` on disk and more than one snapshot survives, the oldest
    surviving snapshot is dropped (the newest is never deleted — a run must
    not GC away its only resume point).  Finally, shard and state files no
    surviving manifest references are deleted.  Files hard-linked into
    ``archive/`` are exempt throughout (hard links share the inode, so
    archiving live shards costs no extra bytes until GC would have
    reclaimed them).

    ``protect_uncommitted`` (multi-process runs: the committer's GC on a
    directory other processes append to) additionally spares unreferenced
    shard/state files at or beyond the newest manifest's boundary — a
    peer's durably-written-but-not-yet-committed newest files — and skips
    the foreign tmp sweep (see :func:`_gc_orphans`).

    Epoched runs (the directory carries an ``epochs.json`` registry):
    every committed epoch is GC-PINNED by default — rotation and the byte
    budget apply *within* each epoch's directory (the newest manifest of
    every epoch always survives, so every committed epoch stays loadable),
    and shards referenced by any surviving epoch manifest are never
    reclaimed.  ``pin_epochs=`` is the explicit escape hatch: pass an
    iterable of epoch indices to pin only those — an UNPINNED epoch's
    whole layout may then be reclaimed (oldest epoch first) when the
    ``max_bytes`` budget demands it, and the registry is rewritten without
    it.  The newest committed epoch is always pinned regardless."""
    try:
        reg = read_epoch_registry(path)
    except CheckpointError:
        reg = None                 # corrupt registry: fall back to the
                                   # single-directory policy; never let GC
                                   # widen the damage by unpinning epochs
    if reg is not None and reg.get("epochs"):
        _gc_epoched(path, reg, keep, max_age_s=max_age_s,
                    max_bytes=max_bytes,
                    protect_uncommitted=protect_uncommitted,
                    pin_epochs=pin_epochs)
        return
    rotate_checkpoints(path, keep, max_age_s=max_age_s)
    _gc_orphans(path, protect_uncommitted=protect_uncommitted)
    if max_bytes is not None:
        files = checkpoint_files(path)
        # the newest snapshot plus everything it references is the floor:
        # a budget below it is unsatisfiable, and burning the fallback
        # slots would buy nothing but lost resumability — keep them and
        # warn instead (warnings dedup per call site, so a long run says
        # this once, not once per snapshot)
        floor = _snapshot_floor_bytes(files[0]) if files else 0
        if floor > max_bytes:
            # stable message (no byte counts): the default warning filter
            # dedups on the exact text, so a long run says this once — an
            # embedded, growing footprint would re-fire every snapshot
            warnings.warn(
                "checkpoint_max_bytes is below the newest snapshot's own "
                "footprint (manifest + state + referenced shards); "
                "deleting older snapshots cannot meet the budget, so they "
                "are kept as fallback resume slots.  Raise the budget or "
                "lower the shard volume (record= selection, record_dtype)",
                RuntimeWarning, stacklevel=3)
        elif floor > 0:
            # floor == 0 means the newest snapshot is unreadable: trimming
            # by budget then would delete the only VALID fallback slots
            # while sparing the corrupt newest — leave the directory to
            # the resume-time corrupt-slot fallback instead
            while len(files) > 1 and _layout_bytes(path) > max_bytes:
                victim = files.pop()           # oldest snapshot
                try:
                    os.unlink(victim)
                except OSError:
                    pass
                _gc_orphans(path, protect_uncommitted=protect_uncommitted)


def _epoch_dir_bytes(run_dir: str, k: int) -> int:
    """One epoch's on-disk footprint for the budget loop: the layout files
    plus the refit ancillary files (appended data, markers, the probe
    transient) for ``epoch-<k>/`` subdirectories; the run root counts its
    layout files only (matching the single-epoch accounting)."""
    d = epoch_dir_path(run_dir, k)
    if k == 0:
        return _layout_bytes(d)
    total = 0
    for base, _dirs, fns in os.walk(d):
        for fn in fns:
            try:
                total += os.path.getsize(os.path.join(base, fn))
            except OSError:
                pass
    return total


def _reclaim_epoch(run_dir: str, reg: dict, k: int) -> None:
    """Drop one unpinned epoch: registry first (atomically — a reader can
    never resolve an epoch whose files are mid-delete), then the files."""
    reg["epochs"] = [e for e in reg["epochs"] if int(e["epoch"]) != k]
    write_epoch_registry(run_dir, reg)
    d = epoch_dir_path(run_dir, k)
    if k == 0:
        # the root cannot be removed wholesale: reclaim its layout files
        # only (model.json / telemetry streams survive)
        for p in _layout_files(d):
            try:
                os.unlink(p)
            except OSError:
                pass
    else:
        import shutil
        shutil.rmtree(d, ignore_errors=True)


def _gc_epoched(run_dir: str, reg: dict, keep: int, *,
                max_age_s: float | None, max_bytes: int | None,
                protect_uncommitted: bool, pin_epochs) -> None:
    """Epoch-aware GC (see :func:`gc_checkpoints`): per-epoch rotation with
    every epoch's newest manifest protected, then a run-level byte budget
    that may reclaim whole UNPINNED epochs, oldest first, never the
    newest."""
    epochs = sorted(int(e["epoch"]) for e in reg["epochs"])
    pinned = set(epochs) if pin_epochs is None else {int(k)
                                                    for k in pin_epochs}
    pinned.add(epochs[-1])           # the newest epoch is always pinned
    for k in epochs:
        d = epoch_dir_path(run_dir, k)
        rotate_checkpoints(d, keep, max_age_s=max_age_s)
        _gc_orphans(d, protect_uncommitted=protect_uncommitted)
    if max_bytes is None:
        return
    total = sum(_epoch_dir_bytes(run_dir, k) for k in epochs)
    victims = [k for k in epochs if k not in pinned]
    while total > max_bytes and victims:
        k = victims.pop(0)           # oldest unpinned epoch first
        _reclaim_epoch(run_dir, reg, k)
        epochs.remove(k)
        total = sum(_epoch_dir_bytes(run_dir, kk) for kk in epochs)
    if total > max_bytes:
        warnings.warn(
            "checkpoint_max_bytes is below the pinned epochs' combined "
            "footprint; committed epochs are GC-pinned while referenced, "
            "so they are kept loadable instead.  Unpin old epochs "
            "explicitly via gc_checkpoints(pin_epochs=...) to reclaim "
            "them", RuntimeWarning, stacklevel=3)


def latest_valid_checkpoint(path: str, hM, *,
                            allow_legacy_pickle: bool = False) -> LoadedCheckpoint:
    """Newest checkpoint that loads cleanly; corrupt slots are skipped with
    a warning (falling back to the previous rotation slot).  Under the
    append-only layout a corrupt *shard* corrupts every manifest that
    references it, so the fallback lands on the newest manifest whose shard
    prefix is fully intact — truncation to the last consistent prefix.  A
    spec mismatch is raised immediately — every slot would mismatch the
    same way."""
    cands = checkpoint_files(path)
    if not cands:
        raise CheckpointError(f"no checkpoints found under {path!r}")
    failures = []
    for p in cands:
        try:
            return load_checkpoint_full(
                p, hM, allow_legacy_pickle=allow_legacy_pickle)
        except CheckpointCorruptError as e:
            warnings.warn(
                f"skipping corrupt checkpoint {p} ({e}); falling back to "
                "the previous rotation slot", RuntimeWarning, stacklevel=2)
            failures.append(f"{p}: {e}")
    raise CheckpointError(
        "every candidate checkpoint failed to load:\n  "
        + "\n  ".join(failures))


# ---------------------------------------------------------------------------
# CheckpointWriter: the sampler's on-disk snapshot machinery
# ---------------------------------------------------------------------------

class CheckpointWriter:
    """Every on-disk artifact of an auto-checkpointing run, in one object.

    Extracted from ``sample_mcmc`` (ROADMAP item): the sampler's loop now
    only *submits* snapshot calls; all layout logic — append-only shards /
    state files / manifest commits, the legacy rotating self-contained
    files, rotation + GC + archive links, splice repairs, and the
    multi-process manifest coordination — lives here, constructed from
    ``(dir, layout, base, shards)`` explicitly and unit-testable with no
    sampler in the loop (``tests/test_checkpoint_writer.py``).

    Threading contract: every mutating method runs on the sampler's single
    background writer thread (FIFO submission order), so the internal
    bookkeeping needs no locks.  ``records`` is the (shared, sampler-owned)
    list of fetched host record trees; the writer reads and folds it only
    from that same thread.

    Multi-process runs (``coordinator`` with ``process_count > 1``): each
    process's writer appends ONLY its own ``seg-<proc>-…`` shard stream and
    ``state-<tag>-p<proc>.npz`` chain-slice carry; a snapshot then
    all-gathers the per-process manifest entries (an implicit barrier that
    certifies every process fsynced its files up to the boundary), the
    committer (rank 0) alone writes the stitched ``manifest-<tag>.json``
    and runs GC (with ``protect_uncommitted`` so a peer's newest
    not-yet-committed files are never reclaimed), and a final barrier
    releases the peers only after the commit is durable (it doubles as
    the per-mark pacing that keeps rank skew from accumulating into
    gather stalls).  The gather also carries each process's preemption
    flag, so a SIGTERM on ANY process unwinds EVERY process at the same
    committed boundary (``abort_agreed``)."""

    def __init__(self, dirpath: str, layout: str, spec, *, hM=None,
                 records: list | None = None, base_post=None,
                 base_samples: int = 0, shards: list | None = None,
                 keep: int = 3, max_age_s: float | None = None,
                 archive_every: int = 0, max_bytes: int | None = None,
                 keys_impl: str | None = None, shard_index: int = 0,
                 coordinator=None, compress: bool = False,
                 preempt_fn=None, telemetry=None):
        if layout not in ("append", "rotating"):
            raise ValueError(f"layout must be 'append' or 'rotating', "
                             f"got {layout!r}")
        self.dir = os.fspath(dirpath)
        os.makedirs(self.dir, exist_ok=True)
        self.layout = layout
        self.spec = spec
        self.hM = hM
        self.records = records if records is not None else []
        self.base_post = base_post
        self.base_samples = int(base_samples)
        self.keep = int(keep)
        self.max_age_s = max_age_s
        self.archive_every = int(archive_every)
        self.max_bytes = max_bytes
        self.keys_impl = keys_impl
        self.shard_index = int(shard_index)
        self.coordinator = coordinator
        self.compress = bool(compress)
        self._preempt_fn = preempt_fn or (lambda: False)
        # spans for every on-disk/coordination stage land here; a writer
        # constructed standalone (unit tests) gets a disabled telemetry
        # whose aggregates still back the io accounting below
        self.telem = (telemetry if telemetry is not None
                      else RunTelemetry(proc=int(shard_index), enabled=False))
        self._multi = (coordinator is not None
                       and int(coordinator.process_count) > 1)
        if self._multi and layout != "append":
            raise ValueError(
                "multi-process checkpointing requires the append layout "
                "(the rotating self-contained format has no per-process "
                "commit point)")
        self._carried = [dict(s) for s in shards or []]
        self._own: list = []
        # one-time legacy migration: a rotating-layout run continued in the
        # append layout flushes its base draws once as a base shard
        self._base_flush = (base_post
                            if (layout == "append" and base_post is not None
                                and not self._carried) else None)
        if self._multi and self._base_flush is not None:
            raise ValueError(
                "resuming a legacy rotating directory on a multi-process "
                "mesh is not supported — resume it single-process once to "
                "migrate it to the append layout first")
        self._flush = {
            "idx": 0, "cursor": self.base_samples,
            # seed past any repair ordinal the carried shard list holds so
            # a later splice-rewrite never reuses a repair file name
            "repair": max((int(m.group(4) or 0) for m in
                           (_SHARD_RE.fullmatch(s["file"])
                            for s in self._carried) if m), default=0)}
        self.n_writes = 0
        import threading
        self._abort_lock = threading.Lock()
        self._abort_agreed = False
        self.io = {"bytes": 0, "snapshot_bytes": [], "shards_written": 0}

    # -- shared helpers ----------------------------------------------------

    # the ONE cross-thread attribute of this otherwise writer-thread-
    # confined object: the commit gather's abort verdict is set on the
    # background writer and polled by the driver loop at marks.
    # hmsc: guarded-by[_abort_lock]: _abort_agreed

    @property
    def abort_agreed(self) -> bool:
        """True once any rank's preemption flag rode a commit gather."""
        with self._abort_lock:
            return self._abort_agreed

    def _set_abort_agreed(self) -> None:
        with self._abort_lock:
            self._abort_agreed = True

    def _span_total(self, name: str) -> float:
        return self.telem.totals().get(name, {}).get("total_s", 0.0)

    @property
    def barrier_wait_s(self) -> float:
        """Seconds spent in commit gathers + release barriers — derived
        from the telemetry span aggregates (``io`` keeps only byte
        counters, so the two accountings cannot drift)."""
        return self._span_total("barrier_wait")

    @property
    def manifest_commit_s(self) -> float:
        """Seconds the committer spent writing manifest commits (the
        telemetry ``manifest_commit`` span aggregate)."""
        return self._span_total("manifest_commit")

    @property
    def _is_committer(self) -> bool:
        return (not self._multi) or self.coordinator.is_coordinator

    def path_for(self, done: int = 0, burnin_it: int | None = None) -> str:
        """The snapshot path a matching :meth:`snapshot` call will commit
        (the preemption message names it before the write has drained)."""
        tag = (f"t{burnin_it:08d}" if burnin_it is not None
               else f"{self.base_samples + int(done):08d}")
        if self.layout == "append":
            return os.path.join(self.dir, f"manifest-{tag}.json")
        return os.path.join(self.dir, f"ckpt-{tag}.npz")

    def _merged_records(self) -> dict:
        """Fold the fetched host segments into one tree (kept folded so
        repeated rotating snapshots stay linear, not quadratic)."""
        import jax
        if len(self.records) > 1:
            merged = jax.tree.map(lambda *xs: np.concatenate(xs, axis=1),
                                  *self.records)
            self.records[:] = [merged]
        return self.records[0] if self.records else {}

    def _merged_first_bad(self, first_bad) -> np.ndarray:
        fb = np.asarray(first_bad)
        if self.base_post is not None:
            fb0 = np.asarray(self.base_post.chain_health["first_bad_it"])
            fb = np.where(fb0 >= 0, fb0, fb)
        return fb

    def _nf_sat(self, state) -> dict:
        return {str(r): np.asarray(state.levels[r].nf_sat).reshape(-1)
                for r in range(self.spec.nr)}

    def _gc(self) -> None:
        with self.telem.span("gc"):
            gc_checkpoints(self.dir, self.keep, max_age_s=self.max_age_s,
                           max_bytes=self.max_bytes,
                           protect_uncommitted=self._multi)

    def _archive_link(self, src: str) -> None:
        # hard-link (copy fallback) into archive/, exempt from rotation
        # and GC — post-hoc divergence debugging; links share the inode
        # so archiving a live shard costs no extra bytes
        adir = os.path.join(self.dir, "archive")
        os.makedirs(adir, exist_ok=True)
        apath = os.path.join(adir, os.path.basename(src))
        try:
            if os.path.exists(apath):
                os.unlink(apath)
            os.link(src, apath)
        except OSError:
            import shutil
            shutil.copy2(src, apath)

    # -- the one public snapshot entry point -------------------------------

    def snapshot(self, done: int, state, keys, first_bad, meta: dict, *,
                 burnin_it: int | None = None) -> str:
        """Commit one snapshot: recorded draws up to local count ``done``
        (plus any resumed base segment), the carry ``state``, the RNG
        ``keys`` (typed keys or raw key data), and divergence health.
        ``burnin_it`` marks a state-only burn-in snapshot at that absolute
        sweep.  ``meta`` is the sampler's run-metadata dict (resume reads
        the run configuration from it)."""
        self.n_writes += 1
        ordinal = self.n_writes
        if burnin_it is not None:
            meta = dict(meta, transient_done=int(burnin_it))
        if self.layout == "append":
            b0 = self.io["bytes"]
            if burnin_it is None:
                self._flush_shards(done)
                tag = f"{self.base_samples + int(done):08d}"
            else:
                tag = f"t{burnin_it:08d}"
            path = self._append_snapshot(
                tag,
                meta["samples_done"] if burnin_it is None
                else self.base_samples,
                state, keys, first_bad, meta, ordinal)
            self.io["snapshot_bytes"].append(self.io["bytes"] - b0)
            return path
        if burnin_it is not None:
            return self._write_burnin_ck(burnin_it, state, keys, first_bad,
                                         meta, ordinal)
        return self._write_ck(done, state, keys, first_bad, meta, ordinal)

    # -- append-only layout ------------------------------------------------

    def _flush_shards(self, done: int) -> None:
        """Make every draw recorded up to local count ``done`` durable as
        immutable shards of THIS process's stream.  Runs FIFO after all
        pending segment fetches, so ``records`` holds everything up to the
        snapshot boundary; cost is O(draws since the last flush), never
        O(history) — the layout's whole point."""
        import jax
        if self._base_flush is not None:
            bp, self._base_flush = self._base_flush, None
            with self.telem.span("shard_write", kind_of="base") as sp:
                entry = save_shard(
                    self.dir,
                    {k: np.asarray(v) for k, v in bp.arrays.items()},
                    0, self.base_samples - 1, shard_index=self.shard_index,
                    compress=self.compress)
                sp.fields["nbytes"] = entry["nbytes"]
            self._own.append(entry)
            self.io["bytes"] += entry["nbytes"]
            self.io["shards_written"] += 1
        done_g = self.base_samples + int(done)
        if done_g <= self._flush["cursor"]:
            return
        new = self.records[self._flush["idx"]:]
        arrays = (new[0] if len(new) == 1
                  else jax.tree.map(
                      lambda *xs: np.concatenate(xs, axis=1), *new))
        with self.telem.span("shard_write", first=self._flush["cursor"],
                             last=done_g - 1) as sp:
            entry = save_shard(self.dir, arrays, self._flush["cursor"],
                               done_g - 1, shard_index=self.shard_index,
                               compress=self.compress)
            sp.fields["nbytes"] = entry["nbytes"]
        self._flush["idx"] = len(self.records)
        self._flush["cursor"] = done_g
        self._own.append(entry)
        self.io["bytes"] += entry["nbytes"]
        self.io["shards_written"] += 1

    def _manifest_common(self, samples_done: int, meta: dict) -> dict:
        import hmsc_tpu as _pkg
        return {
            "package_version": _pkg.__version__,
            "samples": int(samples_done),
            "transient": int(meta["transient"]),
            "thin": int(meta["thin"]), "n_chains": int(meta["n_chains"]),
            "nf_cap": int(meta["nf_cap"]),
            "spec_sha256": spec_fingerprint(self.spec),
            "keys_impl": self.keys_impl,
            "run": meta,
        }

    def _append_snapshot(self, tag: str, samples_done: int, state, keys,
                         first_bad, meta: dict, ordinal: int) -> str:
        """State file + coordinated manifest commit + archive + GC for one
        append-layout snapshot."""
        with self.telem.span("state_write", tag=tag) as sp:
            st_entry = save_state_file(
                self.dir, tag, self.spec, state, keys_data=keys,
                proc=self.shard_index if self._multi else None,
                compress=self.compress)
            sp.fields["nbytes"] = st_entry["nbytes"]
        self.io["bytes"] += st_entry["nbytes"]
        if self._multi:
            # each process publishes its own dirents durably before the
            # barrier certifies the boundary (single-process relies on the
            # manifest commit's directory fsync covering all three)
            _fsync_dir(os.path.join(self.dir, st_entry["file"]))
        fb = [int(x) for x in self._merged_first_bad(first_bad)]
        nf_sat = {r: v.tolist() for r, v in self._nf_sat(state).items()}
        man = self._manifest_common(samples_done, meta)
        path = os.path.join(self.dir, f"manifest-{tag}.json")
        if not self._multi:
            man.update(state=st_entry, shards=self._carried + self._own,
                       first_bad_it=fb, nf_saturation=nf_sat)
            with self.telem.span("manifest_commit", tag=tag):
                save_manifest(self.dir, tag, man)
            self.io["bytes"] += int(os.path.getsize(path))
            self._maybe_archive(path, man, ordinal)
            self._gc()
            return path
        coord = self.coordinator
        # each rank rides its per-mark telemetry deltas on the commit
        # gather (no extra collective): the committer derives cross-rank
        # skew from them and records it at every mark
        payload = {"state": st_entry, "shards": self._own,
                   "first_bad_it": fb, "nf_saturation": nf_sat,
                   "preempt": bool(self._preempt_fn()),
                   "telemetry": self.telem.mark_delta()}
        with self.telem.span("barrier_wait", tag=tag, what="commit-gather"):
            parts = coord.all_gather(payload, tag=f"ck-{tag}")
        if any(p["preempt"] for p in parts):
            self._set_abort_agreed()
        if coord.is_coordinator:
            # stitch: per-process new shards regrouped into sample windows
            # (process order within a window); the carried prefix is the
            # prior manifest's already-global sequence
            new = [dict(s) for p in parts for s in p["shards"]]
            stitched = [s for _, grp in _group_shard_windows(new)
                        for s in grp]
            states = [p["state"] for p in parts]
            man.update(
                state=states[0], states=states,
                process_count=int(coord.process_count),
                shards=self._carried + stitched,
                first_bad_it=[x for p in parts for x in p["first_bad_it"]],
                nf_saturation={
                    r: [x for p in parts for x in p["nf_saturation"][r]]
                    for r in nf_sat},
            )
            with self.telem.span("manifest_commit", tag=tag):
                save_manifest(self.dir, tag, man)
            self.io["bytes"] += int(os.path.getsize(path))
            self._record_skew(tag, parts)
            self._maybe_archive(path, man, ordinal)
            self._gc()
        # Every commit ends with a release barrier.  It buys two things:
        # no rank exits the run (normal completion or preemption unwind)
        # before the manifest its exit message names is durable, and —
        # just as important — it re-paces the ranks' writer threads each
        # mark.  Skipping it on intermediate commits looks like a free
        # win (the next mark's gather already orders ranks behind the
        # committer's manifest write), but was measured to be a large
        # regression on an oversubscribed host: without the per-mark
        # resync, rank skew accumulates, the committer stalls in
        # ever-longer gather polls, its bounded queue fills, and the
        # backpressure lands on the driver (A/B on the same box:
        # commit overhead 1.5% with the barrier vs 27% without;
        # scaling efficiency 97% vs 62%).
        with self.telem.span("barrier_wait", tag=tag, what="release"):
            coord.barrier(f"committed-{tag}")
        return path

    def _record_skew(self, tag: str, parts: list) -> None:
        """Committer-side cross-rank skew at one commit mark, derived from
        the per-rank telemetry deltas the gather carried (see
        :func:`hmsc_tpu.obs.events.record_rank_skew` — shared with the
        sampler's end-of-run gather on checkpoint-free mesh runs)."""
        from ..obs.events import record_rank_skew
        record_rank_skew(self.telem, tag,
                         [p.get("telemetry") for p in parts])

    def _maybe_archive(self, man_path: str, man: dict, ordinal: int) -> None:
        if not (self.archive_every and ordinal % self.archive_every == 0):
            return
        self._archive_link(man_path)
        for st in (man.get("states") or [man["state"]]):
            self._archive_link(os.path.join(self.dir, st["file"]))
        for s in man.get("shards", []):
            src = os.path.join(self.dir, s["file"])
            dst = os.path.join(self.dir, "archive", s["file"])
            try:
                # same inode = already archived (hard link); a same-NAME
                # file from a previous run in a reused directory must be
                # re-linked, or this manifest's archive copy would pair
                # with the old run's bytes
                if os.path.exists(dst) and os.path.samefile(src, dst):
                    continue
            except OSError:
                pass
            self._archive_link(src)

    def _replace_changed_tail(self, changed_from: int, total_samples: int,
                              arrays) -> None:
        """Supersede THIS process's shards overlapping the changed window
        ``[changed_from, total_samples)`` with one repair shard cut from
        ``arrays`` (this process's chain slice).  Shard files are
        immutable, so the repaired window gets a NEW name (``-r<n>``) and
        the superseded files are garbage-collected once no manifest
        references them.  The carried prefix (a resumed run's pre-existing
        global history) always predates this call's sampling window, so it
        is never touched."""
        changed_g = self.base_samples + int(changed_from)
        keep_shards, doomed = [], []
        for s in self._own:
            (keep_shards if int(s["last"]) < changed_g
             else doomed).append(s)
        # the repair window opens at the first superseded shard's start
        # (a shard straddling the change boundary is replaced whole)
        rep_first = (min(int(s["first"]) for s in doomed)
                     if doomed else changed_g)
        end_g = self.base_samples + int(total_samples)
        if rep_first < end_g:
            self._flush["repair"] += 1
            lo = rep_first - self.base_samples
            out = {k: np.asarray(v)[:, lo:] for k, v in arrays.items()}
            with self.telem.span("shard_write", kind_of="repair") as sp:
                entry = save_shard(self.dir, out, rep_first, end_g - 1,
                                   shard_index=self.shard_index,
                                   repair=self._flush["repair"],
                                   compress=self.compress)
                sp.fields["nbytes"] = entry["nbytes"]
            keep_shards.append(entry)
            self.io["bytes"] += entry["nbytes"]
            self.io["shards_written"] += 1
        self._own = keep_shards

    def rewrite_spliced(self, changed_from: int, total_samples: int,
                        state, keys, first_bad, post, meta: dict) -> str:
        """Post-splice repair of a completed append-layout run (after the
        background writer drained): shards entirely before the changed
        window are untouched; the changed tail is re-written ONCE as a
        repair shard (immutable files never mutate — a repaired window gets
        a new name), and a new final manifest commits the repaired
        sequence.  Cost is O(changed draws): a warm-restart splice
        re-writes only the post-snapshot tail."""
        if self._multi:
            raise CheckpointError(
                "rewrite_spliced is the single-process repair; a "
                "coordinated run repairs through rewrite_spliced_multi")
        with self.telem.span("splice_rewrite",
                             changed_from=int(changed_from)):
            self._replace_changed_tail(changed_from, total_samples,
                                       post.arrays)
            end_g = self.base_samples + int(total_samples)
            return self._append_snapshot(f"{end_g:08d}", end_g, state, keys,
                                         first_bad, meta, self.n_writes)

    def rewrite_spliced_multi(self, changed_from: int, total_samples: int,
                              state, keys, first_bad, post, meta: dict, *,
                              changed: bool) -> str:
        """Coordinated post-splice repair of a completed multi-process run
        — the multi-rank counterpart of :meth:`rewrite_spliced`.  EVERY
        rank calls this (it is a collective); ranks whose chain slice was
        spliced pass ``changed=True`` and first supersede their changed
        tail with a repair shard.  All ranks then meet at the shared final
        boundary through the ordinary coordinated commit
        (:meth:`_append_snapshot`): each re-saves its (possibly repaired)
        chain-slice state file, the commit gather certifies every repair
        durable, the committer alone overwrites the final manifest with
        the repaired shard sequence plus the gathered post-retry health,
        and the release barrier holds every rank until the commit is
        durable.  Healthy ranks' shard files are untouched bit-for-bit —
        only their state files are (identically) re-written."""
        if not self._multi:
            raise CheckpointError(
                "rewrite_spliced_multi requires a multi-process "
                "coordinator; single-process repairs use rewrite_spliced")
        if changed:
            with self.telem.span("splice_rewrite",
                                 changed_from=int(changed_from)):
                self._replace_changed_tail(changed_from, total_samples,
                                           post.arrays)
        end_g = self.base_samples + int(total_samples)
        return self._append_snapshot(f"{end_g:08d}", end_g, state, keys,
                                     first_bad, meta, self.n_writes)

    # -- legacy rotating self-contained layout ------------------------------

    def _finish_ck(self, path, partial, state, keys, meta, ordinal) -> None:
        with self.telem.span("snapshot_write") as sp:
            save_checkpoint(path, partial, state, keys=keys,
                            keys_impl=self.keys_impl, run_meta=meta,
                            compress=self.compress)
            sp.fields["nbytes"] = int(os.path.getsize(path))
        nbytes = sp.fields["nbytes"]
        self.io["bytes"] += nbytes
        self.io["snapshot_bytes"].append(nbytes)
        self._gc()
        if self.archive_every and ordinal % self.archive_every == 0:
            self._archive_link(path)

    def _write_ck(self, done: int, state, keys, first_bad, meta: dict,
                  ordinal: int, post_override=None,
                  state_override=None) -> str:
        """Self-contained snapshot: draws-so-far (prepending a resumed
        run's base segment) + carry state + carried keys; atomic write,
        rotate.  ``post_override``/``state_override`` re-write a slot from
        an already-built posterior and spliced carry state (the
        retry_diverged splice re-writes the final one)."""
        from ..post.posterior import Posterior as _P
        if post_override is None:
            arrays = {k: np.asarray(v)
                      for k, v in self._merged_records().items()}
            fb = np.asarray(first_bad)
        else:
            arrays = {k: np.asarray(v)
                      for k, v in post_override.arrays.items()}
            fb = np.asarray(post_override.chain_health["first_bad_it"])
        if self.base_post is not None:
            if set(arrays) != set(self.base_post.arrays):
                raise CheckpointError(
                    "continuation records different parameters than the "
                    "checkpointed base segment — was record= changed?")
            arrays = {k: np.concatenate([self.base_post.arrays[k],
                                         arrays[k]], axis=1)
                      for k in arrays}
            fb0 = np.asarray(self.base_post.chain_health["first_bad_it"])
            fb = np.where(fb0 >= 0, fb0, fb)
        partial = _P(self.hM, self.spec, arrays,
                     samples=int(meta["samples_done"]),
                     transient=int(meta["transient"]),
                     thin=int(meta["thin"]))
        partial.set_chain_health(fb)
        partial.nf_saturation = (
            dict(post_override.nf_saturation) if post_override is not None
            else self._nf_sat(state))
        path = os.path.join(self.dir,
                            f"ckpt-{int(meta['samples_done']):08d}.npz")
        self._finish_ck(path, partial,
                        state if state_override is None else state_override,
                        keys, meta, ordinal)
        return path

    def _write_burnin_ck(self, it_now: int, state, keys, first_bad,
                         meta: dict, ordinal: int) -> str:
        """State-only burn-in snapshot (carry + keys, no draws): a kill
        during a long transient resumes from here instead of restarting
        burn-in from scratch."""
        from ..post.posterior import Posterior as _P
        partial = _P(self.hM, self.spec, {}, samples=0,
                     transient=int(meta["transient"]),
                     thin=int(meta["thin"]))
        partial.n_chains = int(meta["n_chains"])
        partial.set_chain_health(np.asarray(first_bad))
        partial.nf_saturation = self._nf_sat(state)
        path = os.path.join(self.dir, f"ckpt-t{int(it_now):08d}.npz")
        self._finish_ck(path, partial, state, keys, meta, ordinal)
        return path

    def rewrite_rotating(self, total_samples: int, state, keys, first_bad,
                         post, meta: dict) -> str:
        """Re-write the final rotating slot from a spliced posterior."""
        return self._write_ck(int(total_samples), state, keys, first_bad,
                              meta, self.n_writes, post_override=post,
                              state_override=state)


# ---------------------------------------------------------------------------
# resume / concat
# ---------------------------------------------------------------------------

def _bounded_align(post, max_passes: int = 5) -> None:
    from ..post.align import align_posterior
    for _ in range(max_passes):
        if align_posterior(post) == 0:
            break


def resume_run(hM, checkpoint_path: str, *, verbose: int = 0,
               progress_callback=None, extra_samples: int = 0,
               checkpoint_every: int | None = None,
               checkpoint_keep: int | None = None,
               checkpoint_max_age_s: float | None = None,
               checkpoint_archive_every: int | None = None,
               checkpoint_max_bytes: int | None = None,
               checkpoint_layout: str | None = None,
               allow_legacy_pickle: bool = False, mesh=None,
               chain_axis: str = "chains", species_axis: str = "species",
               site_axis: str = "sites", shard_sweep=None,
               pipeline: bool = True, coordinator=None, telemetry=None):
    """Continue an auto-checkpointed ``sample_mcmc`` run to completion.

    Locates the newest valid checkpoint under ``checkpoint_path`` (corrupt
    slots fall back to the previous rotation slot), restores the carry state
    *and the carried RNG keys*, and samples the remaining draws with the
    stored run configuration — so the concatenated posterior is bit-identical
    to the uninterrupted run.  A burn-in snapshot (``ckpt-t<sweep>.npz``)
    resumes mid-transient: the remaining burn-in runs first, then sampling.
    The continuation keeps auto-checkpointing into the same directory, so
    repeated kill → resume cycles compose.  A run that already completed
    returns its posterior without sampling; ``extra_samples`` extends the
    target beyond the original total.

    Overrides: ``verbose`` and ``checkpoint_every`` may differ from the
    stored run configuration — both only re-segment the host loop, and the
    carried per-chain key makes the draw stream segmentation-invariant, so
    neither can change a single draw (asserted by the pipeline test suite).
    The rotation knobs (``checkpoint_keep`` / ``checkpoint_max_age_s`` /
    ``checkpoint_archive_every`` / ``checkpoint_max_bytes``) and the
    on-disk ``checkpoint_layout`` (``"append"`` / ``"rotating"``) are
    likewise overridable — they only manage files on disk (resuming a
    legacy rotating directory continues in the append-only layout by
    default: the base draws are flushed once as a base shard and every
    later snapshot is O(segment); see MIGRATION.md).  Parameters that
    *would* change the stream (seed, thin, updaters, RNG impl, record
    selection) are deliberately not overridable and always come from the
    checkpoint.  A device ``mesh`` is not serializable, so a
    sharded run passes its (possibly different) mesh back in via
    ``mesh=``/``chain_axis=``/``species_axis=``.

    ``coordinator`` continues the run on a multi-process mesh — with ANY
    process count, equal to or different from the one that wrote the
    snapshot: the loaded checkpoint carries the GLOBAL chain state (a
    multi-process manifest's per-process state files are stitched on
    load), and each process re-shards to its contiguous chain slice.  The
    per-chain draw stream is layout-invariant, so a 2-process run resumed
    single-process (or vice versa) reproduces the identical draws.  Each
    process returns the Posterior of its own chain slice; the committed
    final manifest holds the global run."""
    import jax
    import jax.numpy as jnp

    from .coordination import get_coordinator

    coord = get_coordinator(coordinator)
    n_procs = int(coord.process_count)
    ck = latest_valid_checkpoint(checkpoint_path, hM,
                                 allow_legacy_pickle=allow_legacy_pickle)
    meta = dict(ck.run_meta)
    if not meta:
        raise CheckpointError(
            f"{ck.path}: no run metadata in this checkpoint (it was written "
            "by save_checkpoint, not by sample_mcmc auto-checkpointing) — "
            "continue it manually via sample_mcmc(init_state=...)")
    if checkpoint_every is None:
        ck_every = int(meta.get("checkpoint_every", 0))
    else:
        ck_every = int(checkpoint_every)
        if ck_every < 0:
            raise ValueError(
                f"checkpoint_every override must be >= 0, got {ck_every}")
    # rotation-policy overrides manage files only — validate them here so a
    # bad override fails before any sampling (they can never change draws)
    if checkpoint_keep is not None and int(checkpoint_keep) < 0:
        raise ValueError("checkpoint_keep override must be >= 0 (0 keeps "
                         f"every snapshot), got {checkpoint_keep}")
    if checkpoint_max_age_s is not None and checkpoint_max_age_s <= 0:
        raise ValueError("checkpoint_max_age_s override must be > 0, got "
                         f"{checkpoint_max_age_s}")
    if checkpoint_archive_every is not None and checkpoint_archive_every < 0:
        raise ValueError("checkpoint_archive_every override must be >= 0, "
                         f"got {checkpoint_archive_every}")
    if checkpoint_max_bytes is not None and int(checkpoint_max_bytes) < 1:
        raise ValueError("checkpoint_max_bytes override must be >= 1, got "
                         f"{checkpoint_max_bytes}")
    if checkpoint_layout is not None \
            and checkpoint_layout not in ("append", "rotating"):
        raise ValueError("checkpoint_layout override must be 'append' or "
                         f"'rotating', got {checkpoint_layout!r}")

    total = int(meta["samples_total"]) + int(extra_samples)
    done = int(meta["samples_done"])
    align = bool(meta.get("align_post", True))
    if total <= done:
        out = ck.post
        if n_procs > 1 and len(out.arrays):
            if out.n_chains % n_procs:
                raise CheckpointError(
                    f"{ck.path}: carries {out.n_chains} chains, not "
                    f"divisible over {n_procs} processes — resume with a "
                    "process count that divides the chain count")
            k = out.n_chains // n_procs
            lo = int(coord.process_index) * k
            out = out.subset(chain_index=np.arange(lo, lo + k))
        if align and out.spec.nr > 0:
            _bounded_align(out)
        return out

    # a burn-in snapshot carries no draws: finish the remaining transient
    # first, then sample everything; the continuation has no base segment
    t_done = int(meta.get("transient_done", 0))
    remaining_t = (max(0, int(meta["transient"]) - t_done)
                   if done == 0 and t_done else 0)
    base = ck.post if ck.post.arrays else None

    # multi-process continuation: the checkpoint carries the GLOBAL chain
    # state; this process takes its contiguous slice (the process count may
    # differ from the writing run's — chains re-shard freely because seeds
    # and key streams are derived from the global chain index)
    init_state, init_keys = ck.state, ck.keys
    n_chains_g = int(ck.post.n_chains)
    if n_procs > 1:
        if n_chains_g % n_procs:
            raise CheckpointError(
                f"{ck.path}: carries {n_chains_g} chains, not divisible "
                f"over {n_procs} processes — resume with a process count "
                "that divides the chain count")
        k = n_chains_g // n_procs
        lo = int(coord.process_index) * k
        sl = slice(lo, lo + k)
        init_state = jax.tree_util.tree_map(lambda x: x[sl], ck.state)
        if init_keys is not None:
            init_keys = init_keys[sl]
        if base is not None:
            base = base.subset(chain_index=np.arange(lo, lo + k))

    rd = meta.get("record_dtype")
    record = meta.get("record")
    ckdir = (os.fspath(checkpoint_path) if os.path.isdir(checkpoint_path)
             else (os.path.dirname(ck.path) or "."))
    # stream-defining extras (PR 12): the mixed-precision policy and the
    # shard-local RNG mode come from the checkpoint, like seed/thin — a
    # local_rng continuation additionally needs the SAME species extent
    # (the shard index is folded into every species draw's key)
    stored_local_rng = bool(meta.get("local_rng", False))
    if stored_local_rng:
        # the full mesh tuple is pinned: shard-folded key streams fold
        # BOTH axis indices, so a continuation must re-shard over the
        # same species AND site extents
        axes = getattr(mesh, "axis_names", ()) if mesh is not None else ()
        want_sp = meta.get("species_shards")
        have_sp = (int(mesh.shape[species_axis])
                   if species_axis in axes else None)
        if want_sp is not None and have_sp != want_sp:
            raise CheckpointError(
                f"{ck.path}: run used local_rng over {want_sp} species "
                f"shard(s); resume must pass a mesh with the same "
                f"'{species_axis}' extent (got "
                f"{have_sp if have_sp is not None else 'no species axis'}) "
                "— the shard-local key streams are not layout-invariant")
        want_st = meta.get("site_shards")
        # compare ENGAGED extents, not raw mesh extents: a run whose site
        # axis fell back (stored site_shards == 1) must stay resumable on
        # the very mesh that produced it — the continuation falls back
        # identically, so the folded key streams match
        from ..mcmc.partition import engaged_site_extent
        from ..mcmc.structs import build_spec
        have_st = (engaged_site_extent(
            build_spec(hM, int(meta.get("nf_cap", 16))), mesh,
            species_axis, site_axis, meta.get("updater"),
            has_policy=meta.get("precision_policy") is not None)
            if mesh is not None else 1)
        if want_st is not None and have_st != want_st:
            raise CheckpointError(
                f"{ck.path}: run used local_rng over "
                f"(species_shards={want_sp}, site_shards={want_st}); "
                f"resume must pass a mesh with the same '{site_axis}' "
                f"extent (got {have_st}) — the shard-local key streams "
                "are not layout-invariant")
    from ..mcmc.sampler import sample_mcmc
    cont = sample_mcmc(
        hM, samples=total - done, transient=remaining_t,
        thin=int(meta["thin"]),
        n_chains=n_chains_g, seed=meta.get("seed"),
        init_state=init_state, init_keys=init_keys,
        coordinator=coordinator,
        # the original (resolved) adaptation window: its gate is on the
        # carried iteration counter, so it is a no-op here — but matching it
        # lets the continuation reuse the original run's compiled program
        adapt_nf=meta.get("adapt_nf"),
        nf_cap=int(meta["nf_cap"]), updater=meta.get("updater"),
        # model data must be rebuilt at the original precision, or an f64
        # run would continue against f32 data (init_par/data_par are not
        # serializable and so not restored; they only affect retry restarts)
        dtype=getattr(jnp, meta.get("dtype", "float32")),
        record=tuple(record) if record else None,
        record_dtype=None if rd is None else getattr(jnp, rd),
        rng_impl=meta.get("rng_impl"),
        # the divergence splice now has a coordinated multi-process path
        # (every rank unwinds to the shared last-healthy manifest, the
        # owning rank warm-restarts, the repair commits at that boundary),
        # so the stored retry policy survives a re-sharded continuation
        retry_diverged=int(meta.get("retry_diverged", 0)),
        precision_policy=meta.get("precision_policy"),
        local_rng=stored_local_rng,
        align_post=False, verbose=verbose, mesh=mesh,
        chain_axis=chain_axis, species_axis=species_axis,
        site_axis=site_axis, shard_sweep=shard_sweep,
        progress_callback=progress_callback,
        checkpoint_every=ck_every,
        checkpoint_path=ckdir,
        checkpoint_keep=int(meta.get("checkpoint_keep", 3)
                            if checkpoint_keep is None else checkpoint_keep),
        checkpoint_max_age_s=(meta.get("checkpoint_max_age_s")
                              if checkpoint_max_age_s is None
                              else checkpoint_max_age_s),
        checkpoint_archive_every=int(
            (meta.get("checkpoint_archive_every", 0) or 0)
            if checkpoint_archive_every is None else checkpoint_archive_every),
        checkpoint_max_bytes=(meta.get("checkpoint_max_bytes")
                              if checkpoint_max_bytes is None
                              else checkpoint_max_bytes),
        checkpoint_layout=(meta.get("checkpoint_layout", "append")
                           if checkpoint_layout is None
                           else checkpoint_layout),
        pipeline=pipeline, telemetry=telemetry,
        _ckpt_base=base, _transient_base=t_done if base is None else 0,
        # append-layout continuation: the already-flushed shard sequence is
        # carried forward so new manifests reference it instead of the base
        # draws being re-serialised into every snapshot
        _ckpt_shards=list(ck.header.get("shards", []))
        if ck.path.endswith(".json") else None)
    if base is None:
        out = cont
    else:
        out = concat_posteriors(base, cont, align=False)
        # the continuation's telemetry describes the only segment this
        # process actually ran — carry it onto the spliced posterior
        out.telemetry = getattr(cont, "telemetry", None)
    if align and out.spec.nr > 0:
        _bounded_align(out)
    return out


def concat_posteriors(first, second, *, align: bool = True,
                      max_align_passes: int = 5):
    """Splice two sampling segments of the same model: the recorded-sample
    axis is concatenated per parameter.  Validates that the segments are
    actually compatible — chain counts, parameter keys, per-parameter
    shapes and the ``thin`` stride — naming the offending key on mismatch."""
    if first.n_chains != second.n_chains:
        raise ValueError(
            f"concat_posteriors: chain counts differ "
            f"({first.n_chains} vs {second.n_chains})")
    only_a = sorted(set(first.arrays) - set(second.arrays))
    only_b = sorted(set(second.arrays) - set(first.arrays))
    if only_a or only_b:
        raise ValueError(
            "concat_posteriors: recorded parameter sets differ — "
            f"only in first: {only_a}; only in second: {only_b} "
            "(were the segments run with different record= selections?)")
    for k, v in first.arrays.items():
        w = second.arrays[k]
        if v.shape[2:] != w.shape[2:]:
            raise ValueError(
                f"concat_posteriors: parameter {k!r} has incompatible "
                f"shapes {v.shape} vs {w.shape} (differs beyond the "
                "(chains, samples) axes) — the segments come from "
                "different model configurations")
    if first.thin != second.thin:
        raise ValueError(
            f"concat_posteriors: thin strides differ ({first.thin} vs "
            f"{second.thin}) — the spliced sample axis would not be a "
            "single MCMC stride")
    if second.transient not in (0, first.transient):
        raise ValueError(
            f"concat_posteriors: second segment carries transient="
            f"{second.transient}; expected 0 (a continuation) or "
            f"{first.transient} (an independent replicate)")

    arrays = {k: np.concatenate([first.arrays[k], second.arrays[k]], axis=1)
              for k in first.arrays}
    from ..post.posterior import Posterior

    out = Posterior(first.hM, first.spec, arrays,
                    samples=first.samples + second.samples,
                    transient=first.transient, thin=first.thin)
    fb1 = np.asarray(first.chain_health["first_bad_it"])
    fb2 = np.asarray(second.chain_health["first_bad_it"])
    out.set_chain_health(np.where(fb1 >= 0, fb1, fb2))
    out.nf_saturation = {
        r: np.maximum(np.asarray(first.nf_saturation[r]),
                      np.asarray(second.nf_saturation[r]))
        if r in first.nf_saturation and r in second.nf_saturation
        else np.asarray(first.nf_saturation.get(r,
                        second.nf_saturation.get(r)))
        for r in set(first.nf_saturation) | set(second.nf_saturation)}
    # segments may have been sign-aligned against their own posterior-mean
    # Lambda; re-align per (chain, sample) over the spliced window so factor
    # signs are consistent across segments (bounded: stop once a pass makes
    # no flips instead of the former blind 5 iterations)
    if align and first.spec.nr > 0:
        _bounded_align(out, max_align_passes)
    return out
