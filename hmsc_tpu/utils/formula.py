"""Minimal model-formula support: R-style ``~ ...`` formulas -> design matrices.

The reference builds design matrices with R's ``model.matrix`` (reference
``R/Hmsc.R:202,214``).  We support the subset of Wilkinson notation that the
reference's vignettes and tests exercise:

- ``~ x1 + x2``           main effects (implicit intercept)
- ``~ x1 * x2``           main effects + interaction
- ``~ x1:x2``             interaction only
- ``~ . ``                all columns of the data frame
- ``~ x - 1`` / ``~ x + 0``   drop the intercept
- ``poly(x, n)``          raw orthogonal polynomial columns (numpy Legendre-free
                          QR orthogonalisation, like R's ``poly``)
- arbitrary numpy expressions via ``I(...)``, ``log(x)``, ``exp(x)`` etc.
- categorical expansion with treatment (drop-first) coding for string /
  categorical / boolean columns, matching R factor handling.

This is host-side, numpy-only code; it runs once at model construction.
"""

from __future__ import annotations

import re

import numpy as np

__all__ = ["design_matrix", "Formula", "align_factor_levels"]

_SAFE_FUNCS = {
    "log": np.log, "log2": np.log2, "log10": np.log10, "log1p": np.log1p,
    "exp": np.exp, "sqrt": np.sqrt, "abs": np.abs, "sin": np.sin,
    "cos": np.cos, "tan": np.tan, "scale": lambda a: (np.asarray(a, float) - np.mean(a)) / np.std(a, ddof=1),
}


def _tokenize_terms(rhs: str) -> tuple[list[str], bool]:
    """Split the RHS on top-level ``+``/``-`` into term strings.

    Returns (terms, intercept).  ``- 1`` / ``+ 0`` toggle the intercept off.
    """
    terms: list[str] = []
    intercept = True
    depth = 0
    cur = ""
    sign = "+"
    for ch in rhs + "+":
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch in "+-" and depth == 0:
            tok = cur.strip()
            if tok:
                if tok in ("1", "0"):
                    if (sign == "-" and tok == "1") or (sign == "+" and tok == "0"):
                        intercept = False
                    elif sign == "+" and tok == "1":
                        intercept = True
                elif sign == "-":
                    terms = [t for t in terms if t != tok]
                else:
                    terms.append(tok)
            cur = ""
            sign = ch
        else:
            cur += ch
    return terms, intercept


def _expand_star(term: str) -> list[str]:
    """``a*b`` -> ``a, b, a:b`` (only top-level ``*``)."""
    depth = 0
    parts = []
    cur = ""
    for ch in term:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == "*" and depth == 0:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
    parts.append(cur)
    parts = [p.strip() for p in parts if p.strip()]
    if len(parts) == 1:
        return parts
    out = list(parts)
    # all pairwise+higher interactions, in R's order (mains, then 2-way, ...)
    from itertools import combinations

    for k in range(2, len(parts) + 1):
        for combo in combinations(parts, k):
            out.append(":".join(combo))
    return out


def _split_interaction(term: str) -> list[str]:
    depth = 0
    parts = []
    cur = ""
    for ch in term:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == ":" and depth == 0:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
    parts.append(cur)
    return [p.strip() for p in parts if p.strip()]


def _poly(x, degree: int) -> np.ndarray:
    """Orthogonal polynomial basis like R's ``poly(x, degree)``."""
    x = np.asarray(x, dtype=float)
    xbar = x.mean()
    M = np.vander(x - xbar, degree + 1, increasing=True)
    Q, R = np.linalg.qr(M)
    Z = Q[:, 1:] * np.sign(np.diag(R)[1:])
    norms = np.sqrt((Z**2).sum(axis=0))
    return Z / norms


def _eval_factor(expr: str, df) -> tuple[list[str], list[np.ndarray], bool]:
    """Evaluate a single factor expression.

    Returns (column names, columns, is_categorical). Categorical factors return
    the *full* one-hot set; contrast dropping happens at term assembly.
    """
    expr = expr.strip()
    m = re.fullmatch(r"poly\(\s*([A-Za-z_.][\w.]*)\s*,\s*(\d+)\s*\)", expr)
    if m:
        name, deg = m.group(1), int(m.group(2))
        Z = _poly(np.asarray(df[name]), deg)
        return ([f"poly({name},{deg}){i+1}" for i in range(deg)],
                [Z[:, i] for i in range(deg)], False)
    if re.fullmatch(r"[A-Za-z_.][\w.]*", expr):  # bare column name
        col = df[expr]
        vals = np.asarray(col)
        if vals.dtype.kind in "OUSb" or str(getattr(col, "dtype", "")) == "category":
            cats = getattr(getattr(col, "cat", None), "categories", None)
            if cats is None:
                cats = sorted({str(v) for v in vals})
            else:
                cats = list(cats)
            cols = [np.asarray([str(v) == str(c) for v in vals], dtype=float) for c in cats]
            return ([f"{expr}{c}" for c in cats], cols, True)
        return ([expr], [vals.astype(float)], False)
    # I(...) wrapper or a general expression
    inner = expr
    if expr.startswith("I(") and expr.endswith(")"):
        inner = expr[2:-1]
    ns = dict(_SAFE_FUNCS)
    for c in df.columns if hasattr(df, "columns") else []:
        ns[str(c)] = np.asarray(df[c])
    val = eval(inner, {"__builtins__": {}}, ns)  # noqa: S307 - restricted namespace
    return ([expr], [np.asarray(val, dtype=float)], False)


class Formula:
    """Parsed model formula; call :meth:`design` to build the matrix."""

    def __init__(self, formula: str):
        formula = formula.strip()
        if formula.startswith("~"):
            formula = formula[1:]
        self.rhs = formula.strip()

    def design(self, df) -> tuple[np.ndarray, list[str]]:
        rhs = self.rhs
        if rhs == ".":
            rhs = " + ".join(str(c) for c in df.columns)
        raw_terms, intercept = _tokenize_terms(rhs)
        terms: list[str] = []
        for t in raw_terms:
            for e in _expand_star(t):
                if e not in terms:
                    terms.append(e)

        names: list[str] = []
        cols: list[np.ndarray] = []
        if intercept:
            n = len(df)
            names.append("(Intercept)")
            cols.append(np.ones(n))
        drop_contrast = intercept  # without an intercept the first categorical
        for term in terms:         # main effect keeps all its levels (R rule)
            factors = [_eval_factor(f, df) for f in _split_interaction(term)]
            pieces = []
            for fnames, fcols, is_cat in factors:
                if is_cat:
                    if not drop_contrast and len(factors) == 1:
                        drop_contrast = True
                    else:
                        fnames, fcols = fnames[1:], fcols[1:]
                pieces.append((fnames, fcols))
            # cross the pieces
            cur = [("", np.ones(len(df)))]
            for fnames, fcols in pieces:
                cur = [((f"{n0}:{n1}" if n0 else n1), c0 * c1)
                       for (n0, c0) in cur for (n1, c1) in zip(fnames, fcols)]
            for n1, c1 in cur:
                if n1 not in names:
                    names.append(n1)
                    cols.append(c1)
        Xm = np.column_stack(cols) if cols else np.empty((len(df), 0))
        return Xm.astype(float), names


def design_matrix(formula: str, df) -> tuple[np.ndarray, list[str]]:
    """R ``model.matrix(formula, df)`` equivalent (subset; see module doc)."""
    return Formula(formula).design(df)


def align_factor_levels(df, ref_df):
    """``df`` with every categorical column coerced to the *training*
    frame's level set (R's ``xlev=`` argument to ``model.matrix``).

    Prediction frames routinely hold a SUBSET of the fitted levels — a
    gradient frame sets a non-focal factor to one constant value — and
    deriving the one-hot set from the observed values would then build a
    design with fewer columns than the fitted Beta has rows (the
    ``predict(gradient=...)`` einsum shape failure).  Pandas categorical
    columns carry their level set explicitly, and :func:`design_matrix`
    already honours it; this helper installs the training levels.  A new
    value absent from the training levels is an error (the fitted model
    has no coefficient for it), matching R's ``model.matrix`` behaviour.
    """
    import pandas as pd

    if ref_df is None or not hasattr(df, "columns"):
        return df
    out = df.copy()
    for col in df.columns:
        if col not in getattr(ref_df, "columns", ()):
            continue
        ref = ref_df[col]
        ref_vals = np.asarray(ref)
        is_cat = (ref_vals.dtype.kind in "OUSb"
                  or str(getattr(ref, "dtype", "")) == "category")
        if not is_cat:
            continue
        cats = getattr(getattr(ref, "cat", None), "categories", None)
        if cats is None:
            cats = sorted({str(v) for v in ref_vals})
        else:
            cats = [str(c) for c in cats]
        new_vals = [str(v) for v in np.asarray(df[col])]
        unknown = sorted(set(new_vals) - set(cats))
        if unknown:
            raise ValueError(
                f"prediction data: factor {col!r} has level(s) {unknown} "
                f"absent from the fitted levels {cats} — the model has no "
                "coefficient for them")
        out[col] = pd.Categorical(new_vals, categories=cats)
    return out
