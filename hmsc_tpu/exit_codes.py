"""Process exit codes shared by every CLI entry point and the fleet.

A supervised fleet (and any external operator — a k8s restart policy, a
batch scheduler, a shell script) branches on worker exit codes, so they
are a contract, not an implementation detail: ``python -m hmsc_tpu run``,
the multi-process test workers (``hmsc_tpu.testing.multiproc``) and the
fleet supervisor (``hmsc_tpu.fleet``) all use THIS module's values.

- ``EXIT_OK`` (0) — run completed, posterior healthy.
- ``EXIT_FAILURE`` (1) — unclassified failure (a traceback).
- ``EXIT_PREEMPTED`` (75, ``EX_TEMPFAIL``) — preempted by SIGTERM/SIGINT
  after writing a resumable snapshot: *retry with ``--resume``*.
- ``EXIT_COORDINATION`` (76) — a multi-process collective failed (a peer
  died or timed out); committed checkpoints are intact, resumable.
- ``EXIT_DIVERGED`` (77) — the run completed but one or more chains ended
  non-finite and no retry healed them: the posterior excludes those
  chains, and a supervisor should NOT blindly restart (a deterministic
  blow-up would recur) — inspect, then retry with ``retry_diverged``.
- ``EXIT_CKPT_CORRUPT`` (78) — a resume found no usable checkpoint (every
  slot corrupt, or the directory mismatches the model): restarting will
  not help without operator intervention, so the supervisor treats it as
  fatal for that run directory.
- ``EXIT_DROP_REJECTED`` (79) — an autopilot data drop failed append
  validation against the run's pinned stream-defining parameters and was
  quarantined to ``rejected/`` with a machine-readable reason; the run
  itself is untouched, so processing continues with the next drop.
"""

from __future__ import annotations

EXIT_OK = 0
EXIT_FAILURE = 1
EXIT_PREEMPTED = 75          # EX_TEMPFAIL: resumable, try again
EXIT_COORDINATION = 76       # a peer died/stalled; checkpoints intact
EXIT_DIVERGED = 77           # completed with unhealed diverged chains
EXIT_CKPT_CORRUPT = 78       # no usable checkpoint to resume from
EXIT_DROP_REJECTED = 79      # data drop failed validation; quarantined

__all__ = ["EXIT_OK", "EXIT_FAILURE", "EXIT_PREEMPTED", "EXIT_COORDINATION",
           "EXIT_DIVERGED", "EXIT_CKPT_CORRUPT", "EXIT_DROP_REJECTED",
           "describe"]

_NAMES = {
    EXIT_OK: "ok",
    EXIT_FAILURE: "failure",
    EXIT_PREEMPTED: "preempted",
    EXIT_COORDINATION: "coordination",
    EXIT_DIVERGED: "diverged",
    EXIT_CKPT_CORRUPT: "checkpoint-corrupt",
    EXIT_DROP_REJECTED: "drop-rejected",
}


def describe(returncode: int) -> str:
    """Symbolic name for an exit code (negative = killed by that signal)."""
    rc = int(returncode)
    if rc < 0:
        import signal
        try:
            return f"signal:{signal.Signals(-rc).name}"
        except ValueError:
            return f"signal:{-rc}"
    return _NAMES.get(rc, f"exit:{rc}")
