"""Partition-spec tables and the shard context for the species-sharded
Gibbs sweep (``shard_map`` over a ``(chains, species)`` device mesh).

PR 8's named block schedule made every Gibbs block a seam; this module is
the committed answer to "which axis does each array live on" when the
sweep itself is sharded over the mesh's ``species`` axis:

- **Spec tables** (:data:`STATE_SPECIES_DIMS`, :data:`DATA_SPECIES_DIMS`,
  :data:`RECORD_SPECIES_DIMS`): the species dimension of every carry /
  model-data / recorded-sample array, by field name.  Anything not listed
  is **replicated** over the species axis (Eta and every per-unit array is
  deliberately replicated in v1 — the site axis is the next frontier).
- :class:`ShardCtx`: the static shard geometry handed to the updaters.
  Inside the ``shard_map`` body every updater sees a *local* spec
  (``spec.ns == ns_local``) plus this context for the three operations
  that must know about the mesh:

  * ``psum`` — the explicit cross-species reductions (the factor grams in
    updateEta, GammaV's ``B`` products, the rho/phylo quadratics, BetaSel
    likelihood deltas, divergence tracking);
  * ``gather_sp`` — all-gathers of *small* (O(ns·k)) per-species vectors
    where bit-identical replicated compute is cheaper than a psum
    (InvSigma's gamma shape vector, the DA-interweave truncation bounds);
  * full-width RNG (``uniform`` / ``normal`` / ``slice_sp`` of a
    full-width draw) — every random draw with a species dimension is
    drawn at the GLOBAL width with the replicated key and sliced to the
    local shard.  This keeps each shard's draws independent (a naive
    local-shape draw would reuse the same key for different species on
    every device) AND keeps the sharded draw stream equal to the
    replicated sweep's, so the two programs are comparable draw-by-draw.

**Tolerance contract** (:data:`SHARD_AGREEMENT_TOL`): the sharded sweep
targets the replicated sweep's exact draw stream; the only divergence
sources are the ``psum`` reductions, whose partial-sum order differs from
the replicated single-dot order by float rounding.  Agreement is
therefore ULP-level per sweep and drifts slowly with chain length;
``tests/test_shard.py`` pins all four canonical specs × {1,2,4,8}
emulated devices to this tolerance after a fixed sweep count.
"""

from __future__ import annotations

import dataclasses

__all__ = ["ShardCtx", "STATE_SPECIES_DIMS", "DATA_SPECIES_DIMS",
           "RECORD_SPECIES_DIMS", "SHARD_AGREEMENT_TOL",
           "shard_unsupported_reason", "tree_pspecs", "record_pspecs",
           "place_on_mesh", "collective_bytes", "nearest_divisor",
           "force_emulated_device_count", "COLLECTIVE_PRIMS"]

# tolerance for sharded-vs-replicated state agreement after a few sweeps
# on the canonical specs (tests/test_shard.py): max ABS error per state
# leaf, normalised by that leaf's max magnitude (an elementwise relative
# error would explode on near-zero entries whose absolute psum-rounding
# error is float-ULP).  Measured: psum-vs-fused-dot rounding is ~1e-7
# per reduction; a few sweeps of chaotic Gibbs amplification stay well
# inside 5e-3 (observed ~1e-5 after 5 sweeps).
SHARD_AGREEMENT_TOL = 5e-3

# species-dimension index per CARRY field (chain axis excluded); fields
# not listed are replicated over the species mesh axis
STATE_SPECIES_DIMS = {
    "Z": 1, "Beta": 1, "iSigma": 0, "Lambda": 1, "Psi": 1,
}

# species-dimension index per MODEL-DATA field.  Deliberately replicated
# despite carrying a species dim: Qeig/UTr (the rho-grid and phylo-trait
# projections are consumed at full width by every shard), y_scale_par
# (host-side back-transform only).  U is sharded by ROWS: E @ U
# contractions psum partial products; U.T column blocks serve the local
# writebacks.  X is sharded only for per-species design lists.
DATA_SPECIES_DIMS = {
    "Y": 1, "Ymask": 1, "Tr": 0, "distr_family": 0,
    "distr_estsig": 0, "sigma_fixed": 0, "aSigma": 0, "bSigma": 0,
    "U": 0, "sel_spg": 0,
}

# species-dimension index per RECORDED-SAMPLE key (before the leading
# (chain, sample) axes the runner adds); per-level names ("Lambda_0")
# resolve through their base name
RECORD_SPECIES_DIMS = {
    "Beta": 1, "sigma": 0, "Lambda": 1, "Psi": 1,
}

# collective primitives counted by the static comm ledger and recorded in
# the sharded jaxpr fingerprints
COLLECTIVE_PRIMS = ("psum", "all_gather", "all_to_all", "ppermute",
                    "all_gather_invariant", "reduce_scatter")


def force_emulated_device_count(n: int = 8) -> None:
    """Ensure the process sees at least ``n`` emulated CPU devices by
    appending ``--xla_force_host_platform_device_count=n`` to XLA_FLAGS —
    but only while the JAX backend is still uninitialised (afterwards the
    flag is inert, and callers gate on the actual device count instead).
    One shared helper so the lint CLI, the profile CLI, and any future
    entry point append the same flag the same way."""
    import os
    try:
        import jax
        fresh = not jax._src.xla_bridge.backends_are_initialized()  # noqa: SLF001
    except Exception:             # noqa: BLE001 — private API moved: assume
        fresh = True              # fresh and let the flag no-op if not
    if fresh:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def nearest_divisor(n: int, k: int) -> int:
    """The divisor of ``n`` nearest to ``k`` (ties prefer the larger —
    more parallelism); used by error/warning messages so the user is told
    a working value, not just that theirs failed."""
    n, k = int(n), int(k)
    divs = [d for d in range(1, n + 1) if n % d == 0]
    return min(divs, key=lambda d: (abs(d - k), -d))


@dataclasses.dataclass(frozen=True)
class ShardCtx:
    """Static geometry of the species sharding, closed over by the
    updaters inside the ``shard_map`` body.  ``ns`` is the GLOBAL species
    count (the local spec's ``spec.ns`` is ``ns // n``).

    ``local_rng`` (opt-in, ``sample_mcmc(local_rng=True)``) switches
    every species-dim random draw from the default full-width-and-slice
    scheme to a LOCAL draw: the shard index is folded into the block's
    key (distinct streams per shard by construction) and only
    ``ns_local``-wide randoms are generated.  This trades the
    replicated-draw equality contract — the sharded stream no longer
    equals the replicated sweep's, so sharded-vs-replicated agreement
    only holds in distribution — for O(ns_local) draw cost (the
    full-width draws are the main weak-scaling overhead at RNG-bound
    sizes).  Determinism is unchanged: the same mesh/seed reproduces the
    same stream, and kill -> resume stays bit-identical
    (``tests/test_shard.py::test_local_rng_resume_roundtrip``)."""
    axis: str                   # mesh axis name ("species")
    n: int                      # number of shards
    ns: int                     # GLOBAL species count
    local_rng: bool = False     # fold shard index, draw at local width

    @property
    def ns_local(self) -> int:
        return self.ns // self.n

    # -- traced helpers -------------------------------------------------
    def offset(self):
        import jax
        return jax.lax.axis_index(self.axis) * self.ns_local

    def slice_sp(self, x, dim: int):
        """This shard's species block of a full-width array."""
        import jax
        return jax.lax.dynamic_slice_in_dim(x, self.offset(), self.ns_local,
                                            axis=dim)

    def psum(self, x):
        import jax
        return jax.lax.psum(x, self.axis)

    def gather_sp(self, x, dim: int):
        """Full-width reassembly of a species-sharded array (tiled
        all-gather: shard i lands at block i, exactly the replicated
        layout)."""
        import jax
        return jax.lax.all_gather(x, self.axis, axis=dim, tiled=True)

    def all_ok(self, ok):
        """Cross-shard AND of a boolean (divergence tracking)."""
        import jax.numpy as jnp
        bad = jnp.where(ok, 0, 1).astype(jnp.int32)
        return self.psum(bad) == 0

    # -- species-dim RNG ------------------------------------------------
    # default: drawn at the GLOBAL width with the replicated key and
    # sliced (replicated-draw equality); local_rng: shard-folded key,
    # local width (O(ns_local) draw cost, streams differ from replicated)
    def fold(self, key):
        """The shard-local key for ``local_rng`` draws: the mesh axis
        index folded into the replicated key."""
        import jax
        return jax.random.fold_in(key, jax.lax.axis_index(self.axis))

    def local_shape(self, shape, dim: int) -> tuple:
        """``shape`` with the species dimension cut to this shard."""
        shape = tuple(shape)
        return shape[:dim] + (self.ns_local,) + shape[dim + 1:]

    def uniform(self, key, shape, dtype, dim: int, **kw):
        import jax
        if self.local_rng:
            return jax.random.uniform(self.fold(key),
                                      self.local_shape(shape, dim),
                                      dtype=dtype, **kw)
        return self.slice_sp(jax.random.uniform(key, shape, dtype=dtype,
                                                **kw), dim)

    def normal(self, key, shape, dtype, dim: int):
        import jax
        if self.local_rng:
            return jax.random.normal(self.fold(key),
                                     self.local_shape(shape, dim),
                                     dtype=dtype)
        return self.slice_sp(jax.random.normal(key, shape, dtype=dtype),
                             dim)


def shard_unsupported_reason(spec, updater: dict | None) -> str | None:
    """Why this model class cannot run the species-sharded sweep, or
    ``None`` when eligible.  Single source for the sampler's gate and its
    fallback warning."""
    updater = updater or {}
    if spec.has_phylo and (spec.has_na or spec.x_is_list
                           or not spec.homoskedastic_fixed):
        return ("the phylogenetic Beta draw falls back to the dense "
                "(nc*ns)^2 system on NA/per-species-X/heteroskedastic "
                "models, which has no sharded formulation")
    for name in ("Gamma2", "GammaEta"):
        if updater.get(name) is True:
            return (f"the opt-in collapsed updater {name} has no "
                    "shard-aware implementation")
    return None


def _leaf_name(path) -> str | None:
    for p in reversed(path):
        n = getattr(p, "name", None)
        if n is None:
            n = getattr(p, "key", None)
            n = n if isinstance(n, str) else None
        if n is not None:
            return n
    return None


def tree_pspecs(tree, spec, species_axis: str, dims: dict,
                lead: str | None = None, x_is_list: bool = False):
    """Per-leaf ``PartitionSpec`` pytree for a state/data tree: optional
    leading chain axis, species dims from ``dims`` (guarded on the dim
    actually being ``spec.ns``-sized), everything else replicated."""
    import jax
    from jax.sharding import PartitionSpec as P

    def one(path, leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return P()
        ax = [None] * leaf.ndim
        off = 0
        if lead is not None:
            ax[0] = lead
            off = 1
        name = _leaf_name(path)
        d = dims.get(name)
        if name == "X":
            d = 0 if x_is_list else None
        if d is not None and d + off < leaf.ndim \
                and leaf.shape[d + off] == spec.ns:
            ax[d + off] = species_axis
        return P(*ax)

    return jax.tree_util.tree_map_with_path(one, tree)


def record_pspecs(chain_axis: str, species_axis: str):
    """``name, rank -> PartitionSpec`` resolver for the runner's
    recorded-sample leaves: leading (chain, sample) axes then
    :data:`RECORD_SPECIES_DIMS` (per-level names like ``Lambda_0``
    resolve through their base name).  The caller enumerates the record
    dict's keys/ranks (the runner abstract-evals ``record_sample`` with
    its ``record=`` filter applied) and maps each through this."""
    from jax.sharding import PartitionSpec as P

    def spec_for(name, rank):
        head, _, tail = name.rpartition("_")
        base = head if tail.isdigit() else name
        ax = [None] * rank
        ax[0] = chain_axis
        d = RECORD_SPECIES_DIMS.get(base)
        if d is not None:
            ax[d + 2] = species_axis
        return P(*ax)
    return spec_for


def place_on_mesh(tree, mesh, spec, species_axis: str, dims: dict,
                  lead: str | None = None, x_is_list: bool = False):
    """Device-put a tree onto the mesh according to its spec table (the
    eager counterpart of the in_specs the sharded runner uses, so the
    first segment pays no resharding)."""
    import jax
    from jax.sharding import NamedSharding

    specs = tree_pspecs(tree, spec, species_axis, dims, lead=lead,
                        x_is_list=x_is_list)

    def put(leaf, ps):
        if not hasattr(leaf, "ndim"):
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh, ps))

    return jax.tree.map(put, tree, specs)


def collective_bytes(closed) -> dict:
    """Static communication ledger of a traced program: per-collective
    byte counts summed over every collective eqn in the (recursively
    walked) jaxpr.  Bytes are the per-device operand bytes entering each
    collective — the quantity a shard pays per sweep on the wire."""
    import numpy as np

    totals: dict[str, int] = {}

    def walk(jaxpr):
        from jax import core as jcore
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                nb = 0
                for v in eqn.invars:
                    aval = getattr(v, "aval", None)
                    if aval is not None and hasattr(aval, "shape"):
                        nb += int(np.prod(aval.shape, dtype=np.int64)
                                  * np.dtype(aval.dtype).itemsize)
                totals[name] = totals.get(name, 0) + nb
            for v in eqn.params.values():
                _walk_param(v)

    def _walk_param(v):
        from jax import core as jcore
        if isinstance(v, jcore.ClosedJaxpr):
            walk(v.jaxpr)
        elif isinstance(v, jcore.Jaxpr):
            walk(v)
        elif isinstance(v, (tuple, list)):
            for x in v:
                _walk_param(x)

    walk(closed.jaxpr)
    return {"comm_bytes": int(sum(totals.values())),
            "collectives": dict(sorted(totals.items()))}
