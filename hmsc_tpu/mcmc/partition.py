"""Partition-spec tables and the shard context for the sharded Gibbs
sweep (``shard_map`` over a ``(chains, species)`` or
``(chains, species, sites)`` device mesh).

PR 8's named block schedule made every Gibbs block a seam; this module is
the committed answer to "which axis does each array live on" when the
sweep itself is sharded over the mesh's ``species`` (and optionally
``sites``) axes:

- **Spec tables** (:data:`STATE_SPECIES_DIMS`, :data:`DATA_SPECIES_DIMS`,
  :data:`RECORD_SPECIES_DIMS`): the species dimension of every carry /
  model-data / recorded-sample array, by field name.  Their site-axis
  counterparts (:data:`STATE_SITE_DIMS`, :data:`DATA_SITE_DIMS`,
  :data:`RECORD_SITE_DIMS`) name the SAMPLING-ROW / UNIT dimension
  sharded over the mesh's ``sites`` axis: Z's rows, per-level ``Eta``
  rows, the (ny,)-shaped row data (Y/Ymask/X/pi_row/x_row), and the
  NNGP/GPP per-unit structure grids.  Anything not listed in either
  table is replicated over that axis.
- :class:`ShardCtx`: the static shard geometry handed to the updaters.
  Inside the ``shard_map`` body every updater sees a *local* spec
  (``spec.ns == ns_local``, ``spec.ny == ny_local`` under site sharding;
  per-level ``n_units`` stays GLOBAL — unit blocks are sliced
  explicitly) plus this context for the operations that must know about
  the mesh:

  * ``psum`` / ``psum_site`` / ``psum_all`` — the explicit cross-species
    reductions (the factor grams in updateEta, GammaV's ``B`` products,
    the rho/phylo quadratics, BetaSel likelihood deltas) and the
    cross-SITE reductions (the design grams summing over rows, updateZ's
    per-species column statistics, the Alpha grid quadratics, divergence
    tracking ``all_ok`` psum'd over both axes);
  * ``gather_sp`` / ``gather_site`` — all-gathers of *small* per-species
    vectors (InvSigma's gamma shape vector, the DA-interweave truncation
    bounds) and of the (np, nf) ``Eta`` rows wherever a ``Pi`` row
    gather must read units owned by another site shard (level loadings,
    ``eta_star``, the NNGP neighbour reads);
  * full-width RNG (``uniform`` / ``normal`` with a species ``dim``
    and/or a ``site_dim``) — every random draw with a species or site
    dimension is drawn at the GLOBAL width with the replicated key and
    sliced to the local shard.  This keeps each shard's draws
    independent AND keeps the sharded draw stream equal to the
    replicated sweep's — on a 2D mesh the equality holds per (species,
    site) block, so the two programs stay comparable draw-by-draw.

**Tolerance contract** (:data:`SHARD_AGREEMENT_TOL`): the sharded sweep
targets the replicated sweep's exact draw stream; the only divergence
sources are the ``psum`` reductions, whose partial-sum order differs from
the replicated single-dot order by float rounding.  Agreement is
therefore ULP-level per sweep and drifts slowly with chain length;
``tests/test_shard.py`` pins all four canonical specs × {1,2,4,8}
emulated devices (and the spatial canonical specs on the 2D
species × sites meshes) to this tolerance after a fixed sweep count.
"""

from __future__ import annotations

import dataclasses

__all__ = ["ShardCtx", "STATE_SPECIES_DIMS", "DATA_SPECIES_DIMS",
           "RECORD_SPECIES_DIMS", "STATE_SITE_DIMS", "DATA_SITE_DIMS",
           "RECORD_SITE_DIMS", "SERVE_DRAW_DIMS", "SHARD_AGREEMENT_TOL",
           "shard_unsupported_reason", "site_shard_unsupported_reason",
           "engaged_site_extent", "tree_pspecs", "record_pspecs",
           "serve_draw_pspec", "serve_draw_pspecs",
           "place_on_mesh", "collective_bytes", "nearest_divisor",
           "nearest_site_divisor",
           "force_emulated_device_count", "COLLECTIVE_PRIMS"]

# tolerance for sharded-vs-replicated state agreement after a few sweeps
# on the canonical specs (tests/test_shard.py): max ABS error per state
# leaf, normalised by that leaf's max magnitude (an elementwise relative
# error would explode on near-zero entries whose absolute psum-rounding
# error is float-ULP).  Measured: psum-vs-fused-dot rounding is ~1e-7
# per reduction; a few sweeps of chaotic Gibbs amplification stay well
# inside 5e-3 (observed ~1e-5 after 5 sweeps).
SHARD_AGREEMENT_TOL = 5e-3

# species-dimension index per CARRY field (chain axis excluded); fields
# not listed are replicated over the species mesh axis
STATE_SPECIES_DIMS = {
    "Z": 1, "Beta": 1, "iSigma": 0, "Lambda": 1, "Psi": 1,
}

# species-dimension index per MODEL-DATA field.  Deliberately replicated
# despite carrying a species dim: Qeig/UTr (the rho-grid and phylo-trait
# projections are consumed at full width by every shard), y_scale_par
# (host-side back-transform only).  U is sharded by ROWS: E @ U
# contractions psum partial products; U.T column blocks serve the local
# writebacks.  X is sharded only for per-species design lists.
DATA_SPECIES_DIMS = {
    "Y": 1, "Ymask": 1, "Tr": 0, "distr_family": 0,
    "distr_estsig": 0, "sigma_fixed": 0, "aSigma": 0, "bSigma": 0,
    "U": 0, "sel_spg": 0,
}

# species-dimension index per RECORDED-SAMPLE key (before the leading
# (chain, sample) axes the runner adds); per-level names ("Lambda_0")
# resolve through their base name
RECORD_SPECIES_DIMS = {
    "Beta": 1, "sigma": 0, "Lambda": 1, "Psi": 1,
}

# SITE-dimension index per CARRY field: the sampling-row dimension of Z
# and the unit dimension of every per-level Eta, sharded over the mesh's
# `sites` axis.  Guarded in tree_pspecs on the dim actually being
# ny-sized ("row" kind) or that level's n_units ("unit" kind).
STATE_SITE_DIMS = {"Z": 0, "Eta": 0}

# SITE-dimension index per MODEL-DATA field.  Row data (Y/Ymask/X/
# pi_row/x_row) shards by sampling row; the NNGP/GPP per-unit structure
# grids shard by unit so the Vecchia apply / knot solves read local
# blocks.  Deliberately replicated despite a site-sized dim: unit_count
# and x_unit (tiny (np,)-shaped, consumed at full width by global
# statistics), iWg (the Full-method dense precision needs both unit axes
# — Full solves run replicated under site sharding).
DATA_SITE_DIMS = {
    "Y": 0, "Ymask": 0, "X": 0, "pi_row": 0, "x_row": 0,
    "nn_idx": 0, "nn_coef": 1, "nn_D": 1, "idDg": 1, "idDW12g": 1,
}

# fields whose site dim is UNIT-sized (guarded against the owning
# level's n_units); everything else in the site tables is row-sized
_SITE_UNIT_NAMES = {"Eta", "nn_idx", "nn_coef", "nn_D", "idDg", "idDW12g"}

# site-dimension index per RECORDED-SAMPLE key (per-level Eta rows)
RECORD_SITE_DIMS = {"Eta": 0}

# DRAW-dimension index per staged SERVING param (serve/engine.py's
# ``_Staged``): every pooled posterior tensor leads with the draw axis,
# embarrassingly parallel at query time.  Per-level names ("Lambda_0")
# resolve through their base name like the record tables.  Anything not
# listed (fam/ym/ys and the per-request X/unit_idx/key operands) is
# replicated across the draw mesh.
SERVE_DRAW_DIMS = {"Beta": 0, "sigma": 0, "Lambda": 0, "Eta": 0}

# collective primitives counted by the static comm ledger and recorded in
# the sharded jaxpr fingerprints
COLLECTIVE_PRIMS = ("psum", "all_gather", "all_to_all", "ppermute",
                    "all_gather_invariant", "reduce_scatter")


def force_emulated_device_count(n: int = 8) -> None:
    """Ensure the process sees at least ``n`` emulated CPU devices by
    appending ``--xla_force_host_platform_device_count=n`` to XLA_FLAGS —
    but only while the JAX backend is still uninitialised (afterwards the
    flag is inert, and callers gate on the actual device count instead).
    One shared helper so the lint CLI, the profile CLI, and any future
    entry point append the same flag the same way."""
    import os
    try:
        import jax
        fresh = not jax._src.xla_bridge.backends_are_initialized()  # noqa: SLF001
    except Exception:             # noqa: BLE001 — private API moved: assume
        fresh = True              # fresh and let the flag no-op if not
    if fresh:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def nearest_divisor(n: int, k: int) -> int:
    """The divisor of ``n`` nearest to ``k`` (ties prefer the larger —
    more parallelism); used by error/warning messages so the user is told
    a working value, not just that theirs failed."""
    n, k = int(n), int(k)
    divs = [d for d in range(1, n + 1) if n % d == 0]
    return min(divs, key=lambda d: (abs(d - k), -d))


def nearest_site_divisor(ny: int, np_r, k: int) -> int:
    """The ``site_shards`` nearest to ``k`` that divides ny AND every
    level's unit count (a site shard must hold an even block of rows and
    of each level's units) — i.e. the nearest divisor of their gcd.
    Used by the non-divisible fallback warning so the user is told a
    working value, mirroring the species-axis message."""
    import math
    g = int(ny)
    for n in np_r:
        g = math.gcd(g, int(n))
    return nearest_divisor(g, k)


@dataclasses.dataclass(frozen=True)
class ShardCtx:
    """Static geometry of the sharding, closed over by the updaters
    inside the ``shard_map`` body.  ``ns`` is the GLOBAL species count
    (the local spec's ``spec.ns`` is ``ns // n``).

    A 2D mesh adds the site axis: ``site_axis``/``m`` name the mesh's
    second model-parallel axis and its extent, ``ny`` the GLOBAL
    sampling-row count (the local spec's ``spec.ny`` is ``ny // m``) and
    ``np_r`` the GLOBAL per-level unit counts (per-level ``n_units``
    stays GLOBAL in the local spec — unit blocks are sliced explicitly
    with :meth:`slice_site`).  ``site_axis=None`` (or ``m == 1``) is the
    committed species-only geometry, byte-identical to every prior
    release; every site helper is then the identity, so the v1
    fingerprints are untouched.  ``n == 1`` likewise disables the
    species collectives (a site-only mesh), keeping replicated values
    from being multiply-counted over an axis the arrays never shard on.

    ``local_rng`` (opt-in, ``sample_mcmc(local_rng=True)``) switches
    every species/site-dim random draw from the default
    full-width-and-slice scheme to a LOCAL draw: the shard index of each
    axis the drawn array actually shards over is folded into the block's
    key (distinct streams per shard by construction, identical streams
    across shards for dims the array replicates) and only local-width
    randoms are generated.  This trades the replicated-draw equality
    contract — the sharded stream no longer equals the replicated
    sweep's, so sharded-vs-replicated agreement only holds in
    distribution — for O(local) draw cost (the full-width draws are the
    main weak-scaling overhead at RNG-bound sizes).  Determinism is
    unchanged: the same mesh/seed reproduces the same stream, and
    kill -> resume stays bit-identical — which is why resume pins BOTH
    shard counts of the mesh tuple
    (``tests/test_shard.py::test_local_rng_resume_roundtrip``)."""
    axis: str                   # mesh axis name ("species")
    n: int                      # number of species shards
    ns: int                     # GLOBAL species count
    local_rng: bool = False     # fold shard index, draw at local width
    site_axis: str | None = None  # second mesh axis ("sites"), if any
    m: int = 1                  # number of site shards
    ny: int = 0                 # GLOBAL sampling-row count (site mode)
    np_r: tuple = ()            # GLOBAL per-level unit counts (site mode)

    @property
    def ns_local(self) -> int:
        return self.ns // self.n

    @property
    def has_sites(self) -> bool:
        return self.site_axis is not None and self.m > 1

    @property
    def ny_local(self) -> int:
        return self.ny // self.m

    # -- traced helpers -------------------------------------------------
    def offset(self):
        import jax
        return jax.lax.axis_index(self.axis) * self.ns_local

    def site_offset(self, size: int):
        """This site shard's block start within a ``size``-long global
        dimension (rows or a level's units — both divide evenly)."""
        import jax
        return jax.lax.axis_index(self.site_axis) * (int(size) // self.m)

    def slice_sp(self, x, dim: int):
        """This shard's species block of a full-width array."""
        import jax
        if self.n == 1:
            return x
        return jax.lax.dynamic_slice_in_dim(x, self.offset(), self.ns_local,
                                            axis=dim)

    def slice_site(self, x, dim: int):
        """This shard's site block of a full-width array (rows or
        units: the local width is ``x.shape[dim] // m``)."""
        import jax
        if not self.has_sites:
            return x
        width = int(x.shape[dim]) // self.m
        return jax.lax.dynamic_slice_in_dim(
            x, self.site_offset(x.shape[dim]), width, axis=dim)

    def psum(self, x):
        import jax
        if self.n == 1:
            return x
        return jax.lax.psum(x, self.axis)

    def psum_site(self, x):
        """Cross-SITE reduction (identity on a species-only mesh)."""
        import jax
        if not self.has_sites:
            return x
        return jax.lax.psum(x, self.site_axis)

    def psum_all(self, x):
        """Reduction over every model-parallel axis the mesh shards on
        (one fused collective on a 2D mesh; exactly :meth:`psum` on the
        committed species-only geometry)."""
        import jax
        axes = (() if self.n == 1 else (self.axis,)) \
            + ((self.site_axis,) if self.has_sites else ())
        if not axes:
            return x
        return jax.lax.psum(x, axes[0] if len(axes) == 1 else axes)

    def pmax_site(self, x):
        import jax
        if not self.has_sites:
            return x
        return jax.lax.pmax(x, self.site_axis)

    def pmin_site(self, x):
        import jax
        if not self.has_sites:
            return x
        return jax.lax.pmin(x, self.site_axis)

    def gather_sp(self, x, dim: int):
        """Full-width reassembly of a species-sharded array (tiled
        all-gather: shard i lands at block i, exactly the replicated
        layout)."""
        import jax
        if self.n == 1:
            return x
        return jax.lax.all_gather(x, self.axis, axis=dim, tiled=True)

    def gather_site(self, x, dim: int):
        """Full-width reassembly of a site-sharded array — the explicit
        ``Pi`` row-gather collective: Eta rows (and the NNGP structure
        grids on the dense path) reassemble to the replicated layout
        wherever a row-indexed read may cross site shards."""
        import jax
        if not self.has_sites:
            return x
        return jax.lax.all_gather(x, self.site_axis, axis=dim, tiled=True)

    def all_ok(self, ok):
        """Cross-shard AND of a boolean (divergence tracking), psum'd
        over BOTH mesh axes on a 2D mesh — a NaN on any (species, site)
        block must mark the chain on every shard."""
        import jax.numpy as jnp
        bad = jnp.where(ok, 0, 1).astype(jnp.int32)
        return self.psum_all(bad) == 0

    # -- species/site-dim RNG -------------------------------------------
    # default: drawn at the GLOBAL width with the replicated key and
    # sliced (replicated-draw equality); local_rng: shard-folded key,
    # local width (O(local) draw cost, streams differ from replicated)
    def fold(self, key):
        """The shard-local key for species-dim ``local_rng`` draws: the
        species axis index folded into the replicated key."""
        import jax
        return jax.random.fold_in(key, jax.lax.axis_index(self.axis))

    def fold_site(self, key):
        """The shard-local key for site-dim ``local_rng`` draws (offset
        past the species index range so a (species, site) pair never
        collides with a pure species fold)."""
        import jax
        return jax.random.fold_in(
            key, self.n + jax.lax.axis_index(self.site_axis))

    def local_shape(self, shape, dim: int) -> tuple:
        """``shape`` with the species dimension cut to this shard."""
        shape = tuple(shape)
        return shape[:dim] + (self.ns_local,) + shape[dim + 1:]

    def _local_rng_draw(self, draw, key, shape, dim, site_dim, **kw):
        shp = tuple(shape)
        if dim is not None and self.n > 1:
            key = self.fold(key)
            shp = self.local_shape(shp, dim)
        if site_dim is not None and self.has_sites:
            key = self.fold_site(key)
            shp = shp[:site_dim] + (shp[site_dim] // self.m,) \
                + shp[site_dim + 1:]
        return draw(key, shp, **kw)

    def _sliced_draw(self, draw, key, shape, dim, site_dim, **kw):
        x = draw(key, tuple(shape), **kw)
        if dim is not None:
            x = self.slice_sp(x, dim)
        if site_dim is not None:
            x = self.slice_site(x, site_dim)
        return x

    def uniform(self, key, shape, dtype, dim: int | None,
                site_dim: int | None = None, **kw):
        import jax

        def draw(k, s, **kw2):
            return jax.random.uniform(k, s, dtype=dtype, **kw2)
        if self.local_rng:
            return self._local_rng_draw(draw, key, shape, dim, site_dim,
                                        **kw)
        return self._sliced_draw(draw, key, shape, dim, site_dim, **kw)

    def normal(self, key, shape, dtype, dim: int | None,
               site_dim: int | None = None):
        import jax

        def draw(k, s):
            return jax.random.normal(k, s, dtype=dtype)
        if self.local_rng:
            return self._local_rng_draw(draw, key, shape, dim, site_dim)
        return self._sliced_draw(draw, key, shape, dim, site_dim)


def shard_unsupported_reason(spec, updater: dict | None) -> str | None:
    """Why this model class cannot run the species-sharded sweep, or
    ``None`` when eligible.  Single source for the sampler's gate and its
    fallback warning."""
    updater = updater or {}
    if spec.has_phylo and (spec.has_na or spec.x_is_list
                           or not spec.homoskedastic_fixed):
        return ("the phylogenetic Beta draw falls back to the dense "
                "(nc*ns)^2 system on NA/per-species-X/heteroskedastic "
                "models, which has no sharded formulation")
    for name in ("Gamma2", "GammaEta"):
        if updater.get(name) is True:
            return (f"the opt-in collapsed updater {name} has no "
                    "shard-aware implementation")
    return None


def site_shard_unsupported_reason(spec, updater: dict | None) -> str | None:
    """Why this model class cannot shard the SITE axis (on top of every
    species-axis reason), or ``None`` when eligible.  The sampler falls
    back to species-only sharding with a warning; ``shard_sweep=True``
    makes it an error."""
    reason = shard_unsupported_reason(spec, updater)
    if reason is not None:
        return reason
    if spec.x_is_list:
        return ("per-species design matrices have no site-sharded row "
                "layout")
    if spec.ncsel > 0 or spec.nc_rrr > 0:
        return ("the selection/RRR effective-design updaters have no "
                "site-sharded formulation")
    if any(ls.x_dim > 0 for ls in spec.levels):
        return ("covariate-dependent random levels (xDim > 0) keep "
                "per-unit designs the site axis cannot block")
    return None


def engaged_site_extent(spec, mesh, species_axis: str = "species",
                        site_axis: str = "sites", updater: dict | None = None,
                        has_policy: bool = False) -> int:
    """The site-shard extent the sampler WOULD engage for this model on
    this mesh — 1 whenever any of its fallbacks fire (no/extent-1 site
    axis, missing species axis, a species-axis divisibility fallback
    dragging the sites down with it, non-divisible ny/unit counts, or a
    site-ineligible model class).  ``has_policy`` is accepted for API
    compatibility and ignored: the staged shadow table shards its site
    dims like the f32 originals (``staged_pspecs``), so a precision
    policy no longer forces the species-only fallback.  The
    decision mirror of ``sample_mcmc``'s site gating, used by
    ``resume_run``'s local_rng mesh-tuple pinning so a continuation on a
    mesh that falls back identically is not falsely rejected."""
    axes = getattr(mesh, "axis_names", ()) if mesh is not None else ()
    if site_axis not in axes or species_axis not in axes:
        return 1
    m = int(mesh.shape[site_axis])
    if m < 2:
        return 1
    sp_ext = int(mesh.shape[species_axis])
    if sp_ext > 1 and spec.ns % sp_ext:
        return 1                  # species fallback replicates sites too
    if spec.ny % m or any(ls.n_units % m for ls in spec.levels):
        return 1
    if site_shard_unsupported_reason(spec, updater) is not None:
        return 1
    return m


def _leaf_name(path) -> str | None:
    for p in reversed(path):
        n = getattr(p, "name", None)
        if n is None:
            n = getattr(p, "key", None)
            n = n if isinstance(n, str) else None
        if n is not None:
            return n
    return None


def _level_index(path) -> int | None:
    """The ``levels[r]`` tuple index along a tree path, if any (the
    site-dim guards need the owning level's unit count)."""
    prev_levels = False
    for p in path:
        if prev_levels:
            idx = getattr(p, "idx", None)
            return int(idx) if idx is not None else None
        n = getattr(p, "name", None)
        if n is None:
            k = getattr(p, "key", None)
            n = k if isinstance(k, str) else None
        prev_levels = n == "levels"
    return None


def tree_pspecs(tree, spec, species_axis: str, dims: dict,
                lead: str | None = None, x_is_list: bool = False,
                site_axis: str | None = None, site_dims: dict | None = None):
    """Per-leaf ``PartitionSpec`` pytree for a state/data tree: optional
    leading chain axis, species dims from ``dims`` (guarded on the dim
    actually being ``spec.ns``-sized), site dims from ``site_dims`` when
    a ``site_axis`` is given (guarded on the dim being ``spec.ny``-sized
    for row arrays / the owning level's ``n_units`` for unit arrays),
    everything else replicated."""
    import jax
    from jax.sharding import PartitionSpec as P

    def one(path, leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return P()
        ax = [None] * leaf.ndim
        off = 0
        if lead is not None:
            ax[0] = lead
            off = 1
        name = _leaf_name(path)
        d = dims.get(name)
        if name == "X":
            d = 0 if x_is_list else None
        if d is not None and d + off < leaf.ndim \
                and leaf.shape[d + off] == spec.ns:
            ax[d + off] = species_axis
        if site_axis is not None and site_dims is not None:
            ds = site_dims.get(name)
            if name == "X" and x_is_list:
                ds = None          # (ns, ny, nc) lists are site-gated off
            if ds is not None and ds + off < leaf.ndim:
                if name in _SITE_UNIT_NAMES:
                    r = _level_index(path)
                    want = (spec.levels[r].n_units
                            if r is not None and r < len(spec.levels)
                            else -1)
                else:
                    want = spec.ny
                if leaf.shape[ds + off] == want and ax[ds + off] is None:
                    ax[ds + off] = site_axis
        return P(*ax)

    return jax.tree_util.tree_map_with_path(one, tree)


def record_pspecs(chain_axis: str, species_axis: str,
                  site_axis: str | None = None):
    """``name, rank -> PartitionSpec`` resolver for the runner's
    recorded-sample leaves: leading (chain, sample) axes then
    :data:`RECORD_SPECIES_DIMS` / :data:`RECORD_SITE_DIMS` (per-level
    names like ``Lambda_0`` resolve through their base name).  The
    caller enumerates the record dict's keys/ranks (the runner
    abstract-evals ``record_sample`` with its ``record=`` filter
    applied) and maps each through this."""
    from jax.sharding import PartitionSpec as P

    def spec_for(name, rank):
        head, _, tail = name.rpartition("_")
        base = head if tail.isdigit() else name
        ax = [None] * rank
        ax[0] = chain_axis
        d = RECORD_SPECIES_DIMS.get(base)
        if d is not None:
            ax[d + 2] = species_axis
        if site_axis is not None:
            ds = RECORD_SITE_DIMS.get(base)
            if ds is not None and ax[ds + 2] is None:
                ax[ds + 2] = site_axis
        return P(*ax)
    return spec_for


def serve_draw_pspec(name: str, axis: str = "draws"):
    """``PartitionSpec`` for one staged serving param by name: the draw
    dim from :data:`SERVE_DRAW_DIMS` carries the mesh axis (per-level
    names like ``Lambda_0`` resolve through their base name), anything
    unlisted is replicated."""
    from jax.sharding import PartitionSpec as P
    head, _, tail = name.rpartition("_")
    base = head if tail.isdigit() else name
    d = SERVE_DRAW_DIMS.get(base)
    if d is None:
        return P()
    ax = [None] * (d + 1)
    ax[d] = axis
    return P(*ax)


def serve_draw_pspecs(nr: int, axis: str = "draws", *,
                      conditional: bool = False):
    """``in_specs`` tuple for the sharded serving kernels, matching the
    positional arg order of ``serve/kernels.py`` factories:
    ``(Beta, sigma, lams, etas, fam, ym, ys, X, unit_idx[, Yc, mask],
    key)``.  Posterior params shard on their leading draw dim via
    :data:`SERVE_DRAW_DIMS`; the per-request operands and the RNG key
    are replicated (every shard sees the full query batch)."""
    from jax.sharding import PartitionSpec as P
    draw = serve_draw_pspec("Beta", axis)
    specs = (draw,                      # Beta   (n, nc, ns)
             draw,                      # sigma  (n, ns)
             (draw,) * nr,              # lams   [(n, nf_r, ns)]
             (draw,) * nr,              # etas   [(n, np_r+1, nf_r)]
             P(),                       # fam
             P(), P(),                  # ym, ys
             P(),                       # X
             P())                       # unit_idx
    if conditional:
        specs = specs + (P(), P())      # Yc, mask
    return specs + (P(),)               # key


def place_on_mesh(tree, mesh, spec, species_axis: str, dims: dict,
                  lead: str | None = None, x_is_list: bool = False,
                  site_axis: str | None = None, site_dims: dict | None = None):
    """Device-put a tree onto the mesh according to its spec table (the
    eager counterpart of the in_specs the sharded runner uses, so the
    first segment pays no resharding)."""
    import jax
    from jax.sharding import NamedSharding

    specs = tree_pspecs(tree, spec, species_axis, dims, lead=lead,
                        x_is_list=x_is_list, site_axis=site_axis,
                        site_dims=site_dims)

    def put(leaf, ps):
        if not hasattr(leaf, "ndim"):
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh, ps))

    return jax.tree.map(put, tree, specs)


def collective_bytes(closed) -> dict:
    """Static communication ledger of a traced program: per-collective
    byte counts summed over every collective eqn in the (recursively
    walked) jaxpr.  Bytes are the per-device operand bytes entering each
    collective — the quantity a shard pays per sweep on the wire."""
    import numpy as np

    totals: dict[str, int] = {}

    def walk(jaxpr):
        from jax import core as jcore
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                nb = 0
                for v in eqn.invars:
                    aval = getattr(v, "aval", None)
                    if aval is not None and hasattr(aval, "shape"):
                        nb += int(np.prod(aval.shape, dtype=np.int64)
                                  * np.dtype(aval.dtype).itemsize)
                totals[name] = totals.get(name, 0) + nb
            for v in eqn.params.values():
                _walk_param(v)

    def _walk_param(v):
        from jax import core as jcore
        if isinstance(v, jcore.ClosedJaxpr):
            walk(v.jaxpr)
        elif isinstance(v, jcore.Jaxpr):
            walk(v)
        elif isinstance(v, (tuple, list)):
            for x in v:
                _walk_param(x)

    walk(closed.jaxpr)
    return {"comm_bytes": int(sum(totals.values())),
            "collectives": dict(sorted(totals.items()))}
