"""Registry of Gibbs-block updaters for static audit and tooling.

The sweep (:mod:`.sweep`) assembles these blocks positionally at trace
time; nothing at runtime needs a registry.  The static-analysis layer
(:mod:`hmsc_tpu.analysis.jaxpr_rules`) does: it abstract-evals *every
registered updater* on canonical small specs and audits the traced
programs (dtype policy, host callbacks, baked constants, structural
fingerprints).  Each entry wraps one updater into the uniform signature
``fn(spec, data, state, key) -> state-pytree`` with the same auxiliary
inputs (residual ``S``, total random-level loading) the sweep computes,
and declares via ``applies(spec, data)`` which model classes exercise it.

Adding a Gibbs block without registering it here fails the analyzer's
coverage check (``jaxpr-registry-coverage``), so the registry cannot
silently go stale.
"""

from __future__ import annotations

import dataclasses

from . import updaters as U
from . import updaters_sel as USel
from .spatial import update_alpha, update_eta_spatial

__all__ = ["UpdaterEntry", "UPDATER_REGISTRY", "applicable_updaters"]


@dataclasses.dataclass(frozen=True)
class UpdaterEntry:
    name: str                  # the sweep's toggle name (updater={...} key)
    fn: object                 # (spec, data, state, key) -> pytree
    applies: object            # (spec, data) -> bool
    module: str                # implementation home, for the audit report


def _eta_residual(spec, data, state):
    """The residual the sweep hands update_eta for level 0: Z minus the
    fixed part and every *other* level's loading."""
    S = state.Z - U.linear_fixed(spec, data, state.Beta)
    for q in range(spec.nr):
        if q != 0:
            S = S - U.level_loading(data.levels[q], state.levels[q])
    return S


def _lran_total(spec, data, state):
    if spec.nr == 0:
        import jax.numpy as jnp
        return jnp.zeros_like(state.Z)
    return sum(U.level_loading(data.levels[r], state.levels[r])
               for r in range(spec.nr))


def _gamma_eta_ok(which):
    def applies(spec, data):
        from .updaters_marginal import gamma_eta_gates
        return not gamma_eta_gates(spec, data.mGamma)[which]
    return applies


_R = []


def _register(name, fn, applies=lambda spec, data: True, module="updaters"):
    _R.append(UpdaterEntry(name=name, fn=fn, applies=applies, module=module))


# the collapsed updaters import lazily inside their wrappers (matching the
# sweep's deferred import, so merely listing the registry never pays the
# module import)
def _gamma2(s, d, st, k):
    from .updaters_marginal import update_gamma2
    return update_gamma2(s, d, st, k)


def _gamma_eta(s, d, st, k):
    from .updaters_marginal import update_gamma_eta
    return update_gamma_eta(s, d, st, 0, k)


_register("Z", lambda s, d, st, k: U.update_z(s, d, st, k))
_register("BetaLambda", lambda s, d, st, k: U.update_beta_lambda(s, d, st, k))
_register("GammaV", lambda s, d, st, k: U.update_gamma_v(s, d, st, k))
_register("Rho", lambda s, d, st, k: U.update_rho(s, d, st, k),
          applies=lambda s, d: s.has_phylo)
_register("LambdaPriors",
          lambda s, d, st, k: U.update_lambda_priors(s, d, st, k))
_register("InvSigma", lambda s, d, st, k: U.update_inv_sigma(s, d, st, k))
_register("Eta",
          lambda s, d, st, k: U.update_eta_nonspatial(
              s, d, st, 0, k, _eta_residual(s, d, st)),
          applies=lambda s, d: s.nr > 0 and s.levels[0].spatial is None)
_register("EtaSpatial",
          lambda s, d, st, k: update_eta_spatial(
              s, d, st, 0, k, _eta_residual(s, d, st)),
          applies=lambda s, d: s.nr > 0 and s.levels[0].spatial is not None,
          module="spatial")
_register("Alpha", lambda s, d, st, k: update_alpha(s, d, st, 0, k),
          applies=lambda s, d: s.nr > 0 and s.levels[0].spatial is not None,
          module="spatial")
_register("Nf", lambda s, d, st, k: U.update_nf(s, d, st, 0, k),
          applies=lambda s, d: s.nr > 0)
_register("Interweave", lambda s, d, st, k: U.interweave_scale(s, d, st, k),
          applies=lambda s, d: s.nr > 0)
_register("InterweaveLocation",
          lambda s, d, st, k: U.interweave_location(s, d, st, k),
          applies=lambda s, d: s.nr > 0 and d.x_ones_ind is not None)
_register("InterweaveDA",
          lambda s, d, st, k: U.interweave_da_intercept(s, d, st, k),
          applies=lambda s, d: (s.any_probit and not s.x_is_list
                                and d.x_ones_ind is not None))
_register("wRRR",
          lambda s, d, st, k: USel.update_w_rrr(
              s, d, st, k, _lran_total(s, d, st)),
          applies=lambda s, d: s.nc_rrr > 0, module="updaters_sel")
_register("wRRRPriors",
          lambda s, d, st, k: USel.update_w_rrr_priors(s, d, st, k),
          applies=lambda s, d: s.nc_rrr > 0, module="updaters_sel")
_register("BetaSel",
          lambda s, d, st, k: USel.update_beta_sel(
              s, d, st, k, _lran_total(s, d, st)),
          applies=lambda s, d: s.ncsel > 0, module="updaters_sel")
_register("Gamma2", _gamma2, applies=_gamma_eta_ok("Gamma2"),
          module="updaters_marginal")
_register("GammaEta", _gamma_eta, applies=_gamma_eta_ok("GammaEta"),
          module="updaters_marginal")

UPDATER_REGISTRY: tuple[UpdaterEntry, ...] = tuple(_R)
del _R


def applicable_updaters(spec, data) -> list[UpdaterEntry]:
    """Registry entries the given model class exercises."""
    return [e for e in UPDATER_REGISTRY if e.applies(spec, data)]
