"""Core pytrees of the Gibbs engine.

Split cleanly into:

- ``ModelSpec`` / ``LevelSpec``: *static*, hashable metadata (shapes, flags,
  methods).  Closed over by the jitted sweep; changing it triggers a recompile.
- ``ModelData`` / ``LevelData``: HBM-resident constant arrays (data, priors,
  precomputed grids).
- ``GibbsState`` / ``LevelState``: the Markov-chain state pytree carried
  through ``lax.scan``.  Factor blocks are allocated at the static ``nf_max``
  with an active-factor mask; "adapting the number of factors" is mask/permute
  arithmetic inside jit (SURVEY.md §7 point 1).

All shapes are static; chains add a leading batch axis via vmap.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import struct

from ..model import FIXED_SIGMA2, Hmsc
from ..precompute import DataParams, compute_initial_parameters

__all__ = ["LevelSpec", "ModelSpec", "LevelData", "ModelData", "LevelState",
           "GibbsState", "LevelTenant", "TenantMasks", "build_model_data",
           "build_state", "state_nbytes", "DEFAULT_NF_CAP"]

# static cap on latent factors per level (reference grows nf up to ns,
# updateNf.R:26; static XLA shapes need a concrete bound)
DEFAULT_NF_CAP = 16


@dataclasses.dataclass(frozen=True)
class LevelSpec:
    name: str
    n_units: int
    nf_max: int
    nf_min: int
    ncr: int                      # max(x_dim, 1)
    x_dim: int
    spatial: str | None           # None | 'Full' | 'NNGP' | 'GPP'
    n_alpha: int                  # alpha-grid size (0 if non-spatial)
    n_neighbours: int = 0
    n_knots: int = 0
    # True when nf_max was cut below the user's prior bound min(rL.nf_max,
    # ns) by the static nf_cap — only then is blocked factor growth a cap
    # artifact worth warning about (a deliberate nf_min=nf_max freeze is not)
    nf_capped: bool = False


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    ny: int
    ns: int
    nc: int
    nt: int
    nr: int
    n_rho: int
    has_phylo: bool
    has_na: bool
    x_is_list: bool
    any_normal: bool
    any_probit: bool
    any_poisson: bool
    any_estimated_sigma: bool
    # all residual variances fixed to one common value (e.g. all-probit):
    # enables the matrix-normal fast path for the phylogenetic Beta draw
    homoskedastic_fixed: bool
    f0: float
    ncsel: int
    nc_rrr: int
    nc_orrr: int
    nc_nrrr: int
    levels: tuple[LevelSpec, ...]

    @property
    def nf_total(self) -> int:
        """Total stacked factor columns across levels: sum nf_max * ncr."""
        return sum(l.nf_max * l.ncr for l in self.levels)


class LevelData(struct.PyTreeNode):
    pi_row: Any                  # (ny,) int32 unit index per row
    unit_count: Any              # (np,) rows per unit
    x_row: Any                   # (ny, ncr) covariate value per row (ones if x_dim=0)
    x_unit: Any                  # (np, ncr)
    nu: Any                      # (ncr,) shrinkage hyperparams
    a1: Any
    b1: Any
    a2: Any
    b2: Any
    alphapw: Any = None          # (G, 2)
    # spatial 'Full'
    iWg: Any = None              # (G, np, np)
    detWg: Any = None            # (G,) log det W
    # spatial 'NNGP'
    nn_idx: Any = None           # (np, k) int32
    nn_coef: Any = None          # (G, np, k)
    nn_D: Any = None             # (G, np)
    # spatial 'GPP'
    idDg: Any = None             # (G, np)
    idDW12g: Any = None          # (G, np, nK)
    Fg: Any = None               # (G, nK, nK)
    iFg: Any = None              # (G, nK, nK)
    detDg: Any = None            # (G,)


class ModelData(struct.PyTreeNode):
    Y: Any                       # (ny, ns) NaNs replaced by 0
    Ymask: Any                   # (ny, ns) 1.0 observed / 0.0 missing
    X: Any                       # (ny, nc) or (ns, ny, nc)
    Tr: Any                      # (ns, nt)
    distr_family: Any            # (ns,) int32
    distr_estsig: Any            # (ns,) 1.0 where dispersion estimated
    sigma_fixed: Any             # (ns,) fixed sigma^2 values for the rest
    mGamma: Any                  # (nc*nt,)
    iUGamma: Any                 # (nc*nt, nc*nt)
    UGamma: Any                  # (nc*nt, nc*nt) (collapsed updaters)
    V0: Any                      # (nc, nc)
    aSigma: Any                  # (ns,)
    bSigma: Any                  # (ns,)
    rhopw: Any = None            # (G_rho, 2)
    Qeig: Any = None             # (G_rho, ns) eigenvalues of Q(rho_g)
    logdetQ: Any = None          # (G_rho,)
    U: Any = None                # (ns, ns) eigenvectors of C
    UTr: Any = None              # (ns, nt) U' Tr
    levels: tuple = ()
    # reduced-rank regression: scaled XRRR covariates
    XRRRs: Any = None            # (ny, nc_orrr)
    nuRRR: Any = None            # () shrinkage hyperparams for wRRR
    a1RRR: Any = None
    b1RRR: Any = None
    a2RRR: Any = None
    b2RRR: Any = None
    # spike-and-slab variable selection groups (one entry per XSelect)
    sel_cov: tuple = ()          # ((nc,) 1.0-where-switched masks)
    sel_spg: tuple = ()          # ((ns,) int32 species-group index)
    sel_q: tuple = ()            # ((n_groups,) prior inclusion probs)
    # back-transform parameters (combineParameters at record time)
    x_scale_par: Any = None      # (2, nc_nrrr)
    tr_scale_par: Any = None     # (2, nt)
    y_scale_par: Any = None      # (2, ns)
    xrrr_scale_par: Any = None   # (2, nc_orrr)
    x_intercept_ind: Any = None  # () int32 or None
    tr_intercept_ind: Any = None
    # first all-ones column of the *scaled* design (the named intercept when
    # present, else detected by value): the column the interweaving moves can
    # shift.  Detection by name alone (x_intercept_ind) silently no-ops the
    # moves for raw-matrix designs whose first column is ones — measured in
    # round 5: every prior interweave A/B had the move gated off.
    x_ones_ind: Any = None       # () int32 or None
    # pad-and-mask multitenancy (mcmc/multitenant.py): per-model validity
    # masks + real-count scalars.  None on every single-model path — the
    # updaters branch on this at trace time, keeping the default programs
    # byte-identical to the committed fingerprints.
    tenant: Any = None           # TenantMasks or None


class LevelTenant(struct.PyTreeNode):
    """Per-model per-level validity info for one pad-and-mask tenant
    (:mod:`.multitenant`).  Scalars are traced f32/int so they can vary
    per model under the batched runner's model-axis vmap."""
    unit_mask: Any               # (np,) 1.0 real unit / 0.0 padding
    n_units: Any                 # () f32 real unit count
    nf_cap: Any                  # () f32 the model's own factor growth bound
    nf_min: Any                  # () f32 the model's own factor floor
    nf_capped: Any               # () f32 1.0 when nf_cap cut the user bound


class TenantMasks(struct.PyTreeNode):
    """Per-model validity masks for the pad-and-mask batched sweep.

    ``ModelData.tenant`` is ``None`` on every single-model path — the
    updaters test it at TRACE time, so the default traced programs are
    byte-identical to the pre-multitenant ones (fingerprint-pinned).  When
    present, each mask flags the REAL slice of a padded dimension and the
    scalar counts replace the static ``spec`` counts wherever a count
    enters the math (Wishart degrees of freedom, shrinkage gamma shapes,
    Nf statistics, interweave Jacobian exponents)."""
    row_mask: Any                # (ny,) 1.0 real row
    sp_mask: Any                 # (ns,) 1.0 real species
    cov_mask: Any                # (nc,) 1.0 real covariate
    tr_mask: Any                 # (nt,) 1.0 real trait
    n_rows: Any                  # () f32 real ny — no updater reads it
    #   (row statistics come from the Ymask-padded data, e.g. sigma's
    #   per-species n_obs); carried as the per-tenant row-count scalar for
    #   mask consumers (the fault-injection tests key on it)
    n_sp: Any                    # () f32 real ns
    n_cov: Any                   # () f32 real nc
    df_v: Any                    # () f32 Wishart df f0 + real ns
    levels: tuple = ()           # tuple[LevelTenant]


class LevelState(struct.PyTreeNode):
    Eta: Any                     # (np, nf_max)
    Lambda: Any                  # (nf_max, ns, ncr)
    Psi: Any                     # (nf_max, ns, ncr)
    Delta: Any                   # (nf_max, ncr); 1.0 on inactive slots
    alpha_idx: Any               # (nf_max,) int32
    nf_mask: Any                 # (nf_max,) 1.0 active
    # () int32: adaptation events that wanted to ADD a factor but were
    # blocked by the static nf_max cap (factor-cap observability; the
    # reference grows unbounded to nfMax=ns, updateNf.R:26)
    nf_sat: Any = 0


class GibbsState(struct.PyTreeNode):
    Z: Any                       # (ny, ns) latent response
    Beta: Any                    # (nc, ns)
    Gamma: Any                   # (nc, nt)
    iV: Any                      # (nc, nc)
    rho_idx: Any                 # () int32
    iSigma: Any                  # (ns,) residual precisions
    levels: tuple                # tuple[LevelState]
    it: Any                      # () int32 sweep counter (1-based like the reference)
    # extras (variable selection / reduced-rank regression); None-free pytree
    BetaSel: tuple = ()          # tuple of (n_groups,) bool arrays
    wRRR: Any = 0.0              # (nc_rrr, nc_orrr)
    PsiRRR: Any = 0.0
    DeltaRRR: Any = 0.0


# ---------------------------------------------------------------------------

def state_nbytes(state) -> int:
    """Total bytes of a carry pytree (all chains).  The sampler's segment
    runner donates its carry buffers, so steady-state HBM holds exactly ONE
    copy of this — the pre-donation footprint was two (input + output);
    ``benchmarks/bench_host_loop.py`` and the pipeline tests report it."""
    import jax
    return sum(int(x.nbytes) for x in jax.tree_util.tree_leaves(state)
               if hasattr(x, "nbytes"))


def build_spec(hM: Hmsc, nf_cap: int = DEFAULT_NF_CAP) -> ModelSpec:
    level_specs = []
    for r in range(hM.nr):
        rL = hM.ranLevels[r]
        nf_max = int(min(rL.nf_max, hM.ns, nf_cap))
        nf_min = int(min(rL.nf_min, nf_max))
        spatial = rL.spatial_method if rL.s_dim != 0 else None
        level_specs.append(LevelSpec(
            name=hM.rl_names[r], n_units=int(hM.np_[r]), nf_max=nf_max,
            nf_min=nf_min, ncr=max(rL.x_dim, 1), x_dim=rL.x_dim,
            nf_capped=nf_max < min(rL.nf_max, hM.ns),
            spatial=spatial,
            n_alpha=0 if spatial is None else rL.alphapw.shape[0],
            n_neighbours=int(rL.n_neighbours or 10) if spatial == "NNGP" else 0,
            n_knots=0 if rL.s_knot is None else int(np.asarray(rL.s_knot).shape[0]),
        ))
    est = hM.distr[:, 1] == 1
    fixed_vals = np.array([FIXED_SIGMA2[int(f)] for f in hM.distr[:, 0]])
    homo = (not est.any()) and bool(np.all(fixed_vals == fixed_vals[0]))
    return ModelSpec(
        ny=hM.ny, ns=hM.ns, nc=hM.nc, nt=hM.nt, nr=hM.nr,
        n_rho=0 if hM.C is None else hM.rhopw.shape[0],
        has_phylo=hM.C is not None,
        has_na=bool(np.isnan(hM.Y).any()),
        x_is_list=hM.x_is_list,
        any_normal=bool((hM.distr[:, 0] == 1).any()),
        any_probit=bool((hM.distr[:, 0] == 2).any()),
        any_poisson=bool((hM.distr[:, 0] == 3).any()),
        any_estimated_sigma=bool(est.any()),
        homoskedastic_fixed=homo,
        f0=float(hM.f0),
        ncsel=hM.ncsel, nc_rrr=hM.nc_rrr, nc_orrr=hM.nc_orrr,
        nc_nrrr=hM.nc_nrrr,
        levels=tuple(level_specs),
    )


def _find_ones_column(hM) -> Any:
    """First all-ones column of the scaled design the sampler runs on (the
    shiftable direction the interweaving moves need).  Prefers the named
    intercept; otherwise detects by value.  None for per-species X lists
    (the moves are gated off there anyway)."""
    if hM.x_intercept_ind is not None:
        return jnp.asarray(hM.x_intercept_ind, dtype=jnp.int32)
    Xs = np.asarray(hM.XScaled)
    if Xs.ndim != 2:
        return None
    ones = np.nonzero(np.all(Xs == 1.0, axis=0))[0]
    return jnp.asarray(ones[0], dtype=jnp.int32) if ones.size else None


def build_model_data(hM: Hmsc, data_par: DataParams, spec: ModelSpec,
                     dtype=jnp.float32) -> ModelData:
    """Assemble the HBM-resident constant arrays from the host spec."""
    f = lambda a: jnp.asarray(np.asarray(a), dtype=dtype)
    Y = np.asarray(hM.YScaled, dtype=float)
    mask = (~np.isnan(Y)).astype(float)
    Y0 = np.nan_to_num(Y, nan=0.0)

    levels = []
    for r in range(hM.nr):
        rL = hM.ranLevels[r]
        ls = spec.levels[r]
        pi = hM.Pi[:, r]
        counts = np.bincount(pi, minlength=ls.n_units).astype(float)
        if rL.x_dim > 0:
            x_unit = rL.x_for(hM.pi_names[r])
            x_row = x_unit[pi]
        else:
            x_unit = np.ones((ls.n_units, 1))
            x_row = np.ones((hM.ny, 1))
        kw = dict(
            pi_row=jnp.asarray(pi, dtype=jnp.int32),
            unit_count=f(counts), x_row=f(x_row), x_unit=f(x_unit),
            nu=f(rL.nu), a1=f(rL.a1), b1=f(rL.b1), a2=f(rL.a2), b2=f(rL.b2),
        )
        lp = data_par.rL_par[r] if data_par.rL_par else None
        if ls.spatial is not None:
            kw["alphapw"] = f(rL.alphapw)
            if ls.spatial == "Full":
                kw["iWg"] = f(lp.iWg)
                kw["detWg"] = f(lp.detWg)
            elif ls.spatial == "NNGP":
                kw["nn_idx"] = jnp.asarray(lp.nn_idx, dtype=jnp.int32)
                kw["nn_coef"] = f(lp.nn_coef)
                kw["nn_D"] = f(lp.nn_D)
                kw["detWg"] = f(lp.detWg)
            elif ls.spatial == "GPP":
                kw["idDg"] = f(lp.idDg)
                kw["idDW12g"] = f(lp.idDW12g)
                kw["Fg"] = f(lp.Fg)
                kw["iFg"] = f(lp.iFg)
                kw["detDg"] = f(lp.detDg)
        levels.append(LevelData(**kw))

    est = (hM.distr[:, 1] == 1).astype(float)
    fixed_vals = np.array([FIXED_SIGMA2[int(fam)] for fam in hM.distr[:, 0]])
    iUGamma = np.linalg.inv(hM.UGamma)

    kw = dict(
        Y=f(Y0), Ymask=f(mask),
        X=f(hM.XScaled), Tr=f(hM.TrScaled),
        distr_family=jnp.asarray(hM.distr[:, 0], dtype=jnp.int32),
        distr_estsig=f(est), sigma_fixed=f(fixed_vals),
        mGamma=f(hM.mGamma), iUGamma=f(iUGamma), UGamma=f(hM.UGamma),
        V0=f(hM.V0),
        aSigma=f(hM.aSigma), bSigma=f(hM.bSigma),
        levels=tuple(levels),
        x_scale_par=f(hM.x_scale_par),
        tr_scale_par=f(hM.tr_scale_par),
        y_scale_par=f(hM.y_scale_par),
        x_intercept_ind=(None if hM.x_intercept_ind is None
                         else jnp.asarray(hM.x_intercept_ind, dtype=jnp.int32)),
        tr_intercept_ind=(None if hM.tr_intercept_ind is None
                          else jnp.asarray(hM.tr_intercept_ind, dtype=jnp.int32)),
        x_ones_ind=_find_ones_column(hM),
    )
    if hM.nc_rrr > 0:
        kw["xrrr_scale_par"] = f(hM.xrrr_scale_par)
        kw["XRRRs"] = f(hM.XRRRScaled)
        kw.update(nuRRR=f(hM.nuRRR), a1RRR=f(hM.a1RRR), b1RRR=f(hM.b1RRR),
                  a2RRR=f(hM.a2RRR), b2RRR=f(hM.b2RRR))
    if hM.ncsel > 0:
        sel_cov, sel_spg, sel_q = [], [], []
        for sel in hM.x_select:
            cov = np.zeros(hM.nc)
            cov[sel.cov_group] = 1.0
            sel_cov.append(f(cov))
            sel_spg.append(jnp.asarray(sel.sp_group, dtype=jnp.int32))
            sel_q.append(f(sel.q))
        kw.update(sel_cov=tuple(sel_cov), sel_spg=tuple(sel_spg),
                  sel_q=tuple(sel_q))
    if spec.has_phylo:
        kw.update(rhopw=f(hM.rhopw), Qeig=f(data_par.Qeig),
                  logdetQ=f(data_par.logdetQ), U=f(data_par.U),
                  UTr=f(data_par.U.T @ hM.TrScaled))
    return ModelData(**kw)


def build_state(hM: Hmsc, spec: ModelSpec, seed: int,
                init_par=None, dtype=jnp.float32) -> GibbsState:
    """One chain's initial GibbsState (Z starts at the linear predictor; the
    sampler immediately runs update_z once, like the reference's init)."""
    rng = np.random.default_rng(seed)
    nf_max = [ls.nf_max for ls in spec.levels]
    p = compute_initial_parameters(hM, nf_max, rng, init_par)
    f = lambda a: jnp.asarray(np.asarray(a, dtype=float), dtype=dtype)

    levels = tuple(
        LevelState(Eta=f(lv["Eta"]), Lambda=f(lv["Lambda"]), Psi=f(lv["Psi"]),
                   Delta=f(lv["Delta"]),
                   alpha_idx=jnp.asarray(lv["alpha_idx"], dtype=jnp.int32),
                   nf_mask=f(lv["nf_mask"]),
                   nf_sat=jnp.asarray(0, dtype=jnp.int32))
        for lv in p["levels"])

    # linear predictor as the Z starting point (RRR columns appended from the
    # initial wRRR draw, like the reference's X = [X1A, XRRR wRRR'])
    Beta = np.asarray(p["Beta"], dtype=float)
    Xs = np.asarray(hM.XScaled)
    if hM.nc_rrr > 0:
        XB = np.asarray(hM.XRRRScaled) @ np.asarray(p["wRRR"]).T
        Xs = (np.concatenate([Xs, np.broadcast_to(XB, (hM.ns,) + XB.shape)], axis=2)
              if spec.x_is_list else np.concatenate([Xs, XB], axis=1))
    if spec.x_is_list:
        L = np.einsum("jyc,cj->yj", Xs, Beta)
    else:
        L = Xs @ Beta
    for r in range(spec.nr):
        lv = p["levels"][r]
        lam = lv["Lambda"] * lv["nf_mask"][:, None, None]
        eta_rows = lv["Eta"][hM.Pi[:, r]]
        x_row = (hM.ranLevels[r].x_for(hM.pi_names[r])[hM.Pi[:, r]]
                 if hM.ranLevels[r].x_dim > 0 else np.ones((hM.ny, 1)))
        L = L + np.einsum("yf,yk,fjk->yj", eta_rows, x_row, lam)

    iSigma = 1.0 / np.asarray(p["sigma"], dtype=float)
    state = GibbsState(
        Z=f(L), Beta=f(Beta), Gamma=f(p["Gamma"]),
        iV=f(np.linalg.inv(p["V"])),
        rho_idx=jnp.asarray(p["rho_idx"], dtype=jnp.int32),
        iSigma=f(iSigma), levels=levels,
        it=jnp.asarray(0, dtype=jnp.int32),
        BetaSel=tuple(jnp.asarray(b) for b in p["BetaSel"]),
        wRRR=0.0 if p["wRRR"] is None else f(p["wRRR"]),
        PsiRRR=0.0 if p["PsiRRR"] is None else f(p["PsiRRR"]),
        DeltaRRR=0.0 if p["DeltaRRR"] is None else f(p["DeltaRRR"]),
    )
    return state
