"""Multi-tenant batched fitting: one vmapped pad-and-mask sweep over K models.

The north-star workload is many *small* regional / taxon-specific JSDMs
(PAPER.md's model family — probit/normal/Poisson observation models with
traits, phylogeny and unstructured random levels).  Run serially, each
tiny model wastes a chip: per-sweep dispatch overhead and XLA compilation
dominate while the arithmetic is microscopic.  This module batches K
same-structure models into ONE jitted segment runner:

- **Shape buckets**: model specs are grouped by a structural fingerprint
  (:func:`bucket_key`) — static flags that pick traced code paths — plus
  their padded dims (``ny``/``ns``/``nc``/``nt``/``np``/``nf`` rounded up
  to the bucket granularity).  Models in one bucket run as one program.
- **Pad and mask**: every per-model array is padded to the bucket dims.
  Padded rows/species ride the existing ``has_na`` masked-gram machinery
  (a padded cell IS a missing cell), padded covariates/traits carry
  exact-zero design columns with identity prior blocks, and a
  :class:`~.structs.TenantMasks` threads per-model validity masks +
  real-count scalars through the updaters (Wishart degrees of freedom,
  shrinkage gamma shapes, Nf statistics, interweave Jacobian exponents).
  The batched sweep re-masks the carry after every Gibbs block, so padded
  slots provably contribute exact zeros to every real entry — block
  precisions stay block-diagonal between real and padded indices, and
  Cholesky/solve factors preserve that decoupling bitwise
  (``tests/test_multitenant.py`` pins junk-in-padding invariance per
  registered updater).
- **vmap over the model axis**: the existing chain-vmapped segment body is
  vmapped once more over models, with per-model data, RNG key streams and
  divergence trackers.  A tenant's blow-up (non-finite carry) is confined
  to its own vmap lane and never disturbs another tenant's draws.
- **Per-tenant manifests**: each tenant checkpoints into its own
  subdirectory through a standard :class:`~..utils.checkpoint.
  CheckpointWriter` — the committed state/draws are sliced back to the
  tenant's REAL shapes, so every manifest is a fully ordinary single-model
  checkpoint (loadable, resumable, splice-repairable by the existing
  tools).  ``retry_diverged`` restarts only a diverged tenant's chains
  from that tenant's last healthy manifest; healthy tenants' shard files
  are byte-untouched.

Contracts:

- **Zero padding** (every model in the bucket already at the bucket dims,
  identical specs): masks are omitted entirely and the batched program
  folds the production sweep verbatim — each tenant's draw stream is
  **bit-identical** to its own unbatched ``sample_mcmc`` run with the
  same seed, *up to XLA's lane-count-sensitive kernel tiling*.
  Bit-exactness is pinned by tests on the CPU backend for the
  formula-built model family at the tier-1 lane counts (K x chains <= 8);
  above that XLA CPU re-tiles its batched kernels and per-lane results
  drift at the ULP level (measured ~1e-6 max, gated in
  ``benchmarks/bench_multitenant.py``).  Models whose trace includes the
  fusion-boundary-sensitive interweave dot (raw-matrix designs with a
  ones column, ``x_ones_ind`` set — see the PR 8 schedule notes) can sit
  at 1-ULP agreement even at K=1, because the extra model-axis vmap
  moves that dot's fusion boundary; the hard cross-family contract is
  the bench's ULP bound, not bitwise equality.
- **Masked padding**: padded slots contribute exact zeros (bitwise
  junk-invariance per updater), but RNG draws happen at padded widths, so
  a padded tenant's stream is a *different realisation* of the same
  posterior; end-to-end agreement with the unbatched run is statistical,
  within :data:`TENANT_PAD_AGREEMENT_TOL` on posterior means.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import numpy as np
import jax
import jax.numpy as jnp

from ..precompute import compute_data_parameters
from .structs import (DEFAULT_NF_CAP, GibbsState, LevelTenant, ModelData,
                      ModelSpec, TenantMasks, build_model_data, build_spec,
                      build_state)
from .sweep import (effective_spec_data, make_sweep_schedule, record_sample,
                    sweep_prologue)
from . import spatial
from . import updaters as U

__all__ = ["sample_mcmc_batched", "bucket_key", "bucket_dims",
           "batch_unsupported_reason", "make_batched_sweep",
           "mask_tenant_state", "pad_tenant", "pad_spec", "pad_state",
           "slice_tenant_state", "TENANT_PAD_AGREEMENT_TOL",
           "DEFAULT_BUCKET_ROUNDING", "tenant_dir"]

# Committed masked-padding contract: a padded tenant's posterior MEANS agree
# with its own unbatched run within this tolerance at the regression tests'
# sample counts (Monte-Carlo error dominates — padding contributes exact
# zeros, only the RNG draw widths differ).  Zero-padding buckets are exempt:
# they are bit-identical.
TENANT_PAD_AGREEMENT_TOL = 0.35

# Default pad granularity per dimension: dims round UP to the next multiple,
# bounding both padding waste (< one granule per dim) and program count
# (every model in a granule-aligned box shares one compiled runner).
DEFAULT_BUCKET_ROUNDING = {"ny": 16, "ns": 4, "nc": 2, "nt": 2,
                           "np": 8, "nf": 2}


def _round_up(n: int, g: int) -> int:
    g = max(1, int(g))
    return int(-(-int(n) // g) * g)


def tenant_dir(base: str, name: str) -> str:
    """The per-tenant checkpoint subdirectory under a batched run's
    ``checkpoint_path`` — one ordinary append-layout snapshot directory
    per model."""
    return os.path.join(os.fspath(base), f"tenant-{name}")


# ---------------------------------------------------------------------------
# bucketing
# ---------------------------------------------------------------------------

def batch_unsupported_reason(spec: ModelSpec,
                             updater: dict | None = None) -> str | None:
    """Why this model cannot join a padded batch, or ``None`` when it can.
    The supported family is PAPER.md's full core: normal/probit/Poisson
    observation models, traits, phylogeny, unstructured AND spatial
    (Full/NNGP/GPP) random levels, covariate-dependent levels (xDim > 0),
    spike-and-slab selection (XSelect) and reduced-rank regression
    (XRRRData).  Spatial precision grids pad block-diagonally (identity /
    inert-Vecchia / zero-knot-correction pad units); sel/RRR models keep
    their covariate axis static (``bucket_dims`` never rounds ``nc`` for
    them) so the selection groups and RRR component rows stay exact."""
    if spec.x_is_list:
        return "per-species design matrices (x_is_list)"
    up = updater or {}
    if up.get("Gamma2") is True or up.get("GammaEta") is True:
        return "opt-in collapsed updaters (Gamma2/GammaEta)"
    if up.get("InterweaveDA") is True:
        return "opt-in probit-DA intercept interweave (InterweaveDA)"
    return None


def bucket_dims(spec: ModelSpec, rounding: dict | None = None) -> dict:
    """This model's padded target dims under the rounding granularity.

    sel/RRR models keep ``nc`` EXACT (never rounded): selection groups
    are per-covariate static structure and the RRR component rows sit at
    ``nc_nrrr:``, so a padded covariate axis would shift traced group
    unrolls — such models only share a bucket at equal ``nc``."""
    g = dict(DEFAULT_BUCKET_ROUNDING)
    g.update(rounding or {})
    nc_static = spec.ncsel > 0 or spec.nc_rrr > 0
    return {
        "ny": _round_up(spec.ny, g["ny"]),
        "ns": _round_up(spec.ns, g["ns"]),
        "nc": spec.nc if nc_static else _round_up(spec.nc, g["nc"]),
        "nt": _round_up(spec.nt, g["nt"]),
        "np": tuple(_round_up(ls.n_units, g["np"]) for ls in spec.levels),
        "nf": tuple(_round_up(ls.nf_max, g["nf"]) for ls in spec.levels),
    }


def _struct_sig(spec: ModelSpec, data: ModelData) -> tuple:
    """The trace-path part of the bucket key: every static flag that picks
    compiled code, EXCLUDING the raw dims (those enter via padded dims)."""
    # sel/RRR: the covariate split (nc_nrrr | nc_rrr | nc_orrr) and the
    # per-selection group counts pick statically-unrolled traced code, so
    # they join the key (alongside the exact nc that bucket_dims keeps)
    sel_rrr = ()
    if spec.ncsel > 0 or spec.nc_rrr > 0:
        sel_rrr = (spec.nc, spec.nc_orrr, spec.nc_nrrr,
                   tuple(int(np.asarray(q).shape[0]) for q in data.sel_q))
    return (
        spec.nr,
        tuple((ls.x_dim, ls.spatial, ls.ncr, ls.n_alpha,
               ls.n_neighbours, ls.n_knots) for ls in spec.levels),
        spec.has_phylo, spec.n_rho,
        spec.any_normal, spec.any_probit, spec.any_poisson,
        spec.any_estimated_sigma, spec.homoskedastic_fixed,
        spec.x_is_list, spec.ncsel, spec.nc_rrr, sel_rrr,
        data.x_ones_ind is not None,
        data.x_intercept_ind is not None,
        data.tr_intercept_ind is not None,
    )


def bucket_key(spec: ModelSpec, data: ModelData,
               rounding: dict | None = None) -> str:
    """The shape-bucket fingerprint: models with equal keys batch into one
    padded vmapped program.  ``has_na`` joins the key as its *effective*
    value — a model that pads at all runs under the masked-gram (has_na)
    trace, so NA and no-NA models share a bucket unless both are already
    exactly at the bucket dims."""
    import hashlib
    dims = bucket_dims(spec, rounding)
    padded = _is_padded(spec, dims)
    sig = (_struct_sig(spec, data), tuple(sorted(dims.items())),
           bool(spec.has_na or padded))
    return hashlib.sha256(repr(sig).encode()).hexdigest()[:16]


def _is_padded(spec: ModelSpec, dims: dict) -> bool:
    return (dims["ny"] != spec.ny or dims["ns"] != spec.ns
            or dims["nc"] != spec.nc or dims["nt"] != spec.nt
            or any(dims["np"][r] != spec.levels[r].n_units
                   for r in range(spec.nr))
            or any(dims["nf"][r] != spec.levels[r].nf_max
                   for r in range(spec.nr)))


# ---------------------------------------------------------------------------
# padding
# ---------------------------------------------------------------------------

def _padded(a, targets: dict, fill: float = 0.0):
    """np-pad ``a`` up to ``targets[axis]`` with a constant fill."""
    a = np.asarray(a)
    pads = [(0, max(0, int(targets.get(ax, a.shape[ax])) - a.shape[ax]))
            for ax in range(a.ndim)]
    if not any(p[1] for p in pads):
        return a
    return np.pad(a, pads, constant_values=fill)


def _pad_diag_one(a, n: int):
    """Pad a square matrix to (n, n) with zeros, ones on the pad diagonal
    (identity pad block: exact real/pad decoupling through Cholesky)."""
    a = np.asarray(a)
    k = a.shape[0]
    out = _padded(a, {0: n, 1: n})
    if n > k:
        idx = np.arange(k, n)
        out[idx, idx] = 1.0
    return out


def _pad_grid_diag_one(a, n: int):
    """Pad a (G, np, np) per-grid-point precision stack to (G, n, n) with
    zeros, ones on each grid point's pad diagonal — every alpha's padded
    precision gains an identity pad block (exact real/pad decoupling
    through the joint Cholesky, zero log-det contribution)."""
    a = np.asarray(a)
    k = a.shape[1]
    out = _padded(a, {1: n, 2: n})
    if n > k:
        idx = np.arange(k, n)
        out[:, idx, idx] = 1.0
    return out


def _pad_scale_par(sp, n: int):
    """(2, d) back-transform params: pad means with 0, scales with 1."""
    sp = np.asarray(sp)
    out = _padded(sp, {1: n})
    if n > sp.shape[1]:
        out[1, sp.shape[1]:] = 1.0
    return out


def _remap_gamma_vec(v, nt: int, nc: int, nt_p: int, nc_p: int):
    """Re-lay a (nt*nc,) Gamma-vec (t-major: index t*nc + c) into the
    padded (nt_p*nc_p,) ordering with zero fill."""
    return _padded(np.asarray(v).reshape(nt, nc), {0: nt_p, 1: nc_p}).ravel()


def _remap_gamma_mat(m, nt: int, nc: int, nt_p: int, nc_p: int):
    """Re-lay a (nt*nc, nt*nc) Gamma-vec matrix into padded vec ordering,
    identity on the pad diagonal."""
    n_p = nt_p * nc_p
    out = np.eye(n_p, dtype=np.asarray(m).dtype)
    idx = (np.arange(nt)[:, None] * nc_p + np.arange(nc)[None, :]).ravel()
    out[np.ix_(idx, idx)] = np.asarray(m)
    return out


def pad_spec(spec: ModelSpec, dims: dict, has_na: bool) -> ModelSpec:
    """The shared bucket spec: padded dims, masked-gram trace forced on."""
    levels = tuple(
        dataclasses.replace(ls, n_units=int(dims["np"][r]),
                            nf_max=int(dims["nf"][r]),
                            nf_min=min(ls.nf_min, int(dims["nf"][r])),
                            nf_capped=ls.nf_capped)
        for r, ls in enumerate(spec.levels))
    return dataclasses.replace(
        spec, ny=int(dims["ny"]), ns=int(dims["ns"]), nc=int(dims["nc"]),
        nt=int(dims["nt"]), has_na=bool(has_na), levels=levels,
        # non-RRR models carry no RRR columns (nc == nc_nrrr), so the
        # padded spec keeps that identity — record_sample's RRR concat
        # branch (spec.nc > nc_nrrr) must not fire against the padded
        # x_scale_par.  RRR models keep nc static (bucket_dims), so their
        # own nc_nrrr stays exact
        nc_nrrr=spec.nc_nrrr if spec.nc_rrr > 0 else int(dims["nc"]))


def _tenant_masks(spec: ModelSpec, dims: dict, dtype=np.float32):
    def m(real, padded):
        out = np.zeros(padded, dtype=dtype)
        out[:real] = 1.0
        return out
    levels = tuple(
        LevelTenant(
            unit_mask=jnp.asarray(m(ls.n_units, dims["np"][r])),
            n_units=jnp.asarray(float(ls.n_units), dtype=dtype),
            nf_cap=jnp.asarray(float(ls.nf_max), dtype=dtype),
            nf_min=jnp.asarray(float(ls.nf_min), dtype=dtype),
            nf_capped=jnp.asarray(float(ls.nf_capped), dtype=dtype))
        for r, ls in enumerate(spec.levels))
    return TenantMasks(
        row_mask=jnp.asarray(m(spec.ny, dims["ny"])),
        sp_mask=jnp.asarray(m(spec.ns, dims["ns"])),
        cov_mask=jnp.asarray(m(spec.nc, dims["nc"])),
        tr_mask=jnp.asarray(m(spec.nt, dims["nt"])),
        n_rows=jnp.asarray(float(spec.ny), dtype=dtype),
        n_sp=jnp.asarray(float(spec.ns), dtype=dtype),
        n_cov=jnp.asarray(float(spec.nc), dtype=dtype),
        df_v=jnp.asarray(float(spec.f0 + spec.ns), dtype=dtype),
        levels=levels)


def pad_tenant(spec: ModelSpec, data: ModelData, dims: dict) -> ModelData:
    """One tenant's padded :class:`ModelData` (with its ``tenant`` masks).

    Padding construction (every choice makes the pad slots exactly inert):
    rows/species pad as MISSING cells (``Ymask=0`` — the has_na grams skip
    them), covariates pad as all-zero design columns with identity prior
    blocks (``V0``/``iUGamma``), traits pad as zero columns, phylogeny
    pads block-diagonally with unit eigenvalues, and the back-transform
    scale params pad as (mean 0, scale 1) so ``record_sample`` divides by
    ones."""
    ny, ns, nc, nt = dims["ny"], dims["ns"], dims["nc"], dims["nt"]
    f32 = lambda a: jnp.asarray(np.asarray(a, dtype=np.float32))

    levels = []
    for r in range(spec.nr):
        lvd = data.levels[r]
        np_p = int(dims["np"][r])
        np_r = spec.levels[r].n_units
        pi = np.asarray(lvd.pi_row)
        # padded rows point at the first padded unit (or unit 0 when the
        # unit axis itself is unpadded) — their stats are Ymask-zeroed
        # either way, this just keeps the segment sums tidy
        pad_unit = np_r if np_p > np_r else 0
        pi_p = _padded(pi, {0: ny}, fill=pad_unit).astype(np.int32)
        lkw = dict(
            pi_row=jnp.asarray(pi_p),
            unit_count=f32(_padded(lvd.unit_count, {0: np_p})),
            x_row=f32(_padded(lvd.x_row, {0: ny}, fill=1.0)),
            x_unit=f32(_padded(lvd.x_unit, {0: np_p}, fill=1.0)),
        )
        # spatial precision grids pad block-diagonally per alpha grid
        # point — padded units decouple from real ones EXACTLY, for every
        # alpha, so the Eta Cholesky/CG factors and the Alpha grid
        # log-densities are bitwise independent of pad content:
        # - Full: identity pad block in each iWg (zero log-det, detWg
        #   unchanged)
        # - NNGP: inert Vecchia pad rows — no neighbours (nn_idx 0 with
        #   nn_coef 0 scatters nothing), unit conditional variance
        #   (nn_D 1) => pad rows of the Cholesky factor are e_i; real
        #   rows never reference pad units (pads append past np_r)
        # - GPP: unit diagonal (idDg 1, the alpha=0 convention) with zero
        #   knot corrections (idDW12g 0) => MtAM / rhs pad contributions
        #   are exact zeros; Fg/iFg/detDg are knot-indexed and pass
        #   through untouched
        # The lone traced consequence of these fills — 1'iW1 counting one
        # per pad unit in eta_ones_forms_at — is corrected per tenant in
        # interweave_location.
        if lvd.iWg is not None:
            lkw["iWg"] = f32(_pad_grid_diag_one(lvd.iWg, np_p))
        if lvd.nn_idx is not None:
            lkw["nn_idx"] = jnp.asarray(
                _padded(np.asarray(lvd.nn_idx), {0: np_p}).astype(np.int32))
            lkw["nn_coef"] = f32(_padded(lvd.nn_coef, {1: np_p}))
            lkw["nn_D"] = f32(_padded(lvd.nn_D, {1: np_p}, fill=1.0))
        if lvd.idDg is not None:
            lkw["idDg"] = f32(_padded(lvd.idDg, {1: np_p}, fill=1.0))
            lkw["idDW12g"] = f32(_padded(lvd.idDW12g, {1: np_p}))
        levels.append(lvd.replace(**lkw))

    # the stored design carries the non-RRR columns only (effective_design
    # appends the RRR components per sweep), so its covariate pad target is
    # nc - nc_rrr — equal to nc for every non-RRR model
    ncn_p = nc - spec.nc_rrr
    kw = dict(
        Y=f32(_padded(data.Y, {0: ny, 1: ns})),
        Ymask=f32(_padded(data.Ymask, {0: ny, 1: ns})),
        X=f32(_padded(data.X, {0: ny, 1: ncn_p})),
        Tr=f32(_padded(data.Tr, {0: ns, 1: nt})),
        distr_family=jnp.asarray(
            _padded(np.asarray(data.distr_family), {0: ns},
                    fill=1).astype(np.int32)),
        distr_estsig=f32(_padded(data.distr_estsig, {0: ns})),
        sigma_fixed=f32(_padded(data.sigma_fixed, {0: ns}, fill=1.0)),
        mGamma=f32(_remap_gamma_vec(data.mGamma, spec.nt, spec.nc, nt, nc)),
        iUGamma=f32(_remap_gamma_mat(data.iUGamma, spec.nt, spec.nc,
                                     nt, nc)),
        UGamma=f32(_remap_gamma_mat(data.UGamma, spec.nt, spec.nc, nt, nc)),
        V0=f32(_pad_diag_one(data.V0, nc)),
        aSigma=f32(_padded(data.aSigma, {0: ns}, fill=1.0)),
        bSigma=f32(_padded(data.bSigma, {0: ns}, fill=1.0)),
        levels=tuple(levels),
        x_scale_par=f32(_pad_scale_par(data.x_scale_par, ncn_p)),
        tr_scale_par=f32(_pad_scale_par(data.tr_scale_par, nt)),
        y_scale_par=f32(_pad_scale_par(data.y_scale_par, ns)),
        x_intercept_ind=data.x_intercept_ind,
        tr_intercept_ind=data.tr_intercept_ind,
        x_ones_ind=data.x_ones_ind,
        tenant=_tenant_masks(spec, dims),
    )
    if spec.nc_rrr > 0:
        kw.update(
            # XRRRs pad rows MUST be exact zeros: A2 = XRRRs' XRRRs has no
            # Ymask gating, and zero rows also kill the padded-row terms of
            # the wRRR data gram (S's pad rows are zero because Z and the
            # loadings are masked, but junk-in-padding inertness must not
            # depend on that)
            XRRRs=f32(_padded(data.XRRRs, {0: ny})),
            nuRRR=f32(data.nuRRR), a1RRR=f32(data.a1RRR),
            b1RRR=f32(data.b1RRR), a2RRR=f32(data.a2RRR),
            b2RRR=f32(data.b2RRR),
            xrrr_scale_par=f32(data.xrrr_scale_par),
        )
    if spec.ncsel > 0:
        kw.update(
            # sel_cov stays exact (nc is static for sel models); padded
            # species join group 0 — their lldif terms are exact zeros
            # (Beta pad columns are masked to zero and logdens carries the
            # Ymask factor), so the MH flips are pad-independent
            sel_cov=tuple(f32(c) for c in data.sel_cov),
            sel_spg=tuple(
                jnp.asarray(_padded(np.asarray(g), {0: ns}).astype(np.int32))
                for g in data.sel_spg),
            sel_q=tuple(f32(q) for q in data.sel_q),
        )
    if spec.has_phylo:
        kw.update(
            rhopw=f32(data.rhopw),
            # padded species are phylogenetically independent: Q(rho)'s pad
            # block is the identity for EVERY rho (rho C_pad + (1-rho) I =
            # I), so the pad eigenvalues are exactly 1 and logdetQ is the
            # real model's unchanged
            Qeig=f32(_padded(data.Qeig, {1: ns}, fill=1.0)),
            logdetQ=f32(data.logdetQ),
            U=f32(_pad_diag_one(data.U, ns)),
            UTr=f32(_padded(data.UTr, {0: ns, 1: nt})),
        )
    return ModelData(**kw)


def pad_state(spec: ModelSpec, state: GibbsState, dims: dict,
              lead: int = 0) -> GibbsState:
    """One tenant's carry padded to the bucket dims, pad slots in their
    masked-neutral values (zeros; ones for precisions/Delta/Psi).

    ``lead`` shifts the padded axes right by that many leading batch axes
    (``lead=1`` pads a whole (chains, ...) carry in one host pass — the
    resume path re-pads loaded real-shape carries this way)."""
    ny, ns, nc, nt = dims["ny"], dims["ns"], dims["nc"], dims["nt"]
    f32 = lambda a: jnp.asarray(np.asarray(a, dtype=np.float32))
    sh = lambda d: {ax + lead: v for ax, v in d.items()}

    def diag_one(a, n):
        a = np.asarray(a)
        k = a.shape[lead]
        out = _padded(a, sh({0: n, 1: n}))
        if n > k:
            idx = np.arange(k, n)
            out[..., idx, idx] = 1.0
        return out

    levels = []
    for r in range(spec.nr):
        lv = state.levels[r]
        np_p, nf_p = int(dims["np"][r]), int(dims["nf"][r])
        levels.append(lv.replace(
            Eta=f32(_padded(lv.Eta, sh({0: np_p, 1: nf_p}))),
            Lambda=f32(_padded(lv.Lambda, sh({0: nf_p, 1: ns}))),
            Psi=f32(_padded(lv.Psi, sh({0: nf_p, 1: ns}), fill=1.0)),
            Delta=f32(_padded(lv.Delta, sh({0: nf_p}), fill=1.0)),
            alpha_idx=jnp.asarray(_padded(np.asarray(lv.alpha_idx),
                                          sh({0: nf_p})).astype(np.int32)),
            nf_mask=f32(_padded(lv.nf_mask, sh({0: nf_p}))),
        ))
    return state.replace(
        Z=f32(_padded(state.Z, sh({0: ny, 1: ns}))),
        Beta=f32(_padded(state.Beta, sh({0: nc, 1: ns}))),
        Gamma=f32(_padded(state.Gamma, sh({0: nc, 1: nt}))),
        iV=f32(diag_one(state.iV, nc)),
        iSigma=f32(_padded(state.iSigma, sh({0: ns}), fill=1.0)),
        levels=tuple(levels))


def slice_tenant_state(spec: ModelSpec, state: GibbsState) -> GibbsState:
    """Slice a padded carry back to the tenant's REAL shapes — the inverse
    of :func:`pad_state`, so per-tenant checkpoints hold ordinary
    unbatched-shape state (directly loadable by the standard tools)."""
    levels = []
    for r in range(spec.nr):
        lv = state.levels[r]
        np_r, nf_r = spec.levels[r].n_units, spec.levels[r].nf_max
        levels.append(lv.replace(
            Eta=lv.Eta[..., :np_r, :nf_r],
            Lambda=lv.Lambda[..., :nf_r, :spec.ns, :],
            Psi=lv.Psi[..., :nf_r, :spec.ns, :],
            Delta=lv.Delta[..., :nf_r, :],
            alpha_idx=lv.alpha_idx[..., :nf_r],
            nf_mask=lv.nf_mask[..., :nf_r],
        ))
    return state.replace(
        Z=state.Z[..., :spec.ny, :spec.ns],
        Beta=state.Beta[..., :spec.nc, :spec.ns],
        Gamma=state.Gamma[..., :spec.nc, :spec.nt],
        iV=state.iV[..., :spec.nc, :spec.nc],
        iSigma=state.iSigma[..., :spec.ns],
        levels=tuple(levels))


# recorded-sample dims per parameter (after the leading chain/sample axes);
# symbols resolve against the tenant's REAL spec
_REC_DIMS = {
    "Beta": ("nc", "ns"), "Gamma": ("nc", "nt"), "V": ("nc", "nc"),
    "sigma": ("ns",), "rho": (),
    "Eta": ("np", "nf"), "Lambda": ("nf", "ns", None), "Psi": ("nf", "ns",
                                                               None),
    "Delta": ("nf", None), "Alpha": ("nf",), "nfMask": ("nf",),
}


def _slice_record(name: str, arr, spec: ModelSpec):
    """Slice one recorded array (leading chain/sample axes preserved) down
    to the tenant's real dims."""
    head, _, tail = name.rpartition("_")
    base, r = (head, int(tail)) if tail.isdigit() else (name, None)
    dims = _REC_DIMS.get(base)
    if dims is None:
        return arr
    sizes = {"nc": spec.nc, "ns": spec.ns, "nt": spec.nt}
    if r is not None:
        sizes["np"] = spec.levels[r].n_units
        sizes["nf"] = spec.levels[r].nf_max
    lead = arr.ndim - len(dims)
    sl = tuple([slice(None)] * lead
               + [slice(None) if d is None else slice(0, sizes[d])
                  for d in dims])
    return arr[sl]


# ---------------------------------------------------------------------------
# the masked batched sweep
# ---------------------------------------------------------------------------

def mask_tenant_state(spec: ModelSpec, ten: TenantMasks,
                      state: GibbsState) -> GibbsState:
    """Re-zero every padded carry slot (ones for the precision-like
    fields, identity pad block for ``iV``).  Applied after every Gibbs
    block: each updater then sees exactly-inert padding on entry, which is
    what makes the real-slice draws independent of pad content — and keeps
    ``record_sample``'s ``inv(iV)`` exactly block-decoupled."""
    rm, sm, cm, tm = ten.row_mask, ten.sp_mask, ten.cov_mask, ten.tr_mask
    iV = state.iV * (cm[:, None] * cm[None, :]) + jnp.diag(1.0 - cm)
    levels = []
    for r in range(spec.nr):
        lv = state.levels[r]
        um = ten.levels[r].unit_mask
        levels.append(lv.replace(
            Eta=lv.Eta * um[:, None],
            Lambda=lv.Lambda * sm[None, :, None],
            Psi=jnp.where(sm[None, :, None] > 0, lv.Psi,
                          jnp.ones((), dtype=lv.Psi.dtype)),
        ))
    return state.replace(
        Z=state.Z * rm[:, None] * sm[None, :],
        Beta=state.Beta * cm[:, None] * sm[None, :],
        Gamma=state.Gamma * cm[:, None] * tm[None, :],
        iV=iV,
        iSigma=jnp.where(sm > 0, state.iSigma,
                         jnp.ones((), dtype=state.iSigma.dtype)),
        levels=tuple(levels))


def make_batched_sweep(spec: ModelSpec, updater: dict | None = None,
                       adapt_nf: tuple | None = None, precision=None):
    """The tenant-masked sweep: the standard schedule's blocks folded with
    a carry re-mask between blocks.  With ``data.tenant is None`` the fold
    is LITERALLY :func:`~.sweep.make_sweep`'s (no mask ops trace), so the
    zero-padding path stays byte-identical to the committed fingerprints;
    composes with a :class:`~.precision.PrecisionPolicy` exactly like the
    production sweep (the policy'd blocks trace inside their scopes, the
    4th ``staged`` argument carries the bf16 shadow table)."""
    steps = make_sweep_schedule(spec, updater, adapt_nf, None, precision)

    def _fold(data, state, ks):
        carry = (state, None, None, None)
        for _name, block in steps:
            # blocks statically index disjoint rows of the subkey table
            carry = block(data, carry, ks)  # hmsc: ignore[rng-key-reuse]
            if data.tenant is not None:
                carry = (mask_tenant_state(spec, data.tenant, carry[0]),
                         *carry[1:])
        return carry[0]

    if precision is None:
        def sweep(data: ModelData, state: GibbsState, key) -> GibbsState:
            state, ks = sweep_prologue(state, key)
            return _fold(data, state, ks)
        return sweep

    from ..ops import mixed

    def sweep_mp(data: ModelData, state: GibbsState, key,
                 staged=None) -> GibbsState:
        state, ks = sweep_prologue(state, key)
        with mixed.staged_scope(staged):
            return _fold(data, state, ks)
    return sweep_mp


@functools.lru_cache(maxsize=32)
def _batched_runner(spec, updater_items, adapt_nf, samples, transient, thin,
                    skip_init_z, record=None, nngp_dense_max=None,
                    precision=None):
    """One jitted (model, chain)-vmapped segment program per static config.

    Mirrors :func:`~.sampler._compiled_runner`'s chain body exactly (same
    init-Z pass, same scan nesting, same donation) with TWO differences:
    the data pytree is vmapped over a leading model axis, and the sweep is
    the tenant-masked fold.  At zero padding (no ``tenant`` masks) the
    per-lane math is the production run-chain's — the bit-identity tests
    pin lane equality on CPU."""
    from .sampler import _keep_record
    updater = dict(updater_items) if updater_items else None
    sweep = make_batched_sweep(spec, updater, adapt_nf, precision)

    def first_bad_update(state, bad_it):
        ok = jnp.bool_(True)
        for leaf in jax.tree.leaves(state):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                ok = ok & jnp.all(jnp.isfinite(leaf))
        return jnp.where((bad_it < 0) & ~ok, state.it, bad_it)

    def run_chain(data, state, key, bad_it, staged=None):
        if not skip_init_z:
            key, k0 = jax.random.split(key)
            spec0, data0 = effective_spec_data(spec, data, state)
            state = U.update_z(spec0, data0, state, k0)
            if data.tenant is not None:
                state = mask_tenant_state(spec, data.tenant, state)
        bad_it = first_bad_update(state, bad_it)

        def one_iter(carry, _):
            state, key, bad_it = carry
            key, sub = jax.random.split(key)
            if precision is None:
                state = sweep(data, state, sub)
            else:
                # single consumption — only one branch traces (static on
                # `precision`)   # hmsc: ignore[rng-key-reuse]
                state = sweep(data, state, sub, staged)
            bad_it = first_bad_update(state, bad_it)
            return (state, key, bad_it), None

        carry = (state, key, bad_it)
        if transient > 0:
            carry, _ = jax.lax.scan(one_iter, carry, None, length=transient)

        def sample_step(carry, _):
            carry, _ = jax.lax.scan(one_iter, carry, None, length=thin)
            rec = record_sample(spec, data, carry[0])
            if record is not None:
                rec = {k: v for k, v in rec.items()
                       if _keep_record(k, record)}
            return carry, rec

        carry, recs = jax.lax.scan(sample_step, carry, None, length=samples)
        return recs, carry[0], carry[2], carry[1]

    if precision is None:
        inner = jax.vmap(run_chain, in_axes=(None, 0, 0, 0))
        mapped = jax.vmap(inner, in_axes=(0, 0, 0, 0))
    else:
        inner = jax.vmap(run_chain, in_axes=(None, 0, 0, 0, None))
        mapped = jax.vmap(inner, in_axes=(0, 0, 0, 0, 0))
    return jax.jit(mapped, donate_argnums=(1, 2, 3))


# ---------------------------------------------------------------------------
# the batched driver
# ---------------------------------------------------------------------------

def _stack(trees):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


@dataclasses.dataclass
class _Tenant:
    """One model's per-run bookkeeping inside a bucket."""
    index: int                    # position in the caller's model list
    name: str
    hM: object
    spec: ModelSpec               # REAL spec
    data: ModelData               # REAL data
    seed: int | None
    base_post: object = None      # resumed base segment
    base_samples: int = 0
    shards: list | None = None
    init_state: object = None     # REAL-shape carry (chains, ...)
    init_keys: object = None
    done: bool = False            # already complete at resume time
    post: object = None
    writer: object = None         # CheckpointWriter
    records: list = dataclasses.field(default_factory=list)
    retry_info: dict | None = None


def _occupancy(tenants, dims) -> dict:
    cell_pad = float(dims["ny"] * dims["ns"]) * max(1, len(tenants))
    cell_real = float(sum(t.spec.ny * t.spec.ns for t in tenants))
    return {"cells_real": int(cell_real), "cells_padded": int(cell_pad),
            "occupancy": round(cell_real / cell_pad, 4),
            "padding_waste": round(1.0 - cell_real / cell_pad, 4)}


def sample_mcmc_batched(models, samples: int, transient: int = 0,
                        thin: int = 1, n_chains: int = 1,
                        seeds=None, seed: int | None = None, names=None,
                        updater: dict | None = None,
                        nf_cap: int = DEFAULT_NF_CAP, adapt_nf=None,
                        record=None, record_dtype=None,
                        align_post: bool = True, rng_impl: str | None = None,
                        precision_policy=None, retry_diverged: int = 0,
                        verbose: int = 0, checkpoint_every: int = 0,
                        checkpoint_path: str | None = None,
                        checkpoint_keep: int = 3,
                        bucket_rounding: dict | None = None,
                        resume: bool = False, pipeline: bool = True,
                        progress_callback=None,
                        return_report: bool = False):
    """Fit K models as vmapped pad-and-mask batches — one jitted segment
    runner per shape bucket instead of K serial ``sample_mcmc`` runs.

    ``models`` is a sequence of :class:`~hmsc_tpu.model.Hmsc`; every model
    runs the same cadence (``samples``/``transient``/``thin``/
    ``n_chains``).  Per-model seeds come from ``seeds`` (a sequence) or
    are derived from the base ``seed``.  Returns the per-model
    :class:`~hmsc_tpu.post.Posterior` list in input order (with
    ``return_report=True``, a ``(posteriors, report)`` tuple — the report
    carries per-bucket occupancy / padding-waste metrics).

    Checkpointing (``checkpoint_every`` + ``checkpoint_path``) fans out to
    per-tenant manifests: each model snapshots into
    ``<checkpoint_path>/tenant-<name>/`` as an ordinary append-layout
    single-model checkpoint (state and draws sliced to the model's REAL
    shapes).  A killed batched run resumes with ``resume=True``: each
    tenant continues from its own last committed manifest — tenants
    interrupted at different marks regroup into same-progress sub-batches,
    so no tenant ever loses a committed draw.  ``retry_diverged`` restarts
    only a diverged tenant's chains (warm, from that tenant's last healthy
    manifest when one exists) and repairs that tenant's manifest; healthy
    tenants' committed shard files are byte-untouched.

    Contracts: zero-padding buckets are bit-identical per tenant to the
    unbatched ``sample_mcmc`` with the same seed; padded buckets agree
    within :data:`TENANT_PAD_AGREEMENT_TOL` (see module docstring).
    """
    import time

    from ..obs import get_logger
    from ..post.posterior import Posterior

    t0 = time.perf_counter()
    models = list(models)
    K = len(models)
    if K == 0:
        return ([], {"buckets": []}) if return_report else []
    if names is None:
        names = [f"m{i:03d}" for i in range(K)]
    names = [str(n) for n in names]
    if len(set(names)) != K:
        raise ValueError("tenant names must be unique")
    if seeds is None:
        if seed is None:
            seeds = [None] * K
        else:
            srng = np.random.default_rng(seed)
            seeds = [int(s) for s in srng.integers(0, 2**31 - 1, size=K)]
    seeds = list(seeds)
    if len(seeds) != K:
        raise ValueError(f"seeds carries {len(seeds)} entries for {K} "
                         "models")
    if adapt_nf is not None:
        # same guard as sample_mcmc: adaptation past the burn-in would
        # mix latent dimensionalities inside the recorded window
        if any(int(a) > int(transient)
               for a in np.atleast_1d(np.asarray(adapt_nf)).ravel()):
            raise ValueError("transient parameter should be no less than "
                             "any element of adaptNf parameter")
    ck_every = int(checkpoint_every or 0)
    if ck_every and checkpoint_path is None:
        raise ValueError("checkpoint_every requires checkpoint_path")
    if checkpoint_path is not None and ck_every == 0:
        ck_every = int(samples)
    # retry_diverged without checkpointing is allowed: the per-tenant warm
    # restart needs manifests, but a cold per-tenant retry works without
    # them — parity with sample_mcmc's checkpoint-free cold retry
    log = get_logger()

    if rng_impl is None:
        plat = jax.default_backend()
        rng_impl = "rbg" if ("tpu" in plat or "axon" in plat) \
            else "threefry2x32"

    adapt_nf_arg = adapt_nf

    # -- per-model build + bucketing ---------------------------------------
    from .sampler import normalize_record
    tenants: list[_Tenant] = []
    for i, hM in enumerate(models):
        spec = build_spec(hM, nf_cap)
        reason = batch_unsupported_reason(spec, updater)
        if reason is not None:
            raise NotImplementedError(
                f"model {names[i]!r} cannot join a padded batch: {reason} "
                "— fit it with sample_mcmc instead")
        # same validation + tuple-normalisation as sample_mcmc (the runner
        # is lru_cache'd on it, and Eta needs its Lambda sign reference);
        # batch-eligible models share structure, so every tenant resolves
        # the same tuple
        record = normalize_record(spec, record)
        dp = compute_data_parameters(hM)
        data = build_model_data(hM, dp, spec)
        tenants.append(_Tenant(index=i, name=names[i], hM=hM, spec=spec,
                               data=data, seed=seeds[i]))

    buckets: dict[str, list[_Tenant]] = {}
    for t in tenants:
        buckets.setdefault(
            bucket_key(t.spec, t.data, bucket_rounding), []).append(t)

    # -- resume: per-tenant manifest recovery ------------------------------
    if resume:
        if checkpoint_path is None:
            raise ValueError("resume=True requires checkpoint_path (the "
                             "batched run's tenant-manifest root)")
        from ..utils.checkpoint import (CheckpointError, checkpoint_files,
                                        latest_valid_checkpoint)
        for t in tenants:
            d = tenant_dir(checkpoint_path, t.name)
            if not checkpoint_files(d):
                continue              # fresh tenant
            try:
                ck = latest_valid_checkpoint(d, t.hM)
            except CheckpointError:
                continue              # unusable: restart this tenant fresh
            meta = dict(ck.run_meta or {})
            # stream-defining parameters always come from the original run
            # (the resume_run invariant): a continuation under different
            # values would splice a DIFFERENT Gibbs schedule / draw stream
            # onto the committed base — refuse up front instead of letting
            # concat_posteriors (or nothing at all) catch it after the
            # continuation's compute is spent
            given = {"transient": int(transient), "thin": int(thin),
                     "n_chains": int(n_chains), "nf_cap": int(nf_cap),
                     "rng_impl": rng_impl,
                     "updater": dict(updater) if updater else None,
                     "seed": None if t.seed is None else int(t.seed),
                     "record": (list(record) if record is not None
                                else None)}
            for kf, gv in given.items():
                if kf in meta and meta[kf] != gv:
                    raise CheckpointError(
                        f"tenant {t.name!r}: resume with a different "
                        f"{kf} ({gv!r}) than the checkpointed run's "
                        f"({meta[kf]!r}) — stream-defining parameters "
                        "are not overridable on a batched resume")
            done = int(meta.get("samples_done", ck.post.samples or 0))
            if done >= int(samples):
                t.done = True
                t.post = ck.post
                continue
            t.base_post = ck.post if ck.post.arrays else None
            t.base_samples = done
            t.shards = (list(ck.header.get("shards", []))
                        if ck.path.endswith(".json") else None)
            t.init_state = ck.state
            t.init_keys = ck.keys

    posts: list = [None] * K
    report = {"buckets": [], "n_models": K,
              "cadence": {"samples": int(samples),
                          "transient": int(transient), "thin": int(thin),
                          "n_chains": int(n_chains)}}

    for bkey, group in sorted(buckets.items()):
        # same-progress sub-batches: a kill mid-fan-out can leave adjacent
        # tenants one committed mark apart — each sub-batch runs uniform
        # remaining segments, so nothing committed is ever re-recorded
        subgroups: dict[int, list[_Tenant]] = {}
        for t in group:
            if t.done:
                posts[t.index] = _finish_tenant(t, align_post)
                continue
            subgroups.setdefault(int(t.base_samples), []).append(t)
        for done0, sub in sorted(subgroups.items()):
            binfo = _run_bucket(
                bkey, sub, samples=int(samples) - done0,
                transient=int(transient) if done0 == 0 else 0,
                thin=int(thin),
                n_chains=int(n_chains), updater=updater, nf_cap=int(nf_cap),
                adapt_nf=adapt_nf_arg, record=record,
                record_dtype=record_dtype,
                rng_impl=rng_impl, precision_policy=precision_policy,
                retry_diverged=int(retry_diverged), verbose=int(verbose),
                ck_every=ck_every, checkpoint_path=checkpoint_path,
                checkpoint_keep=int(checkpoint_keep),
                bucket_rounding=bucket_rounding, pipeline=bool(pipeline),
                progress_callback=progress_callback,
                total_samples=int(samples),
                transient_total=int(transient), log=log)
            report["buckets"].append(binfo)
            for t in sub:
                posts[t.index] = _finish_tenant(t, align_post)

    report["wall_s"] = round(time.perf_counter() - t0, 4)
    if report["buckets"]:
        tot_real = sum(b["cells_real"] for b in report["buckets"])
        tot_pad = sum(b["cells_padded"] for b in report["buckets"])
        report["occupancy"] = round(tot_real / max(tot_pad, 1), 4)
        report["padding_waste"] = round(1.0 - tot_real / max(tot_pad, 1), 4)
    if return_report:
        return posts, report
    return posts


def _finish_tenant(t: _Tenant, align_post: bool):
    post = t.post
    if t.base_post is not None and post is not None \
            and post is not t.base_post:
        from ..utils.checkpoint import concat_posteriors
        post = concat_posteriors(t.base_post, post, align=False)
        if t.retry_info is not None:
            post.retry_info = t.retry_info
    if align_post and post is not None and t.spec.nr > 0:
        from ..post.align import align_posterior
        for _ in range(5):
            if align_posterior(post) == 0:
                break
    return post


def _chain_keys(seed, n_chains: int, rng_impl: str, it0: int = 0):
    """The tenant's per-chain key table, derived EXACTLY like
    ``sample_mcmc``'s (same seed ⇒ same stream — the zero-padding
    bit-identity contract hangs on this)."""
    if it0 > 0:
        rng = np.random.default_rng([0 if seed is None else int(seed), it0])
    else:
        rng = np.random.default_rng(seed)
    chain_seeds = rng.integers(0, 2**31 - 1, size=int(n_chains))
    return jax.vmap(lambda s: jax.random.key(s, impl=rng_impl))(
        jnp.asarray(chain_seeds))


def _run_bucket(bkey, tenants, *, samples, transient, thin, n_chains,
                updater, nf_cap, adapt_nf, record, record_dtype, rng_impl,
                precision_policy, retry_diverged, verbose, ck_every,
                checkpoint_path, checkpoint_keep, bucket_rounding, pipeline,
                progress_callback, total_samples, transient_total,
                log) -> dict:
    """Run one shape bucket's tenants as a single vmapped segment loop."""
    import time

    from ..post.posterior import Posterior
    from .precision import resolve_policy, stage_data
    from .sampler import (_InlineWriter, _SegmentWriter, _pack_records,
                          _unpack_records)

    t0 = time.perf_counter()
    K = len(tenants)
    dims0 = bucket_dims(tenants[0].spec, bucket_rounding)
    # zero padding: every tenant already AT the bucket dims with identical
    # static specs — masks are omitted entirely and the traced per-lane
    # program is the production sweep's (bit-identity contract)
    zero_pad = (all(not _is_padded(t.spec, dims0) for t in tenants)
                and all(t.spec == tenants[0].spec for t in tenants))
    if zero_pad:
        spec_b = tenants[0].spec
        datas = [t.data for t in tenants]
    else:
        spec_b = pad_spec(tenants[0].spec, dims0, has_na=True)
        datas = [pad_tenant(t.spec, t.data, dims0) for t in tenants]
        waste = _occupancy(tenants, dims0)["padding_waste"]
        if waste > 0.5:
            # the dedup key carries the run + tenant identity, not just the
            # bucket fingerprint: two runs (or two tenant groups) sharing a
            # bucket shape in one process must EACH get their warning
            run_id = os.fspath(checkpoint_path) if checkpoint_path else "-"
            members = ",".join(sorted(t.name for t in tenants))
            log.warn_once(
                f"pad-waste:{run_id}:{bkey}:{members}",
                f"shape bucket {bkey}: padding waste {waste:.0%} of batched "
                f"cells ({K} tenants padded to ny={dims0['ny']}, "
                f"ns={dims0['ns']}) — tighten bucket_rounding or regroup "
                "models to reclaim throughput")
    data_b = _stack(datas)

    # per-tenant initial carries + key streams
    states, keys, skip_z = [], [], False
    for t in tenants:
        if t.init_state is not None:
            st = t.init_state          # (chains, ...) REAL shapes
            lead = int(jax.tree.leaves(st)[0].shape[0])
            if lead != n_chains:
                raise ValueError(
                    f"tenant {t.name!r}: resumed carry has {lead} chains, "
                    f"expected {n_chains}")
            it0 = int(np.asarray(st.it).ravel()[0])
            if not zero_pad:
                st = pad_state(t.spec, st, dims0, lead=1)
            else:
                st = jax.tree.map(
                    lambda x: jnp.copy(x) if isinstance(x, jax.Array)
                    else x, st)
            if t.init_keys is not None:
                kt = jnp.copy(t.init_keys)
            else:
                kt = _chain_keys(t.seed, n_chains, rng_impl, it0=it0)
            skip_z = True
        else:
            chain_states = []
            rng = np.random.default_rng(t.seed)
            chain_seeds = rng.integers(0, 2**31 - 1, size=n_chains)
            for s in chain_seeds:
                st1 = build_state(t.hM, t.spec, int(s))
                if not zero_pad:
                    st1 = pad_state(t.spec, st1, dims0)
                chain_states.append(st1)
            st = _stack(chain_states)
            kt = _chain_keys(t.seed, n_chains, rng_impl)
        states.append(st)
        keys.append(kt)
    if skip_z and any(t.init_state is None for t in tenants):
        raise ValueError("a sub-batch mixes resumed and fresh tenants — "
                         "the driver groups by progress before calling "
                         "_run_bucket")
    state_b = _stack(states)
    state_b = jax.tree.map(
        lambda x: jnp.asarray(x, dtype=x.dtype) if hasattr(x, "dtype")
        else x, state_b)
    keys_b = jnp.stack(keys)
    bad_b = jnp.full((K, n_chains), -1, dtype=jnp.int32)

    if adapt_nf is None:
        adapt_nf_res = tuple(transient for _ in range(spec_b.nr))
    else:
        adapt_nf_res = tuple(int(a) for a in
                             np.broadcast_to(adapt_nf, (spec_b.nr,)))
    updater_items = tuple(sorted(updater.items())) if updater else None

    policy = resolve_policy(precision_policy, spec_b)
    staged_tbl = None
    if policy is not None:
        staged_tbl = jax.vmap(lambda d: stage_data(d, policy))(data_b)

    # segment plan: sampling-mark cuts only (burn-in stays fused into the
    # first segment; per-tenant manifests begin at the first recorded mark)
    marks = {int(samples)}
    if verbose:
        chunk = max(1, int(round(verbose / thin)))
        marks.update(range(chunk, int(samples), chunk))
    if ck_every:
        marks.update(range(ck_every, int(samples), ck_every))
    cuts = sorted(marks)
    seg_sizes = [b - a for a, b in zip([0] + cuts[:-1], cuts)]
    ck_marks = ({m for m in cuts if m % ck_every == 0} | {int(samples)}
                if ck_every else set())

    # per-tenant checkpoint writers (ordinary append-layout, real shapes)
    for t in tenants:
        t.records = []
    if ck_every:
        from ..utils.checkpoint import CheckpointWriter
        for t in tenants:
            d = tenant_dir(checkpoint_path, t.name)
            os.makedirs(d, exist_ok=True)
            t.writer = CheckpointWriter(
                d, "append", t.spec, hM=t.hM, records=t.records,
                base_post=t.base_post, base_samples=t.base_samples,
                shards=t.shards, keep=int(checkpoint_keep),
                keys_impl=rng_impl)

    # per-tenant event streams (tenant-<name>/events-p0.jsonl, next to the
    # manifests): one run/start + end-of-bucket health record per tenant,
    # joined to the dispatching queue's trace via the env so the metrics
    # hub links a scenario fold back to the job that spawned it
    tenant_telems: dict = {}
    if ck_every:
        from ..obs import RunTelemetry, events_path
        from ..obs.trace import inherit_or_mint
        tctx = inherit_or_mint()
        for t in tenants:
            tt = RunTelemetry(proc=0)
            tt.set_trace(tctx)
            tt.attach_sink(
                events_path(tenant_dir(checkpoint_path, t.name), 0),
                truncate=(t.base_samples == 0))
            tt.emit("run", "start", tenant=t.name, bucket=bkey,
                    n_chains=int(n_chains), samples=int(samples),
                    zero_padding=bool(zero_pad))
            tt.flush()
            tenant_telems[t.name] = tt

    writer = _SegmentWriter(2) if pipeline else _InlineWriter()
    host_segs: list = []              # fetched (K, C, S, ...) record trees

    def _collect(packed):
        """Writer-thread item: force the fetch and, when checkpointing,
        append each tenant's real-sliced record view (the per-tenant
        CheckpointWriter flush cursors read these lists)."""
        tree = _unpack_records(*packed)
        host_segs.append(tree)
        if ck_every:
            for k, t in enumerate(tenants):
                t.records.append(
                    {name: _slice_record(name, np.asarray(arr[k]), t.spec)
                     for name, arr in tree.items()})

    def _tenant_meta(t: _Tenant, done_now: int) -> dict:
        return {
            "samples_total": int(total_samples),
            "samples_done": t.base_samples + int(done_now),
            "transient": int(transient_total),
            "thin": int(thin), "n_chains": int(n_chains),
            "seed": None if t.seed is None else int(t.seed),
            "nf_cap": int(nf_cap), "rng_impl": rng_impl,
            "adapt_nf": [int(a) for a in adapt_nf_res[:t.spec.nr]],
            "dtype": "float32",
            "record": list(record) if record is not None else None,
            "record_dtype": (None if record_dtype is None
                             else np.dtype(record_dtype).name),
            "updater": dict(updater) if updater else None,
            "retry_diverged": int(retry_diverged),
            "align_post": False,
            "checkpoint_every": ck_every,
            "checkpoint_keep": int(checkpoint_keep),
            "checkpoint_max_age_s": None,
            "checkpoint_archive_every": 0,
            "checkpoint_max_bytes": None,
            "checkpoint_layout": "append",
            "process_count": 1,
            "precision_policy": (policy.to_meta() if policy is not None
                                 else None),
            "local_rng": False, "species_shards": None,
            # multitenant provenance (informational — the manifest is an
            # ordinary single-model checkpoint either way)
            "batched": {"bucket": bkey, "tenant": t.name,
                        "zero_padding": bool(zero_pad)},
        }

    def _fanout_snapshots(state_snap, key_data, bad_snap, done_now):
        """Writer-thread item (FIFO after this segment's fetch): commit one
        ordinary single-model snapshot per tenant, carry sliced to the
        tenant's real shapes."""
        for k, t in enumerate(tenants):
            if t.writer is None:
                continue
            st_k = jax.tree.map(
                lambda x: x[k] if isinstance(x, jax.Array) else x,
                state_snap)
            if not zero_pad:
                st_k = slice_tenant_state(t.spec, st_k)
            t.writer.snapshot(int(done_now), st_k, key_data[k],
                              np.asarray(bad_snap[k]),
                              _tenant_meta(t, done_now))

    done = 0
    try:
        for si, seg in enumerate(seg_sizes):
            trans_seg = int(transient) if si == 0 else 0
            fn = _batched_runner(spec_b, updater_items, adapt_nf_res,
                                 int(seg), trans_seg, int(thin),
                                 skip_z, record, spatial._NNGP_DENSE_MAX,
                                 policy)
            args = (data_b, state_b, keys_b, bad_b)
            if policy is not None:
                args = args + (staged_tbl,)
            recs, state_b, bad_b, keys_b = fn(*args)
            skip_z = True
            done += int(seg)
            writer.submit(functools.partial(
                _collect, _pack_records(recs, record_dtype)))
            del recs
            if done in ck_marks:
                # snapshot fan-out: copies dispatched BEFORE the next
                # segment donates the carry buffers
                st_snap = jax.tree.map(
                    lambda x: jnp.copy(x) if isinstance(x, jax.Array)
                    else x, state_b)
                kd_snap = jnp.array(jax.random.key_data(keys_b))
                bad_snap = jnp.copy(bad_b)
                writer.submit(functools.partial(
                    _fanout_snapshots, st_snap, kd_snap, bad_snap, done))
            if verbose:
                log.info(f"bucket {bkey}: segment {si + 1}/"
                         f"{len(seg_sizes)} ({done}/{samples} samples, "
                         f"{K} tenants)")
            if progress_callback is not None:
                progress_callback(done, int(samples))
        writer.barrier()
    finally:
        writer.shutdown()

    # merge fetched segments (sample axis = 2 after the model/chain axes)
    recs_all = (jax.tree.map(lambda *xs: np.concatenate(xs, axis=2),
                             *host_segs)
                if len(host_segs) > 1 else host_segs[0])

    first_bad = np.asarray(bad_b)
    key_data_final = np.asarray(jax.random.key_data(keys_b))
    wall = time.perf_counter() - t0

    # per-tenant posterior assembly + divergence containment + retry
    for k, t in enumerate(tenants):
        rec_t = {name: _slice_record(name, np.asarray(arr[k]), t.spec)
                 for name, arr in recs_all.items()}
        post = Posterior(t.hM, t.spec, rec_t, samples=samples,
                         transient=int(transient), thin=thin)
        fb = first_bad[k].copy()
        post.set_chain_health(fb)
        for c in np.nonzero(fb >= 0)[0]:
            log.warn(f"tenant {t.name!r}: chain {int(c)} diverged "
                     f"(non-finite state first seen at sweep "
                     f"{int(fb[c])}); its draws are excluded from pooled "
                     "summaries")
        t.post = post
        tt = tenant_telems.get(t.name)
        if tt is not None:
            ndiv = int((fb >= 0).sum())
            tt.emit("metric", "tenant_health", tenant=t.name, bucket=bkey,
                    diverged=ndiv, n_chains=int(n_chains),
                    samples_done=int(t.base_samples) + int(samples),
                    draws_per_s=round(int(samples) * int(n_chains)
                                      / max(wall, 1e-9), 3),
                    done=True)
            tt.emit("run", "end", tenant=t.name, ok=ndiv == 0)
            tt.flush()
        if retry_diverged > 0 and (fb >= 0).any():
            st_k = jax.tree.map(
                lambda x: x[k] if isinstance(x, jax.Array) else x, state_b)
            if not zero_pad:
                st_k = slice_tenant_state(t.spec, st_k)
            _retry_tenant(
                t, fb, samples=samples, transient=transient, thin=thin,
                updater=updater, nf_cap=nf_cap, adapt_nf=adapt_nf,
                record=record, record_dtype=record_dtype,
                rng_impl=rng_impl, precision_policy=precision_policy,
                retry_diverged=retry_diverged,
                checkpoint_path=checkpoint_path, ck_every=ck_every,
                final_state=st_k, final_keys=key_data_final[k],
                meta_fn=_tenant_meta, n_chains=n_chains,
                transient_total=transient_total)

    binfo = dict(_occupancy(tenants, dims0),
                 key=bkey, n_tenants=K, zero_padding=bool(zero_pad),
                 dims={k: v for k, v in dims0.items()},
                 tenants=[t.name for t in tenants],
                 wall_s=round(wall, 4),
                 diverged={t.name: [int(c) for c in
                                    np.nonzero(first_bad[k] >= 0)[0]]
                           for k, t in enumerate(tenants)
                           if (first_bad[k] >= 0).any()})
    return binfo


def _retry_tenant(t: _Tenant, first_bad, *, samples, transient, thin,
                  updater, nf_cap, adapt_nf, record, record_dtype, rng_impl,
                  precision_policy, retry_diverged, checkpoint_path,
                  ck_every, final_state, final_keys, meta_fn, n_chains,
                  transient_total=None):
    """Per-tenant divergence splice (mirrors ``sample_mcmc``'s
    single-process retry): restart ONLY this tenant's diverged chains —
    warm from its own last healthy manifest when one exists — and repair
    that tenant's manifest sequence.  Other tenants' posteriors, manifests
    and shard files are untouched by construction (everything here runs on
    sliced per-tenant data)."""
    from .sampler import _find_warm_restart, sample_mcmc

    bad = np.nonzero(first_bad >= 0)[0]
    rng = np.random.default_rng(
        None if t.seed is None else [int(t.seed), 777])
    warm = None
    if ck_every and checkpoint_path is not None:
        d = tenant_dir(checkpoint_path, t.name)
        warm = _find_warm_restart(d, t.hM, bad, t.base_samples, samples)
    want_state = bool(ck_every)
    # burn-in accounting for a RESUMED tenant: this sub-run's transient is
    # 0 (the continuation), but its manifests and a cold restart both
    # reason in the tenant's OWN absolute iterations — the original run's
    # full transient plus the committed draws (mirrors sample_mcmc's
    # `transient + it0` cold restart)
    trans_full = int(transient if transient_total is None
                     else transient_total)
    it0 = int(t.base_samples) * int(thin)
    adapt_res = (adapt_nf if adapt_nf is not None else trans_full)
    common = dict(
        thin=thin, n_chains=len(bad),
        seed=int(rng.integers(2**31 - 1)), updater=updater, nf_cap=nf_cap,
        align_post=False, rng_impl=rng_impl, record=record,
        record_dtype=record_dtype, retry_diverged=retry_diverged - 1,
        precision_policy=precision_policy, return_state=want_state)
    if warm is not None:
        warm_state, warm_s0, warm_t_done = warm
        sub_init = jax.tree.map(
            lambda x: jnp.asarray(np.asarray(x)[bad]), warm_state)
        rem_t = (max(0, trans_full - int(warm_t_done))
                 if warm_s0 == 0 and warm_t_done else 0)
        out = sample_mcmc(
            t.hM, samples=samples - warm_s0, transient=rem_t,
            adapt_nf=[int(a) for a in
                      np.broadcast_to(adapt_res, (t.spec.nr,))],
            init_state=sub_init, **common)
        splice_from = int(warm_s0)
    else:
        # cold restart: no healthy snapshot — burn-in covers the tenant's
        # full prior progress so freshly initialised chains never splice
        # unburned draws into a resumed continuation
        out = sample_mcmc(t.hM, samples=samples,
                          transient=trans_full + it0,
                          adapt_nf=adapt_nf, **common)
        splice_from = 0
    sub_state = None
    if want_state:
        out, sub_state = out
    sub = out
    post = t.post
    for kname in post.arrays:
        a = post.arrays[kname]
        if not a.flags.writeable:
            a = a.copy()
        a[bad, splice_from:] = sub.arrays[kname]
        post.arrays[kname] = a
    fb = first_bad.copy()
    fb[bad] = sub.chain_health["first_bad_it"]
    post.set_chain_health(fb)
    t.retry_info = post.retry_info = {
        "retried_chains": tuple(int(c) for c in bad),
        "healthy_after_retry": tuple(
            bool(b < 0) for b in
            np.asarray(sub.chain_health["first_bad_it"])),
        "warm_start_samples": splice_from if warm is not None else None,
    }
    if ck_every and t.writer is not None and sub_state is not None:
        def _splice(a, b):
            a = np.asarray(a).copy()
            a[bad] = np.asarray(b)
            return jnp.asarray(a)
        final = jax.tree.map(_splice, final_state, sub_state)
        meta = dict(meta_fn(t, int(samples)), retry_info=t.retry_info)
        t.writer.rewrite_spliced(splice_from, int(samples), final,
                                 jnp.asarray(final_keys), fb, post, meta)
