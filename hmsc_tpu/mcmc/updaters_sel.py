"""Variable-selection and reduced-rank-regression updaters (reference
``R/updateBetaSel.R:3-115``, ``R/updatewRRR.R:7-80``,
``R/updatewRRRPriors.R:3-27``).

Both features modify the *effective* design matrix each sweep — RRR appends
``XRRR @ wRRR'`` columns, selection zeroes covariate blocks per species —
so the sweep recomputes ``effective_design`` from the current state and
passes ``data.replace(X=Xeff)`` to every downstream updater, mirroring the
reference's threading of the updated X list through the iteration
(``sampleMcmc.R:221-294``) without per-updater special cases.

One deliberate deviation: the reference's Metropolis ratio for BetaSel uses
``pnorm(Z; E, sd, log.p=TRUE)`` — the normal *CDF* of the latent Z
(``updateBetaSel.R:53``), which is not the density of any conditional.  On
the augmented space the correct full-conditional uses the Gaussian
log-density of Z around the candidate linear predictor; we use that.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import mixed as mx
from ..ops.linalg import chol_spd, sample_mvn_prec
from ..ops.rand import standard_gamma
from .structs import GibbsState, ModelData, ModelSpec

__all__ = ["effective_design", "selection_mask", "append_rrr", "update_w_rrr",
           "update_w_rrr_priors", "update_beta_sel"]


def append_rrr(spec: ModelSpec, X, wRRR, XRRRs):
    """Append the derived RRR columns XRRR @ wRRR' to the base design
    (per-species designs broadcast the shared columns)."""
    XB = XRRRs @ wRRR.T                                  # (ny, nc_rrr)
    if X.ndim == 3:
        return jnp.concatenate(
            [X, jnp.broadcast_to(XB, (spec.ns,) + XB.shape)], axis=2)
    return jnp.concatenate([X, XB], axis=1)


def selection_mask(spec: ModelSpec, data: ModelData, BetaSel) -> jnp.ndarray:
    """(ns, nc) multiplier: 0 where a species' switched-off covariate block
    zeroes the design (reference updateBetaSel.R:26-41)."""
    mask = jnp.ones((spec.ns, spec.nc), dtype=data.Y.dtype)
    for i in range(spec.ncsel):
        on = jnp.take(BetaSel[i].astype(mask.dtype), data.sel_spg[i])  # (ns,)
        mask = mask * (1.0 - data.sel_cov[i][None, :] * (1.0 - on[:, None]))
    return mask


def effective_design(spec: ModelSpec, data: ModelData, state: GibbsState):
    """The design matrix actually in force this sweep: base X with RRR
    columns appended and selection zeroing applied.  Returns (X, per_species)
    where ``per_species`` says whether X is (ns, ny, nc)."""
    X = data.X
    per_species = spec.x_is_list
    if spec.nc_rrr > 0:
        X = append_rrr(spec, X, state.wRRR, data.XRRRs)
    if spec.ncsel > 0:
        m = selection_mask(spec, data, state.BetaSel)     # (ns, nc)
        X = X * m[:, None, :] if per_species else X[None] * m[:, None, :]
        per_species = True
    return X, per_species


# ---------------------------------------------------------------------------
# updatewRRR (reference R/updatewRRR.R:7-80)
# ---------------------------------------------------------------------------

def update_w_rrr(spec: ModelSpec, data: ModelData, state: GibbsState,
                 key, LRan_total, shard=None) -> GibbsState:
    """GLS draw of the reduced-rank projection weights wRRR | rest: precision
    kron(XRRR'XRRR, B_rrr diag(iSigma) B_rrr') + diag(vec(Psi*tau)), with the
    reference's column-major vec layout on the (nc_rrr, nc_orrr) matrix."""
    ncr, nco, ncn = spec.nc_rrr, spec.nc_orrr, spec.nc_nrrr
    BetaN, BetaR = state.Beta[:ncn], state.Beta[ncn:]

    # residual against the non-RRR fixed part + random loadings; base X
    # carries only the nc_nrrr columns, and any selection zeroing stays in
    # force through the mask
    Xs = mx.staged("X", data.X)
    XRs = mx.staged("XRRRs", data.XRRRs)
    if spec.ncsel > 0:
        m = selection_mask(spec, data, state.BetaSel)[:, :ncn]
        if spec.x_is_list:
            LFix = mx.einsum("jyc,jc,cj->yj", Xs, m, BetaN)
        else:
            LFix = mx.einsum("yc,jc,cj->yj", Xs, m, BetaN)
    elif spec.x_is_list:
        LFix = mx.einsum("jyc,cj->yj", Xs, BetaN)
    else:
        LFix = mx.matmul(Xs, BetaN)
    S = state.Z - LFix - LRan_total

    A1 = mx.matmul(BetaR * state.iSigma[None, :], BetaR.T)  # (ncr, ncr)
    if shard is not None:                 # cross-species B-products psum
        A1 = shard.psum(A1)
    A2 = mx.matmul(XRs.T, XRs)                            # (nco, nco)
    tau = jnp.cumprod(state.DeltaRRR)                     # (ncr,)
    prior_prec = (state.PsiRRR * tau[:, None]).T.reshape(-1)  # col-major vec
    prec = jnp.kron(A2, A1) + jnp.diag(prior_prec)
    if shard is None:
        mu1 = mx.matmul(mx.matmul(BetaR * state.iSigma[None, :], S.T), XRs)
    else:
        mu1 = mx.matmul(shard.psum(
            mx.matmul(BetaR * state.iSigma[None, :], S.T)), XRs)
    rhs = mu1.T.reshape(-1)                               # col-major vec
    L = chol_spd(prec)
    eps = jax.random.normal(key, rhs.shape, dtype=rhs.dtype)
    we = sample_mvn_prec(L, rhs, eps)
    wRRR = we.reshape(nco, ncr).T                         # un-vec (col-major)
    return state.replace(wRRR=wRRR)


def update_w_rrr_priors(spec: ModelSpec, data: ModelData, state: GibbsState,
                        key) -> GibbsState:
    """Multiplicative-gamma shrinkage on wRRR (reference updatewRRRPriors.R):
    psi elementwise conjugate, delta sequential with tau recomputed per step."""
    ncr, nco = spec.nc_rrr, spec.nc_orrr
    kpsi, kdel = jax.random.split(key)
    lam2 = state.wRRR**2                                  # (ncr, nco)
    delta = state.DeltaRRR
    tau = jnp.cumprod(delta)
    a_psi = data.nuRRR / 2 + 0.5
    b_psi = data.nuRRR / 2 + 0.5 * lam2 * tau[:, None]
    psi = standard_gamma(kpsi, jnp.broadcast_to(a_psi, lam2.shape)) / b_psi
    M = psi * lam2
    Msum = M.sum(axis=1)                                  # (ncr,)
    keys = jax.random.split(kdel, ncr)
    for h in range(ncr):
        tau = jnp.cumprod(delta)
        if h == 0:
            ad = data.a1RRR + 0.5 * nco * ncr
            b0 = data.b1RRR
        else:
            ad = data.a2RRR + 0.5 * nco * (ncr - h)
            b0 = data.b2RRR
        bd = b0 + 0.5 * (tau[h:] * Msum[h:]).sum() / delta[h]
        delta = delta.at[h].set(standard_gamma(keys[h], ad) / bd)
    return state.replace(PsiRRR=psi, DeltaRRR=delta)


# ---------------------------------------------------------------------------
# updateBetaSel (reference R/updateBetaSel.R:3-115)
# ---------------------------------------------------------------------------

def update_beta_sel(spec: ModelSpec, data: ModelData, state: GibbsState,
                    key, LRan_total, shard=None) -> GibbsState:
    """Metropolis flip of each (selection, species-group) inclusion switch.
    Group and selection counts are static, so the flips unroll at trace time;
    each proposal's likelihood delta is one masked whole-array reduction."""
    Xa, per_species = effective_design(spec, data, state)   # current masked X
    if per_species:
        E = mx.einsum("jyc,cj->yj", Xa, state.Beta)
    else:
        E = mx.matmul(Xa, state.Beta)
    E = E + LRan_total
    std = state.iSigma[None, :] ** -0.5

    # full (unmasked) design for the candidate blocks, RRR columns included
    Xfull = (append_rrr(spec, data.X, state.wRRR, data.XRRRs)
             if spec.nc_rrr > 0 else data.X)

    def logdens(Ecur):
        return (-0.5 * ((state.Z - Ecur) / std) ** 2
                - jnp.log(std)) * data.Ymask

    BetaSel = list(state.BetaSel)
    for i in range(spec.ncsel):
        cov = data.sel_cov[i]
        # linear-predictor contribution of the switched block, per species
        if spec.x_is_list:
            Lg = mx.einsum("jyc,c,cj->yj", Xfull, cov, state.Beta)
        else:
            Lg = mx.matmul(Xfull * cov[None, :], state.Beta)  # (ny, ns)
        n_groups = data.sel_q[i].shape[0]
        keys = jax.random.split(jax.random.fold_in(key, i), n_groups)
        bs = BetaSel[i]
        for g in range(n_groups):
            cur = bs[g]                                   # bool scalar
            in_g = (data.sel_spg[i] == g).astype(E.dtype)  # (ns,)
            delta = Lg * in_g[None, :]
            Enew = E + jnp.where(cur, -1.0, 1.0) * delta
            lldif = ((logdens(Enew) - logdens(E)) * in_g[None, :]).sum()
            if shard is not None:         # cross-species likelihood delta
                lldif = shard.psum(lldif)
            q = data.sel_q[i][g]
            pridif = jnp.where(cur, jnp.log1p(-q) - jnp.log(q),
                               jnp.log(q) - jnp.log1p(-q))
            u = jax.random.uniform(keys[g], dtype=E.dtype)
            accept = jnp.log(u) < lldif + pridif
            bs = bs.at[g].set(jnp.where(accept, ~cur, cur))
            E = jnp.where(accept, Enew, E)
        BetaSel[i] = bs
    return state.replace(BetaSel=tuple(BetaSel))
