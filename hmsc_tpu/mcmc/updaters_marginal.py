"""Marginalized ("collapsed") updaters: ``update_gamma2`` and
``update_gamma_eta`` (reference ``R/updateGamma2.R:6-60``,
``R/updateGammaEta.R:7-206``).

Both accelerate mixing of the Beta–Gamma–Eta hierarchy by integrating
parameters out of a conditional draw.  They are exact Gibbs moves and fully
optional: the TPU sweep's batched joint BetaLambda update already removes the
per-species bottleneck that motivates them in the reference, so they default
OFF here and are enabled with ``updater={"Gamma2": True, "GammaEta": True}``
(the reference enables them by default whenever its structural gates pass,
``sampleMcmc.R:123-152,206-216``).

The default was **measured, not assumed** (round 3, TPU v5e, probit + one
unstructured level, 4 chains; see BENCHMARKS.md): enabling GammaEta loses on
throughput and min ESS/s at every scale tried, and on median ESS/s at all
but the largest (where it is within noise, 11.3 -> 11.5) —
TD-scale (50x4): 2174 -> 1490 samples/s, median ESS/s 723 -> 409;
mid (400x250): 1080 -> 364 samples/s, ESS/s 174 -> 91;
headline (1000x1000): 198 -> 48 samples/s, min ESS/s 4.1 -> 1.5.
The collapsed move pays its dense algebra without buying mixing this engine
does not already get from the batched joint (Beta, Lambda) draw, so
reference-default parity here would be a regression.

Design notes (TPU-first restatement, not a translation):

- ``update_gamma2`` draws Gamma | Z with **Beta marginalized**.  The
  reference implements only the C=NULL, iSigma==1, X-matrix corner
  (``updateGamma2.R:35-58``); here the species-marginal covariances
  X V X' + sigma_j^2 I are handled per species by a batched Woodbury
  identity, so any iSigma, NA masks, and general mGamma/UGamma work.
  Still requires no phylogeny (independence across species) and a shared X.

- ``update_gamma_eta`` performs the reference's partially-collapsed move as
  one uniform scheme for *every* level kind: (1) draw Beta | Z with Gamma
  AND the level's Eta both marginalized, (2) draw Gamma | Beta, (3) draw
  Eta | Beta, Z via the standard Eta updater.  Given (Z, Beta), Gamma and
  Eta are conditionally independent, so this sequential draw equals the
  reference's joint (Gamma,Eta) draw — and because step (3) reuses the
  engine's Eta updaters it extends to NNGP/GPP levels where the reference
  stops (``updateGammaEta.R:153-158``).  Unlike the reference (which
  discards its auxiliary Beta draw), the collapsed Beta is kept: the triple
  (Beta, Gamma, Eta_r) is then one exact joint draw from
  p(Beta, Gamma, Eta_r | Z, rest), which only improves mixing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.linalg import cho_solve

from ..ops import mixed as mx
from ..ops.linalg import chol_spd, sample_mvn_prec
from .structs import GibbsState, ModelData, ModelSpec
from . import updaters as U

__all__ = ["update_gamma2", "update_gamma_eta", "gamma_eta_gates"]


def gamma_eta_gates(spec: ModelSpec, mGamma=None) -> dict:
    """Why each collapsed updater cannot run for this model, as a dict of
    reasons (empty value = can run).  Mirrors the reference's auto-gating
    (``sampleMcmc.R:123-152``); Gamma2 additionally supports NA masks (its
    Woodbury path is per species), while GammaEta's Eta-marginal algebra
    assumes fully observed rows and gates NA off."""
    import numpy as np

    g2, ge = [], []
    if spec.has_phylo:
        g2.append("phylogeny couples species in the Beta-marginal likelihood")
    if spec.x_is_list or spec.ncsel > 0:
        g2.append("per-species design matrix")
        ge.append("per-species design matrix")
    if mGamma is not None and np.any(np.abs(np.asarray(mGamma)) > 1e-6):
        ge.append("non-zero mGamma")
    if spec.nr == 0:
        ge.append("no random levels")
    if spec.has_na:
        ge.append("NA-masked likelihood not marginalizable in closed form")
    return {"Gamma2": "; ".join(g2), "GammaEta": "; ".join(ge)}


# ---------------------------------------------------------------------------
# updateGamma2: Gamma | Z, Beta marginalized (reference updateGamma2.R)
# ---------------------------------------------------------------------------

def update_gamma2(spec: ModelSpec, data: ModelData, state: GibbsState,
                  key) -> GibbsState:
    """Per species j (no phylogeny): z_j | Gamma ~ N(X Gamma Tr_j',
    X V X' + sigma_j^2 I).  Woodbury gives the information contribution
    W_j = iSig_j (XX_j - iSig_j XX_j (iV + iSig_j XX_j)^{-1} XX_j) batched
    over species; the Gamma full conditional is then one (nc*nt) Gaussian
    with precision iUGamma + sum_j kron(Tr_j Tr_j', W_j)."""
    nc, nt, ns = spec.nc, spec.nt, spec.ns
    S = state.Z
    for r in range(spec.nr):
        S = S - U.level_loading(data.levels[r], state.levels[r])

    V = cho_solve((chol_spd(state.iV), True), jnp.eye(nc, dtype=S.dtype))
    if spec.has_na:
        XX = jnp.einsum("ip,ij,iq->jpq", data.X, data.Ymask, data.X)
        XtS = jnp.einsum("ip,ij,ij->jp", data.X, data.Ymask, S)  # (ns, nc)
    else:
        XX0 = data.X.T @ data.X
        XX = jnp.broadcast_to(XX0, (ns, nc, nc))
        XtS = (data.X.T @ S).T
    isig = state.iSigma                                   # (ns,)
    iP = state.iV[None] + isig[:, None, None] * XX        # (ns, nc, nc)
    LiP = chol_spd(iP)
    if mx.layouts_active():
        # fused batched layout (policy-gated): ONE batched cho_solve on
        # the concatenated [XX | X'z] right-hand side instead of two
        # separate solve chains against the same factor
        sol = cho_solve((LiP, True),
                        jnp.concatenate([XX, XtS[..., None]], axis=-1))
        XXiPXX = jnp.einsum("jpq,jqr->jpr", XX, sol[..., :nc])
        W = isig[:, None, None] * (XX - isig[:, None, None] * XXiPXX)
        XiSz = isig[:, None] * (XtS - isig[:, None] * jnp.einsum(
            "jpq,jq->jp", XX, sol[..., nc]))
    else:
        XXiPXX = jnp.einsum("jpq,jqr->jpr", XX,
                            cho_solve((LiP, True), XX))
        W = isig[:, None, None] * (XX - isig[:, None, None] * XXiPXX)
        # X' Sigma_j^{-1} z_j = iSig_j (X'z_j - iSig_j XX iP^{-1} X'z_j)
        XiSz = isig[:, None] * (XtS - isig[:, None] * jnp.einsum(
            "jpq,jq->jp", XX, cho_solve((LiP, True), XtS[..., None])[..., 0]))

    # column-major vec(Gamma) (t-major blocks of nc), as in update_gamma_v
    prec = data.iUGamma + jnp.einsum("jt,ju,jpq->tpuq", data.Tr, data.Tr,
                                     W).reshape(nt * nc, nt * nc)
    rhs = data.iUGamma @ data.mGamma + jnp.einsum(
        "jt,jp->tp", data.Tr, XiSz).reshape(-1)
    L = chol_spd(prec)
    eps = jax.random.normal(key, rhs.shape, dtype=rhs.dtype)
    gvec = sample_mvn_prec(L, rhs, eps)
    return state.replace(Gamma=gvec.reshape(nt, nc).T)


# ---------------------------------------------------------------------------
# updateGammaEta (reference updateGammaEta.R, restructured; see module doc)
# ---------------------------------------------------------------------------

def _factor_prior_precision(ls, lvd, lv):
    """Dense per-factor prior precision blocks iK_f (nf, np, np) for the
    level's factor prior (identity when unstructured), from the stored
    spatial grids."""
    nf, npr = ls.nf_max, ls.n_units
    if ls.spatial is None:
        return jnp.broadcast_to(jnp.eye(npr, dtype=lv.Eta.dtype),
                                (nf, npr, npr))
    if ls.spatial == "Full":
        return lvd.iWg[lv.alpha_idx]                     # (nf, np, np)
    if ls.spatial == "NNGP":
        # Vecchia factors: B = I - A, iK = B' D^{-1} B
        coef = lvd.nn_coef[lv.alpha_idx]                 # (nf, np, k)
        D = lvd.nn_D[lv.alpha_idx]                       # (nf, np)
        k = coef.shape[-1]
        A = jnp.zeros((nf, npr, npr), dtype=coef.dtype)
        rows = jnp.broadcast_to(jnp.arange(npr)[None, :, None], (nf, npr, k))
        cols = jnp.broadcast_to(lvd.nn_idx[None], (nf, npr, k))
        A = A.at[jnp.arange(nf)[:, None, None], rows, cols].add(coef)
        B = jnp.eye(npr, dtype=coef.dtype)[None] - A
        return jnp.einsum("fqp,fq,fqr->fpr", B, 1.0 / D, B)
    # GPP: K = W12 iW22 W21 + diag(dD); Woodbury with stored F = W22 + W21 idD W12
    idD = lvd.idDg[lv.alpha_idx]                         # (nf, np)
    idDW12 = lvd.idDW12g[lv.alpha_idx]                   # (nf, np, nK)
    iF = lvd.iFg[lv.alpha_idx]                           # (nf, nK, nK)
    corr = jnp.einsum("fpk,fkl,fql->fpq", idDW12, iF, idDW12)
    return jnp.eye(npr, dtype=idD.dtype)[None] * idD[:, :, None] - corr


def _w_solve_blocks(G, counts, V):
    """Solve W x = v for non-spatial W = blockdiag_p(I + count_p G) with
    factor-major vec ordering [f*np + p]; V is (np*nf, m)."""
    npr = counts.shape[0]
    nf = G.shape[0]
    W = jnp.eye(nf, dtype=G.dtype)[None] \
        + counts[:, None, None] * G[None]                     # (np, nf, nf)
    L = chol_spd(W)
    Vr = V.reshape(nf, npr, -1).transpose(1, 0, 2)            # (np, nf, m)
    X = cho_solve((L, True), Vr)
    return X.transpose(1, 0, 2).reshape(nf * npr, -1)


def update_gamma_eta(spec: ModelSpec, data: ModelData, state: GibbsState,
                     r: int, key) -> GibbsState:
    """One partially-collapsed draw for level ``r`` (x_dim==0 only):
    Beta | Z (Gamma, Eta_r marginal) -> Gamma | Beta -> Eta_r | Beta, Z."""
    ls, lvd, lv = spec.levels[r], data.levels[r], state.levels[r]
    if ls.x_dim > 0:
        return state                                     # reference skips too
    nc, ns, nt = spec.nc, spec.ns, spec.nt
    npr, nf = ls.n_units, ls.nf_max
    kb, kg, ke = jax.random.split(key, 3)

    # residual without this level's loading (Beta NOT subtracted)
    S = state.Z
    for q in range(spec.nr):
        if q != r:
            S = S - U.level_loading(data.levels[q], state.levels[q])

    id_ = state.iSigma                                   # (ns,)
    lam = U.lambda_effective(lv)[:, :, 0]                # (nf, ns)
    LamiD = lam * id_[None, :]
    G = LamiD @ lam.T                                    # Lam iD Lam' (nf, nf)
    XtX = data.X.T @ data.X
    XtS = data.X.T @ S                                   # (nc, ns)
    counts = lvd.unit_count                              # (np,)

    # T = kron(LamiD, PtX): rows [f*np+p], cols [j*nc+c] (species-major vec)
    PtX = jax.ops.segment_sum(data.X, lvd.pi_row, num_segments=npr)  # (np, nc)
    T = jnp.einsum("fj,pc->fpjc", LamiD, PtX).reshape(nf * npr, ns * nc)
    PtS = jax.ops.segment_sum(S, lvd.pi_row, num_segments=npr)       # (np, ns)
    u = (PtS @ LamiD.T).T.reshape(-1)                    # [f*np+p] ordering

    spatial = ls.spatial is not None
    if spatial:
        iK = _factor_prior_precision(ls, lvd, lv)        # (nf, np, np)
        Wd = jnp.zeros((nf, npr, nf, npr))
        fr = jnp.arange(nf)
        Wd = Wd.at[fr, :, fr, :].add(iK)
        Wd = Wd + jnp.einsum("fg,p,pq->fpgq", G, counts,
                             jnp.eye(npr))
        Lw = chol_spd(Wd.reshape(nf * npr, nf * npr))
        if mx.layouts_active():
            # fused batched layout (policy-gated): one solve on [T | u]
            sol = cho_solve((Lw, True),
                            jnp.concatenate([T, u[:, None]], axis=1))
            iWT, iWu = sol[:, :-1], sol[:, -1]
        else:
            iWT = cho_solve((Lw, True), T)
            iWu = cho_solve((Lw, True), u)
    else:
        if mx.layouts_active():
            sol = _w_solve_blocks(G, counts,
                                  jnp.concatenate([T, u[:, None]], axis=1))
            iWT, iWu = sol[:, :-1], sol[:, -1]
        else:
            iWT = _w_solve_blocks(G, counts, T)
            iWu = _w_solve_blocks(G, counts, u[:, None])[:, 0]

    # Eta-marginal likelihood precision and rhs on vec(Beta)
    jr = jnp.arange(ns)
    blk = jnp.zeros((ns, nc, ns, nc), dtype=S.dtype)
    blk = blk.at[jr, :, jr, :].set(id_[:, None, None] * XtX[None])
    tmp1 = blk.reshape(ns * nc, ns * nc) - T.T @ iWT
    rhs = (XtS * id_[None, :]).T.reshape(-1) - T.T @ iWu

    # Gamma-marginal prior covariance A = (Tr x I) U_G (Tr x I)' + kron(Q, V)
    V = cho_solve((chol_spd(state.iV), True), jnp.eye(nc, dtype=S.dtype))
    UG = data.UGamma.reshape(nt, nc, nt, nc)
    A = jnp.einsum("jt,tcud,Ju->jcJd", data.Tr, UG, data.Tr)
    if spec.has_phylo:
        e = data.Qeig[state.rho_idx]
        Q = (data.U * e[None, :]) @ data.U.T
    else:
        Q = jnp.eye(ns, dtype=S.dtype)
    A = (A + jnp.einsum("jJ,cd->jcJd", Q, V)).reshape(ns * nc, ns * nc)
    iA = cho_solve((chol_spd(A), True), jnp.eye(ns * nc, dtype=S.dtype))

    M = iA + tmp1
    Lm = chol_spd(M)
    eps = jax.random.normal(kb, rhs.shape, dtype=rhs.dtype)
    Beta = sample_mvn_prec(Lm, rhs, eps).reshape(ns, nc).T
    state = state.replace(Beta=Beta)

    # Gamma | Beta (same full conditional as update_gamma_v's Gamma block)
    state = U.gamma_given_beta(spec, data, state, kg)

    # Eta_r | Beta, Z via the standard Eta updater
    LFix = U.linear_fixed(spec, data, state.Beta)
    S_eta = S - LFix
    if spatial:
        from .spatial import update_eta_spatial
        lv_new = update_eta_spatial(spec, data, state, r, ke, S_eta)
    else:
        lv_new = U.update_eta_nonspatial(spec, data, state, r, ke, S_eta)
    levels = list(state.levels)
    levels[r] = lv_new
    return state.replace(levels=tuple(levels))
