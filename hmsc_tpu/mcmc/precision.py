"""Per-block mixed-precision policy for the Gibbs sweep.

PR 8's cost ledger and measured per-updater wall shares name exactly
which Gibbs blocks dominate each canonical spec; this module spends that
data on the training sweep itself.  A :class:`PrecisionPolicy` maps named
schedule blocks (:func:`~hmsc_tpu.mcmc.sweep.make_sweep_schedule`) to a
reduced compute dtype: inside a policy'd block the heavy dots and grams
run bf16-compute / f32-accumulate (``preferred_element_type`` on every
routed contraction — :mod:`hmsc_tpu.ops.mixed`), reductions and every
Cholesky/triangular-solve pivot stay f32-pinned, and the block's
*sweep-invariant* model-data operands (the phylo eigenbasis ``U``, the
spatial ``iWg`` grid, design matrices) are **staged**: cast to bf16 once
per run and passed to the compiled runner as a real argument, so the hot
blocks stream half the bytes every sweep instead of paying a cast
(measured: XLA does not hoist converts out of the sweep scan, so an
in-trace cast would *add* traffic).

Alongside the dtype map, a policy activates the **fused batched Cholesky
layouts** (``batched_layouts``): the three-triangular-solve
``sample_mvn_prec`` collapses to one forward/back pair, the GPP
per-unit inversion becomes one batched ``cho_solve``, the collapsed
updaters fuse their paired solves, and the spatial quadratic grids
restructure into single-pass contractions — one fused batched kernel per
block instead of K small ones.

Contracts:

- ``precision_policy=None`` (the default) is the exact pre-policy
  engine: no wrapper fires, every traced program is byte-identical to
  the committed jaxpr fingerprints (the lint gate verifies this).
- :data:`PRECISION_AGREEMENT_TOL` pins the one-sweep draw-stream
  agreement between the policy'd sweep and the f32 sweep from an
  identical state (normalised max-abs per state leaf, the
  ``SHARD_AGREEMENT_TOL`` convention).  Unlike psum rounding this is a
  genuine precision trade: the policy targets a *perturbed-within-
  tolerance* posterior, exactly like ``compact --dtype bfloat16``'s
  recorded-tolerance serving artifacts.
- ``precision_tolerance.json`` (next to this module) records the
  *measured* per-block deviation of every default-policy'd block on its
  canonical spec — the training-side mirror of the serving compactor's
  ``cast_tolerance()``.  Re-record with
  ``python -m hmsc_tpu profile --update-precision``.
"""

from __future__ import annotations

import dataclasses
import json
import os

__all__ = ["PrecisionPolicy", "PRECISION_AGREEMENT_TOL", "TOLERANCE_PATH",
           "SUPPORTED_BLOCKS", "classify_spec", "default_policy",
           "resolve_policy", "stage_data", "staged_pspecs",
           "measure_policy_tolerance", "load_tolerance", "save_tolerance",
           "policy_ledger_models"]

# One-sweep draw-stream agreement between the default-policy'd sweep and
# the f32 sweep from an identical mid-chain state: max abs error per
# state leaf normalised by the leaf's max magnitude (the
# SHARD_AGREEMENT_TOL convention).  Measured on the canonical specs
# (tests/test_precision.py): bf16 grams carry ~4e-3 relative rounding
# into the conditional means/covariances, and one sweep of draws lands
# ~1e-3..2e-2 off the f32 stream (worst leaf, spatial Full).  Pinned
# with headroom at 6e-2; per-block measurements live in the committed
# precision_tolerance.json.
PRECISION_AGREEMENT_TOL = 6e-2

TOLERANCE_PATH = os.path.join(os.path.dirname(__file__),
                              "precision_tolerance.json")
TOLERANCE_VERSION = 1

# schedule blocks with a mixed-precision implementation (heavy dots and
# grams routed through hmsc_tpu.ops.mixed); a policy naming any other
# block is rejected at construction
SUPPORTED_BLOCKS = ("BetaLambda", "GammaV", "Rho", "Eta", "EtaSpatial",
                    "Alpha", "Interweave", "wRRR", "BetaSel",
                    "Gamma2", "GammaEta")

# ledger-driven default targets per canonical model class: the top
# wall-share blocks of each class (cost-ledger byte ranking at the
# scaled `scale:` shapes, intersected with SUPPORTED_BLOCKS).  The
# committed ledger's `precision` section records the measured bytes
# ratio per block; the >= 1.5x byte gate (tests/test_precision.py)
# covers the gather-dominated targets of the TWO SPATIAL canonical
# variants (Full + GPP).  The dot-bound base/rrr/sel targets carry
# committed ratios BELOW 1 on the CPU cost model (bf16-dot
# legalisation materialises f32 upcasts the MXU does not pay) — they
# are MXU-motivated, opt-in, and transparently recorded, NOT
# gate-protected; see BENCHMARKS.md "Mixed precision".
_DEFAULT_TARGETS = {
    "base": ("BetaLambda", "GammaV", "Rho"),
    # Alpha is deliberately NOT targeted: its grid einsum lowers to a
    # dot, and XLA's float normalisation materialises f32 upcasts of
    # bf16 dot operands — the committed ledger measured only 1.2x there
    # vs 1.5-1.9x on the gather-dominated blocks below (ledger-driven
    # exclusion; see BENCHMARKS.md)
    "spatial": ("EtaSpatial", "Interweave"),
    "rrr": ("wRRR", "BetaLambda", "GammaV"),
    "sel": ("BetaSel", "BetaLambda", "GammaV"),
}

# sweep-invariant model-data arrays staged to bf16 per class; per-level
# arrays use "<field>_<r>".  Missing/None fields are skipped at staging,
# so the spatial table lists every spatial method's grids and each model
# stages whichever its level actually carries (Full: iWg; NNGP: the
# Vecchia neighbour grids; GPP: the knot grids).
_DEFAULT_STAGED = {
    "base": ("U", "Qeig", "UTr", "X", "Tr"),
    "spatial": ("iWg_0", "nn_coef_0", "nn_D_0", "idDg_0", "idDW12g_0",
                "Fg_0", "iFg_0", "X"),
    "rrr": ("X", "XRRRs"),
    "sel": ("X",),
}


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Hashable per-block precision policy.

    ``blocks``: schedule-block names computed at ``dtype``;
    ``staged``: model-data array names staged to ``dtype`` once per run
    (``"U"`` for :class:`ModelData` fields, ``"iWg_0"`` for level 0's
    grids); ``batched_layouts``: fused batched Cholesky/solve layouts in
    the policy'd blocks.  ``dtype="float32"`` gives a layout-only policy
    (exact compute, restructured kernels)."""
    blocks: tuple
    staged: tuple = ()
    dtype: str = "bfloat16"
    batched_layouts: bool = True

    def __post_init__(self):
        object.__setattr__(self, "blocks", tuple(self.blocks))
        object.__setattr__(self, "staged", tuple(self.staged))
        bad = [b for b in self.blocks if b not in SUPPORTED_BLOCKS]
        if bad:
            raise ValueError(
                f"no mixed-precision implementation for block(s) {bad}; "
                f"supported: {SUPPORTED_BLOCKS}")
        if self.dtype not in ("bfloat16", "float32"):
            raise ValueError("PrecisionPolicy.dtype must be 'bfloat16' or "
                             f"'float32', got {self.dtype!r}")

    def dtype_for(self, block: str):
        """Compute dtype for a schedule block, or None when unpolicied."""
        return self.dtype if block in self.blocks else None

    def to_meta(self) -> dict:
        """JSON-serializable form (checkpoint metadata: the policy changes
        the draw stream, so resume must restore it exactly)."""
        return {"blocks": list(self.blocks), "staged": list(self.staged),
                "dtype": self.dtype,
                "batched_layouts": bool(self.batched_layouts)}

    @classmethod
    def from_meta(cls, meta: dict) -> "PrecisionPolicy":
        return cls(blocks=tuple(meta["blocks"]),
                   staged=tuple(meta.get("staged", ())),
                   dtype=meta.get("dtype", "bfloat16"),
                   batched_layouts=bool(meta.get("batched_layouts", True)))


def classify_spec(spec) -> str:
    """The canonical model class whose ledger entry drives the default
    policy for this spec."""
    if any(ls.spatial is not None for ls in spec.levels):
        return "spatial"
    if spec.nc_rrr > 0:
        return "rrr"
    if spec.ncsel > 0:
        return "sel"
    return "base"


def _block_applies(name: str, spec) -> bool:
    if name == "Rho":
        return bool(spec.has_phylo)
    if name in ("EtaSpatial", "Alpha"):
        return any(ls.spatial is not None for ls in spec.levels)
    if name in ("Eta", "Interweave"):
        return spec.nr > 0
    if name == "wRRR":
        return spec.nc_rrr > 0
    if name == "BetaSel":
        return spec.ncsel > 0
    return True


def default_policy(spec, ledger: dict | None = None):
    """The ledger-driven default policy for this spec's model class, or
    ``None`` when no targeted block applies.

    The committed cost ledger's ``precision`` section (written by
    ``profile --static --update-ledger``) records, per canonical class,
    the targeted blocks and their measured per-sweep bytes ratio at the
    scaled ledger shapes; the selection falls back to the in-code
    defaults when the ledger is absent (fresh checkout mid-edit)."""
    cls_ = classify_spec(spec)
    blocks = _DEFAULT_TARGETS[cls_]
    staged = _DEFAULT_STAGED[cls_]
    if ledger is None:
        from ..obs.profile import load_ledger
        ledger = load_ledger()
    sel = (ledger or {}).get("precision", {}).get(cls_)
    if sel:
        blocks = tuple(sel.get("blocks", blocks))
        staged = tuple(sel.get("staged", staged))
    blocks = tuple(b for b in blocks if _block_applies(b, spec))
    if not blocks:
        return None
    return PrecisionPolicy(blocks=blocks, staged=staged)


def resolve_policy(precision_policy, spec):
    """Normalise ``sample_mcmc``'s ``precision_policy=`` argument:
    ``None`` (exact engine) | ``"auto"``/``"default"`` (ledger-driven) |
    a :class:`PrecisionPolicy` | its ``to_meta()`` dict."""
    if precision_policy is None:
        return None
    if isinstance(precision_policy, str):
        if precision_policy in ("auto", "default"):
            return default_policy(spec)
        raise ValueError(
            f"precision_policy must be None, 'auto', a PrecisionPolicy or "
            f"its to_meta() dict, got {precision_policy!r}")
    if isinstance(precision_policy, dict):
        return PrecisionPolicy.from_meta(precision_policy)
    if isinstance(precision_policy, PrecisionPolicy):
        return precision_policy
    raise ValueError(f"precision_policy must be None, 'auto', a "
                     f"PrecisionPolicy or its to_meta() dict, got "
                     f"{type(precision_policy).__name__}")


# ---------------------------------------------------------------------------
# staging
# ---------------------------------------------------------------------------

def _resolve_staged(data, name: str):
    head, _, tail = name.rpartition("_")
    if tail.isdigit() and head:
        r = int(tail)
        if r >= len(data.levels):
            return None
        return getattr(data.levels[r], head, None)
    return getattr(data, name, None)


def stage_data(data, policy: PrecisionPolicy) -> dict:
    """The bf16 shadow table for ``policy.staged``: one cast per run,
    passed to the compiled runner as a real argument (never a baked
    constant) and resolved inside policy'd blocks by
    :func:`hmsc_tpu.ops.mixed.staged`.  Non-float and absent fields are
    skipped; a ``float32`` policy stages nothing (layout-only)."""
    import jax.numpy as jnp
    if policy.dtype == "float32":
        return {}
    dt = jnp.dtype(policy.dtype)
    out = {}
    for name in policy.staged:
        arr = _resolve_staged(data, name)
        if arr is None or not hasattr(arr, "dtype"):
            continue
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        out[name] = arr.astype(dt)
    return out


def staged_pspecs(staged: dict, spec, species_axis: str,
                  x_is_list: bool = False, site_axis: str | None = None):
    """PartitionSpecs for the staged shadow table on the sharded mesh:
    each entry shards exactly like its f32 counterpart (the committed
    :data:`~hmsc_tpu.mcmc.partition.DATA_SPECIES_DIMS` /
    :data:`~hmsc_tpu.mcmc.partition.DATA_SITE_DIMS` tables, resolved
    through the per-level name suffix, with ``tree_pspecs``'s
    per-species-design special case for ``X``), everything else
    replicated.  With ``site_axis`` the row/unit dims shard too — guarded
    on the dim actually being ``spec.ny``-sized (row arrays) or the
    owning level's ``n_units`` (the NNGP/GPP per-unit structure grids),
    the same guards ``tree_pspecs`` applies to the f32 originals — so a
    precision policy composes with ``site_shards > 1``."""
    from jax.sharding import PartitionSpec as P

    from .partition import (DATA_SITE_DIMS, DATA_SPECIES_DIMS,
                            _SITE_UNIT_NAMES)

    out = {}
    for name, arr in staged.items():
        head, _, tail = name.rpartition("_")
        lvl = int(tail) if (tail.isdigit() and head) else None
        base = head if lvl is not None else name
        ax = [None] * arr.ndim
        d = DATA_SPECIES_DIMS.get(base)
        if base == "X":
            # a per-species design list is (ns, ny, nc): sharded on dim 0,
            # exactly like its f32 counterpart in tree_pspecs
            d = 0 if x_is_list else None
        if d is not None and d < arr.ndim and arr.shape[d] == spec.ns:
            ax[d] = species_axis
        if site_axis is not None:
            ds = DATA_SITE_DIMS.get(base)
            if base == "X" and x_is_list:
                ds = None          # (ns, ny, nc) lists are site-gated off
            if ds is not None and ds < arr.ndim and ax[ds] is None:
                if base in _SITE_UNIT_NAMES:
                    want = (spec.levels[lvl].n_units
                            if lvl is not None and lvl < len(spec.levels)
                            else -1)
                else:
                    want = spec.ny
                if arr.shape[ds] == want:
                    ax[ds] = site_axis
        out[name] = P(*ax)
    return out


# ---------------------------------------------------------------------------
# recorded per-block tolerance (the training-side cast_tolerance())
# ---------------------------------------------------------------------------

def _leaf_dev(a, b) -> float:
    """Max abs deviation normalised by the reference leaf's magnitude
    (the SHARD_AGREEMENT_TOL convention)."""
    import numpy as np
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if a.size == 0 or not np.issubdtype(a.dtype, np.floating):
        return 0.0
    scale = max(float(np.max(np.abs(a))), 1e-6)
    return float(np.max(np.abs(a - b)) / scale)


def _carry_dev(ca, cb) -> float:
    import jax
    la, lb = jax.tree.leaves(ca), jax.tree.leaves(cb)
    devs = [_leaf_dev(x, y) for x, y in zip(la, lb)
            if hasattr(x, "dtype") and x.dtype.kind == "f"]
    return max(devs) if devs else 0.0


def measure_policy_tolerance(models=None, warmup: int = 2) -> dict:
    """Measured per-block deviation of each default-policy'd block on its
    canonical spec: the f32 block chain advances a warmed mid-sweep
    carry, and at every policy'd block the policy variant is evaluated
    on the SAME carry and compared (normalised max-abs over the carry),
    plus the whole-sweep one-pass agreement.  Deterministic on a fixed
    backend — committed like the cost ledger and drift-checked loosely
    (float tolerances) by the tier-1 suite."""
    import jax

    from ..analysis.jaxpr_rules import _build, _canonical_models
    from ..ops import mixed
    from .sweep import make_sweep, make_sweep_schedule, sweep_prologue

    factories = _canonical_models()
    names = tuple(models) if models else tuple(factories)
    out: dict[str, dict] = {}
    for mname in names:
        spec, data, state = _build(factories[mname]())
        policy = default_policy(spec, ledger={})   # in-code defaults
        if policy is None:
            continue
        staged = stage_data(data, policy)
        zeros = tuple(0 for _ in range(spec.nr))
        key = jax.random.key(23, impl="threefry2x32")
        sweep = jax.jit(make_sweep(spec, None, zeros))
        for _ in range(max(0, int(warmup))):
            key, sub = jax.random.split(key)
            state = sweep(data, state, sub)
        state = jax.block_until_ready(state)
        key, sub = jax.random.split(key)

        steps_f32 = make_sweep_schedule(spec, None, zeros)
        steps_mp = make_sweep_schedule(spec, None, zeros, precision=policy)
        state_it, ks = jax.jit(sweep_prologue)(state, sub)
        carry = (state_it, None, None, None)
        blocks: dict[str, dict] = {}
        for (bname, blk_f32), (_, blk_mp) in zip(steps_f32, steps_mp):
            carry_next = jax.jit(blk_f32)(data, carry, ks)
            if policy.dtype_for(bname) is not None:
                def run_mp(data, carry, ks, staged):
                    with mixed.staged_scope(staged):
                        return blk_mp(data, carry, ks)
                carry_mp = jax.jit(run_mp)(data, carry, ks, staged)
                blocks[bname] = {
                    "max_rel": round(_carry_dev(carry_next, carry_mp), 8)}
            carry = carry_next

        sweep_mp = make_sweep(spec, None, zeros, precision=policy)
        # deliberate replay of the SAME subkey: the policy'd sweep must be
        # compared draw-for-draw against the f32 pass traced above
        # hmsc: ignore[rng-key-reuse]
        state_mp = jax.jit(sweep_mp)(data, state, sub, staged)
        out[mname] = {
            "policy": policy.to_meta(),
            "blocks": blocks,
            "sweep_max_rel": round(_carry_dev(carry[0], state_mp), 8),
        }
    return {"version": TOLERANCE_VERSION, "models": out}


def load_tolerance(path: str = TOLERANCE_PATH) -> dict | None:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (FileNotFoundError, ValueError):
        return None
    if doc.get("version") != TOLERANCE_VERSION:
        return None
    return doc


def save_tolerance(doc: dict, path: str = TOLERANCE_PATH) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")


# ---------------------------------------------------------------------------
# scaled ledger models (the shapes the policy byte accounting is honest at)
# ---------------------------------------------------------------------------

def policy_ledger_models():
    """Scaled variants of the canonical model classes for the cost
    ledger's ``scale:`` / ``scale+mp:`` entries: species-heavy shapes
    (the JSDM regime — PR 10's acceptance model is 10k species x 256
    sites) where the policy's staged operands (the (ns, ns) phylo
    eigenbasis, the (G, np, np) spatial grid) carry the block bytes.
    The tiny audit specs stay the fingerprint/tolerance substrate; these
    exist so the committed per-block byte ratios mean something."""
    import numpy as np
    import pandas as pd

    from ..model import Hmsc
    from ..random_level import HmscRandomLevel, set_priors_random_level
    from ..analysis.jaxpr_rules import _canonical_models

    base = _canonical_models()
    models = {
        # phylo base at ns >> ny: U is (ns, ns), Qeig (101, ns)
        "base": lambda: base["base"](ny=48, ns=256),
        # rrr / sel at moderate species counts (no staged grid dominates;
        # the committed ratios record whatever the bf16 routing buys)
        "rrr": lambda: base["rrr"](ny=96, ns=64),
        "sel": lambda: base["sel"](ny=96, ns=64),
    }

    def spatial(ny=192, ns=8, n_units=96, method="Full", n_knots=None):
        rng = np.random.default_rng(12)
        X = np.column_stack([np.ones(ny), rng.standard_normal((ny, 1))])
        Y = rng.standard_normal((ny, ns))
        units = [f"u{i:03d}" for i in rng.integers(0, n_units, ny)]
        for i in range(n_units):
            units[i % ny] = f"u{i:03d}"
        study = pd.DataFrame({"lvl": units})
        s_df = pd.DataFrame(rng.uniform(size=(n_units, 2)),
                            index=sorted(set(units)), columns=["x", "y"])
        kw = dict(s_data=s_df, s_method=method)
        if method == "GPP":
            kw["s_knot"] = rng.uniform(size=(int(n_knots or 16), 2))
        rl = HmscRandomLevel(**kw)
        set_priors_random_level(rl, nf_max=2, nf_min=2)
        return Hmsc(Y=Y, X=X, distr="normal", study_design=study,
                    ran_levels={"lvl": rl})

    models["spatial"] = spatial
    # the knot-based predictive process: the SECOND spatial canonical
    # method (reference vignette 4), whose (G, np, nK) knot grids are the
    # gather-dominated byte stream the policy stages — with Full, the two
    # spatial specs the >= 1.5x acceptance gate rides on
    models["gpp"] = lambda: spatial(ny=448, ns=8, n_units=384,
                                    method="GPP", n_knots=16)
    return models
