"""Spatially structured latent factors: Eta draws and the GP-range (alpha)
grid sampler (reference ``R/updateEta.R:110-196``, ``R/updateAlpha.R:3-86``).

Three methods, as in the reference:

- ``Full``  — exact GP; the (np*nf) coupled precision (block-diagonal iW(alpha_h)
  plus the factor coupling) is assembled dense and factorised once.
- ``NNGP``  — Vecchia sparse precision stored as neighbour-index/coefficient
  grids.  Below ``_NNGP_DENSE_MAX`` coefficients the precision is densified
  on the fly from gathers (a dense np x np build beats sparse scatter on TPU
  for moderate np); above it, a **matrix-free CG sampler** takes over: the
  Vecchia factor is only ever *applied* (gathers + one segment_sum per
  matvec), the draw is exact-by-construction via perturbation optimisation
  (rhs perturbed with RiW' eps for the prior term and per-cell
  sqrt(iSigma)-weighted noise for the likelihood term, so the CG solution
  has exactly the full-conditional law up to CG tolerance), and the current
  Eta warm-starts the solve.  This is the regime the reference recommends
  NNGP for (>1000 units, vignette_4_spatial.Rmd:171-175) but cannot reach
  with its own dense (np*nf)^2 cholesky.
- ``GPP``   — knot-based predictive process: Woodbury identity with per-site
  nf x nf batched blocks and an (nf*nK) knot correction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.linalg import cho_solve, solve_triangular

from ..ops import mixed as mx
from ..ops.linalg import chol_spd, sample_mvn_prec
from .structs import GibbsState, LevelState, ModelData, ModelSpec
from .updaters import _masked_level_gram, lambda_effective

__all__ = ["update_eta_spatial", "update_alpha", "vecchia_ops",
           "vecchia_ops_site", "vecchia_cg_draw", "gpp_factor", "gpp_draw"]

# above this many (units x factors) coefficients, NNGP Eta switches from the
# dense joint cholesky to the matrix-free CG sampler.  Overridable via
# HMSC_TPU_NNGP_DENSE_MAX (read at import) so the crossover can be A/B'd on
# hardware without an edit.  Default set from a measured sweep on the v5
# chip (whole-sweep samples/s at config-3b shape, nf=2, best-of-3):
#   coeff   250: dense 1321/s  cg 1150/s   (dense 1.15x)
#   coeff   500: dense  503/s  cg  943/s   (cg 1.87x)
#   coeff  1000: dense  492/s  cg  851/s   (cg 1.73x)
#   coeff  2000: dense  145/s  cg  531/s   (cg 3.65x)  <- config 3b
# so dense only pays below ~256 coefficients, where the (coeff x coeff)
# cholesky is a trivially small kernel and CG's fixed iteration count costs
# more dispatches than it saves FLOPs.
import os as _os

_NNGP_DENSE_MAX = int(_os.environ.get("HMSC_TPU_NNGP_DENSE_MAX", "256"))


# ---------------------------------------------------------------------------
# shared NNGP / GPP precision algebra — one source for the training-side
# updaters below AND the conditional-prediction refresh
# (predict/predict._conditional_mcmc), so a numerics fix lands in both
# ---------------------------------------------------------------------------

def vecchia_ops(nn, coef, sqD, LiSL):
    """Matrix-free apply closures for the NNGP full-conditional precision
    ``P = blkdiag_f(RiW_f' RiW_f) + unitdiag(LiSL_u)``.

    ``nn`` (np, k) neighbour indices; ``coef`` (nf, np, k) autoregressive
    coefficients and ``sqD`` (nf, np) sqrt conditional variances at each
    factor's alpha; ``LiSL`` (np, nf, nf) per-unit likelihood gram.
    Returns ``(riw_t, pmv)``: RiW' u and the full P x, both (np, nf)."""
    npr, k_nb = nn.shape
    nf = LiSL.shape[-1]

    def riw_t(u):
        t = u / sqD.T
        contrib = -jnp.einsum("fik,if->ikf", coef, t)   # (np, k, nf)
        return t + jax.ops.segment_sum(
            contrib.reshape(npr * k_nb, nf), nn.reshape(-1),
            num_segments=npr)

    def pmv(x):
        xg = x[nn]                                      # (np, k, nf)
        red = jnp.einsum("fik,ikf->if", coef, xg)
        Rx = (x - red) / sqD.T
        return riw_t(Rx) + jnp.einsum("ufg,ug->uf", LiSL, x)

    return riw_t, pmv


def vecchia_ops_site(nn, coef, sqD, LiSL, npr: int, shard):
    """Site-sharded counterpart of :func:`vecchia_ops`: the Vecchia
    factor's rows (and the per-unit likelihood gram) are LOCAL unit
    blocks, iterates stay full-width replicated, and each application
    reassembles with ONE psum over the site axis — so the per-device
    apply work is O(np_local · k · nf) while every shard agrees on the
    full iterate.

    ``nn`` (np_local, k) local neighbour rows holding GLOBAL unit
    indices; ``coef`` (nf, np_local, k) / ``sqD`` (nf, np_local) the
    local grid slices; ``LiSL`` (np_local, nf, nf) the local unit block
    of the psum'd gram; ``npr`` the GLOBAL unit count.  Returns
    ``(riw_t, pmv)`` where ``riw_t`` maps a LOCAL-row residual to the
    full (np, nf) RiW' image and ``pmv`` maps a full iterate to the
    full P x."""
    np_l, k_nb = nn.shape
    nf = LiSL.shape[-1]

    def _scatter_local(local):
        full = jnp.zeros((npr, nf), dtype=local.dtype)
        return jax.lax.dynamic_update_slice_in_dim(
            full, local, shard.site_offset(npr), axis=0)

    def _riw_t_parts(t_l):
        contrib = -jnp.einsum("fik,if->ikf", coef, t_l)   # (np_l, k, nf)
        return jax.ops.segment_sum(contrib.reshape(np_l * k_nb, nf),
                                   nn.reshape(-1), num_segments=npr)

    def riw_t(u_l):
        t_l = u_l / sqD.T
        return shard.psum_site(_riw_t_parts(t_l) + _scatter_local(t_l))

    def pmv(x):
        x_l = jax.lax.dynamic_slice_in_dim(x, shard.site_offset(npr),
                                           np_l, axis=0)
        xg = x[nn]                                      # (np_l, k, nf)
        red = jnp.einsum("fik,ikf->if", coef, xg)
        r_l = (x_l - red) / sqD.T
        t_l = r_l / sqD.T
        lik = jnp.einsum("ufg,ug->uf", LiSL, x_l)
        return shard.psum_site(_riw_t_parts(t_l)
                               + _scatter_local(t_l + lik))

    return riw_t, pmv


def vecchia_cg_draw(riw_t, pmv, F, b_like, eps1, x0, tol=1e-5, maxiter=500):
    """Perturbation-optimisation draw x ~ N(P^{-1}(F), P^{-1}) via CG.

    ``b_like`` must be noise with covariance equal to the likelihood part of
    P (sum of lam sqrt(iSigma)-weighted normals per unit); ``eps1`` (np, nf)
    standard normals feed the prior part through RiW'.  Returns the iterate
    and its relative residual — the caller decides the stall policy (the
    sweep poisons to NaN for divergence containment; conditional prediction
    keeps the iterate and warns)."""
    b = F + riw_t(eps1) + b_like
    x, _ = jax.scipy.sparse.linalg.cg(pmv, b, x0=x0, tol=tol,
                                      maxiter=maxiter)
    res = jnp.linalg.norm(pmv(x) - b) / jnp.maximum(jnp.linalg.norm(b),
                                                    1e-30)
    return x, res


def gpp_factor(LiSL, idD, M1, Fm, shard=None):
    """Step-invariant factorisation of the GPP full-conditional
    ``P = A - M F_blk^{-1} M'`` with ``A = LiSL + unitdiag(idD)`` (reference
    updateEta.R:148-196).  ``idD`` (nf, np), ``M1`` (nf, np, nK), ``Fm``
    (nf, nK, nK); returns the payload consumed by :func:`gpp_draw`.
    Site-sharded (``shard`` with sites): the per-unit A blocks are LOCAL,
    and the knot-space correction ``M' iA M`` — a sum over every unit —
    is completed by one psum over the site axis before the (nf·nK)
    factorisation runs replicated."""
    npr, nf = LiSL.shape[0], LiSL.shape[-1]
    nK = M1.shape[2]
    A = LiSL + jnp.eye(nf, dtype=idD.dtype)[None] * idD.T[:, :, None]
    LA = chol_spd(A)
    if mx.layouts_active():
        # fused batched layout: ONE batched forward/back solve pair over
        # the np-unit batch instead of a vmapped closure of two
        # per-unit triangular solves (policy-gated; the default path
        # below is the fingerprint-pinned original)
        iA = cho_solve((LA, True),
                       jnp.broadcast_to(jnp.eye(nf, dtype=idD.dtype),
                                        A.shape))       # (np, nf, nf)
    else:
        iA = jax.vmap(lambda Lc: solve_triangular(
            Lc.T, solve_triangular(Lc, jnp.eye(nf, dtype=idD.dtype),
                                   lower=True),
            lower=False))(LA)                           # (np, nf, nf)
    # H = blockdiag(F_h) - M' iA M   over the (nf*nK) knot space
    MtAM = jnp.einsum("hum,uhg,gun->hmgn", M1, iA, M1)
    if shard is not None and shard.has_sites:
        MtAM = shard.psum_site(MtAM)      # cross-site unit sum
    H = -MtAM
    fi = jnp.arange(nf)
    H = H.at[fi, :, fi, :].add(Fm)
    LH = chol_spd(H.reshape(nf * nK, nf * nK))
    LiA = jnp.linalg.cholesky(iA)
    return M1, iA, LiA, LH, nK


def gpp_draw(payload, F, eps1, eps2, shard=None):
    """Exact draw eta ~ N(P^{-1} F, P^{-1}) from a :func:`gpp_factor`
    payload: mean via double Woodbury, noise as LiA eps1 + iA M LH^{-T} eps2
    (covariance exactly P^{-1}).  Site-sharded: the knot projection
    ``M' iA F`` sums over units — one psum completes it; everything else
    is per-unit local."""
    M1, iA, LiA, LH, nK = payload
    nf = iA.shape[-1]
    iA_rhs = jnp.einsum("uhg,ug->uh", iA, F)
    Mt_iA_rhs = jnp.einsum("hum,uh->hm", M1, iA_rhs)
    if shard is not None and shard.has_sites:
        Mt_iA_rhs = shard.psum_site(Mt_iA_rhs)
    Mt_iA_rhs = Mt_iA_rhs.reshape(-1)
    corr = solve_triangular(
        LH.T, solve_triangular(LH, Mt_iA_rhs, lower=True),
        lower=False).reshape(nf, nK)
    Mx = jnp.einsum("hum,hm->uh", M1, corr)
    mean = iA_rhs + jnp.einsum("uhg,ug->uh", iA, Mx)
    noise1 = jnp.einsum("uhg,ug->uh", LiA, eps1)
    w = solve_triangular(LH.T, eps2, lower=False).reshape(nf, nK)
    Mw = jnp.einsum("hum,hm->uh", M1, w)
    return mean + noise1 + jnp.einsum("uhg,ug->uh", iA, Mw)


def _nngp_dense_iW(lvd, alpha_idx, npr, r: int = 0, shard=None):
    """Densify the Vecchia precision iW = RiW' RiW for each factor's alpha.

    RiW rows: (e_i - sum_k A[i,k] e_{nn[i,k]}) / sqrt(D_i); built by scattering
    the neighbour coefficients into an (np, np) matrix per factor.
    Policy'd blocks gather from the staged bf16 neighbour grids (the
    dominant read); the densified factor and its gram stay f32.
    Site-sharded: the neighbour grids are local unit slices — the dense
    build (small np by the crossover's definition) gathers them full and
    runs replicated.
    """
    coef = mx.staged_level("nn_coef", r, lvd.nn_coef)[alpha_idx]
    D = mx.staged_level("nn_D", r, lvd.nn_D)[alpha_idx]  # (nf, np)
    nn_idx = lvd.nn_idx
    if shard is not None and shard.has_sites:
        coef = shard.gather_site(coef, 1)
        D = shard.gather_site(D, 1)
        nn_idx = shard.gather_site(nn_idx, 0)
    nf, _, k = coef.shape
    dt = lvd.nn_D.dtype                           # f32 build regardless
    if coef.dtype != dt:
        coef = coef.astype(dt)
    if D.dtype != dt:
        D = D.astype(dt)
    rows = jnp.broadcast_to(jnp.arange(npr)[None, :, None], (nf, npr, k))
    RiW = jnp.zeros((nf, npr, npr), dtype=coef.dtype)
    RiW = RiW.at[jnp.arange(nf)[:, None, None], rows,
                 jnp.broadcast_to(nn_idx[None], (nf, npr, k))].add(-coef)
    RiW = RiW + jnp.eye(npr, dtype=coef.dtype)[None]
    RiW = RiW / jnp.sqrt(D)[:, :, None]
    return jnp.einsum("fij,fik->fjk", RiW, RiW)


def update_eta_spatial(spec: ModelSpec, data: ModelData, state: GibbsState,
                       r: int, key, S, shard=None) -> LevelState:
    lvd, lv, ls = data.levels[r], state.levels[r], spec.levels[r]
    if ls.spatial == "GPP":
        return _eta_gpp(spec, data, state, r, key, S, shard)
    npr, nf = ls.n_units, ls.nf_max
    if (ls.spatial == "NNGP" and ls.x_dim == 0
            and npr * nf > _NNGP_DENSE_MAX):
        return _eta_nngp_cg(spec, data, state, r, key, S, shard=shard)
    LiSL, F = _masked_level_gram(spec, data, lvd, ls, lv, state.iSigma, S,
                                 shard)

    if ls.spatial == "Full":
        # policy'd blocks gather from the staged bf16 grid — the (G, np,
        # np) structure read is the block's dominant byte stream
        iW = mx.staged_level("iWg", r, lvd.iWg)[lv.alpha_idx]  # (nf, np, np)
    else:  # NNGP
        iW = _nngp_dense_iW(lvd, lv.alpha_idx, npr, r, shard)
    if iW.dtype != F.dtype:
        iW = iW.astype(F.dtype)

    # big precision (nf*np)^2, factor-major: blockdiag(iW_h) + unit-diagonal
    # factor coupling LiSL_u scattered at (h*np+u, g*np+u).  Site-sharded:
    # the dense joint solve is inherently global (the Full/dense methods
    # exist for SMALL np), so it runs replicated on the psum'd full-width
    # grams with the replicated key — the draw stream equals the
    # replicated sweep's — and only Eta's local unit block is kept.
    big = jnp.zeros((nf, npr, nf, npr), dtype=F.dtype)
    fi = jnp.arange(nf)
    big = big.at[fi, :, fi, :].add(iW)
    # advanced-index axes move to the front: the indexed view is (np, nf, nf),
    # exactly LiSL's layout
    ui = jnp.arange(npr)
    big = big.at[:, ui, :, ui].add(LiSL)
    big = big.reshape(nf * npr, nf * npr)
    rhs = F.T.reshape(-1)                         # factor-major vec
    L = chol_spd(big)
    eps = jax.random.normal(key, rhs.shape, dtype=rhs.dtype)
    eta = sample_mvn_prec(L, rhs, eps).reshape(nf, npr).T
    if shard is not None and shard.has_sites:
        eta = shard.slice_site(eta, 0)
    return lv.replace(Eta=eta)


def _eta_nngp_cg(spec, data, state, r, key, S, tol: float = 1e-5,
                 maxiter: int = 500, shard=None):
    """Matrix-free NNGP Eta draw for large np (see module docstring).

    The full-conditional precision is ``P = blkdiag_f(RiW_f' RiW_f) +
    unitdiag(LiSL_u)``.  A draw x ~ N(P^{-1} b, P^{-1}) is obtained by
    perturbation optimisation: solve ``P x = b~`` with
    ``b~ = F + RiW' eps1 + sum_rows lam sqrt(iSigma) xi`` — the two
    perturbations have covariances exactly equal to the prior and likelihood
    precision terms, so Cov(x) = P^{-1} (P) P^{-1} = P^{-1} exactly; CG only
    ever applies the sparse Vecchia factor via gathers + one segment_sum.
    """
    lvd, lv, ls = data.levels[r], state.levels[r], spec.levels[r]
    npr, nf = ls.n_units, ls.nf_max
    site = shard is not None and shard.has_sites
    LiSL, F = _masked_level_gram(spec, data, lvd, ls, lv, state.iSigma, S,
                                 shard)
    lam = lambda_effective(lv)[:, :, 0]               # (nf, ns)
    coef = lvd.nn_coef[lv.alpha_idx]                  # (nf, np[_l], k)
    sqD = jnp.sqrt(lvd.nn_D[lv.alpha_idx])            # (nf, np[_l])
    if site:
        # distributed Vecchia apply: rows local, iterate full-width
        # replicated, one psum per application — per-device apply work
        # scales 1/m while the CG scalars stay replicated
        riw_t, pmv = vecchia_ops_site(lvd.nn_idx, coef, sqD,
                                      shard.slice_site(LiSL, 0), npr,
                                      shard)
    else:
        riw_t, pmv = vecchia_ops(lvd.nn_idx, coef, sqD, LiSL)

    k1, k2 = jax.random.split(key)
    if site:
        # local rows of the full-width prior perturbation (riw_t's input
        # space is row-local in the distributed apply)
        eps1 = shard.normal(k1, (npr, nf), F.dtype, dim=None, site_dim=0)
    else:
        eps1 = jax.random.normal(k1, (npr, nf), dtype=F.dtype)
    if shard is None:
        xi = jax.random.normal(k2, S.shape, dtype=F.dtype)
    else:
        xi = shard.normal(k2, ((shard.ny or spec.ny), shard.ns), F.dtype,
                          dim=1, site_dim=0)
    w = xi * jnp.sqrt(state.iSigma)[None, :]
    if spec.has_na:
        w = w * data.Ymask
    b_like = jax.ops.segment_sum(w @ lam.T, lvd.pi_row, num_segments=npr)
    if shard is not None:                 # likelihood-noise gram psum
        b_like = shard.psum_all(b_like)
    x0 = shard.gather_site(lv.Eta, 0) if site else lv.Eta
    eta, res = vecchia_cg_draw(riw_t, pmv, F, b_like, eps1, x0=x0,
                               tol=tol, maxiter=maxiter)
    # cg returns its current iterate at maxiter with no signal; a stalled
    # solve would silently bias the chain.  Check the relative residual and
    # poison the draw to NaN instead — the sampler's divergence containment
    # then reports the chain and first bad sweep loudly.
    thresh = max(100.0 * tol, 1e-3)       # scales with the requested tol
    eta = jnp.where(res < thresh, eta, jnp.nan)
    if site:
        eta = shard.slice_site(eta, 0)
    return lv.replace(Eta=eta)


def _eta_gpp(spec, data, state, r, key, S, shard=None):
    """GPP Eta via double Woodbury (reference updateEta.R:148-196):
    precision P = A - M F_blk^{-1} M' with A = per-unit nf x nf blocks
    (factor coupling + diag idD) and M the knot cross terms; sample as
    LiA eps1 + (iA M R_H^{-1}) eps2 which has covariance exactly P^{-1}."""
    lvd, lv, ls = data.levels[r], state.levels[r], spec.levels[r]
    npr, nf, nK = ls.n_units, ls.nf_max, ls.n_knots
    site = shard is not None and shard.has_sites
    LiSL, F = _masked_level_gram(spec, data, lvd, ls, lv, state.iSigma, S,
                                 shard)
    if site:
        # per-unit Woodbury blocks run on the LOCAL unit slice; the knot
        # grids already arrive site-sharded, so only the grams slice
        LiSL = shard.slice_site(LiSL, 0)
        F = shard.slice_site(F, 0)

    # policy'd blocks gather from the staged bf16 knot grids — the
    # (G, np, nK) structure reads dominate the GPP block's bytes; the
    # gathered (per-alpha) slices widen back to f32 immediately, so the
    # Woodbury factorisation below is exact-pivot f32 either way
    _f32 = lambda a: a.astype(F.dtype) if a.dtype != F.dtype else a
    idD = _f32(mx.staged_level("idDg", r, lvd.idDg)[lv.alpha_idx])
    alpha0 = (lvd.alphapw[lv.alpha_idx, 0] == 0)  # alpha=0 slots: W=I
    idD = jnp.where(alpha0[:, None], 1.0, idD)
    M1 = _f32(mx.staged_level("idDW12g", r, lvd.idDW12g)[lv.alpha_idx])
    M1 = jnp.where(alpha0[:, None, None], 0.0, M1)
    Fm = _f32(mx.staged_level("Fg", r, lvd.Fg)[lv.alpha_idx])  # (nf, nK, nK)
    payload = gpp_factor(LiSL, idD, M1, Fm, shard=shard if site else None)
    k1, k2 = jax.random.split(key)
    if site:
        eps1 = shard.normal(k1, (npr, nf), F.dtype, dim=None, site_dim=0)
    else:
        eps1 = jax.random.normal(k1, (npr, nf), dtype=F.dtype)
    eps2 = jax.random.normal(k2, (nf * nK,), dtype=F.dtype)
    eta = gpp_draw(payload, F, eps1, eps2, shard=shard if site else None)
    return lv.replace(Eta=eta)


# ---------------------------------------------------------------------------

def eta_quad_grid(lvd, ls, eta, r: int = 0, shard=None):
    """(v, ld): per-factor prior quadratics eta_h' iW_g eta_h, both (nf, G),
    over the whole alpha grid.  Consumed by update_alpha; the interweaving
    scale move uses the single-point :func:`eta_quad_at` instead.
    Site-sharded: ``eta`` is the LOCAL unit block — the Alpha grid
    quadratics are cross-site reductions (local partial sums over the
    local units + structure grids, one psum each; the Full method's
    dense grid is replicated, so it gathers eta and computes full)."""
    site = shard is not None and getattr(shard, "has_sites", False)
    if ls.spatial == "Full":
        iWg = mx.staged_level("iWg", r, lvd.iWg)
        if site:
            eta = shard.gather_site(eta, 0)    # dense grid wants full eta
        if mx.layouts_active():
            # single-pass layout: one (G, np*np) x (np*np, nf)
            # contraction over the per-factor outer products instead of
            # the grid-transposing three-operand einsum (policy-gated —
            # the branch below is the fingerprint-pinned original)
            E2 = jnp.einsum("uh,vh->huv", eta, eta)     # (nf, np, np)
            v = mx.einsum("guv,huv->hg", iWg, E2)
        else:
            v = mx.einsum("hu,guv,hv->hg", eta.T, iWg, eta.T)
        ld = lvd.detWg[None, :]
    elif ls.spatial == "NNGP":
        eta_src = shard.gather_site(eta, 0) if site else eta
        eta_nn = eta_src[lvd.nn_idx]                # (np[_l], k, nf)
        pred = mx.einsum("gik,ikh->hgi",
                         mx.staged_level("nn_coef", r, lvd.nn_coef),
                         eta_nn)                                # (nf, G, np)
        res = eta.T[:, None, :] - pred                          # (nf, G, np)
        v = (res**2 / mx.staged_level("nn_D", r, lvd.nn_D)[None]).sum(axis=2)
        if site:
            v = shard.psum_site(v)
        ld = lvd.detWg[None, :]
    else:  # GPP
        q_full = jnp.einsum("uh,uh->h", eta, eta)
        t1 = jnp.einsum("gu,uh->hg", lvd.idDg, eta**2)
        Et = jnp.einsum("uh,gum->hgm", eta, lvd.idDW12g)        # (nf, G, nK)
        if site:
            q_full = shard.psum_site(q_full)
            t1 = shard.psum_site(t1)
            Et = shard.psum_site(Et)
        t2 = jnp.einsum("hgm,gmn,hgn->hg", Et, lvd.iFg, Et)
        v = jnp.where(lvd.alphapw[None, :, 0] == 0, q_full[:, None], t1 - t2)
        ld = lvd.detDg[None, :]
    return v, ld


def eta_quad_at(lvd, ls, eta, alpha_idx, r: int = 0, shard=None):
    """(nf,) prior quadratic eta_h' iW(alpha_h) eta_h at each factor's
    *current* alpha only — same algebra as :func:`eta_quad_grid` with the
    grid axis gathered away up front (the interweaving move needs one point
    per factor; evaluating the whole 101-point grid for it roughly doubled
    the update_alpha-scale prior cost per sweep).  Site-sharded: local
    partial quadratics psum'd over the site axis (Full gathers eta for
    its replicated dense grid)."""
    site = shard is not None and getattr(shard, "has_sites", False)
    if ls.spatial == "Full":
        iW = mx.staged_level("iWg", r, lvd.iWg)[alpha_idx]    # (nf, np, np)
        if site:
            eta = shard.gather_site(eta, 0)
        return mx.einsum("hu,huv,hv->h", eta.T, iW, eta.T)
    if ls.spatial == "NNGP":
        coef = mx.staged_level("nn_coef", r, lvd.nn_coef)[alpha_idx]
        D = mx.staged_level("nn_D", r, lvd.nn_D)[alpha_idx]   # (nf, np[_l])
        eta_src = shard.gather_site(eta, 0) if site else eta
        eta_nn = eta_src[lvd.nn_idx]                          # (np[_l], k, nf)
        pred = mx.einsum("hik,ikh->hi", coef, eta_nn)         # (nf, np[_l])
        res = eta.T - pred
        q = (res**2 / D).sum(axis=1)
        return shard.psum_site(q) if site else q
    # GPP — gathers count the full knot grids; staged bf16 halves them,
    # the gathered slices widen to eta's dtype before the small einsums
    _f32 = lambda a: a.astype(eta.dtype) if a.dtype != eta.dtype else a
    idD = _f32(mx.staged_level("idDg", r, lvd.idDg)[alpha_idx])
    W12 = _f32(mx.staged_level("idDW12g", r, lvd.idDW12g)[alpha_idx])
    iF = _f32(mx.staged_level("iFg", r, lvd.iFg)[alpha_idx])  # (nf, nK, nK)
    t1 = jnp.einsum("hu,uh->h", idD, eta**2)
    Et = jnp.einsum("uh,hum->hm", eta, W12)                   # (nf, nK)
    if site:
        t1 = shard.psum_site(t1)
        Et = shard.psum_site(Et)
    t2 = jnp.einsum("hm,hmn,hn->h", Et, iF, Et)
    q_full = jnp.einsum("uh,uh->h", eta, eta)
    if site:
        q_full = shard.psum_site(q_full)
    return jnp.where(lvd.alphapw[alpha_idx, 0] == 0, q_full, t1 - t2)


def eta_ones_forms_at(lvd, ls, eta, alpha_idx, r: int = 0, shard=None):
    """``(1' iW_h 1, 1' iW_h eta_h)`` per factor at each factor's current
    alpha, with ONE gather of the level's prior structures (the location
    interweave needs both; three :func:`eta_quad_at` polarization calls
    would triple the prior-quadratic cost).  Site-sharded: local partial
    forms psum'd over the site axis (Full gathers eta for its replicated
    dense grid; the GLOBAL unit count comes from the spec — ``n_units``
    stays global under site sharding)."""
    site = shard is not None and getattr(shard, "has_sites", False)
    npr = ls.n_units
    if ls.spatial == "Full":
        iW = mx.staged_level("iWg", r, lvd.iWg)[alpha_idx]    # (nf, np, np)
        if site:
            eta = shard.gather_site(eta, 0)
        if iW.dtype != eta.dtype:
            # staged bf16 gather: accumulate the row sums in f32 — the
            # policy never lets a reduction run at bf16
            w = iW.sum(axis=2, dtype=eta.dtype)
        else:
            w = iW.sum(axis=2)                                # iW_h @ 1
        return w.sum(axis=1), jnp.einsum("hu,uh->h", w, eta)
    if ls.spatial == "NNGP":
        coef = mx.staged_level("nn_coef", r, lvd.nn_coef)[alpha_idx]
        D = mx.staged_level("nn_D", r, lvd.nn_D)[alpha_idx]   # (nf, np[_l])
        # RiW x rows: (x_i - sum_k A[i,k] x_nn[i,k]) / sqrt(D_i)
        sqD = jnp.sqrt(D)
        csum = (coef.sum(axis=2, dtype=eta.dtype)
                if coef.dtype != eta.dtype else coef.sum(axis=2))
        r1 = (1.0 - csum) / sqD                               # RiW @ 1
        eta_src = shard.gather_site(eta, 0) if site else eta
        pred = mx.einsum("hik,ikh->hi", coef, eta_src[lvd.nn_idx])
        re = (eta.T - pred) / sqD                             # RiW @ eta
        q1 = (r1**2).sum(axis=1)
        s = (r1 * re).sum(axis=1)
        if site:
            q1 = shard.psum_site(q1)
            s = shard.psum_site(s)
        return q1, s
    # GPP: x' iW y = sum_u idD x y - (x' M1) iF (M1' y); alpha=0 -> I
    _f32g = lambda a: a.astype(eta.dtype) if a.dtype != eta.dtype else a
    idD = _f32g(mx.staged_level("idDg", r, lvd.idDg)[alpha_idx])
    W12 = _f32g(mx.staged_level("idDW12g", r, lvd.idDW12g)[alpha_idx])
    iF = _f32g(mx.staged_level("iFg", r, lvd.iFg)[alpha_idx])
    E1 = W12.sum(axis=1)                                      # 1' idDW12
    Ee = jnp.einsum("uh,hum->hm", eta, W12)
    if site:
        E1 = shard.psum_site(E1)
        Ee = shard.psum_site(Ee)
        t_d = shard.psum_site(idD.sum(axis=1))
        t_e = shard.psum_site(jnp.einsum("hu,uh->h", idD, eta))
        e_sum = shard.psum_site(eta.sum(axis=0))
    else:
        t_d = idD.sum(axis=1)
        t_e = jnp.einsum("hu,uh->h", idD, eta)
        e_sum = eta.sum(axis=0)
    q1 = t_d - jnp.einsum("hm,hmn,hn->h", E1, iF, E1)
    s = t_e - jnp.einsum("hm,hmn,hn->h", E1, iF, Ee)
    zero = lvd.alphapw[alpha_idx, 0] == 0
    return (jnp.where(zero, float(npr), q1),
            jnp.where(zero, e_sum, s))


def update_alpha(spec: ModelSpec, data: ModelData, state: GibbsState, r: int,
                 key, shard=None) -> LevelState:
    """Per-factor categorical draw of the GP range on the alphapw grid:
    log p_g  =  log prior_g - 0.5 log|W_g| - 0.5 eta' iW_g eta.
    Sharded: the grid quadratics reduce over both mesh axes as needed
    (see :func:`eta_quad_grid`); the categorical draw itself runs
    replicated with the shared key, so alpha stays replicated state."""
    lvd, lv, ls = data.levels[r], state.levels[r], spec.levels[r]
    v, ld = eta_quad_grid(lvd, ls, lv.Eta, r=r, shard=shard)
    loglike = jnp.log(lvd.alphapw[None, :, 1]) - 0.5 * ld - 0.5 * v
    idx = jax.random.categorical(key, loglike, axis=-1).astype(jnp.int32)
    idx = jnp.where(lv.nf_mask > 0, idx, 0)
    return lv.replace(alpha_idx=idx)
