"""One Gibbs sweep in the reference's fixed update order
(``R/sampleMcmc.R:219-306``), assembled at trace time from static flags.

The sweep is a pure function ``(data, state, key) -> state`` suitable for
``lax.scan`` and ``vmap`` over chains.  Updaters can be disabled via the
``updater`` toggle dict exactly like the reference (``updater$Eta=FALSE`` ->
``updater={"Eta": False}``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import updaters as U
from .spatial import update_alpha, update_eta_spatial
from .structs import GibbsState, ModelData, ModelSpec

__all__ = ["make_sweep", "record_sample"]


def make_sweep(spec: ModelSpec, updater: dict | None = None,
               adapt_nf: tuple | None = None):
    updater = updater or {}
    on = lambda name: updater.get(name, True) is not False
    adapt_nf = adapt_nf or tuple(0 for _ in range(spec.nr))

    def sweep(data: ModelData, state: GibbsState, key) -> GibbsState:
        state = state.replace(it=state.it + 1)
        ks = jax.random.split(key, 8)

        if on("BetaLambda"):
            state = U.update_beta_lambda(spec, data, state, ks[0])
        if on("GammaV"):
            state = U.update_gamma_v(spec, data, state, ks[1])
        if spec.has_phylo and on("Rho"):
            state = U.update_rho(spec, data, state, ks[2])
        if on("LambdaPriors"):
            state = U.update_lambda_priors(spec, data, state, ks[3])

        if on("Eta") and spec.nr > 0:
            LFix = U.linear_fixed(spec, data, state.Beta)
            LRan = [U.level_loading(data.levels[r], state.levels[r])
                    for r in range(spec.nr)]
            for r in range(spec.nr):
                S = state.Z - LFix
                for q in range(spec.nr):
                    if q != r:
                        S = S - LRan[q]
                kr = jax.random.fold_in(ks[4], r)
                if spec.levels[r].spatial is None:
                    lv = U.update_eta_nonspatial(spec, data, state, r, kr, S)
                else:
                    lv = update_eta_spatial(spec, data, state, r, kr, S)
                levels = list(state.levels)
                levels[r] = lv
                state = state.replace(levels=tuple(levels))
                LRan[r] = U.level_loading(data.levels[r], state.levels[r])

        if on("Alpha"):
            for r in range(spec.nr):
                if spec.levels[r].spatial is not None:
                    lv = update_alpha(spec, data, state, r,
                                      jax.random.fold_in(ks[5], r))
                    levels = list(state.levels)
                    levels[r] = lv
                    state = state.replace(levels=tuple(levels))

        if on("InvSigma"):
            state = U.update_inv_sigma(spec, data, state, ks[6])
        if on("Z"):
            state = U.update_z(spec, data, state, ks[7])

        # factor-count adaptation during burn-in (iter <= adaptNf[r])
        for r in range(spec.nr):
            if adapt_nf[r] > 0 and on("Nf"):
                kr = jax.random.fold_in(ks[5], 1000 + r)
                lv_new = U.update_nf(spec, data, state, r, kr)
                gate = (state.it <= adapt_nf[r])
                lv_old = state.levels[r]
                lv = jax.tree.map(
                    lambda a, b: jnp.where(gate, a, b), lv_new, lv_old)
                levels = list(state.levels)
                levels[r] = lv
                state = state.replace(levels=tuple(levels))
        return state

    return sweep


# ---------------------------------------------------------------------------
# combineParameters at record time (reference R/combineParameters.R:1-58)
# ---------------------------------------------------------------------------

def record_sample(spec: ModelSpec, data: ModelData, state: GibbsState) -> dict:
    """Back-transform the current state to the original X/Tr scale and return
    the posterior-sample pytree (the postList schema, SURVEY.md §2.2)."""
    Beta = state.Beta
    Gamma = state.Gamma
    iV = state.iV

    # traits: Gamma columns back to raw-trait scale
    tm, ts = data.tr_scale_par[0], data.tr_scale_par[1]
    Gamma = Gamma / ts[None, :]
    if data.tr_intercept_ind is not None:
        corr = (tm[None, :] * Gamma).sum(axis=1) - tm[data.tr_intercept_ind] * Gamma[:, data.tr_intercept_ind]
        Gamma = Gamma.at[:, data.tr_intercept_ind].add(-corr)

    # covariates: Beta/Gamma rows and iV rows+cols
    xm = data.x_scale_par[0], data.x_scale_par[1]
    xmean, xs = xm
    ncn = spec.nc_nrrr
    scale_rows = jnp.concatenate(
        [xs, jnp.ones(spec.nc - ncn, dtype=xs.dtype)]) if spec.nc > ncn else xs
    mean_rows = jnp.concatenate(
        [xmean, jnp.zeros(spec.nc - ncn, dtype=xmean.dtype)]) if spec.nc > ncn else xmean
    if spec.nc_rrr > 0 and data.xrrr_scale_par is not None:
        pass  # XRRR back-transform handled with the wRRR extras (P7)
    Beta = Beta / scale_rows[:, None]
    Gamma = Gamma / scale_rows[:, None]
    if data.x_intercept_ind is not None:
        ii = data.x_intercept_ind
        corrB = (mean_rows[:, None] * Beta).sum(axis=0) - mean_rows[ii] * Beta[ii]
        corrG = (mean_rows[:, None] * Gamma).sum(axis=0) - mean_rows[ii] * Gamma[ii]
        Beta = Beta.at[ii].add(-corrB)
        Gamma = Gamma.at[ii].add(-corrG)
    iV_t = iV * scale_rows[:, None] * scale_rows[None, :]
    V = jnp.linalg.inv(iV_t)

    rec = {
        "Beta": Beta,
        "Gamma": Gamma,
        "V": V,
        "sigma": 1.0 / state.iSigma,
        "rho": (data.rhopw[state.rho_idx, 0] if spec.has_phylo
                else jnp.zeros((), dtype=Beta.dtype)),
    }
    for r in range(spec.nr):
        lv = state.levels[r]
        rec[f"Eta_{r}"] = lv.Eta
        rec[f"Lambda_{r}"] = U.lambda_effective(lv)
        rec[f"Psi_{r}"] = lv.Psi
        rec[f"Delta_{r}"] = lv.Delta
        rec[f"Alpha_{r}"] = lv.alpha_idx
        rec[f"nfMask_{r}"] = lv.nf_mask
    if spec.nc_rrr > 0:
        rec["wRRR"] = state.wRRR
        rec["PsiRRR"] = state.PsiRRR
        rec["DeltaRRR"] = state.DeltaRRR
    return rec
