"""One Gibbs sweep in the reference's fixed update order
(``R/sampleMcmc.R:219-306``), assembled at trace time from static flags.

The sweep is a pure function ``(data, state, key) -> state`` suitable for
``lax.scan`` and ``vmap`` over chains.  Updaters can be disabled via the
``updater`` toggle dict exactly like the reference (``updater$Eta=FALSE`` ->
``updater={"Eta": False}``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import updaters as U
from . import updaters_sel as USel
from .spatial import update_alpha, update_eta_spatial
from .structs import GibbsState, ModelData, ModelSpec

__all__ = ["make_sweep", "make_sweep_schedule", "make_sharded_sweep",
           "sweep_prologue", "record_sample", "effective_spec_data"]


def effective_spec_data(spec: ModelSpec, data: ModelData, state: GibbsState):
    """(spec, data) with the state-dependent effective design in force —
    RRR columns appended, selection zeroing applied (no-op otherwise)."""
    if spec.nc_rrr == 0 and spec.ncsel == 0:
        return spec, data
    Xeff, per_species = USel.effective_design(spec, data, state)
    spec_x = (dataclasses.replace(spec, x_is_list=True)
              if per_species and not spec.x_is_list else spec)
    return spec_x, data.replace(X=Xeff)


# The sweep as a SCHEDULE of named Gibbs blocks.  ``make_sweep_schedule``
# returns the ordered ``(name, block)`` list one sweep comprises; the
# production ``make_sweep`` folds the blocks inline into ONE traced program
# (the op sequence is identical to the historical monolithic body — the
# committed jaxpr fingerprints pin this), while the profiling runner
# (``sampler.instrumented_sweep``) dispatches each block as its own jitted
# call to attribute wall time per updater, and a future mesh-sharded sweep
# can annotate blocks with partition specs without re-deriving the order.
#
# Block signature: ``block(data, carry, ks) -> carry`` with the carry tuple
# ``(state, Xeff, LRan_total, E_shared)`` threading everything that
# crosses block boundaries:
#
# - ``Xeff``: the state-dependent effective design (RRR columns appended,
#   selection zeroing applied); ``None`` on static-X models.
# - ``LRan_total``: total random-level loading, consumed by wRRR/BetaSel.
# - ``E_shared``: the current linear predictor, threaded through the sweep
#   tail (Eta -> InvSigma -> Z) so total_loading's padding-bound small-K
#   matmuls run once instead of three times per sweep.
#
# Names in parentheses ("(design)", "(lran)") are bookkeeping steps, not
# registered updaters; every other name matches ``mcmc/registry.py``.
# Every block runs strictly after ``sweep_prologue`` (it+1 + key split).

def _precision_block(fn, dtype, layouts):
    """Wrap one schedule block in a mixed-precision compute scope
    (:mod:`hmsc_tpu.ops.mixed`) — entered at TRACE time around the
    block's fold, so the routed dots/grams inside see the policy's
    compute dtype and the fused batched layouts.  Never applied when
    ``precision is None``: the default schedule is the exact historical
    blocks (fingerprint-pinned)."""
    from ..ops import mixed

    def wrapped(data, carry, ks):
        with mixed.scope(dtype, layouts):
            return fn(data, carry, ks)
    return wrapped


def make_sweep_schedule(spec: ModelSpec, updater: dict | None = None,
                        adapt_nf: tuple | None = None, shard=None,
                        precision=None):
    updater = updater or {}
    on = lambda name: updater.get(name, True) is not False
    adapt_nf = adapt_nf or tuple(0 for _ in range(spec.nr))
    # RRR appends columns and selection zeroes per-species blocks: both make
    # the in-force design state-dependent, so downstream updaters see a
    # per-sweep effective X (and the per-species design path when selecting)
    has_dynamic_x = spec.nc_rrr > 0 or spec.ncsel > 0
    spec_x = (dataclasses.replace(spec, x_is_list=True)
              if spec.ncsel > 0 and not spec.x_is_list else spec)

    # collapsed updaters are opt-in (see updaters_marginal module docstring);
    # the sampler validates their structural gates before enabling
    want = lambda name: updater.get(name, False) is True

    if shard is not None:
        from .partition import (shard_unsupported_reason,
                                site_shard_unsupported_reason)
        reason = shard_unsupported_reason(spec, updater)
        if reason is None and getattr(shard, "has_sites", False):
            reason = site_shard_unsupported_reason(spec, updater)
        if reason:
            raise NotImplementedError(
                f"sharded sweep unsupported for this model: {reason}")

    def data_x_of(data, Xeff):
        return data if Xeff is None else data.replace(X=Xeff)

    steps: list = []

    def add(name, fn):
        if precision is not None:
            dt = precision.dtype_for(name)
            if dt is not None:
                fn = _precision_block(fn, dt, precision.batched_layouts)
        steps.append((name, fn))

    if has_dynamic_x:
        def _design(data, carry, ks):
            state, _, LRan_total, E_shared = carry
            Xeff, _ = USel.effective_design(spec, data, state)
            return state, Xeff, LRan_total, E_shared
        add("(design)", _design)

    if want("Gamma2"):
        def _gamma2(data, carry, ks):
            state, Xeff, *rest = carry
            from .updaters_marginal import update_gamma2
            state = update_gamma2(spec_x, data_x_of(data, Xeff), state,
                                  ks[10])
            return (state, Xeff, *rest)
        add("Gamma2", _gamma2)

    if want("GammaEta"):
        def _gamma_eta(data, carry, ks):
            state, Xeff, *rest = carry
            from .updaters_marginal import update_gamma_eta
            for r in range(spec.nr):
                state = update_gamma_eta(spec_x, data_x_of(data, Xeff),
                                         state, r,
                                         jax.random.fold_in(ks[11], r))
            return (state, Xeff, *rest)
        add("GammaEta", _gamma_eta)

    if on("BetaLambda"):
        def _beta_lambda(data, carry, ks):
            state, Xeff, *rest = carry
            state = U.update_beta_lambda(spec_x, data_x_of(data, Xeff),
                                         state, ks[0], shard=shard)
            return (state, Xeff, *rest)
        add("BetaLambda", _beta_lambda)

    if has_dynamic_x:
        def _lran(data, carry, ks):
            state, Xeff, _, E_shared = carry
            if spec.nr > 0:
                LRan_total = sum(
                    U.level_loading(data.levels[r], state.levels[r], shard)
                    for r in range(spec.nr))
            else:
                LRan_total = jnp.zeros_like(state.Z)
            return state, Xeff, LRan_total, E_shared
        add("(lran)", _lran)

    if spec.nc_rrr > 0 and on("wRRR"):
        def _w_rrr(data, carry, ks):
            state, Xeff, LRan_total, E_shared = carry
            state = USel.update_w_rrr(spec, data, state, ks[8], LRan_total,
                                      shard=shard)
            Xeff, _ = USel.effective_design(spec, data, state)
            return state, Xeff, LRan_total, E_shared
        add("wRRR", _w_rrr)

    if spec.ncsel > 0 and on("BetaSel"):
        def _beta_sel(data, carry, ks):
            state, Xeff, LRan_total, E_shared = carry
            state = USel.update_beta_sel(spec, data, state, ks[9],
                                         LRan_total, shard=shard)
            Xeff, _ = USel.effective_design(spec, data, state)
            return state, Xeff, LRan_total, E_shared
        add("BetaSel", _beta_sel)

    if on("GammaV"):
        def _gamma_v(data, carry, ks):
            state, *rest = carry
            return (U.update_gamma_v(spec, data, state, ks[1], shard=shard),
                    *rest)
        add("GammaV", _gamma_v)

    if spec.has_phylo and on("Rho"):
        def _rho(data, carry, ks):
            state, *rest = carry
            return (U.update_rho(spec, data, state, ks[2], shard=shard),
                    *rest)
        add("Rho", _rho)

    if on("LambdaPriors"):
        def _lambda_priors(data, carry, ks):
            state, *rest = carry
            return (U.update_lambda_priors(spec, data, state, ks[3],
                                           shard=shard), *rest)
        add("LambdaPriors", _lambda_priors)

    if spec.nc_rrr > 0 and on("wRRRPriors"):
        def _w_rrr_priors(data, carry, ks):
            state, *rest = carry
            state = USel.update_w_rrr_priors(spec, data, state,
                                             jax.random.fold_in(ks[8], 1))
            return (state, *rest)
        add("wRRRPriors", _w_rrr_priors)

    if on("Eta") and spec.nr > 0:
        def _eta(data, carry, ks):
            state, Xeff, LRan_total, _ = carry
            LFix = U.linear_fixed(spec_x, data_x_of(data, Xeff), state.Beta)
            LRan = [U.level_loading(data.levels[r], state.levels[r], shard)
                    for r in range(spec.nr)]
            for r in range(spec.nr):
                S = state.Z - LFix
                for q in range(spec.nr):
                    if q != r:
                        S = S - LRan[q]
                kr = jax.random.fold_in(ks[4], r)
                if spec.levels[r].spatial is None:
                    lv = U.update_eta_nonspatial(spec, data, state, r, kr, S,
                                                 shard=shard)
                else:
                    lv = update_eta_spatial(spec, data, state, r, kr, S,
                                            shard=shard)
                levels = list(state.levels)
                levels[r] = lv
                state = state.replace(levels=tuple(levels))
                LRan[r] = U.level_loading(data.levels[r], state.levels[r],
                                          shard)
            E_shared = LFix
            for r in range(spec.nr):
                E_shared = E_shared + LRan[r]
            return state, Xeff, LRan_total, E_shared
        # one block covers every level's update; label it spatial when ANY
        # level runs the spatial path, so mixed-level models don't book
        # spatial-Eta cost under a non-spatial name
        add("EtaSpatial" if any(spec.levels[r].spatial is not None
                                for r in range(spec.nr)) else "Eta",
            _eta)

    if on("Alpha") and any(spec.levels[r].spatial is not None
                           for r in range(spec.nr)):
        def _alpha(data, carry, ks):
            state, *rest = carry
            for r in range(spec.nr):
                if spec.levels[r].spatial is not None:
                    lv = update_alpha(spec, data, state, r,
                                      jax.random.fold_in(ks[5], r),
                                      shard=shard)
                    levels = list(state.levels)
                    levels[r] = lv
                    state = state.replace(levels=tuple(levels))
            return (state, *rest)
        add("Alpha", _alpha)

    # beyond-reference: per-factor (Eta, Lambda) scale interweaving
    # (measured 2x ESS on association scales) and the per-factor
    # (Eta, Beta_intercept) location move (measured +10% min / +20%
    # median Beta ESS at config 2 once the round-5 gate fix made it
    # actually run — benchmarks/ab_interweave_da.py).  Both default on,
    # both leave the linear predictor invariant, so E_shared stays
    # valid.  interweave_location self-gates (location_gate) on models
    # where its invariance breaks.  Gated on the updaters they perturb:
    # a frozen Eta/BetaLambda run (debugging, conditional sampling)
    # must not see drifting Eta/Lambda/Beta
    iw_ok = spec.nr > 0 and on("Eta") and on("BetaLambda")
    if iw_ok and (on("Interweave") or on("InterweaveLocation")):
        # ONE block for both moves: they share the ks[12] split exactly as
        # the historical monolithic body did, and keeping them in one
        # compiled program is what makes the instrumented per-block
        # dispatch bit-identical to the fused sweep (splitting them was
        # measured to move interweave_location's phylo-path dot by 1 ULP
        # under XLA's boundary-sensitive fusion)
        def _interweave(data, carry, ks):
            state, Xeff, LRan_total, E_shared = carry
            kI1, kI2 = jax.random.split(ks[12])
            if on("Interweave"):
                state = U.interweave_scale(spec, data, state, kI1,
                                           shard=shard)
            if on("InterweaveLocation"):
                state = U.interweave_location(spec, data, state, kI2,
                                              shard=shard)
            return state, Xeff, LRan_total, E_shared
        add("Interweave", _interweave)

    if on("InvSigma"):
        def _inv_sigma(data, carry, ks):
            state, Xeff, LRan_total, E_shared = carry
            state = U.update_inv_sigma(spec_x, data_x_of(data, Xeff), state,
                                       ks[6], E=E_shared, shard=shard)
            return state, Xeff, LRan_total, E_shared
        add("InvSigma", _inv_sigma)

    if on("Z"):
        def _z(data, carry, ks):
            state, Xeff, LRan_total, E_shared = carry
            state = U.update_z(spec_x, data_x_of(data, Xeff), state, ks[7],
                               E=E_shared, shard=shard)
            return state, Xeff, LRan_total, E_shared
        add("Z", _z)

    # opt-in ASIS flip of the probit augmentation on the intercept row
    # (updaters.interweave_da_intercept) — placed after updateZ so the
    # ancillary residual is built from the freshest Z; it changes Beta
    # and Z jointly, and nothing after it consumes E_shared
    if want("InterweaveDA") and on("Z") and on("BetaLambda"):
        def _interweave_da(data, carry, ks):
            state, *rest = carry
            state = U.interweave_da_intercept(
                spec, data, state, jax.random.fold_in(ks[7], 1),
                shard=shard)
            return (state, *rest)
        add("InterweaveDA", _interweave_da)

    # factor-count adaptation during burn-in (iter <= adaptNf[r])
    if any(adapt_nf[r] > 0 and on("Nf") for r in range(spec.nr)):
        def _nf(data, carry, ks):
            state, *rest = carry
            for r in range(spec.nr):
                if adapt_nf[r] > 0 and on("Nf"):
                    kr = jax.random.fold_in(ks[5], 1000 + r)
                    lv_new = U.update_nf(spec, data, state, r, kr,
                                         shard=shard)
                    gate = (state.it <= adapt_nf[r])
                    lv_old = state.levels[r]
                    lv = jax.tree.map(
                        lambda a, b: jnp.where(gate, a, b), lv_new, lv_old)
                    levels = list(state.levels)
                    levels[r] = lv
                    state = state.replace(levels=tuple(levels))
            return (state, *rest)
        add("Nf", _nf)

    return steps


def sweep_prologue(state: GibbsState, key):
    """The iteration bump + 13-way subkey split every sweep begins with.
    Shared by the fused sweep and the instrumented per-block runner
    (``sampler.instrumented_sweep``) so both derive the identical subkey
    table — the op order here is pinned by the committed fingerprints."""
    state = state.replace(it=state.it + 1)
    return state, jax.random.split(key, 13)


def make_sweep(spec: ModelSpec, updater: dict | None = None,
               adapt_nf: tuple | None = None, shard=None, precision=None):
    """The production fused sweep: the schedule's blocks folded inline into
    one pure ``(data, state, key) -> state`` function (one traced program;
    XLA fuses across block boundaries exactly as before the schedule
    existed — the committed jaxpr fingerprints pin the op sequence).

    With a :class:`~hmsc_tpu.mcmc.precision.PrecisionPolicy` the returned
    function takes a fourth ``staged`` argument — the policy's bf16
    shadow table (:func:`~hmsc_tpu.mcmc.precision.stage_data`), passed as
    a real argument so it is never baked into the program — and the
    policy'd blocks trace inside their mixed-precision scopes.
    ``precision=None`` returns the exact historical 3-argument sweep."""
    steps = make_sweep_schedule(spec, updater, adapt_nf, shard, precision)

    if precision is None:
        def sweep(data: ModelData, state: GibbsState, key) -> GibbsState:
            state, ks = sweep_prologue(state, key)
            carry = (state, None, None, None)
            for _name, block in steps:
                # blocks receive the full subkey TABLE and statically index
                # disjoint rows — the fold passes ks through, never consumes it
                carry = block(data, carry, ks)  # hmsc: ignore[rng-key-reuse]
            return carry[0]

        return sweep

    from ..ops import mixed

    def sweep_mp(data: ModelData, state: GibbsState, key,
                 staged=None) -> GibbsState:
        state, ks = sweep_prologue(state, key)
        carry = (state, None, None, None)
        with mixed.staged_scope(staged):
            for _name, block in steps:
                carry = block(data, carry, ks)  # hmsc: ignore[rng-key-reuse]
        return carry[0]

    return sweep_mp


def make_sharded_sweep(spec: ModelSpec, mesh, updater: dict | None = None,
                       adapt_nf: tuple | None = None,
                       species_axis: str = "species", precision=None,
                       local_rng: bool = False, site_axis: str = "sites"):
    """The sharded sweep as a standalone ``shard_map`` program:
    one pure ``(data, state, key) -> state`` function for a CHAINLESS
    state, with the in/out PartitionSpecs from :mod:`.partition` made
    explicit at the boundary.  ``spec`` is the GLOBAL spec; inputs are
    global arrays placed (or re-placed by jit) per the spec tables.
    A mesh naming a ``site_axis`` of extent > 1 engages the 2D
    (species × sites) geometry: Z rows / Eta rows / the row data and the
    NNGP-GPP unit grids shard over sites on top of the v1 species
    layout (``ny`` and every level's unit count must divide the site
    extent; the site-ineligible model classes raise like the species
    gates do).

    This is the program the layer-2 jaxpr audits fingerprint (the
    collective sequence is part of the committed fingerprint), the
    comm-bytes ledger walks, and the agreement tests drive; the
    production segment runner wraps the same body in vmap + scan
    (``sampler._compiled_runner(mesh=...)``)."""
    import dataclasses as _dc

    from jax.experimental.shard_map import shard_map

    from .partition import (DATA_SITE_DIMS, DATA_SPECIES_DIMS,
                            STATE_SITE_DIMS, STATE_SPECIES_DIMS, ShardCtx,
                            tree_pspecs)
    from jax.sharding import PartitionSpec as P

    n_sp = int(mesh.shape[species_axis])
    if spec.ns % n_sp:
        raise ValueError(f"ns={spec.ns} not divisible by the mesh's "
                         f"'{species_axis}' extent ({n_sp})")
    axis_names = getattr(mesh, "axis_names", ())
    n_st = int(mesh.shape[site_axis]) if site_axis in axis_names else 1
    st = site_axis if n_st > 1 else None
    site_dims_d = DATA_SITE_DIMS if st is not None else None
    site_dims_s = STATE_SITE_DIMS if st is not None else None
    if st is not None:
        if spec.ny % n_st:
            raise ValueError(f"ny={spec.ny} not divisible by the mesh's "
                             f"'{site_axis}' extent ({n_st})")
        bad = [ls.name for ls in spec.levels if ls.n_units % n_st]
        if bad:
            raise ValueError(
                f"unit count(s) of level(s) {bad} not divisible by the "
                f"mesh's '{site_axis}' extent ({n_st})")
    shard = ShardCtx(axis=species_axis, n=n_sp, ns=spec.ns,
                     local_rng=bool(local_rng),
                     site_axis=st, m=n_st if st is not None else 1,
                     ny=spec.ny if st is not None else 0,
                     np_r=tuple(ls.n_units for ls in spec.levels)
                     if st is not None else ())
    spec_l = _dc.replace(spec, ns=spec.ns // n_sp,
                         ny=spec.ny // (n_st if st is not None else 1))
    body = make_sweep(spec_l, updater, adapt_nf, shard, precision)

    if precision is None:
        def sharded(data: ModelData, state: GibbsState, key) -> GibbsState:
            in_specs = (
                tree_pspecs(data, spec, species_axis, DATA_SPECIES_DIMS,
                            x_is_list=spec.x_is_list, site_axis=st,
                            site_dims=site_dims_d),
                tree_pspecs(state, spec, species_axis, STATE_SPECIES_DIMS,
                            site_axis=st, site_dims=site_dims_s),
                P())
            return shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=in_specs[1], check_rep=False)(
                                 data, state, key)

        return sharded

    from .precision import staged_pspecs

    def sharded_mp(data: ModelData, state: GibbsState, key,
                   staged=None) -> GibbsState:
        in_specs = (
            tree_pspecs(data, spec, species_axis, DATA_SPECIES_DIMS,
                        x_is_list=spec.x_is_list, site_axis=st,
                        site_dims=site_dims_d),
            tree_pspecs(state, spec, species_axis, STATE_SPECIES_DIMS,
                        site_axis=st, site_dims=site_dims_s),
            P(),
            staged_pspecs(staged or {}, spec, species_axis,
                          x_is_list=spec.x_is_list, site_axis=st))
        return shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=in_specs[1], check_rep=False)(
                             data, state, key, staged or {})

    return sharded_mp


# ---------------------------------------------------------------------------
# combineParameters at record time (reference R/combineParameters.R:1-58)
# ---------------------------------------------------------------------------

def record_sample(spec: ModelSpec, data: ModelData, state: GibbsState) -> dict:
    """Back-transform the current state to the original X/Tr scale and return
    the posterior-sample pytree (the postList schema, SURVEY.md §2.2)."""
    Beta = state.Beta
    Gamma = state.Gamma
    iV = state.iV

    # selection: zero the switched-off covariate blocks FIRST, so the
    # centering/intercept corrections below operate on the effective Beta
    # (the reference zeroes after back-transform, combineParameters.R:45-53,
    # which mis-absorbs off-block slab coefficients into the intercept when
    # X is centered)
    if spec.ncsel > 0:
        from .updaters_sel import selection_mask
        Beta = Beta * selection_mask(spec, data, state.BetaSel).T

    # traits: Gamma columns back to raw-trait scale
    tm, ts = data.tr_scale_par[0], data.tr_scale_par[1]
    Gamma = Gamma / ts[None, :]
    if data.tr_intercept_ind is not None:
        corr = (tm[None, :] * Gamma).sum(axis=1) - tm[data.tr_intercept_ind] * Gamma[:, data.tr_intercept_ind]
        Gamma = Gamma.at[:, data.tr_intercept_ind].add(-corr)

    # covariates: Beta/Gamma rows and iV rows+cols
    xm = data.x_scale_par[0], data.x_scale_par[1]
    xmean, xs = xm
    ncn = spec.nc_nrrr
    scale_rows = jnp.concatenate(
        [xs, jnp.ones(spec.nc - ncn, dtype=xs.dtype)]) if spec.nc > ncn else xs
    mean_rows = jnp.concatenate(
        [xmean, jnp.zeros(spec.nc - ncn, dtype=xmean.dtype)]) if spec.nc > ncn else xmean
    Beta = Beta / scale_rows[:, None]
    Gamma = Gamma / scale_rows[:, None]
    if data.x_intercept_ind is not None:
        ii = data.x_intercept_ind
        corrB = (mean_rows[:, None] * Beta).sum(axis=0) - mean_rows[ii] * Beta[ii]
        corrG = (mean_rows[:, None] * Gamma).sum(axis=0) - mean_rows[ii] * Gamma[ii]
        Beta = Beta.at[ii].add(-corrB)
        Gamma = Gamma.at[ii].add(-corrG)
    iV_t = iV * scale_rows[:, None] * scale_rows[None, :]
    V = jnp.linalg.inv(iV_t)

    # RRR: back-transform wRRR so raw XRRR reproduces the scaled design
    # (XB_raw @ wRRR_rec' == XRRRScaled @ wRRR'), with the centering constant
    # absorbed into the intercept row of Beta/Gamma.  The reference instead
    # divides Beta's RRR rows by XRRRScalePar[,k] (combineParameters.R:30-43),
    # which mixes per-original-covariate scales into per-component rows; the
    # invariant above is the one predict()/WAIC rely on.
    wRRR = state.wRRR
    if spec.nc_rrr > 0 and data.xrrr_scale_par is not None:
        rm, rs = data.xrrr_scale_par[0], data.xrrr_scale_par[1]
        wRRR = state.wRRR / rs[None, :]
        if data.x_intercept_ind is not None:
            ii = data.x_intercept_ind
            cK = (state.wRRR * (rm / rs)[None, :]).sum(axis=1)  # (nc_rrr,)
            Beta = Beta.at[ii].add(-(cK[:, None] * Beta[ncn:]).sum(axis=0))
            Gamma = Gamma.at[ii].add(-(cK[:, None] * Gamma[ncn:]).sum(axis=0))

    rec = {
        "Beta": Beta,
        "Gamma": Gamma,
        "V": V,
        "sigma": 1.0 / state.iSigma,
        "rho": (data.rhopw[state.rho_idx, 0] if spec.has_phylo
                else jnp.zeros((), dtype=Beta.dtype)),
    }
    for r in range(spec.nr):
        lv = state.levels[r]
        rec[f"Eta_{r}"] = lv.Eta
        rec[f"Lambda_{r}"] = U.lambda_effective(lv)
        rec[f"Psi_{r}"] = lv.Psi
        rec[f"Delta_{r}"] = lv.Delta
        rec[f"Alpha_{r}"] = lv.alpha_idx
        rec[f"nfMask_{r}"] = lv.nf_mask
    if spec.nc_rrr > 0:
        rec["wRRR"] = wRRR
        rec["PsiRRR"] = state.PsiRRR
        rec["DeltaRRR"] = state.DeltaRRR
    return rec
