"""Full-conditional Gibbs updaters (non-spatial core).

Each function maps (spec, data, state, key) -> new state fields.  All are
whole-array, batched formulations of the reference's per-species / per-unit R
loops (reference files cited per function); shapes are static, factor blocks
are masked at ``nf_max`` (see structs.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.linalg import solve_triangular

from ..ops import mixed as mx
from ..ops.linalg import chol_spd, sample_mvn_prec, sample_mvn_prec_batched
from ..ops.rand import (polya_gamma, standard_gamma, truncated_normal,
                        truncated_normal_onesided, wishart)
from .structs import GibbsState, LevelState, ModelData, ModelSpec

# Heavy dots and grams route through hmsc_tpu.ops.mixed (`mx.matmul` /
# `mx.einsum` / `mx.staged`): outside a precision-policy scope these are
# LITERALLY the plain jnp calls (byte-identical traces, fingerprint-
# pinned); inside a policy'd block they compute bf16 with f32
# accumulation, and `mx.staged` resolves the policy's pre-cast shadow of
# sweep-invariant model data.  Reductions, Cholesky factorisations and
# triangular solves never route through mx — their dtypes stay pinned to
# their f32 operands (audited by `jaxpr-mixed-precision`).

__all__ = ["linear_fixed", "level_loading", "update_z", "update_beta_lambda",
           "update_gamma_v", "gamma_given_beta", "update_rho",
           "update_lambda_priors", "update_eta_nonspatial",
           "update_inv_sigma", "update_nf", "eta_star", "lambda_effective",
           "interweave_scale", "interweave_location", "location_gate",
           "interweave_da_intercept", "da_intercept_gate"]

_NB_R = 1e3  # Poisson as the r->inf limit of NB (reference updateZ.R:68)


# ---------------------------------------------------------------------------
# linear predictors
# ---------------------------------------------------------------------------

def lambda_effective(lv: LevelState) -> jnp.ndarray:
    """(nf, ns, ncr) loadings with inactive factor rows zeroed."""
    return lv.Lambda * lv.nf_mask[:, None, None]


def linear_fixed(spec: ModelSpec, data: ModelData, Beta: jnp.ndarray) -> jnp.ndarray:
    """LFix = X @ Beta; per-species X handled as a batched contraction
    (reference updateZ.R:12-24)."""
    if spec.x_is_list:
        return mx.einsum("jyc,cj->yj", mx.staged("X", data.X), Beta)
    return mx.matmul(mx.staged("X", data.X), Beta)


def _eta_rows_src(lv: LevelState, shard=None) -> jnp.ndarray:
    """Eta as a full-width (np, nf) table for row-indexed reads.  Under
    site sharding Eta's rows are a local block while ``pi_row`` holds
    GLOBAL unit indices — the explicit ``Pi`` row-gather collective
    reassembles the table so any row may read any unit."""
    if shard is not None and shard.has_sites:
        return shard.gather_site(lv.Eta, 0)
    return lv.Eta


def level_loading(data_lv, lv: LevelState, shard=None) -> jnp.ndarray:
    """LRan_r = sum_k (Eta[pi,:] * x_row[:,k]) @ Lambda[:,:,k]."""
    lam = lambda_effective(lv)
    eta_rows = _eta_rows_src(lv, shard)[data_lv.pi_row]
    return mx.einsum("yf,yk,fjk->yj", eta_rows, data_lv.x_row, lam)


def total_loading(spec: ModelSpec, data: ModelData, state: GibbsState,
                  shard=None) -> jnp.ndarray:
    E = linear_fixed(spec, data, state.Beta)
    for r in range(spec.nr):
        E = E + level_loading(data.levels[r], state.levels[r], shard)
    return E


def eta_star(spec: ModelSpec, data: ModelData, state: GibbsState,
             shard=None) -> jnp.ndarray:
    """Stacked factor design (ny, K), K = sum_r nf_max_r * ncr_r; columns of
    inactive factors are zeroed.  Ordering per level is covariate-major
    (k * nf + h), mirroring the reference's stacking (updateBetaLambda.R:33-41)."""
    cols = []
    for r in range(spec.nr):
        lvd, lv = data.levels[r], state.levels[r]
        eta_rows = _eta_rows_src(lv, shard)[lvd.pi_row] * lv.nf_mask[None, :]
        block = jnp.einsum("yf,yk->ykf", eta_rows, lvd.x_row)
        cols.append(block.reshape(spec.ny, -1))
    if not cols:
        return jnp.zeros((spec.ny, 0), dtype=data.Y.dtype)
    return jnp.concatenate(cols, axis=1)


def _stacked_lambda_prior(spec: ModelSpec, state: GibbsState) -> jnp.ndarray:
    """(K, ns) prior precisions psi_hj * tau_h, stacked like eta_star."""
    rows = []
    for r in range(spec.nr):
        lv = state.levels[r]
        tau = jnp.cumprod(jnp.where(lv.nf_mask[:, None] > 0, lv.Delta, 1.0), axis=0)
        pr = lv.Psi * tau[:, None, :]            # (nf, ns, ncr)
        rows.append(jnp.transpose(pr, (2, 0, 1)).reshape(-1, spec.ns))
    if not rows:
        # dtype pinned: an unpinned empty block would promote the whole
        # joint BetaLambda precision to f64 under an x64 config
        return jnp.zeros((0, spec.ns), dtype=state.Z.dtype)
    return jnp.concatenate(rows, axis=0)


def _unstack_lambda(spec: ModelSpec, BL: jnp.ndarray, state: GibbsState):
    """Split the (nc+K, ns) joint draw back into Beta and per-level Lambda."""
    Beta = BL[:spec.nc]
    new_levels = []
    off = spec.nc
    for r in range(spec.nr):
        ls = spec.levels[r]
        k = ls.nf_max * ls.ncr
        blk = BL[off:off + k]                    # (ncr*nf, ns) covariate-major
        lam = blk.reshape(ls.ncr, ls.nf_max, spec.ns).transpose(1, 2, 0)
        lv = state.levels[r]
        lam = lam * lv.nf_mask[:, None, None]
        new_levels.append(lv.replace(Lambda=lam))
        off += k
    return Beta, tuple(new_levels)


# ---------------------------------------------------------------------------
# updateZ (reference R/updateZ.R:4-94)
# ---------------------------------------------------------------------------

def update_z(spec: ModelSpec, data: ModelData, state: GibbsState, key,
             E=None, shard=None) -> GibbsState:
    """Latent-response data augmentation: normal copies Y, probit draws
    truncated normals for the whole ny x ns block at once, (lognormal-)Poisson
    uses Polya-Gamma augmentation of the NB(r=1000) limit; NA cells are imputed
    from the linear predictor.  ``E`` may pass in the current linear predictor
    (the sweep shares one total_loading across its tail — the small-K matmuls
    are MXU-padding-bound, so recomputes are pure waste).

    ``shard`` (a :class:`~hmsc_tpu.mcmc.partition.ShardCtx`) runs the
    species-sharded variant: all compute is local to the shard's species
    columns, with every random draw taken at the GLOBAL width and sliced —
    see the partition module docstring for the draw-equality contract."""
    if E is None:
        E = total_loading(spec, data, state, shard)
    std = state.iSigma[None, :] ** -0.5
    fam = data.distr_family[None, :]
    k_tn, k_pg, k_pg2, k_na = jax.random.split(key, 4)
    # the GLOBAL draw shape: site sharding localises spec.ny too, so the
    # full-width-and-slice contract reads the globals off the shard ctx
    full = ((spec.ny, spec.ns) if shard is None
            else ((shard.ny or spec.ny), shard.ns))

    Z = state.Z
    if spec.any_normal:
        Z = jnp.where(fam == 1, data.Y, Z)
    if spec.any_probit:
        # probit truncation is always one-sided (Y=1 -> Z>0, Y=0 -> Z<0), so
        # the specialised op spends 1 ndtr + 1 ndtri per cell instead of 2+1
        from ..ops.rand import _TINY
        if shard is None:
            z_tn = truncated_normal_onesided(k_tn, 0.0, data.Y > 0.5, E, std)
        else:
            u = shard.uniform(k_tn, full, E.dtype, dim=1, site_dim=0,
                              minval=_TINY, maxval=1.0)
            # _u pre-drawn from k_tn above; the op only transforms it
            # hmsc: ignore[rng-key-reuse]
            z_tn = truncated_normal_onesided(k_tn, 0.0, data.Y > 0.5, E,
                                             std, _u=u)
        Z = jnp.where(fam == 2, z_tn, Z)
    if spec.any_poisson:
        logr = jnp.log(_NB_R)
        if shard is None:
            w = polya_gamma(k_pg, data.Y + _NB_R, state.Z - logr)
        else:
            eps_pg = shard.normal(k_pg, full, E.dtype, dim=1, site_dim=0)
            # _eps pre-drawn from k_pg above; the op only transforms it
            # hmsc: ignore[rng-key-reuse]
            w = polya_gamma(k_pg, data.Y + _NB_R, state.Z - logr,
                            _eps=eps_pg)
        prec = state.iSigma[None, :]
        s2 = 1.0 / (prec + w)
        mu = s2 * ((data.Y - _NB_R) / 2.0 + prec * (E - logr)) + logr
        if shard is None:
            z_p = mu + jnp.sqrt(s2) * jax.random.normal(k_pg2, mu.shape,
                                                        dtype=mu.dtype)
        else:
            z_p = mu + jnp.sqrt(s2) * shard.normal(k_pg2, full, mu.dtype,
                                                   dim=1, site_dim=0)
        # NaN guard: keep the previous Z for any non-finite cell (reference
        # prints "Fail in Poisson Z update" and aborts the cell, updateZ.R:84-86)
        z_p = jnp.where(jnp.isfinite(z_p), z_p, state.Z)
        Z = jnp.where(fam == 3, z_p, Z)
    if spec.has_na:
        if shard is None:
            eps_na = jax.random.normal(k_na, E.shape, dtype=E.dtype)
        else:
            eps_na = shard.normal(k_na, full, E.dtype, dim=1, site_dim=0)
        z_na = E + std * eps_na
        Z = jnp.where(data.Ymask > 0, Z, z_na)
    return state.replace(Z=Z)


# ---------------------------------------------------------------------------
# updateBetaLambda (reference R/updateBetaLambda.R:8-157)
# ---------------------------------------------------------------------------

def update_beta_lambda(spec: ModelSpec, data: ModelData, state: GibbsState,
                       key, shard=None) -> GibbsState:
    """Joint (Beta, Lambda) draw.

    No phylogeny: the reference's per-species (nc+K)^2 cholesky loop becomes one
    batched (ns, P, P) cholesky on the MXU.

    With phylogeny the reference solves one ((nc+K)*ns)^2 system
    (updateBetaLambda.R:124-147) — infeasible at scale.  We instead block the
    draw as Lambda | Beta (per-species, batched) followed by Beta | Lambda
    (matrix-normal: exact O(ns^2 nc) eigenbasis sampler when residual variances
    are homoskedastic-fixed, else a dense (nc*ns) system).  Same stationary
    distribution, TPU-sized factorisations.
    """
    if not spec.has_phylo:
        return _beta_lambda_joint(spec, data, state, key, shard)
    k1, k2 = jax.random.split(key)
    state = _lambda_given_beta(spec, data, state, k1, shard)
    state = _beta_given_lambda_phylo(spec, data, state, k2, shard)
    return state


def _per_species_design_gram(spec, data, XE, mask, shard=None):
    """Gram matrices XE' diag(mask_j) XE per species: (ns, P, P).
    Site-sharded: the row contraction is partial per site shard — one
    psum completes it (on the shared (P, P) gram before the broadcast in
    the mask-free case)."""
    if spec.x_is_list:
        Es = XE  # (ny, K) factor part shared
        def gram(Xj, mj):
            D = jnp.concatenate([Xj, Es], axis=1)
            return mx.einsum("ip,i,iq->pq", D, mj, D), D
        G, _ = jax.vmap(gram, in_axes=(0, 1))(data.X, mask)
        return G
    if spec.has_na:
        G = mx.einsum("ip,ij,iq->jpq", XE, mask, XE)
        if shard is not None:
            G = shard.psum_site(G)
        return G
    G = mx.matmul(XE.T, XE)
    if shard is not None:
        G = shard.psum_site(G)
    return jnp.broadcast_to(G, (spec.ns,) + G.shape)


def _beta_lambda_joint(spec, data, state, key, shard=None):
    P = spec.nc + spec.nf_total
    XE_factor = eta_star(spec, data, state, shard)
    if spec.x_is_list:
        XE = None
    else:
        XE = jnp.concatenate([data.X, XE_factor], axis=1)

    prior_lam = _stacked_lambda_prior(spec, state)        # (K, ns)
    Mu_beta = state.Gamma @ data.Tr.T                     # (nc, ns)

    mask = data.Ymask
    if spec.x_is_list:
        def per_species(Xj, mj, Sj):
            D = jnp.concatenate([Xj, XE_factor], axis=1)
            G = mx.einsum("ip,i,iq->pq", D, mj, D)
            rhs_lik = mx.matmul(D.T, Sj * mj)
            return G, rhs_lik
        G, rhs_lik = jax.vmap(per_species, in_axes=(0, 1, 1))(data.X, mask, state.Z)
    else:
        G = _per_species_design_gram(spec, data, XE, mask, shard)
        if spec.has_na:
            rhs_lik = mx.einsum("ip,ij,ij->jp", XE, mask, state.Z)
        else:
            rhs_lik = mx.matmul(XE.T, state.Z).T          # (ns, P)
        if shard is not None:             # cross-site row contraction
            rhs_lik = shard.psum_site(rhs_lik)

    # per-species posterior precision = blkdiag(iV, diag(psi*tau)) + iSigma_j*G_j
    eyeP = jnp.eye(P, dtype=G.dtype)
    prior_diag = jnp.concatenate(
        [jnp.zeros((spec.nc, spec.ns), dtype=G.dtype), prior_lam], axis=0)    # (P, ns)
    P0 = jnp.zeros((spec.ns, P, P), dtype=G.dtype)
    P0 = P0.at[:, :spec.nc, :spec.nc].set(state.iV[None])
    P0 = P0 + eyeP[None] * prior_diag.T[:, :, None]
    prec = P0 + state.iSigma[:, None, None] * G

    mu0 = jnp.concatenate(
        [Mu_beta, jnp.zeros((spec.nf_total, spec.ns), dtype=G.dtype)], axis=0)  # (P, ns)
    rhs = jnp.einsum("jpq,qj->jp", P0, mu0) + state.iSigma[:, None] * rhs_lik

    if shard is None:
        eps = jax.random.normal(key, (spec.ns, P), dtype=G.dtype)
    else:
        eps = shard.normal(key, (shard.ns, P), G.dtype, dim=0)
    BL = sample_mvn_prec_batched(prec, rhs, eps)          # (ns, P)
    Beta, levels = _unstack_lambda(spec, BL.T, state)
    return state.replace(Beta=Beta, levels=levels)


def _lambda_given_beta(spec, data, state, key, shard=None):
    """Lambda | Beta, Z: per-species batched K x K solves."""
    K = spec.nf_total
    if K == 0:
        return state
    Es = eta_star(spec, data, state, shard)               # (ny, K)
    S = state.Z - linear_fixed(spec, data, state.Beta)
    prior_lam = _stacked_lambda_prior(spec, state)        # (K, ns)
    mask = data.Ymask
    if spec.has_na:
        G = mx.einsum("ip,ij,iq->jpq", Es, mask, Es)
        rhs_lik = mx.einsum("ip,ij,ij->jp", Es, mask, S)
    else:
        G0 = mx.matmul(Es.T, Es)
        if shard is not None:             # cross-site row gram
            G0 = shard.psum_site(G0)
        G = jnp.broadcast_to(G0, (spec.ns,) + G0.shape)
        rhs_lik = mx.matmul(Es.T, S).T
    if shard is not None:
        if spec.has_na:
            G = shard.psum_site(G)
        rhs_lik = shard.psum_site(rhs_lik)
    prec = state.iSigma[:, None, None] * G \
        + jnp.eye(K, dtype=G.dtype)[None] * prior_lam.T[:, :, None]
    rhs = state.iSigma[:, None] * rhs_lik
    if shard is None:
        eps = jax.random.normal(key, (spec.ns, K), dtype=G.dtype)
    else:
        eps = shard.normal(key, (shard.ns, K), G.dtype, dim=0)
    Lam = sample_mvn_prec_batched(prec, rhs, eps)         # (ns, K)
    _, levels = _unstack_lambda(
        spec, jnp.concatenate([state.Beta, Lam.T], axis=0), state)
    return state.replace(levels=levels)


def _beta_given_lambda_phylo(spec, data, state, key, shard=None):
    """Beta | Lambda, Z under the matrix-normal prior MN(Gamma Tr', V, Q(rho)).

    Fast path (homoskedastic fixed sigma, no NAs, shared X): simultaneous
    diagonalisation — iQ = U diag(1/e) U' (precomputed eigenbasis) and a
    generalised nc x nc eigensolve of (X'X, iV) decouple every coefficient;
    the draw is elementwise (SURVEY.md §7 point 3).

    Sharded: ``data.U`` is row-sharded, so the eigenbasis projection
    ``(XW' R0) @ U`` is a partial product psum'd to the full (nc, ns)
    coefficient table (replicated draw), and the back-projection
    ``Gt @ U.T`` lands directly on the local species columns.  The dense
    general path has no sharded formulation (the sampler gates it).
    """
    S = state.Z - sum(level_loading(data.levels[r], state.levels[r], shard)
                      for r in range(spec.nr)) if spec.nr else state.Z
    e = data.Qeig[state.rho_idx]                          # (ns,) eigvals of Q
    M = state.Gamma @ data.Tr.T                           # prior mean (nc, ns)

    if spec.homoskedastic_fixed and not spec.has_na and not spec.x_is_list:
        sigma2 = data.sigma_fixed[0]
        isig = 1.0 / sigma2
        Xs = mx.staged("X", data.X)
        Us = mx.staged("U", data.U)
        XtX = mx.matmul(Xs.T, Xs)
        if shard is not None:             # X rows are site-local blocks
            XtX = shard.psum_site(XtX)
        Lv = chol_spd(state.iV)
        B = solve_triangular(Lv, solve_triangular(Lv, XtX, lower=True).T, lower=True)
        g, R = jnp.linalg.eigh((B + B.T) / 2)
        Wm = solve_triangular(Lv.T, R, lower=False)       # W' iV W = I, W' XtX W = diag(g)
        XW = mx.matmul(Xs, Wm)
        R0 = S - mx.matmul(Xs, M)
        T = mx.matmul(mx.matmul(XW.T, R0), Us)            # (nc, ns)
        if shard is not None:
            # the projection is partial over the species-sharded U rows
            # AND (on a 2D mesh) the site-sharded design rows: one
            # reduction over every model-parallel axis (exactly the v1
            # species psum on a species-only mesh)
            T = shard.psum_all(T)
        prec = 1.0 / e[None, :] + isig * g[:, None]
        mean = (isig * T) / prec
        eps = jax.random.normal(key, mean.shape, dtype=mean.dtype)
        Gt = mean + eps / jnp.sqrt(prec)
        Beta = M + mx.matmul(Wm, mx.matmul(Gt, Us.T))
        return state.replace(Beta=Beta)

    # general dense (nc*ns) system, species-major vec ordering
    if shard is not None:
        raise NotImplementedError(
            "the dense phylogenetic Beta path has no sharded formulation "
            "(the sampler's shard gate should have caught this model class)")
    nc, ns = spec.nc, spec.ns
    iQ = (data.U / e[None, :]) @ data.U.T                 # (ns, ns)
    if spec.x_is_list:
        G = jnp.einsum("jip,ij,jiq->jpq", data.X, data.Ymask, data.X)
        rhs_lik = jnp.einsum("jip,ij,ij->jp", data.X, data.Ymask, S)
    elif spec.has_na:
        G = jnp.einsum("ip,ij,iq->jpq", data.X, data.Ymask, data.X)
        rhs_lik = jnp.einsum("ip,ij,ij->jp", data.X, data.Ymask, S)
    else:
        G0 = data.X.T @ data.X
        G = jnp.broadcast_to(G0, (ns, nc, nc))
        rhs_lik = (data.X.T @ S).T
    big = jnp.einsum("jm,pq->jpmq", iQ, state.iV)
    big = big.at[jnp.arange(ns), :, jnp.arange(ns), :].add(
        state.iSigma[:, None, None] * G)
    big = big.reshape(ns * nc, ns * nc)
    rhs = (jnp.einsum("jm,pq,qm->jp", iQ, state.iV, M)
           + state.iSigma[:, None] * rhs_lik).reshape(ns * nc)
    L = chol_spd(big)
    eps = jax.random.normal(key, (ns * nc,), dtype=rhs.dtype)
    Beta = sample_mvn_prec(L, rhs, eps).reshape(ns, nc).T
    return state.replace(Beta=Beta)


# ---------------------------------------------------------------------------
# updateGammaV / updateRho (reference R/updateGammaV.R, R/updateRho.R)
# ---------------------------------------------------------------------------

def _phylo_trq(spec, data, state, shard=None):
    """(TrQ = iQ Tr, TtQT = Tr' iQ Tr) in the phylo eigenbasis (identity
    weights without phylogeny).  Sharded: ``data.UTr``/``Qeig`` ride in at
    full width (replicated), so ``TtQT`` is replicated compute; ``TrQ``'s
    rows land local through the row-sharded ``data.U``; the non-phylo
    ``Tr' Tr`` gram is a psum."""
    if spec.has_phylo:
        e = data.Qeig[state.rho_idx]
        se = jnp.sqrt(e)
        UTs = mx.staged("UTr", data.UTr) / se[:, None]
        TrQ = mx.matmul(mx.staged("U", data.U),
                        UTs / se[:, None])                # iQ Tr (ns, nt)
        TtQT = mx.matmul(UTs.T, UTs)
    else:
        Trs = mx.staged("Tr", data.Tr)
        TrQ = data.Tr
        TtQT = mx.matmul(Trs.T, Trs)
        if shard is not None:
            TtQT = shard.psum(TtQT)
    return TrQ, TtQT


def gamma_given_beta(spec: ModelSpec, data: ModelData, state: GibbsState,
                     key, shard=None) -> GibbsState:
    """Gamma | Beta, iV: Gaussian full conditional with precision
    iUGamma + kron(Tr' iQ Tr, iV) (reference updateGammaV.R:30-32)."""
    TrQ, TtQT = _phylo_trq(spec, data, state, shard)
    prec = data.iUGamma + jnp.kron(TtQT, state.iV)
    rhs0 = data.iUGamma @ data.mGamma     # (trace order matches the
    t2 = mx.matmul(mx.matmul(state.iV, state.Beta),
                   TrQ)                   # historical one-liner)
    if shard is not None:                 # cross-species contraction
        t2 = shard.psum(t2)
    rhs = rhs0 + t2.T.reshape(-1)
    L = chol_spd(prec)
    eps = jax.random.normal(key, rhs.shape, dtype=rhs.dtype)
    gvec = sample_mvn_prec(L, rhs, eps)
    return state.replace(Gamma=gvec.reshape(spec.nt, spec.nc).T)


def update_gamma_v(spec: ModelSpec, data: ModelData, state: GibbsState,
                   key, shard=None) -> GibbsState:
    """Conjugate draws: iV ~ Wishart(f0+ns, (E iQ E' + V0)^{-1}), then Gamma
    from its Gaussian full conditional with precision iUGamma +
    kron(Tr' iQ Tr, iV).  Sharded: the ``B``-products (E iQ E', the
    classic cross-species reduction) psum to a replicated (nc, nc) gram;
    the Wishart/Gaussian draws then run replicated on every shard."""
    kv, kg = jax.random.split(key)
    E = state.Beta - state.Gamma @ data.Tr.T
    if spec.has_phylo:
        e = data.Qeig[state.rho_idx]
        se = jnp.sqrt(e)
        # sqrt-split the 1/e weights so f32 intermediates stay ~1/sqrt(e_min)
        # and the Gram products are exactly symmetric PSD
        if shard is None:
            Et = mx.matmul(E, mx.staged("U", data.U)) / se[None, :]
        else:
            Et = shard.psum(mx.matmul(E, mx.staged("U", data.U))) \
                / se[None, :]
        A = mx.matmul(Et, Et.T)
    else:
        A = mx.matmul(E, E.T)
        if shard is not None:
            A = shard.psum(A)

    ns_g = spec.ns if shard is None else shard.ns
    Lw = chol_spd(A + data.V0)
    T = solve_triangular(Lw.T,
                         jnp.eye(spec.nc, dtype=A.dtype), lower=False)  # T T' = (A+V0)^{-1}
    if data.tenant is None:
        iV = wishart(kv, spec.f0 + ns_g, T)
    else:
        # pad-and-mask tenant: the degrees of freedom count REAL species
        # only, and the drawn precision is re-blocked so padded covariates
        # stay exactly decoupled (identity pad block) — the real block of
        # the Bartlett product T A (T A)' reads only real-index normals
        # (T is block-diagonal, A lower-triangular), so the real-block
        # Wishart law is untouched by the masking.  A pad index's chi^2
        # shape (df_v - i)/2 can go non-positive when nc pads far beyond
        # the real model (df_v counts REAL covariates/species only); the
        # resulting NaN diag would contaminate the real block through the
        # TA pad columns (0 * NaN), so pad lanes draw a harmless positive
        # shape instead — gamma draws are per-element, so the real lanes'
        # stream is bit-unchanged
        cm = data.tenant.cov_mask
        idx = jnp.arange(spec.nc, dtype=T.dtype)
        df_vec = jnp.where(cm > 0, data.tenant.df_v, idx + 2.0)
        iV = wishart(kv, df_vec, T)
        iV = iV * (cm[:, None] * cm[None, :]) + jnp.diag(1.0 - cm)
    return gamma_given_beta(spec, data, state.replace(iV=iV), kg, shard)


def update_rho(spec: ModelSpec, data: ModelData, state: GibbsState,
               key, shard=None) -> GibbsState:
    """Discrete-grid draw of the phylogenetic mixing rho: quadratic forms of
    E in C's eigenbasis make all 101 grid evaluations one matvec.  Sharded:
    one psum completes the eigenbasis projection; the grid scan then runs
    replicated at full width (``Qeig`` is replicated data)."""
    E = state.Beta - state.Gamma @ data.Tr.T
    Et = mx.matmul(E, mx.staged("U", data.U))              # (nc, ns)
    if shard is not None:
        Et = shard.psum(Et)
    q = mx.einsum("cj,cd,dj->j", Et, state.iV, Et)         # (ns,)
    v = (q[None, :] / mx.staged("Qeig", data.Qeig)).sum(axis=1)  # (G,)
    # tenant: the Gaussian normalisation counts real covariates (padded
    # Beta rows are exact zeros with unit pad eigenvalues, so q and the
    # per-model logdetQ already exclude the padding)
    nc_g = spec.nc if data.tenant is None else data.tenant.n_cov
    loglike = jnp.log(data.rhopw[:, 1]) - 0.5 * nc_g * data.logdetQ - 0.5 * v
    idx = jax.random.categorical(key, loglike)
    return state.replace(rho_idx=idx.astype(jnp.int32))


# ---------------------------------------------------------------------------
# updateLambdaPriors (reference R/updateLambdaPriors.R:3-53)
# ---------------------------------------------------------------------------

def update_lambda_priors(spec: ModelSpec, data: ModelData, state: GibbsState,
                         key, shard=None) -> GibbsState:
    """Multiplicative-gamma shrinkage: psi elementwise conjugate gamma, delta
    sequential over factor index with tau recomputed per step
    (Bhattacharya-Dunson).  Inactive slots stay neutral (delta=1).
    Sharded: the psi gamma noise is species-free-parameterised, so it is
    drawn full-width and sliced; the delta tail sums psum; delta itself
    stays replicated."""
    ns_g = spec.ns if shard is None else shard.ns
    # tenant: the delta gamma shapes count REAL species (padded Lambda
    # columns are exact zeros, so the Msum tail already excludes them)
    ns_stat = ns_g if data.tenant is None else data.tenant.n_sp
    new_levels = []
    for r in range(spec.nr):
        lvd, lv = data.levels[r], state.levels[r]
        ls = spec.levels[r]
        kpsi, kdel = jax.random.split(jax.random.fold_in(key, r))
        mask = lv.nf_mask                                   # (nf,)
        lam2 = (lv.Lambda * mask[:, None, None]) ** 2       # (nf, ns, ncr)
        delta = jnp.where(mask[:, None] > 0, lv.Delta, 1.0)
        tau = jnp.cumprod(delta, axis=0)                    # (nf, ncr)

        a_psi = lvd.nu[None, None, :] / 2 + 0.5
        b_psi = lvd.nu[None, None, :] / 2 + 0.5 * lam2 * tau[:, None, :]
        if shard is None:
            psi = standard_gamma(
                kpsi, jnp.broadcast_to(a_psi, lam2.shape)) / b_psi
        elif shard.local_rng:
            # O(ns_local) draw with the shard-folded key (lam2 is local)
            psi = standard_gamma(
                shard.fold(kpsi), jnp.broadcast_to(a_psi, lam2.shape)) \
                / b_psi
        else:
            g_full = standard_gamma(kpsi, jnp.broadcast_to(
                a_psi, (ls.nf_max, ns_g, ls.ncr)))
            psi = shard.slice_sp(g_full, 1) / b_psi

        M = psi * lam2                                      # (nf, ns, ncr)
        Msum = M.sum(axis=1)                                # (nf, ncr)
        if shard is not None:
            Msum = shard.psum(Msum)
        nf_act = mask.sum()
        n_geq = jnp.cumsum(mask[::-1])[::-1]                # active factors >= h
        keys = jax.random.split(kdel, ls.nf_max)
        for h in range(ls.nf_max):
            tau = jnp.cumprod(delta, axis=0)
            if h == 0:
                ad = lvd.a1 + 0.5 * ns_stat * nf_act
                b0 = lvd.b1
            else:
                ad = lvd.a2 + 0.5 * ns_stat * n_geq[h]
                b0 = lvd.b2
            tail = (tau[h:] * Msum[h:] * mask[h:, None]).sum(axis=0)
            bd = b0 + 0.5 * tail / delta[h]
            draw = standard_gamma(keys[h], jnp.broadcast_to(ad, (ls.ncr,))) / bd
            delta = delta.at[h].set(jnp.where(mask[h] > 0, draw, 1.0))
        new_levels.append(lv.replace(Psi=psi, Delta=delta))
    return state.replace(levels=tuple(new_levels))


# ---------------------------------------------------------------------------
# updateEta, non-spatial (reference R/updateEta.R:44-109)
# ---------------------------------------------------------------------------

def _masked_level_gram(spec, data, lvd, ls, lv, iSigma, S, shard=None):
    """Per-unit factor precision contributions and RHS:
    returns (LiSL (np, nf, nf), F (np, nf)).  Sharded: both are
    cross-species reductions (the factor grams), completed by explicit
    psums; the (np, nf)-shaped outputs are then replicated on every
    shard — exactly what the per-unit Eta solves need.  Site-sharded:
    the segment sums run over the shard's LOCAL rows into the GLOBAL
    unit space (``ls.n_units`` stays global), so the same psum — fused
    over both mesh axes — completes the cross-site row reduction too;
    callers slice their local unit block afterwards.  The mask-free
    LiSL needs no site reduction: ``unit_count`` is replicated global
    data, already counting every shard's rows."""
    npr, nf = ls.n_units, ls.nf_max
    if ls.x_dim == 0:
        lam = lambda_effective(lv)[:, :, 0]                # (nf, ns)
        if spec.has_na:
            rows = mx.einsum("fj,gj,j,ij->ifg", lam, lam, iSigma, data.Ymask)
            LiSL = jax.ops.segment_sum(rows, lvd.pi_row, num_segments=npr)
            if shard is not None:
                LiSL = shard.psum_all(LiSL)
            Fr = mx.matmul(S * iSigma[None, :] * data.Ymask, lam.T)
        else:
            shared = mx.matmul(lam * iSigma[None, :], lam.T)
            if shard is not None:
                shared = shard.psum(shared)
            LiSL = lvd.unit_count[:, None, None] * shared[None]
            Fr = mx.matmul(S * iSigma[None, :], lam.T)
        F = jax.ops.segment_sum(Fr, lvd.pi_row, num_segments=npr)
        if shard is not None:
            F = shard.psum_all(F)
        return LiSL, F
    lam = lambda_effective(lv)                              # (nf, ns, ncr)
    lam_u = mx.einsum("fjk,uk->ufj", lam, lvd.x_unit)       # (np, nf, ns)
    Mu_cnt = jax.ops.segment_sum(data.Ymask, lvd.pi_row, num_segments=npr)
    LiSL = mx.einsum("ufj,ugj,j,uj->ufg", lam_u, lam_u, iSigma, Mu_cnt)
    T = jax.ops.segment_sum(S * iSigma[None, :] * data.Ymask, lvd.pi_row,
                            num_segments=npr)
    F = mx.einsum("uj,ufj->uf", T, lam_u)
    if shard is not None:
        LiSL = shard.psum(LiSL)
        F = shard.psum(F)
    return LiSL, F


def update_eta_nonspatial(spec, data, state, r: int, key, S, shard=None):
    """Eta_r | rest for one unstructured level: per-unit nf x nf batched
    cholesky; inactive factors fall back to their N(0,1) prior.  Sharded:
    the grams psum; the (np, nf) draw is species-free, so it runs
    replicated on every shard.  Site-sharded: each shard slices its
    local unit block out of the psum'd full-width grams and solves only
    that block, with the draw taken full-width and sliced (the 2D
    draw-equality contract) — Eta's rows stay local."""
    lvd, lv, ls = data.levels[r], state.levels[r], spec.levels[r]
    LiSL, F = _masked_level_gram(spec, data, lvd, ls, lv, state.iSigma, S,
                                 shard)
    prec = LiSL + jnp.eye(ls.nf_max, dtype=F.dtype)[None]
    if shard is not None and shard.has_sites:
        prec = shard.slice_site(prec, 0)
        F_l = shard.slice_site(F, 0)
        eps = shard.normal(key, (ls.n_units, ls.nf_max), F.dtype,
                           dim=None, site_dim=0)
        eta = sample_mvn_prec_batched(prec, F_l, eps)       # (np_l, nf)
        return lv.replace(Eta=eta)
    eps = jax.random.normal(key, F.shape, dtype=F.dtype)
    eta = sample_mvn_prec_batched(prec, F, eps)             # (np, nf)
    return lv.replace(Eta=eta)


# ---------------------------------------------------------------------------
# interweaving scale move (no reference counterpart — a parameter-expanded
# Metropolis step tightening the slowest direction of the shrinkage factor
# model; Liu & Sabatti 2000 generalized Gibbs / Yu & Meng 2011 interweaving)
# ---------------------------------------------------------------------------

def _eta_prior_quad(lvd, lv, ls, r: int = 0, shard=None) -> jnp.ndarray:
    """(nf,) quadratic form eta_h' iW(alpha_h) eta_h under the level's actual
    factor prior (identity for unstructured levels; the spatial precision at
    each factor's current alpha for Full/NNGP/GPP — same grid algebra as
    updateAlpha, gathered at alpha_idx).  Site-sharded: the unit sums are
    cross-site reductions (psum'd; the spatial forms handle their own
    structure gathers)."""
    if ls.spatial is None:
        A = (lv.Eta ** 2).sum(axis=0)
        if shard is not None:
            A = shard.psum_site(A)
        return A
    from .spatial import eta_quad_at
    return eta_quad_at(lvd, ls, lv.Eta, lv.alpha_idx, r=r, shard=shard)


def interweave_scale(spec: ModelSpec, data: ModelData, state: GibbsState,
                     key, shard=None) -> GibbsState:
    """Per-factor scale move (Eta_h, Lambda_h) -> (c Eta_h, Lambda_h / c).

    The likelihood depends only on the product, so the Metropolis target is
    prior x Jacobian x Haar:  log a = -A(c^2-1)/2 - B(1/c^2-1)/2
    + (np - ns*ncr) log c,  with A = eta_h' iW eta_h (prior precision
    quadratic) and B = sum_jk psi tau lambda^2.  Proposal log c ~ N(0,
    2.38^2 / (2(np + ns*ncr))) matches the target's curvature at c=1; the
    draw targets the *identical* posterior — it only shortcuts the slow
    random walk the Gibbs sweep takes along the Eta/Lambda scale ridge
    (shrinkage factor models' classic worst direction).  The Eta*Lambda
    loading is bit-exact invariant in infinite precision and numerically
    invariant to one rounding, so a shared linear predictor stays valid."""
    new_levels = []
    for r in range(spec.nr):
        lvd, lv, ls = data.levels[r], state.levels[r], spec.levels[r]
        kr1, kr2 = jax.random.split(jax.random.fold_in(key, r))
        mask = lv.nf_mask                                 # (nf,)
        A = _eta_prior_quad(lvd, lv, ls, r=r, shard=shard)
        delta = jnp.where(mask[:, None] > 0, lv.Delta, 1.0)
        tau = jnp.cumprod(delta, axis=0)                  # (nf, ncr)
        B = (lv.Psi * tau[:, None, :] * lv.Lambda ** 2).sum(axis=(1, 2))
        if shard is not None:             # cross-species prior-mass sum
            B = shard.psum(B)
        ns_g = spec.ns if shard is None else shard.ns
        if data.tenant is None:
            k_exp = ls.n_units - ns_g * ls.ncr
            # float(): a bare np.float64 scalar is strong-typed and would
            # upcast the whole proposal under an x64 config
            sigma = float(2.38 / np.sqrt(2.0 * (ls.n_units + ns_g * ls.ncr)))
        else:
            # tenant: the Jacobian exponent and the proposal curvature
            # count REAL units/species of THIS model (traced per-model
            # scalars under the batched vmap)
            nu_r = data.tenant.levels[r].n_units.astype(A.dtype)
            ns_r = data.tenant.n_sp.astype(A.dtype)
            k_exp = nu_r - ns_r * ls.ncr
            sigma = 2.38 * jax.lax.rsqrt(2.0 * (nu_r + ns_r * ls.ncr))
        u = sigma * jax.random.normal(kr1, (ls.nf_max,), dtype=A.dtype)
        c = jnp.exp(u)
        log_acc = (-0.5 * A * (c ** 2 - 1.0)
                   - 0.5 * B * (c ** -2 - 1.0) + k_exp * u)
        ok = jnp.log(jax.random.uniform(kr2, (ls.nf_max,),
                                        dtype=A.dtype, minval=1e-38)) < log_acc
        c = jnp.where(ok & (mask > 0), c, 1.0)
        new_levels.append(lv.replace(Eta=lv.Eta * c[None, :],
                                     Lambda=lv.Lambda / c[:, None, None]))
    return state.replace(levels=tuple(new_levels))


def location_gate(spec: ModelSpec, has_intercept: bool) -> str | None:
    """Why :func:`interweave_location` cannot run on this model, or ``None``
    when eligible — the single source for both the updater's guard and the
    sampler's opt-in gate message (a silent structural no-op must never look
    like "the move doesn't help")."""
    if not has_intercept:
        return "the design has no intercept column to shift"
    if spec.x_is_list:
        return "per-species design matrices"
    if spec.ncsel > 0:
        return ("variable selection's effective-Beta zeroing breaks the "
                "move's likelihood invariance")
    return None


def interweave_location(spec: ModelSpec, data: ModelData, state: GibbsState,
                        key, shard=None) -> GibbsState:
    """Per-factor location move (Eta_h, Beta_int) -> (Eta_h + c_h 1,
    Beta_int,j - c_h Lambda_hj): exact Gibbs along the likelihood-invariant
    translation orbit (generalized Gibbs with a translation group — Haar is
    Lebesgue, Jacobian 1, so the orbit conditional is the prior product and
    it is Gaussian in c).

    Measured motivation (benchmarks/diag_mixing.py, configs 2 and 3b): the
    slowest Beta entries are the *intercepts* of species with the largest
    leading-factor loadings (min-ESS vs head-loading correlation -0.51 /
    -0.57; tail loadings uncorrelated at config-2 scale), i.e. the classic
    mean-split ridge between X_int Beta_int and the factor term — not the
    shrinkage tail.  **Measured outcome** (round 5, after the gate fix that
    made the move actually run — every earlier A/B had it silently disabled
    because raw-matrix designs carry no *named* intercept): a 5-seed
    engaged A/B at config 2 gives min/median Beta ESS 53.8/192.6 off ->
    59.1/232.2 on (**+10% min, +20% median**,
    ``benchmarks/ab_interweave_da.py``) at a handful of reductions per
    sweep.  Hence **default on**; disable with
    ``updater={"InterweaveLocation": False}``.
    The joint nf-dim Gaussian for c has precision
    ``P = diag(1' iW_h 1) + iV_int,int Lam iQ Lam'`` and linear term
    ``Lam iQ (R' iV e_int) - 1' iW_h eta_h`` with R = Beta - Gamma Tr'
    (iQ = I without phylogeny); the spatial ``(1'iW1, 1'iW eta)`` forms come
    from :func:`~hmsc_tpu.mcmc.spatial.eta_ones_forms_at` in one structure
    gather.  Structural eligibility lives in :func:`location_gate` (shared
    with the sampler's opt-in gate message); covariate-dependent levels are
    left untouched (their factor term is not row-constant)."""
    if location_gate(spec, has_intercept=data.x_ones_ind is not None):
        return state
    ii = data.x_ones_ind
    Beta = state.Beta
    Mu = jnp.einsum("ct,jt->cj", state.Gamma, data.Tr)
    iV = state.iV
    v00 = iV[ii, ii]
    new_levels = []
    for r in range(spec.nr):
        lvd, lv, ls = data.levels[r], state.levels[r], spec.levels[r]
        if ls.x_dim != 0:
            new_levels.append(lv)
            continue
        lam = lambda_effective(lv)[:, :, 0]               # (nf, ns) masked
        mask = lv.nf_mask
        u = iV[ii] @ (Beta - Mu)                          # (ns,)
        if ls.spatial is None:
            if data.tenant is None:
                q1 = jnp.full((ls.nf_max,), float(ls.n_units),
                              dtype=lam.dtype)
            else:                         # tenant: 1'1 over REAL units only
                q1 = jnp.broadcast_to(
                    data.tenant.levels[r].n_units.astype(lam.dtype),
                    (ls.nf_max,))
            s = lv.Eta.sum(axis=0)                        # 1' eta_h
            if shard is not None:         # cross-site unit sum
                s = shard.psum_site(s)
        else:
            from .spatial import eta_ones_forms_at
            q1, s = eta_ones_forms_at(lvd, ls, lv.Eta, lv.alpha_idx, r=r,
                                      shard=shard)
            if data.tenant is not None:
                # padded spatial units contribute exactly 1.0 each to
                # 1'iW1 under the block-diagonal pad convention (identity
                # iWg blocks / unit Vecchia rows / unit GPP diagonal, see
                # multitenant.pad_tenant) while 1'iW eta gets exact zeros
                # (Eta pads are re-masked between blocks) — subtract the
                # pad count so the orbit prior precision counts REAL units
                q1 = q1 - (float(ls.n_units)
                           - data.tenant.levels[r].n_units.astype(lam.dtype))
        Us = mx.staged("U", data.U) if spec.has_phylo else None
        if spec.has_phylo and shard is None:
            e = data.Qeig[state.rho_idx]                  # (ns,)
            lamU = mx.matmul(lam, Us)
            G = mx.matmul(lamU / e[None, :], lamU.T)      # Lam iQ Lam'
            bB = mx.matmul(lamU / e[None, :], mx.matmul(Us.T, u))
        elif spec.has_phylo:
            e = data.Qeig[state.rho_idx]
            lamU = shard.psum(mx.matmul(lam, Us))         # projections psum
            G = mx.matmul(lamU / e[None, :], lamU.T)
            bB = mx.matmul(lamU / e[None, :],
                           shard.psum(mx.matmul(Us.T, u)))
        elif shard is None:
            G = mx.matmul(lam, lam.T)
            bB = mx.matmul(lam, u)
        else:
            G = shard.psum(mx.matmul(lam, lam.T))
            bB = shard.psum(mx.matmul(lam, u))
        P = v00 * G + jnp.diag(jnp.where(mask > 0, q1, 1.0))
        b = jnp.where(mask > 0, bB - s, 0.0)
        L = chol_spd(P)
        z = jax.random.normal(jax.random.fold_in(key, r), b.shape,
                              dtype=b.dtype)
        c = sample_mvn_prec(L, b, z) * mask
        Beta = Beta.at[ii].add(-(c @ lam))
        new_levels.append(lv.replace(Eta=lv.Eta + c[None, :]))
    return state.replace(levels=tuple(new_levels), Beta=Beta)


def da_intercept_gate(spec: ModelSpec, has_intercept: bool) -> str | None:
    """Why :func:`interweave_da_intercept` cannot run on this model, or
    ``None`` when eligible (same single-source contract as
    :func:`location_gate`)."""
    if not spec.any_probit:
        return "no probit column — the move flips the probit augmentation"
    if not has_intercept:
        return "the design has no intercept column to shift"
    if spec.x_is_list:
        return "per-species design matrices"
    if spec.ncsel > 0:
        return ("variable selection's effective-Beta zeroing decouples the "
                "intercept row from the recorded Beta")
    if spec.nc_rrr > 0:
        return "RRR appends state-dependent design columns"
    if spec.has_phylo:
        return ("the phylogenetic prior couples intercepts across species; "
                "the per-species conditional no longer factorises over the "
                "sign-interval box")
    return None


def interweave_da_intercept(spec: ModelSpec, data: ModelData,
                            state: GibbsState, key, shard=None) -> GibbsState:
    """ASIS flip of the probit data augmentation for the intercept row:
    redraw ``Beta[int, j]`` with the *residual* ``R = Z - Beta[int]`` held
    fixed instead of ``Z`` itself (ancillary augmentation), then rebuild
    ``Z = R + Beta[int]``.

    Motivation (benchmarks/diag_mixing.py): the residual slow mode at
    config-2 scale is probit-DA *saturation* — when ``|E|`` is large the
    truncated-normal Z hugs E, so Z and the intercept take tiny coupled
    steps in the sufficient parameterisation.  In the ancillary
    parameterisation the sign constraints ``Y_ij = 1{R_ij + b0_j > 0}``
    bind directly on ``b0_j``: its conditional is the Gaussian prior
    conditional truncated to the interval
    ``(max_{i: Y=1} -R_ij,  min_{i: Y=0} -R_ij)`` — an exact Gibbs step
    (the (Z, b0) -> (R, b0) change of variables has unit Jacobian), one
    whole-array reduction plus one truncated-normal draw per species.
    Interweaving it with the standard sufficient-augmentation sweep is the
    Yu & Meng (2011) ASIS recipe.  NA cells impose no constraint and their
    imputed Z rides along with the shift; non-probit columns are left
    untouched.  Structural eligibility lives in
    :func:`da_intercept_gate`."""
    ii = data.x_ones_ind
    fam = data.distr_family                           # (ns,)
    prob = fam == 2
    b0 = state.Beta[ii]                               # (ns,)
    R = state.Z - b0[None, :]
    negR = -R
    if spec.has_na:
        one = (data.Y > 0.5) & (data.Ymask > 0)
        zero = (data.Y <= 0.5) & (data.Ymask > 0)
    else:
        one = data.Y > 0.5
        zero = ~one
    inf = jnp.asarray(jnp.inf, dtype=R.dtype)
    lo = jnp.where(one, negR, -inf).max(axis=0)       # (ns,)
    hi = jnp.where(zero, negR, inf).min(axis=0)
    if shard is not None:                 # cross-site row extrema
        lo = shard.pmax_site(lo)
        hi = shard.pmin_site(hi)
    # Gaussian prior conditional of the intercept given the other rows of
    # Beta_j (precision form): mean b0 - u / iV[ii,ii], var 1 / iV[ii,ii]
    Mu = jnp.einsum("ct,jt->cj", state.Gamma, data.Tr)
    u = state.iV[ii] @ (state.Beta - Mu)              # (ns,)
    v00 = state.iV[ii, ii]
    if shard is None:
        t = truncated_normal(key, lo, hi, mean=b0 - u / v00, std=v00 ** -0.5)
    elif shard.local_rng:
        # local mode: draw on the local bounds with the folded key
        t = truncated_normal(shard.fold(key), lo, hi,
                             mean=b0 - u / v00, std=v00 ** -0.5)
    else:
        # the (ns,) truncation bounds are tiny: gather them, draw the
        # full-width truncated normal replicated, keep the local slice —
        # bit-identical to the replicated draw
        t_full = truncated_normal(
            key, shard.gather_sp(lo, 0), shard.gather_sp(hi, 0),
            mean=shard.gather_sp(b0 - u / v00, 0), std=v00 ** -0.5)
        t = shard.slice_sp(t_full, 0)
    t = jnp.where(prob, t, b0)
    Z = jnp.where(prob[None, :], R + t[None, :], state.Z)
    return state.replace(Z=Z, Beta=state.Beta.at[ii].set(t))


# ---------------------------------------------------------------------------
# updateInvSigma (reference R/updateInvSigma.R:3-43)
# ---------------------------------------------------------------------------

def update_inv_sigma(spec: ModelSpec, data: ModelData, state: GibbsState,
                     key, E=None, shard=None) -> GibbsState:
    if not spec.any_estimated_sigma:
        return state
    Eps = state.Z - (total_loading(spec, data, state, shard)
                     if E is None else E)
    n_obs = data.Ymask.sum(axis=0)
    if shard is not None:                 # cross-site column statistics
        n_obs = shard.psum_site(n_obs)
    shape = data.aSigma + 0.5 * n_obs
    sq = ((Eps * data.Ymask) ** 2).sum(axis=0)
    if shard is not None:
        sq = shard.psum_site(sq)
    rate = data.bSigma + 0.5 * sq
    if shard is None:
        draw = standard_gamma(key, shape) / rate
    elif shard.local_rng:
        # local mode: the shapes are already local — no gather, no slice
        draw = standard_gamma(shard.fold(key), shape) / rate
    else:
        # gamma shapes are species-dependent: gather the tiny (ns,) shape
        # vector, draw full-width replicated, slice — bit-identical
        draw = shard.slice_sp(
            standard_gamma(key, shard.gather_sp(shape, 0)), 0) / rate
    iSigma = jnp.where(data.distr_estsig > 0, draw, 1.0 / data.sigma_fixed)
    return state.replace(iSigma=iSigma)


# ---------------------------------------------------------------------------
# updateNf: masked factor-count adaptation (reference R/updateNf.R:3-71)
# ---------------------------------------------------------------------------

def update_nf(spec: ModelSpec, data: ModelData, state: GibbsState, r: int,
              key, shard=None) -> LevelState:
    """Burn-in factor adaptation as pure mask arithmetic: with probability
    1/exp(1 + 5e-4 iter) either appends one factor (fresh prior draws in the
    next inactive slot) or drops all-shrunk factors (stable compaction permute
    so the active block stays a prefix).  Sharded: the shrunk-proportion
    statistics psum exact integer counts (bit-identical), the fresh psi
    column draws full-width-and-slices, and the grow/drop decision stays
    replicated on every shard."""
    lvd, lv, ls = data.levels[r], state.levels[r], spec.levels[r]
    ku, kadd = jax.random.split(jax.random.fold_in(key, r))
    k_eta, k_psi, k_del = jax.random.split(kadd, 3)
    it = state.it.astype(lv.Eta.dtype)
    adapt = jax.random.uniform(ku, dtype=it.dtype) \
        < 1.0 / jnp.exp(1.0 + 5e-4 * it)

    mask = lv.nf_mask
    nf = mask.sum()
    eps_thr = 1e-3
    if shard is None and data.tenant is None:
        small_prop = (jnp.abs(lv.Lambda) < eps_thr).mean(axis=(1, 2))
    elif shard is None:
        # tenant: the shrunk-proportion statistic counts REAL species only
        # (padded Lambda columns are exact zeros — counting them would read
        # as shrunk and spuriously drop factors)
        ten = data.tenant
        cnt = ((jnp.abs(lv.Lambda) < eps_thr)
               * ten.sp_mask[None, :, None]).sum(axis=(1, 2))
        small_prop = cnt / (ten.n_sp * ls.ncr)
    else:
        cnt = shard.psum(
            (jnp.abs(lv.Lambda) < eps_thr).sum(axis=(1, 2))
            .astype(lv.Lambda.dtype))
        small_prop = cnt / float(shard.ns * ls.ncr)
    redundant = (mask > 0) & (small_prop >= 1.0)
    num_red = redundant.sum()

    # tenant: the growth bound and floor are the MODEL's own (the bucket's
    # static nf_max only sizes the padded slots)
    if data.tenant is None:
        nf_hi, nf_lo = ls.nf_max, ls.nf_min
    else:
        nf_hi = data.tenant.levels[r].nf_cap
        nf_lo = data.tenant.levels[r].nf_min
    grow_wanted = (it > 20) & (num_red == 0) \
        & jnp.all(jnp.where(mask > 0, small_prop < 0.995, True))
    add_ok = (nf < nf_hi) & grow_wanted
    drop_ok = (num_red > 0) & (nf > nf_lo)
    # factor-cap observability: count adaptation events where growth was
    # wanted but the static nf_cap blocked it (the sampler warns post-run
    # when nonzero).  Only when the cap — not the user's own
    # min(rL.nf_max, ns) bound, which the reference also honours
    # (updateNf.R:26) — is the binding constraint.
    if data.tenant is not None:
        nf_sat = lv.nf_sat + (
            (adapt & grow_wanted & (nf >= nf_hi)).astype(jnp.int32)
            * data.tenant.levels[r].nf_capped.astype(jnp.int32))
    elif ls.nf_capped:
        nf_sat = lv.nf_sat + (adapt & grow_wanted
                              & (nf >= ls.nf_max)).astype(jnp.int32)
    else:
        nf_sat = lv.nf_sat

    # --- append one factor in slot `nf` -----------------------------------
    slot = jnp.minimum(nf.astype(jnp.int32), ls.nf_max - 1)
    onehot = jax.nn.one_hot(slot, ls.nf_max, dtype=mask.dtype)
    do_add = adapt & add_ok
    sel = jnp.where(do_add, onehot, 0.0)
    if shard is not None and shard.has_sites:
        # site-dim draw: full-width-and-sliced (local_rng: site-folded,
        # local width) so the appended factor column matches the
        # replicated stream per unit block
        new_eta_col = shard.normal(k_eta, (ls.n_units,), lv.Eta.dtype,
                                   dim=None, site_dim=0)
    else:
        new_eta_col = jax.random.normal(k_eta, (ls.n_units,),
                                        dtype=lv.Eta.dtype)
    Eta = lv.Eta * (1 - sel)[None, :] + new_eta_col[:, None] * sel[None, :]
    if shard is None:
        new_psi = standard_gamma(k_psi, jnp.broadcast_to(
            lvd.nu[None, :] / 2, (spec.ns, ls.ncr))) / (lvd.nu[None, :] / 2)
    elif shard.local_rng:
        # local spec: spec.ns is already the shard width
        new_psi = standard_gamma(shard.fold(k_psi), jnp.broadcast_to(
            lvd.nu[None, :] / 2, (spec.ns, ls.ncr))) / (lvd.nu[None, :] / 2)
    else:
        new_psi = shard.slice_sp(standard_gamma(k_psi, jnp.broadcast_to(
            lvd.nu[None, :] / 2, (shard.ns, ls.ncr))), 0) \
            / (lvd.nu[None, :] / 2)
    Psi = lv.Psi * (1 - sel)[:, None, None] \
        + new_psi[None] * sel[:, None, None]
    new_del = standard_gamma(k_del, lvd.a2) / lvd.b2
    Delta = lv.Delta * (1 - sel)[:, None] + new_del[None, :] * sel[:, None]
    Lambda = lv.Lambda * (1 - sel)[:, None, None]
    alpha_idx = (lv.alpha_idx * (1 - sel.astype(jnp.int32))).astype(jnp.int32)
    mask_add = jnp.clip(mask + sel, 0.0, 1.0)

    # --- drop redundant factors (stable compaction) -----------------------
    keep = (mask > 0) & ~redundant
    do_drop = adapt & drop_ok & ~do_add
    # order: kept actives first (original order), then the rest
    order = jnp.argsort(jnp.where(keep, 0, 1), stable=True)
    mask_drop = jnp.where(keep, 1.0, 0.0)[order]

    def permute(m_add, m_drop):
        return jnp.where(do_drop, m_drop, jnp.where(do_add, m_add, m_add))

    Eta_d = lv.Eta[:, order]
    Lambda_d = lv.Lambda[order] * mask_drop[:, None, None]
    Psi_d = lv.Psi[order]
    Delta_d = jnp.where(mask_drop[:, None] > 0, lv.Delta[order], 1.0)
    alpha_d = lv.alpha_idx[order] * mask_drop.astype(jnp.int32)

    return lv.replace(
        Eta=jnp.where(do_drop, Eta_d, Eta),
        Lambda=jnp.where(do_drop, Lambda_d, Lambda),
        Psi=jnp.where(do_drop, Psi_d, Psi),
        Delta=jnp.where(do_drop, Delta_d, Delta),
        alpha_idx=jnp.where(do_drop, alpha_d, alpha_idx),
        nf_mask=jnp.where(do_drop, mask_drop, mask_add),
        nf_sat=nf_sat,
    )
