"""Posterior sampling driver (reference ``R/sampleMcmc.R:68-380``).

TPU execution model (SURVEY.md §2.3 "Parallelism"):

- one jitted sweep per model config, ``lax.scan`` over iterations with
  strided sample recording (transient / thin handled inside the scan);
- independent chains are a leading batch axis via ``vmap``;
- multi-device: the chain axis (and optionally the species axis) is laid out
  over a ``jax.sharding.Mesh`` — XLA inserts the (trivial, gather-only)
  collectives; there is no inter-chain communication during sampling.

The reference's SOCK-cluster process fan-out collapses into this one
compiled program.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..model import Hmsc
from ..precompute import compute_data_parameters
from .structs import (DEFAULT_NF_CAP, build_model_data, build_spec, build_state)
from .sweep import effective_spec_data, make_sweep, record_sample
from . import updaters as U

__all__ = ["sample_mcmc"]


@functools.lru_cache(maxsize=64)
def _compiled_runner(spec, updater_items, adapt_nf, samples, transient, thin,
                     skip_init_z):
    """One jitted chain-vmapped sampling program per static config.

    Keyed on the hashable (spec, updater toggles, scan lengths) so repeated
    ``sample_mcmc`` calls with the same shapes reuse the compiled executable
    (XLA compilation is the dominant cost for small models)."""
    updater = dict(updater_items) if updater_items else None
    sweep = make_sweep(spec, updater, adapt_nf)

    def run_chain(data, state, key):
        key, k0 = jax.random.split(key)
        if not skip_init_z:
            # reference inits Z via one updateZ pass; a resumed or
            # continuation segment keeps its carried Z
            spec0, data0 = effective_spec_data(spec, data, state)
            state = U.update_z(spec0, data0, state, k0)

        def one_iter(carry, _):
            state, key = carry
            key, sub = jax.random.split(key)
            state = sweep(data, state, sub)
            return (state, key), None

        carry = (state, key)
        if transient > 0:
            carry, _ = jax.lax.scan(one_iter, carry, None, length=transient)

        def sample_step(carry, _):
            carry, _ = jax.lax.scan(one_iter, carry, None, length=thin)
            rec = record_sample(spec, data, carry[0])
            return carry, rec

        carry, recs = jax.lax.scan(sample_step, carry, None, length=samples)
        return recs, carry[0]

    return jax.jit(jax.vmap(run_chain, in_axes=(None, 0, 0)))


def sample_mcmc(hM: Hmsc, samples: int, transient: int = 0, thin: int = 1,
                n_chains: int = 1, seed: int | None = None, init_par=None,
                adapt_nf=None, updater: dict | None = None,
                nf_cap: int = DEFAULT_NF_CAP, dtype=jnp.float32,
                data_par=None, from_prior: bool = False,
                align_post: bool = True, mesh=None, chain_axis: str = "chains",
                return_state: bool = False, verbose: int = 0,
                init_state=None, profile_dir: str | None = None,
                rng_impl: str | None = None):
    """Run the blocked Gibbs sampler; returns a :class:`~hmsc_tpu.post.Posterior`.

    Arguments mirror the reference's ``sampleMcmc`` (samples/transient/thin/
    nChains/initPar/adaptNf/updater/dataParList/fromPrior/alignPost/verbose);
    the process-parallel ``nParallel`` is replaced by device parallelism via
    ``mesh``.  Extras over the reference:

    - ``verbose=N`` prints progress every N sweeps from inside the compiled
      scan (device callback).
    - ``init_state`` resumes chains from a saved carry state (see
      ``hmsc_tpu.utils.checkpoint``); transient should usually be 0 then.
    - ``profile_dir`` wraps the run in a ``jax.profiler`` trace.
    - the returned Posterior carries ``timing`` = {setup_s, run_s} wall-clock
      seconds (run_s includes compilation on first use of a config).
    - ``rng_impl`` picks the PRNG bit generator; default is the hardware
      ``rbg`` on TPU backends (the probit Z update is RNG-throughput-bound
      at scale) and ``threefry2x32`` elsewhere.  Reproducibility is bitwise
      per (seed, impl), not across impls.
    """
    import time

    from ..post.posterior import Posterior

    t0 = time.perf_counter()

    if adapt_nf is None:
        adapt_nf = tuple(transient for _ in range(hM.nr))
    else:
        adapt_nf = tuple(int(a) for a in np.broadcast_to(adapt_nf, (hM.nr,)))
    if any(a > transient for a in adapt_nf):
        raise ValueError("transient parameter should be no less than any element of adaptNf parameter")

    spec = build_spec(hM, nf_cap)
    if data_par is None:
        data_par = compute_data_parameters(hM)
    data = build_model_data(hM, data_par, spec, dtype=dtype)

    rng = np.random.default_rng(seed)
    chain_seeds = rng.integers(0, 2**31 - 1, size=n_chains)

    if from_prior:
        from .prior import sample_prior_chains
        post = sample_prior_chains(hM, spec, data_par, samples, n_chains, rng)
        return Posterior(hM, spec, post, samples=samples, transient=transient,
                         thin=thin)

    it0 = 0
    if init_state is not None:
        state0 = init_state                       # (chains, ...) carry pytree
        lead = int(jax.tree.leaves(state0)[0].shape[0])
        if lead != n_chains:
            raise ValueError(f"init_state carries {lead} chains, n_chains={n_chains}")
        it0 = int(np.asarray(state0.it).ravel()[0])
        # a resumed run must not replay the original run's key stream: mix
        # the carried iteration count into the seed derivation
        rng = np.random.default_rng([0 if seed is None else int(seed), it0])
        chain_seeds = rng.integers(0, 2**31 - 1, size=n_chains)
    else:
        states = [build_state(hM, spec, int(s), init_par, dtype=dtype)
                  for s in chain_seeds]
        state0 = jax.tree.map(lambda *xs: jnp.stack(xs), *states)

    # structural gates for the opt-in collapsed updaters (reference
    # auto-gating, sampleMcmc.R:123-152; see updaters_marginal)
    if updater and (updater.get("Gamma2") is True
                    or updater.get("GammaEta") is True):
        from .updaters_marginal import gamma_eta_gates
        gates = gamma_eta_gates(spec, mGamma=hM.mGamma)
        updater = dict(updater)
        for name in ("Gamma2", "GammaEta"):
            if updater.get(name) is True and gates[name]:
                print(f"Setting updater${name}=FALSE: {gates[name]}")
                updater[name] = False

    updater_items = (tuple(sorted(updater.items())) if updater else None)
    sharding = None
    if mesh is not None:
        # shard the chain batch axis over the mesh; everything else replicates
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharding = NamedSharding(mesh, P(chain_axis))
        state0 = jax.tree.map(lambda x: jax.device_put(x, sharding), state0)

    # progress: verbose>0 splits the sample scan into host-level segments so
    # the host prints between compiled chunks (the reference's per-iteration
    # printout, sampleMcmc.R:317-324, at `verbose`-sweep granularity)
    if verbose:
        chunk = max(1, int(round(verbose / thin)))
        seg_sizes = [chunk] * (int(samples) // chunk)
        if int(samples) % chunk:
            seg_sizes.append(int(samples) % chunk)
    else:
        seg_sizes = [int(samples)]
    total_it = it0 + int(transient) + int(samples) * int(thin)

    t1 = time.perf_counter()
    import contextlib
    ctx = (jax.profiler.trace(profile_dir) if profile_dir is not None
           else contextlib.nullcontext())
    with ctx:
        recs_segs = []
        state_cur = state0
        trans_cur = int(transient)
        skip_z = init_state is not None
        if rng_impl is None:
            plat = jax.default_backend()
            rng_impl = "rbg" if ("tpu" in plat or "axon" in plat) \
                else "threefry2x32"
        for si, seg in enumerate(seg_sizes):
            base = jax.vmap(lambda s: jax.random.key(s, impl=rng_impl))(
                jnp.asarray(chain_seeds))
            keys = (base if si == 0
                    else jax.vmap(lambda k: jax.random.fold_in(k, si))(base))
            if sharding is not None:
                keys = jax.device_put(keys, sharding)
            fn = _compiled_runner(spec, updater_items, adapt_nf, seg,
                                  trans_cur, int(thin), skip_z)
            recs, state_cur = fn(data, state_cur, keys)
            recs_segs.append(recs)
            trans_cur = 0
            skip_z = True
            if verbose:
                it_now = int(np.asarray(state_cur.it).ravel()[0])
                phase = "sampling" if it_now > it0 + transient else "transient"
                print(f"iteration {it_now} of {total_it} ({phase})")
        final_state = state_cur
        if len(recs_segs) == 1:
            recs = recs_segs[0]
        else:
            recs = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=1),
                                *recs_segs)
        jax.block_until_ready(recs)
    recs = jax.tree.map(np.asarray, recs)        # (chains, samples, ...)
    t2 = time.perf_counter()

    post = Posterior(hM, spec, recs, samples=samples, transient=transient,
                     thin=thin)
    post.timing = {"setup_s": t1 - t0, "run_s": t2 - t1}
    if align_post and spec.nr > 0:
        from ..post.align import align_posterior
        for _ in range(5):
            align_posterior(post)
    if return_state:
        return post, final_state
    return post
